"""TPU sim plane — whole simulated clusters as dense JAX arrays.

The reference runs one goroutine-driven protocol loop per process
(``swim/gossip.go:151``); simulating big clusters means big fleets.  Here the
*entire cluster* is one pytree and one jitted ``step`` advances every node's
protocol period at once:

* :mod:`ringpop_tpu.sim.fullview` — exact semantics, O(N²) state
  (``view[i, j]`` = node i's belief about node j): ping targeting, piggyback
  dissemination with SWIM's maxP bound, override/refutation rules, indirect
  ping-req probes, suspicion timers, full sync — the host plane's behavior,
  vectorized.  The override rule is a join-semilattice max over
  ``(incarnation, precedence)``, which is exactly why concurrent change
  application vectorizes as an elementwise/segment max without order effects.

* :mod:`ringpop_tpu.sim.delta` — scalable dissemination engine, O(N·K)
  state for K in-flight changes over a converged base — runs 1M+ nodes on
  one chip and shards over a mesh for more.

* :mod:`ringpop_tpu.sim.lifecycle` — O(N·K) full failure-detection engine
  (probe → suspect → deadline → faulty → tombstone → evict + refutation).

* :mod:`ringpop_tpu.sim.montecarlo` — whole clusters vmapped over a
  replica axis: B seeded replicas as ONE compiled program ([B, N, K]
  arrays) for detection-latency distributions and parameter studies;
  replica b is bit-identical to ``LifecycleSim(seed=seeds[b])``.

* :mod:`ringpop_tpu.sim.telemetry` — device-resident telemetry plane:
  per-tick protocol counters carried through the jitted scan
  (elementwise accumulators; zero per-tick collectives under SPMD),
  fetched per tick-block into the host stats/event plumbing and a JSONL
  run journal.  Off by default and bit-transparent when on — see
  OBSERVABILITY.md.

* :mod:`ringpop_tpu.sim.chaos` — the chaos plane: declarative
  time-varying fault scenarios (crash/restart churn, flapping members,
  asymmetric partition split/heal windows, per-node loss / slow-node
  timeout inflation) compiled into dense device arrays and evaluated
  shard-locally inside the jitted step, plus the convergence scorer
  that reduces a telemetry journal into scenario verdicts.  A FaultPlan
  is also a batchable axis (``stack_plans``): B different scenarios as
  one ``[B, ...]`` plan pytree, vmapped through the engines by the
  Monte-Carlo fleet.

* :mod:`ringpop_tpu.sim.scenarios` — the scenario-grid compiler on top:
  sweep a parameter grid (churn dose × loss × partition width ×
  suspicion timeout × topology overlay) into stacked plans, run ONE
  AOT-warm-started batched program, reduce the batched telemetry
  journal into per-scenario verdicts and 2-D response surfaces
  (``simbench mc_chaos``).

* :mod:`ringpop_tpu.sim.topology` — the topology compiler: a
  declarative rack/zone/region tree with per-edge latency/loss compiled
  host-side to per-node tier-id arrays + a per-tier drop table
  (cross-boundary probe-timeout inflation as tier loss), evaluated
  inside the jitted step by shard-local blocked one-hot gathers — no
  dense [G, G] product — plus the correlated-failure scenario family
  (zone loss, switch flap, one-way WAN partition) that batches through
  the fleet and scores with per-tier breakdowns.

Fault injection is first-class: partition group arrays (symmetric or
directed via ``reach[G, G]``), scalar and per-node drop probabilities,
process-liveness masks — plain traced arrays applied to the message
exchange step (BASELINE.json's 5% loss / 30% partition configs), or a
whole ``chaos.FaultPlan`` timeline in their place.
"""

from ringpop_tpu.sim.fullview import FullViewSim, FullViewParams
from ringpop_tpu.sim.delta import DeltaSim, DeltaParams
from ringpop_tpu.sim.lifecycle import LifecycleSim, LifecycleParams
from ringpop_tpu.sim.montecarlo import MonteCarlo, detection_latency_distribution
from ringpop_tpu.sim.chaos import FaultPlan, faults_at, score_blocks, stack_plans
from ringpop_tpu.sim.topology import Topology, TopologySpec, compile_topology

__all__ = [
    "Topology",
    "TopologySpec",
    "compile_topology",
    "FullViewSim",
    "FullViewParams",
    "DeltaSim",
    "DeltaParams",
    "LifecycleSim",
    "LifecycleParams",
    "MonteCarlo",
    "detection_latency_distribution",
    "FaultPlan",
    "faults_at",
    "score_blocks",
    "stack_plans",
]
