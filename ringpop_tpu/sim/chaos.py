"""Chaos plane: device-resident, time-varying fault scenarios with
convergence scoring.

The fault surface used to be one static triple frozen for a whole run
(``DeltaFaults(up, group, drop_rate)``), so the suspect-timer and
partition-healer machinery was only ever exercised against step-function
partitions.  SWIM's original evaluation (Das et al.) and Lifeguard
(Dadgar et al., PAPERS.md) are precisely about behavior under message
loss, slow processors, and flapping members — regimes a static mask
cannot express.

This module is that missing plane, in three parts:

1. **FaultPlan** — a declarative scenario timeline compiled (host-side,
   once) into dense per-node device arrays: crash/restart churn windows,
   flapping schedules, an asymmetric partition window with a directed
   ``reach[G, G]`` matrix, scalar + per-node drop rates, and slow-node
   probe-timeout inflation (folded into the per-node drop plane — an ack
   that tends to arrive after the timeout IS a lost leg at that
   probability).
2. **faults_at(plan, tick)** — the pure shard-local evaluator: every
   output leaf is an elementwise function of the plan's [N] arrays and
   the replicated tick scalar, so under a device mesh fault evaluation
   adds ZERO cross-chip collectives (the ``fault-plan`` named scope is
   in ``analysis/phases.FORBIDDEN_COLLECTIVE_PHASES`` — jaxlint
   RPJ203/RPJ206 forbid a collective there by construction).  Both
   engines call it through ``delta.resolve_faults`` at the top of
   ``step`` (and every convergence/telemetry query), so plans flow
   through ``_run_block``/``run_until_*`` carries unchanged.  A CONSTANT
   plan (only static legs) emits no ops at all — it traces to the exact
   static-``DeltaFaults`` program, which is what keeps the frozen
   goldens green without recapture (``constant_plan``,
   tests/test_chaos.py).
3. **score_blocks** — the convergence scorer: reduces an r7 telemetry
   journal (the per-block counter records ``sim/telemetry.py`` emits)
   plus the plan's event timeline into scenario verdicts — time-to-detect
   per fault event, rumor half-life (the epidemic's half-coverage time),
   false-positive suspect count (counted as refutations: only a LIVE
   accused node ever reincarnates), and re-join convergence ticks after
   the last restart.  Host-side numpy over host scalars; granularity is
   the journal's block size, which the verdict records.

Scenario vocabulary: ``scenario_plan(name, n, ...)`` builds the three
canonical simbench scenarios (``churn``, ``flap``, ``asym``) plus the
``smoke`` churn+flap used by ``make chaos-smoke`` and the profile-mesh
chaos ratchet — one builder shared by the bench, its sharded-twin
subprocess, and the tests, so the certified plan can't drift from the
measured one.

Batching (r12): a FaultPlan is also a *batchable axis*.  ``stack_plans``
stacks B heterogeneous solo plans into one ``[B, ...]`` plan pytree
(missing legs materialize value-neutral defaults; ``reach`` matrices pad
to the largest group count), ``plan_axes`` is its vmap ``in_axes``, and
``index_plan`` slices one member back out for per-scenario scoring.
``faults_at`` is elementwise, so the stacked plan maps through the
engines' step unchanged — ``sim/montecarlo.py`` vmaps the chaos-enabled
step over (plan, seed) and ``sim/scenarios.py`` compiles parameter grids
into stacked plans, one jitted program per sweep.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import N_TIERS, TIER_LEVELS, TIER_NAMES, DeltaFaults

# "this never happens" tick sentinel (same convention as the engines'
# NO_DEADLINE): comparisons against it are always false for real ticks
NO_TICK = np.int32(np.iinfo(np.int32).max)


class FaultPlan(NamedTuple):
    """A compiled scenario timeline.  Every leg is optional; ``None``
    legs are static structure and compile out — a plan with only the
    static legs (``base_up``/``group``/``reach``/drop) traces to the
    exact static-``DeltaFaults`` program.

    Liveness is the AND of three legs (a node is up iff no leg holds it
    down):

    * ``base_up`` — permanently-down overlay (the classic crash set);
    * crash window — down during ``[crash_tick, restart_tick)``
      (``NO_TICK`` restart = crashed forever);
    * flapping — nodes with ``flap_period > 0`` are down for
      ``flap_down`` ticks out of every ``flap_period``, offset by
      ``flap_phase``.

    The partition leg applies ``group`` (with the optional directed
    ``reach`` matrix — see ``DeltaFaults``) only inside
    ``[part_from, part_until)``; outside the window every node reports
    group -1 (unpartitioned), so a split/heal is one plan, not a
    host-side fault swap.  Loss legs (``drop_rate``/``drop_node``) are
    time-invariant and pass through, as are the topology legs
    (``tier_ids``/``tier_drop``, compiled by ``sim/topology.py``) and the
    traced suspicion-timeout override (``suspect_ticks``; -1 = use the
    engine's static param — the value-neutral stacked default).

    Ticks are in the engine clock: the plan is evaluated at
    ``state.tick`` as the step ENTERS (tick t's exchange sees
    ``faults_at(plan, t)``).
    """

    base_up: Optional[jax.Array] = None  # bool[N]
    crash_tick: Optional[jax.Array] = None  # int32[N], NO_TICK = never
    restart_tick: Optional[jax.Array] = None  # int32[N], NO_TICK = never
    flap_period: Optional[jax.Array] = None  # int32[N], 0 = not flapping
    flap_phase: Optional[jax.Array] = None  # int32[N]
    flap_down: Optional[jax.Array] = None  # int32[N] down ticks per period
    group: Optional[jax.Array] = None  # int32[N], -1 = unpartitioned
    part_from: Optional[jax.Array] = None  # int32[] split tick (None = 0)
    part_until: Optional[jax.Array] = None  # int32[] heal tick (None = never)
    reach: Optional[jax.Array] = None  # bool[G, G] directed reachability
    drop_rate: Optional[jax.Array] = None  # float32[] scalar loss
    drop_node: Optional[jax.Array] = None  # float32[N] per-node loss
    tier_ids: Optional[jax.Array] = None  # int32[TIER_LEVELS, N] topology ids
    tier_drop: Optional[jax.Array] = None  # float32[N_TIERS] per-tier loss
    suspect_ticks: Optional[jax.Array] = None  # int32[] traced timeout (-1 = params)

    def at_tick(self, tick) -> DeltaFaults:
        """The duck-typed seam ``delta.resolve_faults`` dispatches on."""
        return faults_at(self, tick)


def faults_at(plan: FaultPlan, tick) -> DeltaFaults:
    """Evaluate the plan's timeline at ``tick`` → a concrete DeltaFaults.

    Pure and shard-local by construction: the only array inputs are the
    plan's [N] per-node legs (node-sharded like every other [N] vector)
    and the replicated tick scalar, and every op is elementwise — the
    SPMD partitioner keeps the whole evaluation on the shard that owns
    each lane, with zero collectives under any mesh.  The ``fault-plan``
    named scope makes that statically checkable (jaxlint RPJ203/RPJ206
    forbid collectives in this phase)."""
    with jax.named_scope("fault-plan"):
        t = jnp.asarray(tick, jnp.int32)
        up = plan.base_up
        if plan.crash_tick is not None:
            down = t >= plan.crash_tick
            if plan.restart_tick is not None:
                down &= t < plan.restart_tick
            up = ~down if up is None else up & ~down
        if plan.flap_period is not None:
            if plan.flap_down is None:
                raise ValueError("flap_period without flap_down: how long is a flap?")
            period = jnp.maximum(plan.flap_period, 1)
            phase = plan.flap_phase if plan.flap_phase is not None else jnp.int32(0)
            pos = jnp.mod(t + phase, period)
            flapped = (plan.flap_period > 0) & (pos < plan.flap_down)
            up = ~flapped if up is None else up & ~flapped
        group = plan.group
        if group is not None and (
            plan.part_from is not None or plan.part_until is not None
        ):
            in_part = jnp.bool_(True)
            if plan.part_from is not None:
                in_part &= t >= plan.part_from
            if plan.part_until is not None:
                in_part &= t < plan.part_until
            group = jnp.where(in_part, group, jnp.int32(-1))
        return DeltaFaults(
            up=up,
            group=group,
            drop_rate=plan.drop_rate,
            drop_node=plan.drop_node,
            reach=plan.reach,
            tier_ids=plan.tier_ids,
            tier_drop=plan.tier_drop,
            suspect_ticks=plan.suspect_ticks,
        )


def constant_plan(faults: DeltaFaults) -> FaultPlan:
    """A FaultPlan encoding a static DeltaFaults: ``faults_at`` then
    returns the same leaves with ZERO added ops, so trajectories — state
    and telemetry — are bit-identical to running the DeltaFaults
    directly (the constant-plan equivalence the goldens pin)."""
    return FaultPlan(
        base_up=faults.up,
        group=faults.group,
        reach=faults.reach,
        drop_rate=faults.drop_rate,
        drop_node=faults.drop_node,
        tier_ids=faults.tier_ids,
        tier_drop=faults.tier_drop,
        suspect_ticks=faults.suspect_ticks,
    )


# -- plan validation (host-side, at build time) -------------------------------


def validate_plan(plan: FaultPlan) -> FaultPlan:
    """Host-side structural validation of a (solo or stacked) plan —
    called by every builder in this module and ``sim/topology.py``, and
    public for hand-built plans.

    The load-bearing checks:

    * ``reach`` must be SQUARE and BOOLEAN — a float or ragged matrix
      would be consumed as truthy garbage by the gather;
    * every ``group`` id must index inside the ``reach`` extent — an
      oversized id silently clamps into someone else's row under jax
      gather semantics (connecting groups the scenario keeps apart),
      which is exactly the failure mode a loud build-time error beats;
    * the topology legs come as a pair with the FIXED shapes the engines
      trace (``tier_ids`` int32[3, N], ``tier_drop`` float32[4] in
      [0, 1]);
    * ``suspect_ticks`` is a positive timeout or the -1 "use params"
      sentinel — 0 or below-(-1) would silently fire every suspicion
      immediately / never.

    Traced leaves skip validation (the checks are about plan-BUILD time;
    a plan constructed under jit is the engine's own doing).  Returns the
    plan so builders can ``return validate_plan(...)``.
    """
    import jax.core as _core

    leaves = [v for v in plan if v is not None]
    if any(isinstance(v, _core.Tracer) for v in leaves):
        return plan

    def _np(x):
        return np.asarray(x)

    if plan.reach is not None:
        reach = _np(plan.reach)
        if reach.ndim not in (2, 3) or reach.shape[-1] != reach.shape[-2]:
            raise ValueError(
                f"reach must be a square [G, G] matrix (stacked: [B, G, G]); "
                f"got shape {reach.shape}"
            )
        if reach.dtype != np.bool_:
            raise ValueError(
                f"reach must be boolean (directed reachability verdicts); "
                f"got dtype {reach.dtype} — cast explicitly if you mean it"
            )
    if plan.group is not None:
        group = _np(plan.group)
        if group.size and int(group.min()) < -1:
            raise ValueError(
                f"group ids must be >= -1 (-1 = unpartitioned); "
                f"min is {int(group.min())}"
            )
        if plan.reach is not None and group.size:
            g_extent = int(_np(plan.reach).shape[-1])
            g_max = int(group.max())
            if g_max >= g_extent:
                raise ValueError(
                    f"group id {g_max} is out of range for the "
                    f"[{g_extent}, {g_extent}] reach matrix — an oversized "
                    "id would silently clamp into another group's row at "
                    "evaluation time"
                )
    if (plan.tier_ids is None) != (plan.tier_drop is None):
        raise ValueError(
            "topology legs come as a pair: tier_ids (int32[3, N]) and "
            "tier_drop (float32[4])"
        )
    if plan.tier_ids is not None:
        ids = _np(plan.tier_ids)
        if ids.shape[-2] != TIER_LEVELS:
            raise ValueError(
                f"tier_ids must carry the fixed {TIER_LEVELS}-level "
                f"rack/zone/region hierarchy on axis -2; got shape {ids.shape}"
            )
        table = _np(plan.tier_drop)
        if table.shape[-1] != N_TIERS:
            raise ValueError(
                f"tier_drop must have one entry per tier distance "
                f"({N_TIERS}: {', '.join(TIER_NAMES)}); got shape {table.shape}"
            )
        if table.size and (float(table.min()) < 0.0 or float(table.max()) > 1.0):
            raise ValueError(
                f"tier_drop entries are loss probabilities in [0, 1]; "
                f"got range [{float(table.min())}, {float(table.max())}]"
            )
    if plan.suspect_ticks is not None:
        st = _np(plan.suspect_ticks)
        if bool(((st < 1) & (st != -1)).any()):
            raise ValueError(
                "suspect_ticks must be >= 1 (or the -1 'use params' "
                f"sentinel); got {st.tolist() if st.ndim else int(st)}"
            )
    if plan.flap_period is not None and plan.flap_down is None:
        raise ValueError("flap_period without flap_down: how long is a flap?")
    return plan


# -- scenario builders (host-side; dense device arrays out) -------------------


def churn_plan(
    n: int,
    *,
    n_churn: Optional[int] = None,
    n_permanent: int = 0,
    first: int = 8,
    stagger: int = 8,
    waves: int = 4,
    down_ticks: int = 64,
    seed: int = 0,
) -> FaultPlan:
    """Crash/restart churn: ``n_churn`` nodes (default ~1%) crash in
    ``waves`` staggered waves starting at tick ``first``, each down for
    ``down_ticks`` before restarting; the first ``n_permanent`` of them
    never restart (the detection workload)."""
    if n_churn is None:
        n_churn = max(4, n // 100)
    rng = np.random.default_rng(seed)
    nodes = rng.choice(n, size=min(n_churn, n), replace=False)
    crash = np.full(n, NO_TICK, np.int32)
    restart = np.full(n, NO_TICK, np.int32)
    for j, node in enumerate(nodes):
        t = first + (j % waves) * stagger
        crash[node] = t
        if j >= n_permanent:
            restart[node] = t + down_ticks
    return FaultPlan(crash_tick=jnp.asarray(crash), restart_tick=jnp.asarray(restart))


def flap_plan(
    n: int,
    *,
    n_flap: Optional[int] = None,
    period: int = 24,
    down: int = 6,
    start: int = 8,
    seed: int = 0,
) -> FaultPlan:
    """Flapping members: ``n_flap`` nodes (default ~1%) cycle
    ``down``-ticks-down out of every ``period``, phases staggered so the
    flaps don't synchronize.  ``start`` delays the first down-phase so
    the cluster boots clean."""
    if n_flap is None:
        n_flap = max(2, n // 100)
    rng = np.random.default_rng(seed)
    nodes = rng.choice(n, size=min(n_flap, n), replace=False)
    fperiod = np.zeros(n, np.int32)
    fphase = np.zeros(n, np.int32)
    fdown = np.zeros(n, np.int32)
    for j, node in enumerate(nodes):
        fperiod[node] = period
        # phase chosen so the node's first down window opens at
        # start + j (staggered): down iff (t + phase) % period < down
        fphase[node] = (-(start + j)) % period
        fdown[node] = down
    return FaultPlan(
        flap_period=jnp.asarray(fperiod),
        flap_phase=jnp.asarray(fphase),
        flap_down=jnp.asarray(fdown),
    )


def asym_partition_plan(
    n: int,
    *,
    minority: float = 0.3,
    split_at: int = 8,
    heal_at: int = 128,
) -> FaultPlan:
    """One-way partition window: the first ``minority`` fraction of nodes
    becomes group 1 during ``[split_at, heal_at)``; the directed reach
    matrix blocks majority→minority exchanges while minority→majority
    still delivers.  The majority therefore piles up FALSE suspicions
    about minority nodes; the minority keeps learning them off the
    response legs of its own probes and refutes — the Lifeguard-class
    regime the symmetric group model could not express."""
    group = np.zeros(n, np.int32)
    group[: int(minority * n)] = 1
    # reach[a, b]: may group a send to group b?  majority(0) -> minority(1)
    # blocked; everything else delivers.
    reach = np.asarray([[True, False], [True, True]])
    return FaultPlan(
        group=jnp.asarray(group),
        part_from=jnp.asarray(np.int32(split_at)),
        part_until=jnp.asarray(np.int32(heal_at)),
        reach=jnp.asarray(reach),
    )


def _merge_plans(*plans: FaultPlan) -> FaultPlan:
    """Combine plans with disjoint legs (a leg set in two plans is a
    scenario-construction error, not a merge)."""
    merged = {}
    for plan in plans:
        for field, value in zip(plan._fields, plan):
            if value is None:
                continue
            if merged.get(field) is not None:
                raise ValueError(f"leg {field!r} set by more than one plan")
            merged[field] = value
    return validate_plan(FaultPlan(**merged))


def scenario_plan(name: str, n: int, seed: int = 0, horizon: int = 256) -> FaultPlan:
    """The canonical simbench/chaos-smoke scenario plans, parameterized
    only by (name, n, seed, horizon) so the measuring bench, its
    sharded-twin subprocess, and the tests all construct the identical
    plan.  Schedules scale with ``horizon`` (the run's tick budget)."""
    if name == "churn":
        return validate_plan(churn_plan(
            n,
            n_churn=max(8, n // 100),
            n_permanent=max(2, n // 400),
            first=max(4, horizon // 32),
            stagger=max(4, horizon // 32),
            waves=4,
            down_ticks=max(16, horizon // 4),
            seed=seed,
        ))
    if name == "flap":
        return _merge_plans(
            flap_plan(
                n,
                n_flap=max(4, n // 100),
                period=max(12, horizon // 10),
                down=max(3, horizon // 40),
                start=max(4, horizon // 32),
                seed=seed,
            ),
            # background loss keeps the indirect-probe machinery busy
            FaultPlan(drop_rate=jnp.float32(0.02)),
        )
    if name == "asym":
        # a small permanent crash cohort rides along so the scenario also
        # measures time-to-detect THROUGH the one-way partition window
        return _merge_plans(
            asym_partition_plan(
                n,
                minority=0.3,
                split_at=max(4, horizon // 32),
                heal_at=horizon // 2,
            ),
            churn_plan(
                n,
                n_churn=max(2, n // 1000),
                n_permanent=max(2, n // 1000),
                first=2,
                stagger=1,
                waves=1,
                seed=seed,
            ),
        )
    if name == "smoke":
        # tiny churn + flap + loss — every time-varying leg in one plan
        # (the make chaos-smoke / profile-mesh chaos program)
        return _merge_plans(
            churn_plan(
                n,
                n_churn=max(4, n // 64),
                n_permanent=2,
                first=4,
                stagger=4,
                waves=2,
                down_ticks=max(12, horizon // 4),
                seed=seed,
            ),
            flap_plan(
                n, n_flap=max(2, n // 64), period=12, down=3, start=6, seed=seed + 1
            ),
            FaultPlan(drop_rate=jnp.float32(0.02)),
        )
    raise ValueError(f"unknown chaos scenario {name!r}")


# -- plan batching: B scenarios as one [B, ...] plan pytree -------------------

# Solo (unbatched) ndim per FaultPlan leg — the contract every batching
# helper dispatches on: a leaf with one MORE axis than its solo rank
# carries a leading scenario axis.  ``faults_at`` is elementwise in the
# per-node legs and broadcasts the scalars, so a stacked plan vmaps
# through the engines unchanged (sim/montecarlo.py maps the step over
# (plan, state) with ``plan_axes``).
PLAN_LEG_NDIM = {
    "base_up": 1,
    "crash_tick": 1,
    "restart_tick": 1,
    "flap_period": 1,
    "flap_phase": 1,
    "flap_down": 1,
    "group": 1,
    "part_from": 0,
    "part_until": 0,
    "reach": 2,
    "drop_rate": 0,
    "drop_node": 1,
    "tier_ids": 2,
    "tier_drop": 1,
    "suspect_ticks": 0,
}


def _leg_rank(field: str, value) -> int:
    nd = int(getattr(value, "ndim", 0))
    solo = PLAN_LEG_NDIM[field]
    if nd not in (solo, solo + 1):
        raise ValueError(
            f"plan leg {field!r} has ndim {nd}; expected {solo} (solo) or "
            f"{solo + 1} (stacked [B, ...])"
        )
    return nd - solo


def plan_axes(plan: FaultPlan) -> Optional[FaultPlan]:
    """vmap ``in_axes`` pytree for a (possibly) stacked plan: 0 for legs
    carrying a leading scenario axis, None for shared legs — or None when
    nothing is batched (the solo-plan fast path)."""
    axes = {}
    batched = False
    for field, value in zip(plan._fields, plan):
        if value is None:
            continue
        if _leg_rank(field, value):
            axes[field] = 0
            batched = True
    return FaultPlan(**axes) if batched else None


def plan_batch_size(plan: FaultPlan) -> Optional[int]:
    """B of a stacked plan (None for a solo plan).  Mixed batch sizes in
    one plan are a construction error."""
    sizes = {
        int(value.shape[0])
        for field, value in zip(plan._fields, plan)
        if value is not None and _leg_rank(field, value)
    }
    if not sizes:
        return None
    if len(sizes) > 1:
        raise ValueError(f"stacked plan carries mixed batch sizes {sorted(sizes)}")
    return sizes.pop()


def _leg_default(field: str, n: Optional[int], groups: int):
    """The inert default a member missing leg ``field`` stacks as — chosen
    so the materialized leg is VALUE-neutral: crash windows that never
    open, flap periods of zero, group -1 everywhere, loss 0.0 (the
    engines' drop comparison ``u >= 0.0``/``u < 1.0`` passes every leg),
    and an identity ``reach`` (same-group ⇔ connected — exactly the
    symmetric-partition semantics a reach-less plan has)."""
    if field == "base_up":
        return jnp.ones((n,), bool)
    if field in ("crash_tick", "restart_tick"):
        return jnp.full((n,), NO_TICK, jnp.int32)
    if field in ("flap_period", "flap_phase", "flap_down"):
        return jnp.zeros((n,), jnp.int32)
    if field == "group":
        return jnp.full((n,), -1, jnp.int32)
    if field == "part_from":
        return jnp.asarray(0, jnp.int32)
    if field == "part_until":
        return jnp.asarray(NO_TICK, jnp.int32)
    if field == "reach":
        return jnp.eye(groups, dtype=bool)
    if field == "drop_rate":
        return jnp.asarray(0.0, jnp.float32)
    if field == "drop_node":
        return jnp.zeros((n,), jnp.float32)
    if field == "tier_ids":
        # a flat topology: every node shares every id, so any pair is
        # tier 0 — and the zero table below never drops a leg anyway
        return jnp.zeros((TIER_LEVELS, n), jnp.int32)
    if field == "tier_drop":
        # all-zero table: the tier coin (its own stateless draw site —
        # sim/delta.py tier_pair_drop) passes every leg, so a member
        # defaulted here is bit-identical to its topology-less solo run
        return jnp.zeros((N_TIERS,), jnp.float32)
    if field == "suspect_ticks":
        # -1 = "use the engine's static params.suspect_ticks" (the
        # engines select on the sentinel, so the default member keeps its
        # solo timeout bit-for-bit)
        return jnp.asarray(-1, jnp.int32)
    raise ValueError(f"unknown plan leg {field!r}")


def _pad_reach(reach, groups: int):
    """Embed a [G, G] reach matrix in [groups, groups]: original verdicts
    top-left, identity (symmetric semantics) on the padded diagonal.  The
    padded rows are unreachable by that member's own group ids — padding
    only exists so heterogeneous members stack to one dense leaf."""
    reach = jnp.asarray(reach, bool)
    g = reach.shape[0]
    if g == groups:
        return reach
    out = jnp.eye(groups, dtype=bool)
    return out.at[:g, :g].set(reach)


def stack_plans(plans) -> FaultPlan:
    """Stack B (heterogeneous) solo FaultPlans into ONE plan whose legs
    carry a leading scenario axis — the batchable unit the Monte-Carlo
    fleet vmaps over (one compiled program evaluates all B scenarios).

    A leg set by ANY member is materialized for every member (missing
    members get the inert default, value-identical to the leg's absence
    — ``_leg_default``); a leg set by NO member stays None and compiles
    out exactly as in a solo plan.  ``reach`` matrices of different group
    counts are padded to the largest (``_pad_reach``).  B = 1 is legal
    and bit-identical to the solo run (pinned by tests/test_scenarios.py).
    """
    plans = list(plans)
    if not plans:
        raise ValueError("stack_plans needs at least one plan")
    for p in plans:
        validate_plan(p)
        for field, value in zip(p._fields, p):
            if value is not None and _leg_rank(field, value):
                raise ValueError(f"stack_plans takes SOLO plans; {field!r} is already stacked")
    # n inferred from any per-node leg (tier_ids carries the node axis
    # last); only needed when one must be defaulted
    n = next(
        (
            int(v.shape[-1]) if f == "tier_ids" else int(v.shape[0])
            for p in plans
            for f, v in zip(p._fields, p)
            if v is not None and (PLAN_LEG_NDIM[f] == 1 or f == "tier_ids")
        ),
        None,
    )
    # the padded reach must cover every member's group-id range, not just
    # the reach-carrying members': a symmetric member's ids index the
    # identity default it materializes, and an out-of-range id would
    # silently clamp into someone else's row (connecting groups its solo
    # run keeps apart)
    groups = max(
        [int(p.reach.shape[0]) for p in plans if p.reach is not None]
        + [int(np.asarray(p.group).max()) + 1 for p in plans if p.group is not None],
        default=0,
    )
    legs = {}
    for field in FaultPlan._fields:
        values = [getattr(p, field) for p in plans]
        if all(v is None for v in values):
            continue
        if field == "reach":
            stacked = [
                _pad_reach(v, groups) if v is not None
                else _leg_default("reach", n, groups)
                for v in values
            ]
        else:
            if n is None and (PLAN_LEG_NDIM[field] == 1 or field == "tier_ids"):
                raise ValueError(
                    f"cannot default per-node leg {field!r}: no member names n"
                )
            default = None
            stacked = []
            for v in values:
                if v is None:
                    if default is None:
                        default = _leg_default(field, n, groups)
                    v = default
                stacked.append(jnp.asarray(v))
        legs[field] = jnp.stack(stacked)
    return FaultPlan(**legs)


def index_plan(plan: FaultPlan, b: int) -> FaultPlan:
    """Member ``b`` of a stacked plan as a solo plan (batched legs are
    sliced, shared legs pass through) — what the scorer hands
    ``plan_events``/``up_at_host`` per scenario."""
    legs = {}
    for field, value in zip(plan._fields, plan):
        if value is None:
            continue
        legs[field] = value[b] if _leg_rank(field, value) else value
    return FaultPlan(**legs)


def slice_plan(plan: FaultPlan, lo: int, hi: int) -> FaultPlan:
    """Members ``[lo, hi)`` of a stacked plan as a (smaller) stacked plan
    — batched legs are sliced along the scenario axis, shared legs pass
    through.  The r19 fleet's process-slicing seam: rank r of a
    P-process sweep runs ``slice_plan(plan, *process_block(B, r, P))``
    and, because a stacked member's trajectory is independent of which
    other members share its program (pinned by the B=1 and heterogeneous
    identity tests), re-slicing onto a different process count is
    bit-exact per scenario."""
    if not 0 <= lo <= hi:
        raise ValueError(f"bad slice [{lo}, {hi})")
    legs = {}
    for field, value in zip(plan._fields, plan):
        if value is None:
            continue
        legs[field] = value[lo:hi] if _leg_rank(field, value) else value
    return FaultPlan(**legs)


# -- host-side timeline introspection ----------------------------------------


def up_at_host(plan: FaultPlan, tick: int, n: int) -> np.ndarray:
    """Host-numpy mirror of the liveness legs of :func:`faults_at` (the
    scorer's ground truth for expected-alive counts)."""
    up = np.ones(n, bool)
    if plan.base_up is not None:
        up &= np.asarray(plan.base_up)
    if plan.crash_tick is not None:
        down = tick >= np.asarray(plan.crash_tick)
        if plan.restart_tick is not None:
            down &= tick < np.asarray(plan.restart_tick)
        up &= ~down
    if plan.flap_period is not None:
        period = np.maximum(np.asarray(plan.flap_period), 1)
        phase = (
            np.asarray(plan.flap_phase) if plan.flap_phase is not None else 0
        )
        pos = np.mod(tick + phase, period)
        up &= ~((np.asarray(plan.flap_period) > 0) & (pos < np.asarray(plan.flap_down)))
    return up


def plan_events(plan: FaultPlan) -> list[dict]:
    """The plan's discrete event timeline, host-side: one record per
    distinct crash/restart tick (with the cohort size), the partition
    split/heal ticks, and a summary record for the flapping population.
    Sorted by tick; flap summaries (continuous, not discrete) sort by
    their first down tick."""
    events: list[dict] = []
    if plan.crash_tick is not None:
        crash = np.asarray(plan.crash_tick)
        for t in np.unique(crash[crash != NO_TICK]):
            events.append(
                {"kind": "crash", "tick": int(t), "nodes": int((crash == t).sum())}
            )
    if plan.restart_tick is not None:
        restart = np.asarray(plan.restart_tick)
        for t in np.unique(restart[restart != NO_TICK]):
            events.append(
                {"kind": "restart", "tick": int(t), "nodes": int((restart == t).sum())}
            )
    # a group leg of all -1 is the materialized stacked default (no node
    # partitioned — stack_plans value-neutrality), and part_until ==
    # NO_TICK is the stacked encoding of "never heals" (solo plans use
    # None): neither is an event that occurs
    if plan.group is not None and bool((np.asarray(plan.group) >= 0).any()):
        split = int(np.asarray(plan.part_from)) if plan.part_from is not None else 0
        events.append({"kind": "partition", "tick": split,
                       "nodes": int((np.asarray(plan.group) > 0).sum()),
                       "directed": plan.reach is not None})
        if plan.part_until is not None and int(np.asarray(plan.part_until)) != NO_TICK:
            events.append({"kind": "heal", "tick": int(np.asarray(plan.part_until))})
    if plan.flap_period is not None:
        period = np.asarray(plan.flap_period)
        flappers = period > 0
        if flappers.any():
            phase = np.asarray(plan.flap_phase) if plan.flap_phase is not None else np.zeros_like(period)
            first_down = np.where(
                flappers, np.mod(-phase, np.maximum(period, 1)), np.int64(NO_TICK)
            )
            events.append({
                "kind": "flap",
                "tick": int(first_down[flappers].min()),
                "nodes": int(flappers.sum()),
                "period": int(period[flappers].max()),
                "down": int(np.asarray(plan.flap_down)[flappers].max()),
            })
    events.sort(key=lambda e: e["tick"])
    return events


# -- the convergence scorer ---------------------------------------------------


def _first_crossing(ticks, series, after: int, level: float):
    """First journal tick >= ``after`` whose series value reaches
    ``level`` — None if it never does (block-granular, like the journal)."""
    for t, v in zip(ticks, series):
        if t >= after and v >= level:
            return int(t)
    return None


def score_blocks(
    blocks: list[dict],
    plan: FaultPlan,
    *,
    n: int,
    scenario: str = "",
    scenario_id: Optional[int] = None,
) -> dict:
    """Reduce a lifecycle run journal (the ``kind == "block"`` records of
    ``sim/telemetry.py``, in order) plus the plan's event timeline into a
    scenario verdict record.

    Metrics (all in ticks, at the journal's block granularity —
    ``block_granularity_ticks`` is recorded so a consumer can't mistake
    a quantized number for an exact one):

    * ``time_to_detect`` — per crash event, first journal tick at which
      the converged base had absorbed the entire current down set
      (``detect_frac`` == 1), minus the crash tick; null if never.
    * ``rumor_half_life`` — per crash event, ticks to ``detect_frac``
      0.5: the epidemic's half-coverage time (the dissemination analog
      of a half-life; SWIM's infection model is exponential, so this is
      the meaningful single-number rate).
    * ``false_positive_suspects`` — refutations that placed, MINUS the
      plan's restarted-node count: a refutation is a LIVE node
      reincarnating over a detraction about itself (a true crash never
      refutes), but a RESTARTED node re-joins through the same
      mechanism — its one reincarnation was a true accusation outliving
      its subject, so the plan-known restart count is subtracted.  A
      flapper's post-flap refutations stay counted: flap-induced
      suspicion churn is exactly the false-positive load Lifeguard
      targets.  Raw total in ``refutations``.
    * ``rejoin_convergence_ticks`` — after the LAST restart event, ticks
      until the base census carries at least the expected end-state
      alive count with no rumors left in flight; null when the plan has
      no restarts or the run never got there.
    """
    blocks = [b for b in blocks if b.get("kind", "block") == "block"]
    events = plan_events(plan)
    ticks = [int(b["tick"]) for b in blocks]
    detect = [float(b.get("detect_frac", 0.0)) for b in blocks]
    granularity = max((int(b.get("ticks", 0)) for b in blocks), default=0)
    total_ticks = ticks[-1] if ticks else 0

    crashes = [e for e in events if e["kind"] == "crash"]
    ttd, half = [], []
    for e in crashes:
        t_full = _first_crossing(ticks, detect, e["tick"], 1.0)
        t_half = _first_crossing(ticks, detect, e["tick"], 0.5)
        ttd.append([e["tick"], None if t_full is None else t_full - e["tick"]])
        half.append([e["tick"], None if t_half is None else t_half - e["tick"]])

    def _median(pairs):
        vals = sorted(v for _, v in pairs if v is not None)
        return vals[len(vals) // 2] if vals else None

    restarts = [e for e in events if e["kind"] == "restart"]
    restarted_nodes = sum(e["nodes"] for e in restarts)
    refutations = int(sum(b.get("refuted", 0) for b in blocks))
    rejoin = None
    if restarts and blocks:
        last_restart = max(e["tick"] for e in restarts)
        expected_alive = int(up_at_host(plan, total_ticks, n).sum())
        for b in blocks:
            if (
                int(b["tick"]) >= last_restart
                and int(b.get("census_alive", -1)) >= expected_alive
                and int(b.get("rumors_active", 1)) == 0
            ):
                rejoin = int(b["tick"]) - last_restart
                break

    out = {
        "kind": "score",
        "scenario": scenario,
        "n": n,
        "ticks": total_ticks,
        "blocks": len(blocks),
        "block_granularity_ticks": granularity,
        "events": events,
        "time_to_detect": ttd,
        "time_to_detect_median": _median(ttd),
        "rumor_half_life": half,
        "rumor_half_life_median": _median(half),
        "refutations": refutations,
        "false_positive_suspects": max(0, refutations - restarted_nodes),
        "suspects_declared": int(sum(b.get("decl_suspect", 0) for b in blocks)),
        "faulty_declared": int(sum(b.get("decl_faulty", 0) for b in blocks)),
        "heal_attempts": int(sum(b.get("heal_attempts", 0) for b in blocks)),
        "final_detect_frac": detect[-1] if detect else None,
        "rejoin_convergence_ticks": rejoin,
    }
    # quorum-read journals (r17, forward/batch.py): blocks carrying the
    # replica-read fields get their worst-case quorum summary in the
    # verdict — the serve plane's "reads still ack at ⌈(R+1)/2⌉ while
    # owners are dead" bar, scored next to the recovery metrics above
    qblocks = [b for b in blocks if "quorum_ok_frac" in b]
    if qblocks:
        out["quorum_ok_frac_min"] = min(
            float(b["quorum_ok_frac"]) for b in qblocks
        )
        out["quorum_acks_min"] = min(
            int(b.get("quorum_acks_min", 0)) for b in qblocks
        )
    # topology journals (sim/topology.py; blocks carry the per-tier
    # suspicion-flow keys of tier-armed telemetry): the per-tier verdict
    # breakdown the correlated-failure scenarios are scored on.  A zone
    # cut and 100 independent crashes produce the same global counters;
    # the tier split is what tells them apart — correlated loss has no
    # live same-rack observers left to accuse, so its suspicion flow
    # arrives only from across the boundary.
    tier_keys = [nm.replace("-", "_") for nm in TIER_NAMES]
    tblocks = [b for b in blocks if f"suspects_{tier_keys[0]}" in b]
    if tblocks:
        out["suspects_by_tier"] = {
            k: int(sum(b.get(f"suspects_{k}", 0) for b in tblocks))
            for k in tier_keys
        }
        # declare-time ground truth (the plan knows who was up), not the
        # refutation arithmetic above: a declaration about a LIVE target
        # is a false positive the moment it is made
        out["false_positive_by_tier"] = {
            k: int(sum(b.get(f"false_suspects_{k}", 0) for b in tblocks))
            for k in tier_keys
        }
        # per-tier time-to-detect: how long after the first fault event
        # the failure becomes VISIBLE at each tier distance (first block
        # with suspicion flow at that tier) — block-granular like every
        # other latency here
        anchor = min(
            (e["tick"] for e in events if e["kind"] in ("crash", "partition", "flap")),
            default=None,
        )
        ttd_tier: dict = {}
        for k in tier_keys:
            first = None
            if anchor is not None:
                for b in tblocks:
                    if int(b["tick"]) >= anchor and float(b.get(f"suspects_{k}", 0)) > 0:
                        first = int(b["tick"]) - anchor
                        break
            ttd_tier[k] = first
        out["time_to_detect_by_tier"] = ttd_tier
    # directed-partition journals: refutations split by whether the
    # refuting subject sits in the unreachable direction of the window
    # (telemetry.fetch attributes by the plan's static group/reach legs)
    dblocks = [b for b in blocks if "refuted_unreachable_dir" in b]
    if dblocks:
        out["refutations_unreachable_dir"] = int(
            sum(b.get("refuted_unreachable_dir", 0) for b in dblocks)
        )
        out["refutations_reachable_dir"] = int(
            sum(b.get("refuted_reachable_dir", 0) for b in dblocks)
        )
    if scenario_id is not None:
        # batched-fleet journals: which member of the stacked plan this
        # verdict scores (same id the fleet's block records carry)
        out["scenario_id"] = int(scenario_id)
    return out


# -- stats bridge -------------------------------------------------------------

CHAOS_STAT_PREFIX = "ringpop.sim.chaos"

# score field -> (statsd method, key suffix) under the chaos namespace;
# documented with the rest of the sim-plane keys in OBSERVABILITY.md
CHAOS_STAT_KEYS = {
    "time_to_detect_median": ("gauge", "time-to-detect"),
    "rumor_half_life_median": ("gauge", "rumor.half-life"),
    "false_positive_suspects": ("gauge", "false-positive.suspects"),
    "rejoin_convergence_ticks": ("gauge", "rejoin.convergence"),
    "final_detect_frac": ("gauge", "detection.fraction"),
}


def emit_score_stats(reporter, score: dict, prefix: str = CHAOS_STAT_PREFIX) -> None:
    """Feed a scenario verdict into a host-plane ``StatsReporter`` under
    ``ringpop.sim.chaos.*`` (null metrics — e.g. a plan with no restarts
    has no rejoin — are skipped, not zeroed)."""
    for field, (kind, suffix) in CHAOS_STAT_KEYS.items():
        value = score.get(field)
        if value is None:
            continue
        assert kind == "gauge"
        reporter.gauge(f"{prefix}.{suffix}", float(value))
