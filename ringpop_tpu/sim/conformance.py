"""Lockstep conformance: sequential reference semantics vs the vectorized sim.

The BASELINE gate is *bit-identical member states versus the sequential
reference semantics* — this module provides both halves:

* :class:`SequentialSwim` — a per-node, change-at-a-time interpreter of the
  SWIM update rules, written against the scalar semantics core
  (``ringpop_tpu.swim.member`` — the same override/refutation/precedence
  rules the host plane runs, parity ``swim/memberlist.go:310-390``), with
  dict member tables per node exactly like the reference's
  ``memberlist.members`` map.  No arrays, no vectorization: every phase is
  plain Python loops applying one candidate change at a time.
* :class:`LockstepRunner` — drives :class:`SequentialSwim` and
  ``fullview.FullViewSim`` through the *same* injected per-tick randomness
  (ping targets, ping-req peers, fault masks) and asserts the full protocol
  state is identical after every tick: membership views (status +
  incarnation + presence), dissemination records (change set + piggyback
  counters), and suspicion timers (pending transition + deadline).

Why this works: change application is a join-semilattice max over
``(incarnation, state-precedence)`` (``member.go:79-128``), so applying a
candidate batch max-merged (vectorized) and applying the same candidates
one-at-a-time in any order (sequential reference) reach the same state.  The
harness proves the vectorized engine implements exactly that — including the
side-effect paths that do NOT commute trivially: refutation-by-reincarnation,
timer schedule/cancel/dedup, full-sync + reverse full-sync, piggyback expiry,
and the evict path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.swim.member import ALIVE, FAULTY, LEAVE, SUSPECT, TOMBSTONE
from ringpop_tpu.sim.fullview import (
    Faults,
    FullViewParams,
    FullViewSim,
    STATE_BITS,
)

_DETRACTIONS = (SUSPECT, FAULTY, TOMBSTONE)


def _key(inc: int, status: int) -> int:
    return (int(inc) << STATE_BITS) | int(status)


@dataclass
class _NodeState:
    """One node's protocol state, reference-shaped: maps keyed by member."""

    view: Dict[int, Tuple[int, int]] = field(default_factory=dict)  # j -> (status, inc)
    changes: Dict[int, int] = field(default_factory=dict)  # j -> pcount
    pending: Dict[int, Tuple[int, int]] = field(default_factory=dict)  # j -> (state, deadline)


class SequentialSwim:
    """Sequential-semantics SWIM cluster interpreter (the reference oracle)."""

    def __init__(self, params: FullViewParams, converged: bool = True):
        self.params = params
        self.tick_no = 0
        n = params.n
        self.nodes = [_NodeState() for _ in range(n)]
        for i in range(n):
            if converged:
                self.nodes[i].view = {j: (ALIVE, 0) for j in range(n)}
            else:
                self.nodes[i].view = {i: (ALIVE, 0)}

    # -- scalar update pipeline (memberlist.Update per candidate) -----------

    def _timeout_for(self, st: int) -> int:
        p = self.params
        return {SUSPECT: p.suspect_ticks, FAULTY: p.faulty_ticks, TOMBSTONE: p.tombstone_ticks}[st]

    def _apply(self, r: int, j: int, cinc: int, cst: int, now_ms: int) -> None:
        """Apply one candidate change about member ``j`` at node ``r``
        (parity: ``memberlist.go:310-390`` + ``node.go:424-445``)."""
        node = self.nodes[r]
        local = node.view.get(j)
        refute = (
            r == j
            and cst in _DETRACTIONS
            and local is not None
            and cinc >= local[1]
        )
        if refute:
            node.view[r] = (ALIVE, now_ms)
            applied = True
        else:
            local_eff = _key(local[1], local[0]) if local is not None else -1
            wins = _key(cinc, cst) > local_eff
            if wins and local is None and cst == TOMBSTONE:
                wins = False  # first-seen tombstones refused (memberlist.go:421-426)
            if wins:
                node.view[j] = (cst, cinc)
            applied = wins
        if not applied:
            return
        node.changes[j] = 0  # RecordChange (node.go:425-427)
        eff_st = node.view[j][0]
        if eff_st in (ALIVE, LEAVE):
            node.pending.pop(j, None)  # Cancel (node.go:431)
        elif j != r:
            prev = node.pending.get(j)
            if prev is None or prev[0] != eff_st:  # same-state dedup
                node.pending[j] = (eff_st, self.tick_no + self._timeout_for(eff_st))

    def _apply_batch(self, batches: Dict[int, Dict[int, Tuple[int, int]]], now_ms: int) -> None:
        """Apply per-receiver candidate sets collected from one snapshot."""
        for r, cands in batches.items():
            for j, (cinc, cst) in cands.items():
                self._apply(r, j, cinc, cst, now_ms)

    # -- one protocol period -------------------------------------------------

    def step(
        self,
        targets: np.ndarray,
        peers: np.ndarray,
        faults: Optional[Faults] = None,
    ) -> None:
        p = self.params
        n = p.n
        now_ms = (self.tick_no + 1) * p.tick_ms
        up = np.asarray(faults.up) if faults is not None and faults.up is not None else np.ones(n, bool)
        group = np.asarray(faults.group) if faults is not None and faults.group is not None else None

        def connected(a: int, b: int) -> bool:
            if not (up[a] and up[b]):
                return False
            if group is not None and group[a] >= 0 and group[b] >= 0 and group[a] != group[b]:
                return False
            return True

        pingable: List[set] = [
            {
                j
                for j, (st, _) in self.nodes[i].view.items()
                if j != i and st in (ALIVE, SUSPECT)
            }
            for i in range(n)
        ]
        any_pingable = [bool(s) for s in pingable]
        delivered = [
            any_pingable[i] and up[i] and connected(i, int(targets[i])) for i in range(n)
        ]

        # maxP per node — the exact expression the vectorized engine uses,
        # evaluated through jnp so float semantics agree bit-for-bit
        num = np.array([len(s) for s in pingable], np.int32)
        max_p = np.asarray(
            (p.p_factor * jnp.ceil(jnp.log10(num.astype(jnp.float32) + 1.0))).astype(jnp.int32)
        )

        # -- request leg: senders' unexpired changes, delivered to targets
        send_mask: Dict[int, List[int]] = {}
        inbound: Dict[int, Dict[int, Tuple[int, int]]] = {}
        for i in range(n):
            if not delivered[i]:
                continue
            t = int(targets[i])
            sends = [j for j, pc in self.nodes[i].changes.items() if pc < max_p[i]]
            send_mask[i] = sends
            dst = inbound.setdefault(t, {})
            for j in sends:
                st, inc = self.nodes[i].view[j]
                prev = dst.get(j)
                if prev is None or _key(inc, st) > _key(prev[0], prev[1]):
                    dst[j] = (inc, st)
        self._apply_batch(inbound, now_ms)

        # -- full-sync detection (post-request-leg state)
        full_sync = [False] * n
        for i in range(n):
            if not delivered[i]:
                continue
            t = int(targets[i])
            has_any_t = bool(self.nodes[t].changes)
            full_sync[i] = (not has_any_t) and (self.nodes[i].view != self.nodes[t].view)

        # -- response leg: target's changes (full membership on full sync)
        responses: Dict[int, Dict[int, Tuple[int, int]]] = {}
        for i in range(n):
            if not delivered[i]:
                continue
            t = int(targets[i])
            tn = self.nodes[t]
            if full_sync[i]:
                cand = {j: (inc, st) for j, (st, inc) in tn.view.items()}
            else:
                cand = {
                    j: (tn.view[j][1], tn.view[j][0])
                    for j, pc in tn.changes.items()
                    if pc < max_p[t]
                }
            responses[i] = cand
        self._apply_batch(responses, now_ms)

        # -- reverse full sync: target pulls the sender's membership
        rfs: Dict[int, Dict[int, Tuple[int, int]]] = {}
        for i in range(n):
            if not (full_sync[i] and delivered[i]):
                continue
            t = int(targets[i])
            dst = rfs.setdefault(t, {})
            for j, (st, inc) in self.nodes[i].view.items():
                prev = dst.get(j)
                if prev is None or _key(inc, st) > _key(prev[0], prev[1]):
                    dst[j] = (inc, st)
        self._apply_batch(rfs, now_ms)

        # -- piggyback bumps + expiry
        got_pinged = [False] * n
        for i in range(n):
            if delivered[i]:
                got_pinged[int(targets[i])] = True
        for i in range(n):
            node = self.nodes[i]
            bumps: Dict[int, int] = {}
            for j in send_mask.get(i, ()):
                if j in node.changes:
                    bumps[j] = bumps.get(j, 0) + 1
            if got_pinged[i]:
                for j, pc in node.changes.items():
                    if pc < max_p[i]:
                        bumps[j] = bumps.get(j, 0) + 1
            for j, b in bumps.items():
                node.changes[j] += b
            for j in [j for j, pc in node.changes.items() if pc >= max_p[i]]:
                del node.changes[j]

        # -- failed direct probe → indirect ping-req → Suspect
        suspects: Dict[int, Dict[int, Tuple[int, int]]] = {}
        for i in range(n):
            if not (any_pingable[i] and up[i] and not delivered[i]):
                continue
            t = int(targets[i])
            pool = pingable[i] - {t}
            ok_ct = 0
            reached = False
            for pr in peers[i]:
                pr = int(pr)
                peer_ok = pr in pool and connected(i, pr)
                if peer_ok:
                    ok_ct += 1
                    if connected(pr, t) and up[t]:
                        reached = True
            if ok_ct == 0:  # all errors → inconclusive (node.go:497-503)
                continue
            if reached:
                continue
            cur = self.nodes[i].view.get(t)
            if cur is None:
                continue
            suspects[i] = {t: (cur[1], SUSPECT)}
        self._apply_batch(suspects, now_ms)

        # -- timers fire against sim time (state_transitions.go:90-117)
        fire: List[Tuple[int, int, int]] = []
        for i in range(n):
            for j, (src_st, deadline) in list(self.nodes[i].pending.items()):
                if self.tick_no >= deadline:
                    fire.append((i, j, src_st))
                    del self.nodes[i].pending[j]
        transitions: Dict[int, Dict[int, Tuple[int, int]]] = {}
        evictions: List[Tuple[int, int]] = []
        for i, j, src_st in fire:
            if src_st == TOMBSTONE:
                evictions.append((i, j))
                continue
            nxt = FAULTY if src_st == SUSPECT else TOMBSTONE
            cur = self.nodes[i].view.get(j)
            if cur is None:
                continue
            transitions.setdefault(i, {})[j] = (cur[1], nxt)
        self._apply_batch(transitions, now_ms)
        for i, j in evictions:
            self.nodes[i].view.pop(j, None)
            self.nodes[i].changes.pop(j, None)

        self.tick_no += 1

    # -- array export for comparison ----------------------------------------

    def as_arrays(self):
        n = self.params.n
        status = np.zeros((n, n), np.int8)
        inc = np.zeros((n, n), np.int32)
        present = np.zeros((n, n), bool)
        has_change = np.zeros((n, n), bool)
        pcount = np.zeros((n, n), np.int32)
        pending = np.full((n, n), -1, np.int8)
        deadline = np.zeros((n, n), np.int32)
        for i, node in enumerate(self.nodes):
            for j, (st, ic) in node.view.items():
                present[i, j] = True
                status[i, j] = st
                inc[i, j] = ic
            for j, pc in node.changes.items():
                has_change[i, j] = True
                pcount[i, j] = pc
            for j, (st, dl) in node.pending.items():
                pending[i, j] = st
                deadline[i, j] = dl
        return status, inc, present, has_change, pcount, pending, deadline


class LockstepRunner:
    """Drive the sequential oracle and the vectorized engine in lockstep."""

    def __init__(self, n: int, seed: int = 0, converged: bool = True, **param_kw):
        self.params = FullViewParams(n=n, **param_kw)
        self.seq = SequentialSwim(self.params, converged=converged)
        self.vec = FullViewSim(n=n, seed=seed, converged=converged, **param_kw)
        self.rng = np.random.default_rng(seed)

    def draw(self) -> Tuple[np.ndarray, np.ndarray]:
        """Per-tick randomness from the oracle's pingable sets — both engines
        receive identical targets/peers (the reference's shuffled round-robin
        and random peer draw, made injectable)."""
        n = self.params.n
        targets = np.zeros(n, np.int32)
        peers = np.zeros((n, self.params.ping_req_size), np.int32)
        for i in range(n):
            pool = sorted(
                j
                for j, (st, _) in self.seq.nodes[i].view.items()
                if j != i and st in (ALIVE, SUSPECT)
            )
            if pool:
                targets[i] = self.rng.choice(pool)
                ppool = [j for j in pool if j != targets[i]] or pool
                peers[i] = self.rng.choice(ppool, size=self.params.ping_req_size)
            else:
                targets[i] = (i + 1) % n
                peers[i] = (i + 1) % n
        return targets, peers

    def tick(self, faults: Faults = Faults()) -> None:
        targets, peers = self.draw()
        self.seq.step(targets, peers, faults)
        self.vec.tick(faults, targets=jnp.asarray(targets), peers=jnp.asarray(peers))

    def assert_identical(self) -> None:
        """Bit-identical protocol state across both engines."""
        status, inc, present, has_change, pcount, pending, deadline = self.seq.as_arrays()
        s = self.vec.state
        v_status = np.asarray(s.status)
        v_inc = np.asarray(s.incarnation)
        v_present = np.asarray(s.present)
        v_has = np.asarray(s.has_change)
        v_pcount = np.asarray(s.pcount)
        v_pending = np.asarray(s.pending)
        v_deadline = np.asarray(s.deadline)

        def _diff(name, a, b, mask=None):
            if mask is not None:
                a = np.where(mask, a, 0)
                b = np.where(mask, b, 0)
            if not (a == b).all():
                idx = np.argwhere(a != b)[:8]
                raise AssertionError(
                    f"tick {self.seq.tick_no}: {name} diverged at cells "
                    f"{idx.tolist()}: seq={a[tuple(idx[0])]} vec={b[tuple(idx[0])]}"
                )

        _diff("present", present, v_present)
        _diff("status", status, v_status, present)
        _diff("incarnation", inc, v_inc, present)
        _diff("has_change", has_change, v_has)
        _diff("pcount", pcount, v_pcount, has_change)
        _diff("pending", pending, v_pending)
        _diff("deadline", deadline, v_deadline, pending >= 0)

    def run(self, ticks: int, faults: Faults = Faults(), check_every: int = 1) -> None:
        for k in range(ticks):
            self.tick(faults)
            if (k + 1) % check_every == 0:
                self.assert_identical()
