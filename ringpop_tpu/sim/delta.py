"""Scalable delta-dissemination simulator: O(N·K) state for million-node
clusters.

A full per-node view is O(N²) — 1M nodes would need 1TB.  But a SWIM view is
``converged base ⊔ set of applied changes``, and because change application
is a lattice max (order-independent — see ``ringpop_tpu.swim.member``), a
node's view is EXACTLY determined by *which* of the K in-flight changes it
has learned.  So the cluster state compresses to:

* a change table (member, incarnation, status) × K — the rumors in flight;
* ``learned[N, W]``  — which rumors each node has absorbed, BIT-PACKED 32
  slots per uint32 word along the rumor axis (``sim/packbits``);
* ``pcount[N, K]``   — per-node piggyback counters with the SWIM maxP bound
  (``disseminator.go:75-97``).

One tick: every node pings one peer (fault-masked), rumors ride both legs of
the exchange (request via scatter-or = ``segment_max``, response via gather),
counters bump, expired rumors stop riding.  Convergence = every live node has
learned every rumor — the million-node analog of "all checksums agree".

This is the benchmark engine (BASELINE north star: 1M-node convergence
< 60s).  Failure-detection *dynamics* (probe → suspect → timers → refute)
live in the exact O(N²) engine (``fullview``); here rumors are injected,
matching the reference's dissemination-bound analysis (the SWIM paper's
infection model).

Sharding: arrays are sharded over the node axis (`shard_map`/NamedSharding on
a mesh); the per-tick cross-shard traffic is the scatter/gather of (N, K)
bools — XLA lowers these to all-to-all/all-gather over ICI.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.packbits import (
    and_reduce_rows,
    or_reduce_rows,
    pack_bool,
    row_mask,
    unpack_bits,
)


class DeltaState(NamedTuple):
    learned: jax.Array  # uint32[N, W], W = ceil(K/32) — packed rumor bits
    pcount: jax.Array  # int8[N, K]
    # derived invariant, carried so it is MATERIALIZED at tick boundaries:
    # ride_ok == pack_bool(pcount < max_p).  Recomputing it inside the tick
    # lets XLA:CPU inline the 32-wide pack-reduce into the per-element
    # pcount fusion (one re-derivation per BIT — measured 2x tick cost);
    # a loop-carried leaf is the one materialization fence XLA cannot
    # strip.  ``step`` maintains it; ``init_state`` seeds it all-riding.
    ride_ok: jax.Array  # uint32[N, W]
    tick: jax.Array  # int32
    key: jax.Array  # PRNG key


# int8 piggyback counters can take a sender + receiver bump (+2) in one tick
# from max_p-1, so the usable cap is 126, not 127 — shared by every engine
INT8_SAFE_MAX_P = 126

# -- topology tiers (the sim/topology.py compile target) ----------------------
# The deployment hierarchy is FIXED at three levels — rack within zone
# within region — so every topology leg has a STATIC shape (``tier_ids``
# is int32[3, N], ``tier_drop`` float32[4]) and heterogeneous scenarios
# stack into one dense fleet axis without shape negotiation (a flat
# topology just repeats ids across levels).  The tier of an (a → b) leg
# is the number of levels whose ids differ — a tree property: same rack
# ⇒ same zone ⇒ same region, so the sum IS the boundary count.
TIER_LEVELS = 3
N_TIERS = TIER_LEVELS + 1
TIER_NAMES = ("same-rack", "cross-rack", "cross-zone", "cross-region")


def resolve_max_p(n: int, p_factor: int, max_p: Optional[int]) -> int:
    """SWIM dissemination bound maxP = pFactor·⌈log10(n+1)⌉ unless overridden
    (parity: ``disseminator.go:75-97``)."""
    if max_p is not None:
        return max_p
    return int(p_factor * np.ceil(np.log10(n + 1)))


def clamped_max_p(params) -> int:
    """The int8-safe piggyback cap every engine compares counters against.
    ONE definition on purpose: the carried ``ride_ok`` invariant
    (== ``pack_bool(pcount < clamped_max_p)``) is maintained at several
    sites per engine (init, step, admit, snapshot migration, the golden
    tests), and any two of them disagreeing on the clamp silently corrupts
    the gate.  Works for DeltaParams and LifecycleParams alike (both carry
    ``resolved_max_p``)."""
    return min(params.resolved_max_p(), INT8_SAFE_MAX_P)


@dataclass(frozen=True)
class DeltaParams:
    n: int
    k: int  # change-table capacity (rumors in flight)
    p_factor: int = 15  # disseminator.go:35
    max_p: Optional[int] = None  # override; default pFactor*ceil(log10(n+1))
    # ping-partner topology per tick:
    #   "shift"   — targets[i] = (i + s) % n with a fresh random shift s each
    #               tick: every node pings AND is pinged exactly once, the
    #               exchange is a pure roll/gather (no scatter — XLA lowers
    #               TPU scatters serially), and under sharding it maps to a
    #               collective permute over ICI.  Same epidemic doubling as
    #               uniform draws (a set S infects S ∪ (S+s) per tick).
    #   "uniform" — independent uniform target per node (collisions merge
    #               via scatter-max), closest to the reference's shuffled
    #               round-robin when probe independence matters.
    exchange: str = "shift"
    # PRNG family: "threefry" = the jax.random draws the frozen goldens pin;
    # "counter" = the partition-invariant stateless generator (sim/prng.py),
    # shard-local with zero collectives and identical lanes on any mesh —
    # the sharded-caller/simbench default.  See LifecycleParams.rng.
    rng: str = "threefry"
    # optional Mesh with a >1-way "node" axis: lower the shift exchange's
    # roll legs as shard-local crossing-block ppermutes
    # (parallel/shift.shard_roll) instead of GSPMD's plane all-gathers.
    # Bit-identical; ``sharded_delta_step`` injects the run's mesh.
    exchange_mesh: Optional["jax.sharding.Mesh"] = None
    # sub-block factor H (H+1 sends per rolled leaf per leg) and the r11
    # pipelined-vs-sequential leg lowering — see LifecycleParams for the
    # full story; both only read when exchange_mesh is set, and both
    # bit-identical + census-identical across settings.
    exchange_h: int = 2
    exchange_pipelined: bool = True

    def resolved_max_p(self) -> int:
        return resolve_max_p(self.n, self.p_factor, self.max_p)


@dataclass(frozen=True)
class DeltaFaults:
    """The per-tick fault model both O(N·K) engines evaluate.

    Every field is a pytree LEAF (the registration below carries no
    aux_data), so sweeping any of them — including ``drop_rate``, which
    used to ride static and forced a full recompile per distinct rate —
    reuses one compilation.  ``None`` legs are static structure: a
    fault-free ``DeltaFaults()`` traces to exactly the fault-free program.

    * ``up`` — process liveness.
    * ``group``/``reach`` — partition groups; without ``reach`` the
      partition is symmetric (same group ⇔ connected).  ``reach[G, G]``
      makes it DIRECTED: the (a → b) exchange is delivered iff
      ``reach[group[a], group[b]]`` (the request direction names the RPC;
      its response rides the same verdict).  Group -1 is always
      unpartitioned, reach or not.
    * ``drop_rate`` — scalar per-leg loss probability (traced).
    * ``drop_node`` — float32[N] per-node loss: a leg survives with
      probability ``(1-drop_rate)·(1-drop_node[a])·(1-drop_node[b])``
      (independent loss processes compose by survival product).  This is
      also how the chaos plane expresses slow-node probe-timeout
      inflation: an ack that tends to arrive after the timeout is a lost
      leg with that probability (``sim/chaos.py``).
    * ``tier_ids``/``tier_drop`` — the topology legs (compiled by
      ``sim/topology.py``): per-node rack/zone/region ids plus a tiny
      per-tier loss table indexed by the (a → b) leg's tier distance
      (:func:`tier_pair_drop`) — per-TIER probe-timeout inflation
      generalizing the slow-node inflation above (a cross-zone ack that
      tends to arrive after the timeout IS a lost leg at that boundary).
      The tier coin is a SEPARATE stateless draw site (``rng="counter"``
      only), so a member whose table is all-zero — the stacked-fleet
      default — draws coins that always pass and stays bit-identical to
      a member with no topology legs at all.
    * ``suspect_ticks`` — traced suspicion-timeout override (int32
      scalar; -1 = "use the static ``params.suspect_ticks``", the
      value-neutral stacked default).  ``None`` compiles out to the
      exact static program; a concrete value makes the suspicion-timeout
      axis batchable through the Monte-Carlo fleet like every other
      plan leg.
    """

    up: Optional[jax.Array] = None  # bool[N]
    group: Optional[jax.Array] = None  # int32[N], -1 = unpartitioned
    drop_rate: Optional[jax.Array] = None  # float32[] (traced; None = no loss)
    drop_node: Optional[jax.Array] = None  # float32[N] per-node loss
    reach: Optional[jax.Array] = None  # bool[G, G] directed group reachability
    tier_ids: Optional[jax.Array] = None  # int32[TIER_LEVELS, N] rack/zone/region
    tier_drop: Optional[jax.Array] = None  # float32[N_TIERS] per-tier loss
    suspect_ticks: Optional[jax.Array] = None  # int32[] traced timeout (-1 = params)


# registered WITH keys so path-aware tree walks (the canonical partition
# table in parallel/partition.py matches leaves by name) see field names
# instead of flat indices; flatten order and aux are unchanged, so every
# existing tree_map/vmap treatment is identical
_FAULT_FIELDS = (
    "up", "group", "drop_rate", "drop_node", "reach",
    "tier_ids", "tier_drop", "suspect_ticks",
)

jax.tree_util.register_pytree_with_keys(
    DeltaFaults,
    lambda f: (
        tuple(
            (jax.tree_util.GetAttrKey(n), getattr(f, n))
            for n in _FAULT_FIELDS
        ),
        None,
    ),
    lambda aux, c: DeltaFaults(**dict(zip(_FAULT_FIELDS, c))),
)


def resolve_faults(faults, tick):
    """The one seam that lets every engine/query accept EITHER a static
    ``DeltaFaults`` or a time-varying ``chaos.FaultPlan``: a plan carries
    an ``at_tick`` method (duck-typed to avoid a sim/chaos import cycle)
    and is evaluated shard-locally at the given tick; a plain fault model
    passes through untouched, so the static path traces to exactly the
    program it always did."""
    at = getattr(faults, "at_tick", None)
    return faults if at is None else at(tick)


def pair_connected(faults: DeltaFaults, a, b):
    """Static (loss-free) connectivity for the (a → b) exchange between
    node index arrays ``a`` and ``b`` under the fault model: both
    processes up and the partition (symmetric groups, or the directed
    ``reach`` matrix when present) lets a's group send to b's."""
    ok = jnp.ones(a.shape, dtype=bool)
    if faults.up is not None:
        ok &= faults.up[a] & faults.up[b]
    if faults.group is not None:
        g = faults.group
        ga, gb = g[a], g[b]
        # getattr: fullview's own Faults class (symmetric-only oracle
        # engine) routes through here too and carries no reach field
        reach = getattr(faults, "reach", None)
        if reach is not None:
            # directed: group -1 stays universally reachable; in-range
            # groups consult the tiny replicated [G, G] matrix
            r = reach[jnp.maximum(ga, 0), jnp.maximum(gb, 0)]
            ok &= (ga < 0) | (gb < 0) | r
        else:
            ok &= (ga < 0) | (gb < 0) | (ga == gb)
    return ok


def has_drop(faults: DeltaFaults) -> bool:
    """Static (trace-time) check: does this fault model lose messages at
    all?  The gate every drop-coin draw sits behind — None legs compile
    out entirely, keeping the loss-free trace the one HEAD had."""
    return faults.drop_rate is not None or faults.drop_node is not None


def leg_survives(faults: DeltaFaults, u, a, b):
    """bool mask: the (a → b) message leg survives packet loss, given
    uniform draws ``u`` (shaped like ``a``/``b``).  With only the scalar
    ``drop_rate`` this is the exact historical comparison ``u >=
    drop_rate`` (bit-compatible with the frozen goldens); per-node rates
    compose as independent survival products."""
    if faults.drop_node is None:
        return u >= faults.drop_rate
    dn = faults.drop_node
    keep = (1.0 - dn[a]) * (1.0 - dn[b])
    if faults.drop_rate is not None:
        keep = keep * (1.0 - jnp.float32(faults.drop_rate))
    return u < keep


# -- topology tier evaluation -------------------------------------------------


def check_tier_legs(faults: DeltaFaults) -> bool:
    """Static (trace-time) gate for the topology legs: both present (a
    topology) or both absent (flat — the legs compile out entirely).
    One alone is a construction error, refused loudly."""
    has_ids = getattr(faults, "tier_ids", None) is not None
    has_drop_t = getattr(faults, "tier_drop", None) is not None
    if has_ids != has_drop_t:
        raise ValueError(
            "topology legs come as a pair: tier_ids (int32[3, N]) and "
            "tier_drop (float32[4]) — one without the other is a "
            "construction error (sim/topology.py compiles both)"
        )
    return has_ids


def tier_pair(faults: DeltaFaults, a, b) -> jax.Array:
    """int32 tier distance of the (a → b) leg: how many hierarchy levels
    the pair's ids differ in — 0 same-rack, 1 cross-rack/same-zone, 2
    cross-zone/same-region, 3 cross-region (``TIER_NAMES``).  The two id
    gathers are the same class of row lookup the partition legs already
    do (``group[a]``/``group[b]``) and ride the caller's phase scope;
    the sum is elementwise."""
    ids = faults.tier_ids
    da = jnp.take(ids, a, axis=-1)  # [TIER_LEVELS, *a.shape]
    db = jnp.take(ids, b, axis=-1)
    return (da != db).astype(jnp.int32).sum(axis=0)


def tier_pair_drop(faults: DeltaFaults, a, b) -> jax.Array:
    """float32 per-leg loss probability from the tiny per-tier table —
    the blocked one-hot gather form (sum of ``(tier == t) · table[t]``
    over the static tier count) instead of a dense [G, G] product, per
    the sparse-GNN-on-dense-hardware pattern (PAPERS.md 1906.11786).
    The expansion is elementwise in the node lane, so the ``fault-plan``
    scope it runs under stays collective-free under any mesh (jaxlint
    RPJ203/RPJ206 forbid a collective there)."""
    t = tier_pair(faults, a, b)
    with jax.named_scope("fault-plan"):
        drop = jnp.zeros(t.shape, jnp.float32)
        for ti in range(N_TIERS):
            drop = drop + jnp.where(
                t == ti, jnp.asarray(faults.tier_drop, jnp.float32)[..., ti], 0.0
            )
    return drop


def init_state(params: DeltaParams, seed: int = 0, sources: Optional[np.ndarray] = None) -> DeltaState:
    """K rumors, each initially known only to its source node (default:
    rumor j starts at node j mod N)."""
    n, k = params.n, params.k
    if sources is None:
        sources = np.arange(k, dtype=np.int64) % n
    learned_b = jnp.zeros((n, k), dtype=bool).at[jnp.asarray(sources), jnp.arange(k)].set(True)
    return DeltaState(
        learned=pack_bool(learned_b),
        pcount=jnp.zeros((n, k), dtype=jnp.int8),
        ride_ok=pack_bool(
            jnp.zeros((n, k), jnp.int8)
            < jnp.int8(clamped_max_p(params))
        ),
        tick=jnp.asarray(0, jnp.int32),
        key=jax.random.PRNGKey(seed),
    )


def step(params: DeltaParams, state: DeltaState, faults: DeltaFaults = DeltaFaults()) -> DeltaState:
    """One protocol period for all N nodes (jit/shard-friendly: fixed
    shapes; with the default "shift" topology the whole exchange is
    bitwise word ops on the packed plane plus index-vector row gathers —
    no scatter, and no traced-shift rolls, whose slice-select lowering
    XLA:CPU re-derives per consuming element; see PERF.md "Round 3").
    Value-identical to the unpacked formulation — certified bit-for-bit
    by tests/test_delta_golden.py.  The ``jax.named_scope`` sections name
    the protocol phase in profiler traces and HLO metadata — the same
    vocabulary as the lifecycle engine (``analysis/phases.PHASES``), so
    the collective census can attribute this engine's sharded traffic
    too; scopes are metadata-only and change no values (jaxlint RPA105
    requires them).

    ``faults`` may be a static ``DeltaFaults`` or a time-varying
    ``chaos.FaultPlan`` — a plan is evaluated shard-locally at
    ``state.tick`` (``resolve_faults``); a constant plan traces to the
    exact static program."""
    faults = resolve_faults(faults, state.tick)
    with jax.named_scope("tick-prologue"):
        n, k = params.n, params.k
        max_p = jnp.int8(clamped_max_p(params))
        if params.rng not in ("threefry", "counter"):
            raise ValueError(f"unknown rng family {params.rng!r}")
        use_counter = params.rng == "counter"
        if use_counter:
            # stateless counter stream (sim/prng.py): the key leaf carries
            # the seed material unchanged and the tick counter advances the
            # stream
            from ringpop_tpu.sim import prng as _prng

            key = state.key
            cseed = _prng.fold_key(state.key)
            ctick = state.tick
        else:
            key, k_target, k_drop = jax.random.split(state.key, 3)
        i_all = jnp.arange(n, dtype=jnp.int32)

    with jax.named_scope("ping-target"):
        shift_mode = params.exchange == "shift"
        emesh = params.exchange_mesh
        use_sm = (
            shift_mode
            and emesh is not None
            and emesh.shape.get("node", 1) > 1
            and n % emesh.shape["node"] == 0
        )
        if shift_mode:
            if use_counter:
                s = _prng.draw_randint(cseed, ctick, _prng.D_SHIFT, 0, 1, n)
            else:
                s = jax.random.randint(k_target, (), 1, n, dtype=jnp.int32)
            targets = (i_all + s) % n
        else:
            if use_counter:
                targets = _prng.draw_randint(cseed, ctick, _prng.D_TARGET, i_all, 0, n - 1)
            else:
                targets = jax.random.randint(k_target, (n,), 0, n - 1, dtype=jnp.int32)
            targets = jnp.where(targets >= i_all, targets + 1, targets)

        up = faults.up if faults.up is not None else jnp.ones(n, dtype=bool)
        conn = pair_connected(faults, i_all, targets)
        if has_drop(faults):
            drop_u = (
                _prng.draw_uniform(cseed, ctick, _prng.D_DROP, i_all)
                if use_counter
                else jax.random.uniform(k_drop, (n,))
            )
            conn &= leg_survives(faults, drop_u, i_all, targets)
        if check_tier_legs(faults):
            # topology tier loss (sim/topology.py): a SEPARATE stateless
            # coin per leg, so an all-zero table — the stacked-fleet
            # default — passes every draw and perturbs nothing (other
            # sites' streams are independent by construction).  An extra
            # threefry split would shift every downstream draw instead,
            # so the topology legs require the counter family.
            if not use_counter:
                raise ValueError(
                    "topology tier legs need rng='counter': their loss "
                    "coin is an extra stateless draw site; under threefry "
                    "the extra split would shift every other draw"
                )
            topo_u = _prng.draw_uniform(cseed, ctick, _prng.D_TOPO, i_all)
            conn &= topo_u >= tier_pair_drop(faults, i_all, targets)

    with jax.named_scope("rumor-exchange"):
        if shift_mode:
            ride_ok_w = state.ride_ok  # carried, materialized at the tick edge
            cmask = row_mask(conn)
            riding_w = state.learned & ride_ok_w
            # request leg: sender i's rumors land at targets[i].  The cyclic
            # permutation makes delivery a row gather (receipt uniqueness is
            # structural: node j is pinged only by j-s).
            sent_w = riding_w & cmask
            if use_sm and params.exchange_pipelined:
                # sharded callers, r11 default: both legs in one fused
                # pipelined region — response-leg ppermutes issued as soon
                # as their two request-leg window pieces arrive, while the
                # request merge computes (parallel/shift.shard_roll_pipelined;
                # bit-identical and census-identical to the sequential legs)
                from jax.sharding import PartitionSpec as _P

                from ringpop_tpu.parallel.shift import shard_roll_pipelined

                wspec = _P("node", "rumor" if "rumor" in emesh.shape else None)
                inbound_w, got_pinged, resp_src = shard_roll_pipelined(
                    (sent_w, conn), s, emesh, "node", (wspec, _P("node")),
                    carry=(state.learned, ride_ok_w), carry_specs=(wspec, wspec),
                    leg2_of=lambda inb, gp, lrn, rd: (lrn | inb) & rd,
                    spec2=wspec, h=params.exchange_h,
                )
                learned1_w = state.learned | inbound_w
            else:
                if use_sm:
                    # sequential r8 legs (kept for the pipelined_exchange
                    # A/B): both rolls as explicit shard-local crossing-block
                    # ppermutes instead of GSPMD's plane-sized all-gathers;
                    # bit-identical data motion
                    from jax.sharding import PartitionSpec as _P

                    from ringpop_tpu.parallel.shift import shard_roll

                    wspec = _P("node", "rumor" if "rumor" in emesh.shape else None)
                    inbound_w, got_pinged = shard_roll(
                        (sent_w, conn), s, emesh, "node", (wspec, _P("node")),
                        h=params.exchange_h,
                    )
                else:
                    idx_fwd = jnp.mod(i_all - s, n)
                    inbound_w = sent_w[idx_fwd]
                    got_pinged = conn[idx_fwd]
                learned1_w = state.learned | inbound_w
                # response leg: the target's riding rumors come back to the pinger
                answerable_w = learned1_w & ride_ok_w
                if use_sm:
                    (resp_src,) = shard_roll(
                        (answerable_w,), n - s, emesh, "node", (wspec,),
                        h=params.exchange_h,
                    )
                else:
                    resp_src = answerable_w[jnp.mod(i_all + s, n)]
            resp_w = resp_src & cmask
            learned2_w = learned1_w | resp_w
        else:
            learned0_b = unpack_bits(state.learned, k)
            ride_ok_b = state.pcount < max_p
            riding_b = learned0_b & ride_ok_b
            sent_b = riding_b & conn[:, None]
            # scatter-or by target (bool max == or; duplicate targets merge)
            inbound_b = jax.ops.segment_max(sent_b, targets, num_segments=n)
            got_pinged = jax.ops.segment_max(conn.astype(jnp.int8), targets, num_segments=n) > 0
            learned1_b = learned0_b | inbound_b
            answerable_b = learned1_b & ride_ok_b
            resp_b = answerable_b[targets] & conn[:, None]
            learned2_b = learned1_b | resp_b
            learned2_w = pack_bool(learned2_b)

    with jax.named_scope("piggyback-counters"):
        if shift_mode:
            # bump = sent + (riding & got_pinged) = riding * (conn + got):
            # the bit factor is ONE materialized-plane product (learned &
            # ride_ok are both state carries), the rest is per-row scalars —
            # so the int8 pass reads two words per 32 elements instead of
            # re-deriving the sent/resp gather chains per bit
            riding_bit = unpack_bits(riding_w, k)
            bump = riding_bit.astype(jnp.int8) * (
                conn.astype(jnp.int8) + got_pinged.astype(jnp.int8)
            )[:, None]
            newly_bit = unpack_bits(learned2_w & ~state.learned, k)
        else:
            bump = sent_b.astype(jnp.int8) + (riding_b & got_pinged[:, None]).astype(
                jnp.int8
            )
            newly_bit = learned2_b & ~learned0_b

        # piggyback bumps: sender on success; receiver once per busy tick;
        # newly learned rumors start at pcount 0 (RecordChange)
        pcount_mid = jnp.minimum(state.pcount + bump, max_p)
        pcount_mid = jnp.where(newly_bit, jnp.int8(0), pcount_mid)

        # full-sync analog (disseminator.go:156-304): a rumor whose piggyback
        # counters all expired short of full coverage (e.g. it saturated one
        # side of a partition) is re-seeded, the way checksum-mismatch full
        # syncs repair divergence the maxP bound left behind
        up_mask = row_mask(up)
        mid_ride_w = pack_bool(pcount_mid < max_p)  # materialized reduce output
        fully = unpack_bits(and_reduce_rows(learned2_w | row_mask(~up)), k)
        riding_now_w = learned2_w & up_mask & mid_ride_w
        stuck = ~unpack_bits(or_reduce_rows(riding_now_w), k) & ~fully
        stuck_w = pack_bool(stuck)
        # one fused reset pass over the int8 plane, reading packed words
        reset_w = learned2_w & stuck_w[None, :]
        pcount = jnp.where(unpack_bits(reset_w, k), jnp.int8(0), pcount_mid)
        # maintain the carried invariant: riding resumes where the stuck reset
        # re-opened counters, plus wherever the mid gate was already open
        ride_ok_next = mid_ride_w | reset_w

    return DeltaState(
        learned=learned2_w, pcount=pcount, ride_ok=ride_ok_next, tick=state.tick + 1, key=key
    )


def converged_fraction(state: DeltaState, faults: DeltaFaults = DeltaFaults()) -> jax.Array:
    """Fraction of (live node, rumor) pairs delivered (popcount over the
    packed plane; tail bits are structurally zero so they never count)."""
    faults = resolve_faults(faults, state.tick)
    k = state.pcount.shape[1]
    n = state.learned.shape[0]
    # float32-accumulated: a uint32 popcount sum wraps at n*k >= 2^32 bits
    # (hit exactly at the 16M x 256 config) and would report 0.0 for a
    # fully converged plane.  Per-row counts (<= K) are float32-exact and
    # the global sum's ~1e-7 relative error is far below any use of a
    # coverage fraction.
    bits = jax.lax.population_count(state.learned).sum(axis=1, dtype=jnp.float32)
    if faults.up is not None:
        live = faults.up
        # float32 denominator too: an int32 live.sum() * k wraps (to
        # exactly zero at 16M live x k=256)
        denom = jnp.maximum(live.sum(dtype=jnp.float32), 1.0) * k
        return jnp.where(live, bits, 0.0).sum() / denom
    return bits.sum() / (n * k)


def converged(state: DeltaState, faults: DeltaFaults = DeltaFaults()) -> jax.Array:
    """bool scalar, on-device: have all rumors reached every live node?
    (Dead rows are vacuously done — a fused masked reduce, no dynamic
    shapes, so it can sit inside a jitted loop.)"""
    faults = resolve_faults(faults, state.tick)
    k = state.pcount.shape[1]
    plane = (
        state.learned
        if faults.up is None
        else state.learned | row_mask(~faults.up)
    )
    return unpack_bits(and_reduce_rows(plane), k).all()


def until_loop(run_block, state, max_blocks, pred):
    """Shared chunked-dispatch machinery for every engine's device runner
    (delta here; lifecycle's detected/converged runners import it):
    while_loop of up-to-``max_blocks`` blocks (``run_block(state) ->
    state``) with ``pred(state) -> bool scalar`` tested between blocks AND
    on entry — an already-satisfied predicate reports 0 blocks without
    stepping.  Both callbacks must be jit-safe."""

    def cond(carry):
        _, blocks, done = carry
        return (~done) & (blocks < max_blocks)

    def body(carry):
        s, blocks, _ = carry
        s = run_block(s)
        return s, blocks + jnp.int32(1), pred(s)

    return jax.lax.while_loop(cond, body, (state, jnp.int32(0), pred(state)))


@functools.partial(jax.jit, static_argnames=("params", "block_ticks"))
def _run_until_converged_device(
    params: DeltaParams,
    state: DeltaState,
    faults: DeltaFaults,
    *,
    block_ticks: int,
    max_blocks: jax.Array,
):
    """Blocks + convergence test + early exit in ONE dispatch (same shape
    of fix as the lifecycle engine's ``_run_until_detected_device``: the
    old host loop paid a dispatch round-trip and — with a fault mask — a
    dynamically-shaped boolean-index gather + readback per check, which
    dominated wall-clock through the TPU tunnel)."""

    def run_block(s):
        return jax.lax.fori_loop(
            0, block_ticks, lambda _, st: step(params, st, faults), s
        )

    return until_loop(run_block, state, max_blocks, lambda s: converged(s, faults))


def run_until_converged(
    params: DeltaParams,
    state: DeltaState,
    faults: DeltaFaults = DeltaFaults(),
    max_ticks: int = 10_000,
    check_every: int = 8,
):
    """Run blocks of ticks until all rumors reach all live nodes, testing
    every ``check_every`` ticks on-device.  Returns (state, ticks_used,
    converged)."""
    max_blocks = -(-max_ticks // check_every)
    state, blocks, done = _run_until_converged_device(
        params, state, faults, block_ticks=check_every, max_blocks=jnp.int32(max_blocks)
    )
    return state, int(blocks) * check_every, bool(done)


class DeltaSim:
    """Host-side convenience wrapper.  ``telemetry_sink`` (any callable
    taking a record dict, e.g. a ``telemetry.TelemetrySink``) turns on the
    run journal: ``run_until_converged`` then dispatches in
    ``journal_every``-tick blocks and emits one record per block (tick,
    live-coverage fraction, state digest — ``telemetry.delta_record``).
    The dissemination engine carries no in-step counters, so the hook
    costs one extra readback per block and nothing per tick; with no sink
    the dispatch path is exactly the old single-call one."""

    def __init__(self, n: int, k: int, seed: int = 0, telemetry_sink=None, **kw):
        self.params = DeltaParams(n=n, k=k, **kw)
        self.state = init_state(self.params, seed=seed)
        self._step = jax.jit(functools.partial(step, self.params))
        self.telemetry_sink = telemetry_sink
        if telemetry_sink is not None:
            from ringpop_tpu.sim import telemetry as _tm

            self._record = jax.jit(_tm.delta_record)

    def tick(self, faults: DeltaFaults = DeltaFaults()) -> DeltaState:
        self.state = self._step(self.state, faults)
        return self.state

    def run_until_converged(
        self,
        faults: DeltaFaults = DeltaFaults(),
        max_ticks: int = 10_000,
        journal_every: int = 64,
    ):
        if self.telemetry_sink is None:
            self.state, ticks, ok = run_until_converged(
                self.params, self.state, faults, max_ticks=max_ticks
            )
            return ticks, ok
        ticks, ok = 0, False
        while ticks < max_ticks and not ok:
            block = min(journal_every, max_ticks - ticks)
            self.state, t, ok = run_until_converged(
                self.params, self.state, faults, max_ticks=block
            )
            ticks += t
            self.telemetry_sink(self._record(self.state, faults))
            if t == 0 and not ok:  # budget too small for one check block
                break
        return ticks, ok
