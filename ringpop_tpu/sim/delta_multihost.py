"""Process-spanning delta engine: the SAME tick at any process count.

The delta step's data motion is mesh-shaped (PERF.md "Multi-host (DCN)
design"): two cyclic row-window exchange legs plus two [W]-word row
reduces per tick.  On a real pod the partitioner drives all of it from the
one jitted ``delta.step`` over a ``make_multihost_mesh`` mesh.  This
module runs the IDENTICAL arithmetic when cross-process XLA execution is
unavailable (the multi-process CPU fabric): each process owns the
contiguous node-block ``partition.process_block`` assigns it, steps it
with shard-local jitted kernels, and bridges exactly the exchange legs and
reduce words over ``parallel.fabric``.

Bit-identity with the single-host ``delta.step`` is by construction, and
certified end-to-end by the 1/2/4-process twins (``simbench
multihost16m``, ``make multihost-smoke``):

* every random quantity is the partition-invariant counter stream
  (``sim/prng``): value = f(seed, tick, site, GLOBAL lane) — identical on
  any rank layout, zero communication;
* the exchange legs move the same rows the traced roll moves;
* the row reduces are bitwise OR/AND — reassociation-exact, so
  block-partial-then-combine equals the single-host halving tree;
* state digests combine from per-rank partial sums at GLOBAL flat indices
  (``partition.leaf_partial_sums``), so a multi-process digest IS the
  single-host ``telemetry.tree_digest`` value.

Scope: ``exchange="shift"`` + ``rng="counter"`` (the sharded-caller
defaults), faults ``None`` or ``up``/scalar ``drop_rate`` (the
convergence-certification models).  Anything else raises — the mesh path
handles the full fault surface; this bridge certifies the DCN layer.
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.parallel.fabric import (
    Fabric,
    encode_array,
    encode_rows,
    plan_window,
    plan_window_swing,
    rows_wire_size,
)
from ringpop_tpu.parallel.partition import (
    combine_leaf_partials,
    leaf_partial_sums,
    process_block,
)
from ringpop_tpu.sim import prng as _prng
from ringpop_tpu.sim.delta import (
    DeltaFaults,
    DeltaParams,
    DeltaState,
    clamped_max_p,
)
from ringpop_tpu.sim.packbits import (
    and_reduce_rows,
    n_words,
    nonzero_rows,
    or_reduce_rows,
    pack_bool,
    popcount_rows,
    row_mask,
    unpack_bits,
)

# low-byte leg ids; the wire tag is ``tick << 8 | leg`` (mod 2^32, see
# _tag) so a message from a diverged rank schedule fails the fabric's
# tag check loudly instead of being consumed as a later tick's payload
_TAG_LEG1 = 0x10
_TAG_LEG2 = 0x20
_TAG_REDUCE = 0x30
_TAG_DIGEST = 0x40
_TAG_COVER = 0x50


def _tag(tick: int, leg: int) -> int:
    return ((tick << 8) | leg) & 0xFFFFFFFF


def _check_supported(params: DeltaParams, faults) -> None:
    if params.exchange != "shift" or params.rng != "counter":
        raise NotImplementedError(
            "multihost delta bridge supports the sharded-caller defaults "
            "only (exchange='shift', rng='counter')"
        )
    if faults is not None and (
        getattr(faults, "group", None) is not None
        or getattr(faults, "drop_node", None) is not None
        or getattr(faults, "reach", None) is not None
        or getattr(faults, "tier_ids", None) is not None
        or getattr(faults, "tier_drop", None) is not None
        or getattr(faults, "suspect_ticks", None) is not None
        or hasattr(faults, "at_tick")
    ):
        raise NotImplementedError(
            "multihost delta bridge supports faults=None or up/drop_rate "
            "legs; group/reach/drop_node/topology-tier/suspect_ticks/"
            "FaultPlan run on the mesh path"
        )


# -- shard-local kernels ------------------------------------------------------
# Each is jitted once per (params, flags); ``lo`` rides as a traced scalar
# so every rank shares one compilation of the same program.


@functools.partial(jax.jit, static_argnames=("params", "block"))
def _k_init(params: DeltaParams, lo, seed, *, block: int):
    """Rows [lo, lo+block) of ``delta.init_state`` — elementwise equality
    against the source row (rumor j seeds at node j mod n), bit-identical
    to the scatter form (duplicate sources land identically)."""
    n, k = params.n, params.k
    g = lo + jnp.arange(block, dtype=jnp.int32)
    src = (jnp.arange(k, dtype=jnp.int32) % n)[None, :]
    learned_b = g[:, None] == src
    pcount = jnp.zeros((block, k), jnp.int8)
    return (
        pack_bool(learned_b),
        pcount,
        pack_bool(pcount < jnp.int8(clamped_max_p(params))),
        jax.random.PRNGKey(seed),
    )


def _conn_rows(params, cseed, ctick, g, s, up, has_up: bool, has_drop: bool, drop_rate):
    """Connectivity verdict for the (g -> g+s) legs of GLOBAL rows ``g`` —
    pure in (seed, tick, lane), so any rank can evaluate any row's verdict
    without communication (the receiver recomputes the sender's coin)."""
    n = params.n
    conn = jnp.ones(g.shape, dtype=bool)
    if has_up:
        conn &= up[g] & up[(g + s) % n]
    if has_drop:
        u = _prng.draw_uniform(cseed, ctick, _prng.D_DROP, g)
        conn &= u >= drop_rate
    return conn


@functools.partial(jax.jit, static_argnames=("params", "has_up", "has_drop"))
def _k_sent(params, learned_l, ride_ok_l, key, tick, lo, up, drop_rate, *, has_up, has_drop):
    """Kernel A: the request-leg plane this block contributes."""
    b = learned_l.shape[0]
    cseed = _prng.fold_key(key)
    s = _prng.draw_randint(cseed, tick, _prng.D_SHIFT, 0, 1, params.n)
    g = lo + jnp.arange(b, dtype=jnp.int32)
    conn = _conn_rows(params, cseed, tick, g, s, up, has_up, has_drop, drop_rate)
    riding = learned_l & ride_ok_l
    sent = riding & row_mask(conn)
    return sent, conn, riding, s


@functools.partial(jax.jit, static_argnames=("params", "has_up", "has_drop"))
def _k_merge(params, learned_l, ride_ok_l, inbound_l, key, tick, lo, s, up, drop_rate, *, has_up, has_drop):
    """Kernel B: merge the request leg; derive the response-leg plane.
    ``got_pinged`` is recomputed locally from the lane-pure connectivity
    verdict of the SENDER rows (g - s) — no second window transfer."""
    b = learned_l.shape[0]
    cseed = _prng.fold_key(key)
    g = lo + jnp.arange(b, dtype=jnp.int32)
    src = (g - s) % params.n
    got_pinged = _conn_rows(params, cseed, tick, src, s, up, has_up, has_drop, drop_rate)
    learned1 = learned_l | inbound_l
    answerable = learned1 & ride_ok_l
    return learned1, answerable, got_pinged


@functools.partial(jax.jit, static_argnames=("params", "has_up"))
def _k_counters(params, learned_l, learned1_l, resp_src_l, conn_l, got_pinged_l, riding_l, pcount_l, up_l, *, has_up):
    """Kernel C: response merge + piggyback counters + this block's
    partial words of the two global row reduces."""
    k = params.k
    max_p = jnp.int8(clamped_max_p(params))
    resp = resp_src_l & row_mask(conn_l)
    learned2 = learned1_l | resp
    riding_bit = unpack_bits(riding_l, k)
    bump = riding_bit.astype(jnp.int8) * (
        conn_l.astype(jnp.int8) + got_pinged_l.astype(jnp.int8)
    )[:, None]
    newly = unpack_bits(learned2 & ~learned_l, k)
    pcount_mid = jnp.minimum(pcount_l + bump, max_p)
    pcount_mid = jnp.where(newly, jnp.int8(0), pcount_mid)
    mid_ride = pack_bool(pcount_mid < max_p)
    if has_up:
        dead_mask = row_mask(~up_l)
        up_mask = row_mask(up_l)
    else:
        dead_mask = jnp.uint32(0)
        up_mask = jnp.uint32(0xFFFFFFFF)
    part_and = and_reduce_rows(learned2 | dead_mask)
    part_or = or_reduce_rows(learned2 & up_mask & mid_ride)
    return learned2, pcount_mid, mid_ride, part_and, part_or


@functools.partial(jax.jit, static_argnames=("params",))
def _k_finish(params, learned2_l, pcount_mid_l, mid_ride_l, fully_w, riding_any_w):
    """Kernel D: apply the full-sync stuck-rumor reset with the GLOBAL
    reduce words; report convergence (free — ``fully`` is the converged
    plane's AND)."""
    k = params.k
    fully = unpack_bits(fully_w, k)
    stuck = ~unpack_bits(riding_any_w, k) & ~fully
    stuck_w = pack_bool(stuck)
    reset = learned2_l & stuck_w[None, :]
    pcount = jnp.where(unpack_bits(reset, k), jnp.int8(0), pcount_mid_l)
    ride_ok = mid_ride_l | reset
    return pcount, ride_ok, fully.all()


@functools.partial(jax.jit, static_argnames=("g",))
def _k_coverage_bits(learned_l, *, g: int):
    """Exact learned-bit count of a block as ``g`` uint32 chunk sums
    (r14 int32-headroom audit: a single flat popcount sum wraps at
    N·K ≥ 2³² — each chunk here covers block/g rows × 32·W bits, kept
    well inside uint32 by the caller's chunk choice; the host folds the
    [g] vector in int64)."""
    per_row = popcount_rows(learned_l)
    return per_row.reshape(g, -1).sum(axis=1, dtype=jnp.uint32)


# -- device-side window programs (r15) ----------------------------------------
# Both run PER PROCESS, outside any mesh — collective-free by construction
# (jaxlint RPJ206's collective-free flavor pins it), and they are what
# keeps device→host transfer at pieces-only: the host-side np fancy-index
# they replace materialized the ENTIRE local plane per exchange leg.


@jax.jit
def _k_window_all(plane, start):
    """The P=1 degenerate window: the whole plane cyclically shifted by
    ``start`` — a materialized-index gather on device (RPA102's blessed
    lowering), so the single-process exchange leg transfers ZERO bytes
    to host."""
    with jax.named_scope("rumor-exchange"):
        n = plane.shape[0]
        idx = (start + jnp.arange(n, dtype=jnp.int32)) % n
        return jnp.take(plane, idx, axis=0)


@jax.jit
def _k_plane_nzbits(plane):
    """Send-side nonzero-row summary of one exchange plane, one cheap
    pass per leg: the nonzero-row bitmap packed LSB-first — byte-for-byte
    the fabric's ROWS wire bitmap (``packbits.pack_bool``'s little-endian
    word view == ``np.packbits(bitorder="little")``).  ~7 ms at 4M rows;
    the cumsum+scatter compaction this replaced cost ~430 ms/leg on
    XLA:CPU (elementwise scatter), which ate the whole wire win."""
    with jax.named_scope("rumor-exchange"):
        return pack_bool(nonzero_rows(plane))


@jax.jit
def _k_rows_gather(plane, idx):
    """The nonzero rows a ROWS-encoded piece actually ships: a
    materialized-index device gather over the host-built index (callers
    pad ``idx`` to a power of two with a repeated last index so distinct
    compiled shapes stay logarithmic; the pad rows are sliced off before
    transfer)."""
    with jax.named_scope("rumor-exchange"):
        return jnp.take(plane, idx, axis=0)


class MultihostDelta:
    """One rank's half^P of a delta run over the host-bridged DCN fabric.

    The same class runs single-process (``nprocs=1``, fabric legs become
    local slices) — that degenerate instance is pinned bit-identical to
    ``delta.step``, and the 2/4-process instances are pinned digest-equal
    to IT, which closes the chain to the single-host engine.

    r16 knobs, both bit-transparent by construction and pinned so by the
    twin tests:

    * ``schedule`` — ``"cyclic"`` (direct window sends, the r14 plan) or
      ``"swing"`` (distance-halving relay rounds, power-of-two P;
      ``plan_window_swing``); the assembled windows and the reduce-word
      gathers are byte-identical either way.
    * ``overlap`` — cross-tick pipelining: every round's sends drain on
      the fabric's persistent sender threads while the engine keeps
      computing (tick t's leg-2/reduce drain runs under tick t+1's
      kernels A–D and leg-1 slicing); the engine joins exactly at the
      point inbound rows are consumed.  The XOR-delta payload history
      stays exact because the fabric advances it in enqueue/decode order
      (FIFO per peer) — the double-buffering contract.
    """

    def __init__(
        self,
        params: DeltaParams,
        fabric: Fabric,
        seed: int = 0,
        faults: Optional[DeltaFaults] = None,
        schedule: str = "cyclic",
        overlap: bool = False,
    ):
        _check_supported(params, faults)
        if schedule not in ("cyclic", "swing"):
            raise ValueError(f"unknown exchange schedule {schedule!r}")
        if schedule == "swing" and fabric.nprocs > 1 and (
            fabric.nprocs & (fabric.nprocs - 1)
        ):
            raise ValueError(
                "swing schedule requires a power-of-two process count, got "
                f"{fabric.nprocs} (select schedule='cyclic')"
            )
        # overlap (r16): issue each round's sends async and join ONLY the
        # receives — tick t's leg-2/reduce drain overlaps tick t+1's
        # shard-local kernels.  Off = the r15 blocking semantics through
        # the same persistent-thread fabric (the A/B baseline).
        self.schedule, self.overlap = schedule, bool(overlap)
        self.params, self.fabric = params, fabric
        self.rank, self.nprocs = fabric.rank, fabric.nprocs
        self.lo, self.hi = process_block(params.n, self.rank, self.nprocs)
        self.block = self.hi - self.lo
        self.has_up = faults is not None and faults.up is not None
        self.has_drop = faults is not None and faults.drop_rate is not None
        # ``up`` is replicated per process (1 bit/node — 2 MB at 16M);
        # the big O(N*K) planes are what sharding is for
        self.up = (
            jnp.asarray(faults.up, bool) if self.has_up else jnp.zeros((1,), bool)
        )
        self.up_l = self.up[self.lo : self.hi] if self.has_up else jnp.zeros((1,), bool)
        self.drop_rate = (
            jnp.float32(faults.drop_rate) if self.has_drop else jnp.float32(0)
        )
        learned, pcount, ride_ok, key = _k_init(
            params, jnp.asarray(self.lo, jnp.int32), seed, block=self.block
        )
        self.learned, self.pcount, self.ride_ok, self.key = learned, pcount, ride_ok, key
        self.tick = 0
        self.converged = None  # unknown until a tick reports the AND plane
        # device→host transfer accounting for the exchange legs (r15):
        # summaries + pieces only — the twin tests pin this under the
        # old full-plane-per-leg floor
        self.d2h_bytes = 0
        # journal per-tick deltas: counters at the last journal_record
        self._journal_prev = {"tick": 0, "wire": 0, "raw": 0,
                              "leg": {"leg1": 0.0, "leg2": 0.0, "reduce": 0.0},
                              "hidden": 0.0}
        # per-leg drain/overlap timing (r16 observability): cumulative
        # seconds BLOCKED waiting on each leg's completions, and the
        # estimated send-drain wall that ran hidden under compute (folded
        # lazily from drained handles — see _fold_round_timings)
        self._leg_wait_s = {"leg1": 0.0, "leg2": 0.0, "reduce": 0.0}
        self._hidden_s = 0.0
        self._inflight: list = []
        # a fresh engine breaks any XOR-delta payload history a reused
        # fabric carries (and restore may change P) — reset is local and
        # every rank constructs its engine at the same protocol point
        self.fabric.reset_codec_state()
        # coverage chunking: block/g rows per chunk, each chunk's bit count
        # bounded by (block/g)·K — keep it under 2^26 bits per chunk
        from ringpop_tpu.sim.packbits import block_count

        g = 1
        while (self.block // g) * params.k > (1 << 26) and g < self.block:
            g *= 2
        self._cover_g = block_count(self.block, g)

    # -- the exchange legs ----------------------------------------------------

    def _plane_summary(self, plane_dev):
        """Send-side nonzero-row summary of one exchange plane (codec
        path): the packed device bitmap unpacked to a host mask + prefix
        sums, one cheap pass per leg shared by every piece decision."""
        b = self.block
        bits_host = np.asarray(_k_plane_nzbits(plane_dev))
        self.d2h_bytes += bits_host.nbytes
        mask_all = np.unpackbits(
            bits_host.view(np.uint8), count=b, bitorder="little"
        ).astype(bool)
        cum = np.zeros(b + 1, np.int64)
        np.cumsum(mask_all, out=cum[1:])
        return mask_all, cum

    def _piece_item(self, plane_dev, s0: int, glen: int, summ):
        """One contiguous LOCAL piece ``[s0, s0+glen)`` of the plane as a
        fabric wire item: device-ROWS pre-encoded when the nonzero-row
        summary says it pays (transfer = nonzero rows only), else the
        dense device slice (pre-encoded ``rows=False`` so the fabric does
        not re-scan what the summary already rejected).  ``summ`` is the
        ``_plane_summary`` pair, or None when the codec is off."""
        row_nbytes = (
            int(np.prod(plane_dev.shape[1:], dtype=np.int64))
            * plane_dev.dtype.itemsize
        )
        if summ is not None:
            mask_all, cum = summ
            nnz = int(cum[s0 + glen] - cum[s0])
            if rows_wire_size(glen, nnz, row_nbytes) < glen * row_nbytes:
                if nnz:
                    idx = np.flatnonzero(mask_all[s0 : s0 + glen]).astype(np.int32)
                    idx += np.int32(s0)
                    pad = 1 << max(int(nnz) - 1, 0).bit_length()
                    idx = np.concatenate(
                        [idx, np.full(pad - nnz, idx[-1], np.int32)]
                    )
                    payload = np.asarray(
                        _k_rows_gather(plane_dev, jnp.asarray(idx))[:nnz]
                    )
                else:
                    payload = np.empty((0,) + plane_dev.shape[1:], plane_dev.dtype)
                self.d2h_bytes += payload.nbytes
                return encode_rows(
                    mask_all[s0 : s0 + glen],
                    payload,
                    (glen,) + plane_dev.shape[1:],
                    plane_dev.dtype,
                )
        raw = np.asarray(plane_dev[s0 : s0 + glen])
        self.d2h_bytes += raw.nbytes
        return encode_array(raw, rows=False) if summ is not None else raw

    def _wait(self, handles, leg: str):
        """Join a round's completions, attributing the blocked wall to
        ``leg``.  ``handles`` is (recv_handle, send_handle) — rounds are
        issued as a receive-expectation post FIRST (so the demux thread
        decodes inbound while this rank is still encoding its own
        pieces) and a send enqueue second.  Sync mode joins the sends
        too (the r15 blocking contract); overlap mode leaves them
        draining and stamps the resume point for the hidden-drain
        fold."""
        recv_h, send_h = handles
        t0 = time.perf_counter()
        got = recv_h.wait(join_sends=not self.overlap)
        if send_h is not None:
            # sync: join the drain; overlap: surface already-failed
            # sends only (non-blocking)
            send_h.wait(join_sends=not self.overlap)
        self._leg_wait_s[leg] += time.perf_counter() - t0
        resume = time.monotonic()
        for h in (recv_h, send_h):
            if h is not None:
                h.resumed_s = resume
                self._inflight.append(h)
        return got

    def _note_reduce_round(self, handle) -> None:
        """Track a reduce-allgather round: its BLOCKED wall (the
        handle's own waited_s — not the surrounding pack/bookkeeping
        CPU, so the attribution matches leg1/leg2's join-only timing)
        and the handle itself for the hidden-drain fold — without this
        the reduce leg (the one XOR-streamed, every-tick round) would
        be invisible to ``overlap_hidden_ms``."""
        self._leg_wait_s["reduce"] += handle.waited_s
        handle.resumed_s = time.monotonic()
        self._inflight.append(handle)

    def _fold_round_timings(self) -> None:
        """Price drained rounds into ``overlap_hidden_ms``: the send-
        drain wall that completed AFTER the engine resumed computing —
        i.e. drain genuinely hidden under compute.  Sync mode joins
        every send before resuming, so its hidden contribution is zero
        by construction; rounds still draining stay queued for a later
        fold."""
        keep = []
        for h in self._inflight:
            done = h.sends_done_s()
            if done is None:
                keep.append(h)
                continue
            self._hidden_s += max(0.0, done - getattr(h, "resumed_s", h.issued_s))
        self._inflight = keep

    def _exchange_window(self, plane_dev, rel_shift: int, tag: int, leg: str):
        """All ranks exchange so each assembles its own window
        ``[lo + rel_shift, lo + rel_shift + B) mod n`` of the globally
        node-sharded ``plane``.  ``rel_shift`` is the same on every rank
        (leg 1: -s; leg 2: +s), which makes the schedule deterministic.

        r15 hot path: the local plane never materializes on host.  At
        P=1 the window is a device gather (zero transfer); at P>1 the
        per-peer pieces are device slices and the nonzero-row summary
        (``_k_plane_nzbits`` + ``_k_rows_gather``) lets ride-masked
        pieces transfer ONLY their nonzero rows, as the fabric's ROWS
        wire format — device→host volume ≈ what actually crosses the
        wire (``d2h_bytes`` accounts every transfer; the twin tests pin
        it under the old full-plane floor).

        r16: ``schedule="swing"`` routes the same pieces through the
        distance-halving relay rounds instead of direct sends; the
        assembled window is byte-identical by construction (the relayed
        rows are the same rows).  ``overlap=True`` joins only receives —
        this round's send drain overlaps whatever the engine computes
        next (``tag`` keeps its low nibble clear so swing rounds can ride
        ``tag + j``)."""
        n, b = self.params.n, self.block
        if self.nprocs == 1:
            return _k_window_all(
                plane_dev, jnp.asarray((self.lo + rel_shift) % n, jnp.int32)
            )
        if self.schedule == "swing":
            return self._exchange_window_swing(plane_dev, rel_shift, tag, leg)
        # post the receive expectations BEFORE computing any send piece:
        # the demux threads decode the peers' payloads (which arrive as
        # soon as THEY finish encoding) while this rank is still slicing
        # and encoding its own — decode overlaps encode on both sides
        my_plan = plan_window((self.lo + rel_shift) % n, b, n, self.nprocs)
        recv_from = sorted({owner for owner, *_ in my_plan if owner != self.rank})
        recv_h = self.fabric.exchange_async(tag, {}, recv_from)
        summ = self._plane_summary(plane_dev) if self.fabric.codec else None
        # build sends: for every other rank, the pieces of MY rows their
        # window needs, in THEIR window order (one wire array per piece)
        sends: dict[int, list] = {}
        for r in range(self.nprocs):
            if r == self.rank:
                continue
            r_lo = process_block(n, r, self.nprocs)[0]
            plan = plan_window((r_lo + rel_shift) % n, b, n, self.nprocs)
            items = [
                self._piece_item(plane_dev, glo - self.lo, glen, summ)
                for owner, glo, glen, _ in plan
                if owner == self.rank
            ]
            if items:
                sends[r] = items
        send_h = self.fabric.exchange_async(tag, sends, []) if sends else None
        # local pieces stay device slices, received pieces upload, one
        # device concatenate stitches the window
        got = self._wait((recv_h, send_h), leg)
        used: dict[int, int] = {r: 0 for r in recv_from}
        parts = []
        for owner, glo, glen, woff in my_plan:
            if owner == self.rank:
                parts.append(plane_dev[glo - self.lo : glo - self.lo + glen])
            else:
                parts.append(jnp.asarray(got[owner][used[owner]]))
                used[owner] += 1
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

    def _exchange_window_swing(self, plane_dev, rel_shift: int, tag: int, leg: str):
        """The distance-halving execution of the same window assembly:
        ``log2(P)`` rounds against one partner each (``plan_window_swing``
        manifests, wire tag ``tag + j``), pieces first hopping off their
        owner as device slices (the r15 ROWS/dense pre-encode path),
        relayed hops forwarding the received host rows — every forwarded
        copy is priced by the fabric's byte accounting, which is exactly
        the swing relay overhead the simbench artifact reports."""
        n, b, P = self.params.n, self.block, self.nprocs
        rounds = plan_window_swing(rel_shift % n, n, P)
        summ = self._plane_summary(plane_dev) if self.fabric.codec else None
        store: dict[tuple, np.ndarray] = {}
        for j, manifest in enumerate(rounds):
            q = self.rank ^ (1 << j)
            out_entries = manifest.get(self.rank, ())
            in_entries = manifest.get(q, ())
            # expectation first (decode-under-encode, as the cyclic path)
            recv_h = self.fabric.exchange_async(
                tag + j, {}, [q] if in_entries else []
            )
            items = []
            for entry in out_entries:
                d, owner, glo, glen, woff = entry
                if owner == self.rank:
                    # first hop: straight off the device plane
                    items.append(self._piece_item(plane_dev, glo - self.lo, glen, summ))
                else:
                    # relay hop: forward the rows received earlier
                    items.append(store.pop(entry))
            send_h = (
                self.fabric.exchange_async(tag + j, {q: items}, [])
                if items else None
            )
            got = self._wait((recv_h, send_h), leg)
            for entry, arr in zip(in_entries, got.get(q, [])):
                store[entry] = arr
        my_plan = plan_window((self.lo + rel_shift) % n, b, n, P)
        parts = []
        for owner, glo, glen, woff in my_plan:
            if owner == self.rank:
                parts.append(plane_dev[glo - self.lo : glo - self.lo + glen])
            else:
                parts.append(
                    jnp.asarray(store.pop((self.rank, owner, glo, glen, woff)))
                )
        assert not store, f"undelivered swing pieces: {sorted(store)}"
        return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]

    # -- one protocol period --------------------------------------------------

    def step(self) -> None:
        p = self.params
        # price any fully drained overlapped rounds from earlier ticks
        # into the hidden gauge before this tick issues new ones
        self._fold_round_timings()
        t = jnp.asarray(self.tick, jnp.int32)
        lo = jnp.asarray(self.lo, jnp.int32)
        sent, conn, riding, s_dev = _k_sent(
            p, self.learned, self.ride_ok, self.key, t, lo, self.up,
            self.drop_rate, has_up=self.has_up, has_drop=self.has_drop,
        )
        s = int(s_dev)
        inbound = self._exchange_window(sent, -s, _tag(self.tick, _TAG_LEG1), "leg1")
        learned1, answerable, got_pinged = _k_merge(
            p, self.learned, self.ride_ok, inbound, self.key, t, lo, s_dev,
            self.up, self.drop_rate, has_up=self.has_up, has_drop=self.has_drop,
        )
        resp_src = self._exchange_window(
            answerable, +s, _tag(self.tick, _TAG_LEG2), "leg2"
        )
        learned2, pcount_mid, mid_ride, part_and, part_or = _k_counters(
            p, self.learned, learned1, resp_src, conn, got_pinged, riding,
            self.pcount, self.up_l, has_up=self.has_up,
        )
        if self.nprocs > 1:
            # stream="reduce": the [2, W] words recur shape-stable every
            # tick and the AND plane saturates — the XOR-delta codec's one
            # naturally matching stream (windows move with s, so the legs
            # stay stream-less).  Under schedule="swing" the gather is
            # recursive doubling (per-round streams "reduce/sw{j}"), and
            # under overlap the final round's drain runs behind kernel D.
            partials = self.fabric.allgather(
                _tag(self.tick, _TAG_REDUCE),
                np.stack([np.asarray(part_and), np.asarray(part_or)]),
                stream="reduce",
                schedule=self.schedule,
                join_sends=not self.overlap,
                on_round=self._note_reduce_round,
            )
            fully_w = functools.reduce(np.bitwise_and, [pp[0] for pp in partials])
            riding_any_w = functools.reduce(np.bitwise_or, [pp[1] for pp in partials])
            fully_w, riding_any_w = jnp.asarray(fully_w), jnp.asarray(riding_any_w)
        else:
            fully_w, riding_any_w = part_and, part_or
        self.pcount, self.ride_ok, conv = _k_finish(
            p, learned2, pcount_mid, mid_ride, fully_w, riding_any_w
        )
        self.learned = learned2
        self.converged = bool(conv)
        self.tick += 1

    # -- certification surface ------------------------------------------------

    def _as_block_state(self) -> DeltaState:
        return DeltaState(
            learned=self.learned,
            pcount=self.pcount,
            ride_ok=self.ride_ok,
            tick=jnp.asarray(self.tick, jnp.int32),
            key=self.key,
        )

    def state_digest(self) -> int:
        """The GLOBAL ``telemetry.tree_digest`` of the full DeltaState —
        per-rank partial leaf sums at global flat indices, one uint32[L]
        allgather, host combine.  Equal to the single-host digest of the
        same trajectory bit-for-bit."""
        part = np.asarray(
            leaf_partial_sums(
                self._as_block_state(), lo=self.lo, include_replicated=self.rank == 0
            )
        )
        parts = (
            self.fabric.allgather(_tag(self.tick, _TAG_DIGEST), part)
            if self.nprocs > 1
            else [part]
        )
        return combine_leaf_partials(parts)

    def coverage(self) -> float:
        """Exact global learned-bit fraction over ALL rows (uint chunk
        partials summed in int64 on host — deterministic at ANY process
        count, unlike a float32 reduction whose value depends on the
        reduction tree; NOTE ``delta.converged_fraction`` divides by LIVE
        rows instead, so under an ``up`` mask the two gauges differ by
        the dead-row denominator — the journal pairing compares digests,
        not this gauge)."""
        mine = np.asarray(_k_coverage_bits(self.learned, g=self._cover_g)).astype(np.int64).sum()
        counts = (
            [
                int(c[0])
                for c in self.fabric.allgather(
                    _tag(self.tick, _TAG_COVER), np.asarray([mine])
                )
            ]
            if self.nprocs > 1
            else [int(mine)]
        )
        return float(sum(counts)) / float(self.params.n * self.params.k)

    def leg_timing(self) -> dict:
        """Cumulative per-leg blocked wall + hidden drain, in ms — the
        run-total view of the per-interval journal keys (bench records
        embed this next to the byte counters)."""
        self._fold_round_timings()
        return {
            "fabric_leg_ms": {
                k: round(v * 1e3, 3) for k, v in self._leg_wait_s.items()
            },
            "overlap_hidden_ms": round(self._hidden_s * 1e3, 3),
        }

    def journal_record(self, light: bool = False) -> dict:
        """One journal block: cumulative fabric counters PLUS the r15
        per-interval deltas and codec ratio — `fabric_*_delta` keys cover
        the ticks since the previous record (``fabric_ticks_delta`` of
        them), which is what lets a journal plot the dissemination-phase
        traffic wave instead of only the cumulative ramp.

        ``light=True`` skips the state digest (coverage stays — a cheap
        popcount, and the wave wants its phase label): the digest mixes
        EVERY state leaf including the [N, K] pcount plane, which at 16M
        costs more than the tick it journals — per-tick wire waves use
        light records and keep the full digest for the exit record.
        Collective either way (coverage allgathers): every rank must pass
        the same ``light``.

        r16 adds the schedule name and per-interval leg timing:
        ``fabric_leg_ms`` is the wall this rank spent BLOCKED waiting on
        each leg's completions over the interval, ``overlap_hidden_ms``
        the send-drain wall that ran hidden under compute instead (zero
        by construction in sync mode) — so the overlap win is a measured
        fact per run, not a hope."""
        self._fold_round_timings()
        ws = self.fabric.wire_stats()
        prev = self._journal_prev
        wire_d = ws["bytes_sent"] - prev["wire"]
        raw_d = ws["raw_bytes_sent"] - prev["raw"]
        leg_ms = {
            k: round((self._leg_wait_s[k] - prev["leg"][k]) * 1e3, 3)
            for k in self._leg_wait_s
        }
        hidden_ms = round((self._hidden_s - prev["hidden"]) * 1e3, 3)
        rec = {
            "tick": self.tick,
            "coverage": round(self.coverage(), 6),
            **({} if light else {"digest": self.state_digest()}),
            "process_count": self.nprocs,
            "process_id": self.rank,
            "schedule": self.schedule,
            "overlap": self.overlap,
            "fabric_bytes_sent": ws["bytes_sent"],
            "fabric_bytes_recv": ws["bytes_recv"],
            "fabric_raw_sent": ws["raw_bytes_sent"],
            "fabric_raw_recv": ws["raw_bytes_recv"],
            "fabric_ticks_delta": self.tick - prev["tick"],
            "fabric_wire_sent_delta": wire_d,
            "fabric_raw_sent_delta": raw_d,
            # raw/wire over the interval; 1.0 when nothing crossed (P=1)
            "fabric_codec_ratio": round(raw_d / wire_d, 4) if wire_d else 1.0,
            "fabric_codec_counts": ws["codec_counts"],
            "fabric_leg_ms": leg_ms,
            "overlap_hidden_ms": hidden_ms,
            "d2h_bytes": self.d2h_bytes,
        }
        self._journal_prev = {
            "tick": self.tick, "wire": ws["bytes_sent"],
            "raw": ws["raw_bytes_sent"],
            "leg": dict(self._leg_wait_s), "hidden": self._hidden_s,
        }
        return rec

    # -- block-sharded snapshot / restore -------------------------------------

    def _snapshot_mesh(self):
        """One node-axis mesh over every device in the job (rumor axis 1:
        snapshot placement wants row-contiguous device blocks).  Built by
        the same ``make_multihost_mesh`` the jitted-mesh path uses, so
        process blocks land exactly where ``partition.process_block``
        says."""
        from ringpop_tpu.parallel.multihost import make_multihost_mesh

        return make_multihost_mesh(rumor_shards=1)

    def save_snapshot(self, path: str) -> None:
        """Block-sharded orbax checkpoint: every process places its LOCAL
        block as the global array's shards (``partition.shard_put`` — no
        host materializes the global state) and writes only those shards
        (OCDBT path).  Collective: every rank must call."""
        import jax as _jax

        from ringpop_tpu.parallel.partition import shard_put
        from ringpop_tpu.sim.snapshot import save_state_orbax

        if self.nprocs > 1 and _jax.process_count() != self.nprocs:
            raise RuntimeError(
                "block-sharded snapshots need the jax.distributed runtime "
                f"up at the fabric's process count ({self.nprocs}); "
                f"jax.process_count()={_jax.process_count()}"
            )
        state = shard_put(
            jax.tree.map(np.asarray, self._as_block_state()),
            self._snapshot_mesh(),
            global_n=self.params.n,
        )
        save_state_orbax(path, state, wait=True)
        self.fabric.barrier(f"snapshot-done-{self.tick}")

    @classmethod
    def restore_snapshot(
        cls,
        path: str,
        params: DeltaParams,
        fabric: Fabric,
        faults: Optional[DeltaFaults] = None,
        schedule: str = "cyclic",
        overlap: bool = False,
    ) -> "MultihostDelta":
        """Restore a block-sharded checkpoint onto THIS fabric's process
        count — which need not match the count that saved it (the 2-proc
        save → 4-proc restore certificate): the partition table names the
        target layout, orbax re-chunks the reads, and each process gathers
        back exactly its rows."""
        import jax as _jax

        from ringpop_tpu.parallel.partition import host_gather, named_shardings
        from ringpop_tpu.sim.snapshot import load_state_orbax

        if fabric.nprocs > 1 and _jax.process_count() != fabric.nprocs:
            raise RuntimeError(
                "block-sharded restore needs the jax.distributed runtime up "
                f"at the fabric's process count ({fabric.nprocs}); "
                f"jax.process_count()={_jax.process_count()}"
            )
        self = cls(params, fabric, seed=0, faults=faults,
                   schedule=schedule, overlap=overlap)
        n, k = params.n, params.k
        w = n_words(k)
        example = DeltaState(
            learned=_jax.ShapeDtypeStruct((n, w), jnp.uint32),
            pcount=_jax.ShapeDtypeStruct((n, k), jnp.int8),
            ride_ok=_jax.ShapeDtypeStruct((n, w), jnp.uint32),
            tick=_jax.ShapeDtypeStruct((), jnp.int32),
            key=_jax.ShapeDtypeStruct((2,), jnp.uint32),
        )
        shardings = named_shardings(example, self._snapshot_mesh())
        gstate = load_state_orbax(path, example, shardings=shardings)
        self._install_block_state(host_gather(gstate))
        self.fabric.barrier(f"restore-done-{self.tick}")
        return self

    def _install_block_state(self, local) -> None:
        """Adopt a restored LOCAL block of DeltaState (tick included) and
        reset the wire-codec streams: any XOR-delta payload history
        predates the restore — and the restoring fabric may run a
        DIFFERENT process count than the saver — so the epoch word bumps
        on every rank here, turning a rank that skipped the reset into a
        loud FabricError instead of silently decoded garbage."""
        self.learned = jnp.asarray(local.learned)
        self.pcount = jnp.asarray(local.pcount)
        self.ride_ok = jnp.asarray(local.ride_ok)
        self.key = jnp.asarray(local.key)
        self.tick = int(np.asarray(local.tick))
        self.converged = None
        self.fabric.reset_codec_state()
        # re-base the journal deltas too: the restored tick may sit
        # BEFORE the last journaled tick (negative ticks_delta) and the
        # restore-era traffic belongs to no wave interval
        self._fold_round_timings()
        ws = self.fabric.wire_stats()
        self._journal_prev = {
            "tick": self.tick, "wire": ws["bytes_sent"],
            "raw": ws["raw_bytes_sent"],
            "leg": dict(self._leg_wait_s), "hidden": self._hidden_s,
        }

    def run_until_converged(
        self,
        max_ticks: int = 10_000,
        sink=None,
        journal_every: int = 0,
        journal_light: bool = False,
    ):
        """Step until the global AND plane reports convergence (checked
        every tick — the reduce words already cross the fabric, so the
        check is free).  Returns (ticks_used, converged).

        ``journal_every > 0`` builds a journal record every that-many
        ticks plus one at exit.  Record building is COLLECTIVE (digest and
        coverage allgather across the fabric), so every rank must pass the
        same ``journal_every`` — ranks without a ``sink`` still take part
        in the combine and simply drop the record.  ``journal_light``
        makes the PERIODIC records skip the state digest (the per-tick
        wire wave's mode — see :meth:`journal_record`); the exit record
        is always full."""
        start = self.tick
        emitted_at = None
        while self.tick - start < max_ticks:
            self.step()
            done = bool(self.converged)
            if journal_every and (((self.tick - start) % journal_every == 0) or done):
                # collective on every rank; the final record is full
                rec = self.journal_record(light=journal_light and not done)
                emitted_at = self.tick
                if sink is not None:
                    sink(rec)
            if done:
                break
        if journal_every and emitted_at != self.tick:
            rec = self.journal_record()  # tail record, still collective
            if sink is not None:
                sink(rec)
        return self.tick - start, bool(self.converged)
