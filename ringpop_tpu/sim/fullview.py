"""Exact full-view SWIM simulator: the whole cluster as (N × N) arrays.

Semantic parity with the host plane, vectorized (reference call stack
``swim/gossip.go:178`` → ``node.go:470-513`` → ``memberlist.go:310-390``):

* one tick = one protocol period for EVERY node simultaneously;
* change application is the lattice max over ``key = (incarnation << 3) |
  state_precedence`` — the pure-function override rule from
  ``ringpop_tpu.swim.member`` lifted to arrays (same ordering, so the result
  is identical to sequential application in any order);
* refutation: a node receiving a detraction about itself at incarnation >=
  its own reasserts Alive at a fresh wall-ms incarnation
  (``memberlist.go:337-354``);
* failed direct probe → k indirect probes → Suspect (``node.go:494-510``,
  all-errors inconclusive rule included);
* suspicion timers are deadline arrays compared against sim time
  (``state_transitions.go:90-117`` — suspect→faulty→tombstone→evict, same-
  state dedup, cross-state replace, never for self);
* full sync: a ping answered with zero changes against a mismatched view is
  answered with the full membership, both directions (``disseminator.go:156-304``).

Deviations from the host plane (documented, not semantic):
* no source-filtering of piggybacked responses (``disseminator.go:185-199``)
  — refiltering only saves bandwidth; application is idempotent under max;
* receiver piggyback counters bump once per tick instead of once per
  concurrent ping; maxP expiry timing can differ by a tick under ping
  collisions.

State dtypes: ``status`` int8, ``incarnation`` int32, counters int32 —
bandwidth-lean for HBM and x64-free (TPUs default to 32-bit).  Incarnations
are *relative*: milliseconds since the sim epoch (the host plane's wall-ms
incarnations map onto this by subtracting a base; 2^27 ms of headroom ≈ 37
hours of simulated time before key packing would overflow int32).  The N×N
key ops fuse into a handful of XLA kernels.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import pair_connected
from ringpop_tpu.swim.member import (
    ALIVE,
    FAULTY,
    KEY_STATE_BITS as STATE_BITS,  # re-export kept for conformance harness
    LEAVE,
    SUSPECT,
    TOMBSTONE,
    is_detraction,
    pack_key,
)


class FullViewState(NamedTuple):
    """One pytree = the whole simulated cluster."""

    status: jax.Array  # int8[N, N]   view[i, j]
    incarnation: jax.Array  # int32[N, N], ms since sim epoch
    present: jax.Array  # bool[N, N]  j exists in i's member table
    has_change: jax.Array  # bool[N, N] i has a dissemination record about j
    pcount: jax.Array  # int32[N, N] piggyback counter
    pending: jax.Array  # int8[N, N]  scheduled transition source state or -1
    deadline: jax.Array  # int32[N, N] tick at which the transition fires
    tick: jax.Array  # int32 scalar, sim time in protocol periods
    key: jax.Array  # PRNG key


@dataclass(frozen=True)
class FullViewParams:
    n: int
    # reference defaults expressed in ticks (protocol period = 200ms):
    suspect_ticks: int = 25  # 5s / 200ms   (swim/node.go:74)
    faulty_ticks: int = 432000  # 24h
    tombstone_ticks: int = 300  # 60s
    ping_req_size: int = 3  # swim/node.go:86
    p_factor: int = 15  # disseminator.go:35
    tick_ms: int = 200  # ms of simulated time per tick


def _now_ms(params: FullViewParams, tick) -> jax.Array:
    # relative wall-ms: strictly positive so refutes always exceed the
    # converged base incarnation (0)
    return (tick.astype(jnp.int32) + 1) * params.tick_ms


def _key_of(inc, status):
    """Override-order key: lexicographic (incarnation, precedence) as one
    int32 — ``member.pack_key`` with array dtype coercion."""
    return pack_key(inc.astype(jnp.int32), status.astype(jnp.int32))


_is_detraction = is_detraction


def init_state(
    params: FullViewParams, seed: int = 0, converged: bool = True
) -> FullViewState:
    """All nodes alive; everyone knows everyone (converged) or only itself."""
    n = params.n
    eye = np.eye(n, dtype=bool)
    present = np.ones((n, n), dtype=bool) if converged else eye.copy()
    status = np.zeros((n, n), dtype=np.int8)
    inc = np.zeros((n, n), dtype=np.int32)  # converged base = incarnation 0
    return FullViewState(
        status=jnp.asarray(status),
        incarnation=jnp.asarray(inc),
        present=jnp.asarray(present),
        has_change=jnp.zeros((n, n), dtype=bool),
        pcount=jnp.zeros((n, n), dtype=jnp.int32),
        pending=jnp.full((n, n), -1, dtype=jnp.int8),
        deadline=jnp.zeros((n, n), dtype=jnp.int32),
        tick=jnp.asarray(0, dtype=jnp.int32),
        key=jax.random.PRNGKey(seed),
    )


@dataclass(frozen=True)
class Faults:
    """Fault model for a step: all plain arrays (BASELINE fault configs)."""

    up: Optional[jax.Array] = None  # bool[N] process liveness
    group: Optional[jax.Array] = None  # int32[N] partition group (-1 = all)
    drop_rate: float = 0.0  # per-message loss probability


# Faults flows through jit: up/group are traced children, drop_rate is
# static aux data (a new rate simply retraces once)
jax.tree_util.register_pytree_node(
    Faults,
    lambda f: ((f.up, f.group), f.drop_rate),
    lambda aux, children: Faults(up=children[0], group=children[1], drop_rate=aux),
)


def _connectivity(params, faults: Faults, key, targets):
    """conn[i] = can i's ping reach targets[i] this tick."""
    n = params.n
    up = faults.up if faults.up is not None else jnp.ones(n, dtype=bool)
    conn = up & up[targets]
    if faults.group is not None:
        g = faults.group
        gt = g[targets]
        conn &= (g < 0) | (gt < 0) | (g == gt)
    if faults.drop_rate > 0:
        conn &= jax.random.uniform(key, (n,)) >= faults.drop_rate
    return conn, up


def _pair_connected(params, faults: Faults, a, b):
    """Static (no-drop) connectivity between index arrays a and b (shared
    impl: ``ringpop_tpu.sim.delta.pair_connected``)."""
    return pair_connected(faults, a, b)


def _max_p(params, status, present, eye):
    """Per-node dissemination bound maxP = pFactor * ceil(log10(pingable+1))
    (parity: ``disseminator.go:75-97``)."""
    pingable = present & ((status == ALIVE) | (status == SUSPECT)) & ~eye
    num = pingable.sum(axis=1)
    return (
        params.p_factor * jnp.ceil(jnp.log10(num.astype(jnp.float32) + 1.0))
    ).astype(jnp.int32)


def _apply_batch(params, state: FullViewState, cand_key, cand_mask, now_ms, eye):
    """Apply a batch of candidate changes (one candidate per (observer,
    subject) cell, already max-merged) — the array form of
    ``memberlist.Update``.  Returns new state pieces + applied mask."""
    n = params.n
    status, inc, present = state.status, state.incarnation, state.present
    pending, deadline = state.pending, state.deadline

    cand_status = (cand_key & ((1 << STATE_BITS) - 1)).astype(jnp.int8)
    cand_inc = cand_key >> STATE_BITS

    local_key = _key_of(inc, status)
    local_eff = jnp.where(present, local_key, jnp.int32(-1))

    # refutation: a detraction about myself at inc >= mine
    # (memberlist.go:337-354; localOverride member.go:98-110)
    refute = (
        cand_mask
        & eye
        & _is_detraction(cand_status)
        & (cand_inc >= inc)
        & present
    )

    # non-local (and first-seen) override by strict key order
    wins = cand_mask & (cand_key > local_eff) & ~refute
    # first-seen tombstones are refused (memberlist.go:421-426)
    first_seen = wins & ~present
    wins &= ~(first_seen & (cand_status == TOMBSTONE))

    new_status = jnp.where(wins, cand_status, status)
    new_inc = jnp.where(wins, cand_inc, inc)
    new_present = present | wins

    # refutations reassert alive at a fresh wall-ms incarnation
    refute_inc = jnp.broadcast_to(now_ms, (n, n))
    new_status = jnp.where(refute, jnp.int8(ALIVE), new_status)
    new_inc = jnp.where(refute, refute_inc, new_inc)

    applied = wins | refute

    # dissemination records for every applied change (node.go:424-427)
    has_change = jnp.where(applied, True, state.has_change)
    pcount = jnp.where(applied, 0, state.pcount)

    # suspicion timers (node.go:429-445, state_transitions.go:119-160):
    # alive/leave cancel; suspect/faulty/tombstone schedule unless a timer
    # for the same state is already pending; never for self.
    eff_status = new_status
    cancel = applied & ((eff_status == ALIVE) | (eff_status == LEAVE))
    timeout_for = {
        SUSPECT: params.suspect_ticks,
        FAULTY: params.faulty_ticks,
        TOMBSTONE: params.tombstone_ticks,
    }
    new_pending = jnp.where(cancel, jnp.int8(-1), pending)
    new_deadline = deadline
    for st, ticks in timeout_for.items():
        sched = applied & (eff_status == st) & ~eye & (pending != st)
        new_pending = jnp.where(sched, jnp.int8(st), new_pending)
        new_deadline = jnp.where(sched, state.tick + ticks, new_deadline)

    return state._replace(
        status=new_status,
        incarnation=new_inc,
        present=new_present,
        has_change=has_change,
        pcount=pcount,
        pending=new_pending,
        deadline=new_deadline,
    ), applied


def _fire_timers(params, state: FullViewState, now_ms, eye):
    """Deadline-array transitions (state_transitions.go:90-117): the timer
    fires a Make{Faulty,Tombstone} / Evict, which is itself a local change."""
    due = (state.pending >= 0) & (state.tick >= state.deadline)

    # suspect->faulty, faulty->tombstone at the member's current incarnation
    fire_faulty = due & (state.pending == SUSPECT)
    fire_tomb = due & (state.pending == FAULTY)
    fire_evict = due & (state.pending == TOMBSTONE)

    cand_status = jnp.where(
        fire_faulty, jnp.int8(FAULTY), jnp.where(fire_tomb, jnp.int8(TOMBSTONE), jnp.int8(0))
    )
    cand_mask = fire_faulty | fire_tomb
    cand_key = _key_of(state.incarnation, cand_status)

    state = state._replace(pending=jnp.where(due, jnp.int8(-1), state.pending))
    state, _ = _apply_batch(params, state, cand_key, cand_mask, now_ms, eye)

    # eviction removes the member entirely (memberlist.Evict; never self —
    # self never gets a timer)
    state = state._replace(
        present=state.present & ~fire_evict,
        has_change=state.has_change & ~fire_evict,
    )
    return state


def step(
    params: FullViewParams,
    state: FullViewState,
    faults: Faults = Faults(),
    targets: Optional[jax.Array] = None,
    peers: Optional[jax.Array] = None,
) -> FullViewState:
    """One protocol period for every node (jit-compatible; ``targets`` and
    ping-req ``peers`` may be injected for deterministic conformance runs —
    with both injected and ``drop_rate == 0`` the step is a pure function of
    the state, which is what the lockstep harness in
    ``ringpop_tpu.sim.conformance`` relies on)."""
    n = params.n
    eye = jnp.eye(n, dtype=bool)
    key, k_target, k_drop, k_peers = jax.random.split(state.key, 4)
    now = _now_ms(params, state.tick)

    # -- ping target selection (memberlist_iter.go round-robin becomes a
    # masked categorical draw; injectable for lockstep conformance)
    pingable = state.present & ((state.status == ALIVE) | (state.status == SUSPECT)) & ~eye
    if targets is None:
        logits = jnp.where(pingable, 0.0, -jnp.inf)
        any_pingable = pingable.any(axis=1)
        safe_logits = jnp.where(any_pingable[:, None], logits, 0.0)
        targets = jax.random.categorical(k_target, safe_logits, axis=1)
    else:
        any_pingable = pingable.any(axis=1)
    targets = targets.astype(jnp.int32)

    conn, up = _connectivity(params, faults, k_drop, targets)
    delivered = conn & any_pingable & up  # dead/idle nodes don't ping

    max_p = _max_p(params, state.status, state.present, eye)

    # -- request leg: senders' unexpired changes, max-merged per target ----
    send_mask = state.has_change & (state.pcount < max_p[:, None]) & delivered[:, None]
    send_key = jnp.where(send_mask, _key_of(state.incarnation, state.status), jnp.int32(-1))
    # scatter-max by target: concurrent pings to one node merge exactly
    # because application is a lattice max
    inbound = jax.ops.segment_max(
        jnp.where(delivered[:, None], send_key, jnp.int32(-1)),
        targets,
        num_segments=n,
        indices_are_sorted=False,
    )
    inbound = jnp.maximum(inbound, jnp.int32(-1))  # segment_max fills -inf-ish
    state, _ = _apply_batch(params, state, inbound, inbound >= 0, now, eye)

    # -- full-sync detection (disseminator.go:156-181): target had no
    # changes to answer with AND the sender's view differs from its own
    t = targets
    has_any = state.has_change.any(axis=1)
    both = state.present & state.present[t]
    cell_eq = jnp.where(
        both,
        (state.status == state.status[t]) & (state.incarnation == state.incarnation[t]),
        state.present == state.present[t],
    )
    views_equal = cell_eq.all(axis=1)
    full_sync = delivered & ~has_any[t] & ~views_equal

    # -- response leg: target's changes (or full membership on full sync)
    resp_mask = state.has_change[t] & (state.pcount[t] < max_p[t][:, None])
    resp_mask = jnp.where(full_sync[:, None], state.present[t], resp_mask)
    resp_key = jnp.where(
        resp_mask & delivered[:, None],
        _key_of(state.incarnation[t], state.status[t]),
        jnp.int32(-1),
    )
    state, _ = _apply_batch(params, state, resp_key, resp_key >= 0, now, eye)

    # reverse full sync (disseminator.go:257-304): the target pulls the
    # sender's membership too — scatter the sender's full view at the target
    rfs_key = jnp.where(
        (full_sync & delivered)[:, None] & state.present,
        _key_of(state.incarnation, state.status),
        jnp.int32(-1),
    )
    rfs_inbound = jax.ops.segment_max(rfs_key, targets, num_segments=n)
    rfs_inbound = jnp.maximum(rfs_inbound, jnp.int32(-1))
    state, _ = _apply_batch(params, state, rfs_inbound, rfs_inbound >= 0, now, eye)

    # -- piggyback counter bumps + expiry (disseminator.go:128-153) --------
    sender_bump = send_mask  # bump on success only (ping_sender.go:52)
    recv_count = jax.ops.segment_sum(
        delivered.astype(jnp.int32), targets, num_segments=n
    )
    receiver_bump = state.has_change & (recv_count[:, None] > 0) & (state.pcount < max_p[:, None])
    pcount = state.pcount + sender_bump + receiver_bump
    expired = pcount >= max_p[:, None]
    state = state._replace(
        pcount=jnp.where(expired, 0, pcount),
        has_change=state.has_change & ~expired,
    )

    # -- failed probe: indirect ping-req then Suspect (node.go:494-510) ----
    probing = any_pingable & up & ~delivered
    # peers drawn from each node's pingable view excluding the target
    # (memberlist.go:200-218 RandomPingableMembers; with replacement here)
    peer_pool = pingable & ~jax.nn.one_hot(targets, n, dtype=bool)
    if peers is None:
        peer_logits = jnp.where(peer_pool, 0.0, -jnp.inf)
        peer_logits = jnp.where(peer_pool.any(axis=1)[:, None], peer_logits, 0.0)
        peer_choices = jax.random.categorical(
            k_peers, peer_logits[:, None, :], axis=-1, shape=(n, params.ping_req_size)
        ).astype(jnp.int32)
    else:
        peer_choices = peers.astype(jnp.int32)
    i_idx = jnp.arange(n)[:, None]
    peer_ok = (
        peer_pool[i_idx, peer_choices]
        & _pair_connected(params, faults, jnp.broadcast_to(i_idx, peer_choices.shape), peer_choices)
    )
    peer_reaches = peer_ok & _pair_connected(
        params, faults, peer_choices, jnp.broadcast_to(targets[:, None], peer_choices.shape)
    )
    # each indirect leg is its own RPC and suffers packet loss too (drawn
    # only when drop_rate > 0, so deterministic conformance runs keep their
    # documented RNG draw order)
    if faults.drop_rate > 0:
        k_pd1, k_pd2 = jax.random.split(jax.random.fold_in(k_peers, 1), 2)
        peer_ok &= jax.random.uniform(k_pd1, peer_choices.shape) >= faults.drop_rate
        peer_reaches &= peer_ok & (
            jax.random.uniform(k_pd2, peer_choices.shape) >= faults.drop_rate
        )
    if faults.up is not None:
        peer_reaches &= faults.up[targets][:, None]
    reached = peer_reaches.any(axis=1)
    errs = (~peer_ok).sum(axis=1)
    inconclusive = errs == params.ping_req_size
    declare_suspect = probing & ~reached & ~inconclusive

    # suspect at the member's current incarnation (node.go:508)
    tgt_inc = state.incarnation[jnp.arange(n), targets]
    cand_key = _key_of(tgt_inc, jnp.int8(SUSPECT))
    suspect_cand = jnp.full((n, n), -1, dtype=jnp.int32)
    suspect_cand = suspect_cand.at[jnp.arange(n), targets].set(
        jnp.where(declare_suspect, cand_key, jnp.int32(-1))
    )
    state, _ = _apply_batch(params, state, suspect_cand, suspect_cand >= 0, now, eye)

    # -- timers fire against sim time --------------------------------------
    state = _fire_timers(params, state, now, eye)

    return state._replace(tick=state.tick + 1, key=key)


def as_fullview_faults(faults) -> Faults:
    """Coerce a shared-harness ``delta.DeltaFaults`` (whose ``drop_rate``
    is a traced-leaf Optional since the chaos plane) into this engine's
    own ``Faults``, where the rate stays STATIC aux data — the oracle
    engine keeps its retrace-per-rate design.  Host-side only: the rate
    must be a concrete (hashable) number here, not a traced array."""
    if isinstance(faults, Faults):
        return faults
    if hasattr(faults, "at_tick"):
        raise TypeError(
            "the fullview oracle takes a static fault model, not a "
            "time-varying chaos.FaultPlan — evaluate it yourself at a "
            "fixed tick (chaos.faults_at(plan, t)) if that is what you "
            "mean"
        )
    if getattr(faults, "reach", None) is not None or getattr(faults, "drop_node", None) is not None:
        # refusing beats silently simulating a DIFFERENT fault model: the
        # oracle's connectivity is symmetric-group + scalar loss only
        raise ValueError(
            "fullview cannot express directed reach / per-node drop — "
            "those legs exist only in the delta/lifecycle engines"
        )
    if (
        getattr(faults, "tier_ids", None) is not None
        or getattr(faults, "suspect_ticks", None) is not None
    ):
        # same rule for the topology round's legs: per-tier loss and the
        # traced suspicion timeout have no fullview counterpart (its
        # suspect_ticks is static aux), so silently dropping them would
        # simulate a different model
        raise ValueError(
            "fullview cannot express topology tier legs or a traced "
            "suspect_ticks override — those exist only in the "
            "delta/lifecycle engines"
        )
    rate = getattr(faults, "drop_rate", None)
    return Faults(
        up=faults.up,
        group=faults.group,
        drop_rate=0.0 if rate is None else rate,
    )


class FullViewSim:
    """Convenience wrapper: init + jitted multi-tick runs.  Accepts this
    engine's ``Faults`` or a shared-harness ``delta.DeltaFaults``
    (coerced via :func:`as_fullview_faults`)."""

    def __init__(self, n: int, seed: int = 0, converged: bool = True, **kw):
        self.params = FullViewParams(n=n, **kw)
        self.state = init_state(self.params, seed=seed, converged=converged)
        self._step = jax.jit(
            functools.partial(step, self.params), static_argnames=()
        )

    def tick(self, faults: Faults = Faults(), targets=None, peers=None) -> FullViewState:
        self.state = self._step(self.state, as_fullview_faults(faults), targets, peers)
        return self.state

    def run(self, ticks: int, faults: Faults = Faults()) -> FullViewState:
        for _ in range(ticks):
            self.tick(faults)
        return self.state

    # -- queries ------------------------------------------------------------

    def views_converged(self) -> bool:
        """All live nodes share an identical view (the sim analog of equal
        checksums)."""
        s = self.state
        ref_status, ref_inc, ref_p = s.status[0], s.incarnation[0], s.present[0]
        return bool(
            (
                (s.status == ref_status[None, :]).all()
                & (s.incarnation == ref_inc[None, :]).all()
                & (s.present == ref_p[None, :]).all()
            )
        )

    def status_matrix(self) -> np.ndarray:
        return np.asarray(self.state.status)

    def has_changes(self) -> bool:
        return bool(self.state.has_change.any())
