"""Scalable full-lifecycle SWIM simulator: failure detection at O(N·K).

The delta engine (``ringpop_tpu.sim.delta``) measures pure dissemination of
pre-injected rumors.  This engine adds the *failure-detection dynamics* of
the reference — probe → indirect probe → Suspect → deadline → Faulty →
Tombstone → evict, and refutation-by-reincarnation (call stack
``swim/node.go:470-513``, ``swim/state_transitions.go:90-117``,
``swim/memberlist.go:337-354``) — while keeping memory O(N·K), so 100k–1M
node clusters fit on one chip (a full per-node view is O(N²)).

Representation.  Every node's view is ``converged base ⊔ learned rumors``:

* ``base_{status,incarnation,present}[N]`` — the view every node agrees on;
* a K-slot rumor table ``(subject, incarnation, status, deadline)`` — the
  changes currently in flight;
* ``learned[N, W]`` (uint32, the K rumor bits packed 32-per-word — see
  ``sim/packbits``) / ``pcount[N, K]`` (int8) — who has absorbed which
  rumor and the SWIM piggyback counters bounding how long it rides
  (``disseminator.go:75-97``).

Because change application is a lattice max over ``key = (incarnation <<
3) | state_precedence`` (``ringpop_tpu.swim.member``), a node's belief about
subject ``s`` is exactly ``max(base_key[s], max of learned rumor keys about
s)`` — order-independent, so "which rumors were learned" fully determines
the view.

A probabilistic partition healer (one attempted full rumor-swap between a
random connected pair per tick, rate-matched to the reference's ~6
discovery-provider calls/min — ``heal_via_discover_provider.go:63-88``)
repairs the mutual-faulty deadlock two partitioned sides otherwise end in.

Rumor lifecycle: allocated (probe failure / refutation / fired timer) →
disseminated by piggybacking on ping request+response legs → learned by all
live nodes → **folded into the base** (its pending deadline transfers to a
per-subject base timer) → slot freed for reuse.  Saturation of the K slots
just delays new declarations a tick — they regenerate as long as their
cause persists.

Deliberate approximations vs the reference (documented, aggregate-faithful):

* suspicion timers are per-rumor (earliest declarer's clock), not
  per-(observer, subject) — the reference's first-firing timer is the one
  that generates the Faulty change anyway (``state_transitions.go:90-117``);
* a node whose sampled ping target is believed unpingable idles for a tick
  instead of advancing a shuffled iterator (``memberlist_iter.go:50-72``);
* a rumor that expired (maxP) before reaching every live node is re-seeded
  (counters reset) — the analog of the checksum-mismatch full-sync repair
  path (``disseminator.go:156-304``), without shipping O(N) payloads;
* eviction clears the subject from the shared base once the Tombstone is
  fully disseminated, instead of per-view removal (``memberlist.go:271-279``).

Exact per-node semantics (including the paths above in full) live in the
O(N²) ``fullview`` engine; the lockstep conformance harness validates that
engine against the sequential host plane.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import (
    DeltaFaults,
    check_tier_legs as _check_tier_legs,
    clamped_max_p,
    has_drop as _has_drop,
    leg_survives as _leg_survives,
    pair_connected as _pair_connected,
    resolve_faults as _resolve_faults,
    resolve_max_p,
    tier_pair as _tier_pair,
    tier_pair_drop as _tier_pair_drop,
    until_loop,
)
from ringpop_tpu.sim.packbits import (
    and_reduce_rows,
    bit_column,
    block_count,
    check_rumor_shardable,
    mix32,
    n_words,
    or_reduce_rows,
    pack_bool,
    row_mask,
    set_bit,
    set_bit_per_row,
    unpack_bits,
)
from ringpop_tpu.swim.member import (
    ALIVE,
    FAULTY,
    KEY_STATE_BITS,
    SUSPECT,
    TOMBSTONE,
    is_detraction as _is_detraction,
    is_pingable,
    key_incarnation,
    key_state,
    pack_key,
)

NO_DEADLINE = np.int32(np.iinfo(np.int32).max)


class LifecycleState(NamedTuple):
    # rumor table (K slots; subject -1 = free)
    r_subject: jax.Array  # int32[K]
    r_inc: jax.Array  # int32[K] incarnation (protocol-tick counter)
    r_status: jax.Array  # int8[K]
    r_deadline: jax.Array  # int32[K] tick when the state timer fires
    # per-(node, rumor); ``learned`` is BIT-PACKED along the rumor axis
    # (slot j = word j>>5, bit j&31 — see sim/packbits.py): a bool plane
    # at 1M x 256 is 256 MB and one tick touches a dozen of them, so the
    # packed layout is what fits the protocol tick in a CPU core's memory
    # bandwidth and trims HBM bytes on TPU
    learned: jax.Array  # uint32[N, W], W = ceil(K/32)
    pcount: jax.Array  # int8[N, K]
    # derived invariant carried as state: ride_ok == pack_bool(pcount <
    # maxp).  A loop-carried leaf is the only materialization fence
    # XLA:CPU honors — recomputing the 32-wide pack-reduce in-tick lets it
    # inline per consuming element (the lesson sim/delta.py learned the
    # hard way; see PERF.md "Round 3")
    ride_ok: jax.Array  # uint32[N, W]
    # converged base view shared by all nodes
    base_status: jax.Array  # int8[N]
    base_inc: jax.Array  # int32[N]
    base_present: jax.Array  # bool[N]
    base_pending: jax.Array  # int8[N] scheduled transition source state or -1
    base_deadline: jax.Array  # int32[N]
    # each node's own incarnation (refutation bumps it)
    self_inc: jax.Array  # int32[N]
    tick: jax.Array  # int32
    key: jax.Array  # PRNG key


@dataclass(frozen=True)
class LifecycleParams:
    n: int
    k: int = 128  # rumor-slot capacity
    # reference defaults in ticks (protocol period 200ms, swim/node.go:74-100)
    suspect_ticks: int = 25  # 5s
    faulty_ticks: int = 432000  # 24h
    tombstone_ticks: int = 300  # 60s
    ping_req_size: int = 3
    p_factor: int = 15
    max_p: Optional[int] = None
    alloc_per_tick: int = 64  # new-rumor budget per tick (<= k)
    tick_ms: int = 200  # simulated ms per tick (reporting only)
    # "shift" = cyclic-permutation partners (scatterless exchange, TPU-fast;
    # exactly one probe per target per tick); "uniform" = independent draws
    # (expected one probe per target).  See DeltaParams.exchange.
    exchange: str = "shift"
    # partition-healer attempt rate, cluster-wide per tick.  Reference: each
    # node tries every 30s with probability 3/n → ~one attempt per 10s
    # cluster-wide (swim/node.go:59-67, heal_via_discover_provider.go:63-88),
    # i.e. ~0.02 per 200ms tick.
    heal_prob: float = 0.02
    # PRNG family: "threefry" = the jax.random draws the frozen goldens pin
    # (replicated/lane-divergent under GSPMD); "counter" = the
    # partition-invariant stateless generator (sim/prng.py) — every lane a
    # pure function of (seed, tick, lane, draw site), shard-local with zero
    # collectives and identical lanes on any mesh.  Sharded callers and
    # simbench default to "counter"; the two families draw different
    # (equally valid) trajectories.
    rng: str = "threefry"
    # optional jax.sharding.Mesh with a >1-way "node" axis: lower the shift
    # exchange's two roll legs as explicit shard-local crossing-block
    # ppermutes (parallel/shift.shard_roll) instead of the plane-sized
    # all-gather GSPMD emits for a traced-shift gather.  Bit-identical to
    # the gather path by construction; None (default) keeps the
    # single-device lowering.
    exchange_mesh: Optional["jax.sharding.Mesh"] = None
    # sub-block factor H of the crossing-block decomposition (H+1 sends
    # per rolled leaf per leg; see parallel/shift.py — falls back to 1
    # when it does not divide the shard block).  Only read when
    # exchange_mesh is set.
    exchange_h: int = 2
    # True (default): both roll legs fused in one pipelined region
    # (shard_roll_pipelined) — response-leg sends issued while the
    # request-leg merge computes.  False: the sequential r8 legs (two
    # shard_roll calls), kept for the tpu_ksweep pipelined_exchange A/B.
    # Bit-identical and collective-census-identical either way.
    exchange_pipelined: bool = True

    def resolved_max_p(self) -> int:
        return resolve_max_p(self.n, self.p_factor, self.max_p)


def init_state(params: LifecycleParams, seed: int = 0) -> LifecycleState:
    return init_state_from_key(params, jax.random.PRNGKey(seed))


def init_state_from_key(params: LifecycleParams, key) -> LifecycleState:
    """Key-taking init variant — vmappable over a batch of PRNG keys (the
    Monte-Carlo sweep in ``sim/montecarlo.py`` builds replica batches this
    way)."""
    n, k = params.n, params.k
    return LifecycleState(
        r_subject=jnp.full((k,), -1, jnp.int32),
        r_inc=jnp.zeros((k,), jnp.int32),
        r_status=jnp.zeros((k,), jnp.int8),
        r_deadline=jnp.full((k,), NO_DEADLINE, jnp.int32),
        learned=jnp.zeros((n, n_words(k)), jnp.uint32),
        pcount=jnp.zeros((n, k), jnp.int8),
        ride_ok=pack_bool(
            jnp.zeros((n, k), jnp.int8)
            < jnp.int8(clamped_max_p(params))
        ),
        base_status=jnp.zeros((n,), jnp.int8),
        base_inc=jnp.zeros((n,), jnp.int32),
        base_present=jnp.ones((n,), bool),
        base_pending=jnp.full((n,), -1, jnp.int8),
        base_deadline=jnp.full((n,), NO_DEADLINE, jnp.int32),
        self_inc=jnp.zeros((n,), jnp.int32),
        tick=jnp.asarray(0, jnp.int32),
        key=key,
    )


def _key_of(inc, status):
    """``member.pack_key`` with array dtype coercion."""
    return pack_key(inc.astype(jnp.int32), status.astype(jnp.int32))


def _status_of(key):
    return key_state(key).astype(jnp.int8)


_inc_of = key_incarnation


def _bel_rumor_dense(learned_b, r_subject, rkey, active, targets):
    """Per-node max learned-rumor key about its ping target — the general
    O(N·K) form (any target assignment; ``learned_b`` unpacked bool)."""
    bmask = learned_b & active[None, :] & (r_subject[None, :] == targets[:, None])
    return jnp.max(
        jnp.where(bmask, rkey[None, :], jnp.int32(-1)), axis=1, initial=jnp.int32(-1)
    )


# candidate-compression capacity for _top_m_sparse (per node-block since
# the hierarchical rewrite), the minimum n at which the sparse path
# engages at all, and the node-block count of the hierarchical select.
# Module-level so tests can monkeypatch them down to force the
# shard-local select, the cross-block merge tie-break, and the overflow
# fallback at small n.  MIN_N matters because ``lax.cond`` under vmap
# (the Monte-Carlo engine vmaps step over a replica axis) lowers to a
# select that executes BOTH branches — the sparse path there would pay
# the full sort AND the compression; keeping every plausible vmapped
# cluster size (MC sweeps run 4k–16k nodes) on the static dense path
# makes that pessimization unreachable, while the 100k–16M single-sim
# shapes that actually suffer the sort get the sparse win.
_SPARSE_TOPK_CAP = 4096
_SPARSE_TOPK_MIN_N = 65536
# node-axis block count: candidates are selected per contiguous block of
# n/B subjects, then merged.  A multiple of every plausible node-shard
# count, so each mesh shard owns whole blocks and the per-block cumsum /
# compress / top_k stay shard-LOCAL under the SPMD partitioner — the
# global-index formulation this replaces forced ~25 all-gathers (90
# MB/chip) per sharded 1M tick (PERF.md "Multi-chip collective cost
# model").  Falls back to the largest power of two that divides n
# (packbits.block_count — the shared rule of every blocked-for-SPMD path).
_TOPK_BLOCKS = 16
# same rule for the two-level row gathers (_gather_rows) — a separate
# knob so tuning the candidate-select fan-out for a bigger mesh doesn't
# silently change the gather paths' traffic shape
_GATHER_BLOCKS = 16


def _top_m_sparse(cand: jax.Array, m: int):
    """Exact ``lax.top_k(cand, m)`` for a sparse candidate vector —
    hierarchical: per-node-block compress + select, then a tiny merge.

    ``top_k`` over [N] lowers to a full stable SORT — measured 446 ms of
    the 1M-node tick on XLA:CPU, ~20% of the whole step — but at most
    ~(victims + K + refuters) entries of ``cand`` are ever >= 0 (every
    other subject carries the -1 sentinel).  So: split the subject axis
    into B contiguous blocks, prefix-sum each block's candidate mask
    LOCALLY, scatter its candidates (in index order) into a per-block
    [C] buffer (a vmapped scatter — the batched form the SPMD
    partitioner keeps shard-local, unlike the global-index scatter it
    could only all-gather), top_k each buffer, and merge the B×m
    (value, subject) pairs with one final top_k over B·m elements.  The
    only cross-shard traffic is that B×m-pair merge — versus the
    [N]-sized cumsum/scatter/sort globals of the flat form.

    Value-identity with the full ``lax.top_k(cand, m)``, including
    scatter side effects downstream:

    * within a block, candidates keep their original index order through
      the compress, and top_k is a stable sort, so a block's survivors
      are its lowest-indexed among equal keys;
    * across blocks, the merge buffer concatenates blocks in ascending
      block order, each internally (value desc, index asc) — so the
      final stable top_k resolves equal keys at the m boundary by
      (block asc, local index asc) = ascending global index, exactly the
      dense sort's tie order;
    * hierarchical exactness: any global top-m element is in its own
      block's top-m under the same (value desc, index asc) order, so
      per-block truncation to m cannot drop a winner — including tie
      groups straddling the boundary;
    * padding entries carry (value -1, subject n): every downstream
      scatter of a -1-valued entry either writes the buffer's default or
      is masked by ``place`` — and subject n is out of range, so the
      write is DROPPED (jax .at[] update semantics), matching the
      original's harmless in-range no-op writes without introducing
      duplicate subjects;
    * if any block holds more than C candidates (impossible at the
      headline config; possible in stretch scenarios like 16M nodes x
      16k victims in one block), a ``lax.cond`` falls back to the full
      sort — bit-for-bit the original path, just at the original speed.

    Certified against the dense form by tests/test_lifecycle.py
    (monkeypatched caps force every branch, sharded and not) and the
    frozen goldens.
    """
    n = cand.shape[0]
    cap = _SPARSE_TOPK_CAP
    if n <= max(cap, _SPARSE_TOPK_MIN_N) or m > cap:
        return jax.lax.top_k(cand, m)
    b = block_count(n, _TOPK_BLOCKS)
    nb = n // b
    cap = min(cap, nb)
    sel = min(m, cap)  # a block with <= cap candidates has <= cap to offer
    cand2 = cand.reshape(b, nb)
    is_c = cand2 >= 0
    pos = jnp.cumsum(is_c.astype(jnp.int32), axis=1) - 1
    n_c = pos[:, -1] + 1  # per-block candidate count

    def hierarchical(_):
        wr = jnp.where(is_c, pos, cap)  # cap = out of range -> dropped
        gidx = jnp.arange(n, dtype=jnp.int32).reshape(b, nb)

        def compress_row(c_row, w_row, g_row):
            buf = jnp.full((cap,), -1, jnp.int32).at[w_row].set(c_row, mode="drop")
            src = jnp.full((cap,), n, jnp.int32).at[w_row].set(g_row, mode="drop")
            return buf, src

        buf, src = jax.vmap(compress_row)(cand2, wr, gidx)
        lv, li = jax.lax.top_k(buf, sel)
        ls = jnp.take_along_axis(src, jnp.asarray(li), axis=1)
        lv = jnp.asarray(lv)
        if sel < m:  # cap < m: pad each block's offer out to m
            pad_v = jnp.full((b, m - sel), -1, jnp.int32)
            pad_s = jnp.full((b, m - sel), n, jnp.int32)
            lv = jnp.concatenate([lv, pad_v], axis=1)
            ls = jnp.concatenate([ls, pad_s], axis=1)
        v, i = jax.lax.top_k(lv.reshape(-1), m)
        return jnp.asarray(v), ls.reshape(-1)[jnp.asarray(i)]

    def full(_):
        v, i = jax.lax.top_k(cand, m)
        return v, i

    return jax.lax.cond((n_c <= cap).all(), hierarchical, full, None)


def _gather_rows(plane: jax.Array, idx: jax.Array) -> jax.Array:
    """``plane[idx]`` (row gather at traced indices) as a two-level block
    pick: take within each of B contiguous node blocks along the
    UNsharded in-block axis (local on every shard), then pick each row's
    owning block from the [B, ...] block stack (B × rows × cols of
    cross-shard traffic, independent of N).  A direct gather at traced
    row indices makes the SPMD partitioner all-gather the whole operand —
    the heal pair-swap's 2-row reads alone cost a full packed-plane
    gather (~16 MB/chip/tick at 1M) that way.  Identical values: row
    ``i`` IS block ``i // nb`` offset ``i % nb``.  On one core the extra
    work is B rows read instead of 1 — noise.  Callers must pass in-range
    indices (scalar or [S]); B falls back to the largest power of two
    dividing n."""
    n = plane.shape[0]
    g = block_count(n, _GATHER_BLOCKS)
    if g == 1 or n <= g:
        return plane[idx]
    nb = n // g
    blocks = plane.reshape((g, nb) + plane.shape[1:])
    within = jnp.take(blocks, idx % nb, axis=1)  # [g, *idx.shape, cols...]
    if jnp.ndim(idx) == 0:
        return jnp.take(within, idx // nb, axis=0)
    pick = (idx // nb).reshape((1,) + idx.shape + (1,) * (plane.ndim - 1))
    pick = jnp.broadcast_to(pick, (1,) + within.shape[1:])
    return jnp.take_along_axis(within, pick, axis=0)[0]


def step(
    params: LifecycleParams,
    state: LifecycleState,
    faults: DeltaFaults = DeltaFaults(),
    telemetry=None,
):
    """One protocol period for all N nodes.  Fixed shapes throughout; jit-
    and shard-friendly (the only cross-node ops are segment reductions by
    ping target / rumor subject and row gathers).

    The per-(node, rumor) booleans run BIT-PACKED (``sim/packbits``): the
    exchange legs, heal merge, and every derived mask are uint32 word ops
    on [N, W] planes, and the int8 ``pcount`` plane is touched in exactly
    two fused passes (bump+resets, then the post-alloc clears) with the
    bit unpacking fused into them.  Shift mode additionally replaces the
    two O(N·K) masked reduces that only involve (subject, prober) pairs —
    target belief and self-detection of detractions — with O(K) gathers +
    scatters, and the per-slot first-live-learner argmax runs only on
    ticks where a suspicion/faulty timer actually fired (lax.cond).  All
    of it is value-identical to the unpacked formulation — certified
    bit-for-bit by tests/test_lifecycle_golden.py.

    ``telemetry`` (a ``telemetry.TelemetryState`` or None): when given,
    the tick additionally accumulates the protocol counters — pure
    elementwise reads of intermediates the tick computes anyway (no PRNG
    draws, no feedback into the state, zero collectives under SPMD; see
    ``sim/telemetry.py``) — and the return becomes ``(state, telemetry)``.
    When None (the default), the traced program is exactly the
    telemetry-free one.  The ``jax.named_scope`` sections name the
    protocol phase in profiler traces and HLO metadata, which is what
    lets ``scripts/profile_mesh.py`` attribute each censused collective
    to a phase; scopes are metadata-only and change no values.

    ``faults`` may be a static ``DeltaFaults`` or a time-varying
    ``chaos.FaultPlan`` — a plan is evaluated shard-locally at
    ``state.tick`` (``delta.resolve_faults``, under the ``fault-plan``
    scope); a constant plan traces to the exact static program."""
    faults = _resolve_faults(faults, state.tick)
    with jax.named_scope("tick-prologue"):
        n, k = params.n, params.k
        m = min(params.alloc_per_tick, params.k, params.n)
        maxp = jnp.int8(clamped_max_p(params))
        if params.rng not in ("threefry", "counter"):
            raise ValueError(f"unknown rng family {params.rng!r}")
        use_counter = params.rng == "counter"
        if use_counter:
            # stateless counter stream (sim/prng.py): the key leaf is never
            # split — it carries the seed material and the tick counter
            # advances the stream, so every draw below is a pure
            # (shard-local, partition-invariant) function of its lane
            from ringpop_tpu.sim import prng as _prng

            key = state.key
            cseed = _prng.fold_key(state.key)
            ctick = state.tick
        else:
            key, k_target, k_drop, k_peers, k_heal = jax.random.split(state.key, 5)
        # incarnation epoch = tick counter (strictly increasing, like the
        # reference's wall-ms but 200× denser in int32: 2^28 ticks ≈ 621 days of
        # simulated time before the packed key would overflow)
        now = state.tick + 1
        i_all = jnp.arange(n, dtype=jnp.int32)

        up = faults.up if faults.up is not None else jnp.ones(n, bool)

        # topology legs present?  (static; the flat path compiles out)
        has_topo = _check_tier_legs(faults)
        if has_topo and not use_counter:
            raise ValueError(
                "topology tier legs need rng='counter': their loss coin is "
                "an extra stateless draw site; under threefry the extra "
                "split would shift every other draw"
            )
        # suspicion timeout: the static param unless the fault model
        # carries the traced override leg (suspect_ticks; -1 = the
        # value-neutral stacked default meaning "use the param").  None
        # traces to the exact static program — what keeps the frozen
        # goldens green without recapture.
        if faults.suspect_ticks is None:
            susp_ticks = params.suspect_ticks
        else:
            leg = jnp.asarray(faults.suspect_ticks, jnp.int32)
            susp_ticks = jnp.where(leg < 0, jnp.int32(params.suspect_ticks), leg)

        active = state.r_subject >= 0
        rkey = jnp.where(active, _key_of(state.r_inc, state.r_status), jnp.int32(-1))
        # segment id n == dump bucket for free slots
        subj = jnp.where(active, state.r_subject, jnp.int32(n))
        subj_rumor_max = jnp.maximum(
            jax.ops.segment_max(rkey, subj, num_segments=n + 1)[:n], jnp.int32(-1)
        )
        base_key = jnp.where(
            state.base_present, _key_of(state.base_inc, state.base_status), jnp.int32(-1)
        )
        eff_max = jnp.maximum(subj_rumor_max, base_key)

        active_w = pack_bool(active)  # [W], tail bits zero

    with jax.named_scope("ping-target"):
        # -- ping target selection + belief gate --------------------------------
        shift_mode = params.exchange == "shift"
        emesh = params.exchange_mesh
        use_sm = (
            shift_mode
            and emesh is not None
            and emesh.shape.get("node", 1) > 1
            and n % emesh.shape["node"] == 0
        )
        if shift_mode:
            if use_counter:
                shift = _prng.draw_randint(cseed, ctick, _prng.D_SHIFT, 0, 1, n)
            else:
                shift = jax.random.randint(k_target, (), 1, n, dtype=jnp.int32)
            targets = (i_all + shift) % n
            # belief[i] about its target: in shift mode each subject has
            # exactly one prober i = (s - shift) mod n, so the dense masked
            # reduce collapses to K bit-gathers + one scatter-max (identical
            # values; the dense form is O(N·K))
            prober = jnp.mod(state.r_subject - shift, n)
            pbit = bit_column(_gather_rows(state.learned, jnp.clip(prober, 0, n - 1)), jnp.arange(k))
            bel_vals = jnp.where(active & pbit, rkey, jnp.int32(-1))
            bel_rumor = jnp.full((n,), -1, jnp.int32).at[
                jnp.where(active, prober, jnp.int32(n))
            ].max(bel_vals, mode="drop")
        else:
            if use_counter:
                targets = _prng.draw_randint(cseed, ctick, _prng.D_TARGET, i_all, 0, n - 1)
            else:
                targets = jax.random.randint(k_target, (n,), 0, n - 1, dtype=jnp.int32)
            targets = jnp.where(targets >= i_all, targets + 1, targets)
            learned0_b = unpack_bits(state.learned, k)
            bel_rumor = _bel_rumor_dense(learned0_b, state.r_subject, rkey, active, targets)
        bel = jnp.maximum(bel_rumor, base_key[targets])
        bel_status = _status_of(jnp.maximum(bel, 0))
        believes_pingable = (bel >= 0) & is_pingable(bel_status)
        wants = up & believes_pingable

    with jax.named_scope("rumor-exchange"):
        conn = _pair_connected(faults, i_all, targets)
        if _has_drop(faults):
            drop_u = (
                _prng.draw_uniform(cseed, ctick, _prng.D_DROP, i_all)
                if use_counter
                else jax.random.uniform(k_drop, (n,))
            )
            conn &= _leg_survives(faults, drop_u, i_all, targets)
        if has_topo:
            # per-tier leg loss (sim/topology.py): its own stateless coin,
            # so an all-zero table — the stacked-fleet default — passes
            # every draw and the member stays bit-identical to a flat one
            topo_u = _prng.draw_uniform(cseed, ctick, _prng.D_TOPO, i_all)
            conn &= topo_u >= _tier_pair_drop(faults, i_all, targets)
        delivered = conn & wants

        # -- piggyback exchange: request leg + response leg ---------------------
        # (packed word ops in shift mode; the uniform path keeps the bool
        # formulation — segment_max has no bitwise-OR combiner — and packs at
        # the end.  Both produce identical bits.)
        if shift_mode:
            ride_ok_w = state.ride_ok  # carried, materialized at the tick edge
            dmask = row_mask(delivered)
            riding_w = state.learned & ride_ok_w & active_w[None, :]
            sent_w = riding_w & dmask
            if use_sm and params.exchange_pipelined:
                # sharded callers, r11 default: BOTH roll legs in one fused
                # shard-local region (parallel/shift.shard_roll_pipelined)
                # — the response leg's crossing ppermutes are issued as
                # soon as the two request-leg pieces of their window
                # arrive, before the request merge consumes the other
                # sub-blocks, so XLA's scheduler can overlap them with the
                # merge compute.  The response plane is built inside the
                # region as (learned | inbound) & ride per sub-block; the
                # [K]-axis active mask commutes with the node roll, so it
                # applies after the region — bit-identical values, and
                # collective-count/byte-identical to the sequential legs.
                from jax.sharding import PartitionSpec as _P

                from ringpop_tpu.parallel.shift import shard_roll_pipelined

                wspec = _P("node", "rumor" if "rumor" in emesh.shape else None)
                vspec = _P("node")
                inbound_w, got_pinged, resp_raw = shard_roll_pipelined(
                    (sent_w, delivered), shift, emesh, "node", (wspec, vspec),
                    carry=(state.learned, ride_ok_w), carry_specs=(wspec, wspec),
                    leg2_of=lambda inb, gp, lrn, rd: (lrn | inb) & rd,
                    spec2=wspec, h=params.exchange_h,
                )
                learned1_w = state.learned | inbound_w
                resp_w = resp_raw & active_w[None, :] & dmask
                learned2_w = learned1_w | resp_w
            else:
                if use_sm:
                    # sequential r8 legs (kept for the tpu_ksweep
                    # pipelined_exchange A/B): the two roll legs as explicit
                    # shard-local crossing-block ppermutes
                    # (parallel/shift.shard_roll, H+1 sub-block sends per
                    # leg) — per-leg cross-chip bytes drop from the
                    # plane-sized all-gather GSPMD emits for a traced-index
                    # gather to ~1.5 local blocks per chip.  Bit-identical:
                    # the region is pure data movement.
                    from jax.sharding import PartitionSpec as _P

                    from ringpop_tpu.parallel.shift import shard_roll

                    wspec = _P("node", "rumor" if "rumor" in emesh.shape else None)
                    vspec = _P("node")
                    inbound_w, got_pinged = shard_roll(
                        (sent_w, delivered), shift, emesh, "node",
                        (wspec, vspec), h=params.exchange_h,
                    )
                else:
                    # rolls as explicit row gathers with precomputed index vectors:
                    # jnp.roll with a traced shift lowers to a slice-select chain that
                    # XLA re-derives PER CONSUMING ELEMENT when fused downstream
                    # (measured as the dominant cost of the tick); a gather through a
                    # materialized [N] index vector is one address lookup per element
                    # and fuses cheaply.  Same values: out[i] = in[(i - s) mod n].
                    idx_fwd = jnp.mod(i_all - shift, n)  # roll by +shift
                    inbound_w = sent_w[idx_fwd]
                    got_pinged = delivered[idx_fwd]
                learned1_w = state.learned | inbound_w
                answerable_w = learned1_w & ride_ok_w & active_w[None, :]
                if use_sm:
                    (resp_src,) = shard_roll(
                        (answerable_w,), n - shift, emesh, "node", (wspec,),
                        h=params.exchange_h,
                    )
                else:
                    idx_back = jnp.mod(i_all + shift, n)  # roll by -shift
                    resp_src = answerable_w[idx_back]
                resp_w = resp_src & dmask
                learned2_w = learned1_w | resp_w
        else:
            ride_ok_b = state.pcount < maxp
            riding_b = learned0_b & active[None, :] & ride_ok_b
            sent_b = riding_b & delivered[:, None]
            inbound_b = jax.ops.segment_max(sent_b, targets, num_segments=n)
            got_pinged = (
                jax.ops.segment_max(delivered.astype(jnp.int8), targets, num_segments=n) > 0
            )
            learned1_b = learned0_b | inbound_b
            answerable_b = learned1_b & active[None, :] & ride_ok_b
            resp_b = answerable_b[targets] & delivered[:, None]
            learned2_b = learned1_b | resp_b
            learned2_w = pack_bool(learned2_b)

    with jax.named_scope("heal"):
        # -- partition healer (heal_via_discover_provider.go, heal_partition.go):
        # a discovery provider knows every address, so the heal channel ignores
        # belief gating.  One probabilistic attempt per tick: a random connected
        # pair swaps its full rumor set (the join + membership-merge of
        # AttemptHeal); detractions thereby reach their subjects, whose
        # refutations re-establish cross-partition liveness.
        if params.heal_prob > 0:
            if use_counter:
                h = _prng.draw_randint(cseed, ctick, _prng.D_HEAL_A, 0, 0, n)
                p = _prng.draw_randint(cseed, ctick, _prng.D_HEAL_B, 0, 0, n)
                heal_u = _prng.draw_uniform(cseed, ctick, _prng.D_HEAL_U, 0)
            else:
                kh1, kh2, kh3 = jax.random.split(k_heal, 3)
                h = jax.random.randint(kh1, (), 0, n, dtype=jnp.int32)
                p = jax.random.randint(kh2, (), 0, n, dtype=jnp.int32)
                heal_u = jax.random.uniform(kh3, ())
            attempt = (
                (heal_u < params.heal_prob)
                & (h != p)
                & up[h]
                & up[p]
                & _pair_connected(faults, h[None], p[None])[0]
            )
            # row reads via the two-level block pick (_gather_rows): a direct
            # plane[h] at a traced index is a gather the SPMD partitioner can
            # only serve by all-gathering the whole packed plane
            heal_rows2 = jnp.stack([h, p])  # int32[2]
            rows_hp = _gather_rows(learned2_w, heal_rows2)  # [2, W]
            merged_row = (rows_hp[0] | rows_hp[1]) & active_w  # [W]
            # apply the pair swap as a 2-row SCATTER, not dynamic_update_slices
            # or a plane-wide select: a DUS whose operand is a fused producer
            # makes XLA:CPU emit a full-plane copy fusion whose body RE-DERIVES
            # the whole upstream chain per element (the round-4 HLO dump showed
            # two 256 MB pcount copies with 153/120-op bodies — the dominant
            # cost of the tick), and a where() against a thin row mask just
            # fuses the same chain back into the big pass (measured 3.0 s/tick).
            # A scatter is not elementwise, so XLA wraps it instead of fusing:
            # the producer materializes once with a thin body and the 2-row
            # update is O(2·K), in-place when the input buffer is dead.
            learned2h_w = learned2_w.at[heal_rows2].set(
                jnp.where(attempt, merged_row[None, :], rows_hp)
            )
            merged_bits = unpack_bits(merged_row, k)  # [K]
        else:
            learned2h_w = learned2_w

    with jax.named_scope("piggyback-counters"):
        # -- pcount pass A: bump + newly-learned + heal resets ------------------
        # (the unpacks fuse into this int8 pass; with gather-based rolls their
        # producer chains are one lookup per element, so the fusion stays thin)
        if shift_mode:
            # bump = sent + (riding & got_pinged) = riding * (delivered + got):
            # one packed-plane bit factor + per-row scalars (same restructure
            # as delta.step — the sent plane's gather chain never has to be
            # re-derived inside the int8 pass)
            bump = unpack_bits(riding_w, k).astype(jnp.int8) * (
                delivered.astype(jnp.int8) + got_pinged.astype(jnp.int8)
            )[:, None]
            newly_bit = unpack_bits(learned2_w & ~state.learned, k)
        else:
            bump = sent_b.astype(jnp.int8) + (riding_b & got_pinged[:, None]).astype(
                jnp.int8
            )
            newly_bit = learned2_b & ~learned0_b
        pcount_a = jnp.minimum(state.pcount + bump, maxp)
        pcount_a = jnp.where(newly_bit, jnp.int8(0), pcount_a)
        if params.heal_prob > 0:
            # heal resets (a join transfer restarts dissemination of everything
            # it carried) as the same 2-row scatter shape as the learned-plane
            # swap above — pass A materializes once with a thin body and the
            # row writes are O(2·K); commutes with newly_bit's reset — both
            # write zero
            pcount_a = pcount_a.at[heal_rows2].set(
                jnp.where(
                    attempt & merged_bits[None, :],
                    jnp.int8(0),
                    _gather_rows(pcount_a, heal_rows2),
                )
            )

        # full-sync analog: re-seed rumors that expired short of full coverage
        up_mask = row_mask(up)
        mid_ride_w = pack_bool(pcount_a < maxp)  # reused for the carried gate below
        riding_now_w = learned2h_w & mid_ride_w & active_w[None, :] & up_mask
        fully_learned = unpack_bits(and_reduce_rows(learned2h_w | row_mask(~up)), k) & active
        has_live_learner = unpack_bits(or_reduce_rows(learned2h_w & up_mask), k)
        stuck = active & ~unpack_bits(or_reduce_rows(riding_now_w), k) & ~fully_learned

        state = state._replace(learned=learned2h_w, pcount=pcount_a)

    with jax.named_scope("timers-fold"):
        # -- timers fire: slot rumors (state_transitions.go:90-117) -------------
        due = active & (state.tick >= state.r_deadline)
        dominant = rkey >= eff_max[jnp.clip(subj, 0, n - 1)]
        fire = due & dominant
        fire_subj = jnp.clip(subj, 0, n - 1)
        # a transition can only fire where some live node can seed the successor
        # rumor (has_live_learner, from the packed OR-reduce above); otherwise
        # the deadline persists and the slot is reclaimed below
        fire_s = fire & (state.r_status == SUSPECT) & has_live_learner
        fire_f = fire & (state.r_status == FAULTY) & has_live_learner
        # eviction additionally waits for the tombstone to be fully disseminated
        # (per-view eviction in the reference only completes once every node has
        # learned it); an undisseminated tombstone's deadline simply refires
        fire_t = fire & (state.r_status == TOMBSTONE) & fully_learned
        slot_next = jnp.where(fire_s, jnp.int8(FAULTY), jnp.int8(TOMBSTONE))
        slot_cand = jnp.where(
            fire_s | fire_f, _key_of(state.r_inc, slot_next), jnp.int32(-1)
        )
        fire_key = jnp.maximum(
            jax.ops.segment_max(slot_cand, subj, num_segments=n + 1)[:n], jnp.int32(-1)
        )
        # seed for a fired transition: first live node that learned the rumor.
        # The per-slot argmax over N is the single most expensive reduce in the
        # tick (strided over the packed plane), and its result only matters on
        # ticks where a suspect/faulty timer actually fired — so it runs under
        # a cond (value-identical: when nothing fired, seed_node is -1 and the
        # zeros never flow anywhere)
        def _first_live_learner(_):
            lb = unpack_bits(state.learned, k) & up[:, None]
            return jnp.argmax(lb, axis=0).astype(jnp.int32)

        slot_seed = jax.lax.cond(
            (fire_s | fire_f).any(),
            _first_live_learner,
            lambda _: jnp.zeros((k,), jnp.int32),
            None,
        )
        seed_node = jnp.maximum(
            jax.ops.segment_max(
                jnp.where(fire_s | fire_f, slot_seed, jnp.int32(-1)), subj, num_segments=n + 1
            )[:n],
            jnp.int32(-1),
        )
        # deadlines are NOT cleared here: a fired transition's deadline survives
        # until its successor rumor actually allocates (deferred clear below), so
        # K-slot saturation only delays the transition instead of dropping it
        r_deadline = state.r_deadline

        # dominated base timers cancel; due+dominant base timers fire
        bdue = (state.base_pending >= 0) & (state.tick >= state.base_deadline) & state.base_present
        bdom = base_key >= subj_rumor_max
        bfire = bdue & bdom
        base_pending = jnp.where(bdue & ~bdom, jnp.int8(-1), state.base_pending)
        bfire_s = bfire & (state.base_pending == SUSPECT)
        bfire_f = bfire & (state.base_pending == FAULTY)
        bfire_t = bfire & (state.base_pending == TOMBSTONE)
        # (skip the argmax when no fault model: XLA constant-folds it slowly)
        first_live = jnp.argmax(up).astype(jnp.int32) if faults.up is not None else jnp.int32(0)
        bfire_key = jnp.where(
            bfire_s | bfire_f,
            _key_of(state.base_inc, jnp.where(bfire_s, jnp.int8(FAULTY), jnp.int8(TOMBSTONE))),
            jnp.int32(-1),
        )
        # seed at whichever candidate won the key merge: slot-fired rumors keep
        # their first live learner; base-fired transitions (no learner set) seed
        # at the first live node.  Ties keep the slot's learner.
        seed_node = jnp.where(bfire_key > fire_key, first_live, seed_node)
        fire_key = jnp.maximum(fire_key, bfire_key)

        # -- evictions (tombstone timer expired; memberlist.Evict analog) -------
        evicted = jnp.zeros((n,), bool).at[jnp.clip(subj, 0, n - 1)].max(fire_t) | bfire_t
        base_present = state.base_present & ~evicted
        freed_by_evict = active & evicted[jnp.clip(subj, 0, n - 1)]

        # -- fold fully-learned dominant rumors into the base -------------------
        foldable = fully_learned & (rkey >= eff_max[jnp.clip(subj, 0, n - 1)]) & ~freed_by_evict
        folded_key = jnp.maximum(
            jax.ops.segment_max(jnp.where(foldable, rkey, jnp.int32(-1)), subj, num_segments=n + 1)[:n],
            jnp.int32(-1),
        )
        fold_mask = folded_key >= 0
        base_status = jnp.where(fold_mask, _status_of(jnp.maximum(folded_key, 0)), state.base_status)
        base_inc = jnp.where(fold_mask, _inc_of(jnp.maximum(folded_key, 0)), state.base_inc)
        # folding any rumor (re-)establishes the subject in the base — this is
        # how an admitted/rejoining member becomes part of the converged view
        base_present = base_present | fold_mask
        # transfer the folded rumor's pending deadline to the base timer
        fold_dl = jax.ops.segment_min(
            jnp.where(
                foldable & (rkey == folded_key[jnp.clip(subj, 0, n - 1)]),
                r_deadline,
                NO_DEADLINE,
            ),
            subj,
            num_segments=n + 1,
        )[:n]
        base_pending = jnp.where(
            fold_mask,
            jnp.where(fold_dl < NO_DEADLINE, _status_of(jnp.maximum(folded_key, 0)), jnp.int8(-1)),
            base_pending,
        )
        base_deadline = jnp.where(fold_mask, fold_dl, state.base_deadline)
        # free every slot of a folded subject (all are dominated by the base
        # now), plus dead rumors whose only learners have crashed — freeing them
        # drops eff_max so a live prober can re-declare from scratch
        freed = (
            freed_by_evict
            | (active & fold_mask[jnp.clip(subj, 0, n - 1)])
            | (active & ~has_live_learner)
        )
        r_subject = jnp.where(freed, jnp.int32(-1), state.r_subject)
        learned3_w = state.learned & ~pack_bool(freed)[None, :]
        active = r_subject >= 0
        base_key = jnp.where(base_present, _key_of(base_inc, base_status), jnp.int32(-1))
        subj = jnp.where(active, r_subject, jnp.int32(n))
        subj_rumor_max = jnp.maximum(
            jax.ops.segment_max(
                jnp.where(active, _key_of(state.r_inc, state.r_status), jnp.int32(-1)),
                subj,
                num_segments=n + 1,
            )[:n],
            jnp.int32(-1),
        )
        eff_max = jnp.maximum(subj_rumor_max, base_key)

    with jax.named_scope("peer-choice"):
        # -- the [N, P] indirect-probe draws, in their own phase scope so the
        # collective census can see them in isolation: under rng="threefry"
        # this is the non-partitionable draw that materializes replicated
        # (~12 MB/chip/tick at 1M) AND generates different lanes sharded vs
        # unsharded; under rng="counter" it is elementwise in (node, column)
        # and the phase carries ZERO cross-chip collectives
        # (tests/test_mesh_budget.py asserts exactly that)
        if use_counter:
            if params.ping_req_size >= _prng.D_COLUMN_SPAN:
                raise ValueError(
                    f"ping_req_size={params.ping_req_size} overflows the "
                    f"counter RNG's per-site column span "
                    f"({_prng.D_COLUMN_SPAN}): column draws would collide "
                    "with the next draw site's stream (sim/prng.py)"
                )
            pcols = jnp.arange(params.ping_req_size, dtype=jnp.int32)[None, :]
            peer_choices = _prng.draw_randint(
                cseed, ctick, _prng.D_PEER + pcols, i_all[:, None], 0, n
            )
            if _has_drop(faults):
                pd_req_u = _prng.draw_uniform(
                    cseed, ctick, _prng.D_PEER_DROP_REQ + pcols, i_all[:, None]
                )
                pd_ack_u = _prng.draw_uniform(
                    cseed, ctick, _prng.D_PEER_DROP_ACK + pcols, i_all[:, None]
                )
            if has_topo:
                topo_req_u = _prng.draw_uniform(
                    cseed, ctick, _prng.D_TOPO_PEER_REQ + pcols, i_all[:, None]
                )
                topo_ack_u = _prng.draw_uniform(
                    cseed, ctick, _prng.D_TOPO_PEER_ACK + pcols, i_all[:, None]
                )
        else:
            k_peers, k_pd1, k_pd2 = jax.random.split(k_peers, 3)
            peer_choices = jax.random.randint(
                k_peers, (n, params.ping_req_size), 0, n, dtype=jnp.int32
            )
            if _has_drop(faults):
                pd_req_u = jax.random.uniform(k_pd1, peer_choices.shape)
                pd_ack_u = jax.random.uniform(k_pd2, peer_choices.shape)

    with jax.named_scope("candidate-select"):
        # -- refutation candidates (memberlist.go:337-354) ----------------------
        # only (node == slot subject) pairs can self-detect a detraction, so
        # the dense [N, K] mask collapses to K bit-gathers + one scatter-OR
        # (identical values to the original any-reduce)
        subj_c = jnp.clip(subj, 0, n - 1)
        own_bit = bit_column(learned3_w[subj_c], jnp.arange(k))
        slot_self_detract = (
            active
            & own_bit
            & _is_detraction(state.r_status)
            & (state.r_inc >= state.self_inc[subj_c])
        )
        self_detract = (
            jnp.zeros((n,), bool)
            .at[jnp.where(active, subj, jnp.int32(n))]
            .max(slot_self_detract, mode="drop")
        )
        base_detract = (
            _is_detraction(base_status) & (base_inc >= state.self_inc) & base_present
        )
        refute = up & (self_detract | base_detract)
        refute_key = jnp.where(refute, _key_of(now, jnp.int8(ALIVE)), jnp.int32(-1))

        # -- failed probe → indirect probes → Suspect (node.go:494-510) ---------
        probing = wants & ~conn
        i_bcast = jnp.broadcast_to(i_all[:, None], peer_choices.shape)
        peer_ok = (
            _pair_connected(faults, i_bcast, peer_choices)
            & (peer_choices != i_bcast)
            & (peer_choices != targets[:, None])
        )
        targets_b = jnp.broadcast_to(targets[:, None], peer_choices.shape)
        peer_reaches = (
            peer_ok
            & _pair_connected(faults, peer_choices, targets_b)
            & up[targets][:, None]
        )
        # each indirect leg is its own RPC and suffers packet loss too
        if _has_drop(faults):
            peer_ok &= _leg_survives(faults, pd_req_u, i_bcast, peer_choices)
            peer_reaches &= peer_ok & _leg_survives(
                faults, pd_ack_u, peer_choices, targets_b
            )
        if has_topo:
            # the indirect legs cross tier boundaries of their own: the
            # (i → peer) and (peer → target) hops each pay the tier table
            peer_ok &= topo_req_u >= _tier_pair_drop(faults, i_bcast, peer_choices)
            peer_reaches &= peer_ok & (
                topo_ack_u >= _tier_pair_drop(faults, peer_choices, targets_b)
            )
        reached = peer_reaches.any(axis=1)
        inconclusive = (~peer_ok).all(axis=1)
        declare = probing & ~reached & ~inconclusive
        susp_cand = jnp.where(
            declare, _key_of(_inc_of(jnp.maximum(bel, 0)), jnp.int8(SUSPECT)), jnp.int32(-1)
        )
        susp_key = jnp.maximum(
            jax.ops.segment_max(
                susp_cand, jnp.where(declare, targets, jnp.int32(n)), num_segments=n + 1
            )[:n],
            jnp.int32(-1),
        )
        susp_key = jnp.where(susp_key > eff_max, susp_key, jnp.int32(-1))

        # -- merge per-subject candidates & allocate into free slots ------------
        cand = jnp.maximum(jnp.maximum(refute_key, susp_key), fire_key)
        cand_vals, cand_subj = _top_m_sparse(cand, m)
        free_vals, free_slots = jax.lax.top_k((~active).astype(jnp.int32), m)
    with jax.named_scope("alloc-seed"):
        place = (cand_vals >= 0) & (free_vals == 1)

        new_status = _status_of(jnp.maximum(cand_vals, 0))
        new_inc = _inc_of(jnp.maximum(cand_vals, 0))
        new_dl = jnp.where(
            new_status == SUSPECT,
            state.tick + susp_ticks,
            jnp.where(
                new_status == FAULTY,
                state.tick + params.faulty_ticks,
                jnp.where(new_status == TOMBSTONE, state.tick + params.tombstone_ticks, NO_DEADLINE),
            ),
        )
        r_subject = r_subject.at[free_slots].set(jnp.where(place, cand_subj, r_subject[free_slots]))
        r_inc = state.r_inc.at[free_slots].set(jnp.where(place, new_inc, state.r_inc[free_slots]))
        r_status = state.r_status.at[free_slots].set(
            jnp.where(place, new_status, state.r_status[free_slots])
        )
        r_deadline = r_deadline.at[free_slots].set(jnp.where(place, new_dl, r_deadline[free_slots]))

        # fresh slots start unlearned, then get seeded
        placed_col = jnp.zeros((k,), bool).at[free_slots].set(place)
        learned4_w = learned3_w & ~pack_bool(placed_col)[None, :]

        # seed row per placed candidate: refute → the subject itself; timer
        # transition → first live learner of the precursor rumor.  Fresh suspect
        # rumors are seeded by their declarers below, not here.
        seed_rows = jnp.where(new_status == ALIVE, cand_subj, seed_node[cand_subj])
        seed_ok = place & (new_status != SUSPECT) & (seed_rows >= 0)
        learned5_w = set_bit(
            learned4_w, jnp.clip(seed_rows, 0, n - 1), free_slots, seed_ok
        )
        # suspect rumors: every declarer that targeted the subject seeds it
        subj_to_slot = jnp.full((n,), -1, jnp.int32).at[cand_subj].set(
            jnp.where(place & (new_status == SUSPECT), free_slots, jnp.int32(-1))
        )
        decl_slot = subj_to_slot[targets]
        decl_ok = declare & (decl_slot >= 0)
        # every-row seeding (rows == iota): the elementwise one-hot form — a
        # scatter here made the partitioner all-gather [N]-sized index/update
        # tensors (see packbits.set_bit_per_row)
        learned6_w = set_bit_per_row(learned5_w, jnp.clip(decl_slot, 0, k - 1), decl_ok)

    with jax.named_scope("piggyback-counters"):
        # -- pcount pass B: the deferred stuck/freed/placed clears (one fused
        # read/write; all resets-to-zero commute with pass A's) ----------------
        pcount_final = jnp.where(
            (freed | placed_col)[None, :]
            | (stuck[None, :] & unpack_bits(learned2h_w, k)),
            jnp.int8(0),
            pcount_a,
        )
        # maintain the carried gate invariant ride_ok == pack(pcount < maxp):
        # a reset-to-zero opens the gate iff maxp > 0 (degenerate max_p=0
        # configs never ride)
        reset_w = (
            pack_bool(freed | placed_col)[None, :]
            | (pack_bool(stuck)[None, :] & learned2h_w)
        ) & jnp.where(maxp > 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
        ride_next = mid_ride_w | reset_w

    with jax.named_scope("commit"):
        # refutation bumps the refuter's own incarnation (iff its rumor placed)
        placed_subject = jnp.zeros((n,), bool).at[cand_subj].max(place & (new_status == ALIVE))
        self_inc = jnp.where(refute & placed_subject, now, state.self_inc)

        # deferred timer clears: a fired suspect/faulty timer only retires once a
        # rumor at least as strong as its successor was actually allocated for
        # its subject (otherwise it refires next tick and retries)
        placed_key = jnp.full((n,), -1, jnp.int32).at[cand_subj].set(
            jnp.where(place, cand_vals, jnp.int32(-1))
        )
        slot_fired_ok = (
            (fire_s | fire_f) & (placed_key[fire_subj] >= slot_cand) & ~placed_col
        )
        r_deadline = jnp.where(slot_fired_ok, NO_DEADLINE, r_deadline)
        base_fired_ok = (
            (bfire_s | bfire_f) & (bfire_key >= 0) & (placed_key >= bfire_key)
        ) | bfire_t
        base_pending = jnp.where(base_fired_ok, jnp.int8(-1), base_pending)

    new_state = LifecycleState(
        r_subject=r_subject,
        r_inc=r_inc,
        r_status=r_status,
        r_deadline=r_deadline,
        learned=learned6_w,
        pcount=pcount_final,
        ride_ok=ride_next,
        base_status=base_status,
        base_inc=base_inc,
        base_present=base_present,
        base_pending=base_pending,
        base_deadline=base_deadline,
        self_inc=self_inc,
        tick=state.tick + 1,
        key=key,
    )
    if telemetry is None:
        return new_state

    # -- telemetry: pure reductions over intermediates the tick already
    # materialized — nothing above this point changes, so telemetry-on is
    # bit-identical to telemetry-off by construction (certified by
    # tests/test_telemetry.py and the make telemetry-smoke pairing)
    with jax.named_scope("telemetry"):
        from ringpop_tpu.sim import telemetry as _tm

        if shift_mode:
            t_sent_w, t_resp_w = sent_w, resp_w
        else:
            t_sent_w, t_resp_w = pack_bool(sent_b), pack_bool(resp_b)
        # per-tier suspicion flow (armed via telemetry.zeros(tiers=True)
        # and a topology-carrying plan): the tier of each (accuser →
        # target) pair and the plan's ground-truth liveness of the
        # target — both read off intermediates the tick already has, so
        # telemetry-on stays bit-identical to off
        declared = declared_tier = declared_up = None
        if telemetry.suspects_by_tier is not None and has_topo:
            declared = decl_ok
            declared_tier = _tier_pair(faults, i_all, targets)
            declared_up = up[targets]
        telemetry = _tm.accumulate(
            telemetry,
            declared=declared,
            declared_tier=declared_tier,
            declared_up=declared_up,
            delivered=delivered,
            probing=probing,
            ping_req_legs=jnp.where(
                probing, peer_ok.sum(axis=1, dtype=jnp.int32), jnp.int32(0)
            ),
            refuted=refute & placed_subject,
            sent_w=t_sent_w,
            resp_w=t_resp_w,
            # ride gates that closed this tick (piggyback budget exhausted);
            # state.ride_ok is still the tick-entry gate — the _replace
            # above only swapped learned/pcount
            closed_w=state.ride_ok & ~mid_ride_w,
            # count timers at RETIREMENT, not firing: a fired timer that
            # couldn't place its successor (K-slot/alloc saturation, or a
            # tombstone short of full dissemination) refires every tick
            # until it lands, and counting raw fires would journal one
            # logical transition dozens of times — the host plane counts
            # each transition once
            fired=slot_fired_ok | fire_t,
            base_fired=base_fired_ok,
            place=place,
            new_status=new_status,
            heal_attempt=attempt if params.heal_prob > 0 else None,
        )
    return new_state, telemetry


def state_shardings(mesh, k: Optional[int] = None) -> LifecycleState:
    """The canonical LifecycleState sharding over a ("node", "rumor")
    mesh: per-node vectors on the node axis, the rumor table on the rumor
    axis, the big planes on both — ``learned``'s rumor axis is WORDS
    (uint32 packs 32 slots), so k must be a multiple of 32 * rumor-shards
    (pass ``k`` to validate against the mesh up front; see
    ``packbits.check_rumor_shardable``).  One definition shared by the
    driver entry (``__graft_entry__``), the sharded-at-scale bench
    (``cli/simbench bench_sharded100k``), and the sharding tests — a
    layout change edits exactly this function."""
    from ringpop_tpu.parallel.partition import named_shardings

    if k is not None:
        check_rumor_shardable(k, mesh.shape.get("rumor", 1))

    # derived from the ONE canonical per-leaf rule table
    # (parallel.partition.PARTITION_RULES) — this wrapper only fixes the
    # pytree type and validates k against the mesh
    skeleton = LifecycleState(**{f: 0 for f in LifecycleState._fields})
    return named_shardings(skeleton, mesh)


# -- membership operations ---------------------------------------------------


def admit(params: LifecycleParams, state: LifecycleState, idx: int) -> LifecycleState:
    """Admit (or re-admit) node ``idx`` into the cluster — the sim analog of
    the join path (``swim/join_sender.go``): the joiner announces itself
    with an Alive rumor at a fresh incarnation, seeded only at itself; the
    rumor gossips outward, peers start pinging the member as they learn of
    it, and once fully disseminated it folds into the converged base
    (restoring ``base_present`` for an evicted index).  Raises if the rumor
    table is full."""
    free = np.flatnonzero(~np.asarray(state.r_subject >= 0))
    if free.size == 0:
        raise RuntimeError("rumor table full; cannot admit now")
    k0 = int(free[0])
    now = jnp.int32(int(state.tick) + 1)
    n = params.n
    w0, bitv = k0 >> 5, jnp.uint32(1 << (k0 & 31))
    col = (state.learned[:, w0] & ~bitv) | jnp.where(
        jnp.arange(n) == idx, bitv, jnp.uint32(0)
    )
    # slot k0's counters reset to 0, so its carried ride gate opens
    # (invariant ride_ok == pack(pcount < maxp); maxp >= 1 except the
    # degenerate max_p=0 override, where nothing ever rides)
    maxp = clamped_max_p(params)
    ride_col = (
        (state.ride_ok[:, w0] | bitv) if maxp > 0 else (state.ride_ok[:, w0] & ~bitv)
    )
    return state._replace(
        r_subject=state.r_subject.at[k0].set(idx),
        r_inc=state.r_inc.at[k0].set(now),
        r_status=state.r_status.at[k0].set(ALIVE),
        r_deadline=state.r_deadline.at[k0].set(NO_DEADLINE),
        learned=state.learned.at[:, w0].set(col),
        pcount=state.pcount.at[:, k0].set(jnp.int8(0)),
        ride_ok=state.ride_ok.at[:, w0].set(ride_col),
        self_inc=state.self_inc.at[idx].set(now),
    )


# -- queries ----------------------------------------------------------------


def believed_key(state: LifecycleState, subjects) -> jax.Array:
    """int32[N, S]: node i's belief key about each subject (-1 = not
    present).  O(N·K·S) — intended for small subject lists."""
    subjects = jnp.asarray(subjects, jnp.int32)
    k = state.r_subject.shape[0]
    active = state.r_subject >= 0
    rkey = jnp.where(active, _key_of(state.r_inc, state.r_status), jnp.int32(-1))
    sel = active[:, None] & (state.r_subject[:, None] == subjects[None, :])  # [K, S]
    per_rumor = jnp.where(sel[None, :, :], rkey[None, :, None], jnp.int32(-1))  # [1,K,S]
    bel_rumor = jnp.max(
        jnp.where(unpack_bits(state.learned, k)[:, :, None], per_rumor, jnp.int32(-1)),
        axis=1,
        initial=jnp.int32(-1),
    )  # [N, S]
    base_key = jnp.where(
        state.base_present, _key_of(state.base_inc, state.base_status), jnp.int32(-1)
    )
    return jnp.maximum(bel_rumor, base_key[subjects][None, :])


def believed_status(state: LifecycleState, subjects) -> jax.Array:
    """int8[N, S]: belief status; -1 where the subject is absent."""
    bk = believed_key(state, subjects)
    return jnp.where(bk >= 0, _status_of(jnp.maximum(bk, 0)), jnp.int8(-1))


def detection_fraction(
    state: LifecycleState,
    subjects,
    faults: DeltaFaults = DeltaFaults(),
    min_status: int = FAULTY,
) -> jax.Array:
    """float[S]: fraction of live observers whose belief about each subject
    has reached ``min_status`` (or the subject is evicted).

    Dispatches on problem size: the vectorized small path materializes
    O(N·K·S); past ~2^28 elements the slot-walk path computes the same
    per-observer first-learned-wins semantics from [N]-column ops (a 1M x
    128 x 1000 query goes from ~500 GB of intermediates to ~2k column
    reductions)."""
    faults = _resolve_faults(faults, state.tick)
    if state.learned.shape[0] * state.r_subject.shape[0] * len(subjects) > 2**28:
        return _detection_fraction_large(state, subjects, faults, min_status)
    subjects = jnp.asarray(subjects, jnp.int32)
    bk = believed_key(state, subjects)
    detected = (bk < 0) | (_status_of(jnp.maximum(bk, 0)) >= min_status)
    up = faults.up if faults.up is not None else jnp.ones(state.learned.shape[0], bool)
    is_subject = jnp.zeros_like(up).at[subjects].set(True)
    observer = up & ~is_subject
    num = (detected & observer[:, None]).sum(axis=0)
    return num / jnp.maximum(observer.sum(), 1)


def _detection_fraction_large(
    state: LifecycleState,
    subjects,
    faults: DeltaFaults = DeltaFaults(),
    min_status: int = FAULTY,
) -> jax.Array:
    """Exact large-scale detection_fraction.

    Per observer, belief about subject ``s`` is governed by the highest-key
    source it knows: walk s's rumor slots in descending key order, counting
    observers whose FIRST learned slot is each one (prefix exclusion over
    [N] boolean columns); observers that learned none fall through to the
    base.  Rumor/base metadata is [K]/scalars — only [N]-sized column ops
    touch the device."""
    n = state.learned.shape[0]
    subjects_np = np.asarray(subjects, np.int64)
    r_subject = np.asarray(state.r_subject)
    r_key = (np.asarray(state.r_inc, np.int64) << KEY_STATE_BITS) | np.asarray(
        state.r_status, np.int64
    )
    active = r_subject >= 0
    base_present = np.asarray(state.base_present)[subjects_np]
    base_key = (np.asarray(state.base_inc, np.int64)[subjects_np] << KEY_STATE_BITS) | np.asarray(
        state.base_status, np.int64
    )[subjects_np]
    base_status = np.asarray(state.base_status)[subjects_np]

    up = faults.up if faults.up is not None else jnp.ones(n, bool)
    is_subject = jnp.zeros(n, bool).at[jnp.asarray(subjects_np)].set(True)
    obs = up & ~is_subject
    obs_total = int(obs.sum())
    frac = np.zeros(len(subjects_np), np.float64)
    for si, s in enumerate(subjects_np):
        slots = np.flatnonzero(active & (r_subject == s))
        order = slots[np.argsort(-r_key[slots], kind="stable")]
        remaining = obs  # observers not yet governed by a higher-key rumor
        count = 0
        for slot in order:
            if base_present[si] and base_key[si] >= r_key[slot]:
                break  # base outranks this and all lower slots for everyone
            col = ((state.learned[:, int(slot) >> 5] >> jnp.uint32(slot & 31)) & 1) != 0
            got = remaining & col
            if int(r_key[slot] & (2**KEY_STATE_BITS - 1)) >= min_status:
                count += int(got.sum())
            remaining = remaining & ~col
        # fall-through: governed by the base (absent subject counts as
        # detected — the eviction end state)
        if (not base_present[si]) or int(base_status[si]) >= min_status:
            count += int(remaining.sum())
        frac[si] = count / max(obs_total, 1)
    return jnp.asarray(frac)


def detection_complete(
    state: LifecycleState,
    subjects,
    faults: DeltaFaults = DeltaFaults(),
    min_status: int = FAULTY,
    *,
    learned_sharding=None,
) -> jax.Array:
    """bool scalar, fully ON-DEVICE: does every live observer believe every
    subject has reached ``min_status`` (or see it evicted)?

    Same predicate as ``(detection_fraction(...) >= 1).all()`` — including
    "no live observers → not complete" (the fraction is 0/1 there) — but
    jittable and O(N·K): belief is a lattice max (``believed_key``) and a
    key encodes its status in the low ``KEY_STATE_BITS``, so the governing
    belief is just the max key and its status is read straight off it.  The
    check walks the K rumor slots sorted by (subject, key desc),
    accumulating each observer's max learned key per subject and reducing
    at subject boundaries — never materializing [N, S].

    This is what lets ``run_until_detected`` run its convergence test inside
    the jitted loop: round-1 profiling showed the 1M-node TPU bench spending
    ~90% of wall-clock in the HOST-side per-subject detection walk between
    device blocks (~2k tunnel dispatches per check at S=1000).

    ``learned_sharding`` (optional, a ``NamedSharding`` like
    ``P("node", None)`` over the run's mesh): pre-replicate the packed
    ``learned`` plane across the rumor axis before the K-iteration slot
    walk — one all-gather per check instead of ~6 collectives per walk
    iteration (see :func:`_walk_subject_slots`).  Purely a layout hint;
    values are bit-identical with or without it.
    """
    faults = _resolve_faults(faults, state.tick)
    with jax.named_scope("detect-walk"):
        n, _ = state.learned.shape
        subjects = jnp.asarray(subjects, jnp.int32)

        base_bad = state.base_present & (state.base_status < min_status)  # [N]
        base_key = jnp.where(
            state.base_present, _key_of(state.base_inc, state.base_status), jnp.int32(-1)
        )  # [N], indexed by subject id

        up = faults.up if faults.up is not None else jnp.ones(n, bool)
        is_subject = jnp.zeros(n, bool).at[subjects].set(True)
        obs = up & ~is_subject
        has_obs = obs.any()

        def finalize(anybad, s, m, fin):
            bad_any = (obs & (m >= 0) & (_status_of(jnp.maximum(m, 0)) < min_status)).any()
            return anybad.at[jnp.where(fin, s, n)].set(
                jnp.where(fin, bad_any, False), mode="drop"
            )

        anybad = _walk_subject_slots(
            state, base_key, jnp.zeros(n, bool), finalize,
            learned_sharding=learned_sharding,
        )
        not_detected = jnp.where(
            _slot_covered(state), anybad, base_bad
        )[subjects]
        return has_obs & ~not_detected.any()


def _slot_covered(state: LifecycleState) -> jax.Array:
    """bool[N]: which subject ids have at least one in-flight rumor slot."""
    n = state.learned.shape[0]
    active = state.r_subject >= 0
    return jnp.zeros(n, bool).at[
        jnp.where(active, state.r_subject, n)
    ].set(True, mode="drop")


def _walk_subject_slots(state: LifecycleState, base_key, carry0, finalize,
                        learned_sharding=None):
    """The shared O(N·K) per-subject slot walk under ``detection_complete``
    and ``view_checksums``: iterate the K rumor slots sorted by (subject
    asc, key desc) — free slots pushed past the end; the lexsort is
    int32-safe because rkey >= -1 so -rkey can't wrap — maintaining each
    node's max learned key ``best``; at every step call ``finalize(carry,
    s, m, fin)`` where ``m[N] = max(best, base_key[s])`` is the per-node
    governing key for clamped subject id ``s`` and ``fin`` marks the
    subject's last slot (callbacks must gate their update on ``fin``).
    Returns the final carry.  Subjects with no in-flight slot never reach
    ``finalize`` — callers handle them via :func:`_slot_covered`.

    ``learned_sharding`` (a ``NamedSharding`` replicating the packed
    plane's rumor/word axis, e.g. ``P("node", None)``): under a device
    mesh, the loop body's per-iteration ``bit_column`` gather at a traced
    word index cannot stay shard-local along a sharded rumor axis — the
    partitioner emitted ~6 collectives PER ITERATION (~1,536 sequential
    tiny collectives per check at K=256; PERF.md "Why the sharded detect
    path is slow").  The constraint pre-replicates ``learned`` across the
    rumor shards ONCE (an all-gather of packed-plane-bytes ÷ rumor-shards)
    and pins the [K] walk metadata + ``base_key`` replicated, so every
    iteration's gathers are local and only ``finalize``'s scalar reduce
    crosses shards.  Pure layout hint — bit-identical values either way."""
    with jax.named_scope("detect-walk"):
        n = state.learned.shape[0]
        k = state.r_subject.shape[0]
        learned = state.learned
        active = state.r_subject >= 0
        rkey = jnp.where(active, _key_of(state.r_inc, state.r_status), jnp.int32(-1))
        subj_or_sentinel = jnp.where(active, state.r_subject, jnp.int32(n))
        order = jnp.lexsort((-rkey, subj_or_sentinel))
        sorted_subj = subj_or_sentinel[order]
        sorted_key = rkey[order]
        is_last = sorted_subj != jnp.concatenate(
            [sorted_subj[1:], jnp.full((1,), n + 1, jnp.int32)]
        )
        if learned_sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            learned = jax.lax.with_sharding_constraint(learned, learned_sharding)
            rep = NamedSharding(learned_sharding.mesh, PartitionSpec())
            order, sorted_subj, sorted_key, is_last, base_key = (
                jax.lax.with_sharding_constraint(x, rep)
                for x in (order, sorted_subj, sorted_key, is_last, base_key)
            )

        def body(j, c):
            best, carry = c
            s = sorted_subj[j]
            valid = s < n
            # slot order[j]'s learned column, extracted from the packed plane
            # (the pre-pack code materialized a [K, N] transpose here)
            lcol = bit_column(learned, order[j])
            best = jnp.where(lcol & valid, jnp.maximum(best, sorted_key[j]), best)
            m = jnp.maximum(best, base_key[jnp.minimum(s, n - 1)])
            fin = is_last[j] & valid
            carry = finalize(carry, jnp.minimum(s, n - 1), m, fin)
            best = jnp.where(fin, jnp.int32(-1), best)
            return best, carry

        best0 = jnp.full(n, -1, jnp.int32)
        _, carry = jax.lax.fori_loop(0, k, body, (best0, carry0))
        return carry


# murmur3 fmix32 (packbits.mix32) — the order-invariant view checksum's
# per-member mixer; see that docstring for the wire-compat caveat
_mix32 = mix32


@jax.jit
def view_checksums(
    state: LifecycleState, faults: DeltaFaults = DeltaFaults()
) -> jax.Array:
    """uint32[N], fully ON-DEVICE: an order-invariant checksum of each
    node's membership view — the sim-plane analog of the reference's
    memberlist checksum (SURVEY §7 hard-part #5: the canonical
    sorted-string farm32 is hostile to TPU, so the sim uses a
    sum-of-mixed-member-hashes that is order-invariant BY CONSTRUCTION
    and needs no sort; the host plane keeps the exact farm32 encoding for
    wire compat, ``swim/memberlist.py``).

    Semantics: node i's view of subject s is ``believed_key`` (lattice
    max of base and learned rumors); its checksum is the wrapping uint32
    sum of ``mix32(mix32(s) ^ governing_key)`` over every subject present
    in its view — tombstoned members excluded exactly as the reference
    excludes them (``memberlist.go:106-128``).  Two nodes agree on their
    views iff their checksums agree (up to hash collision).  Cost is
    O(N·K) via the same sorted slot walk as :func:`detection_complete` —
    subjects with no in-flight rumor contribute one shared scalar term.

    ``faults`` is accepted for signature symmetry with the other queries;
    a node's own checksum is defined whether or not it is up (the
    reference's memberlist exists on a stopped node too).
    """
    with jax.named_scope("view-checksum"):
        n = state.learned.shape[0]
        del faults

        active = state.r_subject >= 0
        rkey = jnp.where(active, _key_of(state.r_inc, state.r_status), jnp.int32(-1))
        base_key = jnp.where(
            state.base_present, _key_of(state.base_inc, state.base_status), jnp.int32(-1)
        )  # [N] indexed by subject id

        def member_term(subject, key):
            """Contribution of (subject, governing key) — zero when absent or
            tombstoned (checksum exclusion per the reference)."""
            include = (key >= 0) & (_status_of(jnp.maximum(key, 0)) != TOMBSTONE)
            h = _mix32(_mix32(subject.astype(jnp.uint32)) ^ key.astype(jnp.uint32))
            return jnp.where(include, h, jnp.uint32(0))

        def finalize(acc, s, m, fin):
            return acc + jnp.where(fin, member_term(s, m), jnp.uint32(0))

        acc = _walk_subject_slots(state, base_key, jnp.zeros(n, jnp.uint32), finalize)

        # subjects with no in-flight rumor are identical in every view: one
        # shared scalar term
        i_all = jnp.arange(n, dtype=jnp.int32)
        base_terms = jnp.where(
            ~_slot_covered(state), member_term(i_all, base_key), jnp.uint32(0)
        )
        return acc + base_terms.sum(dtype=jnp.uint32)


@jax.jit
def checksums_converged(
    state: LifecycleState, faults: DeltaFaults = DeltaFaults()
) -> jax.Array:
    """bool scalar, on-device: do all LIVE nodes' view checksums agree?
    The reference's convergence criterion for protocol tests
    (``swim/test_utils.go:164-199`` ticks until no changes remain and all
    checksums agree)."""
    faults = _resolve_faults(faults, state.tick)
    cs = view_checksums(state, faults)
    up = faults.up if faults.up is not None else jnp.ones(cs.shape[0], bool)
    first_live = jnp.argmax(up)
    return (jnp.where(up, cs, cs[first_live]) == cs[first_live]).all() & up.any()


def _run_block(params: LifecycleParams, state, faults, ticks: int, telemetry=None):
    """``ticks`` steps in one fused loop.  With a telemetry accumulator the
    carry is the (state, telemetry) pair; with None the loop is exactly
    the telemetry-free program (the None leg compiles out)."""
    if telemetry is None:
        return jax.lax.fori_loop(0, ticks, lambda _, s: step(params, s, faults), state)
    return jax.lax.fori_loop(
        0,
        ticks,
        lambda _, c: step(params, c[0], faults, telemetry=c[1]),
        (state, telemetry),
    )


@functools.partial(jax.jit, static_argnames=("params", "block_ticks"))
def _run_until_converged_device(
    params: LifecycleParams,
    state: LifecycleState,
    faults: DeltaFaults,
    *,
    block_ticks: int,
    max_blocks: jax.Array,
    telemetry=None,
):
    """Blocks + convergence test + early exit in one dispatch (the
    lifecycle analog of ``delta._run_until_converged_device``).
    Convergence mirrors the reference's ``waitForConvergence``: NO changes
    remain in flight (no active rumor slots) AND all live checksums agree
    (``swim/test_utils.go:164-199`` — it ticks until the disseminators are
    empty and the checksums match).  Returns (state, blocks_run,
    converged), with the accumulated telemetry appended when a telemetry
    leg rides the carry (None compiles out — same program as before)."""

    def quiescent(c):
        s = c[0]
        return ~(s.r_subject >= 0).any() & checksums_converged(s, faults)

    def run_block(c):
        s, t = c
        out = _run_block(params, s, faults, block_ticks, t)
        return out if t is not None else (out, None)

    (state, telemetry), blocks, done = until_loop(
        run_block, (state, telemetry), max_blocks, quiescent
    )
    if telemetry is None:
        return state, blocks, done
    return state, blocks, done, telemetry


@functools.partial(
    jax.jit,
    static_argnames=("params", "min_status", "block_ticks", "learned_sharding"),
)
def _run_until_detected_device(
    params: LifecycleParams,
    state: LifecycleState,
    faults: DeltaFaults,
    subjects: jax.Array,
    *,
    min_status: int,
    block_ticks: int,
    max_blocks: jax.Array,
    learned_sharding=None,
    telemetry=None,
):
    """Up to ``max_blocks`` blocks of ``block_ticks`` ticks with the
    detection test INSIDE the jitted loop — one dispatch, one readback.
    Returns (state, blocks_run, detected); 0 blocks when the subjects are
    already detected on entry.  ``max_blocks`` is traced (not static) so
    varying final-chunk sizes reuse one compilation.  ``learned_sharding``
    (static; hashable ``NamedSharding``) is the mesh hint forwarded to
    :func:`detection_complete` so the per-check slot walk replicates the
    packed ``learned`` plane across the rumor shards once instead of
    paying ~6 collectives per walk iteration — sharded callers pass
    ``NamedSharding(mesh, P("node", None))``; values are identical with
    or without it."""

    def detected(c):
        return detection_complete(
            c[0], subjects, faults, min_status, learned_sharding=learned_sharding
        )

    def run_block(c):
        s, t = c
        out = _run_block(params, s, faults, block_ticks, t)
        return out if t is not None else (out, None)

    (state, telemetry), blocks, done = until_loop(
        run_block, (state, telemetry), max_blocks, detected
    )
    if telemetry is None:
        return state, blocks, done
    return state, blocks, done, telemetry


class LifecycleSim:
    """Convenience wrapper: jitted step + detection queries.  The jitted
    multi-tick block is cached on the instance (keyed on the static tick
    count; faults flow through as a traced pytree), so repeated run calls
    reuse one compilation.

    ``telemetry``: False/None (default) leaves the hot path untouched —
    the telemetry leg compiles out entirely.  Pass True (or a
    ``telemetry.TelemetrySink``) to carry the device-resident counter
    accumulators through every tick; each ``run``/``run_until_*``
    dispatch then fetches one block record (``sim/telemetry.py``) and —
    when a sink is attached — fans it out to its journal/stats/event-bus
    targets with the block's state digest attached.  ``journal_views=True``
    additionally runs the O(N·K) ``view_checksums`` walk per fetched
    block and journals the wrapped sum + live-agreement bit (pricey at
    1M; meant for the small-config smoke)."""

    def __init__(self, n: int, seed: int = 0, telemetry=None, journal_views: bool = False,
                 aot: Optional[str] = None, telemetry_tiers: bool = False, **kw):
        from ringpop_tpu.sim import telemetry as _tm

        self.params = LifecycleParams(n=n, **kw)
        self.state = init_state(self.params, seed=seed)
        self._step = jax.jit(functools.partial(step, self.params))
        self._block = jax.jit(
            functools.partial(_run_block, self.params), static_argnames="ticks"
        )
        # AOT warm-start (util/aot.py): with a tag, every distinct block
        # program this instance dispatches goes through the load-or-compile
        # front door — serialized on first compile, reloaded warm by the
        # next process.  aot_info collects one front-door record per
        # program (keyed like _aot_calls) for callers that journal them.
        self._aot_tag = aot
        self._aot_calls: dict = {}
        self.aot_info: dict = {}
        self.telemetry = None
        self.telemetry_sink = None
        self.journal_views = journal_views
        if telemetry:
            # telemetry_tiers arms the per-tier suspicion counters (extra
            # [N, 4] accumulators + 8 record keys) — only meaningful when
            # runs carry a topology plan; off by default so the armed
            # pytree (and every compiled program keyed on it) is unchanged
            # for every existing caller
            self.telemetry = _tm.zeros(self.params, tiers=telemetry_tiers)
            self.telemetry_sink = telemetry if callable(telemetry) else None
            self._fetch = jax.jit(_tm.fetch)
            self._digest = jax.jit(_tm.tree_digest)

    def tick(self, faults: DeltaFaults = DeltaFaults()) -> LifecycleState:
        if self.telemetry is None:
            self.state = self._step(self.state, faults)
        else:
            self.state, self.telemetry = self._step(
                self.state, faults, telemetry=self.telemetry
            )
        return self.state

    def _block_call(self, state, faults, ticks: int, telemetry=None):
        """Dispatch one tick block — through the AOT front door when the
        instance carries a tag.  Memoized per (ticks, faults structure
        AND leaf avals, telemetry on/off): the front door binds one
        concrete program, so a faults pytree differing in structure OR
        in a leaf shape/dtype gets its own keyed program instead of a
        mis-fed executable (the plain jit path would have recompiled
        transparently; this memo must be at least as discriminating)."""
        dyn_kw = {} if telemetry is None else {"telemetry": telemetry}
        if self._aot_tag is None:
            return self._block(state, faults, ticks=ticks, **dyn_kw)
        from ringpop_tpu.util import aot as _aot

        fdesc = str(jax.tree.structure(faults)) + "|".join(
            _aot._leaf_descriptor(x) for x in jax.tree.leaves(faults)
        )
        memo = (ticks, fdesc, telemetry is not None)
        if memo not in self._aot_calls:
            # tag is the artifact's human-readable prefix; a short hash of
            # the faults descriptor keeps aot_info records from distinct
            # programs at the same block size from overwriting each other
            import hashlib as _hl

            tag = (
                f"{self._aot_tag}-blk{ticks}"
                + ("-tm" if telemetry is not None else "")
                + f"-f{_hl.sha256(fdesc.encode()).hexdigest()[:6]}"
            )
            call, info = _aot.load_or_compile(
                self._block, state, faults, dyn_kw=dyn_kw or None,
                tag=tag, static_kw={"ticks": ticks}, statics=(repr(self.params),),
            )
            self._aot_calls[memo] = call
            self.aot_info[tag] = info
        return self._aot_calls[memo](state, faults, **dyn_kw)

    def run(self, ticks: int, faults: DeltaFaults = DeltaFaults()) -> LifecycleState:
        if self.telemetry is None:
            self.state = self._block_call(self.state, faults, ticks)
        else:
            self.state, self.telemetry = self._block_call(
                self.state, faults, ticks, telemetry=self.telemetry
            )
            self._flush(faults)
        return self.state

    # -- telemetry plumbing -------------------------------------------------

    def fetch_telemetry(self, faults: DeltaFaults = DeltaFaults()) -> Optional[dict]:
        """Fetch-and-reset the accumulated block record as host scalars
        (one device_get); None when telemetry is off."""
        if self.telemetry is None:
            return None
        record, self.telemetry = self._fetch(self.telemetry, self.state, faults)
        return {
            k: v.item() if hasattr(v, "item") else v
            for k, v in jax.device_get(record).items()
        }

    def _flush(self, faults: DeltaFaults) -> None:
        """Fetch the block record and hand it to the sink (if any), with
        the state digest — and, when ``journal_views`` is set, the view-
        checksum summary — attached."""
        if self.telemetry_sink is None:
            return
        record, self.telemetry = self._fetch(self.telemetry, self.state, faults)
        extra = {"state_digest": self._digest(self.state)}
        if self.journal_views:
            extra["views_sum"] = view_checksums(self.state, faults).sum(dtype=jnp.uint32)
            extra["views_agree"] = checksums_converged(self.state, faults)
        self.telemetry_sink(record, **extra)

    def _run_until(
        self,
        dispatch,
        max_ticks: int,
        check_every: int,
        blocks_per_dispatch: int,
        time_budget_s: Optional[float],
    ):
        """Shared host loop for the budgeted device run-until machinery:
        ``dispatch(max_blocks)`` runs one jitted dispatch (up to that many
        ``check_every``-tick blocks with the early-exit predicate between
        blocks, updating ``self.state``) and returns its (blocks, done)
        arrays; the host reads back ONE pair per dispatch.  With a time
        budget set, the first dispatch runs a single block to measure block
        cost, then dispatch sizes adapt to the remaining budget (up to
        ``blocks_per_dispatch``) so one dispatch can never blow far past
        the deadline; an overrun stops with partial progress.  A
        zero/exhausted tick budget still dispatches once with 0 blocks: the
        entry check runs without stepping, so an already-done state reports
        (0, True) instead of a false negative."""
        import time as _time

        deadline = None if time_budget_s is None else _time.perf_counter() + time_budget_s
        bpd = 1 if deadline is not None else blocks_per_dispatch
        ticks = 0
        while True:
            max_blocks = min(bpd, max(0, (max_ticks - ticks) // check_every))
            t0 = _time.perf_counter()
            blocks, done = dispatch(max_blocks)
            n_blocks = int(blocks)  # blocking readback — completes the dispatch
            now = _time.perf_counter()
            ticks += n_blocks * check_every
            if bool(done):
                return ticks, True
            if max_blocks == 0 or ticks + check_every > max_ticks:
                return ticks, False
            if deadline is not None:
                if now > deadline:
                    return ticks, False
                per_block = (now - t0) / max(n_blocks, 1)
                bpd = max(
                    1,
                    min(blocks_per_dispatch, int((deadline - now) / max(per_block, 1e-9))),
                )

    def run_until_converged(
        self,
        faults: DeltaFaults = DeltaFaults(),
        max_ticks: int = 5000,
        check_every: int = 8,
        blocks_per_dispatch: int = 4,
        time_budget_s: Optional[float] = None,
    ):
        """Tick until every live node's view checksum agrees — the
        reference's convergence criterion for protocol tests
        (``swim/test_utils.go:164-199``), run on-device with early exit
        (``_run_until_converged_device``).  Returns (ticks_used,
        converged).  Loop/budget semantics: :meth:`_run_until`."""

        def dispatch(max_blocks):
            if self.telemetry is None:
                self.state, blocks, done = _run_until_converged_device(
                    self.params,
                    self.state,
                    faults,
                    block_ticks=check_every,
                    max_blocks=jnp.int32(max_blocks),
                )
            else:
                self.state, blocks, done, self.telemetry = _run_until_converged_device(
                    self.params,
                    self.state,
                    faults,
                    block_ticks=check_every,
                    max_blocks=jnp.int32(max_blocks),
                    telemetry=self.telemetry,
                )
                self._flush(faults)
            return blocks, done

        return self._run_until(
            dispatch, max_ticks, check_every, blocks_per_dispatch, time_budget_s
        )

    def run_until_detected(
        self,
        subjects: Sequence[int],
        faults: DeltaFaults = DeltaFaults(),
        min_status: int = FAULTY,
        max_ticks: int = 5000,
        check_every: int = 8,
        time_budget_s: Optional[float] = None,
        blocks_per_dispatch: int = 4,
        learned_sharding=None,
    ):
        """Tick until every live observer believes every subject has reached
        ``min_status``.  Returns (ticks_used, detected).  The loop AND its
        detection test run on-device (``_run_until_detected_device``) so
        the host reads back one (blocks, done) pair per dispatch instead
        of walking rumor slots over the interconnect.  Sharded runs pass
        ``learned_sharding=NamedSharding(mesh, P("node", None))`` so the
        per-check walk replicates the learned plane across the rumor
        shards once per check (bit-identical either way).  Loop/budget
        semantics: :meth:`_run_until`."""
        subjects = jnp.asarray(list(subjects), jnp.int32)

        def dispatch(max_blocks):
            if self.telemetry is None:
                self.state, blocks, done = _run_until_detected_device(
                    self.params,
                    self.state,
                    faults,
                    subjects,
                    min_status=min_status,
                    block_ticks=check_every,
                    max_blocks=jnp.int32(max_blocks),
                    learned_sharding=learned_sharding,
                )
            else:
                self.state, blocks, done, self.telemetry = _run_until_detected_device(
                    self.params,
                    self.state,
                    faults,
                    subjects,
                    min_status=min_status,
                    block_ticks=check_every,
                    max_blocks=jnp.int32(max_blocks),
                    learned_sharding=learned_sharding,
                    telemetry=self.telemetry,
                )
                self._flush(faults)
            return blocks, done

        return self._run_until(
            dispatch, max_ticks, check_every, blocks_per_dispatch, time_budget_s
        )
