"""Monte-Carlo protocol studies: whole simulated clusters vmapped over a
replica axis.

The reference answers "what is the detection-latency distribution?" by
running processes repeatedly (its integration suite runs cluster sizes
1..10 one at a time); our engine-agreement tests did the same with one
`LifecycleSim` per seed.  On an accelerator that's leaving the machine
idle: one `jax.vmap` over the replica axis turns B independent clusters
into ONE compiled program whose arrays are `[B, N, K]` — the natural
TPU-first shape for parameter studies (same step function, zero
per-replica Python).

Semantics are exactly `LifecycleSim`: replica b of
`MonteCarlo.run_until_detected` with seeds[b] == s produces tick-for-tick
the state `LifecycleSim(seed=s)` produces (pinned by
`tests/test_montecarlo.py`).

Reference analogs: failure detection `swim/node.go:470-513`; the suspicion
timeout sweep scenario (BASELINE `sweep100k`).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.lifecycle import (
    FAULTY,
    LifecycleParams,
    detection_complete,
    detection_fraction,
    init_state_from_key,
    step,
)


def init_replicas(params: LifecycleParams, seeds: Sequence[int]):
    """Batched state pytree: every array gains a leading replica axis B.

    Keys are built with ``jax.random.PRNGKey(seed)`` per seed (host loop, B
    is small) so replica b's stream is EXACTLY ``LifecycleSim(seed=...)``'s
    for any seed Python accepts — a uint32 cast would silently wrap seeds
    >= 2**32 and break the bit-identical contract."""
    keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
    return jax.vmap(lambda k: init_state_from_key(params, k))(keys)


def _faults_axes(faults: DeltaFaults):
    """vmap ``in_axes`` pytree for the fault masks, or None when nothing is
    batched.  Heterogeneous-scenario studies (per-replica churn/partitions)
    give ``up`` and/or ``group`` a leading replica axis ([B, N]); each
    2-D leaf maps over axis 0 while 1-D/absent leaves broadcast — so
    batched churn with a shared partition map (or vice versa) both work."""

    def ax(x):
        return 0 if x is not None and getattr(x, "ndim", 1) == 2 else None

    # scalar legs (drop_rate) and per-node legs without a replica axis
    # broadcast (axis None); only 2-D up/group masks map over replicas
    axes = DeltaFaults(up=ax(faults.up), group=ax(faults.group))
    return None if (axes.up is None and axes.group is None) else axes


def _mc_block(params: LifecycleParams, states, faults: DeltaFaults, ticks: int):
    axes = _faults_axes(faults)
    if axes is not None:
        vstep = jax.vmap(lambda s, f: step(params, s, f), in_axes=(0, axes))
        return jax.lax.fori_loop(0, ticks, lambda _, s: vstep(s, faults), states)
    vstep = jax.vmap(lambda s: step(params, s, faults))
    return jax.lax.fori_loop(0, ticks, lambda _, s: vstep(s), states)


@functools.partial(
    jax.jit, static_argnames=("params", "min_status", "block_ticks")
)
def _mc_run_until_device(
    params: LifecycleParams,
    states,
    faults: DeltaFaults,
    subjects: jax.Array,
    *,
    min_status: int,
    block_ticks: int,
    max_blocks: jax.Array,
):
    """The whole detection study in ONE dispatch: step all replicas in
    lockstep blocks, test each with the on-device ``detection_complete``,
    record per-replica first-detected block, stop early when every replica
    has detected.  Same shape of fix as ``_run_until_detected_device`` —
    the host-side per-replica ``detection_fraction`` walk this replaces was
    the pattern 1M-bench profiling showed costing ~90% of wall-clock.
    Returns (states, blocks_run, first_block[B] (-1 = never)) — the order
    of the while_loop carry."""

    def vdone(states):
        axes = _faults_axes(faults)
        if axes is not None:
            return jax.vmap(
                lambda s, f: detection_complete(s, subjects, f, min_status),
                in_axes=(0, axes),
            )(states, faults)
        return jax.vmap(
            lambda s: detection_complete(s, subjects, faults, min_status)
        )(states)

    def cond(carry):
        _, blocks, first = carry
        return (first < 0).any() & (blocks < max_blocks)

    def body(carry):
        states, blocks, first = carry
        states = _mc_block(params, states, faults, block_ticks)
        blocks = blocks + jnp.int32(1)
        first = jnp.where((first < 0) & vdone(states), blocks, first)
        return states, blocks, first

    # entry check keeps tick-for-tick equivalence with LifecycleSim's
    # runner, which reports 0 ticks on an already-detected state
    first0 = jnp.where(vdone(states), jnp.int32(0), jnp.int32(-1))
    return jax.lax.while_loop(cond, body, (states, jnp.int32(0), first0))


class MonteCarlo:
    """B lockstep cluster replicas differing only in PRNG seed.

    >>> mc = MonteCarlo(LifecycleParams(n=512, k=32), seeds=range(32))
    >>> ticks, detected = mc.run_until_detected(victims=[3, 99], faults=f)
    >>> np.median(ticks[detected])   # detection-latency distribution
    """

    def __init__(self, params: LifecycleParams, seeds: Sequence[int]):
        self.params = params
        self.seeds = list(seeds)
        self.states = init_replicas(params, self.seeds)
        self._block = jax.jit(
            functools.partial(_mc_block, self.params), static_argnames="ticks"
        )

    def detection_fractions(
        self, subjects, faults: DeltaFaults = DeltaFaults(), min_status: int = FAULTY
    ) -> np.ndarray:
        """Detection fractions per replica -> float[B, S] (introspection for
        studies that want partial progress, not just the done test; the
        done test itself runs on-device in ``_mc_run_until_device``).

        A host loop over replicas, NOT jit+vmap: ``detection_fraction``'s
        large-problem branch is host-side numpy — it cannot trace — and a
        vmapped small path would materialize O(B·N·K·S)."""
        rows = []
        for b in range(self.n_replicas):
            one = jax.tree.map(lambda x: x[b], self.states)
            # slice only the replica-batched ([B, N]) fault leaves
            fb = jax.tree.map(
                lambda x: x[b] if getattr(x, "ndim", 1) == 2 else x, faults
            )
            rows.append(np.asarray(detection_fraction(one, subjects, fb, min_status)))
        return np.stack(rows)

    @property
    def n_replicas(self) -> int:
        return len(self.seeds)

    def run(self, ticks: int, faults: DeltaFaults = DeltaFaults()):
        self.states = self._block(self.states, faults, ticks=ticks)
        return self.states

    def run_until_detected(
        self,
        victims: Sequence[int],
        faults: DeltaFaults = DeltaFaults(),
        min_status: int = FAULTY,
        max_ticks: int = 2048,
        check_every: int = 8,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Advance all replicas in lockstep until each has every live
        observer believing every victim >= ``min_status``.

        Returns ``(first_detected_tick[B], detected[B])`` — the tick count
        (multiple of ``check_every``, like ``LifecycleSim``'s) at which each
        replica first measured full detection, and whether it did within
        ``max_ticks``.  Replicas that finish early keep stepping (lockstep
        is what makes this one program); their recorded tick is frozen.
        """
        subjects = jnp.asarray(list(victims), jnp.int32)
        max_blocks = -(-max_ticks // check_every)  # host loop ran ceil(max/check)
        self.states, _, first_block = _mc_run_until_device(
            self.params,
            self.states,
            faults,
            subjects,
            min_status=min_status,
            block_ticks=check_every,
            max_blocks=jnp.int32(max_blocks),
        )
        first_block = np.asarray(first_block, np.int64)
        first_tick = np.where(first_block >= 0, first_block * check_every, -1)
        detected = first_tick >= 0
        return first_tick, detected


def detection_latency_distribution(
    n: int,
    seeds: Sequence[int],
    victims: Sequence[int],
    k: int = 32,
    suspect_ticks: Optional[int] = None,
    max_ticks: int = 2048,
    check_every: int = 1,
) -> dict:
    """One-call study: crash ``victims`` in B seeded replicas of an n-node
    cluster and return the detection-latency distribution (in ticks and in
    simulated seconds at the 200 ms protocol period).

    ``check_every`` defaults to 1: the detection predicate runs INSIDE the
    jitted replica loop, so per-tick testing costs one extra O(N·K) check
    per tick — cheap at study scales — and records each replica's EXACT
    first-detection tick.  A coarser stride quantizes every replica into
    the same bucket (a round-2 artifact showed median = p90 = max = 40.0
    across 32 replicas at stride 8 — a distribution that cannot show
    dispersion measures nothing).  Pass a larger stride only for
    far-larger-than-study scales.  Reference discipline analog:
    percentile-grade timing stats, ``swim/stats.go:81-104``."""
    kw = {} if suspect_ticks is None else {"suspect_ticks": suspect_ticks}
    params = LifecycleParams(n=n, k=k, **kw)
    tick_s = params.tick_ms / 1000.0
    up = np.ones(n, bool)
    up[np.asarray(list(victims), np.int64)] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    mc = MonteCarlo(params, seeds)
    ticks, detected = mc.run_until_detected(
        victims, faults, max_ticks=max_ticks, check_every=check_every
    )
    return _distribution(ticks, detected, mc.n_replicas, tick_s)


def _distribution(ticks: np.ndarray, detected: np.ndarray, n_replicas: int, tick_s: float) -> dict:
    det = ticks[detected].astype(float)
    return {
        "n_replicas": n_replicas,
        "detected": int(detected.sum()),
        "ticks_median": float(np.median(det)) if det.size else None,
        "ticks_p90": float(np.percentile(det, 90)) if det.size else None,
        "ticks_max": float(det.max()) if det.size else None,
        "sim_s_median": float(np.median(det) * tick_s) if det.size else None,
        # exact per-replica first-detection ticks (sorted) — the artifact
        # itself shows the dispersion, not just three summary points
        "ticks_all": sorted(int(t) for t in det),
    }


def detection_latency_under_churn(
    n: int,
    seeds: Sequence[int],
    victims: Sequence[int],
    churn_max: int,
    k: int = 32,
    suspect_ticks: Optional[int] = None,
    max_p: Optional[int] = None,
    max_ticks: int = 2048,
    check_every: int = 1,
    churn_seed: int = 1234,
) -> dict:
    """Heterogeneous-scenario study: how long until the SAME victim set is
    detected, as a function of how much *other* churn the cluster is
    digesting?  Replica b shares the study victims but additionally crashes
    ``round(b/(B-1) * churn_max)`` extra background nodes (a per-replica
    ``up`` mask — the fault pytree vmaps alongside the state).  The extra
    crashes compete for the K rumor slots and for piggyback bandwidth,
    so detection latency genuinely disperses across replicas — the
    homogeneous study's 35/36/37-tick spread measured only PRNG noise
    (VERDICT r3 weak 5).  Detection is still judged only on the shared
    victims, by each replica's own live observers.

    Reference discipline analog: percentile-grade timing stats
    (``swim/stats.go:81-104``); the scenario itself (failure detection
    under load) is the product, ``swim/node.go:470-513``."""
    kw = {} if suspect_ticks is None else {"suspect_ticks": suspect_ticks}
    if max_p is not None:
        # study knob: the mc_churn cliff analysis varies maxP to show the
        # saturated plateau tracks baseline + maxP (slot-expiry wait)
        kw["max_p"] = max_p
    params = LifecycleParams(n=n, k=k, **kw)
    tick_s = params.tick_ms / 1000.0
    seeds = list(seeds)  # consumed twice below — a generator must not exhaust
    b_count = len(seeds)
    victims = sorted(int(v) for v in victims)

    rng = np.random.default_rng(churn_seed)
    candidates = np.setdiff1d(np.arange(n), np.asarray(victims, np.int64))
    up = np.ones((b_count, n), bool)
    up[:, victims] = False
    churn_counts = []
    for b in range(b_count):
        extra = round(b / max(b_count - 1, 1) * churn_max)
        churn_counts.append(extra)
        if extra:
            down = rng.choice(candidates, size=extra, replace=False)
            up[b, down] = False
    faults = DeltaFaults(up=jnp.asarray(up))

    mc = MonteCarlo(params, seeds)
    ticks, detected = mc.run_until_detected(
        victims, faults, max_ticks=max_ticks, check_every=check_every
    )
    out = _distribution(ticks, detected, mc.n_replicas, tick_s)
    out["churn_counts"] = churn_counts
    # per-replica (churn, first_detection_tick) pairs, replica order — the
    # dose-response curve is the deliverable.  A replica that never
    # detected within max_ticks reports null, not a sentinel value a
    # plotter would correlate as a latency.
    out["churn_ticks"] = [
        [int(c), int(t) if d else None]
        for c, t, d in zip(churn_counts, ticks, detected)
    ]
    return out
