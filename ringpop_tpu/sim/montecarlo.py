"""Monte-Carlo protocol studies: whole simulated clusters vmapped over a
replica axis.

The reference answers "what is the detection-latency distribution?" by
running processes repeatedly (its integration suite runs cluster sizes
1..10 one at a time); our engine-agreement tests did the same with one
`LifecycleSim` per seed.  On an accelerator that's leaving the machine
idle: one `jax.vmap` over the replica axis turns B independent clusters
into ONE compiled program whose arrays are `[B, N, K]` — the natural
TPU-first shape for parameter studies (same step function, zero
per-replica Python).

Semantics are exactly `LifecycleSim`: replica b of
`MonteCarlo.run_until_detected` with seeds[b] == s produces tick-for-tick
the state `LifecycleSim(seed=s)` produces (pinned by
`tests/test_montecarlo.py`).

The fault model is a batchable axis too (r12): `faults` may carry a
leading replica axis on any `DeltaFaults` leaf, or be a STACKED
`chaos.FaultPlan` (`chaos.stack_plans`) — B *different* time-varying
scenarios evaluated by one compiled program, with the r7 telemetry
counters optionally accumulated under the batch axis and fetched as B
per-scenario journal records in one `device_get` (`fetch_telemetry`).
`sim/scenarios.py` builds parameter-grid sweeps on top of this.

Reference analogs: failure detection `swim/node.go:470-513`; the suspicion
timeout sweep scenario (BASELINE `sweep100k`).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.sim.lifecycle import (
    FAULTY,
    LifecycleParams,
    detection_complete,
    detection_fraction,
    init_state_from_key,
    step,
)


def init_replicas(params: LifecycleParams, seeds: Sequence[int], mesh=None):
    """Batched state pytree: every array gains a leading replica axis B.

    Keys are built with ``jax.random.PRNGKey(seed)`` per seed (host loop, B
    is small) so replica b's stream is EXACTLY ``LifecycleSim(seed=...)``'s
    for any seed Python accepts — a uint32 cast would silently wrap seeds
    >= 2**32 and break the bit-identical contract.

    ``mesh`` (r19): place the batch on a device mesh via the canonical
    partition table — a mesh with a ``"batch"`` axis shards the replica
    dimension itself (``fleet_state_shardings``), so a B=4096 × n=4096
    fleet's arrays split across devices/processes instead of replicating.
    """
    keys = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
    states = jax.vmap(lambda k: init_state_from_key(params, k))(keys)
    if mesh is not None:
        states = jax.tree.map(
            jax.device_put, states, fleet_state_shardings(mesh, k=params.k)
        )
    return states


def make_fleet_mesh(n_devices: Optional[int] = None, shape=None):
    """A ``("batch", "node", "rumor")`` mesh for block-sharded fleets: the
    replica batch is a REAL mesh axis, so the canonical partition table
    (``parallel.partition`` with ``batch_axis="batch"``) shards every
    ``[B, ...]`` fleet leaf's leading dimension across devices.  Default
    shape puts ALL parallelism on the batch axis — scenarios are
    independent, so batch sharding adds zero cross-replica collectives
    and divides per-device residency by the batch factor (the Ising-fleet
    memory story); pass ``shape`` to split devices between batch and the
    node/rumor axes for fleets whose members are themselves large."""
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if len(devices) < n_devices:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= n_devices:
            devices = cpu
    if len(devices) < n_devices:
        raise ValueError(
            f"need {n_devices} devices, have {len(devices)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    if shape is None:
        shape = (n_devices, 1, 1)
    dev_array = np.asarray(devices[:n_devices]).reshape(shape)
    return Mesh(dev_array, axis_names=("batch", "node", "rumor"))


# solo (unbatched) ndim per DeltaFaults leaf — a leaf with one more axis
# carries a leading replica axis and maps over it (chaos.PLAN_LEG_NDIM is
# the FaultPlan analog)
_DELTA_FAULTS_NDIM = {
    "up": 1,
    "group": 1,
    "drop_rate": 0,
    "drop_node": 1,
    "reach": 2,
    "tier_ids": 2,
    "tier_drop": 1,
    "suspect_ticks": 0,
}


def _faults_axes(faults):
    """vmap ``in_axes`` pytree for the fault model, or None when nothing
    is batched.  Both fault vocabularies batch:

    * ``DeltaFaults`` — any leaf with one more axis than its solo rank
      maps over replicas (``up``/``group``/``drop_node`` as [B, N],
      ``drop_rate`` as [B], ``reach`` as [B, G, G]); solo/absent leaves
      broadcast, so batched churn with a shared partition map (or vice
      versa) both work.
    * ``chaos.FaultPlan`` — a STACKED plan (``chaos.stack_plans``), every
      scenario a different member: ``chaos.plan_axes`` decides per leg.
      This is what makes the fault plan a batchable axis end-to-end: one
      jitted program evaluates B scenarios × R replicas.
    """
    from ringpop_tpu.sim import chaos

    if isinstance(faults, chaos.FaultPlan):
        return chaos.plan_axes(faults)

    def ax(field, x):
        if x is None:
            return None
        # .ndim is static Python metadata even on tracers — no concretization
        return 0 if getattr(x, "ndim", 0) == _DELTA_FAULTS_NDIM[field] + 1 else None

    axes = {f: ax(f, getattr(faults, f)) for f in _DELTA_FAULTS_NDIM}
    if all(v is None for v in axes.values()):
        return None
    return DeltaFaults(**axes)


def _mc_block(params: LifecycleParams, states, faults, ticks: int, telemetry=None):
    """``ticks`` vmapped steps in one fused loop.  ``telemetry`` (a
    [B]-batched ``telemetry.TelemetryState`` or None): when given, the
    loop carry is the (states, telemetry) pair and the per-tick counters
    accumulate UNDER the replica axis — the None leg compiles out, so the
    telemetry-free program is exactly the one r9 traced."""
    axes = _faults_axes(faults)
    if telemetry is None:
        if axes is not None:
            vstep = jax.vmap(lambda s, f: step(params, s, f), in_axes=(0, axes))
            return jax.lax.fori_loop(0, ticks, lambda _, s: vstep(s, faults), states)
        vstep = jax.vmap(lambda s: step(params, s, faults))
        return jax.lax.fori_loop(0, ticks, lambda _, s: vstep(s), states)
    if axes is not None:
        vstep = jax.vmap(
            lambda s, t, f: step(params, s, f, telemetry=t), in_axes=(0, 0, axes)
        )
        return jax.lax.fori_loop(
            0, ticks, lambda _, c: vstep(c[0], c[1], faults), (states, telemetry)
        )
    vstep = jax.vmap(lambda s, t: step(params, s, faults, telemetry=t))
    return jax.lax.fori_loop(
        0, ticks, lambda _, c: vstep(c[0], c[1]), (states, telemetry)
    )


def fleet_save_mesh():
    """One-axis ``("batch",)`` mesh over EVERY process's devices in
    process order — the checkpoint placement mesh for process-sliced
    sweeps: ``partition.fleet_shard_put`` places each rank's local batch
    slice on it so orbax writes a process-spanning store with every rank
    writing only its shards (and restores re-chunk onto a different
    process count).  Single-process it degenerates to all local devices
    — the same code path."""
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), ("batch",))


def fleet_state_shardings(mesh, k=None):
    """Shardings for a [B, ...] replica batch over a mesh, derived from
    the ONE canonical rule table (``parallel.partition``) with a one-deep
    batch prefix.  Two mesh families:

    * ``("node", "rumor")`` — the r12 layout: the batch axis REPLICATES
      and every underlying state axis keeps the canonical
      ``lifecycle.state_shardings`` placement (the sharded mc_chaos
      ksweep section and the jaxlint fleet entry point).
    * a mesh carrying a ``"batch"`` axis (``make_fleet_mesh``) — the r19
      block-sharded fleet: the replica dimension itself shards over that
      axis, so per-device (and, process-spanning, per-host) residency
      divides by the batch factor while each member's trajectory stays
      bit-identical to its unsharded twin (scenarios are independent; no
      cross-replica collectives exist to reassociate).
    """
    from ringpop_tpu.parallel.partition import named_shardings
    from ringpop_tpu.sim.lifecycle import LifecycleState
    from ringpop_tpu.sim.packbits import check_rumor_shardable

    if k is not None:
        check_rumor_shardable(k, mesh.shape.get("rumor", 1))
    skeleton = LifecycleState(**{f: 0 for f in LifecycleState._fields})
    return named_shardings(
        skeleton, mesh, batch_axes=1,
        batch_axis="batch" if "batch" in mesh.axis_names else None,
    )


def fleet_shardings(tree, mesh):
    """NamedShardings for ANY ``[B, ...]``-batched fleet pytree (batched
    telemetry accumulators, per-replica first-detection ticks, the whole
    checkpoint carry) over ``mesh`` — same rule as
    :func:`fleet_state_shardings`: canonical table per leaf, batch prefix
    on the mesh's ``"batch"`` axis when it has one, replicated prefix
    otherwise."""
    from ringpop_tpu.parallel.partition import named_shardings

    return named_shardings(
        tree, mesh, batch_axes=1,
        batch_axis="batch" if "batch" in mesh.axis_names else None,
    )


def fleet_faults_shardings(faults, mesh):
    """Per-leg NamedShardings for a (possibly) batched fault model over a
    fleet mesh: STACKED legs (one more axis than their solo rank) get the
    batch prefix — sharded over the ``"batch"`` mesh axis when present —
    while shared/solo legs keep their canonical placement and None legs
    stay None.  The leg-wise analog of :func:`fleet_state_shardings`,
    needed because a stacked plan mixes both kinds in one pytree."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ringpop_tpu.parallel.partition import spec_for
    from ringpop_tpu.sim import chaos

    batch = "batch" if "batch" in mesh.axis_names else None
    if isinstance(faults, chaos.FaultPlan):
        ranks = {f: chaos._leg_rank(f, v) if v is not None else 0
                 for f, v in zip(faults._fields, faults)}
        fields, cls = faults._fields, chaos.FaultPlan
    else:
        ranks = {
            f: (1 if getattr(v, "ndim", 0) == _DELTA_FAULTS_NDIM[f] + 1 else 0)
            for f in _DELTA_FAULTS_NDIM
            for v in (getattr(faults, f),)
            if v is not None
        }
        fields, cls = tuple(_DELTA_FAULTS_NDIM), DeltaFaults
    out = {}
    for f in fields:
        v = getattr(faults, f)
        if v is None:
            continue
        spec = spec_for(f)
        if ranks.get(f):
            spec = P(batch, *spec)
        out[f] = NamedSharding(mesh, spec)
    return cls(**out)


def _index_faults(faults, b: int):
    """Replica ``b``'s solo fault model out of a (possibly) batched one —
    batched leaves are sliced, shared leaves pass through (the DeltaFaults
    analog of ``chaos.index_plan``)."""
    from ringpop_tpu.sim import chaos

    if isinstance(faults, chaos.FaultPlan):
        return chaos.index_plan(faults, b)
    return DeltaFaults(
        **{
            f: (
                None
                if getattr(faults, f) is None
                else getattr(faults, f)[b]
                if getattr(getattr(faults, f), "ndim", 0)
                == _DELTA_FAULTS_NDIM[f] + 1
                else getattr(faults, f)
            )
            for f in _DELTA_FAULTS_NDIM
        }
    )


@functools.partial(jax.jit, static_argnames=("axes",))
def _mc_fetch(tel, states, faults, *, axes):
    """Batched telemetry fetch: reduce every replica's accumulators to a
    [B]-column block record plus per-replica state digests in ONE
    dispatch (``telemetry.split_batched`` then splits the single
    ``device_get`` into per-scenario journal records).  ``axes`` is the
    hashable fault ``in_axes`` pytree (static so each fault structure
    compiles once)."""
    from ringpop_tpu.sim import telemetry as _tm

    record, fresh = jax.vmap(_tm.fetch, in_axes=(0, 0, axes))(tel, states, faults)
    digests = jax.vmap(_tm.tree_digest)(states)
    return record, fresh, digests


@functools.partial(
    jax.jit, static_argnames=("params", "min_status", "block_ticks")
)
def _mc_run_until_device(
    params: LifecycleParams,
    states,
    faults: DeltaFaults,
    subjects: jax.Array,
    telemetry=None,
    *,
    min_status: int,
    block_ticks: int,
    max_blocks: jax.Array,
):
    """The whole detection study in ONE dispatch: step all replicas in
    lockstep blocks, test each with the on-device ``detection_complete``,
    record per-replica first-detected block, stop early when every replica
    has detected.  Same shape of fix as ``_run_until_detected_device`` —
    the host-side per-replica ``detection_fraction`` walk this replaces was
    the pattern 1M-bench profiling showed costing ~90% of wall-clock.

    ``telemetry`` (a [B]-batched accumulator or None): when given it
    rides the while_loop carry, so the r7 counters cover every tick the
    lockstep fleet actually stepped — long-horizon sweeps journal
    counters from the SAME detection loop instead of falling back to
    fixed-horizon stepping (the r12 refusal this replaces).  The None
    leg compiles out: the telemetry-free program is exactly the r12 one.

    Returns (states, telemetry, blocks_run, first_block[B] (-1 = never))
    — the order of the while_loop carry."""

    def vdone(states):
        axes = _faults_axes(faults)
        if axes is not None:
            return jax.vmap(
                lambda s, f: detection_complete(s, subjects, f, min_status),
                in_axes=(0, axes),
            )(states, faults)
        return jax.vmap(
            lambda s: detection_complete(s, subjects, faults, min_status)
        )(states)

    def cond(carry):
        _, _, blocks, first = carry
        return (first < 0).any() & (blocks < max_blocks)

    def body(carry):
        states, tel, blocks, first = carry
        if tel is None:
            states = _mc_block(params, states, faults, block_ticks)
        else:
            states, tel = _mc_block(
                params, states, faults, block_ticks, telemetry=tel
            )
        blocks = blocks + jnp.int32(1)
        first = jnp.where((first < 0) & vdone(states), blocks, first)
        return states, tel, blocks, first

    # entry check keeps tick-for-tick equivalence with LifecycleSim's
    # runner, which reports 0 ticks on an already-detected state
    first0 = jnp.where(vdone(states), jnp.int32(0), jnp.int32(-1))
    return jax.lax.while_loop(
        cond, body, (states, telemetry, jnp.int32(0), first0)
    )


class MonteCarlo:
    """B lockstep cluster replicas differing in PRNG seed AND (optionally)
    fault scenario: ``faults`` may be a ``DeltaFaults`` with [B, ...]
    leaves or a STACKED ``chaos.FaultPlan`` (``chaos.stack_plans``), so
    one compiled program evaluates B scenarios × their seeds.

    ``telemetry=True`` carries a [B]-batched r7 counter accumulator
    through every :meth:`run` tick AND through
    :meth:`run_until_detected`'s device loop (r19 — the loop's while
    carry holds the accumulator, so long-horizon sweeps journal counters
    without falling back to fixed-horizon stepping);
    :meth:`fetch_telemetry` reduces it to B per-scenario block records
    (tagged ``scenario_id``) in one dispatch + one ``device_get`` — the
    journal ``chaos.score_blocks`` reduces into per-scenario verdicts
    with no host round-trips per scenario.  The exact-horizon scored
    path remains :meth:`run` blocks (``scenarios.scored_fleet``).

    ``mesh`` (r19): a ``make_fleet_mesh`` mesh block-shards the fleet —
    states, the telemetry accumulator and every stacked fault leg place
    their batch axis on the mesh's ``"batch"`` axis via the canonical
    partition table, so per-device/per-host residency divides by the
    batch factor while every member stays bit-identical to its unsharded
    twin (pinned by tests/test_fleet_shard.py).  A ``("node", "rumor")``
    mesh keeps the r12 batch-replicated layout.

    ``aot="tag"`` routes the batched detection program through the
    ``util/aot.py`` warm-start front door (``aot_info`` collects the
    measured ``cache_hit``/``compile_s`` per keyed program).

    >>> mc = MonteCarlo(LifecycleParams(n=512, k=32), seeds=range(32))
    >>> ticks, detected = mc.run_until_detected(victims=[3, 99], faults=f)
    >>> np.median(ticks[detected])   # detection-latency distribution
    """

    def __init__(
        self,
        params: LifecycleParams,
        seeds: Sequence[int],
        telemetry: bool = False,
        aot: Optional[str] = None,
        telemetry_tiers: bool = False,
        mesh=None,
    ):
        self.params = params
        self.seeds = list(seeds)
        self.mesh = mesh
        self.states = init_replicas(params, self.seeds, mesh=mesh)
        self._block = jax.jit(
            functools.partial(_mc_block, self.params), static_argnames="ticks"
        )
        self._aot_tag = aot
        self._aot_calls: dict = {}
        self.aot_info: dict = {}
        self._faults_cache: tuple = (None, None)
        self._telemetry_tiers = telemetry_tiers
        self.telemetry = None
        if telemetry:
            self.telemetry = self._fresh_telemetry()

    def _fresh_telemetry(self):
        from ringpop_tpu.sim import telemetry as _tm

        # telemetry_tiers arms the per-tier suspicion counters for
        # topology-carrying fleets (see telemetry.zeros)
        tz = _tm.zeros(self.params, tiers=self._telemetry_tiers)
        b = len(self.seeds)
        tel = jax.tree.map(lambda x: jnp.broadcast_to(x, (b,) + x.shape), tz)
        if self.mesh is not None:
            tel = jax.tree.map(
                jax.device_put, tel, fleet_shardings(tel, self.mesh)
            )
        return tel

    def reset_states(self, seeds: Optional[Sequence[int]] = None):
        """Re-seed the fleet IN PLACE (same B — the compiled programs are
        shape-keyed) without dropping the instance's AOT/jit warm state:
        the adaptive cliff driver (``scenarios.refine_surface``) swaps
        seeds and plan VALUES between dispatches while the fleet program
        stays compiled once.  Zeroes the telemetry accumulator when
        armed."""
        if seeds is not None:
            seeds = list(seeds)
            if len(seeds) != len(self.seeds):
                raise ValueError(
                    f"reset_states got {len(seeds)} seeds for a B="
                    f"{len(self.seeds)} fleet (B is compiled into the program)"
                )
            self.seeds = seeds
        self.states = init_replicas(self.params, self.seeds, mesh=self.mesh)
        if self.telemetry is not None:
            self.telemetry = jax.tree.map(jnp.zeros_like, self.telemetry)

    def _place_faults(self, faults):
        """Device placement for the fault model on a fleet mesh: stacked
        legs shard over the batch axis alongside the states
        (``fleet_faults_shardings``).  Memoized on object identity — the
        sweep loops hand the same plan to every block."""
        if self.mesh is None or faults is None:
            return faults
        cached, placed = self._faults_cache
        if cached is faults:
            return placed
        placed = jax.tree.map(
            jax.device_put, faults, fleet_faults_shardings(faults, self.mesh)
        )
        self._faults_cache = (faults, placed)
        return placed

    def detection_fractions(
        self, subjects, faults: DeltaFaults = DeltaFaults(), min_status: int = FAULTY
    ) -> np.ndarray:
        """Detection fractions per replica -> float[B, S] (introspection for
        studies that want partial progress, not just the done test; the
        done test itself runs on-device in ``_mc_run_until_device``).

        A host loop over replicas, NOT jit+vmap: ``detection_fraction``'s
        large-problem branch is host-side numpy — it cannot trace — and a
        vmapped small path would materialize O(B·N·K·S)."""
        rows = []
        for b in range(self.n_replicas):
            one = jax.tree.map(lambda x: x[b], self.states)
            rows.append(
                np.asarray(
                    detection_fraction(one, subjects, _index_faults(faults, b), min_status)
                )
            )
        return np.stack(rows)

    @property
    def n_replicas(self) -> int:
        return len(self.seeds)

    def run(self, ticks: int, faults: DeltaFaults = DeltaFaults()):
        faults = self._place_faults(faults)
        if self.telemetry is None:
            self.states = self._block(self.states, faults, ticks=ticks)
        else:
            self.states, self.telemetry = self._block(
                self.states, faults, ticks=ticks, telemetry=self.telemetry
            )
        return self.states

    def fetch_telemetry(
        self, faults: DeltaFaults = DeltaFaults(), id_base: int = 0
    ) -> list[dict]:
        """Fetch-and-reset the batched accumulators: B per-scenario host
        block records (``scenario_id`` = ``id_base`` + replica index —
        rank r of a process-sliced fleet passes its slice offset so
        records carry GLOBAL scenario ids), produced by ONE jitted
        reduction and ONE ``device_get`` (``telemetry.split_batched``)."""
        if self.telemetry is None:
            raise ValueError("MonteCarlo built without telemetry=True")
        from ringpop_tpu.sim import telemetry as _tm

        faults = self._place_faults(faults)
        record, self.telemetry, digests = _mc_fetch(
            self.telemetry, self.states, faults, axes=_faults_axes(faults)
        )
        return _tm.split_batched(
            record, {"state_digest": digests}, id_base=id_base
        )

    def _until_call(self, states, faults, subjects, tel, *, min_status, block_ticks, max_blocks):
        """Dispatch the whole-fleet detection program — through the AOT
        warm-start front door when the instance carries a tag.  Memoized
        per (statics, faults structure + leaf avals, subjects aval,
        telemetry armed-ness, the FLEET SHARDING descriptor) — every
        dynamic shape AND placement the exported executable is fixed to:
        a mesh-sharded fleet is a different compiled program than its
        unsharded twin and must never share its memo slot (the leaf
        descriptors inside ``load_or_compile`` already key the artifact
        itself; this keys the per-instance call cache built before the
        leaves are enumerated)."""
        kw = dict(min_status=min_status, block_ticks=block_ticks)
        if self._aot_tag is None:
            return _mc_run_until_device(
                self.params, states, faults, subjects, tel,
                max_blocks=max_blocks, **kw
            )
        from ringpop_tpu.util import aot as _aot

        fdesc = (
            str(jax.tree.structure(faults))
            + "|".join(_aot._leaf_descriptor(x) for x in jax.tree.leaves(faults))
            + "|s:" + _aot._leaf_descriptor(subjects)
            + "|t:" + str(jax.tree.structure(tel))
            + "|m:" + _aot.sharding_descriptor((states, faults, tel))
        )
        memo = (min_status, block_ticks, fdesc)
        if memo not in self._aot_calls:
            import hashlib as _hl

            tag = (
                f"{self._aot_tag}-mc{block_ticks}"
                f"-f{_hl.sha256(fdesc.encode()).hexdigest()[:6]}"
            )
            call, info = _aot.load_or_compile(
                functools.partial(_mc_run_until_device, self.params),
                states, faults, subjects, tel,
                dyn_kw={"max_blocks": max_blocks},
                tag=tag, static_kw=kw, statics=(repr(self.params),),
            )
            self._aot_calls[memo] = call
            self.aot_info[tag] = info
        return self._aot_calls[memo](
            states, faults, subjects, tel, max_blocks=max_blocks
        )

    def run_until_detected(
        self,
        victims: Sequence[int],
        faults: DeltaFaults = DeltaFaults(),
        min_status: int = FAULTY,
        max_ticks: int = 2048,
        check_every: int = 8,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Advance all replicas in lockstep until each has every live
        observer believing every victim >= ``min_status``.

        Returns ``(first_detected_tick[B], detected[B])`` — the tick count
        (multiple of ``check_every``, like ``LifecycleSim``'s) at which each
        replica first measured full detection, and whether it did within
        ``max_ticks``.  Replicas that finish early keep stepping (lockstep
        is what makes this one program); their recorded tick is frozen.

        An armed telemetry accumulator RIDES the device loop's carry
        (r19): the counters cover every tick the lockstep fleet actually
        stepped — ``blocks_run × check_every``, which for early finishers
        exceeds their first-detection tick by construction — and
        :meth:`fetch_telemetry` journals them as usual.  (r12 refused
        this pairing because the loop did not carry the accumulator; the
        carry is the supported route now.)
        """
        faults = self._place_faults(faults)
        subjects = jnp.asarray(list(victims), jnp.int32)
        max_blocks = -(-max_ticks // check_every)  # host loop ran ceil(max/check)
        self.states, self.telemetry, _, first_block = self._until_call(
            self.states,
            faults,
            subjects,
            self.telemetry,
            min_status=min_status,
            block_ticks=check_every,
            max_blocks=jnp.int32(max_blocks),
        )
        first_block = np.asarray(first_block, np.int64)
        first_tick = np.where(first_block >= 0, first_block * check_every, -1)
        detected = first_tick >= 0
        return first_tick, detected


def detection_latency_distribution(
    n: int,
    seeds: Sequence[int],
    victims: Sequence[int],
    k: int = 32,
    suspect_ticks: Optional[int] = None,
    max_ticks: int = 2048,
    check_every: int = 1,
) -> dict:
    """One-call study: crash ``victims`` in B seeded replicas of an n-node
    cluster and return the detection-latency distribution (in ticks and in
    simulated seconds at the 200 ms protocol period).

    ``check_every`` defaults to 1: the detection predicate runs INSIDE the
    jitted replica loop, so per-tick testing costs one extra O(N·K) check
    per tick — cheap at study scales — and records each replica's EXACT
    first-detection tick.  A coarser stride quantizes every replica into
    the same bucket (a round-2 artifact showed median = p90 = max = 40.0
    across 32 replicas at stride 8 — a distribution that cannot show
    dispersion measures nothing).  Pass a larger stride only for
    far-larger-than-study scales.  Reference discipline analog:
    percentile-grade timing stats, ``swim/stats.go:81-104``."""
    kw = {} if suspect_ticks is None else {"suspect_ticks": suspect_ticks}
    params = LifecycleParams(n=n, k=k, **kw)
    tick_s = params.tick_ms / 1000.0
    up = np.ones(n, bool)
    up[np.asarray(list(victims), np.int64)] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    mc = MonteCarlo(params, seeds)
    ticks, detected = mc.run_until_detected(
        victims, faults, max_ticks=max_ticks, check_every=check_every
    )
    return _distribution(ticks, detected, mc.n_replicas, tick_s)


def _distribution(ticks: np.ndarray, detected: np.ndarray, n_replicas: int, tick_s: float) -> dict:
    det = ticks[detected].astype(float)
    return {
        "n_replicas": n_replicas,
        "detected": int(detected.sum()),
        "ticks_median": float(np.median(det)) if det.size else None,
        "ticks_p90": float(np.percentile(det, 90)) if det.size else None,
        "ticks_max": float(det.max()) if det.size else None,
        "sim_s_median": float(np.median(det) * tick_s) if det.size else None,
        # exact per-replica first-detection ticks (sorted) — the artifact
        # itself shows the dispersion, not just three summary points
        "ticks_all": sorted(int(t) for t in det),
    }


def detection_latency_under_churn(
    n: int,
    seeds: Sequence[int],
    victims: Sequence[int],
    churn_max: int,
    k: int = 32,
    suspect_ticks: Optional[int] = None,
    max_p: Optional[int] = None,
    max_ticks: int = 2048,
    check_every: int = 1,
    churn_seed: int = 1234,
) -> dict:
    """Heterogeneous-scenario study: how long until the SAME victim set is
    detected, as a function of how much *other* churn the cluster is
    digesting?  Replica b shares the study victims but additionally crashes
    ``round(b/(B-1) * churn_max)`` extra background nodes (a per-replica
    ``up`` mask — the fault pytree vmaps alongside the state).  The extra
    crashes compete for the K rumor slots and for piggyback bandwidth,
    so detection latency genuinely disperses across replicas — the
    homogeneous study's 35/36/37-tick spread measured only PRNG noise
    (VERDICT r3 weak 5).  Detection is still judged only on the shared
    victims, by each replica's own live observers.

    Reference discipline analog: percentile-grade timing stats
    (``swim/stats.go:81-104``); the scenario itself (failure detection
    under load) is the product, ``swim/node.go:470-513``."""
    kw = {} if suspect_ticks is None else {"suspect_ticks": suspect_ticks}
    if max_p is not None:
        # study knob: the mc_churn cliff analysis varies maxP to show the
        # saturated plateau tracks baseline + maxP (slot-expiry wait)
        kw["max_p"] = max_p
    params = LifecycleParams(n=n, k=k, **kw)
    tick_s = params.tick_ms / 1000.0
    seeds = list(seeds)  # consumed twice below — a generator must not exhaust
    b_count = len(seeds)
    victims = sorted(int(v) for v in victims)

    # the dose ladder and per-dose masks are THE shared definition
    # (sim/scenarios.py) — the mc_chaos surface's loss-0 row reuses them,
    # so the 1-D slice and the surface cannot drift apart (lazy import:
    # scenarios imports MonteCarlo from this module at load time)
    from ringpop_tpu.sim.scenarios import churn_dose_masks, mc_churn_doses

    churn_counts = mc_churn_doses(b_count, churn_max)
    up = churn_dose_masks(n, victims, churn_counts, churn_seed)
    faults = DeltaFaults(up=jnp.asarray(up))

    mc = MonteCarlo(params, seeds)
    ticks, detected = mc.run_until_detected(
        victims, faults, max_ticks=max_ticks, check_every=check_every
    )
    out = _distribution(ticks, detected, mc.n_replicas, tick_s)
    out["churn_counts"] = churn_counts
    # per-replica (churn, first_detection_tick) pairs, replica order — the
    # dose-response curve is the deliverable.  A replica that never
    # detected within max_ticks reports null, not a sentinel value a
    # plotter would correlate as a latency.
    out["churn_ticks"] = [
        [int(c), int(t) if d else None]
        for c, t, d in zip(churn_counts, ticks, detected)
    ]
    return out
