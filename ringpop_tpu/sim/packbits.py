"""Bit-packed boolean planes for the O(N·K) sim engines.

The lifecycle engine's per-(node, rumor) booleans (``learned`` and every
mask derived from it) dominate its memory traffic: at 1M x 256 a single
bool[N, K] plane is 256 MB, and one protocol tick touches a dozen of them.
Packing the K axis 32-to-a-word turns every boolean combine into a uint32
bitwise op — 8x less traffic than XLA's byte-per-bool layout, and 32x
fewer elements for the fused chains — which is what makes the 1M-node
headline fit a single-core CPU fallback (VERDICT round 2 item 2) and
trims HBM bytes on TPU.

Layout: slot ``j`` lives in word ``j >> 5``, bit ``j & 31`` (LSB-first).
Tail bits past ``k`` in the last word are always zero by construction —
``pack_bool`` pads with False and the engine only ever ORs in masks gated
by per-slot ``active`` vectors, which are themselves packed from length-K
bools.

Reference analog: none — the Go reference keeps per-member maps
(``swim/disseminator.go:30-40``); this is density engineering the dense
rebuild owns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

WORD = 32
# numpy, NOT jnp: a device array built at import time would initialize the
# XLA backend as a side effect of importing the sim package, which breaks
# anything that must run first (jax.distributed.initialize in the
# multi-host workers).  jnp ops promote the numpy operand on use.
_BITS = np.arange(WORD, dtype=np.uint32)


def n_words(k: int) -> int:
    """Words needed for k slots."""
    return (k + WORD - 1) // WORD


def mix32(x: jax.Array) -> jax.Array:
    """murmur3 fmix32: a full-avalanche integer mixer (public-domain
    constants) — the ONE copy shared by lifecycle's order-invariant view
    checksum and telemetry's state digest.  NOT the wire-compat farm32
    (which needs the host's canonical sorted-string encoding,
    ``memberlist.go:106-128``)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EB_CA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2_AE35)
    x = x ^ (x >> 16)
    return x


def flat_index_u32(row, ncols: int, col) -> jax.Array:
    """Global flat index ``row * ncols + col`` in WRAPPING uint32
    arithmetic — the blessed spelling for digest/mixing lanes, where the
    value is consumed mod 2³² by design (``mix32`` eats the whole word).

    A flat-plane index computed in int32 silently overflows once
    N·K ≥ 2³¹ (16M × 256 ≈ 4.1e9 — inside the multi-host target scale);
    jaxlint RPA106 flags raw ``row * K + col`` products of traced extents
    so the overflow can't land unaudited.  Routes that genuinely need the
    NUMERIC flat index past 2³¹ (none in the engines today) must
    restructure to (row, col) pairs instead — there is no 64-bit integer
    lane under the repo's x64-off discipline (RPA104)."""
    return (
        jnp.asarray(row).astype(jnp.uint32) * jnp.uint32(ncols & 0xFFFF_FFFF)
        + jnp.asarray(col).astype(jnp.uint32)
    )


def pack_bool(x: jax.Array) -> jax.Array:
    """bool[..., K] -> uint32[..., W] (LSB-first within each word)."""
    k = x.shape[-1]
    w = n_words(k)
    pad = w * WORD - k
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros(x.shape[:-1] + (pad,), dtype=x.dtype)], axis=-1
        )
    x = x.reshape(x.shape[:-1] + (w, WORD))
    return (x.astype(jnp.uint32) << _BITS).sum(axis=-1, dtype=jnp.uint32)


def unpack_bits(p: jax.Array, k: int) -> jax.Array:
    """uint32[..., W] -> bool[..., K]."""
    w = p.shape[-1]
    bits = (p[..., :, None] >> _BITS) & jnp.uint32(1)
    return bits.reshape(p.shape[:-1] + (w * WORD,))[..., :k].astype(bool)


def bit_column(p: jax.Array, j) -> jax.Array:
    """Extract slot bits from a packed plane (``j`` may be traced).

    Scalar ``j`` on p[..., W] -> bool[...] (one slot's column); batched
    ``j`` with ``j.shape == p.shape[:-1]`` -> bool[...] (a per-row slot
    pick, e.g. one gathered slot per row)."""
    j = jnp.asarray(j, jnp.int32)
    if j.ndim == 0:
        word = jnp.take(p, j >> 5, axis=-1)
    else:
        word = jnp.take_along_axis(p, (j >> 5)[..., None], axis=-1)[..., 0]
    return ((word >> (j & 31).astype(jnp.uint32)) & 1).astype(bool)


def row_mask(rows: jax.Array) -> jax.Array:
    """bool[N] -> uint32[N, 1]: all-ones word where True (broadcast gate
    for packed planes)."""
    return jnp.where(rows, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))[..., None]


def nonzero_rows(p: jax.Array) -> jax.Array:
    """[N, ...] -> bool[N]: rows carrying ANY nonzero element — the
    send-side summary of the r15 wire codec's zero-row suppression
    (``parallel/fabric`` ROWS encoding).  Shard-local/elementwise along
    the node axis by construction; the trailing axes reduce in-row.
    INTEGER planes only as a codec summary: the test is value-level, so
    float -0.0 would read as a zero row while its bytes are not (the
    host-side ``fabric._rows_encode`` masks the byte view instead)."""
    return jnp.any(p.reshape(p.shape[0], -1) != 0, axis=-1)


def popcount_rows(p: jax.Array) -> jax.Array:
    """uint32[N, W] -> uint32[N]: per-row set-bit count (each row's is
    ≤ 32·W so uint32 never wraps; callers doing GLOBAL sums chunk and
    fold in wider host arithmetic — the r14 headroom rule)."""
    return jax.lax.population_count(p).sum(axis=-1, dtype=jnp.uint32)


# node-axis block count for the row reduces: reduce WITHIN each of G
# contiguous blocks first (slices along the unpartitioned in-block axis —
# shard-local under SPMD), then combine the G block results (G×W words of
# cross-shard traffic).  The flat halving tree this replaces sliced the
# NODE axis in half every step, which the partitioner could only lower to
# ~log2(N) collective-permutes of half-plane slices — measured as the
# single largest collective class of the sharded 1M lifecycle tick
# (~197 permutes, ~37% of all cross-chip bytes; PERF.md r6).  Bitwise
# OR/AND are exact under reassociation, so the result is bit-identical.
# A multiple of every plausible node-shard count; must divide n (falls
# back to the largest power of two that does).
_REDUCE_BLOCKS = 16


def block_count(n: int, b: int) -> int:
    """Largest power of two <= ``b`` that divides ``n`` — the shared
    node-block fallback rule of every blocked-for-SPMD path (the row
    reduces here, lifecycle's hierarchical top-M and row gathers)."""
    while b > 1 and n % b:
        b //= 2
    return b


def _halving_tree(p: jax.Array, op, identity: int, axis: int) -> jax.Array:
    """Unrolled halving tree along ``axis`` — ``lax.reduce`` with a
    bitwise combiner would be one op, but XLA's SPMD partitioner rejects
    custom reduction computations ("Unsupported reduction computation"),
    and the sharded step must run on device meshes.  log2(n) elementwise
    combines touch ~2n words total — same traffic class as the reduce."""
    n = p.shape[axis]
    pow2 = 1 << max(n - 1, 1).bit_length()
    if pow2 == 2 * n:
        pow2 = n  # n was already a power of two
    if pow2 != n:
        shape = list(p.shape)
        shape[axis] = pow2 - n
        pad = jnp.full(shape, jnp.uint32(identity))
        p = jnp.concatenate([p, pad], axis=axis)
    ix = [slice(None)] * p.ndim
    while pow2 > 1:
        pow2 //= 2
        lo, hi = list(ix), list(ix)
        lo[axis] = slice(0, pow2)
        hi[axis] = slice(pow2, 2 * pow2)
        p = op(p[tuple(lo)], p[tuple(hi)])
    return jnp.squeeze(p, axis=axis)


def _tree_reduce_rows(p: jax.Array, op, identity: int) -> jax.Array:
    """Bitwise reduce over the node axis: blocked halving tree (see
    ``_REDUCE_BLOCKS``) — in-block combines are shard-local, only the
    [G, W] block results cross shards.  Identical bits to the flat tree
    (bitwise ops reassociate exactly); identical word count on one core.
    The named scope tags the reduce in profiler traces / HLO metadata
    (nested under whichever protocol phase called it)."""
    with jax.named_scope("row-reduce"):
        n = p.shape[0]
        g = block_count(n, _REDUCE_BLOCKS)
        if g > 1 and n > g:
            p = _halving_tree(
                p.reshape((g, n // g) + p.shape[1:]), op, identity, axis=1
            )
        return _halving_tree(p, op, identity, axis=0)


def or_reduce_rows(p: jax.Array) -> jax.Array:
    """uint32[N, W] -> uint32[W]: bitwise OR over the node axis."""
    return _tree_reduce_rows(p, jnp.bitwise_or, 0)


def and_reduce_rows(p: jax.Array) -> jax.Array:
    """uint32[N, W] -> uint32[W]: bitwise AND over the node axis."""
    return _tree_reduce_rows(p, jnp.bitwise_and, 0xFFFFFFFF)


# NOTE on fences, for the next person fighting XLA:CPU fusion here: both
# ``lax.optimization_barrier`` (stripped before fusion) and an identity
# self-scatter ``x.at[0].set(x[0])`` (algebraically simplified away) were
# tried and CANNOT force materialization of a producer chain.  The working
# levers are structural: gathers through precomputed index vectors instead
# of traced-shift rolls, and genuine multi-row SCATTERS (``.at[rows].set``)
# for row updates — NOT dynamic_update_slice, whose fused form re-derives
# its whole operand chain per element of a full-plane copy, and NOT
# plane-wide selects, which drag the mask's producer chain into every
# consuming element (see PERF.md "Round 3" / "Round 4").


def set_bit(p: jax.Array, rows: jax.Array, slots: jax.Array, on: jax.Array) -> jax.Array:
    """Scatter-OR bits (rows[i], slots[i]) into packed plane ``p`` where
    ``on[i]``; out-of-range rows are dropped.

    Builds the update as an add-scatter on a zero plane then ORs it in —
    callers must guarantee (row, slot) pairs are distinct where ``on``
    (true everywhere in the engine: each scatter seeds distinct slots or
    distinct rows), because two adds of the same bit would carry into the
    next slot instead of ORing.
    """
    with jax.named_scope("set-bit"):
        n, w = p.shape
        rows = jnp.asarray(rows, jnp.int32)
        slots = jnp.asarray(slots, jnp.int32)
        vals = jnp.where(on, jnp.uint32(1) << (slots & 31).astype(jnp.uint32), jnp.uint32(0))
        upd = jnp.zeros((n, w), jnp.uint32).at[rows, slots >> 5].add(vals, mode="drop")
        return p | upd


def set_bit_per_row(p: jax.Array, slots: jax.Array, on: jax.Array) -> jax.Array:
    """Row ``i`` ORs in bit ``slots[i]`` where ``on[i]`` — the
    ``rows == arange(n)`` special case of :func:`set_bit`, written as a
    pure elementwise one-hot against the word index instead of a scatter.
    A scatter whose row coordinates are an iota still made the SPMD
    partitioner all-gather its [N, 2] index and [N] update tensors
    (~12 MB/chip/tick at 1M); the compare-and-OR form is elementwise over
    the [N, W] plane, so it partitions (and fuses) trivially.  W is a
    handful of words, so the extra N·W compares are noise on one core.
    Out-of-range slots: callers clamp (identical to the engine's previous
    ``set_bit(..., i_all, clip(slots), on)`` contract — the clamped write
    lands in a real word but is masked by ``on``)."""
    with jax.named_scope("set-bit"):
        w = p.shape[1]
        slots = jnp.asarray(slots, jnp.int32)
        hit = (slots[:, None] >> 5) == jnp.arange(w, dtype=jnp.int32)[None, :]
        bit = (jnp.uint32(1) << (slots & 31).astype(jnp.uint32))[:, None]
        return p | jnp.where(hit & on[:, None], bit, jnp.uint32(0))


def check_rumor_shardable(k: int, rumor_shards: int) -> None:
    """Validate that ``k`` rumor slots can shard over a ``rumor_shards``-way
    mesh axis, raising with the real rule instead of the opaque GSPMD
    divisibility error deep inside jit.

    The packed planes (``learned``/``ride_ok``) shard WORDS while the
    unpacked planes (``pcount``) shard SLOTS, so a clean placement needs
    every shard to hold whole words AND the word boundaries to coincide
    with the slot boundaries — i.e. ``k`` must be a multiple of
    ``32 * rumor_shards``.  (k=96 over a 2-way axis passes a bare
    ``k >= 32*rumor_shards`` check but still fails placement: 3 words do
    not divide by 2.)"""
    if rumor_shards > 1 and k % (WORD * rumor_shards):
        raise ValueError(
            f"k={k} cannot shard over a {rumor_shards}-way rumor axis: the "
            f"bit-packed planes shard 32-slot words, so k must be a "
            f"multiple of 32 * rumor_shards (= {WORD * rumor_shards}); "
            f"n_words(k)={n_words(k)} words / slot-alignment would not "
            f"divide evenly"
        )
