"""Partition-invariant counter RNG for the sim engines (``rng="counter"``).

The engines' default threefry draws are correct but GSPMD-hostile: threefry
is not partitionable, so a sharded ``[N, P]`` draw either materializes
REPLICATED (the r6 budget's ~12 MB/chip/tick peer-choice all-reduce) or —
worse — the partitioner generates DIFFERENT lanes for the sharded output
than the unsharded program produces (the r7 telemetry finding: the sharded
peer-sampling draw diverges on ~100% of lanes; protocol state was immune at
the committed configs only because ``up[targets]`` masks every lane that
could matter — see ROADMAP "residual sharded-tick traffic").

This module is the fix, built to the Ising-on-TPU discipline of making
every per-lane random quantity a pure function of its coordinates: a value
is ``h(seed, tick, draw-site, lane)`` where ``h`` is a chain of murmur3
fmix32 finalizers (``packbits.mix32`` — the repo's one shared
full-avalanche mixer).  Consequences, by construction:

* **shard-local**: the lane argument is the only array input, and ``h`` is
  elementwise in it — the partitioner keeps every draw on the shard that
  owns the lane, with ZERO collectives under any mesh;
* **partition-invariant**: lane ``i``'s value never depends on which shard
  computes it, so sharded and unsharded programs draw IDENTICAL lanes
  (``tests/test_prng.py`` pins 1/2/4/8-way meshes bit-equal, and the
  engine-level sharded-vs-unsharded run matches including the telemetry
  counters that exposed the threefry divergence);
* **stateless**: the carried ``key`` leaf is never split — it holds the
  run's seed material and the tick counter advances the stream — so the
  per-tick key-derivation ops vanish from the step too.

NOT a cryptographic generator, and NOT bit-compatible with the threefry
draws: ``rng="counter"`` is a different (equally valid) trajectory family.
The frozen goldens therefore stay on ``rng="threefry"``; sharded callers
and ``simbench`` default to the counter stream.

Statistical quality: each draw site gets its own stream constant
(fmix32-folded), and lanes walk a Weyl sequence through two further fmix32
rounds — the SplitMix construction, which is far beyond what an epidemic
sim needs.  ``tests/test_prng.py`` chi-squares 1M draws as a smoke check.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ringpop_tpu.sim.packbits import mix32

# the golden-ratio Weyl increment (2^32 / phi, odd) — SplitMix's stream
# stride; full-period over uint32 because it is odd
_GAMMA = 0x9E37_79B9

# -- per-call-site draw ids ---------------------------------------------------
# One id per PRNG consumption site per tick, shared by the delta and
# lifecycle engines (a site unused by an engine simply never draws).
# Multi-column sites (the P indirect-probe peers) add their column index to
# a base spaced D_COLUMN_SPAN apart — so two sites collide (correlated
# streams!) if a column index ever reaches the span.  The lifecycle engine
# guards ``ping_req_size < D_COLUMN_SPAN`` at trace time; widen the span
# here if a config ever legitimately needs more indirect-probe fan-out.
D_COLUMN_SPAN = 0x100
D_SHIFT = 1  # exchange="shift" cyclic offset (scalar)
D_TARGET = 2  # exchange="uniform" per-node targets
D_DROP = 3  # per-node packet-loss coin on the direct probe
D_HEAL_A = 4  # healer endpoint a (scalar)
D_HEAL_B = 5  # healer endpoint b (scalar)
D_HEAL_U = 6  # healer attempt coin (scalar)
D_TOPO = 7  # per-node topology tier-loss coin on the direct probe
D_PEER = 1 * D_COLUMN_SPAN  # + column j: indirect-probe peer choice [N, P]
D_PEER_DROP_REQ = 2 * D_COLUMN_SPAN  # + column j: ping-req request-leg loss [N, P]
D_PEER_DROP_ACK = 3 * D_COLUMN_SPAN  # + column j: ping-req ack-leg loss [N, P]
D_TOPO_PEER_REQ = 4 * D_COLUMN_SPAN  # + column j: tier-loss coin, ping-req request leg
D_TOPO_PEER_ACK = 5 * D_COLUMN_SPAN  # + column j: tier-loss coin, ping-req ack leg


def fold_key(key) -> jax.Array:
    """uint32 scalar seed from an engine ``state.key`` leaf (the raw
    uint32[2] threefry key ``init_state`` already carries) — the counter
    stream reuses the existing state layout instead of adding a seed leaf.
    Works for any uint32 vector; vmappable (the Monte-Carlo replica batch
    maps distinct keys to distinct streams)."""
    k = jnp.ravel(jnp.asarray(key)).astype(jnp.uint32)
    seed = jnp.uint32(0)
    for i in range(k.shape[0]):
        seed = mix32(seed ^ k[i] ^ jnp.uint32((i + 1) * _GAMMA & 0xFFFF_FFFF))
    return seed


def draw_u32(seed, tick, draw, lane) -> jax.Array:
    """uint32 ``h(seed, tick, draw, lane)`` — elementwise in every
    argument (all broadcast; ``lane`` is normally the only array).  The
    (seed, tick, draw) triple folds into a per-site stream constant —
    scalar at every engine call site, so it traces to a handful of
    replicated scalar ops — and the lane then takes two fmix32 rounds on
    a Weyl walk seeded by that stream."""
    stream = mix32(
        jnp.asarray(seed).astype(jnp.uint32)
        ^ mix32(
            jnp.asarray(tick).astype(jnp.uint32)
            ^ mix32(jnp.asarray(draw).astype(jnp.uint32) * jnp.uint32(_GAMMA))
        )
    )
    x = jnp.asarray(lane).astype(jnp.uint32) * jnp.uint32(_GAMMA) + stream
    return mix32(mix32(x) ^ stream)


def draw_uniform(seed, tick, draw, lane) -> jax.Array:
    """float32 in [0, 1) — the top 24 bits of the u32 draw (exactly
    representable; same construction as jax.random.uniform's mantissa
    fill)."""
    return (draw_u32(seed, tick, draw, lane) >> 8).astype(jnp.float32) * jnp.float32(
        1.0 / (1 << 24)
    )


def draw_randint(seed, tick, draw, lane, lo: int, hi: int) -> jax.Array:
    """int32 in [lo, hi) via modulo reduction.  The modulo bias is
    (hi-lo)/2^32 — ~2e-4 relative at the 1M-node headline, noise against
    the protocol's own stochasticity and far below what the uniformity
    smoke can resolve; accepted for staying in uint32 (TPU-native, no
    64-bit ops)."""
    span = hi - lo
    if span <= 0:
        raise ValueError(f"empty randint range [{lo}, {hi})")
    return (jnp.int32(lo) + (draw_u32(seed, tick, draw, lane) % jnp.uint32(span)).astype(jnp.int32))
