"""Scenario-grid compiler: parameter sweeps as ONE batched chaos program.

r10's chaos plane scores one FaultPlan per run; r12 made the plan a
batchable axis (``chaos.stack_plans`` + the Monte-Carlo fleet in
``sim/montecarlo.py``).  This module is the host-side compiler on top:
sweep a protocol-parameter grid — background-churn dose × packet loss ×
partition width (plan legs, batched), with suspicion timeout as a static
outer axis — into a stacked ``[B, ...]`` plan, run it through one
AOT-warm-started program, and reduce the results into 2-D response
surfaces.  The exemplar is the Ising-on-TPU-clusters treatment
(PAPERS.md, arXiv:1903.11714): million-replica parameter studies as one
dense program, compilation and dispatch amortized across the sweep.

Grid axes and where they live:

* **churn dose** — per-scenario background crash cohorts, drawn with
  EXACTLY the rng sequence ``montecarlo.detection_latency_under_churn``
  draws (``churn_dose_masks``), so the loss-0 row of the churn×loss
  surface is bit-identical to the committed ``mc_churn`` 1-D slice
  (SIMBENCH_r05: cliff at dose 107) — the surface extends the slice, it
  does not re-measure it.
* **loss** — the scalar ``drop_rate`` leg, batched ``[B]``.  A 0.0 rate
  is value-identical to no drop leg at all (the survival comparisons
  ``u >= 0.0`` / ``u < 1.0`` pass every leg and the engines' key splits
  don't depend on the drop leg), which is what lets one dense program
  cover the loss-free row too.
* **partition width** — optional symmetric split window (minority
  fraction per scenario; width 0 = no partition leg for that member).
* **suspicion timeout** — BATCHED since the topology round: the traced
  ``suspect_ticks`` plan leg (engines select the static param on the -1
  sentinel, so a member without the leg is bit-identical to the old
  static path) rides the ``suspects=`` grid axis inside one compiled
  program.  ``sweep_static`` remains for genuinely compile-time
  parameters.

* **topology overlays** — the ``overlays=`` axis merges
  ``sim/topology.py`` scenario plans (zone loss, switch flap, WAN
  partition, each with its rack/zone/region tier legs) into grid
  members, so correlated-failure families sweep through the same
  batched fleet.

The scored path (``scored_fleet``) carries the r7 telemetry counters
under the batch axis and reduces them per scenario with ONE device fetch
per journal block — ``chaos.score_blocks`` then turns each scenario's
block slice into a verdict with its grid coordinates attached.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim import chaos
from ringpop_tpu.sim.chaos import FaultPlan
from ringpop_tpu.sim.lifecycle import LifecycleParams
from ringpop_tpu.sim.montecarlo import MonteCarlo


# -- grid construction (host-side) --------------------------------------------


def mc_churn_doses(b_count: int, churn_max: int) -> list[int]:
    """The dose ladder ``detection_latency_under_churn`` uses: dose j =
    round(j/(B-1)·churn_max) — shared so the surface's churn axis cannot
    drift from the committed 1-D slice's."""
    return [round(b / max(b_count - 1, 1) * churn_max) for b in range(b_count)]


def churn_dose_masks(
    n: int, victims: Sequence[int], doses: Sequence[int], churn_seed: int
) -> np.ndarray:
    """``up[D, N]`` masks, one per dose: the study victims plus ``dose``
    background crashes.  The rng sequence is EXACTLY the one
    ``detection_latency_under_churn`` consumes (one ``choice`` per
    non-zero dose, in dose order), so dose j's mask here is bit-equal to
    replica j's mask there — the parity the loss-0 surface row rests on."""
    victims = sorted(int(v) for v in victims)
    rng = np.random.default_rng(churn_seed)
    candidates = np.setdiff1d(np.arange(n), np.asarray(victims, np.int64))
    up = np.ones((len(doses), n), bool)
    up[:, victims] = False
    for j, dose in enumerate(doses):
        if dose:
            down = rng.choice(candidates, size=int(dose), replace=False)
            up[j, down] = False
    return up


def scenario_grid(
    n: int,
    *,
    victims: Sequence[int],
    doses: Sequence[int],
    losses: Sequence[float] = (0.0,),
    parts: Sequence[float] = (0.0,),
    suspects: Sequence[Optional[int]] = (None,),
    overlays: Optional[Sequence[tuple[str, Optional[FaultPlan]]]] = None,
    churn_seed: int = 1234,
    part_from: int = 0,
    part_until: Optional[int] = None,
) -> tuple[FaultPlan, list[dict]]:
    """Compile a (overlay × suspicion-timeout × loss × part × churn-dose)
    grid into ONE stacked plan plus its meta table.

    Returns ``(plan, meta)``: ``plan`` is the ``[B, ...]`` stacked
    FaultPlan (B = the axis product, loss-major / dose-minor inside each
    overlay/timeout cell), ``meta[i]`` carries ``scenario_id``, the grid
    coordinates (``churn``/``loss``/``part``, plus ``suspect``/``overlay``
    when those axes are swept) and ``dose_index`` — callers seed scenario
    i with ``base_seed + dose_index`` so every row reuses the churn
    slice's (seed, dose) pairing.  Churn masks are drawn once per dose
    (``churn_dose_masks``) and shared across rows; a non-zero ``part``
    adds a symmetric split window ``[part_from, part_until)`` over the
    first ``part`` fraction of nodes.

    The two post-r12 axes:

    * ``suspects`` — the suspicion timeout, BATCHED: each value rides the
      traced ``suspect_ticks`` plan leg (None = the engine's static
      param, via the -1 stacked sentinel), so the timeout axis runs
      inside ONE compiled program where it used to be a static outer
      loop (``sweep_static`` remains for compile-time parameters proper).
    * ``overlays`` — ``(label, plan-or-None)`` pairs merged into every
      member: the topology axis (``sim/topology.py`` scenario plans —
      zone loss, switch flap, WAN partition, with their tier legs) or
      any other leg family the base grid doesn't set.  Leg collisions
      (e.g. an overlay partition against ``parts`` > 0) are refused
      loudly by ``chaos._merge_plans``.
    """
    masks = churn_dose_masks(n, victims, doses, churn_seed)
    plans, meta = [], []
    for olabel, overlay in (overlays if overlays is not None else ((None, None),)):
        for suspect in suspects:
            for loss in losses:
                for part in parts:
                    for j, dose in enumerate(doses):
                        legs = dict(
                            base_up=jnp.asarray(masks[j]),
                            drop_rate=jnp.asarray(np.float32(loss)),
                        )
                        if part > 0:
                            group = np.zeros(n, np.int32)
                            group[: int(part * n)] = 1
                            legs.update(
                                group=jnp.asarray(group),
                                part_from=jnp.asarray(np.int32(part_from)),
                                part_until=jnp.asarray(
                                    np.int32(part_until if part_until is not None else chaos.NO_TICK)
                                ),
                            )
                        if suspect is not None:
                            legs["suspect_ticks"] = jnp.asarray(
                                np.int32(suspect)
                            )
                        member = FaultPlan(**legs)
                        if overlay is not None:
                            member = chaos._merge_plans(member, overlay)
                        plans.append(member)
                        m = {
                            "scenario_id": len(meta),
                            "churn": int(dose),
                            "loss": float(loss),
                            "part": float(part),
                            "dose_index": j,
                        }
                        if tuple(suspects) != (None,):
                            m["suspect"] = None if suspect is None else int(suspect)
                        if overlays is not None:
                            m["overlay"] = olabel
                        meta.append(m)
    return chaos.stack_plans(plans), meta


def grid_seeds(meta: list[dict], base_seed: int) -> list[int]:
    """Per-scenario seeds reusing the 1-D churn slice's pairing: scenario
    i runs at ``base_seed + dose_index`` (every loss/part row replays the
    same seeds, so rows differ only in the swept parameter)."""
    return [base_seed + m["dose_index"] for m in meta]


def sweep_static(values: Sequence[int], run_fn) -> dict:
    """A static outer axis: ``run_fn(value)`` once per value — one
    compiled program each, everything else batched inside it.  Returns
    {value: result}.  The suspicion timeout no longer needs this (the
    traced ``suspect_ticks`` leg batches it — ``scenario_grid(suspects=
    ...)``); it stays for genuinely compile-time parameters (k, maxP,
    exchange flavor) and as the A/B baseline the traced-timeout tests
    pin against."""
    return {int(v): run_fn(int(v)) for v in values}


# -- fleet runners ------------------------------------------------------------


def detect_surface(
    params: LifecycleParams,
    plan: FaultPlan,
    seeds: Sequence[int],
    victims: Sequence[int],
    *,
    max_ticks: int = 4096,
    check_every: int = 1,
    aot: Optional[str] = None,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """First-detection ticks for every scenario of a stacked plan, in ONE
    dispatch of the fleet detection program (1-tick resolution by
    default, like the committed mc_churn slice).  Returns
    ``(ticks[B], detected[B], aot_info)`` — ``aot_info`` carries the
    front door's measured ``cache_hit``/``compile_s`` when a tag was
    given (``{}`` otherwise)."""
    mc = MonteCarlo(params, seeds, aot=aot)
    ticks, detected = mc.run_until_detected(
        victims, plan, max_ticks=max_ticks, check_every=check_every
    )
    return ticks, detected, next(iter(mc.aot_info.values()), {})


def sequential_detect(
    params: LifecycleParams,
    plan: FaultPlan,
    seeds: Sequence[int],
    victims: Sequence[int],
    *,
    max_ticks: int = 4096,
    check_every: int = 1,
    fresh_compile: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """The baseline the fleet replaces: B sequential solo runs, one per
    scenario — one compile + one dispatch per grid point.
    ``fresh_compile=True`` clears the jit caches between runs so the
    measurement prices that workflow honestly inside one process (each
    grid point of the pre-fleet sweep was its own bench invocation and
    paid its own trace+compile); False prices the best-case warm-cache
    sequential loop instead.  Both are reported by ``simbench mc_chaos``."""
    ticks = np.full(len(seeds), -1, np.int64)
    detected = np.zeros(len(seeds), bool)
    for b, seed in enumerate(seeds):
        if fresh_compile:
            jax.clear_caches()
        mc = MonteCarlo(params, [seed])
        t, d = mc.run_until_detected(
            victims,
            chaos.index_plan(plan, b),
            max_ticks=max_ticks,
            check_every=check_every,
        )
        ticks[b], detected[b] = int(t[0]), bool(d[0])
    return ticks, detected


def scored_fleet(
    params: LifecycleParams,
    plan: FaultPlan,
    meta: list[dict],
    seeds: Sequence[int],
    *,
    horizon: int,
    journal_every: int = 16,
    sink=None,
    scenario: str = "mc_chaos",
) -> list[dict]:
    """Run the fleet for ``horizon`` ticks with the telemetry counters
    accumulated under the batch axis, journal one block record per
    (scenario, block) — ONE device fetch per block for ALL scenarios —
    and reduce each scenario's journal slice into a ``chaos.score_blocks``
    verdict carrying its grid coordinates.  ``sink`` (a
    ``telemetry.TelemetrySink`` or None) receives every per-scenario
    block record and, when it journals, every score record.

    The one-shot wrapper around :class:`FleetSweep` — the resumable form
    with mid-sweep checkpoints, process slicing and mesh sharding."""
    sweep = FleetSweep(
        params, plan, meta, seeds, horizon=horizon,
        journal_every=journal_every, sink=sink, scenario=scenario,
    )
    sweep.run()
    return sweep.scores()


FLEET_CKPT_VERSION = 1


class FleetSweep:
    """A resumable long-horizon scored sweep — the r19 unit of fleet
    work: B scenarios stepped in lockstep journal blocks with the r7
    counters under the batch axis, checkpointable MID-SWEEP and
    restorable bit-exactly, including onto a different process count.

    The checkpoint carry is (batched engine state + batched telemetry
    counters); sweep progress and the already-fetched per-scenario block
    records ride a JSON sidecar next to the orbax store (block records
    are native JSON scalars by the ``_to_host`` coercion, so the sidecar
    round-trip is value-exact and the resumed run's
    ``chaos.score_blocks`` verdicts equal the unbroken run's bit for
    bit).  Process slicing: rank r of a P-process sweep constructs this
    class over ``chaos.slice_plan(plan, lo, hi)`` /
    ``meta[lo:hi]`` / ``seeds[lo:hi]`` with ``global_b=B`` — at save
    time each rank's local slice is placed on the process-spanning
    ``montecarlo.fleet_save_mesh`` (``partition.fleet_shard_put``) so
    every process writes ONLY its shards; at restore the new process
    count's ranks read only theirs (``fleet_scale`` certificate,
    ``make fleet-smoke``).

    ``mesh`` — a ``make_fleet_mesh`` device mesh block-shards the fleet
    in-process (single-host many-device); mutually exclusive with
    multi-process slicing (one partitioning owner at a time).
    """

    def __init__(
        self,
        params: LifecycleParams,
        plan: FaultPlan,
        meta: list[dict],
        seeds: Sequence[int],
        *,
        horizon: int,
        journal_every: int = 16,
        sink=None,
        scenario: str = "mc_chaos",
        mesh=None,
        global_b: Optional[int] = None,
        telemetry_tiers: Optional[bool] = None,
        obs=None,
        on_block=None,
    ):
        if len(meta) != len(list(seeds)):
            raise ValueError(f"{len(meta)} meta entries vs {len(list(seeds))} seeds")
        self.params, self.plan = params, plan
        self.meta, self.seeds = list(meta), list(seeds)
        self.horizon, self.journal_every = horizon, journal_every
        self.sink, self.scenario = sink, scenario
        self.global_b = len(self.meta) if global_b is None else global_b
        # meta carries grid-GLOBAL scenario ids; a process slice keeps
        # them, so the id base is simply the first entry's id
        self.id_base = self.meta[0]["scenario_id"] if self.meta else 0
        ids = [m["scenario_id"] for m in self.meta]
        if ids != list(range(self.id_base, self.id_base + len(ids))):
            raise ValueError(
                "meta scenario_ids must be contiguous (a process_block "
                f"slice of the grid); got {ids[:4]}..."
            )
        # a topology-carrying plan arms the per-tier suspicion counters,
        # so its verdicts get the per-tier ttd/false-positive breakdowns
        tiers = (
            plan.tier_ids is not None
            if telemetry_tiers is None
            else telemetry_tiers
        )
        self.mc = MonteCarlo(
            params, self.seeds, telemetry=True, telemetry_tiers=tiers,
            mesh=mesh,
        )
        self.blocks: dict[int, list[dict]] = {i: [] for i in ids}
        self.ticks_done = 0
        self.resumed: Optional[dict] = None
        # obs: an obs.endpoint.LiveOps — the live operations plane.
        # Host-plane only (it ingests the SAME fetched records the sink
        # sees), so a live-plane-on sweep is bit-identical to off; every
        # rank's sweep must attach one when any does (obs.sync() is a
        # deterministic per-block collective on the obs fabric).
        self.obs = obs
        # on_block(sweep) runs after each block's obs.sync() — the
        # closed-loop hook (obs/gameday.py evaluates its rule engine +
        # controller here).  Host-side only, AFTER the block's records
        # are journaled: a hook cannot change what the sim computed, so
        # hook-on vs hook-off sweeps stay digest-identical.
        self.on_block = on_block
        self._last_checkpoint_tick: Optional[int] = None

    def header_params(self) -> dict:
        """Restore-proof fields for a journal header (OBSERVABILITY.md
        fleet-checkpoint schema): where the sweep stands and — after a
        restore — where it came from."""
        out = {
            "fleet_b": len(self.meta),
            "global_b": self.global_b,
            "id_base": self.id_base,
            "horizon": self.horizon,
            "journal_every": self.journal_every,
            "ticks_done": self.ticks_done,
        }
        if self.resumed is not None:
            out["resumed"] = dict(self.resumed)
        return out

    def run(self, until_tick: Optional[int] = None) -> "FleetSweep":
        """Step to ``until_tick`` (default: the horizon) in journal
        blocks — exactly ``horizon`` total ticks: full blocks plus one
        short remainder block (its own compile of the static-ticks
        program) when ``journal_every`` does not divide.  ``until_tick``
        must land on a block boundary: checkpoints live between blocks,
        so a resumed run replays the identical block structure."""
        target = self.horizon if until_tick is None else min(until_tick, self.horizon)
        if target % self.journal_every and target != self.horizon:
            raise ValueError(
                f"until_tick={target} is not a journal block boundary "
                f"(journal_every={self.journal_every}) — checkpoints live "
                "between blocks"
            )
        while self.ticks_done < target:
            step = min(self.journal_every, self.horizon - self.ticks_done)
            self.mc.run(step, self.plan)
            self.ticks_done += step
            for rec in self.mc.fetch_telemetry(self.plan, id_base=self.id_base):
                self.blocks[rec["scenario_id"]].append(rec)
                # obs first (it never raises): if the sink dies on this
                # record, the flight ring already holds it — the dump's
                # tail can only MATCH the journal's, never trail it
                if self.obs is not None:
                    self.obs.block_record(rec)
                if self.sink is not None:
                    self.sink(rec)
            if self.obs is not None:
                # per-block heartbeat + one obs collection round (the
                # same protocol point on every rank — non-blocking)
                self.obs.progress(
                    self.ticks_done, self.horizon,
                    last_checkpoint_tick=self._last_checkpoint_tick,
                )
                self.obs.sync()
            if self.on_block is not None:
                self.on_block(self)
        return self

    def scores(self) -> list[dict]:
        """Per-scenario ``chaos.score_blocks`` verdicts over EVERY block
        this sweep has seen — including, after a restore, the pre-kill
        blocks read back from the checkpoint sidecar."""
        scores = []
        for b, m in enumerate(self.meta):
            gid = m["scenario_id"]
            sc = chaos.score_blocks(
                self.blocks[gid],
                chaos.index_plan(self.plan, b),
                n=self.params.n,
                scenario=self.scenario,
                scenario_id=gid,
            )
            sc.update({k: v for k, v in m.items() if k != "scenario_id"})
            scores.append(sc)
            if self.sink is not None and getattr(self.sink, "journal", None) is not None:
                self.sink.journal.score(sc)
        return scores

    def digests(self) -> dict[int, int]:
        """{global scenario_id: state digest} for this sweep's slice —
        the per-scenario certification currency (one vmapped digest
        dispatch)."""
        import jax

        from ringpop_tpu.sim import telemetry as _tm

        d = jax.vmap(_tm.tree_digest)(self.mc.states)
        return {
            self.id_base + i: int(v) for i, v in enumerate(jax.device_get(d))
        }

    # -- checkpointing --------------------------------------------------------

    def _carry(self) -> dict:
        return {"states": self.mc.states, "telemetry": self.mc.telemetry}

    def save(self, path: str) -> None:
        """Checkpoint mid-sweep: the carry to orbax (each process writes
        only its shards — multi-process slices place their local batch
        rows on the process-spanning save mesh first) plus a per-rank
        JSON sidecar under ``<path>.meta/`` carrying progress, config
        fingerprints and this rank's fetched block records."""
        import jax

        from ringpop_tpu.sim import snapshot
        from ringpop_tpu.sim.montecarlo import fleet_save_mesh

        nprocs = jax.process_count()
        carry = self._carry()
        if nprocs > 1:
            if self.mc.mesh is not None:
                raise ValueError(
                    "process-sliced sweeps checkpoint their local slice; a "
                    "device mesh on top would need two partitioning owners"
                )
            from ringpop_tpu.parallel.partition import fleet_shard_put

            carry = fleet_shard_put(carry, fleet_save_mesh(), self.global_b)
        snapshot.save_carry_orbax(path, carry)
        meta_dir = path + ".meta"
        os.makedirs(meta_dir, exist_ok=True)
        rank = jax.process_index() if nprocs > 1 else 0
        sidecar = {
            "version": FLEET_CKPT_VERSION,
            "scenario": self.scenario,
            "params": repr(self.params),
            "global_b": self.global_b,
            "lo": self.id_base,
            "hi": self.id_base + len(self.meta),
            "ticks_done": self.ticks_done,
            "horizon": self.horizon,
            "journal_every": self.journal_every,
            "process_count": nprocs,
            "blocks": {str(k): v for k, v in self.blocks.items()},
        }
        tmp = os.path.join(meta_dir, f"rank{rank}.json.tmp{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(sidecar, f)
        os.replace(tmp, os.path.join(meta_dir, f"rank{rank}.json"))
        self._last_checkpoint_tick = self.ticks_done
        if self.obs is not None:
            self.obs.progress(
                self.ticks_done, self.horizon,
                last_checkpoint_tick=self.ticks_done,
            )

    @classmethod
    def restore(
        cls,
        path: str,
        params: LifecycleParams,
        plan: FaultPlan,
        meta: list[dict],
        seeds: Sequence[int],
        *,
        sink=None,
        scenario: Optional[str] = None,
        mesh=None,
        global_b: Optional[int] = None,
        telemetry_tiers: Optional[bool] = None,
        obs=None,
    ) -> "FleetSweep":
        """Resume a killed sweep — at THIS process count, which need not
        match the saver's.  ``plan``/``meta``/``seeds`` are the caller's
        reconstruction of ITS slice of the grid (the grid is
        deterministic in its config; ``chaos.slice_plan`` +
        ``partition.process_block`` re-slice it for the new rank
        layout); the carry restores with every process reading only its
        own shards, and the pre-kill block records merge back from ALL
        ranks' sidecars so the final verdicts cover the whole horizon."""
        import glob as _glob

        import jax

        from ringpop_tpu.sim import snapshot
        from ringpop_tpu.sim.montecarlo import fleet_save_mesh

        meta_dir = path + ".meta"
        sidecars = []
        for p in sorted(_glob.glob(os.path.join(meta_dir, "rank*.json"))):
            with open(p) as f:
                sidecars.append(json.load(f))
        if not sidecars:
            raise ValueError(f"{path}: no fleet checkpoint sidecars in {meta_dir}")
        head = sidecars[0]
        if head.get("version") != FLEET_CKPT_VERSION:
            raise ValueError(
                f"{path}: fleet checkpoint version {head.get('version')} "
                f"(this build reads {FLEET_CKPT_VERSION})"
            )
        for key in ("ticks_done", "horizon", "journal_every", "global_b", "params"):
            vals = {json.dumps(s.get(key)) for s in sidecars}
            if len(vals) > 1:
                raise ValueError(f"{path}: sidecars disagree on {key!r}: {vals}")
        if head["params"] != repr(params):
            raise ValueError(
                f"{path}: checkpoint was taken with {head['params']}, "
                f"restore asked for {params!r}"
            )
        sweep = cls(
            params, plan, meta, seeds,
            horizon=head["horizon"], journal_every=head["journal_every"],
            sink=sink, scenario=scenario or head.get("scenario", "mc_chaos"),
            mesh=mesh, global_b=global_b, telemetry_tiers=telemetry_tiers,
            obs=obs,
        )
        if sweep.global_b != head["global_b"]:
            raise ValueError(
                f"{path}: checkpoint holds a B={head['global_b']} fleet, "
                f"restore sliced B={sweep.global_b}"
            )
        example = sweep._carry()
        nprocs = jax.process_count()
        if nprocs > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            smesh = fleet_save_mesh()

            def _sh(leaf):
                return NamedSharding(
                    smesh, P("batch", *([None] * (np.ndim(leaf) - 1)))
                )

            # the example holds the LOCAL slice; the store holds the
            # GLOBAL fleet — widen the batch axis, restore sharded, and
            # keep only this rank's rows
            gexample = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    (sweep.global_b,) + np.shape(x)[1:], x.dtype
                ),
                example,
            )
            carry = snapshot.load_carry_orbax(
                path, gexample, jax.tree.map(_sh, gexample)
            )
            from ringpop_tpu.parallel.partition import fleet_host_gather

            carry = jax.tree.map(jnp.asarray, fleet_host_gather(carry))
        else:
            # explicit target shardings ALWAYS: a checkpoint written by a
            # process-spanning save carries per-shard sharding metadata
            # that cannot reconstruct on a different topology — the
            # restore target, not the store, names the layout
            if mesh is not None:
                from ringpop_tpu.sim.montecarlo import fleet_shardings

                shardings = fleet_shardings(example, mesh)
            else:
                dev = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
                shardings = jax.tree.map(lambda x: dev, example)
            carry = snapshot.load_carry_orbax(path, example, shardings)
        sweep.mc.states = carry["states"]
        sweep.mc.telemetry = carry["telemetry"]
        sweep.ticks_done = head["ticks_done"]
        # the restored run came FROM a checkpoint at this tick — that is
        # what /progress should report until the next save
        sweep._last_checkpoint_tick = head["ticks_done"]
        for s in sidecars:
            for gid_s, recs in s.get("blocks", {}).items():
                gid = int(gid_s)
                if gid in sweep.blocks:
                    sweep.blocks[gid] = list(recs)
        sweep.resumed = {
            "from_tick": head["ticks_done"],
            "checkpoint": os.path.abspath(path),
            "saved_process_count": head.get("process_count"),
            "restored_process_count": nprocs,
        }
        return sweep


# -- surface reduction --------------------------------------------------------


def response_surface(
    meta: list[dict],
    values: Sequence,
    *,
    rows: str = "loss",
    cols: str = "churn",
) -> dict:
    """Reduce per-scenario values into a 2-D response surface keyed by
    two grid axes.  Cells with several scenarios (a third axis collapsed)
    take the median of their non-null values; cells where every value is
    null stay null.  Returns ``{"row_axis", "rows", "col_axis", "cols",
    "cells"}`` with ``cells[i][j]`` the value at (rows[i], cols[j])."""
    row_vals = sorted({m[rows] for m in meta})
    col_vals = sorted({m[cols] for m in meta})
    buckets: dict[tuple, list] = {}
    for m, v in zip(meta, values):
        buckets.setdefault((m[rows], m[cols]), []).append(v)
    cells = []
    for r in row_vals:
        row = []
        for c in col_vals:
            got = [v for v in buckets.get((r, c), []) if v is not None]
            row.append(float(np.median(got)) if got else None)
        cells.append(row)
    return {
        "row_axis": rows,
        "rows": row_vals,
        "col_axis": cols,
        "cols": col_vals,
        "cells": cells,
    }


def locate_cliff(curve: Sequence[tuple]) -> tuple[Optional[int], Optional[float]]:
    """The dose at the largest jump between consecutive detected points
    of a dose-response curve (the mc_churn cliff finder, factored here so
    the 1-D slice, every surface row, AND the adaptive driver's 2-cell
    windows share one definition).  Takes ``[(dose, ticks-or-None),
    ...]``.

    Contract (explicit since r19 — the old code returned ``(None,
    None)`` ambiguously for both cases):

    * fewer than TWO detected points (empty curve, a single point, or
      everything None) → ``(None, None)``: the curve is too short to
      define a jump at all;
    * two or more detected points but no POSITIVE jump (flat or
      monotone non-increasing) → ``(None, 0.0)``: a well-defined curve
      with no cliff on it;
    * otherwise ``(dose, jump)`` at the largest consecutive-detected
      jump — ties on the jump resolve to the LARGER dose (``max`` over
      ``(jump, dose)``), the rule the bisection driver's keep-upper
      tie-break mirrors.
    """
    pts = [(c, t) for c, t in curve if t is not None]
    if len(pts) < 2:
        return None, None
    jump, at = max((t2 - t1, c2) for (_, t1), (c2, t2) in zip(pts, pts[1:]))
    if jump <= 0:
        return None, 0.0
    return at, jump


# -- adaptive cliff search (r19) ----------------------------------------------


def dose_mask_table(
    n: int, victims: Sequence[int], max_dose: int, churn_seed: int
) -> np.ndarray:
    """``up[max_dose + 1, N]`` — EVERY dose's churn mask at 1-dose
    resolution, drawn by the EXACT sequential rng rule of
    :func:`churn_dose_masks` over the full ladder ``0..max_dose``.  This
    is the shared response-function table: the adaptive driver and its
    dense A/B baseline both INDEX it (a mask is a function of the dose
    alone once the table is fixed), so they measure the same surface
    point for point and "identical cliff coordinates" is a claim about
    the search, not about mask luck.  Host-side and cheap: the full
    1-dose table at n=4096, max_dose=128 is half a megabyte."""
    return churn_dose_masks(n, victims, list(range(max_dose + 1)), churn_seed)


def _points_plan(masks: np.ndarray, points: Sequence[tuple]) -> FaultPlan:
    """A stacked plan for explicit ``(dose, loss)`` points — always the
    same two legs (``base_up``, ``drop_rate``), so every dispatch of the
    driver has the identical plan STRUCTURE and avals: value-only swaps
    through one compiled fleet program."""
    return chaos.stack_plans([
        FaultPlan(
            base_up=jnp.asarray(masks[d]),
            drop_rate=jnp.asarray(np.float32(l)),
        )
        for d, l in points
    ])


class _CliffRunner:
    """Dispatch harness for the adaptive search: a FIXED-width fleet
    (width = the compiled batch size, in replica SLOTS) evaluated
    repeatedly with value-only (plan, seed) swaps —
    ``MonteCarlo.reset_states`` keeps the instance's jit/AOT warm state,
    so the program compiles ONCE and each refinement round costs one
    dispatch.  Each (dose, loss) point occupies ``seeds_per_point``
    slots (seeds ``base_seed + dose·S + j`` — distinct per (dose,
    replica), shared across loss rows like ``grid_seeds``); its value is
    the MEDIAN first-detection tick over those replicas, which is what
    makes "the cliff" a property of the surface rather than of one
    seed's luck (the Ising-ensemble move; ``seeds_per_point=1`` is the
    r12 single-seed pairing).  Short rounds pad by repeating their last
    point; padding costs dispatch slots (reported in ``slots``) but no
    new scenario-evaluations (the ``cache`` is the unique-evaluation
    ledger, in replica-slots: points × seeds_per_point)."""

    def __init__(self, params, victims, masks, width, *, base_seed,
                 max_ticks, check_every, aot, seeds_per_point=1):
        if width % seeds_per_point:
            raise ValueError(
                f"width {width} must be a multiple of seeds_per_point "
                f"{seeds_per_point}"
            )
        self.params, self.victims, self.masks = params, victims, masks
        self.width, self.base_seed = width, base_seed
        self.max_ticks, self.check_every = max_ticks, check_every
        self.aot = aot
        self.spp = seeds_per_point
        self.mc: Optional[MonteCarlo] = None
        self.dispatches = 0
        self.slots = 0
        self.cache: dict[tuple, Optional[float]] = {}

    def eval(self, points: Sequence[tuple]) -> dict:
        todo = [p for p in dict.fromkeys(points) if p not in self.cache]
        per = self.width // self.spp
        while todo:
            chunk, todo = todo[:per], todo[per:]
            batch = chunk + [chunk[-1]] * (per - len(chunk))
            slots = [(pt, j) for pt in batch for j in range(self.spp)]
            seeds = [self.base_seed + d * self.spp + j for (d, _), j in slots]
            if self.mc is None:
                self.mc = MonteCarlo(self.params, seeds, aot=self.aot)
            else:
                self.mc.reset_states(seeds)
            ticks, det = self.mc.run_until_detected(
                self.victims,
                _points_plan(self.masks, [pt for pt, _ in slots]),
                max_ticks=self.max_ticks, check_every=self.check_every,
            )
            self.dispatches += 1
            self.slots += self.width
            for i, pt in enumerate(batch):
                reps = [
                    (float(t) if d else None)
                    for t, d in zip(
                        ticks[i * self.spp:(i + 1) * self.spp],
                        det[i * self.spp:(i + 1) * self.spp],
                    )
                ]
                if pt not in self.cache:
                    if all(r is None for r in reps):
                        self.cache[pt] = None
                    else:
                        self.cache[pt] = float(np.median([
                            self.max_ticks if r is None else r for r in reps
                        ]))
        return {p: self.cache[p] for p in points}

    def result_fields(self) -> dict:
        aot_info = (
            next(iter(self.mc.aot_info.values()), {}) if self.mc is not None
            and self.aot is not None else {}
        )
        return {
            "evals_unique": len(self.cache) * self.spp,
            "evals_dispatched": self.slots,
            "dispatches": self.dispatches,
            "width": self.width,
            "seeds_per_point": self.spp,
            "all_detected": all(v is not None for v in self.cache.values()),
            "compiled_programs": (
                len(self.mc._aot_calls) if self.mc is not None and
                self.aot is not None else None
            ),
            "aot": aot_info,
        }


def refine_surface(
    params: LifecycleParams,
    *,
    victims: Sequence[int],
    losses: Sequence[float],
    max_dose: int,
    coarse: int = 9,
    base_seed: int = 0,
    churn_seed: int = 1234,
    max_ticks: int = 4096,
    check_every: int = 1,
    aot: Optional[str] = None,
    masks: Optional[np.ndarray] = None,
    cells_per_row: int = 2,
    verify_window: int = 2,
    seeds_per_point: int = 1,
) -> dict:
    """Adaptive cliff search: locate each loss row's dose cliff at
    1-dose resolution in O(log max_dose) fleet dispatches instead of a
    dense grid.

    A COARSE pass (``coarse`` evenly spaced doses per row, one fleet
    dispatch) ranks each row's cells by first-detection jump; the top
    ``cells_per_row`` are candidates (detection noise can put two
    near-equal jumps in different cells).  Then an outer host loop
    BISECTS only those cells: each round evaluates every active cell's
    midpoint (all rows and cells share one dispatch; finished rows
    pad), keeps the half with the larger jump (ties keep the upper
    half, mirroring ``locate_cliff``), and stops at width 1.  A final
    VERIFY dispatch evaluates the ±``verify_window`` 1-dose
    neighborhood of every candidate, and the row's answer is the
    largest jump over ADJACENT evaluated dose pairs — the exact
    quantity the dense grid maximizes, so on a surface with a dominant
    cliff the two coincide (the fleet_scale A/B asserts it).  The fleet
    program is compiled ONCE: every dispatch is a value-only (plan,
    seed) swap at fixed batch width (``_CliffRunner``), so refinement
    costs dispatches, not compiles.

    Rows whose coarse curve has fewer than two detected points report
    ``(None, None)``; rows with no positive jump report ``(None, 0.0)``
    — the :func:`locate_cliff` contract.  Undetected points inside an
    active cell count as ``max_ticks`` for jump arithmetic (operationally
    "at least"); ``all_detected`` in the result says whether that ever
    happened.

    Returns ``{"cliffs": {loss: {"cliff_at", "jump", "cell"}},
    "points": {loss: [(dose, tick-or-None), ...]}}`` plus the dispatch
    ledger (``evals_unique``/``evals_dispatched``/``dispatches``/
    ``width``) the dense A/B compares against."""
    if coarse < 3:
        raise ValueError(f"coarse={coarse}: need at least 3 coarse doses")
    if max_dose < 2:
        raise ValueError(f"max_dose={max_dose}: nothing to refine")
    losses = tuple(float(l) for l in losses)
    if masks is None:
        masks = dose_mask_table(params.n, victims, max_dose, churn_seed)
    coarse_doses = sorted({
        int(round(i * max_dose / (coarse - 1))) for i in range(coarse)
    })
    runner = _CliffRunner(
        params, victims, masks,
        width=len(coarse_doses) * len(losses) * seeds_per_point,
        base_seed=base_seed, max_ticks=max_ticks, check_every=check_every,
        aot=aot, seeds_per_point=seeds_per_point,
    )
    got = runner.eval([(d, l) for l in losses for d in coarse_doses])

    def t_of(d, l):
        v = runner.cache[(d, l)]
        return max_ticks if v is None else v

    # per row: the top-`cells_per_row` steepest coarse cells (noise can
    # put two near-equal jumps in different cells — refining only the
    # winner would crown whichever the stride happened to flatter)
    cells: dict[float, list[tuple[int, int]]] = {}
    cliffs: dict = {}
    for l in losses:
        curve = [(d, got[(d, l)]) for d in coarse_doses]
        det = [(d, t) for d, t in curve if t is not None]
        if len(det) < 2:
            cliffs[l] = {"cliff_at": None, "jump": None, "cell": None}
            cells[l] = []
            continue
        ranked = sorted(
            ((t2 - t1, d1, d2) for (d1, t1), (d2, t2) in zip(det, det[1:])),
            reverse=True,
        )
        if ranked[0][0] <= 0:
            cliffs[l] = {"cliff_at": None, "jump": 0.0, "cell": None}
            cells[l] = []
            continue
        cells[l] = [
            (d1, d2) for jump, d1, d2 in ranked[:cells_per_row] if jump > 0
        ]
    while True:
        active = [
            (l, i) for l, cs in cells.items()
            for i, (lo, hi) in enumerate(cs) if hi - lo > 1
        ]
        if not active:
            break
        mids = []
        for l, i in active:
            lo, hi = cells[l][i]
            mids.append(((lo + hi) // 2, l))
        runner.eval(mids)
        for l, i in active:
            lo, hi = cells[l][i]
            m = (lo + hi) // 2
            jl = t_of(m, l) - t_of(lo, l)
            jh = t_of(hi, l) - t_of(m, l)
            # keep the half with the larger jump; ties keep the UPPER
            # half (locate_cliff's larger-dose tie-break)
            cells[l][i] = (m, hi) if jh >= jl else (lo, m)
    # verify pass: a ±verify_window 1-dose neighborhood around every
    # refined candidate, so the final answer rests on adjacent PAIRS,
    # not on which path the bisection took
    extra = []
    for l, cs in cells.items():
        for lo, hi in cs:
            for d in range(hi - 1 - verify_window, hi + 1 + verify_window):
                if 0 <= d <= max_dose:
                    extra.append((d, l))
    if extra:
        runner.eval(extra)
    # final rule per row: the largest jump over ADJACENT evaluated dose
    # pairs — the exact quantity the dense grid maximizes, restricted to
    # the points the search visited (ties to the larger dose, the
    # locate_cliff tie-break)
    for l in losses:
        if not cells[l]:
            continue
        evald = sorted(d for (d, ll) in runner.cache if ll == l)
        pairs = [
            (t_of(d2, l) - t_of(d1, l), d2)
            for d1, d2 in zip(evald, evald[1:]) if d2 == d1 + 1
        ]
        jump, at = max(pairs)
        if jump <= 0:
            # every adjacent evaluated pair is flat or decreasing: the
            # coarse-stride jump that elected this cell did not survive
            # 1-dose resolution — the locate_cliff no-cliff contract
            cliffs[l] = {"cliff_at": None, "jump": 0.0, "cell": None}
            continue
        cell = next(
            ([lo, hi] for lo, hi in cells[l] if hi == at), [at - 1, at]
        )
        cliffs[l] = {"cliff_at": at, "jump": jump, "cell": cell}
    points = {
        l: sorted((d, t) for (d, ll), t in runner.cache.items() if ll == l)
        for l in losses
    }
    return {
        "losses": list(losses),
        "max_dose": max_dose,
        "coarse_doses": coarse_doses,
        "cliffs": cliffs,
        "points": points,
        **runner.result_fields(),
    }


def dense_surface(
    params: LifecycleParams,
    *,
    victims: Sequence[int],
    losses: Sequence[float],
    max_dose: int,
    base_seed: int = 0,
    churn_seed: int = 1234,
    max_ticks: int = 4096,
    check_every: int = 1,
    aot: Optional[str] = None,
    masks: Optional[np.ndarray] = None,
    width: Optional[int] = None,
    seeds_per_point: int = 1,
) -> dict:
    """The baseline :func:`refine_surface` replaces: EVERY dose
    ``0..max_dose`` of every loss row evaluated through the batched
    fleet (one dispatch, or chunks of ``width``), cliffs located by
    :func:`locate_cliff` on the full 1-dose curves.  Shares the
    ``dose_mask_table`` and the seed pairing with the adaptive driver,
    so the two measure the same response function — the fleet_scale A/B
    asserts identical cliff coordinates at a fraction of the
    scenario-evaluations."""
    losses = tuple(float(l) for l in losses)
    if masks is None:
        masks = dose_mask_table(params.n, victims, max_dose, churn_seed)
    points = [(d, l) for l in losses for d in range(max_dose + 1)]
    runner = _CliffRunner(
        params, victims, masks,
        width=width or len(points) * seeds_per_point,
        base_seed=base_seed, max_ticks=max_ticks, check_every=check_every,
        aot=aot, seeds_per_point=seeds_per_point,
    )
    got = runner.eval(points)
    cliffs = {}
    curves = {}
    for l in losses:
        curve = [(d, got[(d, l)]) for d in range(max_dose + 1)]
        curves[l] = curve
        at, jump = locate_cliff(curve)
        cliffs[l] = {"cliff_at": at, "jump": jump}
    return {
        "losses": list(losses),
        "max_dose": max_dose,
        "cliffs": cliffs,
        "curves": curves,
        **runner.result_fields(),
    }
