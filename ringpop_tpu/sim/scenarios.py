"""Scenario-grid compiler: parameter sweeps as ONE batched chaos program.

r10's chaos plane scores one FaultPlan per run; r12 made the plan a
batchable axis (``chaos.stack_plans`` + the Monte-Carlo fleet in
``sim/montecarlo.py``).  This module is the host-side compiler on top:
sweep a protocol-parameter grid — background-churn dose × packet loss ×
partition width (plan legs, batched), with suspicion timeout as a static
outer axis — into a stacked ``[B, ...]`` plan, run it through one
AOT-warm-started program, and reduce the results into 2-D response
surfaces.  The exemplar is the Ising-on-TPU-clusters treatment
(PAPERS.md, arXiv:1903.11714): million-replica parameter studies as one
dense program, compilation and dispatch amortized across the sweep.

Grid axes and where they live:

* **churn dose** — per-scenario background crash cohorts, drawn with
  EXACTLY the rng sequence ``montecarlo.detection_latency_under_churn``
  draws (``churn_dose_masks``), so the loss-0 row of the churn×loss
  surface is bit-identical to the committed ``mc_churn`` 1-D slice
  (SIMBENCH_r05: cliff at dose 107) — the surface extends the slice, it
  does not re-measure it.
* **loss** — the scalar ``drop_rate`` leg, batched ``[B]``.  A 0.0 rate
  is value-identical to no drop leg at all (the survival comparisons
  ``u >= 0.0`` / ``u < 1.0`` pass every leg and the engines' key splits
  don't depend on the drop leg), which is what lets one dense program
  cover the loss-free row too.
* **partition width** — optional symmetric split window (minority
  fraction per scenario; width 0 = no partition leg for that member).
* **suspicion timeout** — BATCHED since the topology round: the traced
  ``suspect_ticks`` plan leg (engines select the static param on the -1
  sentinel, so a member without the leg is bit-identical to the old
  static path) rides the ``suspects=`` grid axis inside one compiled
  program.  ``sweep_static`` remains for genuinely compile-time
  parameters.

* **topology overlays** — the ``overlays=`` axis merges
  ``sim/topology.py`` scenario plans (zone loss, switch flap, WAN
  partition, each with its rack/zone/region tier legs) into grid
  members, so correlated-failure families sweep through the same
  batched fleet.

The scored path (``scored_fleet``) carries the r7 telemetry counters
under the batch axis and reduces them per scenario with ONE device fetch
per journal block — ``chaos.score_blocks`` then turns each scenario's
block slice into a verdict with its grid coordinates attached.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim import chaos
from ringpop_tpu.sim.chaos import FaultPlan
from ringpop_tpu.sim.lifecycle import LifecycleParams
from ringpop_tpu.sim.montecarlo import MonteCarlo


# -- grid construction (host-side) --------------------------------------------


def mc_churn_doses(b_count: int, churn_max: int) -> list[int]:
    """The dose ladder ``detection_latency_under_churn`` uses: dose j =
    round(j/(B-1)·churn_max) — shared so the surface's churn axis cannot
    drift from the committed 1-D slice's."""
    return [round(b / max(b_count - 1, 1) * churn_max) for b in range(b_count)]


def churn_dose_masks(
    n: int, victims: Sequence[int], doses: Sequence[int], churn_seed: int
) -> np.ndarray:
    """``up[D, N]`` masks, one per dose: the study victims plus ``dose``
    background crashes.  The rng sequence is EXACTLY the one
    ``detection_latency_under_churn`` consumes (one ``choice`` per
    non-zero dose, in dose order), so dose j's mask here is bit-equal to
    replica j's mask there — the parity the loss-0 surface row rests on."""
    victims = sorted(int(v) for v in victims)
    rng = np.random.default_rng(churn_seed)
    candidates = np.setdiff1d(np.arange(n), np.asarray(victims, np.int64))
    up = np.ones((len(doses), n), bool)
    up[:, victims] = False
    for j, dose in enumerate(doses):
        if dose:
            down = rng.choice(candidates, size=int(dose), replace=False)
            up[j, down] = False
    return up


def scenario_grid(
    n: int,
    *,
    victims: Sequence[int],
    doses: Sequence[int],
    losses: Sequence[float] = (0.0,),
    parts: Sequence[float] = (0.0,),
    suspects: Sequence[Optional[int]] = (None,),
    overlays: Optional[Sequence[tuple[str, Optional[FaultPlan]]]] = None,
    churn_seed: int = 1234,
    part_from: int = 0,
    part_until: Optional[int] = None,
) -> tuple[FaultPlan, list[dict]]:
    """Compile a (overlay × suspicion-timeout × loss × part × churn-dose)
    grid into ONE stacked plan plus its meta table.

    Returns ``(plan, meta)``: ``plan`` is the ``[B, ...]`` stacked
    FaultPlan (B = the axis product, loss-major / dose-minor inside each
    overlay/timeout cell), ``meta[i]`` carries ``scenario_id``, the grid
    coordinates (``churn``/``loss``/``part``, plus ``suspect``/``overlay``
    when those axes are swept) and ``dose_index`` — callers seed scenario
    i with ``base_seed + dose_index`` so every row reuses the churn
    slice's (seed, dose) pairing.  Churn masks are drawn once per dose
    (``churn_dose_masks``) and shared across rows; a non-zero ``part``
    adds a symmetric split window ``[part_from, part_until)`` over the
    first ``part`` fraction of nodes.

    The two post-r12 axes:

    * ``suspects`` — the suspicion timeout, BATCHED: each value rides the
      traced ``suspect_ticks`` plan leg (None = the engine's static
      param, via the -1 stacked sentinel), so the timeout axis runs
      inside ONE compiled program where it used to be a static outer
      loop (``sweep_static`` remains for compile-time parameters proper).
    * ``overlays`` — ``(label, plan-or-None)`` pairs merged into every
      member: the topology axis (``sim/topology.py`` scenario plans —
      zone loss, switch flap, WAN partition, with their tier legs) or
      any other leg family the base grid doesn't set.  Leg collisions
      (e.g. an overlay partition against ``parts`` > 0) are refused
      loudly by ``chaos._merge_plans``.
    """
    masks = churn_dose_masks(n, victims, doses, churn_seed)
    plans, meta = [], []
    for olabel, overlay in (overlays if overlays is not None else ((None, None),)):
        for suspect in suspects:
            for loss in losses:
                for part in parts:
                    for j, dose in enumerate(doses):
                        legs = dict(
                            base_up=jnp.asarray(masks[j]),
                            drop_rate=jnp.asarray(np.float32(loss)),
                        )
                        if part > 0:
                            group = np.zeros(n, np.int32)
                            group[: int(part * n)] = 1
                            legs.update(
                                group=jnp.asarray(group),
                                part_from=jnp.asarray(np.int32(part_from)),
                                part_until=jnp.asarray(
                                    np.int32(part_until if part_until is not None else chaos.NO_TICK)
                                ),
                            )
                        if suspect is not None:
                            legs["suspect_ticks"] = jnp.asarray(
                                np.int32(suspect)
                            )
                        member = FaultPlan(**legs)
                        if overlay is not None:
                            member = chaos._merge_plans(member, overlay)
                        plans.append(member)
                        m = {
                            "scenario_id": len(meta),
                            "churn": int(dose),
                            "loss": float(loss),
                            "part": float(part),
                            "dose_index": j,
                        }
                        if tuple(suspects) != (None,):
                            m["suspect"] = None if suspect is None else int(suspect)
                        if overlays is not None:
                            m["overlay"] = olabel
                        meta.append(m)
    return chaos.stack_plans(plans), meta


def grid_seeds(meta: list[dict], base_seed: int) -> list[int]:
    """Per-scenario seeds reusing the 1-D churn slice's pairing: scenario
    i runs at ``base_seed + dose_index`` (every loss/part row replays the
    same seeds, so rows differ only in the swept parameter)."""
    return [base_seed + m["dose_index"] for m in meta]


def sweep_static(values: Sequence[int], run_fn) -> dict:
    """A static outer axis: ``run_fn(value)`` once per value — one
    compiled program each, everything else batched inside it.  Returns
    {value: result}.  The suspicion timeout no longer needs this (the
    traced ``suspect_ticks`` leg batches it — ``scenario_grid(suspects=
    ...)``); it stays for genuinely compile-time parameters (k, maxP,
    exchange flavor) and as the A/B baseline the traced-timeout tests
    pin against."""
    return {int(v): run_fn(int(v)) for v in values}


# -- fleet runners ------------------------------------------------------------


def detect_surface(
    params: LifecycleParams,
    plan: FaultPlan,
    seeds: Sequence[int],
    victims: Sequence[int],
    *,
    max_ticks: int = 4096,
    check_every: int = 1,
    aot: Optional[str] = None,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """First-detection ticks for every scenario of a stacked plan, in ONE
    dispatch of the fleet detection program (1-tick resolution by
    default, like the committed mc_churn slice).  Returns
    ``(ticks[B], detected[B], aot_info)`` — ``aot_info`` carries the
    front door's measured ``cache_hit``/``compile_s`` when a tag was
    given (``{}`` otherwise)."""
    mc = MonteCarlo(params, seeds, aot=aot)
    ticks, detected = mc.run_until_detected(
        victims, plan, max_ticks=max_ticks, check_every=check_every
    )
    return ticks, detected, next(iter(mc.aot_info.values()), {})


def sequential_detect(
    params: LifecycleParams,
    plan: FaultPlan,
    seeds: Sequence[int],
    victims: Sequence[int],
    *,
    max_ticks: int = 4096,
    check_every: int = 1,
    fresh_compile: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """The baseline the fleet replaces: B sequential solo runs, one per
    scenario — one compile + one dispatch per grid point.
    ``fresh_compile=True`` clears the jit caches between runs so the
    measurement prices that workflow honestly inside one process (each
    grid point of the pre-fleet sweep was its own bench invocation and
    paid its own trace+compile); False prices the best-case warm-cache
    sequential loop instead.  Both are reported by ``simbench mc_chaos``."""
    ticks = np.full(len(seeds), -1, np.int64)
    detected = np.zeros(len(seeds), bool)
    for b, seed in enumerate(seeds):
        if fresh_compile:
            jax.clear_caches()
        mc = MonteCarlo(params, [seed])
        t, d = mc.run_until_detected(
            victims,
            chaos.index_plan(plan, b),
            max_ticks=max_ticks,
            check_every=check_every,
        )
        ticks[b], detected[b] = int(t[0]), bool(d[0])
    return ticks, detected


def scored_fleet(
    params: LifecycleParams,
    plan: FaultPlan,
    meta: list[dict],
    seeds: Sequence[int],
    *,
    horizon: int,
    journal_every: int = 16,
    sink=None,
    scenario: str = "mc_chaos",
) -> list[dict]:
    """Run the fleet for ``horizon`` ticks with the telemetry counters
    accumulated under the batch axis, journal one block record per
    (scenario, block) — ONE device fetch per block for ALL scenarios —
    and reduce each scenario's journal slice into a ``chaos.score_blocks``
    verdict carrying its grid coordinates.  ``sink`` (a
    ``telemetry.TelemetrySink`` or None) receives every per-scenario
    block record and, when it journals, every score record."""
    # a topology-carrying plan arms the per-tier suspicion counters, so
    # its verdicts get the per-tier ttd/false-positive breakdowns
    mc = MonteCarlo(
        params, seeds, telemetry=True,
        telemetry_tiers=plan.tier_ids is not None,
    )
    blocks: list[list[dict]] = [[] for _ in meta]
    ticks_left = horizon
    while ticks_left > 0:
        # exactly ``horizon`` ticks: full journal blocks plus one short
        # remainder block (its own compile of the static-ticks program)
        # when journal_every does not divide the horizon
        mc.run(min(journal_every, ticks_left), plan)
        ticks_left -= min(journal_every, ticks_left)
        for rec in mc.fetch_telemetry(plan):
            blocks[rec["scenario_id"]].append(rec)
            if sink is not None:
                sink(rec)
    scores = []
    for b, m in enumerate(meta):
        sc = chaos.score_blocks(
            blocks[b],
            chaos.index_plan(plan, b),
            n=params.n,
            scenario=scenario,
            scenario_id=b,
        )
        sc.update({k: v for k, v in m.items() if k != "scenario_id"})
        scores.append(sc)
        if sink is not None and getattr(sink, "journal", None) is not None:
            sink.journal.score(sc)
    return scores


# -- surface reduction --------------------------------------------------------


def response_surface(
    meta: list[dict],
    values: Sequence,
    *,
    rows: str = "loss",
    cols: str = "churn",
) -> dict:
    """Reduce per-scenario values into a 2-D response surface keyed by
    two grid axes.  Cells with several scenarios (a third axis collapsed)
    take the median of their non-null values; cells where every value is
    null stay null.  Returns ``{"row_axis", "rows", "col_axis", "cols",
    "cells"}`` with ``cells[i][j]`` the value at (rows[i], cols[j])."""
    row_vals = sorted({m[rows] for m in meta})
    col_vals = sorted({m[cols] for m in meta})
    buckets: dict[tuple, list] = {}
    for m, v in zip(meta, values):
        buckets.setdefault((m[rows], m[cols]), []).append(v)
    cells = []
    for r in row_vals:
        row = []
        for c in col_vals:
            got = [v for v in buckets.get((r, c), []) if v is not None]
            row.append(float(np.median(got)) if got else None)
        cells.append(row)
    return {
        "row_axis": rows,
        "rows": row_vals,
        "col_axis": cols,
        "cols": col_vals,
        "cells": cells,
    }


def locate_cliff(curve: Sequence[tuple]) -> tuple[Optional[int], Optional[float]]:
    """The dose at the largest jump between consecutive detected points
    of a dose-response curve (the mc_churn cliff finder, factored here so
    the 1-D slice and every surface row share one definition).  Takes
    ``[(dose, ticks-or-None), ...]``; returns ``(cliff_at, jump)`` or
    ``(None, None)`` when fewer than two points detected."""
    pts = [(c, t) for c, t in curve if t is not None]
    if len(pts) < 2:
        return None, None
    jump, at = max((t2 - t1, c2) for (_, t1), (c2, t2) in zip(pts, pts[1:]))
    return at, jump
