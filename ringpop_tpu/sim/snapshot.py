"""Checkpoint / resume for cluster state.

The reference has NO checkpointing — membership is soft state rebuilt from
the network on every boot (``swim/join_handler.go:69-75``; incarnations are
wall-clock ms so reborn nodes self-supersede, ``swim/memberlist.go:235``).
Because the sim plane holds an entire simulated cluster as one pytree of
dense arrays, snapshotting it is nearly free — a capability the reference
architecture cannot offer (SURVEY §5).  A 1M-node lifecycle state is a
handful of ``np.savez``-compressed arrays; save/restore round-trips
bit-exactly, including the PRNG key, so a resumed run continues the exact
trajectory of the original.

Host-plane membership can also be exported/imported as a change list in the
reference's own wire schema (``disseminator.go:107-123``
MembershipAsChanges), which doubles as a warm-boot list: a restarted node
can apply the snapshot before gossiping, then let newer incarnations
supersede stale entries — the same lattice rules make stale snapshots safe.
"""

from __future__ import annotations

import json
from typing import Type, TypeVar

import numpy as np

T = TypeVar("T", bound=tuple)

_MAGIC = "ringpop_tpu-snapshot-v1"


def save_state(path: str, state) -> None:
    """Write any engine state (a NamedTuple of arrays) to ``path`` (.npz).
    Works for DeltaState, FullViewState and LifecycleState alike."""
    arrays = {f: np.asarray(v) for f, v in zip(state._fields, state)}
    meta = json.dumps(
        {"magic": _MAGIC, "type": type(state).__name__, "fields": list(state._fields)}
    )
    np.savez_compressed(path, __meta__=np.frombuffer(meta.encode(), dtype=np.uint8), **arrays)


def load_state(path: str, cls: Type[T]) -> T:
    """Load a snapshot written by :func:`save_state` back into ``cls``.
    Validates the engine type and field list before reconstructing."""
    import jax.numpy as jnp

    with np.load(path) as data:
        if "__meta__" not in data.files:
            raise ValueError(f"{path}: not a ringpop_tpu snapshot")
        meta = json.loads(bytes(data["__meta__"]).decode())
        if meta.get("magic") != _MAGIC:
            raise ValueError(f"{path}: not a ringpop_tpu snapshot")
        if meta["type"] != cls.__name__:
            raise ValueError(
                f"{path}: snapshot holds {meta['type']}, asked to load {cls.__name__}"
            )
        if list(meta["fields"]) != list(cls._fields):
            raise ValueError(
                f"{path}: field mismatch {meta['fields']} != {list(cls._fields)}"
            )
        return cls(**{f: jnp.asarray(data[f]) for f in cls._fields})


# -- host-plane membership export/import -------------------------------------


def export_membership(memberlist, path: str | None = None) -> list[dict]:
    """Serialize a host-plane memberlist as a wire-schema change list
    (the same JSON shape joins/full-syncs ship; ``member.go`` JSON tags)."""
    from ringpop_tpu.swim.member import member_to_change

    local = memberlist.local
    addr = local.address if local else ""
    inc = local.incarnation if local else 0
    changes = [
        member_to_change(m, source=addr, source_inc=inc).to_wire()
        for m in memberlist.get_members()
    ]
    if path is not None:
        with open(path, "w") as f:
            json.dump(changes, f)
    return changes


def import_membership(memberlist, source: str | list[dict]) -> int:
    """Apply an exported change list to a memberlist (warm boot).  Entries
    older than what the node already knows are discarded by the normal
    override rules, so stale snapshots are harmless.  Returns the number of
    changes that applied."""
    from ringpop_tpu.swim.member import Change

    if isinstance(source, str):
        with open(source) as f:
            data = json.load(f)
    else:
        data = source
    applied = memberlist.update([Change.from_wire(d) for d in data])
    return len(applied)
