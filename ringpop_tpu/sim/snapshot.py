"""Checkpoint / resume for cluster state.

The reference has NO checkpointing — membership is soft state rebuilt from
the network on every boot (``swim/join_handler.go:69-75``; incarnations are
wall-clock ms so reborn nodes self-supersede, ``swim/memberlist.go:235``).
Because the sim plane holds an entire simulated cluster as one pytree of
dense arrays, snapshotting it is nearly free — a capability the reference
architecture cannot offer (SURVEY §5).  A 1M-node lifecycle state is a
handful of ``np.savez``-compressed arrays; save/restore round-trips
bit-exactly, including the PRNG key, so a resumed run continues the exact
trajectory of the original.

Host-plane membership can also be exported/imported as a change list in the
reference's own wire schema (``disseminator.go:107-123``
MembershipAsChanges), which doubles as a warm-boot list: a restarted node
can apply the snapshot before gossiping, then let newer incarnations
supersede stale entries — the same lattice rules make stale snapshots safe.
"""

from __future__ import annotations

import json
from typing import Type, TypeVar

import numpy as np

T = TypeVar("T", bound=tuple)

_MAGIC = "ringpop_tpu-snapshot-v1"


def save_state(path: str, state, params=None) -> None:
    """Write any engine state (a NamedTuple of arrays) to ``path`` (.npz).
    Works for DeltaState, FullViewState and LifecycleState alike.

    Pass the run's ``params`` when the engine has a dissemination bound
    (delta/lifecycle): the resolved ``max_p`` is persisted in the snapshot
    meta, so a later :func:`load_state` migration can rebuild derived
    planes without guessing the bound (a custom ``max_p`` run restored
    with the default bound would get a silently wrong ride gate)."""
    arrays = {f: np.asarray(v) for f, v in zip(state._fields, state)}
    meta_dict = {
        "magic": _MAGIC,
        "type": type(state).__name__,
        "fields": list(state._fields),
    }
    if params is not None and hasattr(params, "p_factor"):
        from ringpop_tpu.sim.delta import clamped_max_p

        meta_dict["max_p"] = int(clamped_max_p(params))
    meta = json.dumps(meta_dict)
    np.savez_compressed(path, __meta__=np.frombuffer(meta.encode(), dtype=np.uint8), **arrays)


def load_state(path: str, cls: Type[T], params=None) -> T:
    """Load a snapshot written by :func:`save_state` back into ``cls``.
    Validates the engine type and field list before reconstructing.

    Migration: snapshots written before the round-3 packed engines carry no
    ``ride_ok`` plane.  Since it is derived state (== ``pack_bool(pcount <
    clamped_max_p)``), it is reconstructed here instead of refusing the
    load.  Pass the run's ``params`` when the snapshot was taken with a
    non-default ``p_factor``/``max_p`` — without it the default SWIM bound
    for the snapshot's n is assumed."""
    import jax.numpy as jnp

    with np.load(path) as data:
        if "__meta__" not in data.files:
            raise ValueError(f"{path}: not a ringpop_tpu snapshot")
        meta = json.loads(bytes(data["__meta__"]).decode())
        if meta.get("magic") != _MAGIC:
            raise ValueError(f"{path}: not a ringpop_tpu snapshot")
        if meta["type"] != cls.__name__:
            raise ValueError(
                f"{path}: snapshot holds {meta['type']}, asked to load {cls.__name__}"
            )
        saved = list(meta["fields"])
        want = list(cls._fields)
        migrate_ride = saved != want and [f for f in want if f != "ride_ok"] == saved
        if saved != want and not migrate_ride:
            raise ValueError(f"{path}: field mismatch {saved} != {want}")
        out = {f: jnp.asarray(data[f]) for f in saved}
        if migrate_ride:
            import warnings

            from ringpop_tpu.sim.delta import (
                INT8_SAFE_MAX_P,
                clamped_max_p,
                resolve_max_p,
            )
            from ringpop_tpu.sim.packbits import n_words, pack_bool

            # pre-packing snapshots stored the boolean planes unpacked
            # (bool[N, K]); the packed engines expect uint32[N, ceil(K/32)].
            # Pack them here — loading them raw would shape-error for k>32
            # and, worse, silently broadcast-corrupt the k<=32 case.
            for f in ("learned",):
                if f in out and out[f].dtype == bool:
                    out[f] = pack_bool(out[f])
            if params is not None:
                max_p = clamped_max_p(params)
            elif "max_p" in meta:
                max_p = int(meta["max_p"])
            else:
                n = out["pcount"].shape[0]
                max_p = min(resolve_max_p(n, 15, None), INT8_SAFE_MAX_P)
                warnings.warn(
                    f"{path}: migrating a pre-ride_ok snapshot without params; "
                    f"assuming the default dissemination bound max_p={max_p} "
                    f"for n={n} — pass the run's params if it used a custom "
                    "p_factor/max_p, or the rebuilt ride gate will be wrong",
                    stacklevel=2,
                )
            out["ride_ok"] = pack_bool(out["pcount"] < np.int8(max_p))
            # post-migration structural check: every packed plane must now be
            # word-typed with ceil(K/32) words for pcount's K (the class
            # annotations carry no dtypes, so validate the invariant directly)
            n, k = out["pcount"].shape
            for f in ("learned", "ride_ok"):
                if f in out and (
                    out[f].dtype != np.uint32 or out[f].shape != (n, n_words(k))
                ):
                    raise ValueError(
                        f"{path}: migrated field {f!r} is "
                        f"{out[f].shape}/{out[f].dtype}, expected "
                        f"({n}, {n_words(k)})/uint32"
                    )
        return cls(**out)


# -- orbax backend (optional): async, non-blocking saves ---------------------


def _orbax_mp_options() -> dict:
    """Checkpointer kwargs that make orbax's save/restore barriers work on
    EVERY jax.distributed fabric, not just ones whose backend can run
    cross-process XLA programs.

    Orbax's default multiprocess sync is ``multihost_utils
    .sync_global_devices`` — an XLA psum, which this container's CPU
    backend refuses ("Multiprocess computations aren't implemented").
    Passing an explicit ``active_processes`` set routes every barrier
    through the coordination-service client instead
    (``client.wait_at_barrier`` — plain gRPC), which is also what a
    real pod wants: checkpoint barriers should not occupy the accelerator
    stream.  No-op single-process."""
    import jax

    if jax.process_count() <= 1:
        return {}
    import orbax.checkpoint as ocp

    return {
        "multiprocessing_options": ocp.options.MultiprocessingOptions(
            active_processes=set(range(jax.process_count()))
        )
    }


def save_state_orbax(path: str, state, wait: bool = False, checkpointer=None):
    """Checkpoint via orbax's AsyncCheckpointer: the device→host transfer
    happens synchronously but serialization/IO proceed in a background
    thread, so a long-running sim can keep stepping while the snapshot
    writes (the npz path above blocks ~seconds at 100k+ nodes).

    Pass ``checkpointer`` to reuse one AsyncCheckpointer across periodic
    snapshots (orbax's intended pattern); the caller then owns its
    lifecycle.  Without it, one is constructed here: with ``wait=True``
    the write completes and the checkpointer closes before returning
    (returns None); otherwise the returned checkpointer is the caller's to
    ``.wait_until_finished()`` and ``.close()``.  Construction never leaks
    on failure.  ``path`` must be a directory path (orbax layout).

    BLOCK-SHARDED (r14): state leaves may be process-spanning sharded
    ``jax.Array``s — each process transfers and writes ONLY its
    addressable shards (orbax OCDBT/tensorstore layout), so a 16M-node
    state checkpoints without any host ever materializing a global plane;
    barriers ride the coordination service (:func:`_orbax_mp_options`).
    Restore with :func:`load_state_orbax` ``shardings=`` onto ANY process
    count — the chunked store reads back under a different partition."""
    import os

    import orbax.checkpoint as ocp

    own = checkpointer is None
    ckptr = checkpointer if checkpointer is not None else ocp.AsyncCheckpointer(
        ocp.StandardCheckpointHandler(), **_orbax_mp_options()
    )
    try:
        ckptr.save(
            os.path.abspath(path),
            args=ocp.args.StandardSave(state._asdict()),
            force=True,
        )
        if wait:
            ckptr.wait_until_finished()
    except BaseException:
        if own:
            ckptr.close()
        raise
    if wait and own:
        ckptr.close()
        return None
    return ckptr


def load_state_orbax(path: str, example: T, shardings=None) -> T:
    """Restore a :func:`save_state_orbax` checkpoint into ``type(example)``,
    using ``example`` (any state of the right shapes/dtypes — arrays or
    ``jax.ShapeDtypeStruct``s, e.g. a fresh ``init_state``) as the
    abstract restore target.  Validation is structural: the stored tree
    must match the example's field names (orbax raises) and each array's
    shape/dtype (checked explicitly below).

    ``shardings`` (optional): a matching pytree of ``NamedSharding`` —
    each leaf restores as a sharded ``jax.Array`` with every process
    reading ONLY its own shards from the chunked store.  Because the
    target sharding is independent of the sharding at save time, this is
    how a 2-process checkpoint restores onto 4 processes (and vice
    versa): the partition table (``parallel.partition``) names the
    layout, orbax re-chunks the reads."""
    import os

    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    cls = type(example)
    sh = dict(zip(example._fields, shardings)) if shardings is not None else {}
    target = {
        f: jax.ShapeDtypeStruct(np.shape(v), v.dtype, sharding=sh.get(f))
        for f, v in zip(example._fields, example)
    }
    with ocp.Checkpointer(
        ocp.StandardCheckpointHandler(), **_orbax_mp_options()
    ) as ckptr:
        data = ckptr.restore(os.path.abspath(path), args=ocp.args.StandardRestore(target))
    # NOT dead code: this orbax version's StandardRestore was observed to
    # restore a checkpoint whose shapes differ from the target without
    # raising (tests/test_snapshot.py::test_orbax_shape_mismatch_raises
    # fails "DID NOT RAISE" without this loop) — validate explicitly.
    for f, want in target.items():
        got = data[f]
        if np.shape(got) != want.shape or got.dtype != want.dtype:
            raise ValueError(
                f"{path}: field {f!r} is {np.shape(got)}/{np.asarray(got).dtype}, "
                f"expected {want.shape}/{want.dtype} — wrong engine config?"
            )
    # orbax restores sharding-less targets as np.ndarray; convert so the
    # result behaves like every other state (e.g. .at[] updates).  Sharded
    # restores already ARE jax.Arrays — converting one would gather a
    # process-spanning plane onto every host, exactly what the sharded
    # path exists to avoid.
    return cls(
        **{
            f: (v if isinstance(v, jax.Array) else jnp.asarray(v))
            for f, v in data.items()
        }
    )


# -- fleet carry checkpoints (r19) -------------------------------------------
#
# ``save_state_orbax``/``load_state_orbax`` above take ONE flat NamedTuple
# state.  The scenario fleet's resumable unit is a nested CARRY — batched
# engine state + batched telemetry counters + per-replica first-detection
# ticks + sweep progress — so these two generalize the same orbax
# machinery to any pytree: leaves are stored under "/"-joined tree-path
# names (stable across processes by construction — same structure), each
# process writes/reads ONLY its shards (``_orbax_mp_options`` barriers),
# and the restore target's shardings are independent of the save-time
# partition, which is how a 2-process sweep checkpoint restores onto 1 or
# 4 processes (``parallel.partition.fleet_shard_put`` names the layout).


def _flatten_named(tree) -> dict:
    """Pytree -> flat {path-name: leaf} dict in ``jax.tree`` leaf order
    (None legs are structure, not leaves — they round-trip through the
    example's treedef, not the store).  Names join with "." — "/" is
    orbax/tensorstore's own path separator."""
    import jax

    from ringpop_tpu.parallel.partition import _path_name

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        name = _path_name(path).replace("/", ".")
        if name in out:
            raise ValueError(f"carry flattens to duplicate leaf name {name!r}")
        out[name] = leaf
    return out


def save_carry_orbax(path: str, carry) -> None:
    """Checkpoint an arbitrary pytree carry (the fleet's states +
    telemetry + detection freeze) via orbax, each process writing ONLY
    its addressable shards.  Synchronous — the fleet sweep checkpoints
    at block boundaries and the kill-and-restore certificate needs the
    write complete before the run may die."""
    import os

    import orbax.checkpoint as ocp

    with ocp.Checkpointer(
        ocp.StandardCheckpointHandler(), **_orbax_mp_options()
    ) as ckptr:
        ckptr.save(
            os.path.abspath(path),
            args=ocp.args.StandardSave(_flatten_named(carry)),
            force=True,
        )


def load_carry_orbax(path: str, example, shardings=None):
    """Restore a :func:`save_carry_orbax` checkpoint into the structure
    of ``example`` (arrays or ShapeDtypeStructs).  ``shardings`` — an
    optional MATCHING pytree of NamedSharding — restores each leaf as a
    sharded ``jax.Array`` with every process reading only its own
    shards; because the target sharding is independent of the sharding
    at save time, this is how a sweep killed at P processes resumes at
    P' (the fleet_scale certificate).  Shape/dtype validated explicitly
    (same orbax caveat as :func:`load_state_orbax`)."""
    import os

    import jax
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    flat_ex = _flatten_named(example)
    flat_sh = _flatten_named(shardings) if shardings is not None else {}
    if flat_sh and sorted(flat_sh) != sorted(flat_ex):
        raise ValueError(
            "shardings tree does not match the example carry: "
            f"{sorted(flat_sh)} vs {sorted(flat_ex)}"
        )
    target = {
        name: jax.ShapeDtypeStruct(
            np.shape(v), v.dtype, sharding=flat_sh.get(name)
        )
        for name, v in flat_ex.items()
    }
    with ocp.Checkpointer(
        ocp.StandardCheckpointHandler(), **_orbax_mp_options()
    ) as ckptr:
        data = ckptr.restore(
            os.path.abspath(path), args=ocp.args.StandardRestore(target)
        )
    for name, want in target.items():
        got = data[name]
        if np.shape(got) != want.shape or got.dtype != want.dtype:
            # got.dtype, never np.asarray(got): a process-spanning shard
            # cannot materialize on one host and the diagnostic must not
            # die trying
            raise ValueError(
                f"{path}: carry leaf {name!r} is "
                f"{np.shape(got)}/{got.dtype}, expected "
                f"{want.shape}/{want.dtype} — wrong fleet config?"
            )
    leaves = [
        (v if isinstance(v, jax.Array) else jnp.asarray(v))
        for v in (data[name] for name in flat_ex)
    ]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(example), leaves
    )


# -- host-plane membership export/import -------------------------------------


def export_membership(memberlist, path: str | None = None) -> list[dict]:
    """Serialize a host-plane memberlist as a wire-schema change list
    (the same JSON shape joins/full-syncs ship; ``member.go`` JSON tags)."""
    from ringpop_tpu.swim.member import member_to_change

    local = memberlist.local
    addr = local.address if local else ""
    inc = local.incarnation if local else 0
    changes = [
        member_to_change(m, source=addr, source_inc=inc).to_wire()
        for m in memberlist.get_members()
    ]
    if path is not None:
        with open(path, "w") as f:
            json.dump(changes, f)
    return changes


def import_membership(memberlist, source: str | list[dict]) -> int:
    """Apply an exported change list to a memberlist (warm boot).  Entries
    older than what the node already knows are discarded by the normal
    override rules, so stale snapshots are harmless.  Returns the number of
    changes that applied."""
    from ringpop_tpu.swim.member import Change

    if isinstance(source, str):
        with open(source) as f:
            data = json.load(f)
    else:
        data = source
    applied = memberlist.update([Change.from_wire(d) for d in data])
    return len(applied)
