"""Device-resident telemetry plane for the sim engines.

The host plane mirrors ringpop-go's stats surface (``swim/stats.py``,
``events/``, the CLI reporters); the sim plane — where the 1M-node
headline lives — was a black box: a ``_run_block`` scan emits nothing
until it returns.  This module gives it the Ising-on-TPU treatment
(PAPERS.md): carry cheap on-device reductions *through* the scan and
fetch them in amortized blocks, so observability costs no host
round-trips and, under a device mesh, no per-tick collectives.

Design rules (these are what the acceptance tests pin):

* **Bit-identity.** Telemetry only *reads* intermediates the protocol
  tick already computes — it consumes no PRNG draws and feeds nothing
  back into the state.  A telemetry-on run is bit-identical to a
  telemetry-off run, certified by ``tests/test_telemetry.py`` and the
  ``make telemetry-smoke`` digest pairing.
* **None compiles out.** Every seam (``lifecycle.step``, ``_run_block``,
  the ``run_until_*`` drivers) takes ``telemetry=None`` by default; the
  ``None`` leg is a Python-level branch, so the traced program — and
  therefore the HLO and its collective census — is the one HEAD had.
* **Zero per-tick collectives.** Accumulators are shaped like their
  sources ([N] per-node masks, [N, W] packed planes, [K] slot vectors,
  [M] placement vectors) and updated with *elementwise* adds, which the
  SPMD partitioner keeps shard-local.  The cross-shard reduction to
  scalars happens once per fetched block, in :func:`fetch` — one
  psum-class collective per counter per block (asserted by
  ``tests/test_mesh_budget.py``).

Counter overflow: int32 accumulators hold per-tick increments of at most
N (or 32 per packed word); a fetch resets them, so the cadence bounds the
window — at the 1M headline a block must stay under ~2k ticks, far above
any ``check_every * blocks_per_dispatch`` in the tree.  :func:`fetch`
sums the big planes in float32 (exact to 2^24, ~1e-7 relative beyond —
counters, not invariants).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import (
    N_TIERS,
    TIER_NAMES,
    DeltaFaults,
    converged_fraction,
    resolve_faults,
)

# record-key suffixes for the per-tier counters ("same_rack", ...) — the
# JSON-friendly underscore form of delta.TIER_NAMES, shared by fetch, the
# stats bridge and chaos.score_blocks
TIER_KEYS = tuple(name.replace("-", "_") for name in TIER_NAMES)
from ringpop_tpu.sim.packbits import flat_index_u32, mix32, n_words
from ringpop_tpu.swim.member import ALIVE, FAULTY, SUSPECT, TOMBSTONE

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class TelemetryState(NamedTuple):
    """Per-tick protocol counters, accumulated on device between fetches.

    Every leaf is an *elementwise* accumulator shaped like the mask it
    counts (see the module docstring for why) — ``fetch`` owns the
    reduction to scalars.
    """

    # per-node masks — [N], node-sharded
    pings: jax.Array  # int32[N]: completed direct probe exchanges
    ping_reqs: jax.Array  # int32[N]: indirect probe legs issued
    probes_failed: jax.Array  # int32[N]: direct probes that found no path
    incarnation_bumps: jax.Array  # int32[N]: refutations that placed
    # packed-plane event counts — [N, W], sharded like ``learned``
    piggybacked: jax.Array  # uint32[N, W]: rumor bits ridden (both legs)
    expired: jax.Array  # uint32[N, W]: piggyback gates closed (maxP hit)
    # rumor-table vectors — [K], rumor-sharded
    timer_fires: jax.Array  # int32[K]: in-flight-rumor state-timer transitions completed
    base_timer_fires: jax.Array  # int32[N]: folded-to-base state-timer transitions completed
    # placement vectors — [M], M = alloc budget (replicated post-merge)
    decl_alive: jax.Array  # int32[M]: refutation rumors placed
    decl_suspect: jax.Array  # int32[M]: suspect declarations placed
    decl_faulty: jax.Array  # int32[M]: faulty declarations placed
    decl_tombstone: jax.Array  # int32[M]: tombstone (leave) declarations
    # scalars
    heal_attempts: jax.Array  # int32[]: partition-healer pair swaps tried
    ticks: jax.Array  # int32[]: ticks accumulated since the last fetch
    # OPTIONAL per-tier suspicion flow (topology plane, sim/topology.py):
    # None unless armed via ``zeros(params, tiers=True)`` — the None legs
    # are static structure, so every telemetry program that existed
    # before the topology plane traces unchanged.  [N, N_TIERS] so the
    # per-tick update stays elementwise (node-sharded axis 0); fetch owns
    # the reduction to the 4 per-tier scalars.
    suspects_by_tier: Optional[jax.Array] = None  # int32[N, 4]: declarations by tier
    false_suspects_by_tier: Optional[jax.Array] = None  # int32[N, 4]: target was live


def placement_budget(params) -> int:
    """M, the per-tick rumor-allocation budget — the shared shape rule of
    the placement vectors (mirrors the ``m`` computed in
    ``lifecycle.step``)."""
    return min(params.alloc_per_tick, params.k, params.n)


def zeros(params, tiers: bool = False) -> TelemetryState:
    """A zeroed accumulator for a ``LifecycleParams`` config.  ``tiers``
    arms the per-tier suspicion counters (topology runs); the default
    leaves them None so the pytree — and every program keyed on its
    structure — is exactly the pre-topology one."""
    n, k = params.n, params.k
    w = n_words(k)
    m = placement_budget(params)
    i32 = jnp.int32
    tier_kw = (
        {
            "suspects_by_tier": jnp.zeros((n, N_TIERS), i32),
            "false_suspects_by_tier": jnp.zeros((n, N_TIERS), i32),
        }
        if tiers
        else {}
    )
    return TelemetryState(
        **tier_kw,
        pings=jnp.zeros((n,), i32),
        ping_reqs=jnp.zeros((n,), i32),
        probes_failed=jnp.zeros((n,), i32),
        incarnation_bumps=jnp.zeros((n,), i32),
        piggybacked=jnp.zeros((n, w), jnp.uint32),
        expired=jnp.zeros((n, w), jnp.uint32),
        timer_fires=jnp.zeros((k,), i32),
        base_timer_fires=jnp.zeros((n,), i32),
        decl_alive=jnp.zeros((m,), i32),
        decl_suspect=jnp.zeros((m,), i32),
        decl_faulty=jnp.zeros((m,), i32),
        decl_tombstone=jnp.zeros((m,), i32),
        heal_attempts=jnp.zeros((), i32),
        ticks=jnp.zeros((), i32),
    )


def accumulate(
    tel: TelemetryState,
    *,
    delivered: jax.Array,  # bool[N]
    probing: jax.Array,  # bool[N]
    ping_req_legs: jax.Array,  # int32[N]
    refuted: jax.Array,  # bool[N]
    sent_w: jax.Array,  # uint32[N, W]
    resp_w: jax.Array,  # uint32[N, W]
    closed_w: jax.Array,  # uint32[N, W]
    fired: jax.Array,  # bool[K]
    base_fired: jax.Array,  # bool[N]
    place: jax.Array,  # bool[M]
    new_status: jax.Array,  # int8[M]
    heal_attempt: Optional[jax.Array],  # bool[] or None (healer disabled)
    declared: Optional[jax.Array] = None,  # bool[N] suspicion declarers (placed)
    declared_tier: Optional[jax.Array] = None,  # int32[N] accuser→target tier
    declared_up: Optional[jax.Array] = None,  # bool[N] target live per the plan
) -> TelemetryState:
    """One tick's worth of counter updates — every op elementwise, so the
    partitioner adds no collectives to the step (see module docstring).
    Called by ``lifecycle.step`` with intermediates the tick already has;
    the popcounts read planes that are materialized regardless.

    The ``declared*`` triple feeds the OPTIONAL per-tier suspicion
    counters (armed accumulators + a topology-carrying plan; see
    ``zeros(tiers=True)``): each declarer whose suspect rumor placed this
    tick counts into its accuser→target tier bucket, and — when the plan
    says the target was actually live — into the false-positive bucket
    too.  A one-hot product over the static tier count, elementwise like
    everything else here."""
    i32 = jnp.int32
    pop = jax.lax.population_count
    s_tier, f_tier = tel.suspects_by_tier, tel.false_suspects_by_tier
    if s_tier is not None and declared is not None:
        onehot = (
            declared[:, None]
            & (declared_tier[:, None] == jnp.arange(N_TIERS, dtype=jnp.int32)[None, :])
        ).astype(i32)
        s_tier = s_tier + onehot
        f_tier = f_tier + onehot * declared_up[:, None].astype(i32)
    return TelemetryState(
        suspects_by_tier=s_tier,
        false_suspects_by_tier=f_tier,
        pings=tel.pings + delivered.astype(i32),
        ping_reqs=tel.ping_reqs + ping_req_legs,
        probes_failed=tel.probes_failed + probing.astype(i32),
        incarnation_bumps=tel.incarnation_bumps + refuted.astype(i32),
        piggybacked=tel.piggybacked + pop(sent_w) + pop(resp_w),
        expired=tel.expired + pop(closed_w),
        timer_fires=tel.timer_fires + fired.astype(i32),
        base_timer_fires=tel.base_timer_fires + base_fired.astype(i32),
        decl_alive=tel.decl_alive + (place & (new_status == ALIVE)).astype(i32),
        decl_suspect=tel.decl_suspect + (place & (new_status == SUSPECT)).astype(i32),
        decl_faulty=tel.decl_faulty + (place & (new_status == FAULTY)).astype(i32),
        decl_tombstone=tel.decl_tombstone
        + (place & (new_status == TOMBSTONE)).astype(i32),
        heal_attempts=tel.heal_attempts
        + (heal_attempt.astype(i32) if heal_attempt is not None else 0),
        ticks=tel.ticks + 1,
    )


# -- fetch: the once-per-block reduction + census ----------------------------


def _census(state, faults: DeltaFaults):
    """Point-in-time membership census from the converged base view, plus
    the detection fraction over the fault model's down nodes (the DGRO-
    style convergence series: how much of the crash set the *converged*
    view has absorbed).  All [N]-column reductions."""
    present = state.base_present
    status = state.base_status

    def count(s):
        return (present & (status == s)).sum(dtype=jnp.int32)

    n = present.shape[0]
    out = {
        "num_members": present.sum(dtype=jnp.int32),
        "census_alive": count(ALIVE),
        "census_suspect": count(SUSPECT),
        "census_faulty": count(FAULTY),
        "census_tombstone": count(TOMBSTONE),
        "rumors_active": (state.r_subject >= 0).sum(dtype=jnp.int32),
    }
    if faults.up is not None:
        down = ~faults.up
        detected = down & (~present | (status >= FAULTY))
        down_total = down.sum(dtype=jnp.float32)
        # empty down set reports the vacuous 1.0, matching the up-is-None
        # branch — a time-varying FaultPlan reaches this state routinely
        # (every crashed node restarted), and 0/1 = 0.0 would read as
        # "nothing detected" for a fully recovered cluster
        out["detect_frac"] = jnp.where(
            down_total > 0,
            detected.sum(dtype=jnp.float32) / jnp.maximum(down_total, 1.0),
            jnp.float32(1.0),
        )
    else:
        out["detect_frac"] = jnp.float32(1.0)
    return out


def fetch(
    tel: TelemetryState, state, faults: DeltaFaults = DeltaFaults()
) -> tuple[dict, TelemetryState]:
    """Reduce the block's accumulators to a scalar record and reset them.

    Returns ``(record, zeroed_tel)`` — the record is a flat dict of
    device scalars (one ``jax.device_get`` fetches the whole block).
    This is where the cross-shard psums happen: one reduction per counter
    per fetched block, none per tick.  Jit-safe; ``LifecycleSim`` wraps
    it in a cached jit.  A time-varying ``chaos.FaultPlan`` is resolved
    at the state's tick, so the census/detect_frac gauges describe the
    fault model in force at fetch time."""
    # the UNRESOLVED model's static partition legs: a plan's group/reach
    # are time-invariant, so attribution by them stays defined even when
    # the fetch tick falls outside the split window (the resolved group
    # reads -1 there and every post-heal refutation would go unattributed)
    raw_group = getattr(faults, "group", None)
    raw_reach = getattr(faults, "reach", None)
    faults = resolve_faults(faults, state.tick)
    f32 = jnp.float32
    record = {
        "ticks": tel.ticks,
        # float32 sums for every N·T-scaling reduce (r14 int32-headroom
        # audit): a per-node counter holds up to T per block, so its sum
        # over N reaches N·T — 4.1e9 > 2³¹−1 at 16M nodes × 256-tick
        # blocks, where an int32 sum wraps silently.  Counts, not
        # invariants (exact to 2^24, ~1e-7 relative beyond — same
        # tradeoff the packed-plane sums below always made).
        "ping_send": tel.pings.sum(dtype=f32),
        "ping_req_send": tel.ping_reqs.sum(dtype=f32),
        "ping_timeout": tel.probes_failed.sum(dtype=f32),
        "refuted": tel.incarnation_bumps.sum(dtype=f32),
        # float32 sums: counts, not invariants (see module docstring)
        "rumors_piggybacked": tel.piggybacked.sum(dtype=f32),
        "rumors_expired": tel.expired.sum(dtype=f32),
        # timer_fires is [K] (sum ≤ K·T, int32-safe); base_timer_fires is
        # [N] — the N·T term that forces the float32 promotion
        "timer_fired": tel.timer_fires.sum(dtype=f32)
        + tel.base_timer_fires.sum(dtype=f32),
        # [M] placement vectors: sums ≤ M·T (M = alloc budget ≤ 64) —
        # int32-safe at any committed scale
        "decl_alive": tel.decl_alive.sum(dtype=jnp.int32),
        "decl_suspect": tel.decl_suspect.sum(dtype=jnp.int32),
        "decl_faulty": tel.decl_faulty.sum(dtype=jnp.int32),
        "decl_tombstone": tel.decl_tombstone.sum(dtype=jnp.int32),
        "heal_attempts": tel.heal_attempts,
        "tick": state.tick,
    }
    if tel.suspects_by_tier is not None:
        # per-tier suspicion flow (topology plane): 4 + 4 scalar keys —
        # scalars rather than one [4] column so the batched-fleet split
        # (``split_batched``) and the journal schema stay flat
        s = tel.suspects_by_tier.sum(axis=0, dtype=f32)
        fpos = tel.false_suspects_by_tier.sum(axis=0, dtype=f32)
        for ti, key in enumerate(TIER_KEYS):
            record[f"suspects_{key}"] = s[ti]
            record[f"false_suspects_{key}"] = fpos[ti]
    if raw_group is not None and raw_reach is not None:
        # directed-partition attribution (chaos asym scenarios): split the
        # block's refutations by whether the refuting subject sits in the
        # unreachable DIRECTION of a one-way window — a group g some
        # other group a cannot send to while g can still send to a (the
        # asymmetric shape; that sink side is where false accusations
        # pile up).  The asymmetry requirement matters in stacked fleets:
        # a symmetric member materializes the identity-reach default
        # (``chaos._leg_default``), whose blockages are all MUTUAL — a
        # direction-less partition must report zero unreachable-dir, not
        # claim every refutation for a direction it doesn't have.
        reach_b = jnp.asarray(raw_reach, bool)
        one_way = ~reach_b & jnp.swapaxes(reach_b, -1, -2)  # a can't reach g, g reaches a
        blocked = one_way.any(axis=-2)  # [G]: g sits in some one-way sink
        g = jnp.asarray(raw_group, jnp.int32)
        flag = (g >= 0) & jnp.take(
            blocked, jnp.maximum(g, 0), axis=-1
        )
        record["refuted_unreachable_dir"] = jnp.where(
            flag, tel.incarnation_bumps, 0
        ).sum(dtype=f32)
        record["refuted_reachable_dir"] = jnp.where(
            ~flag, tel.incarnation_bumps, 0
        ).sum(dtype=f32)
    record.update(_census(state, faults))
    fresh = jax.tree.map(jnp.zeros_like, tel)
    return record, fresh


def split_batched(
    record: dict, extra: Optional[dict] = None, id_base: int = 0
) -> list[dict]:
    """Split ONE batched block record (every value ``[B]``-leading, the
    output of a vmapped :func:`fetch`) into B per-scenario host records,
    each tagged ``scenario_id`` — the one ``device_get`` that replaces B
    per-scenario round-trips in the Monte-Carlo fleet.  ``extra`` merges
    additional ``[B]`` columns (e.g. per-replica state digests) before
    the split.  Scalars (no leading axis) broadcast to every record.
    ``id_base`` offsets the ids — rank r of a process-sliced fleet tags
    its records with GLOBAL scenario ids (``id_base = lo`` of its
    ``process_block`` slice), so journals from different ranks merge
    without collisions."""
    host = jax.device_get({**record, **(extra or {})})
    b = max(
        (np.asarray(v).shape[0] for v in host.values() if np.ndim(v) >= 1),
        default=1,
    )
    out = []
    for i in range(b):
        sliced = {
            k: (np.asarray(v)[i] if np.ndim(v) >= 1 else v) for k, v in host.items()
        }
        out.append({"scenario_id": id_base + i, **_to_host(sliced)})
    return out


# -- order-sensitive state digest (journal pairing) --------------------------


# murmur3 fmix32 — the shared packbits.mix32 mixer (same one the view
# checksum uses; here it digests raw state words, not membership views)
_mix32 = mix32


def leaf_digest_sum(leaf, offset=np.uint32(0)) -> jax.Array:
    """uint32 scalar: one leaf's inner digest sum — wrapping-uint32 sum of
    ``mix32(value ^ mix32(global_flat_index))`` over every element, where
    the flat index starts at ``offset``.

    Two properties the multi-host certificates lean on:

    * **int32/iota headroom** (the r14 audit): the index lanes are built
      2-D via ``packbits.flat_index_u32`` (wrapping ``row·rowlen + col``),
      never as a flat 1-D iota — the old ``arange(N·K)`` form needed a
      > 2³¹-element iota at 16M × 256.  Values are bit-identical at every
      scale where the old form was well-defined (the product wraps mod
      2³² exactly like a uint32 arange would).
    * **block partiality**: because the combine is a wrapping SUM, the sum
      over a node-block at its global ``offset`` is an exact partial of
      the full-plane sum — ``parallel.partition.leaf_partial_sums`` is
      built on this.
    """
    v = jnp.asarray(leaf)
    if v.dtype == jnp.bool_:
        v = v.astype(jnp.uint32)
    if v.ndim <= 1:
        flat = v.reshape(-1).astype(jnp.uint32)
        idx = jnp.uint32(offset) + jnp.arange(flat.shape[0], dtype=jnp.uint32)
        return _mix32(flat ^ _mix32(idx)).sum(dtype=jnp.uint32)
    rows, rowlen = v.shape[0], int(np.prod(v.shape[1:], dtype=np.int64))
    m = v.reshape(rows, rowlen).astype(jnp.uint32)
    idx = jnp.uint32(offset) + flat_index_u32(
        jnp.arange(rows, dtype=jnp.uint32)[:, None],
        rowlen,
        jnp.arange(rowlen, dtype=jnp.uint32)[None, :],
    )
    return _mix32(m ^ _mix32(idx)).sum(dtype=jnp.uint32)


def tree_digest(tree) -> jax.Array:
    """uint32 scalar, on-device: a position-sensitive digest of every leaf
    of an integer/bool pytree (both sim engines' states qualify).  Two
    states digest equal iff every leaf is bit-equal (up to hash
    collision) — the cheap pairing check the run journal carries so a
    telemetry-on run can be certified against its telemetry-off twin
    without shipping full planes to the host.  Built on
    :func:`leaf_digest_sum`, whose wrapping-sum partiality is also what
    lets ``parallel.partition`` certify multi-process runs leaf-sum by
    leaf-sum."""
    acc = jnp.uint32(0)
    for li, leaf in enumerate(jax.tree.leaves(tree)):
        leaf_sum = leaf_digest_sum(leaf)
        acc = acc + _mix32(leaf_sum ^ jnp.uint32((li * 0x9E37_79B9) & 0xFFFF_FFFF))
    return acc


def delta_record(state, faults: DeltaFaults = DeltaFaults()) -> dict:
    """The delta engine's per-block journal record (device scalars): the
    dissemination engine carries no in-step counters — coverage fraction
    and the state digest are its convergence series."""
    return {
        "tick": state.tick,
        "coverage": converged_fraction(state, faults),
        "digest": tree_digest(state),
    }


# -- toolchain / mesh-budget fingerprints ------------------------------------


def toolchain_fingerprint() -> dict:
    """The versions that decide whether two trajectory captures are
    comparable (the golden-drift diagnosis in ``tests/golden_tools.py``
    compares exactly this dict)."""
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "numpy": np.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
    }


def mesh_budget_fingerprint(repo: str = _REPO) -> dict:
    """Identity of the collective-budget baseline this run is ratcheted
    against (``captures/mesh_profile_small_budget.json``): file name +
    content sha256 prefix, so a journal names which budget world it was
    produced in.  Missing capture → ``{"budget_capture": None}``."""
    path = os.path.join(repo, "captures", "mesh_profile_small_budget.json")
    if not os.path.exists(path):
        return {"budget_capture": None}
    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return {"budget_capture": os.path.basename(path), "sha256": digest}


def _to_host(record: dict) -> dict:
    """Fetch every value of a record to host JSON scalars — the ONE
    device-to-journal coercion (one ``device_get`` for the whole dict;
    floats rounded to 6 places so the journal, the stats bridge, and
    ``TelemetrySink.records`` all carry the same numbers).  Idempotent on
    already-host dicts."""
    host = {}
    for k, v in jax.device_get(record).items():
        if isinstance(v, (np.generic, np.ndarray)):
            v = v.item() if np.ndim(v) == 0 else np.asarray(v).tolist()
        if isinstance(v, float):
            v = round(v, 6)
        host[k] = v
    return host


# -- JSONL run journal -------------------------------------------------------


class TelemetryJournal:
    """One JSONL stream per run: a ``header`` record (engine, params,
    toolchain + mesh-budget fingerprints), then one ``block`` record per
    fetched tick-block.  Values are plain JSON scalars — device arrays
    are fetched (one ``device_get`` per record) and numpy scalars
    coerced.  Context-manager; safe to hand to multiple scenarios in
    append mode (each writes its own header)."""

    def __init__(self, path: str, *, append: bool = False):
        self.path = path
        self._f = open(path, "a" if append else "w", buffering=1)

    def header(self, engine: str, scenario: str = "", params: Optional[dict] = None) -> None:
        # compile_cache: the accel plane's persistent-cache outcome
        # (cache_dir + the configure error when it could not be enabled)
        # — a journal states its cache world explicitly instead of
        # readers inferring it from first_s - execute_s deltas.  Callers
        # with an AOT front-door result add its cache_hit via params
        # (e.g. simbench step1m).
        from ringpop_tpu.util.accel import cache_status

        # process_count/process_id (r14): a journal names which rank of
        # which job size wrote it — 1/1 single-controller, else the
        # jax.distributed coordinates.  Multi-process runs produce one
        # journal PER RANK; the pairing tools group them by these keys.
        try:
            pc, pid = jax.process_count(), jax.process_index()
        except Exception:  # backend not initialized yet — header still valid
            pc, pid = 1, 0
        # git_commit (r20): the toolchain fingerprint names the
        # interpreter world; this names the SOURCE world — a journal is
        # provenance-complete without the repo it was produced in
        from ringpop_tpu.obs.flight import git_commit

        self._write(
            {
                "kind": "header",
                "engine": engine,
                "scenario": scenario,
                "params": params or {},
                "toolchain": toolchain_fingerprint(),
                "git_commit": git_commit(),
                "mesh_budget": mesh_budget_fingerprint(),
                "compile_cache": cache_status(),
                "process_count": pc,
                "process_id": pid,
            }
        )

    def block(self, record: dict, **extra) -> None:
        self._write({"kind": "block", **_to_host({**record, **extra})})

    def score(self, record: dict) -> None:
        """Append a chaos-scenario verdict (``chaos.score_blocks``) —
        the record that makes a journal a SCORED journal."""
        self._write({**_to_host(record), "kind": "score"})

    def span(self, record: dict) -> None:
        """Append one ``kind:"span"`` record (``obs/trace.py`` — span
        values are already host scalars; pass this method as a Tracer
        sink so traces land in the run's own journal, joinable against
        its block/``ring_update`` records)."""
        self._write({**record, "kind": "span"})

    def _write(self, obj: dict) -> None:
        self._f.write(json.dumps(obj, sort_keys=True) + "\n")

    def close(self) -> None:
        self._f.flush()
        self._f.close()

    def __enter__(self) -> "TelemetryJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_journal(path: str) -> list[dict]:
    """Parse a JSONL journal back into records (the smoke test's loader)."""
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- event bus + StatsReporter bridge ----------------------------------------

SIM_STAT_PREFIX = "ringpop.sim"

# record field -> (statsd method, key suffix).  Suffixes reuse the host
# plane's vocabulary (``ringpop.py`` event->stats table) so dashboards
# built for one plane read the other: see OBSERVABILITY.md for the full
# table with the ringpop-go parity anchors.
STAT_KEYS = {
    "ping_send": ("incr", "ping.send"),
    "ping_req_send": ("incr", "ping-req.send"),
    "ping_timeout": ("incr", "ping.timeout"),
    "refuted": ("incr", "refuted-update"),
    "rumors_piggybacked": ("incr", "changes.disseminate"),
    "rumors_expired": ("incr", "changes.expired"),
    "timer_fired": ("incr", "state-timer.fired"),
    "decl_alive": ("incr", "membership-update.alive"),
    "decl_suspect": ("incr", "membership-update.suspect"),
    "decl_faulty": ("incr", "membership-update.faulty"),
    "decl_tombstone": ("incr", "membership-update.tombstone"),
    "heal_attempts": ("incr", "heal.attempt"),
    "num_members": ("gauge", "num-members"),
    "census_alive": ("gauge", "membership.alive"),
    "census_suspect": ("gauge", "membership.suspect"),
    "census_faulty": ("gauge", "membership.faulty"),
    "census_tombstone": ("gauge", "membership.tombstone"),
    "rumors_active": ("gauge", "rumors.active"),
    "detect_frac": ("gauge", "detection.fraction"),
}

# topology-plane block keys (present only on tier-armed topology runs) —
# surfaced under ringpop.sim.topo.* (OBSERVABILITY.md key table)
for _tk, _dash in zip(TIER_KEYS, TIER_NAMES):
    STAT_KEYS[f"suspects_{_tk}"] = ("incr", f"topo.suspects.{_dash}")
    STAT_KEYS[f"false_suspects_{_tk}"] = ("incr", f"topo.false-suspects.{_dash}")
STAT_KEYS["refuted_unreachable_dir"] = ("incr", "topo.refuted.unreachable-dir")
STAT_KEYS["refuted_reachable_dir"] = ("incr", "topo.refuted.reachable-dir")


def emit_stats(reporter, record: dict, prefix: str = SIM_STAT_PREFIX) -> None:
    """Feed a fetched block record into a host-plane ``StatsReporter``
    under the sim namespace — the same sinks (file/UDP statsd/in-memory)
    the facade uses, so one collection pipeline serves both planes."""
    record = _to_host(record)
    for field, (kind, suffix) in STAT_KEYS.items():
        if field not in record:
            continue
        if kind == "incr":
            reporter.incr(f"{prefix}.{suffix}", int(record[field]))
        else:
            reporter.gauge(f"{prefix}.{suffix}", float(record[field]))


class TelemetrySink:
    """Fan a fetched block record out to any of: a JSONL journal, a
    ``StatsReporter``, a typed event bus, and/or a plain callable —
    the one object ``LifecycleSim``/``simbench`` attach."""

    def __init__(
        self,
        journal: Optional[TelemetryJournal] = None,
        stats=None,
        emitter=None,
        fn: Optional[Callable[[dict], None]] = None,
        stat_prefix: str = SIM_STAT_PREFIX,
    ):
        self.journal = journal
        self.stats = stats
        self.emitter = emitter
        self.fn = fn
        self.stat_prefix = stat_prefix
        self.records: list = []  # host-side history (cheap; per block)

    def __call__(self, record: dict, **extra: Any) -> None:
        host = _to_host({**record, **extra})
        self.records.append(host)
        if self.journal is not None:
            self.journal.block(host)
        if self.stats is not None:
            emit_stats(self.stats, host, self.stat_prefix)
        if self.emitter is not None:
            from ringpop_tpu.events import SimTickBlockEvent

            self.emitter.emit(SimTickBlockEvent(record=host))
        if self.fn is not None:
            self.fn(host)
