"""Topology compiler: rack/zone/region trees → tier-realistic fault
overlays and correlated-failure scenarios (ROADMAP "Topology-realistic
overlays").

The chaos plane models partitions as a dense ``reach[G, G]`` group matrix
and loss as flat per-node/scalar drop planes — the right shape for a
handful of splits, the wrong shape for what production actually sees:
correlated failures along a rack → zone → region hierarchy with
heterogeneous RTT per tier.  This module is the missing compiler, in
three parts:

1. **Declarative tree** — :class:`TopologySpec`: region/zone/rack counts
   plus per-EDGE latency and loss (:class:`TierLink` for the rack
   uplink, the zone aggregation hop, and the WAN link).
   :func:`compile_topology` assigns nodes to racks in CONTIGUOUS equal
   blocks (so rack boundaries align with shard boundaries — the
   "blocked" half of the device pattern) and compiles the tree
   host-side to per-node tier-id arrays ``tier_ids[3, N]`` plus ONE
   small per-tier parameter table ``tier_drop[4]``.

2. **Device evaluation** — the compiled legs ride the existing
   ``chaos.FaultPlan`` / ``delta.DeltaFaults`` seam: the jitted step
   classifies each (a → b) leg's tier as the count of differing ids (a
   tree property: same rack ⇒ same zone ⇒ same region) and expands the
   tiny table by a blocked ONE-HOT gather over the static tier count
   (``delta.tier_pair_drop``; no dense [G, G] product — the
   sparse-GNN-on-dense-hardware pattern, PAPERS.md arXiv:1906.11786).
   The expansion runs under the ``fault-plan`` named scope and is
   elementwise in the node lane — zero collectives by construction,
   censused by jaxlint RPJ206.  Per-TIER probe-timeout inflation
   generalizes the chaos plane's slow-node inflation: a cross-zone ack
   that tends to arrive after the probe timeout IS a lost leg at that
   boundary, so the compiler folds ``P(rtt > timeout)`` (exponential
   tail model) into the tier's loss entry — the same
   "late ack = dropped leg" semantics ``sim/chaos.py`` established.

3. **Correlated events** — zone loss (a whole zone crashes and
   restarts), switch flap (a rack's uplink flapping as ONE unit:
   identical period AND phase for every node behind it), and WAN
   partition (region-level split window, optionally one-way via a tiny
   region-count ``reach``) all compile to the EXISTING FaultPlan legs —
   so they batch through ``chaos.stack_plans`` / ``sim.montecarlo``
   unchanged and score through ``chaos.score_blocks``, whose per-tier
   breakdowns (time-to-detect and false-positive suspects split
   same-rack / cross-rack / cross-zone / cross-region) are what
   distinguish a zone cut from 100 independent crashes: correlated loss
   leaves no live same-rack observers to raise suspicions, so its
   suspicion flow arrives only from across the boundary.

A tree with NO penalties (every link zero) compiles to NO tier legs at
all — the plan is bit-identical to its hand-built flat-chaos twin and
traces to the IDENTICAL jaxpr (the constant-topology property the
goldens and ``make topo-smoke`` pin).  Stats surface under
``ringpop.sim.topo.*`` (OBSERVABILITY.md).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim import chaos
from ringpop_tpu.sim.chaos import NO_TICK, FaultPlan
from ringpop_tpu.sim.delta import N_TIERS, TIER_LEVELS, TIER_NAMES

__all__ = [
    "TierLink",
    "TopologySpec",
    "Topology",
    "compile_topology",
    "default_topology",
    "zone_loss_plan",
    "switch_flap_plan",
    "partition_plan",
    "independent_crash_plan",
    "topo_scenario_plan",
    "topo_scenario_specs",
    "emit_topo_stats",
    "late_ack_prob",
]


@dataclass(frozen=True)
class TierLink:
    """One edge class of the tree: the extra round-trip latency and the
    per-leg loss probability a message pays for crossing it (rack
    uplink, zone aggregation hop, or WAN link)."""

    rtt_ms: float = 0.0  # added round-trip latency across this edge
    loss: float = 0.0  # per-traversal loss probability


@dataclass(frozen=True)
class TopologySpec:
    """The declarative tree: counts per level plus per-edge parameters.
    Node → rack assignment is contiguous equal blocks (rack 0 owns the
    first ``n / racks`` nodes, ...), zones group consecutive racks,
    regions consecutive zones — so topology boundaries coincide with the
    node-axis shard boundaries every blocked-for-SPMD path in this repo
    already uses."""

    regions: int = 1
    zones_per_region: int = 1
    racks_per_zone: int = 1
    rack_link: TierLink = field(default_factory=TierLink)
    zone_link: TierLink = field(default_factory=TierLink)
    region_link: TierLink = field(default_factory=TierLink)
    # probe timeout the per-tier latency is judged against (the engines'
    # protocol period is 200 ms; the reference's ping timeout spans
    # multiple periods, so 400 ms is the default judgment window)
    probe_timeout_ms: float = 400.0

    @property
    def total_racks(self) -> int:
        return self.regions * self.zones_per_region * self.racks_per_zone

    @property
    def total_zones(self) -> int:
        return self.regions * self.zones_per_region


def late_ack_prob(rtt_ms: float, timeout_ms: float) -> float:
    """P(ack arrives after the probe timeout) for a leg whose round trip
    has MEAN ``rtt_ms`` — exponential tail model, ``exp(-timeout/rtt)``.
    The exponential is deliberately heavy-tailed for a network RTT
    (queueing delay dominates the tail), which is the conservative
    choice for a fault overlay: it overestimates late acks rather than
    declaring a boundary loss-free.  0 when the tier adds no latency."""
    if rtt_ms <= 0.0:
        return 0.0
    return float(math.exp(-timeout_ms / rtt_ms))


@dataclass(frozen=True)
class Topology:
    """A compiled tree: per-node tier ids + the per-tier drop table, plus
    the host-side index structure the correlated-event builders consume.
    ``tier_ids`` rows are [rack, zone, region] (globally unique ids per
    level); ``tier_drop[t]`` is the per-leg loss at tier distance t
    (``delta.TIER_NAMES`` order)."""

    spec: TopologySpec
    n: int
    tier_ids: np.ndarray  # int32[TIER_LEVELS, N]
    tier_drop: np.ndarray  # float32[N_TIERS]

    # -- host-side index helpers --------------------------------------------

    def nodes_in_rack(self, rack: int) -> np.ndarray:
        return np.flatnonzero(self.tier_ids[0] == rack)

    def nodes_in_zone(self, zone: int) -> np.ndarray:
        return np.flatnonzero(self.tier_ids[1] == zone)

    def nodes_in_region(self, region: int) -> np.ndarray:
        return np.flatnonzero(self.tier_ids[2] == region)

    def tier_of_pair(self, a, b) -> np.ndarray:
        """Host mirror of ``delta.tier_pair`` (the scorer/test oracle)."""
        a = np.asarray(a, np.int64)
        b = np.asarray(b, np.int64)
        return (self.tier_ids[:, a] != self.tier_ids[:, b]).astype(np.int32).sum(axis=0)

    def has_penalties(self) -> bool:
        return bool((self.tier_drop > 0.0).any())

    def plan_legs(self, force: bool = False) -> FaultPlan:
        """The topology as FaultPlan legs.  A penalty-free tree returns
        the EMPTY plan — the legs compile out entirely, so a constant
        (penalty-free) topology traces to the identical jaxpr as the
        flat fault-plan step (pinned by tests + ``make topo-smoke``).
        ``force=True`` materializes the legs anyway (zero table) — the
        tpu_ksweep ``topo_chaos`` A/B prices the tier machinery that
        way, bit-equal to the flat run by the separate-coin construction
        (``delta.tier_pair_drop``)."""
        if not force and not self.has_penalties():
            return FaultPlan()
        return chaos.validate_plan(
            FaultPlan(
                tier_ids=jnp.asarray(self.tier_ids),
                tier_drop=jnp.asarray(self.tier_drop),
            )
        )


def _tier_table(spec: TopologySpec) -> np.ndarray:
    """Compile the per-edge parameters into the per-tier drop table.
    Tier t's path crosses every edge class up to its level TWICE (out
    through a's side of the tree, down into b's): loss composes as the
    survival product, latency sums into the mean RTT judged against the
    probe timeout (:func:`late_ack_prob`).  Same-rack (tier 0) pays
    nothing — intra-rack latency is far inside any timeout."""
    links = (spec.rack_link, spec.zone_link, spec.region_link)
    table = np.zeros(N_TIERS, np.float32)
    for t in range(1, N_TIERS):
        crossed = links[:t]
        survive = 1.0
        rtt = 0.0
        for link in crossed:
            survive *= (1.0 - float(link.loss)) ** 2
            rtt += 2.0 * float(link.rtt_ms)
        p_late = late_ack_prob(rtt, spec.probe_timeout_ms)
        table[t] = np.float32(1.0 - survive * (1.0 - p_late))
    return table


def compile_topology(spec: TopologySpec, n: int) -> Topology:
    """Compile the declarative tree for an ``n``-node cluster.

    Host-side, once: rack of node i is ``i * racks // n`` (contiguous
    near-equal blocks — a rack never straddles more shard boundaries
    than it must), zone/region ids derive by integer division, and the
    per-edge parameters fold into the ``tier_drop`` table.  Raises when
    the tree has more racks than nodes (an empty rack is a spec error,
    not a scenario)."""
    racks = spec.total_racks
    if racks < 1:
        raise ValueError(f"topology needs at least one rack; spec gives {racks}")
    if racks > n:
        raise ValueError(
            f"{racks}-rack tree over {n} nodes leaves empty racks — "
            "shrink the tree or grow the cluster"
        )
    for name, link in (
        ("rack_link", spec.rack_link),
        ("zone_link", spec.zone_link),
        ("region_link", spec.region_link),
    ):
        if not (0.0 <= float(link.loss) < 1.0):
            raise ValueError(f"{name}.loss must be in [0, 1); got {link.loss}")
        if float(link.rtt_ms) < 0.0:
            raise ValueError(f"{name}.rtt_ms must be >= 0; got {link.rtt_ms}")
    i = np.arange(n, dtype=np.int64)
    rack = (i * racks) // n
    zone = rack // spec.racks_per_zone
    region = zone // spec.zones_per_region
    tier_ids = np.stack([rack, zone, region]).astype(np.int32)
    assert tier_ids.shape == (TIER_LEVELS, n)
    return Topology(spec=spec, n=n, tier_ids=tier_ids, tier_drop=_tier_table(spec))


def default_topology(n: int, **overrides) -> Topology:
    """The canonical small tree the smoke/bench scenarios share: 2
    regions × 2 zones × 2 racks (8 racks), a quiet rack fabric, a lossy
    zone hop, and a WAN link whose 120 ms RTT inflates cross-region
    probe timeouts (``late_ack_prob`` ≈ 0.036 at the 400 ms window) on
    top of its 2% loss.  ``overrides`` replace TopologySpec fields."""
    spec_kw = dict(
        regions=2,
        zones_per_region=2,
        racks_per_zone=2,
        rack_link=TierLink(rtt_ms=0.2, loss=0.0),
        zone_link=TierLink(rtt_ms=2.0, loss=0.005),
        region_link=TierLink(rtt_ms=60.0, loss=0.02),
    )
    spec_kw.update(overrides)
    return compile_topology(TopologySpec(**spec_kw), n)


# -- correlated-failure scenario builders -------------------------------------


def zone_loss_plan(
    topo: Topology,
    zone: int,
    *,
    at: int = 8,
    heal: Optional[int] = None,
) -> FaultPlan:
    """A whole zone goes dark at tick ``at`` (power/cooling/aggregation
    failure — the canonical correlated event) and restarts at ``heal``
    (None = never).  Compiles to the existing crash/restart legs, so it
    batches and scores like any churn plan — but the crash set is a
    CONTIGUOUS tier block, which is exactly what the per-tier score
    split needs to distinguish from independent churn."""
    nodes = topo.nodes_in_zone(zone)
    if nodes.size == 0:
        raise ValueError(f"zone {zone} does not exist in this topology")
    crash = np.full(topo.n, NO_TICK, np.int32)
    restart = np.full(topo.n, NO_TICK, np.int32)
    crash[nodes] = at
    if heal is not None:
        restart[nodes] = heal
    return chaos.validate_plan(
        FaultPlan(crash_tick=jnp.asarray(crash), restart_tick=jnp.asarray(restart))
    )


def switch_flap_plan(
    topo: Topology,
    rack: int,
    *,
    period: int = 24,
    down: int = 6,
    start: int = 8,
) -> FaultPlan:
    """A rack's uplink flapping as ONE unit: every node behind the
    switch shares the identical period AND phase (unlike
    ``chaos.flap_plan``'s per-node staggering — the whole point of a
    correlated flap is that the cohort moves together).  The suspicion
    load it generates is bounded-from-outside only: inside the rack
    nothing changed."""
    nodes = topo.nodes_in_rack(rack)
    if nodes.size == 0:
        raise ValueError(f"rack {rack} does not exist in this topology")
    fperiod = np.zeros(topo.n, np.int32)
    fphase = np.zeros(topo.n, np.int32)
    fdown = np.zeros(topo.n, np.int32)
    fperiod[nodes] = period
    fphase[nodes] = (-start) % period  # first down window opens at ``start``
    fdown[nodes] = down
    return chaos.validate_plan(
        FaultPlan(
            flap_period=jnp.asarray(fperiod),
            flap_phase=jnp.asarray(fphase),
            flap_down=jnp.asarray(fdown),
        )
    )


def partition_plan(
    topo: Topology,
    *,
    level: str = "region",
    cut: Sequence[int] = (1,),
    split_at: int = 8,
    heal_at: Optional[int] = None,
    one_way: bool = False,
) -> FaultPlan:
    """A WAN/zone partition window: the ``cut`` ids at ``level`` (``"rack"``
    / ``"zone"`` / ``"region"``) become group 1 during ``[split_at,
    heal_at)``.  Symmetric by default — bit-identical legs to the
    hand-built symmetric-partition FaultPlan over the same node block
    (the topology-equivalence pin in tests/test_topology.py).
    ``one_way=True`` adds the directed ``reach`` the asym scenario
    established: majority → cut blocked, cut → majority delivering (the
    BGP-leak shape — the cut side still reaches out, nothing reaches
    in), so false accusations pile up about the cut side and refute
    through the open direction."""
    levels = {"rack": 0, "zone": 1, "region": 2}
    if level not in levels:
        raise ValueError(f"level must be one of {sorted(levels)}; got {level!r}")
    ids = topo.tier_ids[levels[level]]
    cut = sorted(int(c) for c in cut)
    if not cut:
        raise ValueError("partition_plan needs at least one cut id")
    present = set(np.unique(ids).tolist())
    missing = [c for c in cut if c not in present]
    if missing:
        raise ValueError(f"{level} ids {missing} do not exist in this topology")
    if len(cut) == len(present):
        raise ValueError(f"cutting every {level} partitions nothing from nothing")
    group = np.isin(ids, cut).astype(np.int32)
    legs = dict(
        group=jnp.asarray(group),
        part_from=jnp.asarray(np.int32(split_at)),
        part_until=jnp.asarray(
            np.int32(heal_at if heal_at is not None else NO_TICK)
        ),
    )
    if one_way:
        legs["reach"] = jnp.asarray(np.asarray([[True, False], [True, True]]))
    return chaos.validate_plan(FaultPlan(**legs))


def independent_crash_plan(
    topo: Topology,
    n_crash: int,
    *,
    at: int = 8,
    heal: Optional[int] = None,
    seed: int = 0,
) -> FaultPlan:
    """The control cohort: the SAME number of crashes as a correlated
    event, scattered uniformly over the cluster (the "100 independent
    crashes" a zone cut must NOT read as).  Same crash/restart legs,
    same tick schedule — only the correlation differs, so any score
    difference is the topology signal."""
    rng = np.random.default_rng(seed)
    nodes = rng.choice(topo.n, size=min(int(n_crash), topo.n), replace=False)
    crash = np.full(topo.n, NO_TICK, np.int32)
    restart = np.full(topo.n, NO_TICK, np.int32)
    crash[nodes] = at
    if heal is not None:
        restart[nodes] = heal
    return chaos.validate_plan(
        FaultPlan(crash_tick=jnp.asarray(crash), restart_tick=jnp.asarray(restart))
    )


# -- canonical scenarios (the simbench/smoke/twin contract) -------------------


def topo_scenario_plan(
    name: str, n: int, seed: int = 0, horizon: int = 256,
    topo: Optional[Topology] = None,
) -> FaultPlan:
    """The canonical topology scenarios, parameterized only by (name, n,
    seed, horizon) — same contract as ``chaos.scenario_plan``, so the
    measuring bench, its sharded-twin subprocess, the smoke gate and the
    tests all construct the identical plan.  All ride the
    ``default_topology(n)`` tree (or ``topo``) WITH its tier legs:

    * ``zone_loss``   — zone 1 dark from horizon/32 to horizon/2;
    * ``switch_flap`` — rack 2's uplink flapping as one unit;
    * ``wan``         — one-way region partition window plus a tiny
      permanent crash cohort that must be detected THROUGH it;
    * ``independent`` — the control: as many scattered crashes as
      ``zone_loss`` takes down, same schedule;
    * ``flat``        — ``zone_loss`` WITHOUT tier penalties (a
      zero-penalty tree compiles to no tier legs at all): the
      constant-topology twin whose jaxpr must equal the flat chaos
      step's;
    * ``smoke``       — zone loss + a rack flap + the tier legs: every
      leg family in one tiny plan (the ``make topo-smoke`` program).
    """
    topo = topo if topo is not None else default_topology(n)
    first = max(4, horizon // 32)
    heal = horizon // 2
    if name == "zone_loss":
        return chaos._merge_plans(
            zone_loss_plan(topo, zone=1, at=first, heal=heal),
            topo.plan_legs(),
        )
    if name == "switch_flap":
        return chaos._merge_plans(
            switch_flap_plan(
                topo, rack=2 % topo.spec.total_racks,
                period=max(12, horizon // 10), down=max(3, horizon // 40),
                start=first,
            ),
            topo.plan_legs(),
        )
    if name == "wan":
        return chaos._merge_plans(
            partition_plan(
                topo, level="region", cut=(topo.spec.regions - 1,),
                split_at=first, heal_at=heal, one_way=True,
            ),
            chaos.churn_plan(
                n, n_churn=max(2, n // 1000), n_permanent=max(2, n // 1000),
                first=2, stagger=1, waves=1, seed=seed,
            ),
            topo.plan_legs(),
        )
    if name == "independent":
        return chaos._merge_plans(
            independent_crash_plan(
                topo, int(topo.nodes_in_zone(1).size), at=first, heal=heal,
                seed=seed,
            ),
            topo.plan_legs(),
        )
    if name == "flat":
        flat_topo = compile_topology(
            TopologySpec(
                regions=topo.spec.regions,
                zones_per_region=topo.spec.zones_per_region,
                racks_per_zone=topo.spec.racks_per_zone,
            ),
            n,
        )
        return chaos._merge_plans(
            zone_loss_plan(flat_topo, zone=1, at=first, heal=heal),
            flat_topo.plan_legs(),  # penalty-free: the EMPTY plan
        )
    if name == "smoke":
        return chaos._merge_plans(
            zone_loss_plan(topo, zone=0, at=first, heal=heal),
            switch_flap_plan(
                topo, rack=topo.spec.total_racks - 1, period=12, down=3,
                start=first + 2,
            ),
            topo.plan_legs(),
        )
    raise ValueError(f"unknown topology scenario {name!r}")


def topo_scenario_specs(topo: Topology, seed: int = 0, horizon: int = 256,
                        reps: int = 1) -> tuple[list[FaultPlan], list[dict]]:
    """The correlated-failure scenario FAMILY as (plans, meta) ready for
    ``chaos.stack_plans`` + ``scenarios.scored_fleet``: one zone-loss
    member per zone, one switch-flap per rack, symmetric + one-way WAN
    partitions, and one independent-crash control per zone (matched
    cohort size), each repeated ``reps`` times with distinct seeds.
    ``meta[i]`` carries ``event``/``locus``/``rep`` next to the
    ``scenario_id`` the fleet stamps."""
    first = max(4, horizon // 32)
    heal = horizon // 2
    legs = topo.plan_legs()
    has_legs = any(v is not None for v in legs)
    plans: list[FaultPlan] = []
    meta: list[dict] = []

    def add(event: str, locus: int, rep: int, plan: FaultPlan):
        plans.append(chaos._merge_plans(plan, legs) if has_legs else plan)
        meta.append(
            {"scenario_id": len(meta), "event": event, "locus": locus, "rep": rep}
        )

    for rep in range(reps):
        for z in range(topo.spec.total_zones):
            add("zone_loss", z, rep, zone_loss_plan(topo, z, at=first, heal=heal))
        for r in range(topo.spec.total_racks):
            add(
                "switch_flap", r, rep,
                switch_flap_plan(
                    topo, r, period=max(12, horizon // 10),
                    down=max(3, horizon // 40), start=first + rep,
                ),
            )
        for one_way in (False, True):
            add(
                "wan_oneway" if one_way else "wan",
                topo.spec.regions - 1, rep,
                partition_plan(
                    topo, level="region", cut=(topo.spec.regions - 1,),
                    split_at=first, heal_at=heal, one_way=one_way,
                ),
            )
        for z in range(topo.spec.total_zones):
            add(
                "independent", z, rep,
                independent_crash_plan(
                    topo, int(topo.nodes_in_zone(z).size), at=first, heal=heal,
                    seed=seed + rep * 1000 + z,
                ),
            )
    return plans, meta


# -- stats bridge -------------------------------------------------------------

TOPO_STAT_PREFIX = "ringpop.sim.topo"


def emit_topo_stats(reporter, score: dict, prefix: str = TOPO_STAT_PREFIX) -> None:
    """Feed a topology verdict's per-tier breakdowns into a host-plane
    ``StatsReporter`` under ``ringpop.sim.topo.*`` (the per-BLOCK keys
    ride the normal ``telemetry.emit_stats`` bridge; this is the
    score-record summary).  Null tiers (no suspicion flow observed) are
    skipped, not zeroed — same convention as ``chaos.emit_score_stats``."""
    for record_key, stat_key in (
        ("suspects_by_tier", "suspects"),
        ("false_positive_by_tier", "false-positives"),
        ("time_to_detect_by_tier", "time-to-detect"),
    ):
        per_tier = score.get(record_key)
        if not per_tier:
            continue
        for tier_name in TIER_NAMES:
            value = per_tier.get(tier_name.replace("-", "_"))
            if value is None:
                continue
            reporter.gauge(f"{prefix}.{stat_key}.{tier_name}", float(value))
    for key, suffix in (
        ("refutations_unreachable_dir", "refuted.unreachable-dir"),
        ("refutations_reachable_dir", "refuted.reachable-dir"),
    ):
        if score.get(key) is not None:
            reporter.gauge(f"{prefix}.{suffix}", float(score[key]))
