"""SWIM membership protocol — host plane + shared semantics core.

Parity target: reference package ``swim/`` (~3.6k LoC Go).  The semantics
core (``member``) is pure and array-friendly; the host-plane classes
(``memberlist``, ``disseminator``, ``state_transitions``, ``gossip``,
``node``) mirror the reference's component split so the judge can check
parity component-by-component (SURVEY.md §2.2).
"""

from ringpop_tpu.swim.member import (
    Member,
    Change,
    ALIVE,
    SUSPECT,
    FAULTY,
    LEAVE,
    TOMBSTONE,
    state_precedence,
    non_local_override,
    local_override,
    overrides,
)
def __getattr__(name):
    # lazy: node pulls the whole host plane; semantics core stays importable
    if name in ("Node", "NodeOptions", "BootstrapOptions"):
        from ringpop_tpu.swim import node as _node

        return getattr(_node, name)
    if name == "StateTimeouts":
        from ringpop_tpu.swim.state_transitions import StateTimeouts

        return StateTimeouts
    raise AttributeError(name)


__all__ = [
    "Member",
    "Change",
    "ALIVE",
    "SUSPECT",
    "FAULTY",
    "LEAVE",
    "TOMBSTONE",
    "state_precedence",
    "non_local_override",
    "local_override",
    "overrides",
    "Node",
    "NodeOptions",
    "BootstrapOptions",
    "StateTimeouts",
]
