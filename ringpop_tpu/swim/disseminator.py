"""Piggyback change dissemination (parity: reference ``swim/disseminator.go``).

Changes ride on every ping/ping-req/ack until each has been propagated
``max_p = p_factor * ceil(log10(n_pingable + 1))`` times — the SWIM paper's
dissemination bound (``disseminator.go:75-97``).  Sender issuance bumps
counters only on delivery success (via callback); receiver issuance bumps
immediately because acks can't be confirmed (``disseminator.go:128-181``).
When there is nothing to piggyback but checksums disagree, the receiver
answers with its whole membership (full sync) and may pull the sender's view
through a bounded reverse-full-sync worker pool (``disseminator.go:257-304``).
"""

from __future__ import annotations

import asyncio
import math
from typing import Callable, Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import Change, member_to_change

DEFAULT_P_FACTOR = 15


class PChange:
    __slots__ = ("change", "p")

    def __init__(self, change: Change, p: int = 0):
        self.change = change
        self.p = p


class Disseminator:
    def __init__(self, node, p_factor: int = DEFAULT_P_FACTOR, max_reverse_full_sync_jobs: int = 5):
        self.node = node
        self.changes: dict[str, PChange] = {}
        self.p_factor = p_factor
        self.max_p = p_factor
        self.max_reverse_full_sync_jobs = max_reverse_full_sync_jobs
        self._reverse_full_sync_jobs = 0
        self.logger = logging_mod.logger("disseminator").with_field("local", node.address)

    # -- dissemination bound (parity: disseminator.go:75-97) ----------------

    def adjust_max_propagations(self) -> None:
        num_pingable = self.node.memberlist.num_pingable_members()
        new_max_p = self.p_factor * math.ceil(math.log10(num_pingable + 1))
        if new_max_p != self.max_p:
            self.node.emit(ev.MaxPAdjustedEvent(self.max_p, new_max_p))
            self.max_p = new_max_p

    # -- issuance -----------------------------------------------------------

    def has_changes(self) -> bool:
        return bool(self.changes)

    def changes_count(self) -> int:
        return len(self.changes)

    def changes_by_address(self, address: str) -> Optional[Change]:
        pc = self.changes.get(address)
        return pc.change if pc else None

    def membership_as_changes(self) -> list[Change]:
        """Entire membership as changes, for joins and full syncs
        (parity: ``disseminator.go:107-123``)."""
        return [
            member_to_change(m, self.node.address, self.node.incarnation())
            for m in self.node.memberlist.get_members()
        ]

    def issue_changes(self) -> list[Change]:
        result = [pc.change for pc in self.changes.values()]
        self.node.emit(ev.ChangesCalculatedEvent(result))
        return result

    def issue_as_sender(self) -> tuple[list[Change], Callable[[], None]]:
        """Changes for an outgoing ping/ping-req + a callback that bumps the
        piggyback counters — called only when the send succeeded
        (parity: ``disseminator.go:128-133``)."""
        changes = self.issue_changes()
        return changes, lambda: self.bump_piggyback_counters(changes)

    def issue_as_receiver(
        self, sender_address: str, sender_incarnation: int, sender_checksum: int
    ) -> tuple[list[Change], bool]:
        """Changes for a ping/ping-req response; counters bump immediately.
        Returns (changes, full_sync_triggered)
        (parity: ``disseminator.go:156-181``)."""
        changes = self.issue_changes()
        changes = self._filter_changes_from_sender(changes, sender_address, sender_incarnation)
        self.bump_piggyback_counters(changes)

        if changes or self.node.memberlist.checksum() == sender_checksum:
            return changes, False

        self.node.emit(ev.FullSyncEvent(sender_address, sender_checksum))
        self.logger.info("full sync with %s", sender_address)
        return self.membership_as_changes(), True

    def _filter_changes_from_sender(
        self, changes: list[Change], source: str, incarnation: int
    ) -> list[Change]:
        """Don't echo changes back to their source
        (parity: ``disseminator.go:185-199``)."""
        out = []
        for c in changes:
            if c.source == source and c.source_incarnation == incarnation:
                self.node.emit(ev.ChangeFilteredEvent(c))
            else:
                out.append(c)
        return out

    def bump_piggyback_counters(self, changes: list[Change]) -> None:
        for change in changes:
            pc = self.changes.get(change.address)
            if pc is None:
                continue
            pc.p += 1
            if pc.p >= self.max_p:
                del self.changes[change.address]

    # -- recording ----------------------------------------------------------

    def record_change(self, change: Change) -> None:
        self.changes[change.address] = PChange(change, 0)

    def clear_change(self, address: str) -> None:
        self.changes.pop(address, None)

    def clear_changes(self) -> None:
        self.changes.clear()

    # -- reverse full sync (parity: disseminator.go:257-304) ----------------

    def try_start_reverse_full_sync(self, target: str, timeout: float) -> Optional[asyncio.Task]:
        if self._reverse_full_sync_jobs >= self.max_reverse_full_sync_jobs:
            self.logger.info("omit reverse full sync with %s: pool exhausted", target)
            self.node.emit(ev.OmitReverseFullSyncEvent(target))
            return None
        self._reverse_full_sync_jobs += 1
        task = asyncio.ensure_future(self._reverse_full_sync_job(target, timeout))
        return task

    async def _reverse_full_sync_job(self, target: str, timeout: float) -> None:
        try:
            await self.reverse_full_sync(target, timeout)
        finally:
            self._reverse_full_sync_jobs -= 1

    async def reverse_full_sync(self, target: str, timeout: float) -> None:
        """Pull the target's membership through a join request and merge it —
        heals asymmetric divergence (parity: ``disseminator.go:283-304``)."""
        from ringpop_tpu.swim.join import send_join_request

        self.node.emit(ev.StartReverseFullSyncEvent(target))
        try:
            res = await send_join_request(self.node, target, timeout)
        except Exception as e:
            self.logger.warn("reverse full sync join request failed: %s", e)
            return
        applied = self.node.memberlist.update(res.membership)
        if not applied:
            self.node.emit(ev.RedundantReverseFullSyncEvent(target))
