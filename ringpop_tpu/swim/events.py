"""Typed SWIM events (parity: reference ``swim/events.go:40-236``).

The facade maps these to stats (``ringpop.go:385-548``); tests subscribe via
``ringpop_tpu.events.on``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List


@dataclass
class MaxPAdjustedEvent:
    old_pcount: int = 0
    new_pcount: int = 0


@dataclass
class MemberlistChangesReceivedEvent:
    changes: List[Any] = field(default_factory=list)


@dataclass
class MemberlistChangesAppliedEvent:
    changes: List[Any] = field(default_factory=list)
    old_checksum: int = 0
    new_checksum: int = 0
    num_members: int = 0


@dataclass
class FullSyncEvent:
    remote: str = ""
    remote_checksum: int = 0


@dataclass
class StartReverseFullSyncEvent:
    target: str = ""


@dataclass
class OmitReverseFullSyncEvent:
    target: str = ""


@dataclass
class RedundantReverseFullSyncEvent:
    target: str = ""


@dataclass
class JoinReceiveEvent:
    local: str = ""
    source: str = ""


@dataclass
class JoinCompleteEvent:
    duration: float = 0.0
    num_joined: int = 0
    joined: List[str] = field(default_factory=list)


@dataclass
class JoinFailedEvent:
    reason: str = ""
    error: str = ""


@dataclass
class JoinTriesUpdateEvent:
    retries: int = 0


@dataclass
class PingSendEvent:
    local: str = ""
    remote: str = ""
    changes: List[Any] = field(default_factory=list)


@dataclass
class PingSendCompleteEvent:
    local: str = ""
    remote: str = ""
    changes: List[Any] = field(default_factory=list)
    duration: float = 0.0


@dataclass
class PingReceiveEvent:
    local: str = ""
    source: str = ""
    changes: List[Any] = field(default_factory=list)


@dataclass
class PingRequestsSendEvent:
    local: str = ""
    target: str = ""
    peers: List[str] = field(default_factory=list)


@dataclass
class PingRequestsSendCompleteEvent:
    local: str = ""
    target: str = ""
    peers: List[str] = field(default_factory=list)
    peer: str = ""
    duration: float = 0.0


@dataclass
class PingRequestSendErrorEvent:
    local: str = ""
    target: str = ""
    peers: List[str] = field(default_factory=list)
    peer: str = ""


@dataclass
class PingRequestReceiveEvent:
    local: str = ""
    source: str = ""
    target: str = ""
    changes: List[Any] = field(default_factory=list)


@dataclass
class PingRequestPingEvent:
    local: str = ""
    source: str = ""
    target: str = ""
    duration: float = 0.0


@dataclass
class ProtocolDelayComputeEvent:
    duration: float = 0.0


@dataclass
class ProtocolFrequencyEvent:
    duration: float = 0.0


@dataclass
class ChecksumComputeEvent:
    duration: float = 0.0
    checksum: int = 0
    old_checksum: int = 0


@dataclass
class ChangesCalculatedEvent:
    changes: List[Any] = field(default_factory=list)


@dataclass
class ChangeFilteredEvent:
    change: Any = None


@dataclass
class RequestBeforeReadyEvent:
    endpoint: str = ""


@dataclass
class RefuteUpdateEvent:
    pass


@dataclass
class MakeNodeStatusEvent:
    status: int = 0


@dataclass
class AttemptHealEvent:
    pass


@dataclass
class DiscoHealEvent:
    pass


@dataclass
class AddJoinListEvent:
    duration: float = 0.0


@dataclass
class SelfEvictedEvent:
    phases: List[Any] = field(default_factory=list)
