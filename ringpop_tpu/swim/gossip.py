"""Gossip protocol-period driver (parity: reference ``swim/gossip.go``).

One asyncio task runs ``protocol_period → sleep(delay)``; the delay
self-tunes: ``delay = max(last_period + last_rate - now, min_period)`` with
the rate re-computed every second as 2× the median of observed period timings
(``gossip.go:88-115``) — slow networks automatically slow the gossip.
Tests drive :meth:`protocol_period` directly, the reference test suite's
synchronous-drive trick (``swim/test_utils.go:164-199``).
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.swim import events as ev
from ringpop_tpu.util.metrics import Histogram

DEFAULT_MIN_PROTOCOL_PERIOD = 0.2  # 200ms (swim/node.go:80)


class Gossip:
    def __init__(
        self,
        node,
        min_protocol_period: float = DEFAULT_MIN_PROTOCOL_PERIOD,
        rng: Optional[random.Random] = None,
    ):
        self.node = node
        self.min_protocol_period = min_protocol_period
        self._rng = rng or random.Random()
        self._stopped = True
        self.timing = Histogram(sample_size=10)
        self.timing.update(min_protocol_period)
        self._last_period: Optional[float] = None
        self._last_rate: float = min_protocol_period
        self._num_periods = 0
        self._period_task: Optional[asyncio.Task] = None
        self._rate_task: Optional[asyncio.Task] = None
        self.logger = logging_mod.logger("gossip").with_field("local", node.address)

    def stopped(self) -> bool:
        return self._stopped

    # -- self-tuning (parity: gossip.go:88-115) -----------------------------

    def compute_protocol_delay(self) -> float:
        if self._num_periods != 0:
            target = self._last_period + self._last_rate
            return max(target - self.node.clock.now(), self.min_protocol_period)
        # first tick fires at a random point within one period
        return self._rng.uniform(0, self.min_protocol_period)

    def protocol_rate(self) -> float:
        return self._last_rate

    def adjust_protocol_rate(self) -> None:
        observed = self.timing.percentile(0.5) * 2.0
        self._last_rate = max(observed, self.min_protocol_period)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if not self._stopped:
            self.logger.warn("gossip already started")
            return
        self._stopped = False
        self._period_task = asyncio.ensure_future(self._run_protocol_period_loop())
        self._rate_task = asyncio.ensure_future(self._run_protocol_rate_loop())

    def stop(self) -> None:
        if self._stopped:
            self.logger.warn("gossip already stopped")
            return
        self._stopped = True
        for t in (self._period_task, self._rate_task):
            if t is not None:
                t.cancel()
        self._period_task = self._rate_task = None

    async def _run_protocol_period_loop(self) -> None:
        try:
            while not self._stopped:
                delay = self.compute_protocol_delay()
                self.node.emit(ev.ProtocolDelayComputeEvent(delay))
                t0 = self.node.clock.now()
                await self.protocol_period()
                await asyncio.sleep(delay)
                self.node.emit(ev.ProtocolFrequencyEvent(self.node.clock.now() - t0))
        except asyncio.CancelledError:
            pass

    async def _run_protocol_rate_loop(self) -> None:
        try:
            while not self._stopped:
                await asyncio.sleep(1.0)
                self.adjust_protocol_rate()
        except asyncio.CancelledError:
            pass

    # -- one period (parity: gossip.go:178-188) -----------------------------

    async def protocol_period(self) -> None:
        start = self.node.clock.now()
        await self.node.ping_next_member()
        self._last_period = self.node.clock.now()
        self._num_periods += 1
        self.timing.update(self.node.clock.now() - start)
