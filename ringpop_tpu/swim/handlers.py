"""SWIM admin endpoints (parity: reference ``swim/handlers.go:63-168``).

``/admin/gossip{,/start,/stop,/tick}``, ``/admin/member/{join,leave}``,
``/admin/reap``, ``/admin/healpartition/disco``, ``/admin/debugSet``/
``debugClear``.
"""

from __future__ import annotations

import logging as stdlog

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.swim.member import FAULTY


def register_admin_handlers(node) -> None:
    svc = node.service

    async def gossip_toggle(body, headers):
        if node.gossip.stopped():
            node.gossip.start()
        else:
            node.gossip.stop()
        return {}

    async def gossip_start(body, headers):
        node.gossip.start()
        return {}

    async def gossip_stop(body, headers):
        node.gossip.stop()
        return {}

    async def tick(body, headers):
        await node.gossip.protocol_period()
        return {"checksum": node.memberlist.checksum()}

    async def member_join(body, headers):
        node.memberlist.reincarnate()
        return {"status": "rejoined"}

    async def member_leave(body, headers):
        node.memberlist.make_leave(node.address, node.memberlist.local.incarnation)
        return {"status": "ok"}

    async def reap(body, headers):
        # tombstone all faulty members cluster-wide via gossip
        for m in node.memberlist.get_members():
            if m.status == FAULTY:
                node.memberlist.make_tombstone(m.address, m.incarnation)
        return {"status": "ok"}

    async def heal_disco(body, headers):
        targets = await node.healer.heal()
        return {"targets": targets, "error": ""}

    async def debug_set(body, headers):
        logging_mod.set_levels({name: stdlog.DEBUG for name in ("gossip", "node", "membership")})
        return {}

    async def debug_clear(body, headers):
        logging_mod.set_levels({name: stdlog.ERROR for name in ("gossip", "node", "membership")})
        return {}

    node.channel.register(svc, "/admin/gossip", gossip_toggle)
    node.channel.register(svc, "/admin/gossip/start", gossip_start)
    node.channel.register(svc, "/admin/gossip/stop", gossip_stop)
    node.channel.register(svc, "/admin/tick", tick)
    node.channel.register(svc, "/admin/gossip/tick", tick)
    node.channel.register(svc, "/admin/member/join", member_join)
    node.channel.register(svc, "/admin/member/leave", member_leave)
    node.channel.register(svc, "/admin/reap", reap)
    node.channel.register(svc, "/admin/healpartition/disco", heal_disco)
    node.channel.register(svc, "/admin/debugSet", debug_set)
    node.channel.register(svc, "/admin/debugClear", debug_clear)
