"""Partition healing (parity: reference ``swim/heal_partition.go`` +
``swim/heal_via_discover_provider.go``).

``attempt_heal``: join the target to fetch its membership; any node that
would become unpingable after merging either view is first reincarnated by
disseminating Suspect declarations to both sides; once views are mergeable,
merge by applying B locally and pinging our membership over to B.

``DiscoverProviderHealer``: background loop attempting heals every ``period``
with probability ``base_prob / cluster_size`` (~6 provider calls/min
cluster-wide at defaults, ``swim/node.go:59-67``).
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import FAULTY, SUSPECT, Change
from ringpop_tpu.swim.join import send_join_request
from ringpop_tpu.swim.ping import send_ping_with_changes

# reference defaults (swim/node.go:59-67)
DEFAULT_HEAL_PERIOD = 30.0
DEFAULT_HEAL_BASE_PROBABILITY = 3.0
HEAL_JOIN_TIMEOUT = 1.0
MAX_HEAL_FAILURES = 10


def _select_member(changes: list[Change], address: str) -> Optional[Change]:
    for c in changes:
        if c.address == address:
            return c
    return None


def nodes_that_need_to_reincarnate(
    ma: list[Change], mb: list[Change]
) -> tuple[list[Change], list[Change]]:
    """Find nodes that would become unpingable when merging either way
    (parity: ``heal_partition.go:64-92``)."""
    changes_for_a: list[Change] = []
    changes_for_b: list[Change] = []
    for b in mb:
        a = _select_member(ma, b.address)
        if a is None:
            continue
        if b.is_pingable and a.overrides(b) and not a.is_pingable:
            changes_for_b.append(Change(address=a.address, incarnation=a.incarnation, status=SUSPECT))
        if a.is_pingable and b.overrides(a) and not b.is_pingable:
            changes_for_a.append(Change(address=b.address, incarnation=b.incarnation, status=SUSPECT))
    return changes_for_a, changes_for_b


def pingable_hosts(changes: list[Change]) -> list[str]:
    return [c.address for c in changes if c.is_pingable]


async def attempt_heal(node, target: str) -> list[str]:
    """(parity: ``heal_partition.go:33-59`` AttemptHeal)"""
    node.emit(ev.AttemptHealEvent())
    node.logger.info("attempt heal with %s", target)

    join_res = await send_join_request(node, target, HEAL_JOIN_TIMEOUT)
    ma = node.disseminator.membership_as_changes()
    mb = join_res.membership

    changes_for_a, changes_for_b = nodes_that_need_to_reincarnate(ma, mb)

    if changes_for_a or changes_for_b:
        # reincarnate first; the heal completes on a later attempt
        node.memberlist.update(changes_for_a)
        if changes_for_b:
            await send_ping_with_changes(node, target, changes_for_b, HEAL_JOIN_TIMEOUT)
        return pingable_hosts(mb)

    # mergeable: apply B locally, push A to B
    node.memberlist.update(mb)
    ma = node.disseminator.membership_as_changes()
    await send_ping_with_changes(node, target, ma, HEAL_JOIN_TIMEOUT)
    return pingable_hosts(mb)


class DiscoverProviderHealer:
    """(parity: ``heal_via_discover_provider.go``)"""

    def __init__(
        self,
        node,
        period: float = DEFAULT_HEAL_PERIOD,
        base_probability: float = DEFAULT_HEAL_BASE_PROBABILITY,
        rng: Optional[random.Random] = None,
    ):
        self.node = node
        self.period = period
        self.base_probability = base_probability
        self.previous_host_list_size = 0
        self.rng = rng or random.Random()
        self._task: Optional[asyncio.Task] = None
        self.logger = logging_mod.logger("healer").with_field("local", node.address)

    def probability(self) -> float:
        """(parity: ``heal_via_discover_provider.go:104-113``)"""
        size = max(
            self.previous_host_list_size, self.node.memberlist.count_reachable_members(), 1
        )
        self.previous_host_list_size = size
        return self.base_probability / size

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        try:
            while True:
                if self.rng.random() < self.probability():
                    await self.heal()
                await asyncio.sleep(self.period)
        except asyncio.CancelledError:
            pass

    async def heal(self) -> list[str]:
        """Attempt heals against provider hosts that are faulty-or-unknown
        locally (parity: ``heal_via_discover_provider.go:120-177``)."""
        self.node.emit(ev.DiscoHealEvent())
        provider = self.node.discover_provider
        if provider is None:
            return []
        try:
            host_list = provider.hosts()
        except Exception as e:
            self.logger.warn("healer could not get hosts: %s", e)
            return []

        self.previous_host_list_size = len(host_list)
        targets = []
        for address in host_list:
            m = self.node.memberlist.member(address)
            if m is None or m.status >= FAULTY:
                targets.append(address)
        self.rng.shuffle(targets)

        healed: list[str] = []
        failures = 0
        while targets and failures < MAX_HEAL_FAILURES:
            target = targets.pop(0)
            try:
                other_side = await attempt_heal(self.node, target)
            except Exception as e:
                self.logger.warn("heal attempt failed: %s", e)
                failures += 1
                continue
            targets = [t for t in targets if t not in other_side]
            healed.append(target)
        if failures >= MAX_HEAL_FAILURES:
            self.logger.warn("healer reached max failures")
        return healed
