"""Ping-target iteration: shuffled round-robin with reshuffle each full pass
(parity: reference ``swim/memberlist_iter.go:50-72``) — gives SWIM's
bounded-staleness probe ordering.  The sim plane's analog is a per-node
permutation stream (``ringpop_tpu.sim``)."""

from __future__ import annotations

import random
from typing import Optional

from ringpop_tpu.swim.member import Member


class MemberlistIter:
    def __init__(self, memberlist, rng: Optional[random.Random] = None):
        self.memberlist = memberlist
        self._rng = rng or random.Random()
        self._index = -1
        self._ordering: list[str] = []

    def _reshuffle(self) -> None:
        self._ordering = [m.address for m in self.memberlist.get_members()]
        self._rng.shuffle(self._ordering)
        self._index = -1

    def next(self) -> Optional[Member]:
        """Next pingable member; gives up after a full pass without finding
        one (parity: ``memberlist_iter.go:50-72``)."""
        num_members = self.memberlist.num_members()
        visited = 0
        while visited < num_members + 1:
            self._index += 1
            if self._index >= len(self._ordering) or num_members != len(self._ordering):
                self._reshuffle()
                self._index = 0
                if not self._ordering:
                    return None
            member = self.memberlist.member(self._ordering[self._index])
            if member is not None and self.memberlist.pingable(member):
                return member
            visited += 1
        return None
