"""Cluster join / bootstrap path (parity: reference ``swim/join_sender.go``,
``swim/join_handler.go``, ``swim/join_delayer.go``).

Resolve hosts from the discover provider, prefer peers on *other* physical
hosts, join in parallel groups of ``(join_size - joined) * parallelism``
until ``join_size`` distinct nodes answered or ``max_join_duration`` passes,
with jittered-shifting-window exponential backoff between rounds.  The remote
handler validates app/self and returns its full membership + checksum.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import util
from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import Change

JOIN_ENDPOINT = "/protocol/join"

# reference defaults (join_sender.go:38-52, join_delayer.go:33-36)
DEFAULT_JOIN_TIMEOUT = 1.0
DEFAULT_JOIN_SIZE = 3
DEFAULT_MAX_JOIN_DURATION = 120.0
DEFAULT_PARALLELISM_FACTOR = 2
DEFAULT_INITIAL_DELAY = 0.1
DEFAULT_MAX_DELAY = 60.0


@dataclass
class JoinRequest:
    app: str = ""
    source: str = ""
    incarnation: int = 0
    timeout: float = 0.0

    def to_wire(self) -> dict:
        # the reference's Timeout is a Go time.Duration, which encoding/json
        # marshals as INTEGER NANOSECONDS (join_sender.go:58-63) — keep that
        # unit on the wire; this codec holds float seconds internally
        return {
            "app": self.app,
            "source": self.source,
            "incarnationNumber": self.incarnation,
            "timeout": int(self.timeout * 1e9),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "JoinRequest":
        return cls(
            app=d.get("app", ""),
            source=d.get("source", ""),
            incarnation=int(d.get("incarnationNumber", 0)),
            timeout=float(d.get("timeout", 0)) / 1e9,
        )


@dataclass
class JoinResponse:
    app: str = ""
    coordinator: str = ""
    membership: list[Change] = field(default_factory=list)
    checksum: int = 0

    def to_wire(self) -> dict:
        return {
            "app": self.app,
            "coordinator": self.coordinator,
            "membership": [c.to_wire() for c in self.membership],
            "membershipChecksum": self.checksum,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "JoinResponse":
        return cls(
            app=d.get("app", ""),
            coordinator=d.get("coordinator", ""),
            membership=[Change.from_wire(c) for c in d.get("membership") or []],
            checksum=int(d.get("membershipChecksum", 0)),
        )


async def send_join_request(node, target: str, timeout: float) -> JoinResponse:
    """One join RPC (reused by bootstrap, reverse full sync and the healer —
    parity: ``join_sender.go:438-478`` sendJoinRequest)."""
    req = JoinRequest(
        app=node.app,
        source=node.address,
        incarnation=node.incarnation(),
        timeout=timeout,
    )
    body = await node.channel.call(
        target, node.service, JOIN_ENDPOINT, req.to_wire(), timeout=timeout
    )
    return JoinResponse.from_wire(body)


async def handle_join(node, body: dict, headers: dict) -> dict:
    """Validate app & non-self, answer with full membership
    (parity: ``join_handler.go:52-77``)."""
    req = JoinRequest.from_wire(body)
    if req.source == node.address:
        raise ValueError(
            f"A node tried joining a cluster by attempting to join itself. "
            f"The node, {req.source}, must join someone else."
        )
    if req.app != node.app:
        raise ValueError(
            f"A node tried joining a different app cluster. The expected app, "
            f"{node.app}, did not match the actual app, {req.app}"
        )
    node.emit(ev.JoinReceiveEvent(node.address, req.source))
    node.server_rate.mark()
    node.total_rate.mark()
    return JoinResponse(
        app=node.app,
        coordinator=node.address,
        membership=node.disseminator.membership_as_changes(),
        checksum=node.memberlist.checksum(),
    ).to_wire()


class ExponentialDelayer:
    """Jittered shifting-window exponential backoff
    (parity: ``join_delayer.go:144-191``): the jitter window for attempt N is
    [capped(N-1), capped(N)], so successive delays never shrink."""

    def __init__(
        self,
        initial: float = DEFAULT_INITIAL_DELAY,
        maximum: float = DEFAULT_MAX_DELAY,
        rng: Optional[random.Random] = None,
        sleeper=None,
    ):
        self.initial = initial
        self.max = maximum
        self.num_delays = 0
        self.next_delay_min = 0.0
        self.rng = rng or random.Random()
        self.sleeper = sleeper  # async callable; None -> asyncio.sleep

    async def delay(self) -> float:
        uncapped = self.initial * (2**self.num_delays)
        capped = min(self.max, uncapped)
        if capped == self.next_delay_min:
            jittered = capped
        else:
            jittered = self.rng.uniform(self.next_delay_min, capped)
        self.next_delay_min = capped
        self.num_delays += 1
        sleeper = self.sleeper or asyncio.sleep
        await sleeper(jittered)
        return jittered


class NullDelayer:
    async def delay(self) -> float:
        return 0.0


class JoinSender:
    """Drives the whole bootstrap join (parity: ``join_sender.go:281-435``)."""

    def __init__(
        self,
        node,
        timeout: float = 0.0,
        size: int = 0,
        max_join_duration: float = 0.0,
        parallelism_factor: int = 0,
        delayer=None,
        rng: Optional[random.Random] = None,
    ):
        self.node = node
        self.timeout = util.select_duration(timeout, DEFAULT_JOIN_TIMEOUT)
        self.size = util.select_int(size, DEFAULT_JOIN_SIZE)
        self.max_join_duration = util.select_duration(max_join_duration, DEFAULT_MAX_JOIN_DURATION)
        self.parallelism_factor = util.select_int(parallelism_factor, DEFAULT_PARALLELISM_FACTOR)
        self.delayer = delayer or ExponentialDelayer(rng=rng)
        self.rng = rng or random.Random()
        self.logger = logging_mod.logger("join").with_field("local", node.address)
        self.potential_nodes: list[str] = []

    def resolve_hosts(self) -> list[str]:
        """Provider hosts, ensuring self is present
        (parity: ``join_sender.go:128-138``), with hostname/IP sanity warning
        (``join_sender.go:171-185``)."""
        hosts = list(self.node.discover_provider.hosts())
        if self.node.address not in hosts:
            hosts.append(self.node.address)
        warning = util.check_hostname_ip_mismatch(self.node.address, hosts)
        if warning:
            self.logger.warn("%s", warning)
        return hosts

    def _partition(self, hosts: list[str]) -> tuple[list[str], list[str]]:
        """preferred = different physical host than us
        (parity: ``join_sender.go:207-233``)."""
        local_host = util.capture_host(self.node.address)
        preferred, non_preferred = [], []
        for hp in hosts:
            if hp == self.node.address:
                continue
            (non_preferred if util.capture_host(hp) == local_host else preferred).append(hp)
        return preferred, non_preferred

    def select_group(self, preferred: list[str], non_preferred: list[str], joined: set[str]) -> list[str]:
        """Draw the next round's targets, preferred-first
        (parity: ``join_sender.go:248-279``)."""
        group_size = (self.size - len(joined)) * self.parallelism_factor
        group: list[str] = []
        while len(group) < group_size and (preferred or non_preferred):
            pool = preferred if preferred else non_preferred
            candidate = util.take_node(pool, -1, self.rng)
            if candidate is None or candidate in joined:
                continue
            group.append(candidate)
        return group

    async def join_group(self, group: list[str]) -> tuple[list[str], list[Exception]]:
        """Join each target concurrently
        (parity: ``join_sender.go:364-435``)."""
        results = await asyncio.gather(
            *(send_join_request(self.node, target, self.timeout) for target in group),
            return_exceptions=True,
        )
        joined, errors = [], []
        for target, res in zip(group, results):
            if isinstance(res, BaseException):
                errors.append(res)
                continue
            self.node.memberlist.add_join_list(res.membership)
            joined.append(target)
        return joined, errors

    async def join_cluster(self) -> list[str]:
        """Rounds until join_size distinct coordinators answered or the
        duration cap passes (parity: ``join_sender.go:281-359``)."""
        hosts = self.resolve_hosts()
        self.potential_nodes = [h for h in hosts if h != self.node.address]

        if util.single_node_cluster(self.node.address, hosts):
            self.logger.info("got a single node cluster to join")
            return []

        preferred, non_preferred = self._partition(hosts)
        joined: set[str] = set()
        start = self.node.clock.now()
        num_failed_rounds = 0

        while len(joined) < self.size:
            if self.node.clock.now() - start > self.max_join_duration:
                msg = f"join duration {self.max_join_duration}s exceeded"
                self.node.emit(ev.JoinFailedEvent(reason="timeout", error=msg))
                raise JoinTimeoutError(msg)

            group = self.select_group(preferred, non_preferred, joined)
            if not group:
                # every candidate tried: successful if anyone answered,
                # otherwise retry the full candidate set after a delay
                if joined:
                    break
                preferred, non_preferred = self._partition(hosts)
                num_failed_rounds += 1
                self.node.emit(ev.JoinTriesUpdateEvent(num_failed_rounds))
                await self.delayer.delay()
                continue

            round_joined, errs = await self.join_group(group)
            joined.update(round_joined)
            if not round_joined:
                num_failed_rounds += 1
                self.node.emit(ev.JoinTriesUpdateEvent(num_failed_rounds))
                await self.delayer.delay()

        duration = self.node.clock.now() - start
        self.node.emit(
            ev.JoinCompleteEvent(duration=duration, num_joined=len(joined), joined=sorted(joined))
        )
        return sorted(joined)


class JoinTimeoutError(Exception):
    pass


async def send_join(node, **opts) -> list[str]:
    """(parity: ``join_sender.go:480-486`` sendJoin)"""
    return await JoinSender(node, **opts).join_cluster()
