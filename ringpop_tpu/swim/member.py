"""SWIM member/change semantics core — shared by host plane and sim plane.

Parity: reference ``swim/member.go``.  The five states and their precedence
(``member.go:112-128``), the override predicates (``member.go:79-110``) and
the wire tombstone-compat shims (``member.go:150-167``) are the consistency
heart of the whole protocol; they are implemented here ONCE as pure functions
over plain ints so that:

* the host plane calls them on scalars, and
* the sim plane calls the *identical expressions* on jnp/numpy int arrays
  (every function below uses only ``>``, ``&``, ``|``, ``==`` — valid for
  Python ints, numpy arrays and traced JAX values alike).

States are small ints on this side (the reference uses strings on the wire —
the wire codec translates).  Crucially the int encoding IS the precedence
order, so ``state_precedence`` is the identity; an override comparison is a
lexicographic max over ``(incarnation, state)`` — a join-semilattice, which is
what makes the sim plane's order-independent "learned change set" state
representation exact.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ringpop_tpu import util

# Member states, ordered by precedence (reference member.go:30-45,112-128).
ALIVE = 0
SUSPECT = 1
FAULTY = 2
LEAVE = 3
TOMBSTONE = 4

STATE_NAMES = ("alive", "suspect", "faulty", "leave", "tombstone")
STATE_IDS = {name: i for i, name in enumerate(STATE_NAMES)}

# unknown wire states never take precedence (parity: member.go:124-127
# statePrecedence returns -1 for unknown states rather than failing)
UNKNOWN = -1


def state_name(state: int) -> str:
    return STATE_NAMES[state] if 0 <= state < len(STATE_NAMES) else "unknown"


def state_id(name: str) -> int:
    return STATE_IDS.get(name, UNKNOWN)


def state_precedence(state):
    """Identity by construction: the int encoding is the precedence order
    (parity: ``member.go:112-128`` statePrecedence)."""
    return state


def overrides(inc_a, state_a, inc_b, state_b):
    """True when change A=(inc_a, state_a) overrides B — strictly greater in
    the (incarnation, precedence) lexicographic order
    (parity: ``member.go:178-187`` Change.overrides and
    ``member.go:79-93`` nonLocalOverride, which share this comparison)."""
    return (inc_a > inc_b) | ((inc_a == inc_b) & (state_a > state_b))


# alias matching reference naming: a non-local member applies a change iff the
# change strictly overrides the current (incarnation, state)
non_local_override = overrides


def local_override(inc_change, state_change, inc_local):
    """True when a change about the LOCAL node must be refuted by
    reincarnation: any Suspect/Faulty/Tombstone claim at incarnation >= ours
    (parity: ``member.go:98-110`` localOverride).  Works elementwise on
    arrays."""
    return is_detraction(state_change) & (inc_change >= inc_local)


def is_detraction(state):
    """Suspect/Faulty/Tombstone claims are detractions — the ones a live
    subject must refute (the predicate inside ``member.go:98-110``
    localOverride).  Elementwise on arrays."""
    return (state == SUSPECT) | (state == FAULTY) | (state == TOMBSTONE)


def is_reachable(state):
    """Alive or Suspect members count for the ring / are pingable
    (parity: ``member.go:130-132`` isReachable, ``member.go:189-191``
    isPingable)."""
    return (state == ALIVE) | (state == SUSPECT)


is_pingable = is_reachable


# -- packed override keys (sim plane) ----------------------------------------
# The (incarnation, state-precedence) lexicographic order of ``overrides``
# packs into one int32 so array engines can take lattice maxes over it.
# 5 states fit in 3 bits; incarnations get 28 bits.

KEY_STATE_BITS = 3


def pack_key(incarnation, state):
    """Order-embedding of ``overrides``: pack_key(a) > pack_key(b) iff
    change a overrides b.  Works on ints and int32 arrays."""
    return (incarnation << KEY_STATE_BITS) | state


def key_state(key):
    return key & ((1 << KEY_STATE_BITS) - 1)


def key_incarnation(key):
    return key >> KEY_STATE_BITS


@dataclass
class Member:
    """A member of the cluster as seen by one node
    (parity: ``member.go:48-53``)."""

    address: str
    status: int = ALIVE
    incarnation: int = 0

    @property
    def is_reachable(self) -> bool:
        return bool(is_reachable(self.status))

    @property
    def is_pingable(self) -> bool:
        return bool(is_pingable(self.status))

    def non_local_override(self, change: "Change") -> bool:
        return bool(non_local_override(change.incarnation, change.status, self.incarnation, self.status))

    def local_override(self, local_address: str, change: "Change") -> bool:
        if self.address != local_address:
            return False
        return bool(local_override(change.incarnation, change.status, self.incarnation))


@dataclass
class Change:
    """A membership change to disseminate (parity: ``member.go:135-145``).

    ``status`` is an int state here; the wire codec maps to the reference's
    string states and applies the tombstone back-compat shim."""

    address: str
    incarnation: int
    status: int
    source: str = ""
    source_incarnation: int = 0
    timestamp: int = 0  # integer Unix seconds (util.Timestamp codec)
    # original wire string for states we don't recognize: the reference keeps
    # unknown status strings verbatim (they decode to precedence -1 but
    # re-serialize unchanged); without this, an int-encoded UNKNOWN would
    # corrupt into a different state on re-send
    raw_status: str = ""

    def overrides(self, other: "Change") -> bool:
        return bool(
            overrides(self.incarnation, self.status, other.incarnation, other.status)
        )

    @property
    def is_pingable(self) -> bool:
        return bool(is_pingable(self.status))

    # -- wire codec (parity: member.go JSON tags + :150-167 shims) ----------

    def to_wire(self) -> dict:
        """Serialize with reference-compatible JSON keys; Tombstone is sent as
        Faulty+tombstone flag for old peers (parity: ``member.go:159-167``
        validateOutgoing)."""
        status = self.status
        d: dict[str, Any] = {
            "source": self.source,
            "sourceIncarnationNumber": self.source_incarnation,
            "address": self.address,
            "incarnationNumber": self.incarnation,
            "timestamp": int(self.timestamp),
        }
        if status == TOMBSTONE:
            d["status"] = STATE_NAMES[FAULTY]
            d["tombstone"] = True
        elif status == UNKNOWN:
            d["status"] = self.raw_status or "unknown"
        else:
            d["status"] = STATE_NAMES[status]
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "Change":
        """Parse with the incoming tombstone shim: Faulty+flag → Tombstone
        (parity: ``member.go:150-157`` validateIncoming)."""
        status = state_id(d["status"])
        if status == FAULTY and d.get("tombstone"):
            status = TOMBSTONE
        return cls(
            address=d["address"],
            incarnation=int(d["incarnationNumber"]),
            status=status,
            source=d.get("source", ""),
            source_incarnation=int(d.get("sourceIncarnationNumber", 0)),
            timestamp=int(d.get("timestamp", 0)),
            raw_status=d["status"] if status == UNKNOWN else "",
        )


def member_to_change(m: Member, source: str, source_inc: int, ts: int = 0) -> Change:
    """A full-membership entry sent on the wire (joins/full-syncs) is just a
    Change (parity: ``swim/disseminator.go:107-123`` MembershipAsChanges)."""
    return Change(
        address=m.address,
        incarnation=m.incarnation,
        status=m.status,
        source=source,
        source_incarnation=source_inc,
        timestamp=ts,
    )
