"""Authoritative member table (parity: reference ``swim/memberlist.go``).

Holds the update/override pipeline — the consistency core of SWIM
(``memberlist.go:310-390``): first-seen changes apply wholesale, detractions
about the local node are refuted by reincarnation, everything else applies by
the (incarnation, state-precedence) override rule from the shared semantics
core.  Checksum is farm32 over the reference's exact canonical string
(``memberlist.go:106-128``) so host-plane checksums are wire-compatible.
"""

from __future__ import annotations

import random
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import util
from ringpop_tpu.hashing import membership_checksum
from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import (
    ALIVE,
    FAULTY,
    LEAVE,
    SUSPECT,
    TOMBSTONE,
    Change,
    Member,
    state_name,
)


class Memberlist:
    def __init__(self, node, rng: Optional[random.Random] = None):
        self.node = node
        self.local: Optional[Member] = None
        self._members: list[Member] = []
        self._by_address: dict[str, Member] = {}
        self._checksum: int = 0
        self._rng = rng or random.Random()
        self.logger = logging_mod.logger("membership").with_field("local", node.address)
        self.compute_checksum()

    # -- queries ------------------------------------------------------------

    def member(self, address: str) -> Optional[Member]:
        return self._by_address.get(address)

    def member_at(self, i: int) -> Member:
        return self._members[i]

    def num_members(self) -> int:
        return len(self._members)

    def checksum(self) -> int:
        return self._checksum

    def pingable(self, m: Member) -> bool:
        """(parity: ``memberlist.go:180-184``)"""
        return m.address != self.node.address and m.is_pingable

    def num_pingable_members(self) -> int:
        return sum(1 for m in self._members if self.pingable(m))

    def random_pingable_members(self, n: int, excluding: set[str]) -> list[Member]:
        """n random pingable members (parity: ``memberlist.go:200-218``)."""
        candidates = [
            m for m in self._members if self.pingable(m) and m.address not in excluding
        ]
        self._rng.shuffle(candidates)
        return candidates[:n]

    def get_members(self) -> list[Member]:
        return [Member(m.address, m.status, m.incarnation) for m in self._members]

    def get_reachable_members(self) -> list[str]:
        return [m.address for m in self._members if m.is_reachable]

    def count_reachable_members(self) -> int:
        return sum(1 for m in self._members if m.is_reachable)

    # -- checksum (parity: memberlist.go:83-128) ----------------------------

    def _checksum_entries(self) -> list[str]:
        """Unsorted per-member canonical entries ``addr+status+incarnation``,
        tombstones excluded to avoid resurrecting them through full syncs."""
        return [
            f"{m.address}{state_name(m.status)}{m.incarnation}"
            for m in self._members
            if m.status != TOMBSTONE
        ]

    def gen_checksum_string(self) -> str:
        """Exact reference canonical form: sorted entries joined with ';'
        (trailing ';')."""
        return "".join(s + ";" for s in sorted(self._checksum_entries()))

    def compute_checksum(self) -> int:
        old = self._checksum
        # one native sort+join+hash call over the per-member entries;
        # bit-identical to fingerprint32(self.gen_checksum_string())
        self._checksum = membership_checksum(self._checksum_entries())
        if self.node is not None:
            self.node.emit(
                ev.ChecksumComputeEvent(checksum=self._checksum, old_checksum=old)
            )
        return self._checksum

    # -- the update pipeline (parity: memberlist.go:310-390) ----------------

    def update(self, changes: list[Change]) -> list[Change]:
        if self.node.stopped() or not changes:
            return []

        self.node.emit(ev.MemberlistChangesReceivedEvent(list(changes)))
        applied: list[Change] = []

        for change in changes:
            member = self._by_address.get(change.address)

            # first time this member is seen: take the change wholesale
            if member is None:
                if self.apply(change):
                    applied.append(change)
                continue

            # a detraction about the local node: refute by reincarnation
            if member.local_override(self.node.address, change):
                self.node.emit(ev.RefuteUpdateEvent())
                new_inc = util.now_ms(self.node.clock)
                override = Change(
                    source=self.node.address,
                    source_incarnation=new_inc,
                    address=change.address,
                    incarnation=new_inc,
                    status=ALIVE,
                    timestamp=int(self.node.clock.now()),
                )
                if self.apply(override):
                    applied.append(override)
                continue

            # non-local override by (incarnation, precedence)
            if member.non_local_override(change):
                if self.apply(change):
                    applied.append(change)

        if applied:
            old = self._checksum
            self.compute_checksum()
            self.node.emit(
                ev.MemberlistChangesAppliedEvent(
                    changes=list(applied),
                    old_checksum=old,
                    new_checksum=self._checksum,
                    num_members=self.num_members(),
                )
            )
            self.node.handle_changes(applied)
            self.node.rollup.track_updates(applied)

        return applied

    def apply(self, change: Change) -> bool:
        """Insert-or-overwrite a member from a change
        (parity: ``memberlist.go:417-460`` Apply)."""
        member = self._by_address.get(change.address)
        if member is None:
            # never create a first-seen member directly as tombstone — it
            # would re-import evicted tombstones forever through full syncs
            # (parity: memberlist.go:421-426)
            if change.status == TOMBSTONE:
                return False
            member = Member(change.address, change.status, change.incarnation)
            pos = self._join_position()
            self._members.insert(pos, member)
            self._by_address[change.address] = member
            if change.address == self.node.address:
                self.local = member
            return True
        member.status = change.status
        member.incarnation = change.incarnation
        return True

    def _join_position(self) -> int:
        """Random insert position spreads iteration order
        (parity: ``memberlist.go:409-415``)."""
        l = len(self._members)
        return self._rng.randrange(l) if l else 0

    def add_join_list(self, join_list: list[Change]) -> list[Change]:
        """Apply a (possibly huge) join list but don't gossip it onward —
        clear all resulting dissemination except our own make-alive
        (parity: ``memberlist.go:398-406``)."""
        applied = self.update(join_list)
        for change in applied:
            if change.address == self.node.address:
                continue
            self.node.disseminator.clear_change(change.address)
        return applied

    def remove_member(self, address: str) -> bool:
        member = self._by_address.pop(address, None)
        if member is None:
            return False
        self._members.remove(member)
        self.compute_checksum()
        return True

    # -- declarations (parity: memberlist.go:231-300) -----------------------

    def reincarnate(self) -> list[Change]:
        """Self back to Alive at incarnation = now-ms
        (parity: ``memberlist.go:233-236``)."""
        return self.make_alive(self.node.address, util.now_ms(self.node.clock))

    def make_alive(self, address: str, incarnation: int) -> list[Change]:
        self.node.emit(ev.MakeNodeStatusEvent(ALIVE))
        return self.make_change(address, incarnation, ALIVE)

    def make_suspect(self, address: str, incarnation: int) -> list[Change]:
        self.node.emit(ev.MakeNodeStatusEvent(SUSPECT))
        return self.make_change(address, incarnation, SUSPECT)

    def make_faulty(self, address: str, incarnation: int) -> list[Change]:
        self.node.emit(ev.MakeNodeStatusEvent(FAULTY))
        return self.make_change(address, incarnation, FAULTY)

    def make_leave(self, address: str, incarnation: int) -> list[Change]:
        self.node.emit(ev.MakeNodeStatusEvent(LEAVE))
        return self.make_change(address, incarnation, LEAVE)

    def make_tombstone(self, address: str, incarnation: int) -> list[Change]:
        self.node.emit(ev.MakeNodeStatusEvent(TOMBSTONE))
        return self.make_change(address, incarnation, TOMBSTONE)

    def evict(self, address: str) -> None:
        """Remove a member; refuses the local node
        (parity: ``memberlist.go:271-279``)."""
        if address == self.node.address:
            self.logger.error("refusing to evict the local member")
            return
        self.remove_member(address)

    def make_change(self, address: str, incarnation: int, status: int) -> list[Change]:
        if self.local is None:
            # standalone identity only — NOT inserted into the table, so the
            # self change below flows through the first-seen path of update()
            # and is emitted/applied like any other (parity:
            # memberlist.go:433-446: Apply inserts and binds m.local)
            self.local = Member(self.node.address, ALIVE, util.now_ms(self.node.clock))
        return self.update(
            [
                Change(
                    source=self.local.address,
                    source_incarnation=self.local.incarnation,
                    address=address,
                    incarnation=incarnation,
                    status=status,
                    timestamp=int(self.node.clock.now()),
                )
            ]
        )
