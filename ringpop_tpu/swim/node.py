"""SWIM node — aggregates all sub-protocols (parity: reference
``swim/node.go``).

Lifecycle: ``Node(...)`` wires memberlist/disseminator/state-transitions/
gossip/healer/rollup and registers the ``/protocol/*`` handlers; ``bootstrap``
reincarnates self, joins the cluster and starts gossip + healing; one gossip
period pings the next member with indirect ping-req fallback and Suspect
declaration (``node.go:470-513``).
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Optional

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import util
from ringpop_tpu.discovery import DiscoverProvider, as_provider
from ringpop_tpu.events import EventEmitter
from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.disseminator import Disseminator, DEFAULT_P_FACTOR
from ringpop_tpu.swim.gossip import Gossip, DEFAULT_MIN_PROTOCOL_PERIOD
from ringpop_tpu.swim.heal import (
    DEFAULT_HEAL_BASE_PROBABILITY,
    DEFAULT_HEAL_PERIOD,
    DiscoverProviderHealer,
)
from ringpop_tpu.swim.iter import MemberlistIter
from ringpop_tpu.swim.join import send_join
from ringpop_tpu.swim.member import Change
from ringpop_tpu.swim.memberlist import Memberlist
from ringpop_tpu.swim.ping import handle_ping, send_ping
from ringpop_tpu.swim.ping_request import handle_ping_request, indirect_ping
from ringpop_tpu.swim.rollup import UpdateRollup
from ringpop_tpu.swim.state_transitions import StateTimeouts, StateTransitions
from ringpop_tpu.swim.member import ALIVE, FAULTY, LEAVE, SUSPECT, TOMBSTONE
from ringpop_tpu.util.clock import Clock, MockClock, drive_clock
from ringpop_tpu.util.metrics import Meter

# reference defaults (swim/node.go:72-100)
DEFAULT_PING_TIMEOUT = 1.5
DEFAULT_PING_REQUEST_TIMEOUT = 5.0
DEFAULT_PING_REQUEST_SIZE = 3
DEFAULT_MAX_REVERSE_FULL_SYNC_JOBS = 5


class NotReadyError(Exception):
    """(parity: ``swim/node.go:41`` ErrNodeNotReady)"""

    def __str__(self) -> str:
        return "node is not ready to handle requests"


@dataclass
class NodeOptions:
    """(parity: ``swim/node.go:45-70`` Options; zero selects defaults)"""

    state_timeouts: StateTimeouts = field(default_factory=StateTimeouts)
    min_protocol_period: float = 0.0
    ping_timeout: float = 0.0
    ping_request_timeout: float = 0.0
    ping_request_size: int = 0
    max_reverse_full_sync_jobs: int = 0
    partition_heal_period: float = 0.0
    partition_heal_base_probability: float = 0.0
    p_factor: int = 0
    clock: Optional[Clock] = None
    seed: Optional[int] = None


@dataclass
class BootstrapOptions:
    """(parity: ``swim/node.go:350-373``)"""

    discover_provider: Optional[object] = None
    join_size: int = 0
    max_join_duration: float = 0.0
    parallelism_factor: int = 0
    join_timeout: float = 0.0


class Node:
    NotReadyError = NotReadyError

    def __init__(self, app: str, address: str, channel, options: Optional[NodeOptions] = None):
        opts = options or NodeOptions()
        self.app = app
        self.address = address
        self.channel = channel
        self.service = "ringpop"
        self.clock: Clock = opts.clock or Clock()
        rng_seed = opts.seed
        self._rng = random.Random(rng_seed)

        self.ping_timeout = util.select_duration(opts.ping_timeout, DEFAULT_PING_TIMEOUT)
        self.ping_request_timeout = util.select_duration(
            opts.ping_request_timeout, DEFAULT_PING_REQUEST_TIMEOUT
        )
        self.ping_request_size = util.select_int(opts.ping_request_size, DEFAULT_PING_REQUEST_SIZE)

        self.emitter = EventEmitter()
        self.logger = logging_mod.logger("node").with_field("local", address)

        self.client_rate = Meter(self.clock)
        self.server_rate = Meter(self.clock)
        self.total_rate = Meter(self.clock)

        self._ready = False
        self._stopped = False  # Go zero-value parity: a fresh node is not stopped
        self._destroyed = False
        self._pinging = False

        self.discover_provider: Optional[DiscoverProvider] = None

        self.memberlist = Memberlist(self, rng=random.Random(self._rng.random()))
        self.memberiter = MemberlistIter(self.memberlist, rng=random.Random(self._rng.random()))
        self.disseminator = Disseminator(
            self,
            p_factor=util.select_int(opts.p_factor, DEFAULT_P_FACTOR),
            max_reverse_full_sync_jobs=util.select_int(
                opts.max_reverse_full_sync_jobs, DEFAULT_MAX_REVERSE_FULL_SYNC_JOBS
            ),
        )
        self.state_transitions = StateTransitions(self, opts.state_timeouts)
        self.gossip = Gossip(
            self,
            util.select_duration(opts.min_protocol_period, DEFAULT_MIN_PROTOCOL_PERIOD),
            rng=random.Random(self._rng.random()),
        )
        self.rollup = UpdateRollup(self)
        self._clock_driver: Optional[asyncio.Task] = None
        self.healer = DiscoverProviderHealer(
            self,
            period=util.select_duration(opts.partition_heal_period, DEFAULT_HEAL_PERIOD),
            base_probability=util.select_float(
                opts.partition_heal_base_probability, DEFAULT_HEAL_BASE_PROBABILITY
            ),
            rng=random.Random(self._rng.random()),
        )
        self._register_handlers()

    # -- plumbing -----------------------------------------------------------

    def emit(self, event) -> None:
        self.emitter.emit(event)

    def register_listener(self, listener) -> None:
        self.emitter.register_listener(listener)

    def incarnation(self) -> int:
        """(parity: ``swim/node.go`` Incarnation)"""
        if self.memberlist.local is not None:
            return self.memberlist.local.incarnation
        return -1

    def _register_handlers(self) -> None:
        """(parity: ``swim/handlers.go:63-82``)"""
        from ringpop_tpu.swim.join import handle_join
        from ringpop_tpu.swim import handlers as admin

        self.channel.register(self.service, "/protocol/ping", lambda b, h: handle_ping(self, b, h))
        self.channel.register(
            self.service, "/protocol/ping-req", lambda b, h: handle_ping_request(self, b, h)
        )
        self.channel.register(self.service, "/protocol/join", lambda b, h: handle_join(self, b, h))
        admin.register_admin_handlers(self)

    # -- lifecycle (parity: node.go:281-341) --------------------------------

    def ready(self) -> bool:
        return self._ready

    def stopped(self) -> bool:
        return self._stopped

    def destroyed(self) -> bool:
        return self._destroyed

    def _start_clock_driver(self) -> None:
        # real clocks need an asyncio pump so transition timers actually
        # fire; mock clocks are driven by tests via advance()
        if isinstance(self.clock, MockClock):
            return
        if self._clock_driver is None or self._clock_driver.done():
            self._clock_driver = asyncio.ensure_future(drive_clock(self.clock))

    def _stop_clock_driver(self) -> None:
        if self._clock_driver is not None:
            self._clock_driver.cancel()
            self._clock_driver = None

    def start(self) -> None:
        self.gossip.start()
        self.state_transitions.enable()
        self._start_clock_driver()
        self._stopped = False

    def stop(self) -> None:
        self.gossip.stop()
        self.state_transitions.disable()
        self._stopped = True

    def destroy(self) -> None:
        self.stop()
        self.healer.stop()
        self.rollup.destroy()
        self._stop_clock_driver()
        self._ready = False
        self._destroyed = True

    async def bootstrap(self, opts: Optional[BootstrapOptions] = None) -> list[str]:
        """(parity: ``swim/node.go:377-416`` Bootstrap)"""
        opts = opts or BootstrapOptions()
        if opts.discover_provider is None:
            raise ValueError("a discover provider is required to bootstrap")
        self.discover_provider = as_provider(opts.discover_provider)

        self.memberlist.reincarnate()
        self._stopped = False
        joined = await send_join(
            self,
            timeout=opts.join_timeout,
            size=opts.join_size,
            max_join_duration=opts.max_join_duration,
            parallelism_factor=opts.parallelism_factor,
            rng=random.Random(self._rng.random()),
        )
        self.gossip.start()
        self.healer.start()
        self._start_clock_driver()
        self._ready = True
        return joined

    # -- change reactions (parity: node.go:424-447) -------------------------

    def handle_changes(self, changes: list[Change]) -> None:
        self.disseminator.adjust_max_propagations()
        for change in changes:
            self.disseminator.record_change(change)
            if change.status == ALIVE:
                self.state_transitions.cancel(change)
            elif change.status == SUSPECT:
                self.state_transitions.schedule_suspect_to_faulty(change)
            elif change.status == FAULTY:
                self.state_transitions.schedule_faulty_to_tombstone(change)
            elif change.status == LEAVE:
                self.state_transitions.cancel(change)
            elif change.status == TOMBSTONE:
                self.state_transitions.schedule_tombstone_to_evict(change)

    # -- gossip round (parity: node.go:470-513) -----------------------------

    async def ping_next_member(self) -> None:
        member = self.memberiter.next()
        if member is None:
            self.logger.warn("no pingable members")
            return
        if self._pinging:
            self.logger.warn("node already pinging")
            return
        self._pinging = True
        try:
            self.client_rate.mark()
            self.total_rate.mark()
            try:
                res = await send_ping(self, member.address, self.ping_timeout)
                self.memberlist.update(res.changes)
                return
            except Exception:
                pass

            target = member.address
            reached, errs = await indirect_ping(
                self, target, self.ping_request_size, self.ping_request_timeout
            )
            if len(errs) == self.ping_request_size:
                self.logger.warn("ping request inconclusive due to errors")
                return
            if not reached:
                self.logger.info("ping request target unreachable: %s", target)
                self.memberlist.make_suspect(member.address, member.incarnation)
                return
        finally:
            self._pinging = False

    # -- convenience queries ------------------------------------------------

    def get_reachable_members(self) -> list[str]:
        return self.memberlist.get_reachable_members()

    def count_reachable_members(self) -> int:
        return self.memberlist.count_reachable_members()

    def member_count(self) -> int:
        return self.memberlist.num_members()


def new_node(app: str, address: str, channel, options: Optional[NodeOptions] = None) -> Node:
    return Node(app, address, channel, options)
