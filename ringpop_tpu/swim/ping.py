"""Direct probe path (parity: reference ``swim/ping_sender.go`` +
``swim/ping_handler.go``).

Request/response both carry ``{changes, checksum, source,
sourceIncarnationNumber}`` (``ping_sender.go:35-40``); the handler applies
piggybacked changes, answers with its own changes or a full sync, and may
kick off a reverse full sync (``ping_handler.go:25-58``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import Change

PING_ENDPOINT = "/protocol/ping"
REVERSE_FULL_SYNC_TIMEOUT = 1.0  # ping_handler.go:55 (time.Second)


@dataclass
class Ping:
    changes: list[Change] = field(default_factory=list)
    checksum: int = 0
    source: str = ""
    source_incarnation: int = 0

    def to_wire(self) -> dict:
        return {
            "changes": [c.to_wire() for c in self.changes],
            "checksum": self.checksum,
            "source": self.source,
            "sourceIncarnationNumber": self.source_incarnation,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Ping":
        return cls(
            changes=[Change.from_wire(c) for c in d.get("changes") or []],
            checksum=int(d.get("checksum", 0)),
            source=d.get("source", ""),
            source_incarnation=int(d.get("sourceIncarnationNumber", 0)),
        )


async def send_ping(node, target: str, timeout: float) -> Ping:
    """Send a direct ping; piggyback counters bump only on success
    (parity: ``ping_sender.go:43-120``)."""
    changes, bump = node.disseminator.issue_as_sender()
    return await _send(node, target, changes, timeout, bump)


async def send_ping_with_changes(node, target: str, changes: list[Change], timeout: float) -> Ping:
    """Ping carrying an explicit change list — used by the partition healer
    (parity: ``ping_sender.go`` sendPingWithChanges)."""
    return await _send(node, target, changes, timeout, None)


async def _send(node, target, changes, timeout, bump) -> Ping:
    req = Ping(
        changes=changes,
        checksum=node.memberlist.checksum(),
        source=node.address,
        source_incarnation=node.incarnation(),
    )
    node.emit(ev.PingSendEvent(node.address, target, changes))
    start = node.clock.now()
    res_body = await node.channel.call(
        target, node.service, PING_ENDPOINT, req.to_wire(), timeout=timeout
    )
    node.emit(
        ev.PingSendCompleteEvent(node.address, target, changes, node.clock.now() - start)
    )
    if bump is not None:
        bump()
    return Ping.from_wire(res_body)


async def handle_ping(node, body: dict, headers: dict) -> dict:
    """(parity: ``ping_handler.go:25-58``)"""
    if not node.ready():
        node.emit(ev.RequestBeforeReadyEvent(PING_ENDPOINT))
        raise node.NotReadyError()

    req = Ping.from_wire(body)
    node.emit(ev.PingReceiveEvent(node.address, req.source, req.changes))
    node.server_rate.mark()
    node.total_rate.mark()

    node.memberlist.update(req.changes)
    changes, full_sync = node.disseminator.issue_as_receiver(
        req.source, req.source_incarnation, req.checksum
    )

    res = Ping(
        changes=changes,
        checksum=node.memberlist.checksum(),
        source=node.address,
        source_incarnation=node.incarnation(),
    )
    if full_sync:
        node.disseminator.try_start_reverse_full_sync(req.source, REVERSE_FULL_SYNC_TIMEOUT)
    return res.to_wire()
