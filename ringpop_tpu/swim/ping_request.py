"""Indirect probe path (parity: reference ``swim/ping_request_sender.go`` +
``swim/ping_request_handler.go``).

On direct-ping failure the prober asks ``k`` random pingable peers (excluding
the target) to ping the target on its behalf; any Ok answer proves the target
reachable, all-errors is inconclusive, reached-but-not-ok drives MakeSuspect
back in the node (``swim/node.go:494-510``)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ringpop_tpu.swim import events as ev
from ringpop_tpu.swim.member import Change
from ringpop_tpu.swim.ping import send_ping

PING_REQ_ENDPOINT = "/protocol/ping-req"


@dataclass
class PingRequest:
    source: str = ""
    source_incarnation: int = 0
    target: str = ""
    checksum: int = 0
    changes: list[Change] = field(default_factory=list)

    def to_wire(self) -> dict:
        return {
            "source": self.source,
            "sourceIncarnationNumber": self.source_incarnation,
            "target": self.target,
            "checksum": self.checksum,
            "changes": [c.to_wire() for c in self.changes],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PingRequest":
        return cls(
            source=d.get("source", ""),
            source_incarnation=int(d.get("sourceIncarnationNumber", 0)),
            target=d.get("target", ""),
            checksum=int(d.get("checksum", 0)),
            changes=[Change.from_wire(c) for c in d.get("changes") or []],
        )


@dataclass
class PingResponse:
    ok: bool = False
    target: str = ""
    changes: list[Change] = field(default_factory=list)

    def to_wire(self) -> dict:
        return {
            "pingStatus": self.ok,
            "target": self.target,
            "changes": [c.to_wire() for c in self.changes],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PingResponse":
        return cls(
            ok=bool(d.get("pingStatus")),
            target=d.get("target", ""),
            changes=[Change.from_wire(c) for c in d.get("changes") or []],
        )


async def _send_one_ping_request(node, peer: str, target: str, timeout: float) -> PingResponse:
    """One ping-req to one peer (parity: ``ping_request_sender.go:65-115``).
    Note the reference bumps piggyback counters on *error* here (the inverse
    of the ping path) — mirrored for parity."""
    changes, bump = node.disseminator.issue_as_sender()
    req = PingRequest(
        source=node.address,
        source_incarnation=node.incarnation(),
        target=target,
        checksum=node.memberlist.checksum(),
        changes=changes,
    )
    try:
        res_body = await node.channel.call(
            peer, node.service, PING_REQ_ENDPOINT, req.to_wire(), timeout=timeout
        )
    except Exception:
        bump()
        raise
    res = PingResponse.from_wire(res_body)
    node.memberlist.update(res.changes)
    return res


async def indirect_ping(
    node, target: str, amount: int, timeout: float
) -> tuple[bool, list[Exception]]:
    """Fan out ping-reqs; short-circuit on first Ok
    (parity: ``ping_request_sender.go:120-208``)."""
    peers = node.memberlist.random_pingable_members(amount, {target})
    peer_addresses = [p.address for p in peers]
    node.emit(ev.PingRequestsSendEvent(node.address, target, peer_addresses))

    if not peers:
        return False, []

    errs: list[Exception] = []
    reached = False
    tasks = {
        asyncio.ensure_future(_send_one_ping_request(node, p.address, target, timeout)): p.address
        for p in peers
    }
    pending = set(tasks)
    start = node.clock.now()
    try:
        while pending:
            done, pending = await asyncio.wait(pending, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                peer = tasks[t]
                err = t.exception()
                if err is not None:
                    node.emit(
                        ev.PingRequestSendErrorEvent(node.address, target, peer_addresses, peer)
                    )
                    errs.append(err)
                    continue
                res = t.result()
                node.emit(
                    ev.PingRequestsSendCompleteEvent(
                        node.address, target, peer_addresses, peer, node.clock.now() - start
                    )
                )
                if res.ok:
                    reached = True
            if reached:
                break
    finally:
        for t in pending:
            t.cancel()
    return reached, errs


async def handle_ping_request(node, body: dict, headers: dict) -> dict:
    """Peer-side: ping the target for the prober
    (parity: ``ping_request_handler.go:32-76``)."""
    if not node.ready():
        node.emit(ev.RequestBeforeReadyEvent(PING_REQ_ENDPOINT))
        raise node.NotReadyError()

    req = PingRequest.from_wire(body)
    node.emit(
        ev.PingRequestReceiveEvent(node.address, req.source, req.target, req.changes)
    )
    node.server_rate.mark()
    node.total_rate.mark()
    node.memberlist.update(req.changes)

    start = node.clock.now()
    ping_ok = False
    try:
        res = await send_ping(node, req.target, node.ping_timeout)
        ping_ok = True
        node.emit(
            ev.PingRequestPingEvent(
                node.address, req.source, req.target, node.clock.now() - start
            )
        )
        node.memberlist.update(res.changes)
    except Exception:
        pass

    changes, _ = node.disseminator.issue_as_receiver(
        req.source, req.source_incarnation, req.checksum
    )  # full sync deliberately ignored on this path (ping_request_handler.go:70)

    return PingResponse(ok=ping_ok, target=req.target, changes=changes).to_wire()
