"""Debounced update logging buffer (parity: reference ``swim/update_rollup.go``).

Buffers applied changes and flushes them as one log line when the stream goes
quiet for ``flush_interval`` — pure observability."""

from __future__ import annotations

from typing import Optional

from ringpop_tpu import logging as logging_mod

DEFAULT_FLUSH_INTERVAL = 5.0  # seconds


class UpdateRollup:
    def __init__(self, node, flush_interval: float = DEFAULT_FLUSH_INTERVAL):
        self.node = node
        self.flush_interval = flush_interval
        self._buffer: list = []
        self._last_update: Optional[float] = None
        self._timer = None
        self.logger = logging_mod.logger("rollup").with_field("local", node.address)

    def track_updates(self, changes: list) -> None:
        """(parity: ``update_rollup.go:95-123``)"""
        if not changes:
            return
        now = self.node.clock.now()
        if self._last_update is not None and now - self._last_update >= self.flush_interval:
            self.flush_buffer()
        self._buffer.extend(changes)
        self._last_update = now
        self._renew_timer()

    def _renew_timer(self) -> None:
        if self._timer is not None:
            self._timer.stop()
        self._timer = self.node.clock.after(self.flush_interval, self.flush_buffer)

    def buffer(self) -> list:
        return list(self._buffer)

    def flush_timer(self):
        return self._timer

    def flush_buffer(self) -> None:
        """(parity: ``update_rollup.go:148-186``)"""
        if self._timer is not None:
            self._timer.stop()
            self._timer = None
        if not self._buffer:
            return
        self.logger.info(
            "membership update rollup: %d updates buffered", len(self._buffer)
        )
        self._buffer.clear()

    def destroy(self) -> None:
        if self._timer is not None:
            self._timer.stop()
            self._timer = None
