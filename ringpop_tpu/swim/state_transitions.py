"""Suspicion subsystem: timer-driven member state lifecycle
(parity: reference ``swim/state_transitions.go``).

Suspect→Faulty, Faulty→Tombstone, Tombstone→evict after configured timeouts
(``state_transitions.go:90-117``).  One pending transition per member: a
same-state reschedule is ignored, a cross-state one replaces the timer; the
local node never gets a timer (``state_transitions.go:119-160``).  Timers run
on the node's mockable clock — the deadline-wheel design shared with the sim
plane's deadline arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ringpop_tpu import logging as logging_mod
from ringpop_tpu import util
from ringpop_tpu.swim.member import FAULTY, SUSPECT, TOMBSTONE


@dataclass
class StateTimeouts:
    """Seconds; zero selects the default
    (parity: ``state_transitions.go:59-76``)."""

    suspect: float = 0.0
    faulty: float = 0.0
    tombstone: float = 0.0

    def merged_with(self, defaults: "StateTimeouts") -> "StateTimeouts":
        return StateTimeouts(
            suspect=util.select_duration(self.suspect, defaults.suspect),
            faulty=util.select_duration(self.faulty, defaults.faulty),
            tombstone=util.select_duration(self.tombstone, defaults.tombstone),
        )


# reference defaults (swim/node.go:74-78)
DEFAULT_TIMEOUTS = StateTimeouts(suspect=5.0, faulty=24 * 60 * 60.0, tombstone=60.0)


class _TransitionTimer:
    __slots__ = ("timer", "state")

    def __init__(self, timer, state: int):
        self.timer = timer
        self.state = state


class StateTransitions:
    def __init__(self, node, timeouts: StateTimeouts):
        self.node = node
        self.timeouts = timeouts.merged_with(DEFAULT_TIMEOUTS)
        self.timers: dict[str, _TransitionTimer] = {}
        self.enabled = True
        self.logger = logging_mod.logger("stateTransitions").with_field("local", node.address)

    def schedule_suspect_to_faulty(self, subject) -> None:
        self._schedule(
            subject,
            SUSPECT,
            self.timeouts.suspect,
            lambda: self.node.memberlist.make_faulty(subject.address, subject.incarnation),
        )

    def schedule_faulty_to_tombstone(self, subject) -> None:
        self._schedule(
            subject,
            FAULTY,
            self.timeouts.faulty,
            lambda: self.node.memberlist.make_tombstone(subject.address, subject.incarnation),
        )

    def schedule_tombstone_to_evict(self, subject) -> None:
        self._schedule(
            subject,
            TOMBSTONE,
            self.timeouts.tombstone,
            lambda: self.node.memberlist.evict(subject.address),
        )

    def _schedule(self, subject, state: int, timeout: float, transition: Callable[[], None]) -> None:
        if not self.enabled:
            self.logger.warn("cannot schedule a transition while disabled")
            return
        if self.node.address == subject.address:
            self.logger.warn("refusing transition timer for the local member")
            return
        existing = self.timers.get(subject.address)
        if existing is not None:
            if existing.state == state:
                return  # dedup same-state reschedule
            existing.timer.stop()

        def fire():
            # the timer may have been replaced/cancelled between fire and run
            cur = self.timers.get(subject.address)
            if cur is None or cur.state != state:
                return
            del self.timers[subject.address]
            transition()

        timer = self.node.clock.after(timeout, fire)
        self.timers[subject.address] = _TransitionTimer(timer, state)

    def cancel(self, subject) -> None:
        existing = self.timers.pop(subject.address, None)
        if existing is not None:
            existing.timer.stop()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        """Stop-the-world: cancel everything
        (parity: ``state_transitions.go:179-213``)."""
        self.enabled = False
        for t in self.timers.values():
            t.timer.stop()
        self.timers.clear()

    def timer(self, address: str):
        t = self.timers.get(address)
        return t.timer if t else None
