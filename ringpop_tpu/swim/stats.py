"""Protocol/member stats snapshots (parity: reference ``swim/stats.go``)."""

from __future__ import annotations

from dataclasses import dataclass

from ringpop_tpu.swim.member import state_name


@dataclass
class MemberStats:
    address: str = ""
    status: str = ""
    incarnation: int = 0


def member_stats(node) -> dict:
    """(parity: ``swim/stats.go:36-60`` MemberStats)"""
    members = sorted(node.memberlist.get_members(), key=lambda m: m.address)
    return {
        "checksum": node.memberlist.checksum(),
        "members": [
            {
                "address": m.address,
                "status": state_name(m.status),
                "incarnationNumber": m.incarnation,
            }
            for m in members
        ],
    }


def protocol_stats(node) -> dict:
    """(parity: ``swim/stats.go:62-104`` ProtocolStats)"""
    timing = node.gossip.timing
    return {
        "timing": {
            "type": "histogram",
            "min": timing.min(),
            "max": timing.max(),
            "mean": timing.mean(),
            "count": timing.count,
            "p50": timing.percentile(0.50),
            "p95": timing.percentile(0.95),
            "p99": timing.percentile(0.99),
        },
        "protocolRate": node.gossip.protocol_rate(),
        "clientRate": node.client_rate.rate1(),
        "serverRate": node.server_rate.rate1(),
        "totalRate": node.total_rate.rate1(),
    }
