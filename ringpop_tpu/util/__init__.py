"""Utility substrate (parity: reference ``util/util.go``).

Hostport parsing/validation, shuffles, zero-means-default option selection,
millisecond time helpers and the integer-Unix ``Timestamp`` JSON codec.
"""

from __future__ import annotations

import random
import re
import time as _time
from typing import Iterable, Optional, Sequence, TypeVar

T = TypeVar("T")

_HOSTPORT_RE = re.compile(r"^(\d+\.\d+\.\d+\.\d+):\d+$")
_HOSTPORT_PATTERN = re.compile(r"^([^:]+):\d+$")


def capture_host(hostport: str) -> str:
    """Extract the host part of a ``host:port`` string; empty string when the
    input does not parse (parity: reference ``util/util.go:37-46`` CaptureHost).
    """
    m = _HOSTPORT_PATTERN.match(hostport)
    return m.group(1) if m else ""


def is_valid_hostport(hostport: str) -> bool:
    """True when the string looks like ``ip:port`` (reference validation used
    by identity checks, ``util/util.go``)."""
    return bool(_HOSTPORT_PATTERN.match(hostport))


def host_ports_by_host(host_ports: Iterable[str]) -> dict[str, list[str]]:
    """Group a list of hostports per host (parity: ``util/util.go``
    HostPortsByHost)."""
    out: dict[str, list[str]] = {}
    for hp in host_ports:
        host = capture_host(hp)
        if host:
            out.setdefault(host, []).append(hp)
    return out


def check_hostname_ip_mismatch(local: str, host_ports: Iterable[str]) -> Optional[str]:
    """Warn-condition check: mixing hostnames and IPs in a bootstrap list is a
    common misconfiguration (parity: ``util/util.go:48-85``).  Returns a
    warning message or None."""

    def is_ip(hp: str) -> bool:
        return bool(_HOSTPORT_RE.match(hp))

    local_is_ip = is_ip(local)
    mismatched = [hp for hp in host_ports if is_ip(hp) != local_is_ip]
    if not mismatched:
        return None
    kind = "hostname" if local_is_ip else "IP"
    return (
        f"local identity {local!r} mixes with {kind} entries in the bootstrap "
        f"list ({mismatched[:3]}...); all hosts should use the same form"
    )


def single_node_cluster(local: str, host_ports: Sequence[str]) -> bool:
    """True when the bootstrap list designates a single-node cluster: the only
    host is the local node itself (parity: ``util/util.go:120-128``)."""
    return len(host_ports) == 1 and host_ports[0] == local


def shuffle_strings(strings: Sequence[str], rng: Optional[random.Random] = None) -> list[str]:
    """Return a new pseudo-randomly shuffled list (parity: ``util/util.go``
    ShuffleStrings)."""
    out = list(strings)
    (rng or random).shuffle(out)
    return out


def take_node(
    nodes: list[str], index: int = -1, rng: Optional[random.Random] = None
) -> Optional[str]:
    """Remove and return a node from the list: at ``index`` when >= 0, at a
    random position otherwise (parity: ``util/util.go`` TakeNode)."""
    if not nodes:
        return None
    if index < 0:
        index = (rng or random).randrange(len(nodes))
    if index >= len(nodes):
        return None
    return nodes.pop(index)


def select_int(opt: int, default: int) -> int:
    """Zero-means-default option merge (parity: ``util/util.go:222-245``
    SelectInt)."""
    return default if opt == 0 else opt


def select_float(opt: float, default: float) -> float:
    return default if opt == 0 else opt


def select_duration(opt: float, default: float) -> float:
    """Durations are seconds (float) on this side; 0 selects the default."""
    return default if opt == 0 else opt


def ms_to_s(ms: int) -> float:
    return ms / 1000.0


def s_to_ms(s: float) -> int:
    return int(s * 1000)


def now_ms(clock=None) -> int:
    """Current wall time in milliseconds; the unit used for incarnation
    numbers (parity: ``swim/memberlist.go`` nowInMillis)."""
    if clock is not None:
        return s_to_ms(clock.now())
    return s_to_ms(_time.time())


class Timestamp(int):
    """Timestamp encoded as integer Unix *seconds* in JSON (parity:
    ``util/util.go:257-277``).  It is an ``int`` subtype so it JSON-encodes
    naturally."""

    @classmethod
    def now(cls, clock=None) -> "Timestamp":
        t = clock.now() if clock is not None else _time.time()
        return cls(int(t))
