"""Accelerator liveness probe shared by the benchmark entry points.

This environment reaches the TPU through an ``axon`` tunnel that, when
wedged, makes ``jax.devices()`` HANG indefinitely rather than raise
(round-1 artifacts recorded a 124 timeout for exactly this).  Probing in a
subprocess with a timeout is the only safe way to ask "is the accelerator
usable?" before letting the current process initialize a backend.

Reference analog: none — the Go reference talks TCP and cannot wedge this
way; this is TPU-runtime plumbing the rebuild owns.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Sequence


def probe_accelerator(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe device init + one tiny computation in a subprocess.

    Returns a diagnostic dict (JSON-serializable, embedded in bench
    artifacts): ``{"alive": bool, "platform": str|None, "probe_s": float,
    "reason": str}``.  Escalating timeouts: a cold axon tunnel can be
    slow-but-alive, so a failed quick probe earns one patient retry.
    """
    code = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "jnp.ones((8, 8)).sum().block_until_ready();"
        "print(d[0].platform)"
    )
    t0 = time.perf_counter()
    reason = "ok"
    platform: Optional[str] = None
    alive = False
    for i, timeout_s in enumerate(timeouts_s):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            reason = f"probe timeout after {timeout_s:.0f}s (attempt {i + 1})"
            continue
        if r.returncode == 0:
            alive = True
            platform = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else None
            reason = "ok"
            break
        reason = f"probe rc={r.returncode}: {(r.stderr or '').strip()[-200:]}"
    return {
        "alive": alive,
        "platform": platform,
        "probe_s": round(time.perf_counter() - t0, 1),
        "reason": reason,
    }


def ensure_live_backend(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe, then pin this process to CPU if the accelerator is dead.

    Must run before anything initializes a jax backend.  Returns the probe
    dict with a ``"fallback"`` key added (None when the accelerator is
    live, else the reason the run fell back to CPU).
    """
    info = probe_accelerator(timeouts_s=timeouts_s)
    if info["alive"]:
        info["fallback"] = None
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already up — caller initialized earlier
        info["fallback"] = info["reason"]
    return info


def compile_cache_dir(base: str, create: bool = True) -> str:
    """Return a per-platform-fingerprint subdirectory of ``base`` for the
    persistent XLA compilation cache.

    The cache must never be shared across heterogeneous containers: XLA:CPU
    kernels are compiled for the build host's CPU features, and loading one
    on a host missing those features "could lead to execution errors such
    as SIGILL" (LLVM's own warning, observed in the round-3 bench artifact
    when a shared ``.jax_cache`` crossed containers).  Keying the directory
    by platform + device kind + jax version + the host CPU flag set makes a
    mismatched entry unreachable instead of trusted.

    Requires jax to be importable; initializes the backend (callers set
    platform pins first, same as they must before any jax use)."""
    import hashlib

    import jax

    bits = ["cache-v1", jax.__version__]
    try:
        dev = jax.devices()[0]
        bits += [dev.platform, str(getattr(dev, "device_kind", ""))]
    except Exception:  # pragma: no cover - backendless environments
        bits.append("no-backend")
    try:
        with open("/proc/cpuinfo") as f:
            seen = set()
            for line in f:
                # x86 "flags" + identity lines; arm64 "Features"/"CPU part".
                # The flags line alone is NOT enough: XLA:CPU keys tuning
                # preferences (e.g. +prefer-no-gather on some Xeons) to the
                # CPU *model*, so two containers with identical CPUID flags
                # but different models produce AOT entries whose target
                # configs mismatch — observed as the "could lead to
                # execution errors such as SIGILL" loader warning even with
                # flags-keyed cache dirs.
                key = line.split(":", 1)[0].strip()
                # dedup by full LINE, not by key: a heterogeneous
                # (big.LITTLE) host lists per-core identity lines, and
                # keeping only the first core's would collide two hosts
                # that differ in later-listed cores
                if key in ("flags", "Features", "model name", "vendor_id",
                           "cpu family", "model", "stepping", "CPU part",
                           "CPU implementer") and line.strip() not in seen:
                    seen.add(line.strip())
                    bits.append(line.strip())
    except OSError:  # pragma: no cover - non-Linux
        pass
    fp = hashlib.sha1("|".join(bits).encode()).hexdigest()[:12]
    path = os.path.join(os.path.abspath(base), fp)
    if create:
        os.makedirs(path, exist_ok=True)
    return path


def configure_compile_cache(base: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at the fingerprinted subdir
    of ``base`` (see :func:`compile_cache_dir`), with the cache thresholds
    every entry point here wants (cache anything that took >= 1 s to
    compile, regardless of size).  One helper — with one default base:
    ``$RINGPOP_TPU_COMPILE_CACHE`` or ``<repo root>/.jax_cache`` — so
    bench.py, the test conftest, the driver entries, the watcher's ksweep
    and the simbench children cannot drift.  Returns the directory used,
    or None when this jax version has no cache flags (the caller runs
    uncached)."""
    import jax

    if base is None:
        base = os.environ.get("RINGPOP_TPU_COMPILE_CACHE") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        path = compile_cache_dir(base)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:
        return None
