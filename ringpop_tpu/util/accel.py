"""Accelerator liveness probe shared by the benchmark entry points.

This environment reaches the TPU through an ``axon`` tunnel that, when
wedged, makes ``jax.devices()`` HANG indefinitely rather than raise
(round-1 artifacts recorded a 124 timeout for exactly this).  Probing in a
subprocess with a timeout is the only safe way to ask "is the accelerator
usable?" before letting the current process initialize a backend.

Reference analog: none — the Go reference talks TCP and cannot wedge this
way; this is TPU-runtime plumbing the rebuild owns.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Optional, Sequence


def probe_accelerator(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe device init + one tiny computation in a subprocess.

    Returns a diagnostic dict (JSON-serializable, embedded in bench
    artifacts): ``{"alive": bool, "platform": str|None, "probe_s": float,
    "reason": str}``.  Escalating timeouts: a cold axon tunnel can be
    slow-but-alive, so a failed quick probe earns one patient retry.
    """
    code = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "jnp.ones((8, 8)).sum().block_until_ready();"
        "print(d[0].platform)"
    )
    t0 = time.perf_counter()
    reason = "ok"
    platform: Optional[str] = None
    alive = False
    for i, timeout_s in enumerate(timeouts_s):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            reason = f"probe timeout after {timeout_s:.0f}s (attempt {i + 1})"
            continue
        if r.returncode == 0:
            alive = True
            platform = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else None
            reason = "ok"
            break
        reason = f"probe rc={r.returncode}: {(r.stderr or '').strip()[-200:]}"
    return {
        "alive": alive,
        "platform": platform,
        "probe_s": round(time.perf_counter() - t0, 1),
        "reason": reason,
    }


def ensure_live_backend(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe, then pin this process to CPU if the accelerator is dead.

    Must run before anything initializes a jax backend.  Returns the probe
    dict with a ``"fallback"`` key added (None when the accelerator is
    live, else the reason the run fell back to CPU).
    """
    info = probe_accelerator(timeouts_s=timeouts_s)
    if info["alive"]:
        info["fallback"] = None
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already up — caller initialized earlier
        info["fallback"] = info["reason"]
    return info
