"""Accelerator liveness probe shared by the benchmark entry points.

This environment reaches the TPU through an ``axon`` tunnel that, when
wedged, makes ``jax.devices()`` HANG indefinitely rather than raise
(round-1 artifacts recorded a 124 timeout for exactly this).  Probing in a
subprocess with a timeout is the only safe way to ask "is the accelerator
usable?" before letting the current process initialize a backend.

Reference analog: none — the Go reference talks TCP and cannot wedge this
way; this is TPU-runtime plumbing the rebuild owns.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Optional, Sequence

_log = logging.getLogger("ringpop_tpu.accel")

# outcome of the LAST configure_compile_cache call in this process —
# {"cache_dir": str|None, "error": str|None}.  The simbench journal
# header embeds this (OBSERVABILITY.md) so a run record states whether
# the persistent cache was live and, if not, WHY — instead of readers
# inferring cache state from first_s - execute_s timing deltas.
_CACHE_STATUS: dict = {"cache_dir": None, "error": "configure_compile_cache not called"}


def cache_status() -> dict:
    """The last :func:`configure_compile_cache` outcome (copy)."""
    return dict(_CACHE_STATUS)


def probe_accelerator(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe device init + one tiny computation in a subprocess.

    Returns a diagnostic dict (JSON-serializable, embedded in bench
    artifacts): ``{"alive": bool, "platform": str|None, "probe_s": float,
    "reason": str}``.  Escalating timeouts: a cold axon tunnel can be
    slow-but-alive, so a failed quick probe earns one patient retry.
    """
    code = (
        "import jax, jax.numpy as jnp;"
        "d = jax.devices();"
        "jnp.ones((8, 8)).sum().block_until_ready();"
        "print(d[0].platform)"
    )
    t0 = time.perf_counter()
    reason = "ok"
    platform: Optional[str] = None
    alive = False
    for i, timeout_s in enumerate(timeouts_s):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            reason = f"probe timeout after {timeout_s:.0f}s (attempt {i + 1})"
            continue
        if r.returncode == 0:
            alive = True
            platform = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else None
            reason = "ok"
            break
        reason = f"probe rc={r.returncode}: {(r.stderr or '').strip()[-200:]}"
    return {
        "alive": alive,
        "platform": platform,
        "probe_s": round(time.perf_counter() - t0, 1),
        "reason": reason,
    }


def ensure_live_backend(timeouts_s: Sequence[float] = (90.0, 240.0)) -> dict:
    """Probe, then pin this process to CPU if the accelerator is dead.

    Must run before anything initializes a jax backend.  Returns the probe
    dict with a ``"fallback"`` key added (None when the accelerator is
    live, else the reason the run fell back to CPU).
    """
    info = probe_accelerator(timeouts_s=timeouts_s)
    if info["alive"]:
        info["fallback"] = None
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # backend already up — caller initialized earlier
        info["fallback"] = info["reason"]
    return info


_XLA_TARGET_BITS: Optional[list] = None


def _xla_detected_target_bits() -> list:
    """XLA:CPU's OWN detected target-machine feature string, extracted by
    compiling a tiny canary into a throwaway persistent-cache dir and
    scanning the zstd-compressed AOT entry it writes.

    Why not ``/proc/cpuinfo``: two containers can present identical cpuinfo
    text while XLA's cpuid-based detection (which also bakes in per-model
    tuning preferences like ``+prefer-no-gather``) differs — observed as
    the round-4 driver artifacts' "Target machine feature ... doesn't
    match the machine type" / "could lead to execution errors such as
    SIGILL" loader warnings surviving a cpuinfo-keyed cache split.  The
    string XLA embeds in the entry is exactly the string its loader later
    compares against the current machine, so hashing it keys the cache by
    the comparison that actually decides compatibility.

    Returns a (possibly empty) list of fingerprint bits; memoized per
    process (XLA detection is deterministic within one process).  On a
    non-CPU backend returns a platform tag only — the AOT loader warning
    class is XLA:CPU-specific."""
    global _XLA_TARGET_BITS
    if _XLA_TARGET_BITS is not None:
        return _XLA_TARGET_BITS
    import glob
    import re
    import shutil
    import tempfile

    import jax

    bits: list = []
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover - backendless environments
        _XLA_TARGET_BITS = ["xla-fp-no-backend"]
        return _XLA_TARGET_BITS
    if platform != "cpu":
        _XLA_TARGET_BITS = [f"xla-fp-accel:{platform}"]
        return _XLA_TARGET_BITS
    tmp = tempfile.mkdtemp(prefix="xla_target_probe_")
    saved = {}
    try:
        for key in (
            "jax_compilation_cache_dir",
            "jax_persistent_cache_min_compile_time_secs",
            "jax_persistent_cache_min_entry_size_bytes",
        ):
            saved[key] = getattr(jax.config, key)
        # the compilation-cache singleton binds its directory at FIRST use:
        # if anything in this process already compiled against a configured
        # cache, the tmp-dir redirect below would be ignored and the canary
        # entry would land in the real cache — reset so the canary binds tmp
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
        jax.config.update("jax_compilation_cache_dir", tmp)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        import jax.numpy as jnp

        x = jnp.arange(64.0).reshape(8, 8)
        jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x))
        feat = set()
        for path in glob.glob(os.path.join(tmp, "*")):
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError:
                continue
            blobs = [raw]
            try:
                import zstandard

                blobs.append(zstandard.ZstdDecompressor().decompress(raw))
            except Exception:
                pass
            try:
                import zlib

                # jax falls back to zlib entries when zstandard is absent
                blobs.append(zlib.decompress(raw))
            except Exception:
                pass
            for blob in blobs:
                feat.update(
                    re.findall(
                        rb"[+\-][a-z0-9][a-z0-9.\-]*(?:,[+\-][a-z0-9][a-z0-9.\-]*){10,}",
                        blob,
                    )
                )
        if feat:
            bits = ["xla-fp:" + b.decode("ascii", "replace") for b in sorted(feat)]
        else:
            bits = ["xla-fp-none"]
    except Exception:  # pragma: no cover - never block cache setup on the probe
        bits = ["xla-fp-error"]
    finally:
        for key, val in saved.items():
            try:
                jax.config.update(key, val)
            except Exception:  # pragma: no cover
                pass
        # and reset again: the canary bound the singleton to the (deleted)
        # probe dir — without this, every later write in this process would
        # still target it and persistent caching would silently stop working
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover - private API moved
            pass
        shutil.rmtree(tmp, ignore_errors=True)
    _XLA_TARGET_BITS = bits
    return bits


def compile_cache_dir(base: str, create: bool = True) -> str:
    """Return a per-platform-fingerprint subdirectory of ``base`` for the
    persistent XLA compilation cache.

    The cache must never be shared across heterogeneous containers: XLA:CPU
    kernels are compiled for the build host's CPU features, and loading one
    on a host missing those features "could lead to execution errors such
    as SIGILL" (LLVM's own warning, observed in the round-3 bench artifact
    when a shared ``.jax_cache`` crossed containers).  Keying the directory
    by platform + device kind + jax version + XLA's own detected target
    features (:func:`_xla_detected_target_bits` — the very string the AOT
    loader compares at load time) + the host CPU flag set makes a
    mismatched entry unreachable instead of trusted.

    Requires jax to be importable; initializes the backend (callers set
    platform pins first, same as they must before any jax use)."""
    import hashlib

    import jax

    bits = ["cache-v2", jax.__version__]
    try:
        dev = jax.devices()[0]
        bits += [dev.platform, str(getattr(dev, "device_kind", ""))]
    except Exception:  # pragma: no cover - backendless environments
        bits.append("no-backend")
    # XLA's own detected target features — the exact string its AOT loader
    # compares at entry-load time; see _xla_detected_target_bits.  The
    # cpuinfo lines below stay as additional segmentation (they cost only
    # extra cache dirs, never a false share).
    bits += _xla_detected_target_bits()
    try:
        with open("/proc/cpuinfo") as f:
            seen = set()
            for line in f:
                # x86 "flags" + identity lines; arm64 "Features"/"CPU part".
                # The flags line alone is NOT enough: XLA:CPU keys tuning
                # preferences (e.g. +prefer-no-gather on some Xeons) to the
                # CPU *model*, so two containers with identical CPUID flags
                # but different models produce AOT entries whose target
                # configs mismatch — observed as the "could lead to
                # execution errors such as SIGILL" loader warning even with
                # flags-keyed cache dirs.
                key = line.split(":", 1)[0].strip()
                # dedup by full LINE, not by key: a heterogeneous
                # (big.LITTLE) host lists per-core identity lines, and
                # keeping only the first core's would collide two hosts
                # that differ in later-listed cores
                if key in ("flags", "Features", "model name", "vendor_id",
                           "cpu family", "model", "stepping", "CPU part",
                           "CPU implementer") and line.strip() not in seen:
                    seen.add(line.strip())
                    bits.append(line.strip())
    except OSError:  # pragma: no cover - non-Linux
        pass
    fp = hashlib.sha1("|".join(bits).encode()).hexdigest()[:12]
    path = os.path.join(os.path.abspath(base), fp)
    if create:
        os.makedirs(path, exist_ok=True)
    return path


def configure_compile_cache(base: Optional[str] = None) -> Optional[str]:
    """Point jax's persistent compilation cache at the fingerprinted subdir
    of ``base`` (see :func:`compile_cache_dir`), with the cache thresholds
    every entry point here wants (cache anything that took >= 1 s to
    compile, regardless of size).  One helper — with one default base:
    ``$RINGPOP_TPU_COMPILE_CACHE`` or ``<repo root>/.jax_cache`` — so
    bench.py, the test conftest, the driver entries, the watcher's ksweep
    and the simbench children cannot drift.  Returns the directory used,
    or None when the cache could not be configured — an unwritable cache
    dir or missing cache flags no longer no-op SILENTLY: the reason is
    logged and recorded in :func:`cache_status` (the simbench journal
    header surfaces it)."""
    import jax

    if base is None:
        base = os.environ.get("RINGPOP_TPU_COMPILE_CACHE") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        path = compile_cache_dir(base)
        # fail HERE, with a diagnosis, if the dir cannot actually take
        # writes (read-only volume, perms): jax's own writer failures are
        # async and easy to miss — this probe is what turns "silently
        # cold every run" into one logged line + a journal-header field
        probe = os.path.join(path, f".writable.{os.getpid()}")
        with open(probe, "w") as f:
            f.write("probe")
        os.remove(probe)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # the cache singleton binds its directory at FIRST use: if this
        # process already compiled against an earlier dir (e.g. a second
        # configure call with a different base), the update above would be
        # silently ignored without a reset — rebinds lazily on next compile
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover - private API moved
            pass
        _CACHE_STATUS.update(cache_dir=path, error=None)
        return path
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"
        _CACHE_STATUS.update(cache_dir=None, error=reason)
        _log.warning(
            "persistent compile cache disabled (base %s): %s — every run "
            "in this process compiles cold", base, reason,
        )
        return None
