"""AOT warm-start executables: serialize compiled sim programs, reload
them in a fresh process, start warm.

Cold-start compile of the sharded 1M lifecycle program costs tens of
seconds (SIMBENCH_r05 ``step1m.compile_s`` 26.7 s) and the existing
``.jax_cache`` persistent compilation cache is best-effort: its key is
jax-internal (module text + compile options), a miss is silent, and
nothing in a bench record says whether a number was produced warm or
cold.  This module is the explicit plane on top:

* every program is keyed by OUR deterministic signature — tag + static
  config repr + per-leaf aval/sharding descriptors + the r8 toolchain
  fingerprint (``tests/golden_tools.fp8`` over
  ``telemetry.toolchain_fingerprint``) + a fingerprint of the
  ``ringpop_tpu`` package source (an engine edit must never serve the
  pre-edit executable as a hit) — and stored as a
  ``jax.export``-serialized artifact under the platform-fingerprinted
  cache dir (``util/accel.compile_cache_dir`` — the same segmentation
  that keeps cross-container XLA:CPU kernels unreachable);
* :func:`load_or_compile` is the one front door: a hit deserializes the
  artifact and compiles its StableHLO (skipping the python trace +
  jaxpr→StableHLO lowering entirely; the persistent cache — seeded with
  exactly this module by the miss path — makes the XLA step a
  sub-second executable load); a miss exports, compiles, and saves;
* the returned info dict carries an explicit ``cache_hit`` + measured
  ``compile_s`` — bench records stop inferring cache state from
  ``first_s - execute_s`` timing deltas.

Both paths execute the SAME exported program (the miss path compiles
its own export rather than the original jit), so hit-vs-miss is
bit-identical by construction; ``scripts/aot_smoke.py`` certifies the
cross-process reload against an in-process compile per CI run.

The front door must never break a bench: any export/serialize failure
falls back to the plain jitted callable, with the reason in
``info["error"]`` and ``cache_hit=False``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Optional

log = logging.getLogger("ringpop_tpu.aot")

_REGISTERED = False


def _register_serializations() -> None:
    """Register the sim plane's pytree containers with jax.export so
    Exported in/out trees round-trip (NamedTuple states + the registered
    fault pytrees).  Idempotent per process; individual registrations are
    best-effort because the corresponding module may be absent in a
    stripped deployment."""
    global _REGISTERED
    if _REGISTERED:
        return
    _REGISTERED = True
    try:
        from jax import export
    except ImportError:  # older jax: load_or_compile degrades to plain jit
        return

    def _named(cls, name):
        try:
            export.register_namedtuple_serialization(cls, serialized_name=name)
        except Exception:  # pragma: no cover - double registration / API drift
            pass

    try:
        from ringpop_tpu.sim.delta import DeltaState
        from ringpop_tpu.sim.lifecycle import LifecycleState

        _named(LifecycleState, "ringpop_tpu.sim.lifecycle.LifecycleState")
        _named(DeltaState, "ringpop_tpu.sim.delta.DeltaState")
    except Exception:  # pragma: no cover
        pass
    try:
        from ringpop_tpu.sim.telemetry import TelemetryState

        _named(TelemetryState, "ringpop_tpu.sim.telemetry.TelemetryState")
    except Exception:  # pragma: no cover
        pass


def toolchain_fp8() -> str:
    """8-hex digest of the r8 toolchain fingerprint (jax/jaxlib/numpy/
    python versions) — the same id the fingerprint-keyed goldens use."""
    import numpy as np  # noqa: F401 - fingerprint import guard

    from ringpop_tpu.sim.telemetry import toolchain_fingerprint

    fp = toolchain_fingerprint()
    return hashlib.sha256(json.dumps(fp, sort_keys=True).encode()).hexdigest()[:8]


_SOURCE_FP8: Optional[str] = None


def source_fp8() -> str:
    """8-hex digest of the ``ringpop_tpu`` package SOURCE — every .py
    file's content, path-keyed.  Folded into the artifact key so an
    engine edit on an unchanged toolchain cannot silently reload the
    pre-edit executable as a "hit": the traced program's code is part of
    the program's identity, exactly like the toolchain is.  Memoized per
    process (sources don't change under a running bench)."""
    global _SOURCE_FP8
    if _SOURCE_FP8 is None:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        h = hashlib.sha256()
        for root, dirs, files in sorted(os.walk(pkg)):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for f in sorted(files):
                if not f.endswith(".py"):
                    continue
                path = os.path.join(root, f)
                h.update(os.path.relpath(path, pkg).encode())
                try:
                    with open(path, "rb") as fh:
                        h.update(fh.read())
                except OSError:  # pragma: no cover - racing edit/remove
                    h.update(b"?")
        _SOURCE_FP8 = h.hexdigest()[:8]
    return _SOURCE_FP8


def default_cache_dir(create: bool = True) -> str:
    """``<compile-cache fingerprint dir>/aot`` — AOT artifacts live next
    to the persistent compilation cache entries they seed, under the same
    platform/CPU-feature fingerprinting (``accel.compile_cache_dir``), so
    a cross-container artifact is unreachable instead of trusted.
    Override base via $RINGPOP_TPU_AOT_CACHE."""
    from ringpop_tpu.util.accel import compile_cache_dir

    base = os.environ.get("RINGPOP_TPU_AOT_CACHE") or os.environ.get(
        "RINGPOP_TPU_COMPILE_CACHE"
    ) or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache",
    )
    path = os.path.join(compile_cache_dir(base, create=create), "aot")
    if create:
        os.makedirs(path, exist_ok=True)
    return path


def _leaf_descriptor(leaf) -> str:
    """Stable signature bit for one argument leaf: aval shape/dtype plus
    the device-mesh placement (axis names + shape + spec) when sharded —
    the same program on a different mesh is a different executable."""
    import jax

    aval = jax.api_util.shaped_abstractify(leaf)
    desc = f"{aval.dtype}{list(aval.shape)}"
    sh = getattr(leaf, "sharding", None)
    if sh is not None:
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "shape", None):
            desc += f"@{dict(mesh.shape)}:{getattr(sh, 'spec', '')}"
    return desc


def sharding_descriptor(tree) -> str:
    """Compact placement signature of a pytree: the set of distinct
    mesh-axis/spec descriptors its leaves carry (empty string for an
    all-unsharded tree).  Entry-point memo keys that cache ``call``
    wrappers per program fold this in so a mesh-sharded fleet never
    shares a memo slot with its unsharded twin — the r19
    fleet-sharding descriptor (the per-leaf avals are already covered
    by ``_leaf_descriptor``; this is the cheap tree-level discriminant
    for keys built before leaves are enumerated)."""
    import jax

    descs = set()
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        mesh = getattr(sh, "mesh", None)
        if mesh is not None and getattr(mesh, "shape", None):
            descs.add(f"{dict(mesh.shape)}:{getattr(sh, 'spec', '')}")
    return ";".join(sorted(descs))


def signature_key(tag: str, statics, leaves) -> str:
    """16-hex deterministic key: tag + static config reprs + leaf
    descriptors + toolchain fingerprint + package-source fingerprint
    (a source edit must never serve the pre-edit executable)."""
    bits = [tag, toolchain_fp8(), source_fp8()]
    bits += [repr(s) for s in statics]
    bits += [_leaf_descriptor(x) for x in leaves]
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:16]


def load_or_compile(
    fn: Callable,
    *args,
    tag: str,
    static_kw: Optional[dict] = None,
    dyn_kw: Optional[dict] = None,
    statics: tuple = (),
    cache_dir: Optional[str] = None,
    save: bool = True,
) -> tuple:
    """The load-or-compile front door.  Returns ``(call, info)``.

    ``fn`` is called as ``fn(*args, **dyn_kw, **static_kw)``; ``args`` and
    ``dyn_kw`` are traced pytrees (their leaves + ``statics`` +
    ``static_kw`` + the toolchain fingerprint form the artifact key),
    ``static_kw`` is closed over (compile-time constants like
    ``ticks=``).  ``call(*args2, **dyn_kw2)`` then executes the program
    on any same-structure inputs.

    ``info``: ``cache_hit`` (an artifact existed and loaded),
    ``compile_s`` (deserialize+XLA time on a hit; export+compile on a
    miss), ``key``/``path``/``cache_dir``, ``saved``, and ``error`` when
    the export plane failed and the plain jit path was used instead.
    """
    import jax

    _register_serializations()
    # the hit path's XLA step is only a sub-second executable LOAD when
    # the persistent compilation cache is live (the miss path seeds it
    # with exactly the exported module a later hit compiles) — entry
    # points configure it themselves, but the front door must not depend
    # on that ordering
    if not jax.config.jax_compilation_cache_dir:
        from ringpop_tpu.util.accel import configure_compile_cache

        configure_compile_cache()
    static_kw = static_kw or {}
    dyn_kw = dyn_kw or {}
    leaves, in_tree = jax.tree.flatten((args, dyn_kw))
    info: dict = {
        "tag": tag,
        "cache_hit": False,
        "compile_s": None,
        "saved": False,
        "error": None,
    }

    def plain(*a, **dk):
        return fn(*a, **dk, **static_kw)

    try:
        key = signature_key(
            tag, tuple(statics) + (repr(sorted(static_kw.items())),), leaves
        )
        cdir = cache_dir or default_cache_dir()
        path = os.path.join(cdir, f"{tag}-{key}.jexp")
        info.update(key=key, path=path, cache_dir=cdir)
    except Exception as e:  # pragma: no cover - fingerprint/backendless envs
        info["error"] = f"keying failed: {type(e).__name__}: {e}"
        log.warning("aot %s: %s — running uncached", tag, info["error"])
        return plain, info

    def flat_fn(*flat_leaves):
        a, dk = jax.tree.unflatten(in_tree, flat_leaves)
        return plain(*a, **dk)

    try:
        from jax import export
    except ImportError as e:  # older jax: no export plane
        info["error"] = f"jax.export unavailable: {e}"
        log.warning("aot %s: %s — running uncached", tag, info["error"])
        return plain, info

    compiled = None
    if os.path.exists(path):
        try:
            t0 = time.perf_counter()
            with open(path, "rb") as f:
                exported = export.deserialize(bytearray(f.read()))
            compiled = jax.jit(exported.call).lower(*leaves).compile()
            info["compile_s"] = round(time.perf_counter() - t0, 3)
            info["cache_hit"] = True
        except Exception as e:
            compiled = None
            info["error"] = f"load failed: {type(e).__name__}: {e}"
            log.warning(
                "aot %s: artifact %s unusable (%s) — recompiling",
                tag, path, info["error"],
            )
    if compiled is None:
        try:
            t0 = time.perf_counter()
            exported = export.export(jax.jit(flat_fn))(*leaves)
            blob = exported.serialize()
            compiled = jax.jit(exported.call).lower(*leaves).compile()
            info["compile_s"] = round(time.perf_counter() - t0, 3)
            if save:
                try:
                    tmp = path + f".tmp{os.getpid()}"
                    with open(tmp, "wb") as f:
                        f.write(bytes(blob))
                    os.replace(tmp, path)
                    info["saved"] = True
                except OSError as e:
                    info["error"] = f"save failed: {type(e).__name__}: {e}"
                    log.warning("aot %s: %s (artifact not persisted)", tag, info["error"])
        except Exception as e:
            info["error"] = f"export failed: {type(e).__name__}: {e}"
            log.warning(
                "aot %s: %s — falling back to the plain jit path", tag, info["error"]
            )
            return plain, info

    expect_desc = [_leaf_descriptor(x) for x in leaves]

    def call(*a, **dk):
        flat, tree2 = jax.tree.flatten((a, dk))
        if tree2 != in_tree or [_leaf_descriptor(x) for x in flat] != expect_desc:
            # structure OR leaf aval drifted from the keyed program (a
            # different faults pytree, a different n) — the fixed
            # executable cannot serve it; trace fresh like plain jit would
            return plain(*a, **dk)
        return compiled(*flat)

    return call, info
