"""Mockable clock (parity: the reference's ``benbjohnson/clock`` dependency).

The host plane schedules suspicion timeouts, gossip periods and stat tickers
through this interface so tests can drive time deterministically — the same
trick the reference test suite uses (``swim/test_utils.go`` mock clocks,
``ringpop_test.go:55-120``).

Timers are a deadline-wheel, not timer-per-member: ``after(delay, fn)``
registers into a sorted deadline list that ``MockClock.advance`` (tests) or the
asyncio loop (production, via :class:`AsyncClockDriver`) fires.  This is the
array-friendly design the sim plane shares (deadlines as int64 arrays).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time as _time
from typing import Callable, Optional


class Timer:
    """Handle for a scheduled callback; ``stop()`` cancels it."""

    __slots__ = ("deadline", "fn", "_cancelled", "_seq")

    def __init__(self, deadline: float, fn: Callable[[], None], seq: int):
        self.deadline = deadline
        self.fn = fn
        self._cancelled = False
        self._seq = seq

    def stop(self) -> None:
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled


class Clock:
    """Base clock: real wall time, timers fired by whoever pumps
    :meth:`fire_due` (the asyncio driver in production)."""

    def __init__(self) -> None:
        self._timers: list[tuple[float, int, Timer]] = []
        self._lock = threading.Lock()
        self._seq = itertools.count()

    def now(self) -> float:
        """Seconds (float, Unix epoch)."""
        return _time.time()

    def now_ms(self) -> int:
        return int(self.now() * 1000)

    def after(self, delay: float, fn: Callable[[], None]) -> Timer:
        """Schedule ``fn`` to run once, ``delay`` seconds from now."""
        with self._lock:
            seq = next(self._seq)
            t = Timer(self.now() + delay, fn, seq)
            heapq.heappush(self._timers, (t.deadline, seq, t))
            return t

    def next_deadline(self) -> Optional[float]:
        with self._lock:
            while self._timers and self._timers[0][2].cancelled:
                heapq.heappop(self._timers)
            return self._timers[0][0] if self._timers else None

    def fire_due(self) -> int:
        """Fire all timers whose deadline has passed; returns count fired."""
        fired = 0
        while True:
            with self._lock:
                while self._timers and self._timers[0][2].cancelled:
                    heapq.heappop(self._timers)
                if not self._timers or self._timers[0][0] > self.now():
                    break
                _, _, t = heapq.heappop(self._timers)
            try:
                t.fn()  # outside the lock: fn may schedule more timers
            except Exception:  # one bad callback must not kill the pump
                import logging

                logging.getLogger("ringpop").exception("timer callback raised")
            fired += 1
        return fired


async def drive_clock(clock: Clock, max_poll: float = 0.05) -> None:
    """Asyncio pump for a real Clock: sleeps until the next deadline (capped
    at ``max_poll`` so newly scheduled earlier timers are picked up) and
    fires due timers.  The production counterpart of MockClock.advance."""
    import asyncio

    while True:
        nd = clock.next_deadline()
        now = clock.now()
        delay = max_poll if nd is None else min(max(nd - now, 0.0), max_poll)
        await asyncio.sleep(delay)
        clock.fire_due()


class MockClock(Clock):
    """Deterministic clock for tests: time only moves via :meth:`advance` /
    :meth:`set`, which also fires due timers in deadline order."""

    def __init__(self, start: float = 0.0) -> None:
        super().__init__()
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> int:
        return self.set(self._now + dt)

    def set(self, t: float) -> int:
        fired = 0
        # step through deadlines so a timer scheduled by a firing timer can
        # itself fire within the same advance window
        while True:
            nd = self.next_deadline()
            if nd is None or nd > t:
                break
            self._now = max(self._now, nd)
            fired += self.fire_due()
        self._now = t
        return fired
