"""Minimal metrics primitives (parity: the reference's ``go-metrics`` usage —
uniform-sample histogram for protocol timing ``swim/gossip.go:65-66`` and
1-minute meters for client/server/total rates ``swim/stats.go``)."""

from __future__ import annotations

import math
import random
import time as _time


class Histogram:
    """Uniform (reservoir) sample histogram."""

    def __init__(self, sample_size: int = 10, seed: int = 0):
        self.sample_size = sample_size
        self._sample: list[float] = []
        self._count = 0
        self._rng = random.Random(seed)

    def update(self, value: float) -> None:
        self._count += 1
        if len(self._sample) < self.sample_size:
            self._sample.append(value)
        else:
            i = self._rng.randrange(self._count)
            if i < self.sample_size:
                self._sample[i] = value

    def percentile(self, p: float) -> float:
        if not self._sample:
            return 0.0
        s = sorted(self._sample)
        idx = p * (len(s) + 1)
        if idx < 1:
            return s[0]
        if idx >= len(s):
            return s[-1]
        lo = s[int(idx) - 1]
        hi = s[int(idx)]
        return lo + (idx - int(idx)) * (hi - lo)

    def percentiles(self, ps: list[float]) -> list[float]:
        return [self.percentile(p) for p in ps]

    @property
    def count(self) -> int:
        return self._count

    def mean(self) -> float:
        return sum(self._sample) / len(self._sample) if self._sample else 0.0

    def min(self) -> float:
        return min(self._sample) if self._sample else 0.0

    def max(self) -> float:
        return max(self._sample) if self._sample else 0.0


class Meter:
    """EWMA rate meter (1-minute), mark()-based."""

    _ALPHA_1M = 1 - math.exp(-5.0 / 60.0)

    def __init__(self, clock=None):
        self._clock = clock
        self._count = 0
        self._rate = 0.0
        self._uncounted = 0
        self._last_tick = self._now()

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else _time.time()

    def mark(self, n: int = 1) -> None:
        self._tick_if_needed()
        self._count += n
        self._uncounted += n

    def _tick_if_needed(self) -> None:
        now = self._now()
        while now - self._last_tick >= 5.0:
            inst = self._uncounted / 5.0
            self._uncounted = 0
            self._rate += self._ALPHA_1M * (inst - self._rate)
            self._last_tick += 5.0

    @property
    def count(self) -> int:
        return self._count

    def rate1(self) -> float:
        self._tick_if_needed()
        return self._rate
