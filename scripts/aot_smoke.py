"""aot-smoke — the CI gate for the AOT warm-start plane (util/aot.py).

Proves, per run, the property the r11 bench integrations rely on:

1. **serialize**: this process routes a sharded lifecycle tick block
   through ``aot.load_or_compile`` against a FRESH cache dir (a miss by
   construction), runs one block, and digests the result;
2. **reload warm in a fresh process**: a subprocess loads the SAME
   program through the front door — it must report ``cache_hit=True``
   with ``compile_s`` under the 2 s warm-start bar — runs the same
   block, and prints its digest;
3. **bit-identity**: the child's digest must equal the parent's
   in-process one (a reloaded executable computes exactly what the
   compile it came from computed), and the front-door output must be
   bit-equal to the plain jitted path, leaf for leaf.

The pipelined exchange (the r11 default sharded lowering) is what gets
serialized, so this gate also re-certifies that the pipelined program
survives an export round-trip.  Exit 0 on success, 1 with a diagnosis.

Usage:
    python scripts/aot_smoke.py [--cache DIR] [--warm-bar SECONDS]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, K, SEED, TICKS = 2048, 64, 0, 8


def _run_block(cache_dir: str) -> dict:
    """Route the sharded block through the front door; return the
    front-door info + state digest + leaf-equality vs the plain jit."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    jax.config.update("jax_platforms", "cpu")
    from ringpop_tpu.sim import lifecycle, telemetry
    from ringpop_tpu.sim.delta import DeltaFaults
    from ringpop_tpu.util import aot

    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    params = lifecycle.LifecycleParams(
        n=N, k=K, suspect_ticks=10, rng="counter", exchange_mesh=mesh
    )
    up = np.ones(N, bool)
    up[::64] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    state = jax.tree.map(
        jax.device_put,
        lifecycle.init_state(params, seed=SEED),
        lifecycle.state_shardings(mesh, k=K),
    )
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    call, info = aot.load_or_compile(
        blk, state, faults, tag="aot-smoke", static_kw={"ticks": TICKS},
        statics=(repr(params),), cache_dir=cache_dir,
    )
    out = call(state, faults)
    jax.block_until_ready(out.learned)
    ref = blk(state, faults, ticks=TICKS)
    leaf_equal = all(
        bool((np.asarray(a) == np.asarray(b)).all())
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out))
    )
    return {
        "cache_hit": info["cache_hit"],
        "compile_s": info["compile_s"],
        "saved": info["saved"],
        "error": info["error"],
        "digest": int(telemetry.tree_digest(out)),
        "leaf_equal_vs_jit": leaf_equal,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cache", default=None,
                    help="AOT cache dir (default: a fresh temp dir, so the "
                    "first pass is a miss by construction)")
    ap.add_argument("--warm-bar", type=float, default=2.0,
                    help="max seconds for the fresh-process warm load")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        print("AOTSMOKE " + json.dumps(_run_block(args.cache)), flush=True)
        return 0

    own_cache = args.cache is None
    cache = args.cache or tempfile.mkdtemp(prefix="aotsmoke_")
    try:
        return _smoke(cache, args)
    finally:
        if own_cache:  # don't leak one artifact dir per `make test` run
            import shutil

            shutil.rmtree(cache, ignore_errors=True)


def _smoke(cache: str, args) -> int:
    failures: list[str] = []

    first = _run_block(cache)
    if first["error"]:
        failures.append(f"front door errored on the serialize pass: {first['error']}")
    if not first["cache_hit"] and not first["saved"] and not first["error"]:
        failures.append("miss pass saved no artifact and reported no error")
    if not first["leaf_equal_vs_jit"]:
        failures.append("front-door output diverged from the plain jitted block")

    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "--cache", cache],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    child = None
    for ln in reversed(r.stdout.strip().splitlines()):
        if ln.startswith("AOTSMOKE "):
            child = json.loads(ln[len("AOTSMOKE "):])
            break
    if child is None:
        failures.append(
            f"fresh-process reload produced no result (rc={r.returncode}): "
            + (r.stderr or "")[-300:]
        )
    else:
        if child["error"]:
            failures.append(f"fresh-process front door errored: {child['error']}")
        if not child["cache_hit"]:
            failures.append("fresh process MISSED the cache — the artifact key "
                            "is unstable across processes")
        elif child["compile_s"] is None or child["compile_s"] > args.warm_bar:
            failures.append(
                f"warm reload took {child['compile_s']} s (bar {args.warm_bar} s) "
                "— the serialized-executable path stopped being warm"
            )
        if child["digest"] != first["digest"]:
            failures.append(
                f"reloaded executable diverged: digest {child['digest']:#010x} "
                f"vs in-process {first['digest']:#010x}"
            )
        if not child["leaf_equal_vs_jit"]:
            failures.append("reloaded output diverged from a fresh in-process compile")

    if failures:
        print("aot-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print(
        f"aot-smoke: OK — serialized at {cache} "
        f"(miss compile {first['compile_s']} s), fresh process reloaded warm "
        f"in {child['compile_s']} s (< {args.warm_bar} s) with bit-identical "
        f"block digest {child['digest']:#010x}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
