"""bench-trend — regression tripwire over the committed BENCH_*.json
trajectory.

Every round that runs ``bench.py`` commits its one-line JSON artifact as
``BENCH_rNN.json`` (``{"n", "cmd", "rc", "tail", "parsed": {...}}``).
Those files form a perf trajectory nobody was reading: a slow drift in
a secondary metric (the r21 honest-cost note: ``transport_rtt_us``) can
ride along unnoticed for rounds.  This script closes that gap:

1. load every committed ``BENCH_*.json``, keep each tracked row's
   NEWEST committed value (highest ``n`` whose ``parsed`` carries it);
2. take a fresh measurement — by default the quick path (only
   ``transport_rtt_us`` via ``bench._transport_rtt_us``, a few seconds,
   no jax import), or a full pre-captured bench JSON via ``--fresh``;
3. compare direction-aware: a row regresses when it is worse than the
   newest committed value by more than ``--threshold`` (default 15%).
   "Worse" respects each metric's direction — RTT up is a regression,
   lookup qps down is a regression.  Headline ``value`` rows are only
   comparable when the ``metric`` names match exactly (a 100k-node
   detect time vs a 1M-node one is not a trend, it's a scale change).

Exit 1 on any regression, 0 otherwise; ``--report-only`` always exits 0
(how ``make test`` wires it — the tripwire reports in CI, gates only
when invoked as ``make bench-trend``).  Prints one JSON summary line.

Usage:
    python scripts/bench_trend.py                  # quick, gating
    python scripts/bench_trend.py --report-only    # quick, report only
    python scripts/bench_trend.py --fresh out.json # compare a full run
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# tracked rows: key -> direction ("lower" is better / "higher" is
# better).  Only unambiguous rows belong here — compile times etc. are
# too noisy per container to gate on.
DIRECTIONS = {
    "value": "lower",  # headline detect/convergence seconds (same metric only)
    "transport_rtt_us": "lower",
    "ring_lookup_qps": "higher",
    "serve_lookup_qps": "higher",
    "ticks_per_s": "higher",
    "delta_converge_s": "lower",
}


def load_committed() -> dict[str, dict]:
    """Newest committed value per tracked row: key -> {n, value, metric}."""
    newest: dict[str, dict] = {}
    for path in glob.glob(os.path.join(REPO, "BENCH_*.json")):
        m = re.search(r"BENCH_r?(\d+)\.json$", os.path.basename(path))
        if m is None:
            continue
        n = int(m.group(1))
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
        except (OSError, ValueError):
            continue  # a truncated artifact is not a trend point
        for key in DIRECTIONS:
            v = parsed.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if key not in newest or n > newest[key]["n"]:
                newest[key] = {
                    "n": n, "value": float(v),
                    "metric": parsed.get("metric"),
                }
    return newest


def fresh_quick() -> dict:
    """The quick fresh measurement: transport RTT only (no jax).

    Best-of-N p50: single p50s swing ~25% with scheduler luck on shared
    CPU containers; the min measures the channel's floor, which is what
    actually trends when the RPC plane grows a thread hop.  The
    committed BENCH_r22 row was taken the same way (best-of-3); the
    fresh side takes 5 for extra margin against a one-sided gate.  r23:
    the probe returns ``{"p50_us", "p99_us"}`` (trimmed median-of-
    batches p50) — the tracked row is the p50."""
    import bench

    return {
        "metric": "transport_rtt_quick",
        "transport_rtt_us": round(
            min(bench._transport_rtt_us(400)["p50_us"] for _ in range(5)), 1
        ),
    }


def compare(fresh: dict, committed: dict[str, dict], threshold: float) -> list:
    rows = []
    for key, base in sorted(committed.items()):
        v = fresh.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue  # fresh run did not measure this row
        if key == "value" and fresh.get("metric") != base["metric"]:
            continue  # headline seconds only trend at identical scale
        direction = DIRECTIONS[key]
        baseline = base["value"]
        if baseline == 0:
            continue
        change = (float(v) - baseline) / abs(baseline)
        worse = change if direction == "lower" else -change
        rows.append({
            "row": key,
            "fresh": float(v),
            "committed": baseline,
            "committed_round": base["n"],
            "direction": direction,
            "change_pct": round(change * 100, 1),
            "regressed": worse > threshold,
        })
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fresh", metavar="JSON", default=None,
                    help="path to a full bench.py JSON artifact (the "
                         "one-line result or a BENCH_rNN.json wrapper); "
                         "default: quick in-process transport RTT probe")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="regression threshold as a fraction (default 0.15)")
    ap.add_argument("--report-only", action="store_true",
                    help="always exit 0 (make test wiring)")
    args = ap.parse_args()

    committed = load_committed()
    if args.fresh:
        with open(args.fresh) as f:
            fresh = json.load(f)
        fresh = fresh.get("parsed", fresh)  # accept either shape
    else:
        fresh = fresh_quick()

    rows = compare(fresh, committed, args.threshold)
    regressed = [r for r in rows if r["regressed"]]
    print(json.dumps({
        "bench_trend": {
            "fresh_metric": fresh.get("metric"),
            "threshold_pct": args.threshold * 100,
            "rows": rows,
            "regressions": [r["row"] for r in regressed],
        }
    }))
    if not rows:
        print("bench-trend: no comparable rows (fresh run measured none of "
              "the committed trajectory) — nothing to gate")
        return 0
    if regressed:
        for r in regressed:
            arrow = "rose" if r["direction"] == "lower" else "fell"
            print(f"bench-trend: REGRESSION {r['row']} {arrow} "
                  f"{abs(r['change_pct'])}% vs BENCH_r{r['committed_round']:02d} "
                  f"({r['committed']} -> {r['fresh']})")
        return 0 if args.report_only else 1
    print(f"bench-trend: OK ({len(rows)} rows within "
          f"{args.threshold * 100:.0f}%)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
