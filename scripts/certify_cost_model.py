"""Judge a fresh on-chip capture against PERF.md's round-4 cost model.

Reads the watcher's ksweep capture (``.tpu_ksweep.json`` /
``captures/tpu_ksweep_*``) and prints, per measurement, whether it
CERTIFIES or REFUTES the reconciled per-tick model — so folding a tunnel
window's numbers into PERF.md is a transcription job, not an analysis one.

The model under test (PERF.md "Round 4: the reconciled on-chip per-tick
story"):

- v5e-1 packed lifecycle tick at 1M: ~2-15 ms/tick at k=128..256,
  roughly linear in k (the retracted round-2 reading was 142 ms/tick at
  k=128; anything within ~5x of 142 ms at its k REFUTES the round-4
  model and reinstates the trace reading).
- 1M detection at the headline config: well under the 60 s north star.
- 16M delta convergence: sub-second-per-tick scale corroboration.
- (r6→r8) the multi-chip ICI projection: the sharded tick's collective
  budget is ~115 collectives / ~42.5 MB/chip/tick after the r8
  shard-local exchange legs + counter RNG
  (captures/mesh_profile_r8_after.json; was ~118/~83 at r6), so a
  ksweep window exposing >1 real device records a ``sharded_tick``
  section and its median is judged against the ICI-floor..single-chip
  bracket — and the committed budget capture itself is re-checked
  against the bracket constants.
- (r8) the exchange-leg A/B: the same window records
  ``sharded_exchange`` — the shard_map crossing-block legs vs the
  partitioner roll gathers, same counter RNG both sides.  The r8 model
  says the shard_map legs move ~2.6× fewer exchange bytes, so on real
  ICI they must be no slower (and should be faster); slower REFUTES the
  lowering, as does any bit-inequality.
- (r11) the pipelined-exchange A/B: ``pipelined_exchange`` — the fused
  leg loop (``shard_roll_pipelined``, response-leg sends issued while
  the request merge computes) vs the sequential r8 legs.  The census
  says both move IDENTICAL collective counts/bytes, so the model
  predicts the pipelined side is no slower — any win is overlap the
  schedule now hides.  Slower beyond noise REFUTES the pipelining (the
  fused switch costs more than the overlap buys), as does any
  bit-inequality.
- (r12) the batched chaos fleet: ``mc_chaos`` — B stacked-FaultPlan
  scenarios as one vmapped program vs the same B stepped sequentially,
  both warm.  The model says the fleet amortizes per-dispatch overhead,
  so it must be no slower per tick; slower REFUTES the fleet lowering,
  as does any scenario's final state diverging from its solo run.
- (topology round) the tier machinery A/B: ``topo_chaos`` — the
  topology-enabled chaos tick (rack/zone/region tier legs FORCED with a
  zero drop table, so every tier coin passes) vs the flat chaos tick.
  The separate-coin model says zero-table legs change no values —
  bit-unequal REFUTES the lowering — and the tier evaluation (id
  gathers + blocked one-hot table expansion + coin sites) must cost
  <= 10% over the flat tick on real hardware.
- (r13) the serve tier's shared-ring dispatch: ``serve_lookup`` — the
  capacity-padded fused lookup program (owners + generation, one
  transfer) over a 1M-vnode ring vs the per-process host bisect walk,
  bit_equal per key.  The serving model says one amortized device
  dispatch beats a host process by >= 2x per-key throughput (the CPU
  container already shows >2x END TO END through sockets/shm; the raw
  dispatch on a real chip should be orders beyond) — less than 2x or
  any bit-inequality REFUTES the serve-tier premise.

- (r15) the DCN wire codec A/B: ``dcn_wire`` — unlike every item above
  this is NOT behind the TPU gate (fabric bytes + wall-clock are host
  measurements), so the judge reads the committed SIMBENCH_r09.json
  artifact directly and runs even with no ksweep capture on disk.  The
  wire model says sparsity-aware encoding moves >= 2x fewer MB/tick/host
  than raw frames averaged over the run at no wall-clock cost;
  bit-unequal digests or slower-than-raw REFUTES the codec.

- (r16) the exchange-schedule + cross-tick-pipelining A/B:
  ``swing_overlap`` — also host-level (SIMBENCH_r10.json).  The model
  says the async completion layer's overlap must not lose wall-clock vs
  the blocking r15 path (min-of-interleaved-reps, the noise-floor
  estimator on this shared container) and the swing relay schedule must
  stay bit-identical and within noise of cyclic while its relay bytes
  are priced explicitly; any bit-inequality, a pipelined min-wall above
  sequential, or swing beyond 1.05x cyclic REFUTES.  (The real-DCN leg
  pricing of the same schedules is the ksweep ``swing_exchange``
  section, behind the TPU gate.)

- (r17) the production-fan-in serve plane: ``serve_fanin`` — also
  host-level (SIMBENCH_r11.json), judged with or without a ksweep
  capture.  The serve model says the P∈{1,2,4} mesh answers every
  (owner, successors, generation) tuple digest-identical to the
  single-process oracle, the forwarding plane coalesces so message
  count is O(owners) — STRICTLY below one-per-forwarded-key naive —
  and quorum replica reads hold ⌈(R+1)/2⌉ acks while a FaultPlan kills
  owners mid-read.  Bit-unequal digests, per-key RPC count not
  strictly below naive, or a lost quorum REFUTES.  (The real-chip
  keys/s pricing of the same plane is the ksweep ``serve_fanin``
  section, behind the TPU gate.)

- (r19) the million-replica scenario fleet: ``fleet_scale`` — also
  host-level (SIMBENCH_r13.json), judged with or without a ksweep
  capture.  The fleet model says: batch-axis process slicing is
  bit-exact per scenario (P=2 digests+scores == P=1 unbroken) AND
  actually shards residency (max per-rank peak RSS at P=2 < 0.75 of
  P=1); a mid-sweep orbax fleet checkpoint restores onto a DIFFERENT
  process count and reproduces the unbroken run's digests and score
  records bit-exactly; the GSPMD batch-mesh twin is digest-equal; and
  the adaptive cliff driver lands the dense 1-dose grid's cliff
  coordinate at <= 1/4 the scenario-evaluations.  Any inequality, an
  RSS fraction >= 0.75, or a cheaper-than-claimed search that missed
  the coordinate REFUTES.  (The real-chip batch-sharded-vs-replicated
  pricing is the ksweep ``fleet_scale`` section, behind the TPU gate:
  bit-unequal or slower than the replicated layout beyond noise
  REFUTES — batch sharding must be free compute, pure HBM headroom.)

Usage: ``python scripts/certify_cost_model.py [capture.json]``
(defaults to the newest ksweep capture found).
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# model bounds: predicted ms/tick per k at n=1M on a v5e-1 class chip,
# PACKED engine.  Generous brackets — the point is to separate the two
# competing models (~2-15 ms vs ~142 ms at k=128), not to grade noise.
MODEL_MS_PER_TICK = {128: (0.5, 30.0), 256: (1.0, 60.0), 512: (2.0, 120.0)}
RETRACTED_MS_AT_K128 = 142.0
NORTH_STAR_S = 60.0

# multi-chip ICI model (r6, re-based r8): the sharded 1M x 256 tick's
# collective budget, measured from partitioned HLO on the 8-virtual-device
# mesh (captures/mesh_profile_r8_after.json — ~115 collectives, ~42.5
# MB/chip/tick with the shard-local exchange legs + counter RNG; the r6
# figure was ~118/~83, and 297/~193 before r6).  At public v5e ICI rates
# (~90–180 GB/s/chip) 42.5 MB is ~0.25–0.5 ms/tick plus ~0.1–0.3 ms of
# launch latency, against a ~3–10 ms single-chip HBM tick — so the
# 8-way sharded tick should land BETWEEN the ICI floor and the
# single-chip tick.  A sharded tick slower than one chip's REFUTES the
# projection (ICI or partitioner overhead dominates after all); so does
# one faster than the floor (the budget numbers are off).
MULTICHIP_BUDGET = {
    "collectives_per_tick_max": 150,  # 115 measured + partitioner noise
    "mb_per_chip_tick_max": 60.0,  # 42.5 measured + headroom
}
MULTICHIP_SHARDED_MS_PER_TICK = (0.2, 60.0)  # floor..~single-chip k=256 hi
# budget captures this script can re-check, newest first, each judged
# against ITS OWN era's budget (an r6-era capture meeting the r6 budget
# is not a failure just because r8 tightened the bar; only the newest
# capture present on disk is re-checked)
BUDGET_CAPTURES = (
    ("mesh_profile_r8_after.json", MULTICHIP_BUDGET),
    ("mesh_profile_r6_after.json",
     {"collectives_per_tick_max": 180, "mb_per_chip_tick_max": 120.0}),
)


def newest_ksweep() -> str | None:
    # the r3 archive (tpu_ksweep_r3_*) cannot match this glob — only
    # dated round-4+ captures are considered
    cands = sorted(glob.glob(os.path.join(REPO, "captures", "tpu_ksweep_2*.json")))
    if cands:
        return cands[-1]
    p = os.path.join(REPO, ".tpu_ksweep.json")
    return p if os.path.exists(p) else None


def judge_dcn_wire():
    """The r15 wire-codec verdict from the committed SIMBENCH_r09.json —
    host-certifiable, so it is judged with or without a ksweep capture.
    Returns a (name, ok, detail) verdict tuple, or None when the
    artifact does not exist."""
    path = os.path.join(REPO, "SIMBENCH_r09.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ("dcn wire codec A/B", None, f"unreadable SIMBENCH_r09.json: {e}")
    sc = next(
        (s for s in data.get("scenarios", [])
         if str(s.get("metric", "")).startswith("dcn_wire")),
        None,
    )
    if sc is None:
        return ("dcn wire codec A/B", None,
                "SIMBENCH_r09.json carries no dcn_wire scenario")
    ratio, wall = sc.get("wire_ratio"), sc.get("wall_ratio_on_over_off")
    ok = (
        bool(sc.get("digests_equal")) and bool(sc.get("twin_certified"))
        and ratio is not None and ratio >= 2.0
        and wall is not None and wall <= 1.05
    )
    return (
        f"dcn wire codec A/B (n={sc.get('n_nodes')}, P=2)",
        ok,
        f"wire {sc.get('wire_mb_per_tick_on')} vs raw "
        f"{sc.get('wire_mb_per_tick_off')} MB/tick/host = {ratio}x "
        f"(dissemination phase {sc.get('dissemination_ratio')}x), "
        f"wall on/off {wall} (<= 1.05 required), "
        f"digests_equal={sc.get('digests_equal')} "
        f"twin_certified={sc.get('twin_certified')}",
    )


def judge_swing_overlap():
    """The r16 schedule/pipelining verdict from the committed
    SIMBENCH_r10.json — host-certifiable, judged with or without a
    ksweep capture.  Returns a (name, ok, detail) tuple, or None when
    the artifact does not exist."""
    path = os.path.join(REPO, "SIMBENCH_r10.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ("swing/overlap exchange A/B", None,
                f"unreadable SIMBENCH_r10.json: {e}")
    sc = next(
        (s for s in data.get("scenarios", [])
         if str(s.get("metric", "")).startswith("swing_overlap")),
        None,
    )
    if sc is None:
        return ("swing/overlap exchange A/B", None,
                "SIMBENCH_r10.json carries no swing_overlap scenario")
    ab = sc.get("overlap_ab") or {}
    sw = sc.get("swing_ab") or {}
    ratio = ab.get("wall_ratio_min")
    sw_ratio = sw.get("wall_ratio_min")
    ok = (
        bool(sc.get("twin_certified"))
        and bool(ab.get("digests_equal")) and bool(sw.get("digests_equal"))
        and ratio is not None and ratio <= 1.0
        and sw_ratio is not None and sw_ratio <= 1.05
    )
    return (
        f"swing/overlap exchange A/B (n={ab.get('n')} P=2 overlap, "
        f"n={sw.get('n')} P=4 swing)",
        ok,
        f"pipelined/sequential wall min {ratio} (<= 1.0 required, median "
        f"{ab.get('wall_ratio_median')}), swing/cyclic wall min {sw_ratio} "
        f"(<= 1.05), relay raw ratio {sw.get('relay_raw_ratio')}x priced, "
        f"digests_equal={ab.get('digests_equal')}/{sw.get('digests_equal')} "
        f"twin_certified={sc.get('twin_certified')}",
    )


def judge_serve_fanin():
    """The r17 fan-in serve-plane verdict from the committed
    SIMBENCH_r11.json — host-certifiable, judged with or without a
    ksweep capture.  Returns a (name, ok, detail) tuple, or None when
    the artifact does not exist."""
    path = os.path.join(REPO, "SIMBENCH_r11.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ("serve fan-in plane", None, f"unreadable SIMBENCH_r11.json: {e}")
    sc = next(
        (s for s in data.get("scenarios", [])
         if str(s.get("metric", "")).startswith("serve_fanin")),
        None,
    )
    if sc is None:
        return ("serve fan-in plane", None,
                "SIMBENCH_r11.json carries no serve_fanin scenario")
    q = sc.get("quorum") or {}
    curve = sc.get("scaling_curve") or []
    multi = [p for p in curve if p.get("nprocs", 1) > 1]
    rpc_ok = bool(multi) and all(
        p.get("messages") is not None and p.get("messages_naive") is not None
        and p["messages"] < p["messages_naive"]
        for p in multi
    )
    quorum_ok = bool(
        q.get("owners_killed") and q.get("quorum_held")
        and q.get("answers_agree")
        and q.get("rpcs") is not None and q.get("rpcs_naive") is not None
        and q["rpcs"] < q["rpcs_naive"]
    )
    ok = bool(sc.get("digests_equal")) and rpc_ok and quorum_ok
    curve_s = ", ".join(
        f"P={p.get('nprocs')}: {p.get('keys_per_s_per_host')}/s/host "
        f"({p.get('messages')} msgs vs {p.get('messages_naive')} naive)"
        for p in curve
    )
    return (
        f"serve fan-in plane (n={sc.get('n_servers')}x"
        f"{sc.get('replica_points')} vnodes, R={sc.get('lookup_n')})",
        ok,
        f"digests_equal={sc.get('digests_equal')} (oracle "
        f"{sc.get('oracle_digest')}); {curve_s}; quorum "
        f"{q.get('quorum')}/{q.get('r')} held={q.get('quorum_held')} under "
        f"owner kills={q.get('owners_killed')} at rpc ratio "
        f"{q.get('rpc_ratio')} (strictly-below-naive required)",
    )


def judge_fleet_scale():
    """The r19 scenario-fleet verdict from the committed
    SIMBENCH_r13.json — host-certifiable, judged with or without a
    ksweep capture.  Returns a (name, ok, detail) tuple, or None when
    the artifact does not exist."""
    path = os.path.join(REPO, "SIMBENCH_r13.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ("scenario fleet at scale", None, f"unreadable SIMBENCH_r13.json: {e}")
    sc = next(
        (s for s in data.get("scenarios", [])
         if str(s.get("metric", "")).startswith("fleet_scale")),
        None,
    )
    if sc is None:
        return ("scenario fleet at scale", None,
                "SIMBENCH_r13.json carries no fleet_scale scenario")
    ad = sc.get("adaptive") or {}
    rss = sc.get("rss_frac")
    ok = (
        bool(sc.get("digests_equal")) and bool(sc.get("scores_equal"))
        and bool(sc.get("restore_exact"))
        and rss is not None and rss < 0.75
        and bool((sc.get("twin") or {}).get("equal"))
        and bool(ad.get("cliffs_match"))
        and ad.get("evals_ratio") is not None and ad["evals_ratio"] <= 0.25
    )
    return (
        f"scenario fleet at scale (B={sc.get('b')}, n={sc.get('n_nodes')}, "
        f"k={sc.get('k')})",
        ok,
        f"digests_equal={sc.get('digests_equal')} "
        f"scores_equal={sc.get('scores_equal')} "
        f"restore_exact={sc.get('restore_exact')} (P=2 save -> P=1 restore); "
        f"RSS frac {rss} (< 0.75 required, {sc.get('rss_p2_max_mb')} vs "
        f"{sc.get('rss_p1_mb')} MB); twin={(sc.get('twin') or {}).get('equal')}; "
        f"adaptive cliff {ad.get('cliffs')} == dense at evals ratio "
        f"{ad.get('evals_ratio')} (<= 0.25 required, "
        f"{ad.get('evals_adaptive')}/{ad.get('evals_dense')})",
    )


def judge_gameday():
    """The r22 closed-loop verdict from the committed SIMBENCH_r22.json —
    host-certifiable.  The zone-cut game day certifies when the
    controller mitigated STRICTLY earlier than the no-controller twin
    AND the controller-on / controller-off / bare-HEAD digests are bit
    identical (slower-than-twin or a digest split REFUTES — a loop that
    perturbs the sim is worse than no loop).  The switch-flap scenario
    is reported, not gating.  Returns a (name, ok, detail) tuple, or
    None when the artifact does not exist."""
    path = os.path.join(REPO, "SIMBENCH_r22.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return ("closed-loop game day", None,
                f"unreadable SIMBENCH_r22.json: {e}")
    sc = next(
        (s for s in data.get("scenarios", [])
         if str(s.get("metric", "")).startswith("gameday")),
        None,
    )
    if sc is None:
        return ("closed-loop game day", None,
                "SIMBENCH_r22.json carries no gameday scenario")
    zc = sc.get("zone_cut") or {}
    ok = (
        bool(zc.get("mitigated_earlier"))
        and bool(zc.get("digest_equal"))
        and bool(zc.get("digest_matches_head"))
        and zc.get("twin_actions") == 0
        and bool(zc.get("chain_ok"))
        and bool(sc.get("certified"))
    )
    flap = sc.get("switch_flap") or {}
    flap_note = (
        f"; switch_flap ttm {flap.get('ttm_on')} vs {flap.get('ttm_off')} "
        f"(reported only)" if flap else ""
    )
    return (
        f"closed-loop game day (n={sc.get('n_nodes')}, "
        f"horizon={sc.get('horizon')})",
        ok,
        f"zone_cut ttm {zc.get('ttm_on')} vs twin {zc.get('ttm_off')} "
        f"(strictly-earlier required); digest_equal={zc.get('digest_equal')} "
        f"matches_head={zc.get('digest_matches_head')} "
        f"twin_actions={zc.get('twin_actions')} chain_ok={zc.get('chain_ok')}"
        f"{flap_note}",
    )


def _print_solo(host_verdicts) -> int:
    """Render the host-level verdicts (dcn_wire r15, swing_overlap r16)
    when no on-chip capture is judgeable — these claims never wait on
    the TPU gate."""
    known = [v for v in host_verdicts if v is not None]
    if not known:
        return 1
    bad = False
    judged = False
    for name, ok, detail in known:
        mark = "?" if ok is None else ("CERTIFIES" if ok else "REFUTES  ")
        print(f"  [{mark}] {name}: {detail}")
        bad = bad or ok is False
        judged = judged or ok is True
    if bad:
        print("VERDICT: committed SIMBENCH artifacts REFUTE the host-level "
              "wire/schedule/serve model")
        return 2
    if judged:
        print("VERDICT: host-level wire/schedule/serve claims CERTIFY "
              "(on-chip model still unjudged)")
        return 0
    return 1


def main() -> int:
    host = [judge_dcn_wire(), judge_swing_overlap(), judge_serve_fanin(),
            judge_fleet_scale(), judge_gameday()]
    path = sys.argv[1] if len(sys.argv) > 1 else newest_ksweep()
    if not path:
        print("no ksweep capture found (run make tpu-watch and wait for a window)")
        rc = _print_solo(host)
        return rc
    try:
        with open(path) as f:
            cap = json.load(f)
    except (OSError, ValueError) as e:
        # a torn concurrent write by the watcher's flush() must yield a
        # clean message, not a traceback (same guard as bench.py)
        print(f"unreadable capture {path}: {e}")
        return 1
    print(f"capture: {path}")
    print(f"  platform={cap.get('platform')} git_head={str(cap.get('git_head'))[:12]} "
          f"dirty={cap.get('git_dirty')} at={cap.get('captured_at')}")
    if cap.get("platform") == "cpu":
        # same knowledge state as "no capture": the on-chip model is
        # unjudgeable, only the host-level claims decide rc
        print("  CPU capture — the on-chip model is unjudgeable from it; "
              "only the host-level dcn_wire / swing_overlap claims can be "
              "certified")
        return _print_solo(host)

    verdicts = [v for v in host if v is not None]

    for k_str, tc in (cap.get("tick_cost") or {}).items():
        if "ms_per_tick_median" not in tc:
            verdicts.append((f"tick_cost k={k_str}", None, tc.get("error", "missing")))
            continue
        ms = tc["ms_per_tick_median"]
        k = int(k_str)
        lo, hi = MODEL_MS_PER_TICK.get(k, (0.5, 240.0 * k / 512))
        if lo <= ms <= hi:
            verdicts.append((f"tick_cost k={k}", True, f"{ms} ms/tick in model range [{lo}, {hi}]"))
        elif k == 128 and RETRACTED_MS_AT_K128 / 5 < ms < RETRACTED_MS_AT_K128 * 5:
            verdicts.append(
                (f"tick_cost k={k}", False,
                 f"{ms} ms/tick is within 5x of the RETRACTED 142 ms reading — "
                 "the round-4 reconciliation is wrong; reinstate the trace model")
            )
        else:
            verdicts.append((f"tick_cost k={k}", False, f"{ms} ms/tick outside [{lo}, {hi}]"))

    dh = cap.get("detect_headline") or {}
    if dh.get("detected") is not None:
        wall = dh.get("wall_s")
        ok = bool(dh.get("detected")) and wall is not None and wall < NORTH_STAR_S
        verdicts.append(
            ("1M detection vs 60s north star", ok,
             f"detected={dh.get('detected')} in {wall} s / {dh.get('ticks')} ticks "
             f"({dh.get('ms_per_tick_implied')} ms/tick implied)")
        )
    cv = cap.get("converge_after_detect") or {}
    if cv.get("converged") is not None:
        total = (dh.get("wall_s") or 0) + (cv.get("wall_s") or 0)
        verdicts.append(
            ("1M convergence (literal north star)", bool(cv.get("converged")) and total < NORTH_STAR_S,
             f"converged={cv.get('converged')} total {round(total, 3)} s "
             f"({cv.get('total_ticks')} ticks)")
        )
    # multi-chip: the sharded tick vs the r6 ICI-bound projection.  Judged
    # the same way as tick_cost: a real-ICI median inside the bracket
    # certifies the projection; outside refutes it (the model loses, not
    # the measurement).  The committed collective budget itself is also
    # re-checked so the bracket can't drift away from its evidence.
    sh = cap.get("sharded_tick") or {}
    if sh.get("ms_per_tick_median") is not None:
        ms = sh["ms_per_tick_median"]
        lo, hi = MULTICHIP_SHARDED_MS_PER_TICK
        verdicts.append(
            (f"sharded tick ({sh.get('n_devices')} chips, k={sh.get('k')})",
             lo <= ms <= hi,
             f"{ms} ms/tick vs ICI-bound bracket [{lo}, {hi}] "
             f"(budget {MULTICHIP_BUDGET['mb_per_chip_tick_max']} MB/chip/tick max)")
        )
    elif "error" in sh:
        verdicts.append(("sharded tick", None, sh["error"]))
    # the r8 exchange-leg A/B: shard_map crossing-block legs must be
    # bit-equal to the roll legs and no slower on real ICI (the byte model
    # says ~2.6x fewer exchange bytes — losing would refute the lowering)
    se = cap.get("sharded_exchange") or {}
    if se.get("shardmap_ms_per_tick_median") is not None and se.get(
        "roll_ms_per_tick_median"
    ) is not None:
        sm_ms, roll_ms = se["shardmap_ms_per_tick_median"], se["roll_ms_per_tick_median"]
        ok = bool(se.get("bit_equal")) and sm_ms <= roll_ms * 1.05
        verdicts.append(
            (f"sharded exchange legs ({se.get('n_devices')} chips, k={se.get('k')})",
             ok,
             f"shard_map {sm_ms} vs roll {roll_ms} ms/tick, "
             f"bit_equal={se.get('bit_equal')}")
        )
    elif "error" in se:
        verdicts.append(("sharded exchange legs", None, se["error"]))
    # r14 multihost_tick: the process-spanning mesh step.  The DCN legs
    # are slice-edge ppermutes — latency, not volume — so the per-tick
    # median must stay inside the (generous) 4x sharded-tick bracket, and
    # the MEASURED per-chip collective volume (compiled-HLO census, same
    # parser as the budget ratchet) must fit the committed 42.5
    # MB/chip/tick budget — a multi-host lowering that added traffic
    # classes shows up as census bytes and refutes.
    mh = cap.get("multihost_tick") or {}
    if mh.get("ms_per_tick_median") is not None:
        ms = mh["ms_per_tick_median"]
        lo, hi = MULTICHIP_SHARDED_MS_PER_TICK
        hi_dcn = hi * 4.0  # DCN latency allowance over the ICI bracket
        census_mb = mh.get("census_mb_per_chip_tick")
        budget_ok = census_mb is not None and census_mb <= 42.5 + 1e-6
        verdicts.append(
            (
                f"multihost tick ({mh.get('process_count')} processes, "
                f"{mh.get('n_devices')} chips)",
                (lo <= ms <= hi_dcn) and budget_ok,
                f"{ms} ms/tick vs DCN bracket [{lo}, {hi_dcn}]; censused "
                f"{census_mb} MB/chip/tick "
                f"({'<=' if budget_ok else 'EXCEEDS or missing'} the "
                f"42.5 MB/chip/tick budget"
                + (f"; census_error: {mh['census_error']}" if "census_error" in mh else "")
                + ")",
            )
        )
    elif "error" in mh:
        verdicts.append(("multihost tick", None, mh["error"]))
    # the r11 pipelined-exchange A/B: census-identical traffic, so the
    # pipelined legs must be bit-equal and no slower than sequential —
    # faster is the overlap window actually cashing out on real ICI
    pe = cap.get("pipelined_exchange") or {}
    if pe.get("pipelined_ms_per_tick_median") is not None and pe.get(
        "sequential_ms_per_tick_median"
    ) is not None:
        p_ms, s_ms = pe["pipelined_ms_per_tick_median"], pe["sequential_ms_per_tick_median"]
        ok = bool(pe.get("bit_equal")) and p_ms <= s_ms * 1.05
        verdicts.append(
            (f"pipelined exchange legs ({pe.get('n_devices')} chips, k={pe.get('k')})",
             ok,
             f"pipelined {p_ms} vs sequential {s_ms} ms/tick "
             f"(overlap win {round(s_ms - p_ms, 3)} ms/tick), "
             f"bit_equal={pe.get('bit_equal')}")
        )
    elif "error" in pe:
        verdicts.append(("pipelined exchange legs", None, pe["error"]))
    # the r16 swing-exchange A/B over a real pod's DCN: the host-bridged
    # fabric's cyclic vs swing schedules and the cross-tick overlap, all
    # bit-identical by construction — on real inter-host links the swing
    # relays trade bytes for power-of-two leg distances and the overlap
    # hides the drain, so neither may be slower than cyclic/sequential
    # beyond noise; bit-unequal or slower-than-cyclic REFUTES.
    sx = cap.get("swing_exchange") or {}
    if "error" in sx:
        verdicts.append(("swing exchange (DCN schedules)", None, sx["error"]))
    elif sx.get("cyclic_ms_per_tick_median") is not None:
        cy = sx["cyclic_ms_per_tick_median"]
        sw_ms = sx.get("swing_ms_per_tick_median")
        ov_ms = sx.get("overlap_ms_per_tick_median")
        ok = (
            bool(sx.get("bit_equal"))
            and sw_ms is not None and sw_ms <= cy * 1.05
            and ov_ms is not None and ov_ms <= cy * 1.05
        )
        verdicts.append(
            (f"swing exchange (P={sx.get('process_count')} hosts, "
             f"n={sx.get('n')})",
             ok,
             f"cyclic {cy} vs swing {sw_ms} vs overlap {ov_ms} ms/tick, "
             f"relay raw ratio {sx.get('relay_raw_ratio')}x, "
             f"bit_equal={sx.get('bit_equal')}")
        )
    # the topology round's tier machinery: the topology-enabled chaos
    # tick (tier legs forced, zero drop table) vs the flat chaos tick.
    # The separate-coin construction says zero-table tier legs change NO
    # values — bit-unequal refutes the lowering — and the tier
    # evaluation (id gathers + blocked one-hot expansion + coin sites)
    # must stay noise against the packed-plane passes on real hardware.
    tc = cap.get("topo_chaos") or {}
    if "error" in tc:
        verdicts.append(("topology tier machinery", None, tc["error"]))
    elif tc.get("topo_ms_per_tick_median") is not None and tc.get(
        "flat_ms_per_tick_median"
    ) is not None:
        t_ms, f_ms = tc["topo_ms_per_tick_median"], tc["flat_ms_per_tick_median"]
        ok = bool(tc.get("bit_equal")) and t_ms <= f_ms * 1.10
        verdicts.append(
            (f"topology tier machinery (n={tc.get('n')}, "
             f"{tc.get('racks')} racks, sharded={tc.get('sharded')})",
             ok,
             f"topo {t_ms} vs flat {f_ms} ms/tick "
             f"(overhead {tc.get('overhead_pct')}%), "
             f"bit_equal={tc.get('bit_equal')}")
        )
    # the r12 batched chaos fleet: B stacked-FaultPlan scenarios as one
    # vmapped program vs the same B stepped sequentially (both warm — the
    # compile-amortization half of the claim is the CPU SIMBENCH mc_chaos
    # record).  The model says batching amortizes per-dispatch overhead,
    # so the fleet must be no slower per tick than the sequential sweep;
    # slower REFUTES the fleet lowering, as does any scenario's final
    # state diverging from its solo run.
    # "error" wins even when both medians landed first: a crash in the
    # bit_equal comparison (host transfer of the fleet state) leaves the
    # medians behind, and that run is INCONCLUSIVE, not a refutation.
    mc = cap.get("mc_chaos") or {}
    if "error" in mc:
        verdicts.append(("batched chaos fleet", None, mc["error"]))
    elif mc.get("batched_ms_per_tick_median") is not None and mc.get(
        "sequential_ms_per_tick_median"
    ) is not None:
        b_ms, s_ms = mc["batched_ms_per_tick_median"], mc["sequential_ms_per_tick_median"]
        ok = bool(mc.get("bit_equal")) and b_ms <= s_ms * 1.05
        verdicts.append(
            (f"batched chaos fleet (B={mc.get('b')}, n={mc.get('n')}, "
             f"sharded={mc.get('sharded')})",
             ok,
             f"batched {b_ms} vs sequential {s_ms} ms/tick "
             f"(amortization {round(s_ms / max(b_ms, 1e-9), 2)}x), "
             f"bit_equal={mc.get('bit_equal')}")
        )
    # the r19 batch-sharded fleet on real chips: the batch axis shards
    # over the mesh with NO cross-batch collectives, so the model says
    # sharded == replicated per tick (free compute, pure HBM headroom);
    # slower beyond noise or any scenario divergence REFUTES.
    fl = cap.get("fleet_scale") or {}
    if "error" in fl:
        verdicts.append(("batch-sharded fleet (mesh batch axis)", None, fl["error"]))
    elif fl.get("sharded_ms_per_tick_median") is not None and fl.get(
        "replicated_ms_per_tick_median"
    ) is not None:
        s_ms, r_ms = fl["sharded_ms_per_tick_median"], fl["replicated_ms_per_tick_median"]
        ok = bool(fl.get("bit_equal")) and s_ms <= r_ms * 1.05
        verdicts.append(
            (f"batch-sharded fleet (B={fl.get('b')}, n={fl.get('n')}, "
             f"{fl.get('n_devices')} chips)",
             ok,
             f"batch-sharded {s_ms} vs batch-replicated {r_ms} ms/tick, "
             f"bit_equal={fl.get('bit_equal')}")
        )
    # the r13 serve-tier dispatch: bit-equal to the host walk and >= 2x a
    # host bisect process per key, else the shared-ring premise is refuted
    sl = cap.get("serve_lookup") or {}
    if "error" in sl:
        verdicts.append(("serve-tier shared-ring dispatch", None, sl["error"]))
    elif sl.get("device_qps") is not None and sl.get(
        "bisect_qps_per_process"
    ) is not None:
        ok = bool(sl.get("bit_equal")) and (
            sl["device_qps"] >= 2.0 * sl["bisect_qps_per_process"]
        )
        verdicts.append(
            (f"serve-tier shared-ring dispatch (batch={sl.get('batch')}, "
             f"{sl.get('n_servers')}x{sl.get('replica_points')} vnodes)",
             ok,
             f"device {sl['device_qps']} vs bisect "
             f"{sl['bisect_qps_per_process']} keys/s per process "
             f"(amortization {sl.get('amortization')}x), "
             f"bit_equal={sl.get('bit_equal')}")
        )
    # the r17 fused LookupN serve dispatch on real HW: bit-equal to the
    # host LookupNUniqueAt walk (generation riding the same transfer) and
    # >= 2x a host walk process per key, same bar as serve_lookup — the
    # preference-list flavor of the serving premise
    sf = cap.get("serve_fanin") or {}
    if "error" in sf:
        verdicts.append(("serve fan-in LookupN dispatch", None, sf["error"]))
    elif sf.get("device_qps") is not None and sf.get(
        "host_walk_qps_per_process"
    ) is not None:
        ok = (
            bool(sf.get("bit_equal")) and bool(sf.get("gen_in_tail"))
            and sf["device_qps"] >= 2.0 * sf["host_walk_qps_per_process"]
        )
        verdicts.append(
            (f"serve fan-in LookupN dispatch (batch={sf.get('batch')}, "
             f"R={sf.get('n')}, {sf.get('n_servers')}x"
             f"{sf.get('replica_points')} vnodes)",
             ok,
             f"device {sf['device_qps']} vs host walk "
             f"{sf['host_walk_qps_per_process']} keys/s per process "
             f"(amortization {sf.get('amortization')}x), "
             f"bit_equal={sf.get('bit_equal')} gen_in_tail={sf.get('gen_in_tail')}")
        )
    prof = next(
        ((p, budget) for p, budget in
         ((os.path.join(REPO, "captures", f), b) for f, b in BUDGET_CAPTURES)
         if os.path.exists(p)),
        None,
    )
    if prof:
        prof_path, budget = prof
        try:
            with open(prof_path) as f:
                data = json.load(f)
            bk = data["step"]["by_kind"]
            cnt = sum(e["count"] for e in bk.values())
            mb = sum(e["bytes"] for e in bk.values()) / 1e6
            ok = (cnt <= budget["collectives_per_tick_max"]
                  and mb <= budget["mb_per_chip_tick_max"])
            verdicts.append(
                (f"committed collective budget ({os.path.basename(prof_path)})", ok,
                 f"{cnt} collectives, {round(mb, 1)} MB/chip/tick vs budget "
                 f"{budget['collectives_per_tick_max']} / "
                 f"{budget['mb_per_chip_tick_max']} MB")
            )
        except (OSError, ValueError, KeyError) as e:
            verdicts.append(("committed collective budget", None, f"unreadable: {e}"))

    d16 = cap.get("delta_16m") or {}
    if d16.get("converged") is not None and d16.get("ticks"):
        ms = (d16.get("wall_s") or 0) / d16["ticks"] * 1e3
        verdicts.append(
            ("16M delta corroboration", ms < 200.0,
             f"{round(ms, 1)} ms/tick at 16M x {d16.get('k')}")
        )
    st = cap.get("sparse_topk") or {}
    if st.get("bit_equal") is not None:
        if not st.get("sparse_engaged") or st.get("overflowed"):
            verdicts.append(("sparse top-k (section 4b)", None,
                             "compressed path not exercised (below the static "
                             "floor, or candidates overflowed the buffer and "
                             "the cond fell back to the full sort) — vacuous"))
        else:
            # the round-4 claim: bit-equal to the dense sort AND at least
            # not slower on-chip (on CPU it is ~16x faster; a chip where
            # the compressed path LOSES to a 1M sort would be news)
            ok = bool(st.get("bit_equal")) and (
                st.get("sparse_ms") is not None
                and st.get("dense_sort_ms") is not None
                and st["sparse_ms"] <= st["dense_sort_ms"] * 1.1
            )
            verdicts.append(
                ("sparse top-k (section 4b)", ok,
                 f"bit_equal={st.get('bit_equal')} sparse={st.get('sparse_ms')} ms "
                 f"vs dense sort={st.get('dense_sort_ms')} ms")
            )

    print()
    all_known = True
    for name, ok, detail in verdicts:
        mark = "?" if ok is None else ("CERTIFIES" if ok else "REFUTES  ")
        if ok is None:
            all_known = False
        print(f"  [{mark}] {name}: {detail}")
    if not verdicts:
        print("  capture has no judgeable sections")
        return 1
    bad = [v for v in verdicts if v[1] is False]
    good = [v for v in verdicts if v[1] is True]
    print()
    if bad:
        print("VERDICT: capture REFUTES the round-4 cost model on "
              f"{len(bad)} point(s) — update PERF.md accordingly (the model, "
              "not the measurement, loses)")
        return 2
    if not good:
        # every section errored out (e.g. the tunnel died mid-sweep):
        # nothing was actually judged, so nothing is certified
        print("VERDICT: capture contains no successful measurements — nothing judged")
        return 1
    print("VERDICT: capture CERTIFIES the round-4 cost model"
          + ("" if all_known else " (some sections missing)"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
