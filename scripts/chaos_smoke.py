"""chaos-smoke — the CI gate for the chaos plane (sim/chaos.py).

Runs the canonical tiny churn+flap+loss scenario (``chaos.scenario_plan
("smoke")``) through the lifecycle engine and asserts:

1. **telemetry-on/off bit-identity under a time-varying plan**: the
   telemetry-carrying run ends digest-equal (and leaf-by-leaf bit-equal)
   to a telemetry-off run of the same plan — the r7 transparency
   property extended to the chaos plane;
2. **scorer output shape**: ``chaos.score_blocks`` over the run's journal
   produces the full verdict schema (events, per-event time-to-detect /
   half-life, false-positive count, re-join convergence) with sane
   values for this scenario (crash events exist, the permanent victims
   were detected, the flappers produced refutations);
3. **the scored journal round-trips**: the JSONL stream carries header +
   blocks + one ``kind: "score"`` record that parses back equal.

Exit 0 on success, 1 with a diagnosis on any failure.  Wall cost is a
few seconds (n=256) — wired into `make test` next to telemetry-smoke.

Usage:
    python scripts/chaos_smoke.py [--out /tmp/chaos_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="journal path (default: temp file)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from ringpop_tpu.sim import chaos, lifecycle, telemetry
    from ringpop_tpu.util.accel import configure_compile_cache

    # before the journal opens: the header's compile_cache field snapshots
    # accel.cache_status(), which only reflects reality once the cache is
    # actually configured (aot.load_or_compile would otherwise configure
    # it mid-run, after the header was already written)
    configure_compile_cache()

    path = args.out or os.path.join(
        tempfile.mkdtemp(prefix="chaossmoke_"), "chaos_smoke.jsonl"
    )
    n, k, seed, horizon, block = 256, 64, 0, 128, 16
    plan = chaos.scenario_plan("smoke", n, seed=seed, horizon=horizon)
    failures: list[str] = []

    aot_infos = {}

    def run(sink):
        # aot="chaos-smoke": the block program goes through the AOT
        # warm-start front door (util/aot.py) — first-ever run serializes
        # the executable, every later chaos-smoke (same toolchain) starts
        # warm; values are bit-identical either way (the on/off digest
        # pairing below runs THROUGH this path, so it re-certifies that
        # each CI run)
        sim = lifecycle.LifecycleSim(
            n=n, k=k, seed=seed, suspect_ticks=8, rng="counter", telemetry=sink,
            aot="chaos-smoke",
        )
        for _ in range(horizon // block):
            sim.run(block, plan)
        aot_infos.update(sim.aot_info)
        return sim.state

    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "chaos-smoke", {"n": n, "k": k, "seed": seed})
        sink = telemetry.TelemetrySink(journal=journal)
        s_on = run(sink)
        score = chaos.score_blocks(sink.records, plan, n=n, scenario="chaos-smoke")
        journal.score(score)
    s_off = run(None)

    # 1: bit-identity under the time-varying plan
    d_on, d_off = int(telemetry.tree_digest(s_on)), int(telemetry.tree_digest(s_off))
    if d_on != d_off:
        failures.append(
            f"digest mismatch under FaultPlan: telemetry-on {d_on:#010x} vs off {d_off:#010x}"
        )
    for name, a, b in zip(s_on._fields, jax.tree.leaves(s_on), jax.tree.leaves(s_off)):
        if not bool((np.asarray(a) == np.asarray(b)).all()):
            failures.append(f"state leaf {name} diverged between telemetry on/off")

    # 2: scorer shape + scenario sanity
    want = {
        "kind", "scenario", "n", "ticks", "blocks", "block_granularity_ticks",
        "events", "time_to_detect", "time_to_detect_median", "rumor_half_life",
        "rumor_half_life_median", "refutations", "false_positive_suspects",
        "suspects_declared",
        "faulty_declared", "heal_attempts", "final_detect_frac",
        "rejoin_convergence_ticks",
    }
    missing = want - set(score)
    if missing:
        failures.append(f"score record missing fields: {sorted(missing)}")
    kinds = {e["kind"] for e in score.get("events", ())}
    if not {"crash", "restart", "flap"} <= kinds:
        failures.append(f"smoke plan events incomplete: {sorted(kinds)}")
    if not score.get("time_to_detect"):
        failures.append("no time-to-detect entries for the crash events")
    if score.get("suspects_declared", 0) <= 0:
        failures.append("scenario declared no suspects — the plan never bit")

    # 3: the scored journal round-trips
    try:
        records = telemetry.read_journal(path)
    except Exception as e:  # noqa: BLE001 — the diagnosis IS the product
        records = []
        failures.append(f"journal unparseable: {type(e).__name__}: {e}")
    scores = [r for r in records if r.get("kind") == "score"]
    blocks = [r for r in records if r.get("kind") == "block"]
    if len(scores) != 1:
        failures.append(f"expected exactly one score record, found {len(scores)}")
    elif scores[0].get("false_positive_suspects") != score["false_positive_suspects"]:
        failures.append("journaled score differs from the computed one")
    if sum(b.get("ticks", 0) for b in blocks) != horizon:
        failures.append("journal blocks do not cover the run")

    if failures:
        print("chaos-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    aot_line = "; ".join(
        f"{t}: {'warm' if i['cache_hit'] else 'cold'} compile {i['compile_s']}s"
        + (f" ({i['error']})" if i["error"] else "")
        for t, i in sorted(aot_infos.items())
    )
    print(
        f"chaos-smoke: OK — {len(blocks)} blocks + 1 score journaled at {path}; "
        f"ttd_median={score['time_to_detect_median']} "
        f"fp_suspects={score['false_positive_suspects']} "
        f"rejoin={score['rejoin_convergence_ticks']}; "
        f"telemetry-on digest-equal to off ({d_on:#010x}); aot {aot_line}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
