#!/usr/bin/env python
"""DCN wire-codec + exchange-schedule CI gate (r15/r16, < 60 s, 2-core
container).

Codec, schedule and overlap A/Bs over the host-bridged fabric —
in-process ranks (LocalKV threads; the same fabric code path the
OS-process runs take) stepping one seeded delta scenario to convergence:

1. **digests equal** — codec-on == codec-off == the in-process engine's
   ``telemetry.tree_digest`` (the codec is bit-transparent or it is
   wrong);
2. **bytes strictly lower during dissemination** — the codec run's wire
   bytes must undercut the raw run's cumulatively AND on every early
   (dissemination-phase) tick interval, where the ride-masked planes are
   sparsest;
3. **raw fallback exercised** — at least one array in the codec run must
   have shipped RAW (the measured fallback is a live code path, not dead
   armor), alongside at least one compressed encoding;
4. **pieces-only device→host** — the exchange legs' d2h accounting stays
   under the pre-r15 full-plane floor;
5. **(r16) swing / overlap A/B** — every (schedule, overlap) combination
   at P=2 plus the P=4 swing relay leg lands the SAME digest in the same
   tick count, the per-leg drain/overlap journal keys are present, wall
   and bytes are recorded (wall is *recorded* here, *judged* by the
   committed simbench artifact — this is a 2-core CI box), swing wire
   bytes match cyclic exactly at P=2 (the schedule degenerates) and the
   P=4 relay overhead is visible in the raw accounting.

Exit 0 = certified; any assertion prints and exits 1.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_SCRIPTS))

T0 = time.perf_counter()
N, K, SEED, NPROCS, MAX_TICKS = 4096, 64, 17, 2, 512


def _run(codec: bool, schedule: str = "cyclic", overlap: bool = False,
         nprocs: int = NPROCS):
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=N, k=K, rng="counter")
    kv = LocalKV()
    out = [None] * nprocs
    errs = []
    ns = f"dcn{int(codec)}{schedule}{int(overlap)}{nprocs}"

    def run(rank):
        try:
            with Fabric(rank, nprocs, kv, namespace=ns, codec=codec) as fab:
                mh = MultihostDelta(params, fab, seed=SEED,
                                    schedule=schedule, overlap=overlap)
                per_tick = []
                t0 = time.perf_counter()
                for _ in range(MAX_TICKS):
                    mh.step()
                    per_tick.append(mh.journal_record())
                    if mh.converged:
                        break
                wall = time.perf_counter() - t0
                out[rank] = (per_tick, mh.d2h_bytes, fab.wire_stats(), wall,
                             mh.leg_timing())
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(nprocs)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(240)
    if errs:
        raise errs[0]
    assert all(o is not None for o in out), "a rank hung"
    return out


def main() -> int:
    import jax

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, step
    from ringpop_tpu.sim.packbits import n_words
    from ringpop_tpu.sim.telemetry import tree_digest

    on = _run(codec=True)
    off = _run(codec=False)

    # 1. digest chain: every rank, both modes, == engine
    params = DeltaParams(n=N, k=K, rng="counter")
    st = init_state(params, seed=SEED)
    stp = jax.jit(functools.partial(step, params))
    ticks_on = len(on[0][0])
    for _ in range(ticks_on):
        st = stp(st, DeltaFaults())
    anchor = int(tree_digest(st))
    d_on = {pt[-1]["digest"] for pt, *_ in on}
    d_off = {pt[-1]["digest"] for pt, *_ in off}
    assert len(on[0][0]) == len(off[0][0]), "codec changed the tick count"
    assert d_on == d_off == {anchor}, (
        f"digest chain broken: codec-on {d_on}, codec-off {d_off}, "
        f"engine {anchor}"
    )
    print(f"digests OK: codec-on == codec-off == engine {anchor} "
          f"({ticks_on} ticks)")

    # 2. bytes strictly lower — cumulatively and per dissemination tick
    wire_on = on[0][2]["bytes_sent"]
    wire_off = off[0][2]["bytes_sent"]
    assert wire_on < wire_off, (wire_on, wire_off)
    dissem = max(2, ticks_on // 2)
    for t in range(dissem):
        a = on[0][0][t]["fabric_wire_sent_delta"]
        b = off[0][0][t]["fabric_wire_sent_delta"]
        assert a < b, f"tick {t}: codec {a} B not below raw {b} B"
    ratio = on[0][2]["raw_bytes_sent"] / wire_on
    print(f"bytes OK: wire {wire_on} < raw-mode {wire_off} "
          f"(codec ratio {ratio:.2f}x, every dissemination tick lower)")

    # 3. measured raw fallback is a live path
    counts = on[0][2]["codec_counts"]
    assert counts.get("raw", 0) >= 1, f"raw fallback never taken: {counts}"
    assert sum(v for k, v in counts.items() if k != "raw") >= 1, counts
    print(f"codec mix OK: {counts}")

    # 4. pieces-only device→host (the acceptance floor)
    plane_nbytes = (N // NPROCS) * n_words(K) * 4
    floor = 2 * ticks_on * plane_nbytes
    for pt, d2h, *_ in on:
        assert 0 < d2h < floor, (d2h, floor)
    print(f"d2h OK: {on[0][1]} B < full-plane floor {floor} B")

    # 5. r16 swing / overlap A/B legs: digest chain + schedule accounting
    grid = {("cyclic", False): on}
    for schedule, overlap in (("swing", False), ("cyclic", True), ("swing", True)):
        grid[(schedule, overlap)] = _run(codec=True, schedule=schedule,
                                         overlap=overlap)
    for (schedule, overlap), res in grid.items():
        pt = res[0][0]
        assert {p[-1]["digest"] for p, *_ in res} == {anchor}, (
            f"{schedule}/overlap={overlap} broke the digest chain")
        assert len(pt) == ticks_on, (schedule, overlap, len(pt), ticks_on)
        rec = pt[-1]
        assert rec["schedule"] == schedule and rec["overlap"] is overlap
        assert set(rec["fabric_leg_ms"]) == {"leg1", "leg2", "reduce"}
        assert rec["overlap_hidden_ms"] >= 0.0
    # at P=2 the swing schedule degenerates to the cyclic messages — the
    # wire totals must agree EXACTLY (relay-free by construction)
    assert (grid[("swing", False)][0][2]["bytes_sent"]
            == grid[("cyclic", False)][0][2]["bytes_sent"]), "P=2 swing relayed"
    for key, res in grid.items():
        print(f"A/B OK: schedule={key[0]} overlap={key[1]} digest={anchor} "
              f"wall {max(r[3] for r in res):.2f}s "
              f"wire {res[0][2]['bytes_sent']} B "
              f"leg_ms {res[0][4]['fabric_leg_ms']}")
    # P=4 swing: relays priced in the raw accounting, digests still exact
    sw4 = _run(codec=True, schedule="swing", nprocs=4)
    cy4 = _run(codec=True, schedule="cyclic", nprocs=4)
    assert {p[-1]["digest"] for p, *_ in sw4} == {anchor}, "P=4 swing digest"
    assert {p[-1]["digest"] for p, *_ in cy4} == {anchor}, "P=4 cyclic digest"
    raw4_sw = sw4[0][2]["raw_bytes_sent"]
    raw4_cy = cy4[0][2]["raw_bytes_sent"]
    assert raw4_sw > raw4_cy, "P=4 swing relay overhead not accounted"
    print(f"P=4 swing OK: digest={anchor}, relay overhead "
          f"{raw4_sw - raw4_cy} B raw ({raw4_sw / raw4_cy:.2f}x cyclic)")

    print(f"dcn-smoke PASS in {time.perf_counter() - T0:.1f}s")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"dcn-smoke FAIL: {e}", file=sys.stderr)
        sys.exit(1)
