#!/usr/bin/env python
"""DCN wire-codec CI gate (r15, < 30 s, 2-core container).

Tiny codec A/B over the host-bridged fabric — 2 in-process ranks (LocalKV
threads; the same fabric code path the OS-process runs take) stepping one
seeded delta scenario to convergence, once with the r15 wire codec and
once shipping raw frames:

1. **digests equal** — codec-on == codec-off == the in-process engine's
   ``telemetry.tree_digest`` (the codec is bit-transparent or it is
   wrong);
2. **bytes strictly lower during dissemination** — the codec run's wire
   bytes must undercut the raw run's cumulatively AND on every early
   (dissemination-phase) tick interval, where the ride-masked planes are
   sparsest;
3. **raw fallback exercised** — at least one array in the codec run must
   have shipped RAW (the measured fallback is a live code path, not dead
   armor), alongside at least one compressed encoding;
4. **pieces-only device→host** — the exchange legs' d2h accounting stays
   under the pre-r15 full-plane floor.

Exit 0 = certified; any assertion prints and exits 1.
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_SCRIPTS))

T0 = time.perf_counter()
N, K, SEED, NPROCS, MAX_TICKS = 4096, 64, 17, 2, 512


def _run(codec: bool):
    from ringpop_tpu.parallel.fabric import Fabric, LocalKV
    from ringpop_tpu.sim.delta import DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    params = DeltaParams(n=N, k=K, rng="counter")
    kv = LocalKV()
    out = [None] * NPROCS
    errs = []

    def run(rank):
        try:
            with Fabric(rank, NPROCS, kv, namespace=f"dcn{int(codec)}",
                        codec=codec) as fab:
                mh = MultihostDelta(params, fab, seed=SEED)
                per_tick = []
                for _ in range(MAX_TICKS):
                    mh.step()
                    per_tick.append(mh.journal_record())
                    if mh.converged:
                        break
                out[rank] = (per_tick, mh.d2h_bytes, fab.wire_stats())
        except BaseException as e:
            errs.append(e)

    ts = [threading.Thread(target=run, args=(r,), daemon=True) for r in range(NPROCS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(240)
    if errs:
        raise errs[0]
    assert all(o is not None for o in out), "a rank hung"
    return out


def main() -> int:
    import jax

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, step
    from ringpop_tpu.sim.packbits import n_words
    from ringpop_tpu.sim.telemetry import tree_digest

    on = _run(codec=True)
    off = _run(codec=False)

    # 1. digest chain: every rank, both modes, == engine
    params = DeltaParams(n=N, k=K, rng="counter")
    st = init_state(params, seed=SEED)
    stp = jax.jit(functools.partial(step, params))
    ticks_on = len(on[0][0])
    for _ in range(ticks_on):
        st = stp(st, DeltaFaults())
    anchor = int(tree_digest(st))
    d_on = {pt[-1]["digest"] for pt, _, _ in on}
    d_off = {pt[-1]["digest"] for pt, _, _ in off}
    assert len(on[0][0]) == len(off[0][0]), "codec changed the tick count"
    assert d_on == d_off == {anchor}, (
        f"digest chain broken: codec-on {d_on}, codec-off {d_off}, "
        f"engine {anchor}"
    )
    print(f"digests OK: codec-on == codec-off == engine {anchor} "
          f"({ticks_on} ticks)")

    # 2. bytes strictly lower — cumulatively and per dissemination tick
    wire_on = on[0][2]["bytes_sent"]
    wire_off = off[0][2]["bytes_sent"]
    assert wire_on < wire_off, (wire_on, wire_off)
    dissem = max(2, ticks_on // 2)
    for t in range(dissem):
        a = on[0][0][t]["fabric_wire_sent_delta"]
        b = off[0][0][t]["fabric_wire_sent_delta"]
        assert a < b, f"tick {t}: codec {a} B not below raw {b} B"
    ratio = on[0][2]["raw_bytes_sent"] / wire_on
    print(f"bytes OK: wire {wire_on} < raw-mode {wire_off} "
          f"(codec ratio {ratio:.2f}x, every dissemination tick lower)")

    # 3. measured raw fallback is a live path
    counts = on[0][2]["codec_counts"]
    assert counts.get("raw", 0) >= 1, f"raw fallback never taken: {counts}"
    assert sum(v for k, v in counts.items() if k != "raw") >= 1, counts
    print(f"codec mix OK: {counts}")

    # 4. pieces-only device→host (the acceptance floor)
    plane_nbytes = (N // NPROCS) * n_words(K) * 4
    floor = 2 * ticks_on * plane_nbytes
    for pt, d2h, _ in on:
        assert 0 < d2h < floor, (d2h, floor)
    print(f"d2h OK: {on[0][1]} B < full-plane floor {floor} B")

    print(f"dcn-smoke PASS in {time.perf_counter() - T0:.1f}s")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"dcn-smoke FAIL: {e}", file=sys.stderr)
        sys.exit(1)
