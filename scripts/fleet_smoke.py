"""fleet-smoke — the CI gate for the r19 million-replica scenario fleet
(block-sharded fleets + resume-exact checkpoints + adaptive cliff
search).

Two legs, both correctness-only (scale and RSS are priced by the
committed SIMBENCH ``fleet_scale`` artifact, never asserted on the CI
container):

1. **Kill-and-restore across process counts**: a tiny scenario grid runs
   three ways through ``cli/fleet_bench.py`` — P=1 unbroken; P=2 with a
   MID-SWEEP fleet checkpoint (each rank writing only its shards) that
   then CONTINUES; and a P=1 restore of that P=2 checkpoint (a different
   process count than the saver).  All three must land identical
   per-scenario state digests AND identical score records, bit for bit.

2. **Adaptive vs dense cliff search**: ``scenarios.refine_surface`` must
   find the dense 1-dose grid's cliff coordinate with strictly fewer
   scenario-evaluations, through ONE compiled fleet program.

Exit 0 on success, 1 with a diagnosis on any failure.

Usage:
    python scripts/fleet_smoke.py
"""

from __future__ import annotations

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))


def main() -> int:
    failures: list[str] = []

    # -- 1: kill-and-restore across process counts ---------------------------
    from multihost_launch import launch

    ck = os.path.join(tempfile.mkdtemp(prefix="fleet_smoke_"), "ck")
    grid_args = [
        "--n", "256", "--k", "16", "--b-doses", "4", "--losses", "0.0,0.1",
        "--churn-max", "8", "--horizon", "48", "--journal-every", "16",
        "--suspect-ticks", "6",
    ]
    worker = ["-m", "ringpop_tpu.cli.fleet_bench"]
    try:
        unbroken = launch(1, worker + ["sweep"] + grid_args)[0]["records"][0]
        saved = launch(
            2, worker + ["sweep", "--save-at", "32", "--path", ck] + grid_args
        )
        restored = launch(
            1, worker + ["sweep-restore", "--path", ck] + grid_args
        )[0]["records"][0]
    except Exception as e:  # noqa: BLE001 — the diagnosis IS the product
        print("fleet-smoke: FAIL")
        print(f"  - launcher leg died: {type(e).__name__}: {e}")
        return 1

    dig_p2: dict = {}
    scores_p2: list = []
    for r in saved:
        rec = r["records"][0]
        dig_p2.update(rec["digests"])
        scores_p2 += rec["scores"]
    scores_p2.sort(key=lambda s: s["scenario_id"])

    if unbroken["digests"] != dig_p2:
        failures.append(
            f"P=2 (mid-sweep save) digests diverge from P=1 unbroken: "
            f"{dig_p2} vs {unbroken['digests']}"
        )
    if unbroken["scores"] != scores_p2:
        failures.append("P=2 score records diverge from P=1 unbroken")
    if unbroken["digests"] != restored["digests"]:
        failures.append(
            f"P=2-save -> P=1-restore digests diverge: {restored['digests']} "
            f"vs {unbroken['digests']}"
        )
    if unbroken["scores"] != restored["scores"]:
        failures.append("restored score records diverge from unbroken run")
    if restored.get("resumed", {}).get("saved_process_count") != 2:
        failures.append(
            f"restore-proof header wrong: {restored.get('resumed')}"
        )

    # -- 2: adaptive vs dense cliff coordinates ------------------------------
    import numpy as np

    from ringpop_tpu.sim import lifecycle, scenarios
    from ringpop_tpu.util.accel import configure_compile_cache

    configure_compile_cache()
    n = 512
    params = lifecycle.LifecycleParams(n=n, k=16)
    rng = np.random.default_rng(0)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    kw = dict(
        victims=victims, losses=(0.0,), max_dose=64, churn_seed=777,
        max_ticks=1024, check_every=1,
    )
    ad = scenarios.refine_surface(params, coarse=9, **kw)
    de = scenarios.dense_surface(params, **kw)
    ad_at = ad["cliffs"][0.0]["cliff_at"]
    de_at = de["cliffs"][0.0]["cliff_at"]
    if ad_at != de_at or ad_at is None:
        failures.append(
            f"adaptive cliff {ad_at} != dense {de_at} "
            f"(adaptive points {ad['points'][0.0]})"
        )
    if not ad["evals_unique"] < de["evals_unique"]:
        failures.append(
            f"adaptive used {ad['evals_unique']} evals vs dense "
            f"{de['evals_unique']} — no saving"
        )

    if failures:
        print("fleet-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print(
        f"fleet-smoke: OK — B={unbroken['b']} fleet: P=1 unbroken == P=2 "
        f"(mid-sweep save, each rank its own shards) == P=2-save->P=1-restore "
        f"({len(unbroken['digests'])} digests + {len(unbroken['scores'])} "
        f"score records bit-exact); adaptive cliff at dose {ad_at} == dense "
        f"({ad['evals_unique']} vs {de['evals_unique']} scenario-evals, "
        f"{ad['dispatches']} dispatches)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
