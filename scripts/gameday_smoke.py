"""gameday-smoke — the CI gate for the r22 closed observability loop.

One in-process game day (P=2 fleet threads over a LocalKV) with the
full loop attached — AggregatingStats → LiveOps → RuleEngine →
OpsController — and a zone cut injected mid-run, judged on four legs:

1. **The controller mitigates.**  The probe-timeout spike rule fires
   one journal block after the cut; the controller drains the cut
   zone's ring block (a RingStore generation commit) and the effect
   probe confirms the drained server's key share over the probe
   population reads 0 against the post-drain ring.
2. **Strictly earlier than SWIM.**  Time-to-mitigate beats the
   no-controller twin, whose "mitigation" is the organic faulty
   declaration (suspect_ticks + dissemination).
3. **Bit-transparency, twice over.**  The controller-on fleet, the
   controller-off twin, and a bare P=1 run with NO obs plane at all
   (the HEAD oracle) land identical digests — observing and reacting
   on the host plane never perturbs the simulation.
4. **The chain reconstructs from the journal alone.**  For the drain's
   trace, ``obs.chain()`` returns alert → action → effect with the
   action's parent equal to the alert's span and the effect's parent
   equal to the action's span; the twin journals zero actions and only
   the spike rule ever fired (the skew/staleness rules stayed quiet).

Exit 0 on success, 1 with a diagnosis on any failure.  ~15 s — wired
into ``make test``.

Usage:
    python scripts/gameday_smoke.py
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CFG = dict(scenario="zone_cut", n=64, seed=0, horizon=48, journal_every=8)


def main() -> int:
    from ringpop_tpu.obs.gameday import bare_digests, gameday_pair

    failures: list[str] = []
    pair = gameday_pair(**CFG)
    on, off = pair["on"], pair["off"]

    # -- leg 1: the controller mitigated --------------------------------------
    drains = [a for a in on["actions"] if a["action"] == "drain" and a["ok"]]
    effects = [a for a in on["actions"] if a["action"] == "effect" and a["ok"]]
    if not drains:
        failures.append(f"controller took no successful drain: {on['actions']}")
    if not effects:
        failures.append(
            "drain effect probe did not read share 0 for the drained server: "
            f"{[a for a in on['actions'] if a['action'] == 'effect']}"
        )
    if on["mitigation_tick"] is None:
        failures.append("no mitigation tick recorded on the controller run")
    elif on["mitigation_tick"] <= on["cut_at"]:
        failures.append(
            f"mitigation at tick {on['mitigation_tick']} precedes the cut "
            f"at {on['cut_at']} — the loop reacted to nothing"
        )

    # -- leg 2: strictly earlier than the organic twin ------------------------
    if not pair["mitigated_earlier"]:
        failures.append(
            f"controller was not strictly earlier: ttm_on={pair['ttm_on']} "
            f"vs ttm_off={pair['ttm_off']}"
        )

    # -- leg 3: digest-identical to the twin AND to bare HEAD -----------------
    if not pair["digest_equal"]:
        failures.append(
            f"controller-on digests {on['digests']} != controller-off "
            f"{off['digests']} — the loop is not host-plane-only"
        )
    head = bare_digests(**CFG)
    if off["digests"] != head:
        failures.append(
            f"controller-off digests {off['digests']} != bare no-obs run "
            f"{head} — the obs plane itself perturbs the sim"
        )

    # -- leg 4: chain + twin silence ------------------------------------------
    if off["actions"]:
        failures.append(f"the no-controller twin took actions: {off['actions']}")
    stray = {a["rule"] for a in on["alerts"]} - {"probe-timeout-spike"}
    if stray:
        failures.append(f"quiet-by-construction rules fired: {sorted(stray)}")
    if not on["chains"]:
        failures.append("no alert→action chain reconstructed from the journal")
    for ch in on["chains"]:
        kinds = [r["kind"] for r in ch]
        if not ch or ch[0]["kind"] != "alert" or ch[0]["parent"] is not None:
            failures.append(f"chain does not root at the alert: {kinds}")
            continue
        acts = [r for r in ch if r["kind"] == "action"
                and r["action"] == "drain"]
        if not acts:
            failures.append(f"chain carries no drain action: {kinds}")
            continue
        root_span = ch[0]["span"]
        for act in acts:
            if act["parent"] != root_span:
                failures.append(
                    f"drain span {act['span']} does not parent on the "
                    f"alert span {root_span}"
                )
            kids = [r for r in ch if r["kind"] == "action"
                    and r["action"] == "effect"
                    and r.get("parent") == act["span"]]
            if not kids:
                failures.append(
                    f"drain span {act['span']} has no effect record "
                    "parented on it"
                )

    if failures:
        print("gameday-smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(json.dumps({
        "gameday_smoke": {
            "scenario": pair["scenario"],
            "cut_at": on["cut_at"],
            "ttm_on": pair["ttm_on"],
            "ttm_off": pair["ttm_off"],
            "digest_equal": True,
            "digest_matches_head": True,
            "drains": len(drains),
            "effects": len(effects),
            "chains": len(on["chains"]),
        }
    }))
    print("gameday-smoke: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
