"""jaxlint — drive the static-analysis planes (``make lint``).

Plane 1 (``ringpop_tpu/analysis/astlint``) lints the package source for
codebase-specific hazards; plane 2 (``ringpop_tpu/analysis/trace_checks``)
traces the public jitted entry points dense + under the 8-way virtual
mesh and checks the invariants of the traced programs themselves; plane 3
(``ringpop_tpu/analysis/hostlint``) lints the host concurrency layer —
lock-order inversions, blocking-under-lock, thread leaks, unlocked
shared attributes, journal-schema drift.  Rule catalog and the story
behind each rule: ANALYSIS.md.

Usage:
    python scripts/jaxlint.py                      # full repo, both planes
    python scripts/jaxlint.py --plane 1            # AST plane only (fast)
    python scripts/jaxlint.py --format json        # machine-readable listing
    python scripts/jaxlint.py path/to/file.py ...  # explicit files

Explicit file arguments are linted by every applicable AST rule; a file
defining ``JAXLINT_TRACE_RULE = "RPJ2xx"`` and ``build()`` is a trace
fixture and additionally runs that jaxpr/HLO-plane rule on its built
program — this is how the fixture corpus under
``tests/analysis_fixtures/`` exercises plane 2 (and how ``make lint``
can be pointed at a trip-case to prove it fails).

Exit codes: 0 clean, 1 unwaived findings, 2 waiver-file config error.
``--format json`` emits every finding (waived ones flagged) plus unused
waivers — a stable diffable surface for future budget re-baselines.

Waivers: ``ringpop_tpu/analysis/waivers.toml`` — (rule, path, scope)
matches with mandatory justification strings; unused entries are
reported so they rot visibly.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# plane 2 traces under the same 8-virtual-device CPU topology as the
# tests and profile_mesh; must be pinned before jax initializes (the
# import is deferred until a plane-2 check actually runs, so plane-1-only
# invocations never pay jax startup)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# the default plane-1 sweep: every tree that holds device code or drives
# it (tests are deliberately out — they pin threefry goldens and host
# coercions by design; the fixture corpus routes through explicit paths)
DEFAULT_PATHS = ("ringpop_tpu", "scripts", "examples", "bench.py", "__graft_entry__.py")
WAIVERS_PATH = os.path.join("ringpop_tpu", "analysis", "waivers.toml")


def _trace_fixture_rule(path: str) -> str | None:
    """The JAXLINT_TRACE_RULE marker of a fixture file, or None."""
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id == "JAXLINT_TRACE_RULE"
                    and isinstance(node.value, ast.Constant)
                ):
                    return str(node.value.value)
    return None


def _run_trace_fixture(path: str, rule: str):
    """Load a fixture module and run its declared plane-2 rule."""
    import importlib.util

    from ringpop_tpu.analysis import trace_checks

    spec = importlib.util.spec_from_file_location(
        "jaxlint_fixture_" + os.path.basename(path)[:-3], path
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    built = mod.build()
    fn, args = built[:-1], built[-1]
    if len(fn) == 1:
        fn = fn[0]
    findings = trace_checks.check_fixture(rule, fn, args)
    rel = os.path.relpath(path, _REPO).replace(os.sep, "/")
    for f in findings:
        f.path = rel  # anchor fixture findings at the file, not the trace tag
    return findings


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("paths", nargs="*", help="explicit files/dirs (default: repo sweep)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--plane", choices=("1", "2", "3", "all"), default="all",
        help="1 = AST lint only (no jax import), 2 = trace checks only, "
        "3 = host-concurrency lint only (no jax import), all = every "
        "plane (default)",
    )
    ap.add_argument(
        "--waivers", default=os.path.join(_REPO, WAIVERS_PATH),
        help="waiver file (default: ringpop_tpu/analysis/waivers.toml)",
    )
    args = ap.parse_args()

    from ringpop_tpu.analysis import astlint, findings as findings_mod, waivers

    all_findings = []
    explicit = bool(args.paths)
    paths = args.paths or list(DEFAULT_PATHS)

    if args.plane in ("1", "all"):
        all_findings += astlint.lint_paths(paths, _REPO)

    if args.plane in ("3", "all"):
        from ringpop_tpu.analysis import hostlint

        all_findings += hostlint.lint_paths(paths, _REPO)

    if args.plane in ("2", "all"):
        if explicit:
            files = []
            for p in paths:
                ap_ = p if os.path.isabs(p) else os.path.join(_REPO, p)
                if os.path.isdir(ap_):
                    for dirpath, dirnames, filenames in os.walk(ap_):
                        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                        files += [
                            os.path.join(dirpath, f)
                            for f in sorted(filenames) if f.endswith(".py")
                        ]
                elif os.path.isfile(ap_):
                    files.append(ap_)
            for ap_ in files:
                rule = _trace_fixture_rule(ap_)
                if rule:
                    all_findings += _run_trace_fixture(ap_, rule)
        else:
            from ringpop_tpu.analysis import trace_checks

            all_findings += trace_checks.run_trace_checks()
            all_findings += trace_checks.run_hlo_checks()

    try:
        wlist = waivers.load_waivers(args.waivers)
        unused = waivers.apply_waivers(all_findings, wlist)
    except waivers.WaiverError as e:
        print(f"jaxlint: waiver config error: {e}", file=sys.stderr)
        return 2
    if explicit or args.plane != "all":
        # a scoped run (explicit paths, or a single plane) only lints a
        # subset — a waiver for an un-linted file or another plane's rule
        # is not stale, so the unused report would mislead (and its
        # "delete it" advice would break the full sweep)
        unused = []

    unwaived = [f for f in all_findings if not f.waived]
    if args.format == "json":
        print(findings_mod.to_json(
            all_findings, unused,
            extra={"planes": args.plane, "paths": paths},
        ))
    else:
        for f in all_findings:
            print(f.render())
        for w in unused:
            print(
                f"jaxlint: WARNING unused waiver {w['rule']} {w['path']} "
                f"{w['scope']} (waivers.toml:{w['_line']}) — delete or fix it"
            )
        n_wv = len(all_findings) - len(unwaived)
        print(
            f"jaxlint: {len(unwaived)} finding(s), {n_wv} waived"
            + (f", {len(unused)} unused waiver(s)" if unused else "")
        )
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
