"""live-smoke — the CI gate for the r20 live operations plane (obs/).

Drives a P=2 IN-PROCESS fleet sweep (LocalKV threads — the same obs
fabric code paths real OS processes run, r14's threaded-twin
discipline) with the full live plane attached, and asserts:

1. **/progress serves both ranks** — rank 0's endpoint reports every
   rank's ``ticks_done``/``horizon`` (scraped over real HTTP, mid-run
   when the container is slow enough to catch it, and at completion);
2. **aggregation is exact** — the unlabeled ``/metrics`` aggregate of
   ``ringpop_sim_ping_send`` equals the sum of BOTH ranks' journal
   ``ping_send`` block sums (the cross-rank collector loses nothing);
3. **bit-transparency** — a live-plane-on P=1 sweep lands per-scenario
   digests and score records identical to a plane-off run;
4. **the flight recorder leaves the last seconds behind** — killing
   rank 1 mid-sweep (its journal sink raises at a block boundary)
   produces a flight dump whose LAST block record equals the rank's
   journal tail record exactly.

Exit 0 on success, 1 with a diagnosis on any failure.  Wall cost is a
few seconds (n=256, B=8) — wired into ``make test``.

Usage:
    python scripts/live_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _scrape(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=5) as r:
        return r.read().decode()


def main() -> int:
    import numpy as np

    from ringpop_tpu.obs.endpoint import LiveOps
    from ringpop_tpu.obs.flight import FlightRecorder
    from ringpop_tpu.parallel.fabric import LocalKV
    from ringpop_tpu.parallel.partition import process_block
    from ringpop_tpu.sim import chaos, scenarios, telemetry
    from ringpop_tpu.sim.lifecycle import LifecycleParams

    failures: list[str] = []
    tmp = tempfile.mkdtemp(prefix="livesmoke_")
    n, k, horizon, journal_every, seed = 256, 16, 32, 8, 0

    params = LifecycleParams(n=n, k=k, suspect_ticks=6, rng="counter")
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=4, replace=False).tolist())
    doses = scenarios.mc_churn_doses(4, n // 32)
    plan, meta = scenarios.scenario_grid(
        n, victims=victims, doses=doses, losses=(0.0, 0.1),
        churn_seed=seed + 777,
    )
    seeds = scenarios.grid_seeds(meta, seed)
    b = len(meta)

    def rank_slice(rank, nprocs):
        lo, hi = process_block(b, rank, nprocs)
        return chaos.slice_plan(plan, lo, hi), meta[lo:hi], seeds[lo:hi]

    def run_rank(rank, nprocs, kv, ns, journal_path, *, obs=None,
                 kill_after_blocks=None):
        """One rank's sweep; returns (sweep, journal records)."""
        sink_seen = [0]

        def killer(rec):
            sink_seen[0] += 1
            if (
                kill_after_blocks is not None
                and sink_seen[0] >= kill_after_blocks * len(meta_s)
            ):
                raise RuntimeError("live-smoke: simulated mid-sweep crash")

        plan_s, meta_s, seeds_s = rank_slice(rank, nprocs)
        with telemetry.TelemetryJournal(journal_path) as journal:
            journal.header("montecarlo", "live_smoke", {"rank": rank})
            sink = telemetry.TelemetrySink(journal=journal, fn=killer)
            sweep = scenarios.FleetSweep(
                params, plan_s, meta_s, seeds_s, horizon=horizon,
                journal_every=journal_every, scenario="live_smoke",
                global_b=b, sink=sink, obs=obs,
            )
            sweep.run()
            # score inside the journal's lifetime (scores() writes the
            # verdict records into it)
            return sweep, sweep.digests(), sweep.scores()

    # -- legs 1+2: P=2 live endpoint + exact aggregation ----------------------
    kv = LocalKV()
    opses: list = [None, None]
    sweeps: list = [None, None]
    errs: list = [None, None]
    journals = [os.path.join(tmp, f"rank{r}.jsonl") for r in range(2)]
    ready = threading.Barrier(2, timeout=60)

    def worker(rank):
        try:
            ops = LiveOps(rank, 2, kv=kv, namespace="live-smoke")
            opses[rank] = ops
            ready.wait()
            sweeps[rank] = run_rank(rank, 2, kv, "live-smoke",
                                    journals[rank], obs=ops)
        except BaseException as e:  # noqa: BLE001
            errs[rank] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    # serve rank 0's endpoint as soon as its LiveOps exists, then poll
    # /progress while the sweep runs (best-effort mid-run observation)
    addr = None
    midrun_seen = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and addr is None:
        if opses[0] is not None:
            addr = opses[0].serve()
        else:
            time.sleep(0.01)
    while any(t.is_alive() for t in threads):
        if addr is not None:
            try:
                p = json.loads(_scrape(addr, "/progress"))
                if len(p["ranks"]) == 2 and midrun_seen is None:
                    midrun_seen = p
            except OSError:
                pass
        time.sleep(0.02)
    for t in threads:
        t.join(60)
    if any(errs):
        print("live-smoke: FAIL")
        print(f"  - a sweep rank died: {errs}")
        return 1

    # final /progress must show BOTH ranks at the horizon; poll briefly
    # for the last obs round to land (sync is non-blocking by design)
    prog = None
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        prog = json.loads(_scrape(addr, "/progress"))
        done = [
            r for r in prog["ranks"].values()
            if r.get("ticks_done") == horizon
        ]
        if len(done) == 2:
            break
        time.sleep(0.05)
    if prog is None or len(prog["ranks"]) != 2 or any(
        r.get("ticks_done") != horizon or r.get("horizon") != horizon
        for r in prog["ranks"].values()
    ):
        failures.append(f"/progress does not show both ranks done: {prog}")
    if midrun_seen is not None and len(midrun_seen["ranks"]) != 2:
        failures.append(f"mid-run /progress missing a rank: {midrun_seen}")

    health = json.loads(_scrape(addr, "/healthz"))
    if not health["ok"]:
        failures.append(f"/healthz not ok on a healthy run: {health}")

    metrics = _scrape(addr, "/metrics")
    agg = None
    for line in metrics.splitlines():
        if line.startswith("ringpop_sim_ping_send ") and "{" not in line:
            agg = float(line.split()[1])
    journal_sum = 0
    for path in journals:
        journal_sum += sum(
            int(r["ping_send"]) for r in telemetry.read_journal(path)
            if r["kind"] == "block"
        )
    if agg is None:
        failures.append("no aggregated ringpop_sim_ping_send in /metrics")
    elif int(agg) != journal_sum:
        failures.append(
            f"aggregated counter {agg} != ranks' journal sum {journal_sum}"
        )
    for o in opses:
        if o is not None:
            o.close()

    # -- leg 3: bit-transparency (plane-on == plane-off) ----------------------
    _, bare_digests, bare_scores = run_rank(
        0, 1, None, "", os.path.join(tmp, "bare.jsonl"))
    ops1 = LiveOps(0, 1, recorder=FlightRecorder(
        capacity=128, path=os.path.join(tmp, "fl0.jsonl")))
    ops1.serve()
    _, live_digests, live_scores = run_rank(
        0, 1, None, "", os.path.join(tmp, "live.jsonl"), obs=ops1)
    ops1.close()
    if bare_digests != live_digests:
        failures.append(
            f"live plane perturbed the sweep: digests {live_digests} "
            f"vs {bare_digests}"
        )
    if bare_scores != live_scores:
        failures.append("live plane perturbed the score records")

    # -- leg 4: kill a rank mid-sweep -> flight dump == journal tail ----------
    kv2 = LocalKV()
    flight_path = os.path.join(tmp, "flight-rank1.jsonl")
    recorder1 = FlightRecorder(capacity=64, rank=1, path=flight_path)
    recorder1.install(fabric=False, excepthook=False, threads=True)
    kill_errs: list = [None, None]
    ready2 = threading.Barrier(2, timeout=60)
    kj = [os.path.join(tmp, f"kill-rank{r}.jsonl") for r in range(2)]

    def kill_worker(rank):
        ops = LiveOps(rank, 2, kv=kv2, namespace="live-kill",
                      recorder=recorder1 if rank == 1 else None,
                      timeout_ms=10_000)
        ready2.wait()
        try:
            run_rank(rank, 2, kv2, "live-kill", kj[rank], obs=ops,
                     kill_after_blocks=2 if rank == 1 else None)
        finally:
            if rank == 0:
                ops.close()
        # rank 1 leaves its ops open: the thread dies with the sweep,
        # exactly like a crashed process

    kt = []
    for r in range(2):
        t = threading.Thread(target=kill_worker, args=(r,))
        t.start()
        kt.append(t)
    for t in kt:
        t.join(120)
    recorder1.uninstall()
    if recorder1.dumped is None:
        failures.append("killing rank 1 produced no flight dump")
    else:
        dump = [json.loads(x) for x in open(recorder1.dumped)]
        head = dump[0]
        if head["kind"] != "flight_header" or "crash" not in str(
            head.get("error")
        ):
            failures.append(f"flight header malformed: {head}")
        dump_blocks = [r for r in dump if r.get("kind") == "block"]
        jr = [
            r for r in telemetry.read_journal(kj[1]) if r["kind"] == "block"
        ]
        if not dump_blocks or not jr:
            failures.append("kill leg produced no block records to compare")
        else:
            last_dump = {
                kk: v for kk, v in dump_blocks[-1].items()
                if kk != "flight_seq"
            }
            if last_dump != jr[-1]:
                failures.append(
                    "flight dump tail != rank 1 journal tail:\n"
                    f"    dump:    {last_dump}\n    journal: {jr[-1]}"
                )

    if failures:
        print("live-smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(json.dumps({
        "live_smoke": {
            "ranks": 2,
            "horizon": horizon,
            "progress_midrun_seen": midrun_seen is not None,
            "aggregated_ping_send": int(agg),
            "journal_sum": journal_sum,
            "digests_bit_identical": True,
            "flight_dump": os.path.basename(recorder1.dumped or ""),
        }
    }))
    print("live-smoke: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
