"""mc-smoke — the CI gate for the batched chaos fleet (r12 tentpole:
``chaos.stack_plans`` + the Monte-Carlo fleet + ``sim/scenarios.py``).

Runs a tiny churn×loss scenario grid through the batched machinery and
asserts:

1. **B=1 identity**: a single-member stacked plan run through the fleet
   (vmapped step, batched telemetry) ends bit-identical — state digest
   AND telemetry block record — to the same plan through the solo
   ``LifecycleSim`` chaos path.  The batch axis must never change a
   member's trajectory.
2. **Scored-journal round-trip**: the fleet journal (one header, B block
   records per fetch each tagged ``scenario_id``, one ``kind: "score"``
   verdict per scenario with its grid coordinates) parses back equal.
3. **Surface shape**: the grid's detection response surface has one cell
   per (loss, dose) and the batched first-detection ticks match a solo
   re-run of one probe scenario exactly.

Exit 0 on success, 1 with a diagnosis on any failure.  Wall cost is a
few seconds (n=128) — wired into `make test` next to chaos-smoke.

Usage:
    python scripts/mc_smoke.py [--out /tmp/mc_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="journal path (default: temp file)")
    args = ap.parse_args()

    import numpy as np

    from ringpop_tpu.sim import chaos, lifecycle, scenarios, telemetry
    from ringpop_tpu.sim.montecarlo import MonteCarlo
    from ringpop_tpu.util.accel import configure_compile_cache

    configure_compile_cache()

    path = args.out or os.path.join(
        tempfile.mkdtemp(prefix="mcsmoke_"), "mc_smoke.jsonl"
    )
    n, k, seed, horizon, block = 128, 16, 0, 64, 16
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=6, rng="counter")
    rng = np.random.default_rng(seed)
    victims = sorted(rng.choice(n, size=2, replace=False).tolist())
    doses = [0, 4]
    losses = (0.0, 0.1)
    plan, meta = scenarios.scenario_grid(
        n, victims=victims, doses=doses, losses=losses, churn_seed=seed + 777
    )
    seeds = scenarios.grid_seeds(meta, seed)
    failures: list[str] = []

    # -- 1: B=1 identity (fleet vs solo LifecycleSim, same chaos plan) -------
    solo_plan = chaos.scenario_plan("smoke", n, seed=seed, horizon=horizon)
    b1 = chaos.stack_plans([solo_plan])
    mc1 = MonteCarlo(params, [seed], telemetry=True)
    recs1 = []
    for _ in range(horizon // block):
        mc1.run(block, b1)
        recs1.append(mc1.fetch_telemetry(b1)[0])
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(
        n=n, k=k, seed=seed, suspect_ticks=6, rng="counter", telemetry=sink
    )
    for _ in range(horizon // block):
        sim.run(block, solo_plan)
    solo_digest = int(telemetry.tree_digest(sim.state))
    if recs1[-1]["state_digest"] != solo_digest:
        failures.append(
            f"B=1 state digest {recs1[-1]['state_digest']:#010x} != solo "
            f"{solo_digest:#010x}"
        )
    for i, (fleet_rec, solo_rec) in enumerate(zip(recs1, sink.records)):
        for key in solo_rec:
            if key in ("state_digest",):
                continue
            if fleet_rec.get(key) != solo_rec[key]:
                failures.append(
                    f"B=1 telemetry block {i} field {key!r}: fleet "
                    f"{fleet_rec.get(key)} != solo {solo_rec[key]}"
                )
                break

    # -- 2: scored journal round-trip over the tiny grid ---------------------
    with telemetry.TelemetryJournal(path) as journal:
        journal.header(
            "lifecycle", "mc-smoke",
            {"n": n, "k": k, "seed": seed, "grid": {"doses": doses, "losses": list(losses)}},
        )
        gsink = telemetry.TelemetrySink(journal=journal)
        scores = scenarios.scored_fleet(
            params, plan, meta, seeds, horizon=horizon, journal_every=block,
            sink=gsink, scenario="mc-smoke",
        )
    try:
        records = telemetry.read_journal(path)
    except Exception as e:  # noqa: BLE001 — the diagnosis IS the product
        records = []
        failures.append(f"journal unparseable: {type(e).__name__}: {e}")
    jblocks = [r for r in records if r.get("kind") == "block"]
    jscores = [r for r in records if r.get("kind") == "score"]
    if len(jscores) != len(meta):
        failures.append(f"expected {len(meta)} score records, found {len(jscores)}")
    if {b.get("scenario_id") for b in jblocks} != set(range(len(meta))):
        failures.append("journal blocks missing scenario_id coverage")
    for s in jscores:
        if "churn" not in s or "loss" not in s:
            failures.append("score record lost its grid coordinates")
            break
    by_id = {s["scenario_id"]: s for s in jscores if "scenario_id" in s}
    if by_id and scores:
        want = scores[0]["false_positive_suspects"]
        if by_id.get(0, {}).get("false_positive_suspects") != want:
            failures.append("journaled score differs from the computed one")

    # -- 3: surface shape + one-probe solo agreement -------------------------
    ticks, detected, _ = scenarios.detect_surface(
        params, plan, seeds, victims, max_ticks=512, check_every=4
    )
    surface = scenarios.response_surface(
        meta, [int(t) if d else None for t, d in zip(ticks, detected)],
        rows="loss", cols="churn",
    )
    if (len(surface["cells"]), len(surface["cells"][0])) != (len(losses), len(doses)):
        failures.append(f"surface shape {np.shape(surface['cells'])} != grid")
    probe = len(meta) - 1  # highest-loss, highest-dose corner
    mc_solo = MonteCarlo(params, [seeds[probe]])
    t_solo, d_solo = mc_solo.run_until_detected(
        victims, chaos.stack_plans([chaos.index_plan(plan, probe)]),
        max_ticks=512, check_every=4,
    )
    if (int(t_solo[0]), bool(d_solo[0])) != (int(ticks[probe]), bool(detected[probe])):
        failures.append(
            f"probe scenario {probe}: solo ticks {int(t_solo[0])} != "
            f"batched {int(ticks[probe])}"
        )

    if failures:
        print("mc-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print(
        f"mc-smoke: OK — B={len(meta)} grid scored ({len(jscores)} verdicts, "
        f"{len(jblocks)} blocks) at {path}; B=1 fleet digest-equal to solo "
        f"({solo_digest:#010x}); surface {len(losses)}x{len(doses)} with "
        f"{int(np.asarray(detected).sum())}/{len(meta)} detected"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
