#!/usr/bin/env python
"""Tiny multi-process launcher for the DCN-fabric certification runs.

Forks N CPU worker processes wired into one ``jax.distributed`` job — the
standard env contract (``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
/ ``JAX_PROCESS_ID``) exported per rank, a free localhost port for the
coordinator, ``JAX_PLATFORMS=cpu`` pinned, and one JSONL journal per rank
(``MULTIHOST_JSONL``) collected after exit.  Reused by ``simbench
multihost16m``, ``make multihost-smoke`` and the test suite — one spawn
path, so every certificate runs through the same bring-up the launcher
documentation shows a real pod operator.

Importable: :func:`launch`.  CLI::

    python scripts/multihost_launch.py --nprocs 2 -- \
        -m ringpop_tpu.cli.multihost_bench twin --n 4096 --k 64 --ticks 24
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
from typing import Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(
    nprocs: int,
    argv: Sequence[str],
    devices_per_proc: int = 1,
    timeout_s: float = 900.0,
    env_extra: Optional[dict] = None,
    live_port_base: Optional[int] = None,
) -> list[dict]:
    """Run ``python <argv>`` as ``nprocs`` coordinated ranks; return one
    record per rank: ``{"rank", "rc", "records" (parsed JSONL),
    "stdout", "stderr"}``.  Raises on nonzero exit so a dead worker can't
    read as an empty-but-green run."""
    port = _free_port()
    tmp = tempfile.mkdtemp(prefix="multihost_")
    procs, logs = [], []
    for rank in range(nprocs):
        jsonl = os.path.join(tmp, f"rank{rank}.jsonl")
        logs.append(jsonl)
        env = dict(os.environ)
        env.pop("BENCH_PIN", None)
        env.update(
            {
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(nprocs),
                "JAX_PROCESS_ID": str(rank),
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": (
                    env.get("XLA_FLAGS", "").replace(
                        "--xla_force_host_platform_device_count=8", ""
                    )
                    + f" --xla_force_host_platform_device_count={devices_per_proc}"
                ).strip(),
                "MULTIHOST_JSONL": jsonl,
                "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            }
        )
        if live_port_base:
            # the live operations plane (r20): rank r serves /metrics,
            # /healthz and /progress at base + r — workers that honor
            # RINGPOP_OBS_PORT (cli/fleet_bench.py) pick it up; others
            # ignore it
            env["RINGPOP_OBS_PORT"] = str(live_port_base + rank)
        env.update(env_extra or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, *argv],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
    # drain every rank's pipes CONCURRENTLY: a sequential communicate()
    # walk deadlocks the job the moment any later rank writes more than a
    # pipe buffer (~64 KB) to stdout — that rank blocks mid-write and
    # never reaches the coordinated exit, while the earlier rank waits
    # for it inside jax.distributed teardown (observed at fleet scale,
    # where a rank's record line is ~0.5 MB)
    import threading

    drained: list = [None] * nprocs

    def _drain(rank: int, p) -> None:
        try:
            drained[rank] = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            drained[rank] = p.communicate() + ("timeout",)

    threads = [
        threading.Thread(target=_drain, args=(rank, p), daemon=True)
        for rank, p in enumerate(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = []
    failure = None
    for rank, p in enumerate(procs):
        stdout, stderr = drained[rank][0], drained[rank][1]
        if len(drained[rank]) > 2:
            failure = failure or f"rank {rank} timed out after {timeout_s}s"
        records = []
        if os.path.exists(logs[rank]):
            with open(logs[rank]) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        records.append(json.loads(line))
        out.append(
            {
                "rank": rank,
                "rc": p.returncode,
                "records": records,
                "stdout": stdout,
                "stderr": stderr,
            }
        )
        if p.returncode != 0 and failure is None:
            failure = (
                f"rank {rank} rc={p.returncode}\nstdout: {stdout[-800:]}\n"
                f"stderr: {stderr[-2000:]}"
            )
    if failure:
        raise RuntimeError(f"multihost launch failed: {failure}")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--devices-per-proc", type=int, default=1)
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--live-port-base", type=int, default=0,
                   help="export RINGPOP_OBS_PORT=base+rank per rank so "
                   "obs-aware workers serve their live endpoints there "
                   "(0 = off)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker argv after '--' (passed to python)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        p.error("worker command required after --")
    ranks = launch(args.nprocs, cmd, devices_per_proc=args.devices_per_proc,
                   timeout_s=args.timeout,
                   live_port_base=args.live_port_base or None)
    for r in ranks:
        for rec in r["records"]:
            print(json.dumps({"rank": r["rank"], **rec}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
