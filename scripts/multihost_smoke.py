#!/usr/bin/env python
"""Multi-host DCN-fabric CI gate (< 60 s, 2-core container).

Certifies, at small n through the REAL ``jax.distributed`` bring-up
(``scripts/multihost_launch.py`` forks coordinated OS processes):

1. **Sharded == unsharded digests** — the same seeded delta scenario
   (victims + loss) stepped at 1 and 2 processes must produce the same
   global state digest, and that digest must equal the in-process
   ``delta.step`` engine's ``telemetry.tree_digest`` (the single-host
   anchor of the chain).
2. **Cross-process-count snapshot round-trip** — a 2-process block-sharded
   orbax save restored at 1 process continues digest-equal to an unbroken
   reference run.

The heavier 4-process twin and the 4-way restore live in the slow-marked
``tests/test_multihost.py``; the artifact-scale run is ``simbench
multihost16m``.  Exit 0 = certified; any assertion prints and exits 1.
"""

from __future__ import annotations

import functools
import os
import shutil
import sys
import tempfile
import time

_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _SCRIPTS)  # multihost_launch
sys.path.insert(0, os.path.dirname(_SCRIPTS))  # ringpop_tpu package root

T0 = time.perf_counter()
N, K, SEED, TICKS, EXTRA = 2048, 64, 11, 12, 6
VICTIMS, DROP = 16, 0.05


def main() -> int:
    from multihost_launch import launch

    base = ["-m", "ringpop_tpu.cli.multihost_bench"]
    common = [
        "--n", str(N), "--k", str(K), "--seed", str(SEED),
        "--victims", str(VICTIMS), "--drop", str(DROP),
    ]

    # -- leg 1: 1-proc vs 2-proc twin ----------------------------------------
    digests = {}
    for nprocs in (1, 2):
        ranks = launch(nprocs, base + ["twin", *common, "--ticks", str(TICKS)],
                       timeout_s=240)
        recs = [r["records"][-1] for r in ranks]
        ds = {r["digest"] for r in recs}
        assert len(ds) == 1, f"ranks disagree at P={nprocs}: {ds}"
        digests[nprocs] = ds.pop()
    assert digests[1] == digests[2], f"P=1 vs P=2 digest mismatch: {digests}"

    # the single-host engine anchor (in this process, plain jit)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams, init_state, step
    from ringpop_tpu.sim.telemetry import tree_digest

    params = DeltaParams(n=N, k=K, rng="counter")
    rng = np.random.default_rng(SEED + 999)
    up = np.ones(N, bool)
    up[rng.choice(N, size=VICTIMS, replace=False)] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(DROP))
    st = init_state(params, seed=SEED)
    stp = jax.jit(functools.partial(step, params))
    for _ in range(TICKS):
        st = stp(st, faults)
    anchor = int(tree_digest(st))
    assert anchor == digests[1], (
        f"fabric digest {digests[1]} != engine digest {anchor}"
    )
    print(f"twin OK: P=1 == P=2 == engine digest {anchor}")

    # -- leg 2: 2-proc save -> 1-proc restore -> digest-equal continue -------
    ckpt = tempfile.mkdtemp(prefix="mh_smoke_ckpt_")
    shutil.rmtree(ckpt)  # orbax wants to create it
    try:
        ranks = launch(
            2, base + ["snapshot-save", *common, "--ticks", str(TICKS), "--path", ckpt],
            timeout_s=240,
        )
        saved = ranks[0]["records"][-1]
        assert saved["digest"] == anchor, "digest at save != engine digest"
        ranks = launch(
            1,
            base + ["snapshot-restore", *common, "--extra-ticks", str(EXTRA), "--path", ckpt],
            timeout_s=240,
        )
        rest = ranks[0]["records"][-1]
        assert rest["digest_at_restore"] == anchor, "restore broke the state"
        for _ in range(EXTRA):
            st = stp(st, faults)
        ref = int(tree_digest(st))
        assert rest["digest"] == ref, (
            f"continued run diverged: {rest['digest']} != unbroken {ref}"
        )
        print(f"snapshot OK: 2-proc save -> 1-proc restore -> +{EXTRA} ticks == unbroken {ref}")
    finally:
        shutil.rmtree(ckpt, ignore_errors=True)

    wall = time.perf_counter() - T0
    print(f"multihost-smoke PASS in {wall:.1f}s")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(f"multihost-smoke FAIL: {e}", file=sys.stderr)
        sys.exit(1)
