"""Multi-chip collective cost model for the sharded lifecycle engine
(VERDICT r4 item 4): compile the sharded programs on the 8-virtual-device
CPU mesh, dump optimized HLO, and count + size every cross-device
collective — the evidence behind PERF.md's bytes-per-tick-per-chip table.

Two programs are profiled:

1. the one-tick 1M x 256 lifecycle step over the 4x2 ("node" x "rumor")
   mesh — the per-tick ICI traffic of the headline config;
2. the 100k sharded detect program (`_run_until_detected_device`) — to
   answer whether `detection_complete`'s K-iteration slot walk
   serializes under sharding (it holds a fori_loop whose body touches
   [N]-sharded planes one rumor column at a time).

Compile-only (`.lower(...).compile()`); nothing executes, so the run is
CPU-compile-bound (~minutes for the 1M program).  Collectives are read
from the after-optimizations HLO per computation, so while-loop bodies
(executed once per tick / per walk iteration) are reported separately
from one-shot entry computations.

Usage:
    python scripts/profile_mesh.py [--step-n N] [--detect-n N] [--out FILE]
                                   [--compare BASE.json] [--force-sparse]
                                   [--rng counter|threefry]
                                   [--exchange shardmap|gspmd]
                                   [--phase-budget]

``--compare BASE.json`` diffs this run against a prior capture (same n/k
config) and prints a per-collective-class delta table — count and
MB/chip/tick — exiting non-zero if any class regressed beyond the
tolerance, so the collective budget is a ratchet, not a trivia table.
``--phase-budget`` additionally ratchets the per-phase table for the
protocol phases named in ``PHASE_BUDGET_PHASES`` (the exchange and
peer-choice classes this round's work pinned), so a regression can't
hide inside an unchanged global total.
``--force-sparse`` drops the sparse candidate path's engagement floor so
a small --step-n profile exercises the same hierarchical-select code
path as the 1M headline (CI-speed budget checks).
``--rng``/``--exchange`` select the engine's PRNG family and roll-leg
lowering (defaults: the sharded-caller defaults, ``counter`` +
``shardmap``; the r8 'before' capture was taken with ``threefry`` +
``gspmd`` — the r6/r7 program — under the SAME parser).

Census semantics (r8): collectives inside sibling branches of one
``conditional`` (``lax.switch``/``lax.cond``) are mutually exclusive per
execution — the shift exchange's shard-local lowering switches over the
traced shard offset, and the sparse candidate select conds between the
hierarchical path and its full-sort fallback — so every summary charges
only the most expensive branch of each conditional (worst case actually
executable per tick), not the sum of all branches in the program text.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile

# the census parser and the phase vocabulary live in the analysis package
# (ringpop_tpu/analysis/{hlo_census,phases}.py) so the jaxlint HLO plane
# and the pytest budget guards share ONE implementation; this script
# re-exports the names its callers (tests/test_mesh_budget.py,
# tests/test_prng.py) historically imported from here.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.analysis.hlo_census import (  # noqa: E402
    COLLECTIVES,  # noqa: F401 - re-export
    executed_rows,
    newest_module as _newest_module,
    parse_collectives,
    summarize as _summarize,
    summarize_phases as _summarize_phases,
)
from ringpop_tpu.analysis.phases import (  # noqa: E402,F401 - re-exports
    PHASES,
    PHASE_BUDGET_PHASES,
)

def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--step-n", type=int, default=1_000_000)
    ap.add_argument("--step-k", type=int, default=256)
    ap.add_argument("--detect-n", type=int, default=100_000)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--compare", metavar="BASE.json", default=None,
        help="diff this run against a prior capture of the SAME config and "
        "exit non-zero if any collective class regressed beyond --tolerance",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.05,
        help="allowed relative growth per collective class before the "
        "compare fails (default 0.05 = 5%%)",
    )
    ap.add_argument(
        "--force-sparse", action="store_true",
        help="drop the sparse candidate path's n floor so small --step-n "
        "profiles exercise the hierarchical select like the 1M step does",
    )
    ap.add_argument(
        "--rng", choices=("counter", "threefry"), default="counter",
        help="engine PRNG family (default: the sharded-caller default, "
        "'counter' — partition-invariant, zero peer-choice collectives); "
        "'threefry' reproduces the r6/r7 program",
    )
    ap.add_argument(
        "--exchange", choices=("shardmap", "shardmap-seq", "gspmd"),
        default="shardmap",
        help="shift-exchange roll-leg lowering: 'shardmap' = the r11 fused "
        "pipelined crossing-block ppermutes (default), 'shardmap-seq' = the "
        "sequential r8 legs (two shard_roll regions), 'gspmd' = the r6/r7 "
        "partitioner-inferred all-gathers",
    )
    ap.add_argument(
        "--overlap", action="store_true",
        help="analyze the compiled step's exchange schedule "
        "(analysis/overlap.py): report whether response-leg crossing sends "
        "depend only on partial request-leg receives and interleave with "
        "the merge.  With the default pipelined exchange, NO overlap is a "
        "failure (exit 5) — the fused leg loop stopped emitting an "
        "overlappable dependency graph",
    )
    ap.add_argument(
        "--chaos", action="store_true",
        help="drive the profiled step with the canonical churn+flap+loss "
        "FaultPlan (chaos.scenario_plan('smoke')) instead of the static "
        "fault mask — fault-timeline evaluation is elementwise, so the "
        "census must land within the SAME committed budget (the chaos "
        "plane's zero-added-collectives ratchet)",
    )
    ap.add_argument(
        "--fail-unattributed", action="store_true",
        help="promote the '(unattributed)' phase warning to a hard "
        "failure (exit 6): every censused collective must carry a "
        "named-scope phase — OBSERVABILITY.md calls an unattributed "
        "collective a coverage bug, so CI enforces it",
    )
    ap.add_argument(
        "--phase-budget", action="store_true",
        help="with --compare: also ratchet the per-phase table for "
        f"{PHASE_BUDGET_PHASES} (fails on per-phase regressions that an "
        "unchanged global total would hide)",
    )
    args = ap.parse_args()

    dump = tempfile.mkdtemp(prefix="meshhlo_")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count=8"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        sys.exit(_run(args, dump))
    finally:
        shutil.rmtree(dump, ignore_errors=True)


class DumpError(SystemExit):
    """XLA dump missing/unparseable — exit 4, never an empty passing budget."""


def _census_or_die(mod: str | None, dump: str, prog: str) -> dict:
    """Parse the dumped module or die loudly.  An empty/unparseable dump
    used to report an empty census — which ``--compare`` then scored as a
    miracle optimization "within budget" (the exact r6 'before'-capture
    failure mode).  A missing module, a module the parser cannot see a
    single computation in, or a sharded program censusing ZERO
    collectives are all hard errors (exit 4) with the actual dump dir
    contents in the message."""
    if mod is None:
        listing = sorted(os.path.basename(p) for p in glob.glob(os.path.join(dump, "*")))[:12]
        print(f"profile_mesh: {prog}: no *after_optimizations.txt module in "
              f"the XLA dump dir — nothing compiled, or the dump flag/file "
              f"naming rotated.  dump dir holds: {listing or '(empty)'}",
              file=sys.stderr)
        raise DumpError(4)
    census = parse_collectives(mod)
    if census.get("total_computations", 0) == 0:
        print(f"profile_mesh: {prog}: parsed ZERO computations from "
              f"{os.path.basename(mod)} ({os.path.getsize(mod)} bytes) — "
              "HLO text format drift; fix "
              "ringpop_tpu/analysis/hlo_census.parse_collectives before "
              "trusting any budget result", file=sys.stderr)
        raise DumpError(4)
    if not any(census["computations"].values()):
        print(f"profile_mesh: {prog}: censused ZERO collectives in a "
              f"sharded-mesh program ({os.path.basename(mod)}) — parser "
              "drift or the mesh stopped partitioning; refusing to report "
              "an empty census as a passing budget", file=sys.stderr)
        raise DumpError(4)
    return census


def _run(args, dump: str) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import functools
    import time

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    if args.force_sparse:
        lifecycle._SPARSE_TOPK_MIN_N = 0

    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    report: dict = {
        "mesh": "4x2 (node x rumor), virtual CPU devices",
        "rng": args.rng,
        "exchange_lowering": args.exchange,
        "chaos": bool(args.chaos),
    }
    engine_kw = dict(rng=args.rng)
    if args.exchange in ("shardmap", "shardmap-seq"):
        engine_kw["exchange_mesh"] = mesh
        engine_kw["exchange_pipelined"] = args.exchange == "shardmap"

    # -- 1) one-tick step at headline scale --------------------------------
    n, k = args.step_n, args.step_k
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, **engine_kw)
    up = np.ones(n, bool)
    up[:: max(n // 1000, 1)] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    if args.chaos:
        # the chaos-enabled step: same static crash overlay PLUS the
        # canonical churn+flap+loss timeline.  faults_at is elementwise
        # in the node lane, so the census must fit the SAME budget the
        # static program is ratcheted against — deliberately compared
        # against the unchanged committed capture.
        from ringpop_tpu.sim import chaos

        faults = chaos._merge_plans(
            chaos.scenario_plan("smoke", n, seed=0, horizon=64),
            chaos.FaultPlan(base_up=jnp.asarray(up)),
        )
    state = jax.tree.map(
        jax.device_put, lifecycle.init_state(params, seed=0),
        lifecycle.state_shardings(mesh, k=k),
    )
    blk = jax.jit(functools.partial(lifecycle._run_block, params),
                  static_argnames="ticks")
    t0 = time.perf_counter()
    blk.lower(state, faults, ticks=1).compile()
    step_compile_s = time.perf_counter() - t0
    mod = _newest_module(dump, "_run_block")
    if mod is None:
        mod = _newest_module(dump, "")
    census = _census_or_die(mod, dump, "step")
    report["step"] = {
        "n": n, "k": k, "compile_s": round(step_compile_s, 1),
        "module": os.path.basename(mod),
        "by_kind": _summarize(census),
        "by_phase": _summarize_phases(census),
        "by_computation": {
            c: {
                "count": len(rows),
                "bytes": sum(r["bytes"] for r in rows),
                "loop_depth": census["loop_depth"].get(c, 0),
            }
            for c, rows in census["computations"].items()
        },
    }

    # -- 1b) exchange overlap schedule (r11, --overlap): analyzed on the
    # step module BEFORE section 2 clears the dump dir
    overlap_rc = 0
    unattributed_rc = 0
    if args.overlap:
        from ringpop_tpu.analysis import overlap as _overlap

        rep = _overlap.analyze(mod)
        report["overlap"] = rep
        _overlap.print_report(rep)
        if args.exchange == "shardmap" and not rep["overlap"]:
            print("profile_mesh: --overlap: the PIPELINED exchange compiled "
                  "to a strictly sequential schedule — shard_roll_pipelined "
                  "stopped issuing leg-2 sends off partial receives",
                  file=sys.stderr)
            overlap_rc = 5

    # -- 2) the sharded detect program (serialization question) ------------
    for f in glob.glob(os.path.join(dump, "*")):
        shutil.rmtree(f) if os.path.isdir(f) else os.remove(f)
    nd = args.detect_n
    dparams = lifecycle.LifecycleParams(n=nd, k=256, suspect_ticks=10, **engine_kw)
    dup = np.ones(nd, bool)
    dup[:: max(nd // 100, 1)] = False
    dfaults = DeltaFaults(up=jnp.asarray(dup))
    dstate = jax.tree.map(
        jax.device_put, lifecycle.init_state(dparams, seed=0),
        lifecycle.state_shardings(mesh, k=256),
    )
    subjects = jnp.asarray(np.flatnonzero(~dup), jnp.int32)
    # the rumor-axis replication hint for the per-check slot walk — the
    # same static arg the sharded bench paths pass (older engine
    # revisions don't take it; fall back so --compare can profile them)
    detect_kw = dict(
        min_status=lifecycle.FAULTY, block_ticks=32, max_blocks=jnp.int32(16)
    )
    t0 = time.perf_counter()
    try:
        lifecycle._run_until_detected_device.lower(
            dparams, dstate, dfaults, subjects,
            learned_sharding=NamedSharding(mesh, P("node", None)), **detect_kw,
        ).compile()
    except TypeError:
        lifecycle._run_until_detected_device.lower(
            dparams, dstate, dfaults, subjects, **detect_kw
        ).compile()
    detect_compile_s = time.perf_counter() - t0
    mod = _newest_module(dump, "")
    census = _census_or_die(mod, dump, "detect")
    report["detect"] = {
        "n": nd, "k": 256, "compile_s": round(detect_compile_s, 1),
        "module": os.path.basename(mod),
        "by_kind": _summarize(census),
        "by_phase": _summarize_phases(census),
        "by_computation": {
            c: {
                "count": len(rows),
                "bytes": sum(r["bytes"] for r in rows),
                "loop_depth": census["loop_depth"].get(c, 0),
            }
            for c, rows in census["computations"].items()
        },
    }

    for name in ("step", "detect"):
        sec = report[name]
        print(f"\n== {name} (n={sec['n']}, k={sec['k']}, "
              f"compile {sec['compile_s']}s) ==")
        print(f"{'kind':>22} {'count':>6} {'MB total':>10}")
        for kind, e in sorted(sec["by_kind"].items()):
            print(f"{kind:>22} {e['count']:>6} {e['bytes'] / 1e6:>10.2f}")
        print("  by protocol phase (named-scope attribution):")
        for phase, kinds in sorted(sec["by_phase"].items()):
            for kind, e in sorted(kinds.items()):
                print(f"    {phase:>20} {kind:>22} {e['count']:>4} "
                      f"{e['bytes'] / 1e6:>8.2f} MB")
        unattr = sec["by_phase"].get("(unattributed)")
        if unattr:
            n_unattr = sum(e["count"] for e in unattr.values())
            if args.fail_unattributed:
                # the doc calls this a coverage bug; under the CI flag it
                # IS one — a collective outside every named scope can
                # hide from the per-phase budget ratchet
                print("    FAILURE: %d collectives in %r carry no phase "
                      "scope — extend the named_scope coverage in "
                      "sim/lifecycle.py (--fail-unattributed)"
                      % (n_unattr, name))
                unattributed_rc = 6
            else:
                print("    WARNING: %d collectives carry no phase scope — "
                      "extend the named_scope coverage in sim/lifecycle.py"
                      % n_unattr)
        print("  per computation (collective-bearing only; depth = enclosing "
              "while-loop nesting):")
        for c, e in sorted(sec["by_computation"].items(),
                           key=lambda kv: -kv[1]["bytes"])[:12]:
            print(f"    d{e['loop_depth']} {c[:54]:54s} {e['count']:>4}  "
                  f"{e['bytes'] / 1e6:>8.2f} MB")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"\nwrote {args.out}")
    print(json.dumps({"profile_mesh": {k2: report[k2]["by_kind"]
                                       for k2 in ("step", "detect")}}))
    if args.compare:
        rc = _compare(report, args.compare, args.tolerance,
                      phase_budget=args.phase_budget)
        return rc or overlap_rc or unattributed_rc
    return overlap_rc or unattributed_rc


def _compare(report: dict, base_path: str, tol: float,
             phase_budget: bool = False) -> int:
    """Per-collective-class delta vs a prior capture; non-zero on any
    regression beyond ``tol`` (relative count/bytes growth, with a small
    absolute slack so zero-byte classes don't trip on rounding).  With
    ``phase_budget``, the PHASE_BUDGET_PHASES rows of the per-phase table
    are ratcheted the same way — so e.g. a new exchange-leg all-gather
    fails even if a win elsewhere keeps the global class total flat."""
    with open(base_path) as f:
        base = json.load(f)
    rc = 0
    slack_bytes = 64 * 1024  # one stray [16, cap]-class buffer, not an [N]
    # pre-r8 captures carry no rng/exchange keys — every one of them was
    # the threefry + partitioner-roll program, so default the comparison
    # to that instead of silently skipping the program-identity check
    legacy_program = {"rng": "threefry", "exchange_lowering": "gspmd"}
    for key in ("rng", "exchange_lowering"):
        base_val = base.get(key, legacy_program[key])
        if base_val != report.get(key):
            print(f"compare: {key} mismatch vs {base_path}: "
                  f"{report.get(key)} baseline {base_val} — "
                  "the budgets describe different programs (re-capture the "
                  f"baseline, or pass --{key.split('_')[0]} {base_val})")
            return 3
    for prog in ("step", "detect"):
        cur, old = report.get(prog, {}), base.get(prog, {})
        for field in ("n", "k"):
            if cur.get(field) != old.get(field):
                print(f"compare: {prog} config mismatch vs {base_path}: "
                      f"{field}={cur.get(field)} baseline {old.get(field)} — "
                      "per-class deltas would be meaningless")
                return 3
        kinds = sorted(set(cur["by_kind"]) | set(old["by_kind"]))
        print(f"\n== {prog} delta vs {os.path.basename(base_path)} "
              f"(n={cur['n']}, k={cur['k']}; tolerance {tol:.0%}) ==")
        print(f"{'kind':>22} {'count':>11} {'MB/chip':>17}  verdict")
        for kind in kinds:
            c = cur["by_kind"].get(kind, {"count": 0, "bytes": 0})
            o = old["by_kind"].get(kind, {"count": 0, "bytes": 0})
            worse_count = c["count"] > o["count"] + max(2, tol * o["count"])
            worse_bytes = c["bytes"] > o["bytes"] * (1 + tol) + slack_bytes
            verdict = "REGRESSED" if (worse_count or worse_bytes) else "ok"
            if verdict == "REGRESSED":
                rc = 2
            print(f"{kind:>22} {o['count']:>5}->{c['count']:<5} "
                  f"{o['bytes'] / 1e6:>8.2f}->{c['bytes'] / 1e6:<8.2f} {verdict}")
        ct = sum(e["count"] for e in cur["by_kind"].values())
        cb = sum(e["bytes"] for e in cur["by_kind"].values())
        ot = sum(e["count"] for e in old["by_kind"].values())
        ob = sum(e["bytes"] for e in old["by_kind"].values())
        print(f"{'TOTAL':>22} {ot:>5}->{ct:<5} {ob / 1e6:>8.2f}->{cb / 1e6:<8.2f}")
        if ct == 0 and ot > 0:
            # an all-zero census against a collective-bearing baseline is
            # the parser/dump-format-drift failure mode (it bit the r6
            # 'before' capture), not a miracle optimization — refuse to
            # certify it as within budget
            print(f"compare: {prog} census parsed ZERO collectives against a "
                  f"{ot}-collective baseline — HLO dump format drift? fix "
                  "parse_collectives before trusting any budget result")
            return 3
        if phase_budget:
            cur_p = cur.get("by_phase") or {}
            old_p = old.get("by_phase")
            if old_p is None:
                print(f"compare: {prog} baseline {base_path} has no by_phase "
                      "table — re-capture it before using --phase-budget")
                return 3
            print(f"  phase budget ({', '.join(PHASE_BUDGET_PHASES)}):")
            for phase in PHASE_BUDGET_PHASES:
                kinds = sorted(set(cur_p.get(phase, {})) | set(old_p.get(phase, {})))
                for kind in kinds:
                    c = cur_p.get(phase, {}).get(kind, {"count": 0, "bytes": 0})
                    o = old_p.get(phase, {}).get(kind, {"count": 0, "bytes": 0})
                    worse = (c["count"] > o["count"] + max(2, tol * o["count"])
                             or c["bytes"] > o["bytes"] * (1 + tol) + slack_bytes)
                    if worse:
                        rc = 2
                    print(f"    {phase:>16} {kind:>20} "
                          f"{o['count']:>4}->{c['count']:<4} "
                          f"{o['bytes'] / 1e6:>8.2f}->{c['bytes'] / 1e6:<8.2f} "
                          f"{'REGRESSED' if worse else 'ok'}")
    print("\ncompare:", "REGRESSED beyond tolerance" if rc else "within budget")
    return rc


if __name__ == "__main__":
    main()
