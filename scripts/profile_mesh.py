"""Multi-chip collective cost model for the sharded lifecycle engine
(VERDICT r4 item 4): compile the sharded programs on the 8-virtual-device
CPU mesh, dump optimized HLO, and count + size every cross-device
collective — the evidence behind PERF.md's bytes-per-tick-per-chip table.

Two programs are profiled:

1. the one-tick 1M x 256 lifecycle step over the 4x2 ("node" x "rumor")
   mesh — the per-tick ICI traffic of the headline config;
2. the 100k sharded detect program (`_run_until_detected_device`) — to
   answer whether `detection_complete`'s K-iteration slot walk
   serializes under sharding (it holds a fori_loop whose body touches
   [N]-sharded planes one rumor column at a time).

Compile-only (`.lower(...).compile()`); nothing executes, so the run is
CPU-compile-bound (~minutes for the 1M program).  Collectives are read
from the after-optimizations HLO per computation, so while-loop bodies
(executed once per tick / per walk iteration) are reported separately
from one-shot entry computations.

Usage:
    python scripts/profile_mesh.py [--step-n N] [--detect-n N] [--out FILE]
                                   [--compare BASE.json] [--force-sparse]
                                   [--rng counter|threefry]
                                   [--exchange shardmap|gspmd]
                                   [--phase-budget]

``--compare BASE.json`` diffs this run against a prior capture (same n/k
config) and prints a per-collective-class delta table — count and
MB/chip/tick — exiting non-zero if any class regressed beyond the
tolerance, so the collective budget is a ratchet, not a trivia table.
``--phase-budget`` additionally ratchets the per-phase table for the
protocol phases named in ``PHASE_BUDGET_PHASES`` (the exchange and
peer-choice classes this round's work pinned), so a regression can't
hide inside an unchanged global total.
``--force-sparse`` drops the sparse candidate path's engagement floor so
a small --step-n profile exercises the same hierarchical-select code
path as the 1M headline (CI-speed budget checks).
``--rng``/``--exchange`` select the engine's PRNG family and roll-leg
lowering (defaults: the sharded-caller defaults, ``counter`` +
``shardmap``; the r8 'before' capture was taken with ``threefry`` +
``gspmd`` — the r6/r7 program — under the SAME parser).

Census semantics (r8): collectives inside sibling branches of one
``conditional`` (``lax.switch``/``lax.cond``) are mutually exclusive per
execution — the shift exchange's shard-local lowering switches over the
traced shard offset, and the sparse candidate select conds between the
hierarchical path and its full-sort fallback — so every summary charges
only the most expensive branch of each conditional (worst case actually
executable per tick), not the sum of all branches in the program text.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import shutil
import sys
import tempfile

COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
    "reduce-scatter",
)

# protocol-phase named scopes (jax.named_scope in sim/lifecycle.py and
# sim/packbits.py) — XLA carries them through to each instruction's
# metadata op_name, which is how a censused collective gets attributed to
# the protocol phase that emitted it.  Outermost-first: a collective under
# "rumor-exchange/row-reduce" belongs to the exchange phase.
PHASES = (
    "tick-prologue",
    "ping-target",
    "rumor-exchange",
    "heal",
    "piggyback-counters",
    "timers-fold",
    "peer-choice",
    "candidate-select",
    "alloc-seed",
    "commit",
    "telemetry",
    "detect-walk",
    "view-checksum",
    "row-reduce",
    "set-bit",
    "shard-roll",
)

# the phases --phase-budget ratchets (r8): the exchange legs must stay
# ppermute-only and the peer-choice draws collective-free — a regression
# in either can hide inside a roughly-unchanged global total, which is
# exactly what the per-phase ratchet exists to catch
PHASE_BUDGET_PHASES = ("rumor-exchange", "ping-target", "peer-choice", "shard-roll")

_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_SRC_RE = re.compile(r'source_file="([^"]+)" source_line=(\d+)')
_PHASE_SPAN_CACHE: dict = {}


def _source_spans(path: str):
    """(named-scope spans, function starts) of one source file — the
    fallback attributor for collectives whose op_name lost its scope (the
    SPMD partitioner re-homes resharding ops onto loop boundaries, whose
    metadata names only the enclosing while)."""
    if path not in _PHASE_SPAN_CACHE:
        spans, funcs = [], []
        try:
            src = open(path).read().split("\n")
        except OSError:
            src = []
        for i, ln in enumerate(src):
            m = re.match(r'(\s*)with jax\.named_scope\("([^"]+)"\):', ln)
            if m:
                indent = len(m.group(1))
                j = i + 1
                while j < len(src) and (
                    not src[j].strip()
                    or len(src[j]) - len(src[j].lstrip()) > indent
                ):
                    j += 1
                spans.append((i + 1, j, m.group(2)))
            d = re.match(r"def (\w+)\(", ln)
            if d:
                funcs.append((i + 1, d.group(1)))
        _PHASE_SPAN_CACHE[path] = (spans, funcs)
    return _PHASE_SPAN_CACHE[path]


def _phase_of(line: str) -> str:
    """Protocol phase of one HLO instruction line: the named-scope path
    XLA keeps in metadata op_name when present (fusions inherit a
    representative instruction's metadata), else the scope lexically
    enclosing the op's source line, else ``loop:<function>`` for ops the
    partitioner re-homed onto a loop boundary (e.g. the detect walk's
    learned-plane replication hoisted to the tick loop)."""
    m = _OPNAME_RE.search(line)
    if m:
        for part in m.group(1).split("/"):
            if part in PHASES:
                return part
    s = _SRC_RE.search(line)
    if s:
        spans, funcs = _source_spans(s.group(1))
        ln = int(s.group(2))
        for a, b, name in spans:
            if a <= ln <= b:
                return name
        owner = None
        for a, name in funcs:
            if a <= ln:
                owner = name
            else:
                break
        if owner:
            return f"loop:{owner}"
    return "(unattributed)"

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every array in an HLO result type string (handles
    tuples; layout annotations ignored)."""
    total = 0
    for dtype, dims in re.findall(r"(pred|[suf]\d+|bf16)\[([\d,]*)\]", shape_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def parse_collectives(hlo_path: str) -> dict:
    """Per-computation collective census of one optimized HLO module.

    Returns {computation_name: [{op, kind, bytes}...]} plus, for loop
    attribution, each computation's while-loop depth (a collective inside
    a while BODY executes once per iteration, so depth distinguishes the
    one-shot entry collectives from the per-tick / per-walk-step ones),
    the ``conditional`` branch groups (lists of sibling branch
    computations, of which exactly ONE executes per evaluation), and the
    ``executed`` computation set: everything reachable from the module
    roots taking only the most expensive branch of each conditional —
    the worst case one execution can actually pay.  Summaries charge the
    executed set only; ``by_computation`` keeps the full text census."""
    comps: dict = {}
    bodies: dict = {}  # while-body computation -> owning computation
    calls: dict = {}  # computation -> calling computations (reverse edges)
    fwd: dict = {}  # computation -> called computations (forward edges)
    cond_groups: list = []  # [{caller, branches: [comp, ...]}, ...]
    cur = None
    # instruction/computation names carry a "%" sigil in older XLA text
    # dumps and none in current ones — accept both, or a format rotation
    # silently reports an empty census (bit us once: the r6 'before'
    # capture came out all-zero against a 297-collective program)
    for line in open(hlo_path):
        stripped = line.rstrip()
        if stripped.endswith("{") and not line.lstrip().startswith("ROOT"):
            cur = stripped.split()[0].lstrip("%")
            comps.setdefault(cur, [])
        elif cur is not None and line.strip() == "}":
            cur = None
        elif cur is not None:
            m = re.search(
                r"%?([\w.\-]+) = (.+?) (" + "|".join(COLLECTIVES) + r")(?:-start)?\(",
                line,
            )
            if m and "-done" not in line.split("=", 1)[1][:60]:
                comps[cur].append(
                    {
                        "op": m.group(1),
                        "kind": m.group(3),
                        "bytes": _shape_bytes(m.group(2)),
                        "phase": _phase_of(line),
                    }
                )
            b = re.search(r"body=%?([\w.\-]+)", line)
            if b:
                bodies[b.group(1)] = cur
            # conditional branches: N-ary (lax.switch) and binary forms
            branches = []
            bm = re.search(r"branch_computations=\{([^}]*)\}", line)
            if bm:
                branches = [c.strip().lstrip("%") for c in bm.group(1).split(",") if c.strip()]
            else:
                tm = re.search(r"true_computation=%?([\w.\-]+)", line)
                fm = re.search(r"false_computation=%?([\w.\-]+)", line)
                if tm and fm:
                    branches = [tm.group(1), fm.group(1)]
            if branches:
                cond_groups.append({"caller": cur, "branches": branches})
            for callee in re.findall(
                r"(?:calls|to_apply|condition|body|true_computation|"
                r"false_computation)=%?([\w.\-]+)",
                line,
            ) + branches:
                calls.setdefault(callee, set()).add(cur)
                fwd.setdefault(cur, set()).add(callee)

    def loop_depth(name: str, seen=()) -> int:
        if name in seen:
            return 0
        best = 0
        if name in bodies:
            best = 1 + loop_depth(bodies[name], seen + (name,))
        for owner in calls.get(name, ()):
            best = max(best, loop_depth(owner, seen + (name,)))
        return best

    # -- worst-case-executed computation set: at every conditional take the
    # branch whose subtree carries the most collective bytes (count as
    # tie-break); sibling branches are mutually exclusive per execution
    branch_edges = {
        (g["caller"], b) for g in cond_groups for b in g["branches"]
    }
    groups_of = {}
    for g in cond_groups:
        groups_of.setdefault(g["caller"], []).append(g["branches"])

    def subtree_cost(name, seen=()):
        if name in seen:
            return (0, 0)
        seen = seen + (name,)
        by, ct = 0, 0
        for r in comps.get(name, ()):
            by += r["bytes"]
            ct += 1
        for branches in groups_of.get(name, []):
            bb, bc = max((subtree_cost(b, seen) for b in branches), default=(0, 0))
            by += bb
            ct += bc
        for callee in fwd.get(name, ()):
            if (name, callee) in branch_edges:
                continue
            cb, cc = subtree_cost(callee, seen)
            by += cb
            ct += cc
        return (by, ct)

    executed: set = set()

    def walk(name):
        if name in executed:
            return
        executed.add(name)
        for branches in groups_of.get(name, []):
            walk(max(branches, key=lambda b: subtree_cost(b)))
        for callee in fwd.get(name, ()):
            if (name, callee) not in branch_edges:
                walk(callee)

    all_names = set(comps) | set(fwd) | {c for cs in fwd.values() for c in cs}
    roots = all_names - {c for cs in fwd.values() for c in cs}
    for r in sorted(roots):
        walk(r)
    if not roots:  # degenerate single-computation module
        executed = all_names

    return {
        "computations": {k: v for k, v in comps.items() if v},
        "loop_depth": {k: loop_depth(k) for k, v in comps.items() if v},
        "cond_groups": cond_groups,
        "executed": sorted(executed),
    }


def _newest_module(dump: str, marker: str) -> str | None:
    mods = [
        p
        for p in glob.glob(os.path.join(dump, "*after_optimizations.txt"))
        if marker in os.path.basename(p) and "buffer" not in p and "memory" not in p
    ]
    return max(mods, key=os.path.getsize) if mods else None


def executed_rows(census: dict):
    """Iterate (computation, row) over the worst-case EXECUTED collective
    set: sibling conditional branches contribute only their most expensive
    member (see parse_collectives) — the census tests and both summaries
    share this one definition of "per-tick cost"."""
    executed = set(census.get("executed") or census["computations"])
    for comp, rows in census["computations"].items():
        if comp in executed:
            for r in rows:
                yield comp, r


def _summarize(census: dict) -> dict:
    by_kind: dict = {}
    for _, r in executed_rows(census):
        e = by_kind.setdefault(r["kind"], {"count": 0, "bytes": 0})
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return by_kind


def _summarize_phases(census: dict) -> dict:
    """{phase: {kind: {count, bytes}}} — the protocol-phase attribution of
    the collective census (the table PERF.md's budget discussion reads)."""
    by_phase: dict = {}
    for _, r in executed_rows(census):
        kinds = by_phase.setdefault(r.get("phase", "(unattributed)"), {})
        e = kinds.setdefault(r["kind"], {"count": 0, "bytes": 0})
        e["count"] += 1
        e["bytes"] += r["bytes"]
    return by_phase


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--step-n", type=int, default=1_000_000)
    ap.add_argument("--step-k", type=int, default=256)
    ap.add_argument("--detect-n", type=int, default=100_000)
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--compare", metavar="BASE.json", default=None,
        help="diff this run against a prior capture of the SAME config and "
        "exit non-zero if any collective class regressed beyond --tolerance",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.05,
        help="allowed relative growth per collective class before the "
        "compare fails (default 0.05 = 5%%)",
    )
    ap.add_argument(
        "--force-sparse", action="store_true",
        help="drop the sparse candidate path's n floor so small --step-n "
        "profiles exercise the hierarchical select like the 1M step does",
    )
    ap.add_argument(
        "--rng", choices=("counter", "threefry"), default="counter",
        help="engine PRNG family (default: the sharded-caller default, "
        "'counter' — partition-invariant, zero peer-choice collectives); "
        "'threefry' reproduces the r6/r7 program",
    )
    ap.add_argument(
        "--exchange", choices=("shardmap", "gspmd"), default="shardmap",
        help="shift-exchange roll-leg lowering: 'shardmap' = the shard-local "
        "crossing-block ppermutes (default), 'gspmd' = the r6/r7 "
        "partitioner-inferred all-gathers",
    )
    ap.add_argument(
        "--phase-budget", action="store_true",
        help="with --compare: also ratchet the per-phase table for "
        f"{PHASE_BUDGET_PHASES} (fails on per-phase regressions that an "
        "unchanged global total would hide)",
    )
    args = ap.parse_args()

    dump = tempfile.mkdtemp(prefix="meshhlo_")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count=8"
        + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        sys.exit(_run(args, dump))
    finally:
        shutil.rmtree(dump, ignore_errors=True)


def _run(args, dump: str) -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import functools
    import time

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    if args.force_sparse:
        lifecycle._SPARSE_TOPK_MIN_N = 0

    devs = np.asarray(jax.devices("cpu")[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("node", "rumor"))
    report: dict = {
        "mesh": "4x2 (node x rumor), virtual CPU devices",
        "rng": args.rng,
        "exchange_lowering": args.exchange,
    }
    engine_kw = dict(rng=args.rng)
    if args.exchange == "shardmap":
        engine_kw["exchange_mesh"] = mesh

    # -- 1) one-tick step at headline scale --------------------------------
    n, k = args.step_n, args.step_k
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, **engine_kw)
    up = np.ones(n, bool)
    up[:: max(n // 1000, 1)] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    state = jax.tree.map(
        jax.device_put, lifecycle.init_state(params, seed=0),
        lifecycle.state_shardings(mesh, k=k),
    )
    blk = jax.jit(functools.partial(lifecycle._run_block, params),
                  static_argnames="ticks")
    t0 = time.perf_counter()
    blk.lower(state, faults, ticks=1).compile()
    step_compile_s = time.perf_counter() - t0
    mod = _newest_module(dump, "_run_block")
    if mod is None:
        mod = _newest_module(dump, "")
    census = parse_collectives(mod) if mod else {"computations": {}, "loop_depth": {}}
    report["step"] = {
        "n": n, "k": k, "compile_s": round(step_compile_s, 1),
        "module": os.path.basename(mod) if mod else None,
        "by_kind": _summarize(census),
        "by_phase": _summarize_phases(census),
        "by_computation": {
            c: {
                "count": len(rows),
                "bytes": sum(r["bytes"] for r in rows),
                "loop_depth": census["loop_depth"].get(c, 0),
            }
            for c, rows in census["computations"].items()
        },
    }

    # -- 2) the sharded detect program (serialization question) ------------
    for f in glob.glob(os.path.join(dump, "*")):
        shutil.rmtree(f) if os.path.isdir(f) else os.remove(f)
    nd = args.detect_n
    dparams = lifecycle.LifecycleParams(n=nd, k=256, suspect_ticks=10, **engine_kw)
    dup = np.ones(nd, bool)
    dup[:: max(nd // 100, 1)] = False
    dfaults = DeltaFaults(up=jnp.asarray(dup))
    dstate = jax.tree.map(
        jax.device_put, lifecycle.init_state(dparams, seed=0),
        lifecycle.state_shardings(mesh, k=256),
    )
    subjects = jnp.asarray(np.flatnonzero(~dup), jnp.int32)
    # the rumor-axis replication hint for the per-check slot walk — the
    # same static arg the sharded bench paths pass (older engine
    # revisions don't take it; fall back so --compare can profile them)
    detect_kw = dict(
        min_status=lifecycle.FAULTY, block_ticks=32, max_blocks=jnp.int32(16)
    )
    t0 = time.perf_counter()
    try:
        lifecycle._run_until_detected_device.lower(
            dparams, dstate, dfaults, subjects,
            learned_sharding=NamedSharding(mesh, P("node", None)), **detect_kw,
        ).compile()
    except TypeError:
        lifecycle._run_until_detected_device.lower(
            dparams, dstate, dfaults, subjects, **detect_kw
        ).compile()
    detect_compile_s = time.perf_counter() - t0
    mod = _newest_module(dump, "")
    census = parse_collectives(mod) if mod else {"computations": {}, "loop_depth": {}}
    report["detect"] = {
        "n": nd, "k": 256, "compile_s": round(detect_compile_s, 1),
        "module": os.path.basename(mod) if mod else None,
        "by_kind": _summarize(census),
        "by_phase": _summarize_phases(census),
        "by_computation": {
            c: {
                "count": len(rows),
                "bytes": sum(r["bytes"] for r in rows),
                "loop_depth": census["loop_depth"].get(c, 0),
            }
            for c, rows in census["computations"].items()
        },
    }

    for name in ("step", "detect"):
        sec = report[name]
        print(f"\n== {name} (n={sec['n']}, k={sec['k']}, "
              f"compile {sec['compile_s']}s) ==")
        print(f"{'kind':>22} {'count':>6} {'MB total':>10}")
        for kind, e in sorted(sec["by_kind"].items()):
            print(f"{kind:>22} {e['count']:>6} {e['bytes'] / 1e6:>10.2f}")
        print("  by protocol phase (named-scope attribution):")
        for phase, kinds in sorted(sec["by_phase"].items()):
            for kind, e in sorted(kinds.items()):
                print(f"    {phase:>20} {kind:>22} {e['count']:>4} "
                      f"{e['bytes'] / 1e6:>8.2f} MB")
        unattr = sec["by_phase"].get("(unattributed)")
        if unattr:
            print("    WARNING: %d collectives carry no phase scope — extend "
                  "the named_scope coverage in sim/lifecycle.py"
                  % sum(e["count"] for e in unattr.values()))
        print("  per computation (collective-bearing only; depth = enclosing "
              "while-loop nesting):")
        for c, e in sorted(sec["by_computation"].items(),
                           key=lambda kv: -kv[1]["bytes"])[:12]:
            print(f"    d{e['loop_depth']} {c[:54]:54s} {e['count']:>4}  "
                  f"{e['bytes'] / 1e6:>8.2f} MB")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(f"\nwrote {args.out}")
    print(json.dumps({"profile_mesh": {k2: report[k2]["by_kind"]
                                       for k2 in ("step", "detect")}}))
    if args.compare:
        return _compare(report, args.compare, args.tolerance,
                        phase_budget=args.phase_budget)
    return 0


def _compare(report: dict, base_path: str, tol: float,
             phase_budget: bool = False) -> int:
    """Per-collective-class delta vs a prior capture; non-zero on any
    regression beyond ``tol`` (relative count/bytes growth, with a small
    absolute slack so zero-byte classes don't trip on rounding).  With
    ``phase_budget``, the PHASE_BUDGET_PHASES rows of the per-phase table
    are ratcheted the same way — so e.g. a new exchange-leg all-gather
    fails even if a win elsewhere keeps the global class total flat."""
    with open(base_path) as f:
        base = json.load(f)
    rc = 0
    slack_bytes = 64 * 1024  # one stray [16, cap]-class buffer, not an [N]
    # pre-r8 captures carry no rng/exchange keys — every one of them was
    # the threefry + partitioner-roll program, so default the comparison
    # to that instead of silently skipping the program-identity check
    legacy_program = {"rng": "threefry", "exchange_lowering": "gspmd"}
    for key in ("rng", "exchange_lowering"):
        base_val = base.get(key, legacy_program[key])
        if base_val != report.get(key):
            print(f"compare: {key} mismatch vs {base_path}: "
                  f"{report.get(key)} baseline {base_val} — "
                  "the budgets describe different programs (re-capture the "
                  f"baseline, or pass --{key.split('_')[0]} {base_val})")
            return 3
    for prog in ("step", "detect"):
        cur, old = report.get(prog, {}), base.get(prog, {})
        for field in ("n", "k"):
            if cur.get(field) != old.get(field):
                print(f"compare: {prog} config mismatch vs {base_path}: "
                      f"{field}={cur.get(field)} baseline {old.get(field)} — "
                      "per-class deltas would be meaningless")
                return 3
        kinds = sorted(set(cur["by_kind"]) | set(old["by_kind"]))
        print(f"\n== {prog} delta vs {os.path.basename(base_path)} "
              f"(n={cur['n']}, k={cur['k']}; tolerance {tol:.0%}) ==")
        print(f"{'kind':>22} {'count':>11} {'MB/chip':>17}  verdict")
        for kind in kinds:
            c = cur["by_kind"].get(kind, {"count": 0, "bytes": 0})
            o = old["by_kind"].get(kind, {"count": 0, "bytes": 0})
            worse_count = c["count"] > o["count"] + max(2, tol * o["count"])
            worse_bytes = c["bytes"] > o["bytes"] * (1 + tol) + slack_bytes
            verdict = "REGRESSED" if (worse_count or worse_bytes) else "ok"
            if verdict == "REGRESSED":
                rc = 2
            print(f"{kind:>22} {o['count']:>5}->{c['count']:<5} "
                  f"{o['bytes'] / 1e6:>8.2f}->{c['bytes'] / 1e6:<8.2f} {verdict}")
        ct = sum(e["count"] for e in cur["by_kind"].values())
        cb = sum(e["bytes"] for e in cur["by_kind"].values())
        ot = sum(e["count"] for e in old["by_kind"].values())
        ob = sum(e["bytes"] for e in old["by_kind"].values())
        print(f"{'TOTAL':>22} {ot:>5}->{ct:<5} {ob / 1e6:>8.2f}->{cb / 1e6:<8.2f}")
        if ct == 0 and ot > 0:
            # an all-zero census against a collective-bearing baseline is
            # the parser/dump-format-drift failure mode (it bit the r6
            # 'before' capture), not a miracle optimization — refuse to
            # certify it as within budget
            print(f"compare: {prog} census parsed ZERO collectives against a "
                  f"{ot}-collective baseline — HLO dump format drift? fix "
                  "parse_collectives before trusting any budget result")
            return 3
        if phase_budget:
            cur_p = cur.get("by_phase") or {}
            old_p = old.get("by_phase")
            if old_p is None:
                print(f"compare: {prog} baseline {base_path} has no by_phase "
                      "table — re-capture it before using --phase-budget")
                return 3
            print(f"  phase budget ({', '.join(PHASE_BUDGET_PHASES)}):")
            for phase in PHASE_BUDGET_PHASES:
                kinds = sorted(set(cur_p.get(phase, {})) | set(old_p.get(phase, {})))
                for kind in kinds:
                    c = cur_p.get(phase, {}).get(kind, {"count": 0, "bytes": 0})
                    o = old_p.get(phase, {}).get(kind, {"count": 0, "bytes": 0})
                    worse = (c["count"] > o["count"] + max(2, tol * o["count"])
                             or c["bytes"] > o["bytes"] * (1 + tol) + slack_bytes)
                    if worse:
                        rc = 2
                    print(f"    {phase:>16} {kind:>20} "
                          f"{o['count']:>4}->{c['count']:<4} "
                          f"{o['bytes'] / 1e6:>8.2f}->{c['bytes'] / 1e6:<8.2f} "
                          f"{'REGRESSED' if worse else 'ok'}")
    print("\ncompare:", "REGRESSED beyond tolerance" if rc else "within budget")
    return rc


if __name__ == "__main__":
    main()
