"""Profile the lifecycle tick on this host: per-tick wall cost plus a
ranking of the optimized-HLO fusions by (elements x body-ops).

This is the committed form of the methodology that found the round-4
wins (PERF.md "Round 3"/"Round 4"): ``--xla_hlo_profile`` crashes on the
step program (XLA-internal check failure), and trace tooling is heavier
than needed — dumping the optimized HLO and ranking loop fusions by
output-element count times fusion-body size localizes the expensive
passes well enough to act on (it is how the heal-DUS full-plane copies
and the 1M candidate sort were found).

Usage:
    python scripts/profile_tick.py [n] [k] [ticks]      # defaults 1M 256 8

Prints per-tick wall cost, then the top fusions/ops of the step module.
CPU-pinned by default (PROFILE_PIN=axon to aim at the tunnel instead —
but profile on-chip via scripts/tpu_ksweep.py, which the watcher runs).
"""

from __future__ import annotations

import glob
import os
import re
import shutil
import sys
import tempfile
import time


def rank_fusions(hlo_path: str, top: int = 15) -> list[tuple]:
    lines = open(hlo_path).read().splitlines()
    comps: dict[str, int] = {}
    cur = None
    for line in lines:
        if line.rstrip().endswith("{") and not line.lstrip().startswith("ROOT"):
            cur = line.split()[0].lstrip("%")
            comps[cur] = 0
        elif cur is not None:
            if line.strip() == "}":
                cur = None
            elif "=" in line:
                comps[cur] += 1
    rows = []
    for line in lines:
        m = re.search(
            r"%([\w.\-]+) = (.+?) (fusion|sort|scatter|while|reduce-window)\(", line
        )
        if not m:
            continue
        c = re.search(r"calls=%([\w.\-]+)", line)
        body = comps.get(c.group(1), 0) if c else 0
        elems = 0
        for dims in re.findall(r"(?:f|s|u|pred)(?:\d+)?\[([\d,]+)\]", m.group(2)):
            n = 1
            for d in dims.split(","):
                n *= int(d)
            elems = max(elems, n)
        rows.append((elems * max(body, 1), elems, body, m.group(3), m.group(1)))
    rows.sort(reverse=True)
    return rows[:top]


def main() -> None:
    # XLA reads XLA_FLAGS once, when the backend client is created — the
    # dump flags must be in the environment BEFORE jax is imported, or a
    # pre-initialized backend (e.g. the axon site hook importing jax at
    # interpreter start) silently ignores them and no HLO is dumped.
    dump = tempfile.mkdtemp(prefix="tickhlo_")
    try:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_dump_to={dump} --xla_dump_hlo_as_text"
        ).strip()

        import jax

        try:
            jax.config.update("jax_platforms", os.environ.get("PROFILE_PIN", "cpu"))
        except RuntimeError:
            pass  # backend already initialized (e.g. by the axon site hook)
        import jax.numpy as jnp
        import numpy as np

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from ringpop_tpu.sim import lifecycle
        from ringpop_tpu.sim.delta import DeltaFaults

        n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
        k = int(sys.argv[2]) if len(sys.argv) > 2 else 256
        ticks = int(sys.argv[3]) if len(sys.argv) > 3 else 8

        _profile(jax, jnp, np, lifecycle, DeltaFaults, n, k, ticks, dump)
    finally:
        shutil.rmtree(dump, ignore_errors=True)


def _profile(jax, jnp, np, lifecycle, DeltaFaults, n, k, ticks, dump):
    params = lifecycle.LifecycleParams(n=n, k=k)
    state = lifecycle.init_state(params, seed=0)
    rng = np.random.default_rng(0)
    victims = np.sort(rng.choice(n, size=max(1, n // 1000), replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up))

    step = jax.jit(lambda s: lifecycle.step(params, s, faults))
    t0 = time.perf_counter()
    state = jax.block_until_ready(step(state))
    print(f"compile+first tick: {time.perf_counter() - t0:.2f}s")
    t0 = time.perf_counter()
    for _ in range(ticks):
        state = step(state)
    jax.block_until_ready(state.learned)
    dt = time.perf_counter() - t0
    print(f"{ticks} ticks in {dt:.2f}s -> {dt / ticks * 1000:.0f} ms/tick (n={n}, k={k})")

    mods = [
        p
        for p in glob.glob(os.path.join(dump, "*lambda*after_optimizations.txt"))
        if "buffer" not in p and "memory" not in p
    ]
    if mods:
        biggest = max(mods, key=os.path.getsize)
        print(f"\ntop fusions of {os.path.basename(biggest)}")
        print(f"{'cost~':>12} {'Melem':>8} {'body':>5}  kind      name")
        for cost, elems, body, kind, name in rank_fusions(biggest):
            print(f"{cost / 1e6:12.1f} {elems / 1e6:8.1f} {body:5d}  {kind:8s}  {name[:40]}")
    else:
        print(
            "no step-module HLO dump found (jit cache hit, or the backend was "
            "initialized before this script set the dump flags — e.g. a site "
            "hook importing jax at interpreter start; rerun in a fresh process)"
        )


if __name__ == "__main__":
    main()
