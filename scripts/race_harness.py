"""race_harness — drive the smokes under racecheck's hostile scheduler
(``make race-smoke``; the dynamic half of analysis plane 3, ANALYSIS.md).

The reference repo runs its whole suite under Go's race detector
(``make test-race``); this is that gate for the rebuild's host layer.
Each leg launches a smoke in a subprocess with
``ringpop_tpu.analysis.racecheck`` installed — every ``threading.Lock``
/ ``RLock`` / ``Condition`` allocated by the smoke is instrumented, a
seeded perturbation stream injects sub-millisecond preemptions at lock
acquisition and wait points, and the process dumps its dynamic
lock-order graph + held-while-blocking events on exit.  A leg fails if
the smoke itself fails under the adversarial schedule OR its dynamic
lock graph contains a cycle (a realizable deadlock order).

Legs (default):
  * transport_smoke under EVERY seed (the concurrency-heavy surface:
    persistent links, inline completion, coalescing, shm lane)
  * serve / dcn / gameday smokes one seed each, round-robin
    (dcn/gameday child OS processes run uninstrumented — the harness
    covers the parent; cross-process order is the smokes' own job)
  * the **non-vacuity pair**: an in-process TCPChannel echo probe whose
    client reads the server's ``wire_stats()`` immediately after each
    reply and asserts ``frames_sent >= replies_observed`` — the exact
    invariant the r22 count-after-respond flake broke.  Run once clean
    (must hold) and once with the r22 mutant deliberately reintroduced
    (``_respond`` flipped to write-then-count): the perturbed schedule
    MUST catch it, proving the harness can see the bug class it exists
    for.  A harness that can't catch its own seeded bug is vacuous.

Usage:
    python scripts/race_harness.py                    # full gate
    python scripts/race_harness.py --seeds 7,8,9
    python scripts/race_harness.py --smokes transport --skip-mutant
    python scripts/race_harness.py --report /tmp/race.json

Exit codes: 0 green; 1 a smoke failed or a dynamic cycle was found;
3 the seeded mutant was NOT caught (vacuity); 4 the clean probe
violated (a real count-after-respond regression at HEAD).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SMOKES = {
    "transport": "scripts/transport_smoke.py",
    "serve": "scripts/serve_smoke.py",
    "dcn": "scripts/dcn_smoke.py",
    "gameday": "scripts/gameday_smoke.py",
}
LEG_TIMEOUT_S = 600

_BOOT = (
    "import sys, runpy;"
    "sys.path.insert(0, {repo!r});"
    "from ringpop_tpu.analysis import racecheck;"
    "racecheck.install(seed={seed}, perturb=True, p={p}, "
    "sleep_range_us=(200, 1500));"
    "runpy.run_path({script!r}, run_name='__main__')"
)


def _env() -> dict:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return env


def _run_smoke_leg(name: str, seed: int, p: float) -> dict:
    """One smoke under one perturbation seed; returns the leg record."""
    script = os.path.join(_REPO, SMOKES[name])
    fd, report_path = tempfile.mkstemp(prefix=f"race_{name}_", suffix=".json")
    os.close(fd)
    env = _env()
    env["RINGPOP_RACE_REPORT"] = report_path
    boot = _BOOT.format(repo=_REPO, seed=seed, p=p, script=script)
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-c", boot], env=env, cwd=_REPO,
        capture_output=True, text=True, timeout=LEG_TIMEOUT_S,
    )
    leg = {
        "leg": name, "seed": seed, "rc": proc.returncode,
        "wall_s": round(time.time() - t0, 2),
        "cycles": [], "edges": 0, "block_events": 0, "perturb_count": 0,
    }
    try:
        with open(report_path) as fh:
            rep = json.load(fh)
        leg["cycles"] = rep.get("cycles", [])
        leg["edges"] = len(rep.get("edges", []))
        leg["block_events"] = len(rep.get("block_events", []))
        leg["perturb_count"] = rep.get("perturb_count", 0)
        leg["acquire_count"] = rep.get("acquire_count", 0)
    except (OSError, ValueError):
        leg["report_missing"] = True
    finally:
        try:
            os.unlink(report_path)
        except OSError:
            pass
    if proc.returncode != 0:
        leg["tail"] = (proc.stdout + proc.stderr)[-2000:]
    return leg


# -- the count-after-respond probe (non-vacuity pair) -------------------------


def _probe(mutant: bool, seed: int, calls: int = 150) -> int:
    """Echo-RPC loop over a real TCPChannel pair; after every reply the
    client immediately reads the SERVER's legacy counters and checks the
    r22 invariant: a reply on the wire implies its frame was already
    counted (``frames_sent >= replies_observed``).  With ``mutant``,
    ``_respond`` is flipped back to the r22 write-then-count ordering —
    under perturbation (a seeded sleep lands between the socket write
    and the count's lock acquisition) the stale read becomes near-
    certain within a few dozen calls."""
    from ringpop_tpu.analysis import racecheck

    racecheck.install(
        seed=seed, perturb=True, p=0.35, sleep_range_us=(500, 3000))
    from ringpop_tpu.net.channel import TCPChannel

    if mutant:
        def buggy_respond(self, link, rid, res):
            # the r22 bug, verbatim ordering: socket write first, count
            # after — wire_stats() readers woken by the reply race it
            payload = self._encode(res)
            link.respond(rid, payload)
            self._count_sent(len(payload))
        TCPChannel._respond = buggy_respond

    server = TCPChannel(app="race-probe", codec="msgpack")
    server.register("probe", "/echo", lambda body, headers: body)
    addr = server.listen_sync("127.0.0.1", 0)
    client = TCPChannel(app="race-probe-cli", codec="msgpack")
    violations = 0
    replies = 0
    try:
        for i in range(calls):
            client.call_sync(addr, "probe", "/echo", {"i": i}, timeout=10)
            replies += 1
            if server.wire_stats()["frames_sent"] < replies:
                violations += 1
    finally:
        client.close_sync()
        server.close_sync()
    out = {
        "probe": "mutant" if mutant else "clean",
        "seed": seed, "calls": replies, "violations": violations,
    }
    print(json.dumps(out))
    if mutant:
        # caught == good: exit 0 when the harness SAW the seeded bug
        return 0 if violations > 0 else 3
    return 0 if violations == 0 else 4


def _run_probe_leg(mutant: bool, seed: int) -> dict:
    mode = "mutant" if mutant else "clean"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--probe", mode,
         "--seeds", str(seed)],
        env=_env(), cwd=_REPO, capture_output=True, text=True,
        timeout=LEG_TIMEOUT_S,
    )
    leg = {"leg": f"probe-{mode}", "seed": seed, "rc": proc.returncode}
    for line in proc.stdout.splitlines():
        try:
            leg.update(json.loads(line))
            break
        except ValueError:
            continue
    if proc.returncode != 0:
        leg["tail"] = (proc.stdout + proc.stderr)[-2000:]
    return leg


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seeds", default="1,2,3",
                    help="comma-separated perturbation seeds (default 1,2,3)")
    ap.add_argument("--smokes", default="transport,serve,dcn,gameday",
                    help="comma-separated smoke legs (subset of %s)"
                    % ",".join(SMOKES))
    ap.add_argument("--p", type=float, default=0.03,
                    help="perturbation probability per instrumentation point")
    ap.add_argument("--skip-mutant", action="store_true",
                    help="skip the non-vacuity probe pair")
    ap.add_argument("--report", default=None,
                    help="write the aggregate leg report as JSON here")
    ap.add_argument("--probe", choices=("clean", "mutant"), default=None,
                    help=argparse.SUPPRESS)  # internal: probe child mode
    args = ap.parse_args()
    seeds = [int(s) for s in args.seeds.split(",") if s]

    if args.probe is not None:
        return _probe(args.probe == "mutant", seeds[0])

    smokes = [s for s in args.smokes.split(",") if s]
    unknown = [s for s in smokes if s not in SMOKES]
    if unknown:
        print(f"race-harness: unknown smoke leg(s) {unknown}", file=sys.stderr)
        return 2

    legs = []
    # transport rides every seed; the jax-heavy smokes rotate one each
    plan: list[tuple[str, int]] = []
    if "transport" in smokes:
        plan += [("transport", s) for s in seeds]
    others = [s for s in smokes if s != "transport"]
    for i, name in enumerate(others):
        plan.append((name, seeds[i % len(seeds)]))

    failed = False
    for name, seed in plan:
        leg = _run_smoke_leg(name, seed, args.p)
        legs.append(leg)
        ok = leg["rc"] == 0 and not leg["cycles"]
        failed |= not ok
        print(
            f"race-harness: {name} seed={seed} "
            f"{'OK' if ok else 'FAIL'} rc={leg['rc']} "
            f"edges={leg['edges']} cycles={len(leg['cycles'])} "
            f"blocked={leg['block_events']} "
            f"perturbs={leg['perturb_count']} ({leg['wall_s']}s)"
        )
        for cyc in leg["cycles"]:
            print(f"race-harness:   DYNAMIC LOCK CYCLE: {' -> '.join(cyc)}")
        if leg["rc"] != 0 and "tail" in leg:
            print(leg["tail"], file=sys.stderr)

    vacuous = clean_broken = False
    if not args.skip_mutant:
        clean = _run_probe_leg(mutant=False, seed=seeds[0])
        mut = _run_probe_leg(mutant=True, seed=seeds[0])
        legs += [clean, mut]
        clean_broken = clean["rc"] != 0
        vacuous = mut["rc"] != 0
        print(
            f"race-harness: probe-clean seed={seeds[0]} "
            f"{'OK' if not clean_broken else 'FAIL'} "
            f"violations={clean.get('violations')}"
        )
        print(
            f"race-harness: probe-mutant seed={seeds[0]} "
            f"{'CAUGHT' if not vacuous else 'MISSED (vacuous!)'} "
            f"violations={mut.get('violations')}"
        )

    if args.report:
        with open(args.report, "w") as fh:
            json.dump({"seeds": seeds, "p": args.p, "legs": legs},
                      fh, indent=1, sort_keys=True)
            fh.write("\n")

    if clean_broken:
        print("race-harness: FAIL — clean probe violated the count-before-"
              "respond invariant at HEAD", file=sys.stderr)
        return 4
    if vacuous:
        print("race-harness: FAIL — seeded count-after-respond mutant was "
              "NOT caught; the harness is vacuous", file=sys.stderr)
        return 3
    if failed:
        return 1
    tail = ("mutant probe skipped" if args.skip_mutant
            else "seeded r22 mutant caught")
    print(f"race-harness OK: {len(legs)} legs green under seeds {seeds}; "
          f"no dynamic lock cycles; {tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
