"""serve-fanin-smoke: the CI gate for the production-fan-in serve plane.

Three correctness legs, no throughput asserts (2-core container):

1. **forward-then-answer round trip** — a BlockRouter holding the wrong
   ring block coalesces mis-routed keys into per-owner batches, the
   owning side answers through the fused LookupN dispatch, and every
   returned (owner, successors) tuple must equal the host
   ``LookupNUniqueAt`` walk; RPC count must be O(owners), not O(keys).
2. **quorum read under an owner-killing FaultPlan** — staggered crashes
   with restarts: every wave must still ack at ⌈(R+1)/2⌉, answers must
   agree, and ``chaos.score_blocks`` must see full-replication recovery
   after every crash.
3. **P=2 serve mesh** — every rank's combined (owner, successors,
   generation) stream digest must equal the single-process oracle's,
   with per-host wire bytes recorded and messages strictly below the
   one-per-forwarded-key naive plane.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np

    failures = []

    # -- leg 1: forward-then-answer round trip -------------------------------
    from ringpop_tpu.forward.batch import (
        BatchForwarder,
        BlockRouter,
        rank_of_hashes,
    )
    from ringpop_tpu.net.channel import (
        LocalChannel,
        LocalNetwork,
        decode_array,
        encode_array,
    )
    from ringpop_tpu.ops.ring_ops import build_ring_tokens, host_lookup_n
    from ringpop_tpu.serve.state import device_ring, serve_lookup_n_fused

    n_servers, rp, n = 8, 20, 3
    servers = [f"10.31.0.{i}:3000" for i in range(n_servers)]
    toks, owns = build_ring_tokens(servers, rp)
    tokens = np.asarray(toks, np.uint32)
    owners = np.asarray(owns, np.int32)
    ring = device_ring(tokens, owners, 512, gen=9)

    import jax.numpy as jnp

    net = LocalNetwork()
    owner_chan = LocalChannel(net, "owner:1")

    async def answer(body, headers):
        h = decode_array(body["h"], "<u4")
        fused = np.asarray(
            serve_lookup_n_fused(ring, n_servers, jnp.asarray(h), n)
        )
        return {
            "o": encode_array(fused[:-1], "json", "<i4"),
            "gen": int(fused[-1]),
        }

    owner_chan.register("serve", "/lookup", answer)
    client = LocalChannel(net, "fe:1")
    fwd = BatchForwarder(client)

    def local_lookup(h, _n):  # this frontend owns NOTHING — all forwards
        raise AssertionError("frontend unexpectedly claimed a block")

    router = BlockRouter(
        1, 2, lambda: tokens, local_lookup, ["owner:1", "owner:1"], fwd
    )
    rng = np.random.default_rng(7)
    hashes = rng.integers(0, 2**32, size=512, dtype=np.uint32)
    # force every key remote: the router claims rank 1, keys span both
    # blocks — rank-0 keys forward; mask to just those so local never fires
    remote = hashes[rank_of_hashes(tokens, hashes, 2) == 0]

    loop = asyncio.new_event_loop()
    try:
        got, gens = loop.run_until_complete(router.route(remote, n=n))
    finally:
        loop.close()
    want = host_lookup_n(tokens, owners, remote, n, n_servers)
    if not np.array_equal(got, want):
        failures.append("forwarded answers diverge from the host LookupN walk")
    if not (gens == 9).all():
        failures.append(f"forwarded answers lost the generation: {set(gens)}")
    if fwd.rpcs != 1:
        failures.append(
            f"per-owner coalescing broken: {fwd.rpcs} RPCs for one owner"
        )
    print(
        f"serve-fanin-smoke leg1 OK: {len(remote)} keys forwarded in "
        f"{fwd.rpcs} RPC, tuples == host walk, gen pinned"
    )

    # -- leg 2: quorum read under an owner-killing plan ----------------------
    from ringpop_tpu.forward.batch import quorum_chaos_run

    rec = quorum_chaos_run(horizon=24, keys_per_tick=48, seed=0)
    if not rec["owners_killed"]:
        failures.append("quorum leg never killed an owner — vacuous")
    if not rec["quorum_held"]:
        failures.append("quorum LOST under the owner-killing plan")
    if not rec["answers_agree"]:
        failures.append("replica answers diverged")
    ttd = rec["score"]["time_to_detect"]
    if not ttd or any(v is None for _, v in ttd):
        failures.append(f"full-replication recovery not observed: {ttd}")
    if not rec["rpcs"] < rec["rpcs_naive"]:
        failures.append("quorum reads not coalesced below naive")
    print(
        f"serve-fanin-smoke leg2 OK: quorum {rec['quorum']}/{rec['r']} held "
        f"across {rec['horizon']} ticks (acks_min "
        f"{rec['score']['quorum_acks_min']}), recovery {ttd}, "
        f"rpc ratio {rec['rpc_ratio']}"
    )

    # -- leg 3: P=2 mesh digest == single-process oracle ---------------------
    from ringpop_tpu.serve.mesh import run_serve_mesh

    cfg = dict(n_servers=16, replica_points=20, n=3, streams=4, rounds=2,
               keys_per_stream=1024, seed=0)
    oracle = run_serve_mesh(1, **cfg)[0]["digest"]
    recs = run_serve_mesh(2, **cfg)
    if not all(r["digest"] == oracle for r in recs):
        failures.append(
            f"P=2 mesh digests {[r['digest'] for r in recs]} != oracle {oracle}"
        )
    msgs = sum(r["messages_sent"] for r in recs)
    naive = sum(r["messages_naive"] for r in recs)
    if not msgs < naive:
        failures.append(f"mesh messages {msgs} not below naive {naive}")
    wire = [r["wire"]["bytes_sent"] for r in recs]
    if not all(w > 0 for w in wire):
        failures.append("mesh wire accounting empty")
    print(
        f"serve-fanin-smoke leg3 OK: P=2 digests == oracle {oracle}, "
        f"{msgs} messages (naive {naive}), wire bytes/host {wire}"
    )

    if failures:
        for f in failures:
            print(f"serve-fanin-smoke FAIL: {f}")
        return 1
    print("serve-fanin-smoke PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
