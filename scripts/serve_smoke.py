"""serve-smoke: the CI gate for the serve-the-ring tier.

Runs a small multi-process paired A/B (2 frontends, shared-memory
transport) plus a DGRO placement score and asserts the CORRECTNESS
certificates — owner digests bit-identical serve vs bisect per (worker,
rep), answers pinned to the membership generation, live-update
re-certification, B=1 owners matching the oracle, the movement gate —
and that the serve journal carries the batch-size / queue-wait
telemetry schema.  Throughput ratios are recorded but NOT asserted: the
committed SIMBENCH artifact prices those on a full run; a 2-core CI
container under ambient load must not flake the gate on wall-clock.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    from ringpop_tpu.serve.bench import run_ab
    from ringpop_tpu.serve.placement import dgro_place
    from ringpop_tpu.sim.telemetry import TelemetryJournal

    path = os.path.join(tempfile.gettempdir(), f"serve_smoke_{os.getpid()}.jsonl")
    journal = TelemetryJournal(path)
    journal.header("serve", "serve_smoke", {"gate": "make serve-smoke"})
    try:
        rec = run_ab(
            n_servers=32, frontends=2, batch=2048, batches_per_rep=4,
            reps=2, warm_reps=1, latency_reqs=60, transport="shm",
            journal=journal,
        )
    finally:
        journal.close()

    failures = []
    if not rec["digest_equal"]:
        failures.append("serve/bisect owner digests diverged")
    if not rec["generation_pinned"]:
        failures.append(f"answers left the pinned generation: {rec['generations_seen']}")
    if not rec["update_certified"]:
        failures.append("live ring update failed the generation certificate")
    if not rec["latency_b1"]["owners_match_oracle"]:
        failures.append("B=1 degenerate path mis-routed vs the bisect oracle")

    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    os.unlink(path)
    serves = [r for r in records if r.get("kind") == "serve"]
    updates = [r for r in records if r.get("kind") == "ring_update"]
    if not serves:
        failures.append("journal carries no 'serve' telemetry records")
    else:
        want = {"keys_per_flush", "queue_wait_us", "dispatch_us", "flushes",
                "requests", "keys", "gen"}
        missing = want - set(serves[0])
        if missing:
            failures.append(f"serve record missing fields: {sorted(missing)}")
        hist = serves[0].get("keys_per_flush", {})
        if not {"mean", "p50", "p90", "max"} <= set(hist):
            failures.append(f"batch-size histogram malformed: {hist}")
    if not updates:
        failures.append("journal carries no 'ring_update' generation record")
    elif updates[-1].get("gen") != rec["update_record"]["gen"]:
        failures.append("ring_update journal gen != committed generation")

    _t, _o, report = dgro_place(
        [f"10.5.0.{i}:3000" for i in range(24)], 50,
        candidates=4, probes=1 << 12, churn_frac=0.05,
    )
    if report["movement_chosen"] > report["movement_random"] + 1e-9:
        failures.append(
            f"DGRO movement gate broken: chosen {report['movement_chosen']} "
            f"> random {report['movement_random']}"
        )
    if any(e != 0.0 for e in report["excess_movement"]):
        failures.append("DGRO candidate broke consistent hashing (excess movement)")

    summary = {
        "speedup_median": rec["speedup_median"],
        "latency_b1_ratio_p50": rec["latency_b1"]["ratio_p50"],
        "keys_per_flush_mean": rec["telemetry"]["keys_per_flush_mean"],
        "movement_random": report["movement_random"],
        "movement_chosen": report["movement_chosen"],
        "failures": failures,
    }
    print(json.dumps(summary, indent=1))
    if failures:
        print("serve-smoke: FAIL", file=sys.stderr)
        return 1
    print("serve-smoke: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
