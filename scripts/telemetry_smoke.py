"""telemetry-smoke — the CI gate for the sim-plane telemetry plane.

Runs the lifecycle engine twice at a tiny config — telemetry ON (with a
JSONL journal) and telemetry OFF — through the same detect + converge
drivers, and asserts:

1. the two final states are DIGEST-EQUAL (and leaf-by-leaf bit-equal):
   carrying the counter accumulators through the scan changed nothing;
2. the journal was produced, parses, and carries the full record schema
   (header with toolchain + mesh-budget fingerprints; per-block counters,
   state digest, view-checksum summary);
3. the delta engine's journal hook produces a monotone coverage series
   ending converged, bit-identically to an unjournaled run.

Exit 0 on success, 1 with a diagnosis on any failure.  Wall cost is a
few seconds (n=256) — wired into `make test` next to the profile-mesh
collective-budget ratchet.

Usage:
    python scripts/telemetry_smoke.py [--out /tmp/telemetry_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="journal path (default: temp file)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim import lifecycle, telemetry
    from ringpop_tpu.sim.delta import DeltaFaults, DeltaSim

    path = args.out or os.path.join(
        tempfile.mkdtemp(prefix="telsmoke_"), "telemetry_smoke.jsonl"
    )
    n, k, seed = 256, 64, 0
    rng = np.random.default_rng(seed)
    victims = np.sort(rng.choice(n, size=4, replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    failures: list[str] = []

    def run(telemetry_arg, views):
        sim = lifecycle.LifecycleSim(
            n=n, k=k, seed=seed, suspect_ticks=10,
            telemetry=telemetry_arg, journal_views=views,
        )
        sim.run_until_detected(victims.tolist(), faults, max_ticks=1024)
        sim.run_until_converged(faults, max_ticks=1024)
        return sim.state

    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "telemetry-smoke", {"n": n, "k": k, "seed": seed})
        sink = telemetry.TelemetrySink(journal=journal)
        s_on = run(sink, views=True)
    s_off = run(None, views=False)

    d_on = int(telemetry.tree_digest(s_on))
    d_off = int(telemetry.tree_digest(s_off))
    if d_on != d_off:
        failures.append(f"digest mismatch: telemetry-on {d_on:#010x} vs off {d_off:#010x}")
    for name, a, b in zip(s_on._fields, jax.tree.leaves(s_on), jax.tree.leaves(s_off)):
        if not bool((np.asarray(a) == np.asarray(b)).all()):
            failures.append(f"state leaf {name} diverged between telemetry on/off")

    # journal shape
    try:
        records = telemetry.read_journal(path)
    except Exception as e:  # noqa: BLE001 — the diagnosis IS the product
        records = []
        failures.append(f"journal unparseable: {type(e).__name__}: {e}")
    headers = [r for r in records if r.get("kind") == "header"]
    blocks = [r for r in records if r.get("kind") == "block"]
    if not headers or "toolchain" not in headers[0] or "mesh_budget" not in headers[0]:
        failures.append("journal header missing toolchain/mesh_budget fingerprints")
    if not blocks:
        failures.append("journal has no block records")
    else:
        want = {"ticks", "ping_send", "decl_suspect", "decl_faulty", "detect_frac",
                "census_alive", "state_digest", "views_sum", "views_agree", "tick"}
        missing = want - set(blocks[0])
        if missing:
            failures.append(f"block record missing fields: {sorted(missing)}")
        if sum(b["ticks"] for b in blocks) <= 0:
            failures.append("journal covered zero ticks")
        if blocks[-1].get("views_agree") is not True:
            failures.append("final block: live view checksums do not agree")
        if blocks[-1].get("state_digest") != d_on:
            failures.append("final block digest != final state digest")

    # delta hook
    rows: list = []
    d1 = DeltaSim(n=512, k=32, seed=seed,
                  telemetry_sink=lambda r: rows.append(jax.device_get(r)))
    t1, ok1 = d1.run_until_converged(max_ticks=512, journal_every=16)
    d2 = DeltaSim(n=512, k=32, seed=seed)
    t2, ok2 = d2.run_until_converged(max_ticks=512)
    if not (ok1 and ok2 and t1 == t2):
        failures.append(f"delta journal changed convergence: {(t1, ok1)} vs {(t2, ok2)}")
    if not all(bool((np.asarray(a) == np.asarray(b)).all())
               for a, b in zip(jax.tree.leaves(d1.state), jax.tree.leaves(d2.state))):
        failures.append("delta state diverged with journal hook attached")
    covs = [float(r["coverage"]) for r in rows]
    if not rows or covs != sorted(covs) or abs(covs[-1] - 1.0) > 1e-6:
        failures.append(f"delta coverage series not monotone-to-1: {covs}")

    if failures:
        print("telemetry-smoke: FAIL")
        for f in failures:
            print("  -", f)
        return 1
    print(
        f"telemetry-smoke: OK — {len(blocks)} lifecycle blocks + {len(rows)} "
        f"delta blocks journaled at {path}; telemetry-on digest-equal to off "
        f"({d_on:#010x})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
