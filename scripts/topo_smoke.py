"""topo-smoke — the CI gate for the topology plane (sim/topology.py).

Runs the tiny 2-rack/2-zone tree end to end and asserts:

1. **compile** — contiguous blocked tier ids, a monotone tier-drop
   table, and the penalty-free tree compiling to NO tier legs at all;
2. **scored fleet round-trip** — a small correlated-failure family
   (zone loss / switch flap / independent control) through the stacked
   Monte-Carlo fleet with per-tier telemetry armed: the journal blocks
   carry the ``suspects_*``/``false_suspects_*`` tier keys, every score
   record carries the per-tier ttd/false-positive split, and the
   correlated member's near-tier suspicion share stays below the
   independent control's (a zone cut must NOT read as independent
   crashes);
3. **sharded == unsharded digest twin** — the canonical ``smoke``
   topology plan over the 4×2 virtual mesh in a child process, digests
   + every leaf bit-equal;
4. **constant-tree jaxpr identity** — a zero-penalty tree's scenario
   traces to the IDENTICAL jaxpr as the flat fault-plan step (the
   tier legs compile out; no golden recapture needed).

Exit 0 on success, 1 with a diagnosis on any failure.  Wired into
``make test`` next to chaos-smoke.

Usage:
    python scripts/topo_smoke.py [--out /tmp/topo_smoke.jsonl]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="journal path (default: temp file)")
    args = ap.parse_args()

    import jax
    import numpy as np

    from ringpop_tpu.sim import chaos, lifecycle, scenarios, telemetry, topology
    from ringpop_tpu.util.accel import configure_compile_cache

    configure_compile_cache()

    failures: list[str] = []
    n, k, seed, horizon = 256, 32, 0, 128

    # -- 1: compile the tiny 2-rack/2-zone tree ------------------------------
    spec = topology.TopologySpec(
        regions=1, zones_per_region=2, racks_per_zone=2,
        zone_link=topology.TierLink(rtt_ms=2.0, loss=0.01),
    )
    topo = topology.compile_topology(spec, n)
    rack, zone = topo.tier_ids[0], topo.tier_ids[1]
    if not (np.all(np.diff(rack) >= 0) and len(np.unique(rack)) == 4):
        failures.append(f"rack ids not contiguous blocks: {np.unique(rack)}")
    if not np.all(np.diff(topo.tier_drop.astype(np.float64)) >= 0):
        failures.append(f"tier_drop not monotone: {topo.tier_drop}")
    if topo.tier_drop[2] <= 0:
        failures.append("cross-zone tier carries no penalty — the spec set one")
    flat = topology.compile_topology(
        topology.TopologySpec(regions=1, zones_per_region=2, racks_per_zone=2), n
    )
    if any(v is not None for v in flat.plan_legs()):
        failures.append("penalty-free tree emitted tier legs (must compile out)")

    # -- 2: scored fleet round-trip ------------------------------------------
    path = args.out or os.path.join(
        tempfile.mkdtemp(prefix="toposmoke_"), "topo_smoke.jsonl"
    )
    first, heal = 4, horizon // 2
    plans = [
        chaos._merge_plans(
            topology.zone_loss_plan(topo, 1, at=first, heal=heal), topo.plan_legs()
        ),
        chaos._merge_plans(
            topology.switch_flap_plan(topo, 0, period=12, down=3, start=first),
            topo.plan_legs(),
        ),
        chaos._merge_plans(
            topology.independent_crash_plan(
                topo, int(topo.nodes_in_zone(1).size), at=first, heal=heal, seed=seed
            ),
            topo.plan_legs(),
        ),
    ]
    meta = [
        {"scenario_id": 0, "event": "zone_loss"},
        {"scenario_id": 1, "event": "switch_flap"},
        {"scenario_id": 2, "event": "independent"},
    ]
    stacked = chaos.stack_plans(plans)
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=8, rng="counter")
    with telemetry.TelemetryJournal(path) as journal:
        journal.header("lifecycle", "topo-smoke", {"n": n, "k": k, "seed": seed})
        sink = telemetry.TelemetrySink(journal=journal)
        scores = scenarios.scored_fleet(
            params, stacked, meta, [seed, seed + 1, seed + 2],
            horizon=horizon, journal_every=16, sink=sink, scenario="topo_smoke",
        )

    records = telemetry.read_journal(path)
    blocks = [r for r in records if r.get("kind") == "block"]
    score_recs = [r for r in records if r.get("kind") == "score"]
    tier_keys = [f"suspects_{t}" for t in telemetry.TIER_KEYS] + [
        f"false_suspects_{t}" for t in telemetry.TIER_KEYS
    ]
    if not blocks or not all(all(tk in b for tk in tier_keys) for b in blocks):
        failures.append("journal blocks missing the per-tier suspicion keys")
    if len(score_recs) != 3:
        failures.append(f"expected 3 score records, journal has {len(score_recs)}")
    for s in scores:
        for key in ("suspects_by_tier", "false_positive_by_tier",
                    "time_to_detect_by_tier"):
            if not isinstance(s.get(key), dict):
                failures.append(f"score {s.get('scenario_id')} missing {key}")

    def near_share(score):
        bt = score.get("suspects_by_tier") or {}
        total = float(sum(bt.values()))
        if total <= 0:
            return None
        return (bt.get("same_rack", 0) + bt.get("cross_rack", 0)) / total

    z, ind = near_share(scores[0]), near_share(scores[2])
    if ind is None or ind <= 0:
        failures.append(
            f"independent control raised no near-tier suspicion (share={ind}) — "
            "the discriminator is vacuous"
        )
    elif z is not None and z >= ind:
        failures.append(
            f"zone loss near-tier share {z} not below independent control {ind} "
            "— the correlated event reads as independent crashes"
        )

    # -- 3: sharded == unsharded digest twin ---------------------------------
    from ringpop_tpu.cli.simbench import _chaos_sharded_twin

    # k=64: the 4×2 twin mesh shards 32-slot packed words over a 2-way
    # rumor axis (packbits.check_rumor_shardable)
    twin = _chaos_sharded_twin("smoke", seed, n=512, k=64, ticks=24,
                               horizon=64, builder="topo")
    if not twin.get("equal"):
        failures.append(f"sharded twin diverged: {twin}")

    # -- 4: constant-tree jaxpr identity -------------------------------------
    state = lifecycle.init_state(params, seed=seed)
    const_plan = topology.topo_scenario_plan("flat", n, seed=seed, horizon=horizon)
    hand_plan = topology.zone_loss_plan(
        flat, zone=1, at=max(4, horizon // 32), heal=horizon // 2
    )
    ja = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, const_plan)
    jb = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, hand_plan)
    if str(ja) != str(jb):
        failures.append(
            "constant (penalty-free) topology does NOT trace to the flat "
            "fault-plan jaxpr — the tier legs failed to compile out"
        )

    if failures:
        print("topo-smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(
        f"topo-smoke OK: tree compiled (tier_drop={topo.tier_drop.tolist()}), "
        f"{len(blocks)} journal blocks + {len(score_recs)} scores with per-tier "
        f"split (near-tier share zone={z} vs independent={round(ind, 4)}), "
        f"sharded twin digest {twin['digest_sharded']} == unsharded, "
        "constant-tree jaxpr identical"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
