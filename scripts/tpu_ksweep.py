"""On-chip measurement sweep, run by the tunnel watcher (``make tpu-watch``)
while the TPU is alive.  This is the round-4 replacement for the off-tree
round-3 script the verdict rejected as inadmissible: it lives in-tree, it
stamps every capture with git head + dirty flag + UTC timestamp + config,
and its per-tick numbers are measured with explicit ``block_until_ready``
around every timed rep so a sub-60s claim can never be an async-dispatch
artifact.

Sections (each independently try/excepted; the JSON is rewritten after
every section so a mid-run tunnel death still leaves partial evidence):

1. **Per-tick cost model** — the centerpiece.  For each k in ``KSWEEP_KS``
   at n=``KSWEEP_N``: compile one 32-tick lifecycle block, then time
   synced reps.  This single-sources the "ms/tick at 1M" number that
   round 3's artifacts disagreed about (0.57 s/64 ticks vs a 142 ms/tick
   trace reading — see PERF.md round-4 reconciliation).
1d. **chaos_tick** — the churn+flap-enabled tick (``sim/chaos.py``
   FaultPlan evaluated inside the jitted step) vs the plain tick, at the
   same config; sharded over the visible chips when >1 (the number that
   certifies the chaos plane's claimed ~zero overhead on real ICI).
1d2. **topo_chaos** — the topology-enabled chaos tick (``sim/topology.py``
   tier legs forced with a zero drop table) vs the flat chaos tick: the
   id gathers + blocked one-hot tier expansion + extra coin sites run in
   full but every coin passes, so the A/B must be BIT-EQUAL and the
   overhead number prices the tier machinery itself on real ICI.
1e. **mc_chaos** — the r12 batched chaos fleet: B=16 stacked-FaultPlan
   (churn×loss) scenarios stepped as ONE vmapped program vs the same 16
   stepped sequentially, both warm; sharded (batch replicated,
   node/rumor canonical) when >1 chip.  Judged by certify_cost_model:
   the fleet must be no slower per tick and bit-equal per scenario.
1f. **fleet_scale** — the r19 block-sharded fleet: the SAME stacked grid
   stepped with its batch axis ON the mesh (``make_fleet_mesh`` — B
   shards over the chips, per-chip residency divides by the batch
   factor) vs the r12 batch-replicated layout.  No cross-batch
   collectives exist, so the model says batch sharding is free compute
   and pure HBM headroom: certify_cost_model REFUTES if the sharded
   fleet is slower beyond noise or any scenario's final state diverges
   (bit_equal).
2. Headline detection at the official config (k=256, 1000 victims),
   fresh state, wall + ticks; cross-checked against the cost model.
3. Convergence (view-checksum agreement + quiescence) continuing from
   the detected state — the literal BASELINE.md north-star wording.
4. Delta rumor convergence at 1M and at 16M (16x north-star scale).
4b. Sparse candidate selection (``lifecycle._top_m_sparse``) vs the
   dense ``lax.top_k`` full sort it replaced in round 4 — per-call ms
   for both, a bit-equality cross-check, and whether the sparse branch
   statically engaged at this n (below the floor both sides are the
   same dense program and the comparison is vacuous).
5. Batched ring lookup qps (sustained: 10 batches inside one jitted
   loop — per-dispatch timing through the tunnel would measure the
   tunnel, not the op; methodology per bench.py).
6. Pallas FarmHash kernel vs the jnp lowering (the reference's
   ``hashring/hashring_test.go:332`` micro-benchmark analog, on-chip).

Reference analog: none — the Go reference has no accelerator plane; this
is rebuild-owned measurement infrastructure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# invoked as `python scripts/tpu_ksweep.py` — the repo root (one level up)
# is not on sys.path then, so add it for the ringpop_tpu imports
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _env_capture() -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _git(*args):
        try:
            return subprocess.run(
                ["git", "-C", repo, *args], capture_output=True, text=True, timeout=10
            ).stdout.strip()
        except (OSError, subprocess.SubprocessError):
            return None

    import jax

    return {
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "captured_by": "scripts/tpu_ksweep.py",
        "git_head": _git("rev-parse", "HEAD"),
        "git_dirty": bool(_git("status", "--porcelain")),
        "jax_version": jax.__version__,
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
    }


def main() -> None:
    import jax

    # KSWEEP_PIN=cpu for smoke runs: this environment's axon site hook can
    # initialize the (hang-prone, tunnel-backed) axon client regardless of
    # JAX_PLATFORMS, so an explicit config pin is the only reliable opt-out
    pin = os.environ.get("KSWEEP_PIN")
    if pin:
        try:
            jax.config.update("jax_platforms", pin)
        except RuntimeError:
            pass  # backend already initialized

    import jax.numpy as jnp

    # same persistent, platform-fingerprinted compile cache as bench.py —
    # a repeat capture in a later tunnel window pays zero recompiles
    from ringpop_tpu.util.accel import configure_compile_cache

    configure_compile_cache()

    out = _env_capture()
    if os.environ.get("KSWEEP_REQUIRE_TPU") and out["platform"] == "cpu":
        raise SystemExit(f"KSWEEP_REQUIRE_TPU set but platform={out['platform']}")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_override = os.environ.get("KSWEEP_OUT")
    if out_override:
        # smoke/test runs: write ONLY here — never clobber the round's
        # real .tpu_ksweep.json / captures/ evidence with CPU smoke data
        paths = (out_override,)
    else:
        ts = out["captured_at"].replace(":", "").replace("-", "")
        cap_dir = os.path.join(repo, "captures")
        os.makedirs(cap_dir, exist_ok=True)
        paths = (
            os.path.join(repo, ".tpu_ksweep.json"),
            os.path.join(cap_dir, f"tpu_ksweep_{ts}.json"),
        )

    def flush():
        blob = json.dumps(out, indent=1)
        for p in paths:
            with open(p, "w") as f:
                f.write(blob)

    flush()

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import (
        DeltaFaults,
        DeltaParams,
        init_state,
        run_until_converged,
    )

    n = int(os.environ.get("KSWEEP_N", 1_000_000))
    ks = [int(k) for k in os.environ.get("KSWEEP_KS", "128,256,512").split(",")]
    k_head = int(os.environ.get("KSWEEP_K_HEADLINE", 256))
    block = 32
    reps = int(os.environ.get("KSWEEP_REPS", 3))

    rng = np.random.default_rng(0)
    victims = np.sort(rng.choice(n, size=max(2, n // 1000), replace=False))
    up = np.ones(n, bool)
    up[victims] = False
    faults = DeltaFaults(up=jnp.asarray(up))

    # -- 1: per-tick cost model across k ------------------------------------
    out["tick_cost"] = {}
    for k in ks:
        try:
            sim = lifecycle.LifecycleSim(n=n, k=k, seed=0)
            t0 = time.perf_counter()
            jax.block_until_ready(sim.run(block, faults))  # compile + first block
            compile_s = time.perf_counter() - t0
            per_rep = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(sim.run(block, faults))
                per_rep.append(time.perf_counter() - t0)
            out["tick_cost"][str(k)] = {
                "n": n,
                "block_ticks": block,
                "compile_plus_first_block_s": round(compile_s, 3),
                "block_s_reps": [round(r, 4) for r in per_rep],
                "ms_per_tick_median": round(sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3),
            }
            del sim
        except Exception as e:  # pragma: no cover - hardware-dependent
            out["tick_cost"][str(k)] = {"error": f"{type(e).__name__}: {e}"[:300]}
        flush()

    # -- 1b: the SHARDED tick over every visible chip (multi-chip ICI model,
    # r6): only runs when the window exposes >1 device — the virtual-CPU
    # variant of this number measures host thread rendezvous, not ICI, so
    # a CPU fallback records nothing here.  certify_cost_model judges the
    # median against the ICI-floor bracket derived from the committed
    # profile_mesh collective budget (captures/mesh_profile_r6_after.json).
    if len(jax.devices()) > 1 and out["platform"] != "cpu":
        try:
            from jax.sharding import Mesh

            k = 256
            params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10)
            n_dev = len(jax.devices())
            rumor = 2 if n_dev % 2 == 0 else 1
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            sstate = jax.tree.map(
                jax.device_put,
                lifecycle.init_state(params, seed=0),
                lifecycle.state_shardings(mesh, k=k),
            )
            import functools as _ft

            sblk = jax.jit(
                _ft.partial(lifecycle._run_block, params), static_argnames="ticks"
            )
            t0 = time.perf_counter()
            sstate = sblk(sstate, faults, ticks=block)
            jax.block_until_ready(sstate.learned)
            compile_s = time.perf_counter() - t0
            per_rep = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sstate = sblk(sstate, faults, ticks=block)
                jax.block_until_ready(sstate.learned)
                per_rep.append(time.perf_counter() - t0)
            out["sharded_tick"] = {
                "n": n,
                "k": k,
                "n_devices": n_dev,
                "mesh": f"{n_dev // rumor}x{rumor} (node x rumor)",
                "block_ticks": block,
                "compile_plus_first_block_s": round(compile_s, 3),
                "block_s_reps": [round(r, 4) for r in per_rep],
                "ms_per_tick_median": round(
                    sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
                ),
            }
        except Exception as e:  # pragma: no cover - hardware-dependent
            out["sharded_tick"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        flush()

        # -- 1b2: multihost_tick (r14) — the SAME jitted delta step over the
        # process-spanning mesh (init_distributed + make_multihost_mesh +
        # the canonical partition table), measured per tick.  Only a real
        # multi-process job prices the DCN legs — a single-process run of
        # this section measures ICI again, so it records the reason and
        # moves on.  certify_cost_model judges ms/tick against the
        # sharded-tick bracket (DCN adds slice-edge latency, not volume:
        # the exchange's crossing sends are the only DCN class) and the
        # CENSUSED per-chip MB/tick of the compiled program against the
        # committed 42.5 MB/chip/tick budget.
        try:
            import jax as _jx

            if _jx.process_count() > 1:
                import functools as _ft

                from ringpop_tpu.parallel.mesh import with_exchange_mesh
                from ringpop_tpu.parallel.multihost import make_multihost_mesh
                from ringpop_tpu.parallel.partition import named_shardings
                from ringpop_tpu.sim.delta import DeltaParams as _DP
                from ringpop_tpu.sim.delta import init_state as _dinit
                from ringpop_tpu.sim.delta import step as _dstep

                k = 64
                mh_mesh = make_multihost_mesh()
                mh_params = with_exchange_mesh(
                    _DP(n=n, k=k, rng="counter"), mh_mesh
                )
                sh = named_shardings(_dinit(_DP(n=8, k=k), seed=0), mh_mesh)
                mstate = jax.jit(
                    lambda: _dinit(mh_params, seed=0), out_shardings=sh
                )()
                mstep = jax.jit(
                    _ft.partial(_dstep, mh_params), in_shardings=(sh, None),
                    out_shardings=sh,
                )
                t0 = time.perf_counter()
                mstate = mstep(mstate, DeltaFaults())
                jax.block_until_ready(mstate.learned)
                compile_s = time.perf_counter() - t0
                per_rep = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    for _t in range(block):
                        mstate = mstep(mstate, DeltaFaults())
                    jax.block_until_ready(mstate.learned)
                    per_rep.append(time.perf_counter() - t0)
                chips_per_host = len(jax.local_devices())
                # MEASURED per-tick collective volume: census the COMPILED
                # program's collective ops (the same parser the budget
                # ratchet uses) — this is what certify_cost_model judges
                # against the 42.5 MB/chip budget, so a multi-host
                # lowering that added traffic classes shows up as bytes,
                # not as a derived constant agreeing with itself.
                census_row = {}
                try:
                    from ringpop_tpu.analysis.hlo_census import summarize
                    from ringpop_tpu.analysis.trace_checks import census_of_text

                    compiled = jax.jit(
                        _ft.partial(_dstep, mh_params),
                        in_shardings=(sh, None), out_shardings=sh,
                    ).lower(mstate, DeltaFaults()).compile()
                    by_kind = summarize(census_of_text(compiled.as_text()))
                    total_mb = sum(v["bytes"] for v in by_kind.values()) / 1e6
                    census_row = {
                        "census_mb_per_tick_total": round(total_mb, 2),
                        "census_mb_per_chip_tick": round(
                            total_mb / max(len(jax.devices()), 1), 2
                        ),
                        "census_by_kind": {
                            k: {"count": v["count"], "mb": round(v["bytes"] / 1e6, 2)}
                            for k, v in by_kind.items()
                        },
                    }
                except Exception as ce:
                    census_row = {"census_error": f"{type(ce).__name__}: {ce}"[:200]}
                out["multihost_tick"] = {
                    **census_row,
                    "n": n,
                    "k": k,
                    "process_count": _jx.process_count(),
                    "n_devices": len(jax.devices()),
                    "chips_per_host": chips_per_host,
                    "mesh": "x".join(map(str, mh_mesh.devices.shape))
                    + " (node x rumor, DCN on node)",
                    "compile_plus_first_tick_s": round(compile_s, 3),
                    "block_ticks": block,
                    "block_s_reps": [round(r, 4) for r in per_rep],
                    "ms_per_tick_median": round(
                        sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
                    ),
                }
            else:
                out["multihost_tick"] = {
                    "error": "single-process job: DCN legs not exercised "
                    "(launch via scripts/multihost_launch.py on a pod slice)"
                }
        except Exception as e:  # pragma: no cover - hardware-dependent
            out["multihost_tick"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        flush()

        # -- 1b3: swing_exchange (r16) — the host-bridged fabric's window
        # schedules priced on REAL inter-host links: cyclic direct sends
        # vs swing distance-halving relays (plan_window_swing) vs the
        # cross-tick overlap (exchange_async completions), digests
        # bit-identical by construction and re-checked here.  On this
        # container's loopback the three are parity (SIMBENCH_r10
        # swing_overlap); real DCN is where swing's power-of-two leg
        # distances and the overlap's hidden drain can actually cash out
        # — certify_cost_model judges the medians (bit-unequal or
        # slower-than-cyclic REFUTES).
        try:
            import jax as _jx

            if _jx.process_count() > 1:
                from ringpop_tpu.parallel.fabric import DistributedKV, Fabric
                from ringpop_tpu.sim.delta import DeltaParams as _DP
                from ringpop_tpu.sim.delta_multihost import MultihostDelta

                nproc = _jx.process_count()
                sec = {"n": n, "k": 64, "process_count": nproc,
                       "block_ticks": block}
                out["swing_exchange"] = sec
                digests, ticks_run, raws = {}, {}, {}
                configs = [("cyclic", "cyclic", False)]
                if nproc & (nproc - 1) == 0:
                    configs.append(("swing", "swing", False))
                configs.append(("overlap", "cyclic", True))
                for label, schedule, overlap in configs:
                    fab = Fabric(
                        _jx.process_index(), nproc, DistributedKV(),
                        namespace=f"ksweep-swing-{label}",
                    )
                    mh = MultihostDelta(
                        _DP(n=n, k=64, rng="counter"), fab, seed=0,
                        schedule=schedule, overlap=overlap,
                    )
                    for _ in range(2):
                        mh.step()  # warm the shard-local kernels
                    per_rep = []
                    for _ in range(reps):
                        t0 = time.perf_counter()
                        for _t in range(block):
                            mh.step()
                        per_rep.append(time.perf_counter() - t0)
                    digests[label] = mh.state_digest()
                    ticks_run[label] = mh.tick
                    raws[label] = fab.wire_stats()["raw_bytes_sent"]
                    timing = mh.leg_timing()
                    sec[f"{label}_ms_per_tick_median"] = round(
                        sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
                    )
                    sec[f"{label}_leg_ms"] = timing["fabric_leg_ms"]
                    sec[f"{label}_overlap_hidden_ms"] = timing[
                        "overlap_hidden_ms"
                    ]
                    fab.close()
                    flush()
                # equal tick counts by construction; digest equality is
                # the cross-schedule bit-identity certificate
                sec["bit_equal"] = (
                    len(set(ticks_run.values())) == 1
                    and len(set(digests.values())) == 1
                )
                if "swing" in raws and raws["cyclic"]:
                    sec["relay_raw_ratio"] = round(
                        raws["swing"] / raws["cyclic"], 3
                    )
            else:
                out["swing_exchange"] = {
                    "error": "single-process job: DCN schedules not "
                    "exercised (launch via scripts/multihost_launch.py on "
                    "a pod slice)"
                }
        except Exception as e:  # pragma: no cover - hardware-dependent
            out.setdefault("swing_exchange", {})[
                "error"
            ] = f"{type(e).__name__}: {e}"[:300]
        flush()

        # -- 1c: the r8 exchange-leg A/B — shard_map crossing-block ppermutes
        # vs the partitioner-inferred roll gathers, same counter RNG on both
        # sides so ONLY the exchange lowering differs.  The r8 budget says
        # the shard_map legs move ~2.6x fewer exchange bytes (12.6 vs 33
        # MB/chip/tick at 1M x 256 on the 4x2 census); this section is what
        # lets certify_cost_model judge that model against real ICI, and
        # the bit-equality bit certifies the lowering on hardware.
        try:
            import functools as _ft

            from jax.sharding import Mesh

            from ringpop_tpu.parallel.mesh import with_exchange_mesh

            n_dev = len(jax.devices())
            rumor = 2 if n_dev % 2 == 0 else 1
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            k = 256
            base_p = lifecycle.LifecycleParams(
                n=n, k=k, suspect_ticks=10, rng="counter"
            )
            sm_p = with_exchange_mesh(base_p, mesh)
            sec = {"n": n, "k": k, "n_devices": n_dev, "block_ticks": block}
            out["sharded_exchange"] = sec
            finals = {}
            for label, p in (("roll", base_p), ("shardmap", sm_p)):
                sstate = jax.tree.map(
                    jax.device_put,
                    lifecycle.init_state(p, seed=0),
                    lifecycle.state_shardings(mesh, k=k),
                )
                blk_fn = jax.jit(
                    _ft.partial(lifecycle._run_block, p), static_argnames="ticks"
                )
                sstate = blk_fn(sstate, faults, ticks=block)
                jax.block_until_ready(sstate.learned)  # compile + warm
                per_rep = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    sstate = blk_fn(sstate, faults, ticks=block)
                    jax.block_until_ready(sstate.learned)
                    per_rep.append(time.perf_counter() - t0)
                finals[label] = sstate
                sec[f"{label}_ms_per_tick_median"] = round(
                    sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
                )
                flush()
            sec["bit_equal"] = all(
                bool((np.asarray(a) == np.asarray(b)).all())
                for a, b in zip(
                    jax.tree_util.tree_leaves(finals["roll"]),
                    jax.tree_util.tree_leaves(finals["shardmap"]),
                )
            )
        except Exception as e:  # pragma: no cover - hardware-dependent
            out.setdefault("sharded_exchange", {})[
                "error"
            ] = f"{type(e).__name__}: {e}"[:300]
        flush()

        # -- 1c2: the r11 pipelined-exchange A/B — the fused leg loop
        # (shard_roll_pipelined: response-leg ppermutes issued while the
        # request-leg merge computes) vs the sequential r8 legs, same
        # counter RNG and H both sides so ONLY the leg scheduling differs.
        # The census says the two move IDENTICAL collective counts/bytes,
        # so any delta here is pure overlap: the pipelined side should be
        # no slower, and faster by up to the crossing-send latency the
        # schedule now hides.  certify_cost_model judges the pair (and
        # the bit_equal flag) alongside the r8 exchange A/B.
        try:
            import functools as _ft

            from jax.sharding import Mesh

            from ringpop_tpu.parallel.mesh import with_exchange_mesh

            n_dev = len(jax.devices())
            # ALL devices on the node axis: the exchange legs live on the
            # node axis, and with_exchange_mesh no-ops on a <=1-way node
            # axis — a (1, 2) mesh would silently time the SAME gather
            # program on both sides and certify nothing
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev, 1),
                ("node", "rumor"),
            )
            k = 256
            base_p = lifecycle.LifecycleParams(
                n=n, k=k, suspect_ticks=10, rng="counter"
            )
            if with_exchange_mesh(base_p, mesh).exchange_mesh is None:
                raise RuntimeError(
                    "exchange-mesh binding no-opped (node axis <= 1) — "
                    "the A/B would time the same program twice"
                )
            sec = {"n": n, "k": k, "n_devices": n_dev,
                   "node_shards": n_dev, "block_ticks": block}
            out["pipelined_exchange"] = sec
            finals = {}
            for label, p in (
                ("sequential", with_exchange_mesh(base_p, mesh, pipelined=False)),
                ("pipelined", with_exchange_mesh(base_p, mesh, pipelined=True)),
            ):
                sstate = jax.tree.map(
                    jax.device_put,
                    lifecycle.init_state(p, seed=0),
                    lifecycle.state_shardings(mesh, k=k),
                )
                blk_fn = jax.jit(
                    _ft.partial(lifecycle._run_block, p), static_argnames="ticks"
                )
                sstate = blk_fn(sstate, faults, ticks=block)
                jax.block_until_ready(sstate.learned)  # compile + warm
                per_rep = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    sstate = blk_fn(sstate, faults, ticks=block)
                    jax.block_until_ready(sstate.learned)
                    per_rep.append(time.perf_counter() - t0)
                finals[label] = sstate
                sec[f"{label}_ms_per_tick_median"] = round(
                    sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
                )
                flush()
            sec["bit_equal"] = all(
                bool((np.asarray(a) == np.asarray(b)).all())
                for a, b in zip(
                    jax.tree_util.tree_leaves(finals["sequential"]),
                    jax.tree_util.tree_leaves(finals["pipelined"]),
                )
            )
        except Exception as e:  # pragma: no cover - hardware-dependent
            out.setdefault("pipelined_exchange", {})[
                "error"
            ] = f"{type(e).__name__}: {e}"[:300]
        flush()

    # -- 1d: chaos_tick — the churn+flap-enabled tick vs the plain tick ----
    # (sim/chaos.py FaultPlan evaluated inside the jitted step).  The CPU
    # census says fault-timeline evaluation adds zero collectives and the
    # elementwise legs are noise against the packed-plane passes; this
    # section is what lets certify_cost_model judge that claim on real
    # hardware.  Sharded over every visible chip when the window exposes
    # >1 device (mirroring 1b), dense otherwise — both labeled.
    try:
        import functools as _ft

        from ringpop_tpu.sim import chaos

        k = 256
        plan = chaos.scenario_plan("smoke", n, seed=0, horizon=4 * block)
        base_p = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")
        sharded = len(jax.devices()) > 1 and out["platform"] != "cpu"
        sec = {"n": n, "k": k, "block_ticks": block, "sharded": sharded}
        out["chaos_tick"] = sec
        if sharded:
            from jax.sharding import Mesh

            from ringpop_tpu.parallel.mesh import with_exchange_mesh

            n_dev = len(jax.devices())
            rumor = 2 if n_dev % 2 == 0 else 1
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            base_p = with_exchange_mesh(base_p, mesh)
            sec["n_devices"] = n_dev
            sec["mesh"] = f"{n_dev // rumor}x{rumor} (node x rumor)"

            def mk_state():
                return jax.tree.map(
                    jax.device_put,
                    lifecycle.init_state(base_p, seed=0),
                    lifecycle.state_shardings(mesh, k=k),
                )
        else:
            def mk_state():
                return lifecycle.init_state(base_p, seed=0)

        blk_fn = jax.jit(
            _ft.partial(lifecycle._run_block, base_p), static_argnames="ticks"
        )
        for label, f in (("plain", faults), ("chaos", plan)):
            sstate = mk_state()
            sstate = blk_fn(sstate, f, ticks=block)
            jax.block_until_ready(sstate.learned)  # compile + warm
            per_rep = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sstate = blk_fn(sstate, f, ticks=block)
                jax.block_until_ready(sstate.learned)
                per_rep.append(time.perf_counter() - t0)
            sec[f"{label}_ms_per_tick_median"] = round(
                sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
            )
            flush()
        if sec.get("plain_ms_per_tick_median"):
            sec["overhead_pct"] = round(
                (sec["chaos_ms_per_tick_median"] / sec["plain_ms_per_tick_median"] - 1)
                * 100.0,
                1,
            )
    except Exception as e:  # pragma: no cover - hardware-dependent
        out.setdefault("chaos_tick", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 1d2: topo_chaos — the topology-enabled chaos tick vs the flat one --
    # (sim/topology.py).  The same canonical smoke plan, once flat and once
    # with the rack/zone/region tier legs FORCED with a zero drop table:
    # the tier machinery (id gathers + blocked one-hot expansion + the
    # extra coin sites) runs in full, but every coin passes — so the two
    # runs must be BIT-EQUAL by the separate-coin construction, and the
    # overhead number prices the tier evaluation itself.  Sharded over
    # every visible chip when the window exposes >1 device (mirroring 1d).
    try:
        import functools as _ft

        from ringpop_tpu.sim import chaos, topology

        k = 256
        flat_plan = chaos.scenario_plan("smoke", n, seed=0, horizon=4 * block)
        topo = topology.default_topology(n)
        topo_plan = chaos._merge_plans(flat_plan, topo.plan_legs(force=True))
        # zero the table: bit-equality is the certificate; the penalized
        # table would measure a DIFFERENT trajectory, not the machinery
        topo_plan = topo_plan._replace(
            tier_drop=jnp.zeros_like(topo_plan.tier_drop)
        )
        base_p = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=10, rng="counter")
        sharded = len(jax.devices()) > 1 and out["platform"] != "cpu"
        sec = {"n": n, "k": k, "block_ticks": block, "sharded": sharded,
               "racks": topo.spec.total_racks}
        out["topo_chaos"] = sec
        if sharded:
            from jax.sharding import Mesh

            from ringpop_tpu.parallel.mesh import with_exchange_mesh

            n_dev = len(jax.devices())
            rumor = 2 if n_dev % 2 == 0 else 1
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            base_p = with_exchange_mesh(base_p, mesh)
            sec["n_devices"] = n_dev
            sec["mesh"] = f"{n_dev // rumor}x{rumor} (node x rumor)"

            def mk_state():
                return jax.tree.map(
                    jax.device_put,
                    lifecycle.init_state(base_p, seed=0),
                    lifecycle.state_shardings(mesh, k=k),
                )
        else:
            def mk_state():
                return lifecycle.init_state(base_p, seed=0)

        blk_fn = jax.jit(
            _ft.partial(lifecycle._run_block, base_p), static_argnames="ticks"
        )
        finals = {}
        for label, f in (("flat", flat_plan), ("topo", topo_plan)):
            sstate = mk_state()
            sstate = blk_fn(sstate, f, ticks=block)
            jax.block_until_ready(sstate.learned)  # compile + warm
            per_rep = []
            for _ in range(reps):
                t0 = time.perf_counter()
                sstate = blk_fn(sstate, f, ticks=block)
                jax.block_until_ready(sstate.learned)
                per_rep.append(time.perf_counter() - t0)
            finals[label] = sstate
            sec[f"{label}_ms_per_tick_median"] = round(
                sorted(per_rep)[len(per_rep) // 2] / block * 1e3, 3
            )
            flush()
        sec["bit_equal"] = all(
            bool((np.asarray(a) == np.asarray(b)).all())
            for a, b in zip(
                jax.tree_util.tree_leaves(finals["flat"]),
                jax.tree_util.tree_leaves(finals["topo"]),
            )
        )
        if sec.get("flat_ms_per_tick_median"):
            sec["overhead_pct"] = round(
                (sec["topo_ms_per_tick_median"] / sec["flat_ms_per_tick_median"] - 1)
                * 100.0,
                1,
            )
    except Exception as e:  # pragma: no cover - hardware-dependent
        out.setdefault("topo_chaos", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 1e: mc_chaos — the r12 batched chaos fleet vs sequential B runs ----
    # B=16 (churn dose x loss) scenarios — a stacked FaultPlan grid
    # (sim/scenarios.py) — stepped as ONE vmapped program vs the same 16
    # stepped one at a time.  Both sides warm: the compile-amortization
    # half of the claim is priced on CPU in SIMBENCH mc_chaos; this
    # section prices the per-dispatch amortization on real hardware.
    # Sharded over every visible chip when the window exposes >1 device
    # (batch axis replicated, node/rumor canonical —
    # montecarlo.fleet_state_shardings).  certify_cost_model REFUTES if
    # the fleet is slower than the sequential loop or any scenario's
    # final state diverges from its solo run (bit_equal).
    try:
        import functools as _ft

        from ringpop_tpu.sim import chaos, montecarlo, scenarios

        n_mc = int(os.environ.get("KSWEEP_MC_N", 16384))
        k_mc = 64
        mc_ticks = block
        rng2 = np.random.default_rng(1)
        mc_victims = sorted(rng2.choice(n_mc, size=8, replace=False).tolist())
        doses = scenarios.mc_churn_doses(8, n_mc // 32)
        plan, meta = scenarios.scenario_grid(
            n_mc, victims=mc_victims, doses=doses, losses=(0.0, 0.05),
            churn_seed=777,
        )
        seeds = scenarios.grid_seeds(meta, 0)
        b_mc = len(meta)
        params_mc = lifecycle.LifecycleParams(
            n=n_mc, k=k_mc, suspect_ticks=10, rng="counter"
        )
        sharded = len(jax.devices()) > 1 and out["platform"] != "cpu"
        sec = {"n": n_mc, "k": k_mc, "b": b_mc, "block_ticks": mc_ticks,
               "sharded": sharded}
        out["mc_chaos"] = sec
        blk = jax.jit(
            _ft.partial(montecarlo._mc_block, params_mc), static_argnames="ticks"
        )
        bstate = montecarlo.init_replicas(params_mc, seeds)
        if sharded:
            from jax.sharding import Mesh

            n_dev = len(jax.devices())
            rumor = 2 if n_dev % 2 == 0 else 1
            mesh = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            sec["n_devices"] = n_dev
            sec["mesh"] = f"{n_dev // rumor}x{rumor} (node x rumor), batch replicated"
            bstate = jax.tree.map(
                jax.device_put, bstate,
                montecarlo.fleet_state_shardings(mesh, k=k_mc),
            )
        bstate = blk(bstate, plan, ticks=mc_ticks)
        jax.block_until_ready(bstate.learned)  # compile + warm block 1
        per_rep = []
        for _ in range(reps):
            t0 = time.perf_counter()
            bstate = blk(bstate, plan, ticks=mc_ticks)
            jax.block_until_ready(bstate.learned)
            per_rep.append(time.perf_counter() - t0)
        sec["batched_ms_per_tick_median"] = round(
            sorted(per_rep)[len(per_rep) // 2] / mc_ticks * 1e3, 3
        )
        flush()
        # sequential loop: B=1 slices of the same grid, one compile shared
        # (warm), run for the SAME total blocks so finals are comparable
        finals = [
            montecarlo.init_replicas(params_mc, [seeds[b2]]) for b2 in range(b_mc)
        ]
        if sharded:
            # same mesh on both sides: an unsharded baseline would hand the
            # fleet an n_devices x hardware advantage and the certificate
            # would stop pricing dispatch amortization
            finals = [
                jax.tree.map(
                    jax.device_put, f,
                    montecarlo.fleet_state_shardings(mesh, k=k_mc),
                )
                for f in finals
            ]
        solo_plans = [
            chaos.stack_plans([chaos.index_plan(plan, b2)]) for b2 in range(b_mc)
        ]
        per_rep = []
        for r in range(1 + reps):
            t0 = time.perf_counter()
            for b2 in range(b_mc):
                finals[b2] = blk(finals[b2], solo_plans[b2], ticks=mc_ticks)
            jax.block_until_ready(finals[-1].learned)
            if r > 0:  # rep 0 pays the B=1 compile — warm parity with batched
                per_rep.append(time.perf_counter() - t0)
        sec["sequential_ms_per_tick_median"] = round(
            sorted(per_rep)[len(per_rep) // 2] / mc_ticks * 1e3, 3
        )
        # one host transfer per fleet leaf, not one per (leaf, scenario)
        host_b = [np.asarray(a) for a in jax.tree_util.tree_leaves(bstate)]
        sec["bit_equal"] = all(
            bool((hb[b2] == np.asarray(c)[0]).all())
            for b2, fin in enumerate(finals)
            for hb, c in zip(host_b, jax.tree_util.tree_leaves(fin))
        )
    except Exception as e:  # pragma: no cover - hardware-dependent
        out.setdefault("mc_chaos", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 1f: fleet_scale — batch axis ON the mesh vs batch-replicated -------
    # The r19 claim on real chips: sharding the replica axis itself
    # (make_fleet_mesh + the canonical partition table's batch prefix)
    # costs nothing per tick — scenarios are independent, GSPMD adds no
    # cross-batch collectives — while per-chip residency divides by the
    # batch factor.  A/B against the r12 batch-REPLICATED fleet at the
    # same config, bit_equal per scenario required.
    try:
        import functools as _ft

        from ringpop_tpu.sim import chaos, montecarlo, scenarios

        n_fl = int(os.environ.get("KSWEEP_FLEET_N", 16384))
        k_fl = 64
        fl_ticks = block
        n_dev = len(jax.devices())
        sec = {"n": n_fl, "k": k_fl, "block_ticks": fl_ticks, "n_devices": n_dev}
        out["fleet_scale"] = sec
        if n_dev <= 1 or out["platform"] == "cpu":
            sec["error"] = "needs >1 real device (batch axis has nothing to shard over)"
        else:
            rng3 = np.random.default_rng(2)
            fl_victims = sorted(rng3.choice(n_fl, size=8, replace=False).tolist())
            doses = scenarios.mc_churn_doses(n_dev * 4, n_fl // 32)
            plan, meta = scenarios.scenario_grid(
                n_fl, victims=fl_victims, doses=doses, losses=(0.0, 0.05),
                churn_seed=778,
            )
            seeds = scenarios.grid_seeds(meta, 0)
            b_fl = len(meta)
            sec["b"] = b_fl
            params_fl = lifecycle.LifecycleParams(
                n=n_fl, k=k_fl, suspect_ticks=10, rng="counter"
            )
            blk = jax.jit(
                _ft.partial(montecarlo._mc_block, params_fl),
                static_argnames="ticks",
            )
            from jax.sharding import Mesh

            rumor = 2 if n_dev % 2 == 0 else 1
            mesh_rep = Mesh(
                np.asarray(jax.devices()).reshape(n_dev // rumor, rumor),
                ("node", "rumor"),
            )
            mesh_batch = montecarlo.make_fleet_mesh(n_dev, (n_dev, 1, 1))
            sec["mesh_batch"] = f"{n_dev}x1x1 (batch x node x rumor)"
            sec["mesh_replicated"] = f"{n_dev // rumor}x{rumor} (node x rumor)"
            sides = {}
            for label, mesh in (("replicated", mesh_rep), ("sharded", mesh_batch)):
                st = montecarlo.init_replicas(params_fl, seeds, mesh=mesh)
                pl = jax.tree.map(
                    jax.device_put, plan,
                    montecarlo.fleet_faults_shardings(plan, mesh),
                )
                st = blk(st, pl, ticks=fl_ticks)
                jax.block_until_ready(st.learned)  # compile + warm block
                per_rep = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    st = blk(st, pl, ticks=fl_ticks)
                    jax.block_until_ready(st.learned)
                    per_rep.append(time.perf_counter() - t0)
                sec[f"{label}_ms_per_tick_median"] = round(
                    sorted(per_rep)[len(per_rep) // 2] / fl_ticks * 1e3, 3
                )
                sides[label] = st
                flush()
            # one host transfer per fleet leaf per side
            host_a = [np.asarray(x) for x in jax.tree_util.tree_leaves(sides["replicated"])]
            host_b = [np.asarray(x) for x in jax.tree_util.tree_leaves(sides["sharded"])]
            sec["bit_equal"] = all(
                bool((a == b).all()) for a, b in zip(host_a, host_b)
            )
    except Exception as e:  # pragma: no cover - hardware-dependent
        out.setdefault("fleet_scale", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 2+3: headline detection then convergence at the official config ----
    try:
        sim = lifecycle.LifecycleSim(n=n, k=k_head, seed=0)
        # warm exactly the device detect loop the timed run uses
        sim.run_until_detected(victims, faults, max_ticks=block, check_every=block)
        jax.block_until_ready(sim.state.learned)
        sim.state = lifecycle.init_state(sim.params, seed=0)
        t0 = time.perf_counter()
        ticks, ok = sim.run_until_detected(
            victims, faults, max_ticks=2048, check_every=block, time_budget_s=900
        )
        jax.block_until_ready(sim.state.learned)
        detect_wall = time.perf_counter() - t0
        out["detect_headline"] = {
            "n": n,
            "k": k_head,
            "n_victims": int(victims.size),
            "ticks": ticks,
            "detected": bool(ok),
            "wall_s": round(detect_wall, 3),
            "ms_per_tick_implied": round(detect_wall / max(ticks, 1) * 1e3, 3),
        }
        flush()
        t0 = time.perf_counter()
        c_ticks, c_ok = sim.run_until_converged(faults, max_ticks=4096, check_every=block)
        jax.block_until_ready(sim.state.learned)
        out["converge_after_detect"] = {
            "extra_ticks": c_ticks,
            "converged": bool(c_ok),
            "wall_s": round(time.perf_counter() - t0, 3),
            "total_ticks": ticks + c_ticks,
        }
        del sim
    except Exception as e:  # pragma: no cover
        # record the breadcrumb under whichever section was in flight — a
        # detect_headline that already landed must not swallow a converge
        # failure (the capture may be the only evidence from this window)
        err = {"error": f"{type(e).__name__}: {e}"[:300]}
        if "detect_headline" not in out:
            out["detect_headline"] = err
        else:
            out.setdefault("converge_after_detect", err)
    flush()

    # -- 4: delta rumor convergence at 1M and 16M ---------------------------
    for label, dn, dk in (
        ("delta_1m", n, 128),
        ("delta_16m", int(os.environ.get("KSWEEP_DELTA_N", 16_000_000)), 64),
    ):
        try:
            params = DeltaParams(n=dn, k=dk)
            run_until_converged(params, init_state(params, seed=0), max_ticks=8)  # warm
            state = init_state(params, seed=1)
            t0 = time.perf_counter()
            dstate, d_ticks, d_ok = run_until_converged(params, state, max_ticks=4096)
            jax.block_until_ready(dstate.learned)
            out[label] = {
                "n": dn,
                "k": dk,
                "ticks": d_ticks,
                "converged": bool(d_ok),
                "wall_s": round(time.perf_counter() - t0, 3),
            }
        except Exception as e:  # pragma: no cover
            out[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        flush()

    # -- 4b: sparse candidate selection vs the dense sort it replaced -------
    # (round 4: lifecycle._top_m_sparse compresses the sparse [N] candidate
    # vector before top_k; the dense form is a full stable sort.  Quantify
    # the gap on THIS platform so the on-chip tick model can attribute it.)
    try:
        cand_np = np.full(n, -1, np.int32)
        idx = rng.choice(n, max(2, n // 1000), replace=False)
        cand_np[idx] = rng.integers(0, 1 << 30, idx.size).astype(np.int32)
        cand = jnp.asarray(cand_np)
        m_sel = min(64, n)
        sparse_f = jax.jit(lambda c: lifecycle._top_m_sparse(c, m_sel))
        dense_f = jax.jit(lambda c: tuple(jax.lax.top_k(c, m_sel)))
        sec = {
            "n": n,
            "m": m_sel,
            "n_candidates": int(idx.size),
            # below the static floor both jits are the same dense program —
            # a reader must not attribute "no win, verified equal" to a
            # capture where the sparse branch never ran...
            "sparse_engaged": n
            > max(lifecycle._SPARSE_TOPK_CAP, lifecycle._SPARSE_TOPK_MIN_N),
            # ...and above it, a candidate count past the buffer takes the
            # runtime lax.cond full-sort fallback — e.g. KSWEEP_N=8M puts
            # n//1000 = 8000 candidates over the 4096 cap, and the timing
            # would price the fallback, not the compressed path
            "overflowed": int(idx.size) > lifecycle._SPARSE_TOPK_CAP,
        }
        out["sparse_topk"] = sec  # partial evidence survives a mid-section death
        last = {}
        for label, fn in (("sparse_ms", sparse_f), ("dense_sort_ms", dense_f)):
            jax.block_until_ready(fn(cand))  # compile
            t0 = time.perf_counter()
            for _ in range(max(reps, 3)):
                r = fn(cand)
            jax.block_until_ready(r)
            sec[label] = round((time.perf_counter() - t0) / max(reps, 3) * 1e3, 3)
            last[label] = r
            flush()
        (sv, si), (dv, di) = last["sparse_ms"], last["dense_sort_ms"]
        real = np.asarray(dv) >= 0
        sec["bit_equal"] = bool(
            np.array_equal(np.asarray(sv), np.asarray(dv))
            and np.array_equal(np.asarray(si)[real], np.asarray(di)[real])
        )
    except Exception as e:  # pragma: no cover
        # merge, don't replace: timings measured before a mid-section
        # tunnel death are evidence and must survive alongside the error
        out.setdefault("sparse_topk", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 5: sustained batched ring lookup -----------------------------------
    try:
        from ringpop_tpu.ops.ring_ops import build_ring_tokens, ring_lookup

        servers = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(4096)]
        tokens, owners = build_ring_tokens(servers, 256)
        batch = 1_000_000
        hashes = jnp.asarray(
            np.random.default_rng(0).integers(0, 2**32, size=batch, dtype=np.uint32)
        )

        @jax.jit
        def qps_loop(tokens, owners, hashes):
            def body(i, acc):
                o = ring_lookup(tokens, owners, hashes + i.astype(hashes.dtype))
                return acc + o.astype(jnp.uint32).sum()

            return jax.lax.fori_loop(0, 10, body, jnp.uint32(0))

        jax.block_until_ready(qps_loop(tokens, owners, hashes))
        t0 = time.perf_counter()
        jax.block_until_ready(qps_loop(tokens, owners, hashes))
        out["ring_lookup_qps"] = round(batch * 10 / (time.perf_counter() - t0), 0)
    except Exception as e:  # pragma: no cover
        out["ring_lookup_qps"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 5b: serve_lookup — the serve tier's capacity-padded shared-ring
    # dispatch (fused owners+generation transfer, the program the
    # micro-batching collector actually runs) vs the per-process host
    # bisect walk, bit_equal per key.  The serving claim on real HW: one
    # device dispatch amortized across frontends beats any number of
    # per-process bisect walkers; certify_cost_model judges the margin.
    try:
        from ringpop_tpu.serve.client import HostBisectFrontend
        from ringpop_tpu.serve.state import RingStore, serve_lookup_fused

        n_srv, rp = 4096, 256
        srv = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_srv)]
        sec = {"n_servers": n_srv, "replica_points": rp}
        out["serve_lookup"] = sec
        store = RingStore(srv, replica_points=rp)
        sring, _gen, _ns = store.snapshot()
        sb = 262_144
        sec["batch"] = sb
        shashes = np.random.default_rng(1).integers(
            0, 2**32, size=sb, dtype=np.uint32
        )
        dev_h = jnp.asarray(shashes)
        fused = serve_lookup_fused(sring, dev_h)
        jax.block_until_ready(fused)  # compile + warm
        sreps = max(reps, 3)
        t0 = time.perf_counter()
        for _ in range(sreps):
            fused = serve_lookup_fused(sring, dev_h)
        dev_owned = np.asarray(fused)[:sb]  # includes the host sync
        dt = (time.perf_counter() - t0) / sreps
        sec["device_qps"] = round(sb / dt, 0)
        sec["device_ms_per_batch"] = round(dt * 1e3, 3)
        bisect_fe = HostBisectFrontend(srv, rp)
        hb = shashes[:32_768]  # the scalar walk needs no 262k to price
        t0 = time.perf_counter()
        host_owned = bisect_fe.lookup_hashes(hb)
        sec["bisect_qps_per_process"] = round(
            hb.shape[0] / (time.perf_counter() - t0), 0
        )
        sec["bit_equal"] = bool(np.array_equal(dev_owned[: hb.shape[0]], host_owned))
        sec["amortization"] = round(
            sec["device_qps"] / max(sec["bisect_qps_per_process"], 1), 1
        )
    except Exception as e:  # pragma: no cover
        out.setdefault("serve_lookup", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 5c: serve_fanin — the r17 fused LookupN serve dispatch (owners +
    # R successors + generation, one transfer) on real HW, vs the host
    # LookupNUniqueAt walk per key, bit_equal per tuple.  The fan-in
    # claim: one amortized preference-list dispatch prices R successors
    # at nearly the single-owner dispatch's cost; the keys/s here is the
    # per-HOST number the serve mesh's scaling curve multiplies.  Judged
    # by certify_cost_model behind the TPU gate (the host-level mesh
    # digest certificate is the committed SIMBENCH_r11.json).
    try:
        from ringpop_tpu.ops.ring_ops import host_lookup_n
        from ringpop_tpu.serve.state import RingStore, serve_lookup_n_fused

        n_srv, rp, rn = 4096, 256, 3
        srv = [f"10.0.{i // 256}.{i % 256}:3000" for i in range(n_srv)]
        sec = {"n_servers": n_srv, "replica_points": rp, "n": rn}
        out["serve_fanin"] = sec
        store = RingStore(srv, replica_points=rp)
        sring, _gen, ns = store.snapshot()
        sb = 262_144
        sec["batch"] = sb
        shashes = np.random.default_rng(2).integers(
            0, 2**32, size=sb, dtype=np.uint32
        )
        dev_h = jnp.asarray(shashes)
        fused = serve_lookup_n_fused(sring, ns, dev_h, rn)
        jax.block_until_ready(fused)  # compile + warm every window
        sreps = max(reps, 3)
        t0 = time.perf_counter()
        for _ in range(sreps):
            fused = serve_lookup_n_fused(sring, ns, dev_h, rn)
        host = np.asarray(fused)  # includes the host sync
        dt = (time.perf_counter() - t0) / sreps
        sec["device_qps"] = round(sb / dt, 0)
        sec["device_ms_per_batch"] = round(dt * 1e3, 3)
        sec["gen_in_tail"] = int(host[-1]) == store.gen
        ht, ho, _hg, hns = store.snapshot_host()
        nb = 8192  # the python walk needs no 262k keys to price
        want = host_lookup_n(ht, ho, shashes[:nb], rn, hns)
        t0 = time.perf_counter()
        host_lookup_n(ht, ho, shashes[:nb], rn, hns)
        sec["host_walk_qps_per_process"] = round(
            nb / (time.perf_counter() - t0), 0
        )
        sec["bit_equal"] = bool(
            np.array_equal(host[: nb * rn].reshape(nb, rn), want)
        )
        sec["amortization"] = round(
            sec["device_qps"] / max(sec["host_walk_qps_per_process"], 1), 1
        )
    except Exception as e:  # pragma: no cover
        out.setdefault("serve_fanin", {})["error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    # -- 6: Pallas FarmHash vs jnp lowering ---------------------------------
    try:
        from ringpop_tpu.hashing.farm import pack_strings
        from ringpop_tpu.ops.hash_ops import fingerprint32_device
        from ringpop_tpu.ops.hash_pallas import fingerprint32_pallas

        nh = 262_144
        addrs = [
            f"10.{i % 256}.{(i >> 8) % 256}.{i % 100}:{3000 + i % 64}" for i in range(nh)
        ]
        mat, lens = pack_strings(addrs)
        mat, lens = jnp.asarray(mat), jnp.asarray(lens)
        for label, fn in (("farm_pallas", fingerprint32_pallas), ("farm_jnp", fingerprint32_device)):
            try:
                jax.block_until_ready(fn(mat, lens))  # compile
                t0 = time.perf_counter()
                for _ in range(5):
                    r = fn(mat, lens)
                jax.block_until_ready(r)
                dt = (time.perf_counter() - t0) / 5
                out[label] = {"s": round(dt, 5), "mhashes_per_s": round(nh / dt / 1e6, 1)}
            except Exception as e:  # pragma: no cover
                out[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
    except Exception as e:  # pragma: no cover
        out["farm_bench_error"] = f"{type(e).__name__}: {e}"[:300]
    flush()

    print(json.dumps(out))


if __name__ == "__main__":
    main()
