#!/bin/bash
# TPU tunnel watcher (VERDICT r3 item 1: the watcher must be in-tree and
# its captures admissible).  Run detached, e.g.:
#
#     make tpu-watch          # setsid + nohup, log to /tmp/tpu_watch.log
#
# The axon tunnel to the TPU is alive only in occasional windows; this
# loop probes every WATCH_INTERVAL_S seconds (in a subprocess — a wedged
# tunnel HANGS jax device init rather than raising) and, the moment the
# chip answers:
#
#   1. runs the driver bench (bench.py) and saves the JSON — wrapped with
#      git head, dirty flag, and UTC timestamp — to .tpu_bench_result.json
#      (which bench.py embeds as `tpu_watcher_capture` on CPU fallback
#      runs, staleness-guarded) AND to captures/tpu_bench_<ts>.json;
#   2. runs scripts/tpu_ksweep.py (per-tick cost model, detection +
#      convergence headline, delta 1M/16M, ring qps, Pallas hash), which
#      writes .tpu_ksweep.json + captures/tpu_ksweep_<ts>.json itself;
#   3. commits the captures (best-effort, with index-lock retries) so the
#      evidence is in history even if the session is busy elsewhere.
#
# All captures are committed files, not gitignored scratch.
set -u
cd "$(dirname "$0")/.." || exit 1

# single-instance guard: two watchers would double-run the bench in a
# live window and race the capture commits (the lock dies with the
# holder, so a crashed watcher never wedges a later launch)
# children are spawned with 9>&- so an orphaned grandchild (e.g. a
# bench subprocess outliving its timeout'd parent) cannot keep the lock
# held after the watcher itself dies
exec 9>/tmp/tpu_watch.lock
if ! flock -n 9; then
  echo "another watcher holds /tmp/tpu_watch.lock; exiting"
  exit 0
fi

ATTEMPTS=${WATCH_ATTEMPTS:-230}
INTERVAL=${WATCH_INTERVAL_S:-180}
BENCH_TIMEOUT=${WATCH_BENCH_TIMEOUT_S:-2400}
KSWEEP_TIMEOUT=${WATCH_KSWEEP_TIMEOUT_S:-2400}

ts() { date -u +%FT%TZ; }

for i in $(seq 1 "$ATTEMPTS"); do
  alive=$(timeout 110 python 9>&- -c "
from ringpop_tpu.util.accel import probe_accelerator
p = probe_accelerator(timeouts_s=(75,))
print('yes' if p['alive'] and p.get('platform') not in ('cpu', None) else 'no')
" 2>/dev/null | tail -1)
  # one line per probe: the committed log must be auditable evidence of
  # "N probes over M hours, zero windows", not silence (VERDICT r4 item 1)
  echo "[$(ts)] probe $i/$ATTEMPTS: ${alive:-no}"
  if [ "${alive:-no}" = "yes" ]; then
    echo "[$(ts)] tunnel alive at attempt $i; running bench.py"
    BENCH_PROBE_TIMEOUTS_S=75 timeout "$BENCH_TIMEOUT" python bench.py 9>&- \
      2>/tmp/tpu_watch_bench_stderr.log | tail -1 >/tmp/tpu_watch_bench_raw.json
    if [ -s /tmp/tpu_watch_bench_raw.json ] \
        && grep -q '"platform"' /tmp/tpu_watch_bench_raw.json \
        && ! grep -q '"platform": "cpu"' /tmp/tpu_watch_bench_raw.json; then
      python 9>&- - <<'EOF'
import json, os, subprocess, time
repo = os.getcwd()
r = json.load(open("/tmp/tpu_watch_bench_raw.json"))
git = lambda *a: subprocess.run(["git", "-C", repo, *a],
                                capture_output=True, text=True).stdout.strip()
ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
cap = {"captured_at": ts, "captured_by": "scripts/tpu_watch.sh",
       "git_head": git("rev-parse", "HEAD"),
       "git_dirty": bool(git("status", "--porcelain")), "result": r}
blob = json.dumps(cap, indent=1)
open(os.path.join(repo, ".tpu_bench_result.json"), "w").write(blob)
os.makedirs(os.path.join(repo, "captures"), exist_ok=True)
open(os.path.join(repo, "captures",
     f"tpu_bench_{ts.replace(':', '').replace('-', '')}.json"), "w").write(blob)
EOF
      echo "[$(ts)] bench captured:"; cat /tmp/tpu_watch_bench_raw.json
      # commit the bench capture IMMEDIATELY: ksweep + the accel suite
      # can run another ~30-60 min, and a window caught near the end of
      # a round must still leave committed evidence even if the rest of
      # the sweep outlives the session
      if git add captures .tpu_bench_result.json 2>/dev/null 9>&- \
          && git commit --only captures --only .tpu_bench_result.json 9>&- \
               -m "Record TPU watcher bench capture $(ts)" \
               -m "No-Verification-Needed: data-only capture artifact from make tpu-watch" \
               2>/dev/null; then
        echo "[$(ts)] bench capture committed (early)"
      fi
      echo "[$(ts)] running ksweep"
      timeout "$KSWEEP_TIMEOUT" python scripts/tpu_ksweep.py 9>&- \
        2>/tmp/tpu_watch_ksweep_stderr.log
      echo "[$(ts)] ksweep done (rc=$?); running hardware test suite"
      timeout 1200 python -m pytest tests_accel/ -q 9>&- \
        >/tmp/tpu_watch_accel_tests.log 2>&1
      echo "[$(ts)] test-accel rc=$? ($(tail -1 /tmp/tpu_watch_accel_tests.log)); committing captures"
      paths="captures"
      [ -f .tpu_bench_result.json ] && paths="$paths .tpu_bench_result.json"
      [ -f .tpu_ksweep.json ] && paths="$paths .tpu_ksweep.json"
      for try in 1 2 3 4 5; do
        # shellcheck disable=SC2086  # $paths is a deliberate word list
        if git add $paths 2>/dev/null 9>&- \
            && git commit --only $paths 9>&- \
                 -m "Record TPU watcher captures $(ts)" \
                 -m "No-Verification-Needed: data-only capture artifacts from make tpu-watch" \
                 2>/dev/null; then
          echo "[$(ts)] captures committed"
          break
        fi
        echo "[$(ts)] git busy (attempt $try), retrying in 20s"
        sleep 20
      done
      exit 0
    fi
    echo "[$(ts)] bench attempt failed or fell back to cpu; stderr tail:"
    tail -3 /tmp/tpu_watch_bench_stderr.log
  fi
  # 9>&- here too: an orphaned interval sleep would otherwise hold the
  # flock for up to INTERVAL seconds after the watcher itself dies,
  # blocking an immediate relaunch
  sleep "$INTERVAL" 9>&-
done
echo "[$(ts)] tunnel never revived after $ATTEMPTS attempts"
exit 1
