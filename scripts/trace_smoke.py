"""trace-smoke — the CI gate for r20 span tracing (obs/trace.py).

Three legs, all correctness-only:

1. **Chain reconstruction from the journal alone**: a 2-rank block-
   routed serve plane (LocalNetwork) with a quorum reader runs traced;
   every span lands in a real JSONL journal via
   ``TelemetryJournal.span``; for every key whose owner is remote the
   chain frontend route → per-owner forward RPC → receive-side handle
   (and the quorum wave → per-owner read legs) must reconstruct from
   the parsed journal records, with every forward span's ``hops`` field
   equal to the ``ringpop-hops`` header value its downstream server/
   handle spans observed.
2. **Rerun determinism**: the identical workload traced twice produces
   the identical set of (trace, span, parent, leg) tuples — sampling
   and ids are pure functions of the key hashes, so reruns trace the
   SAME requests.
3. **Serve-mesh bit-transparency**: a P=2 serve mesh with spans enabled
   lands digests identical to the untraced twin and the P=1 oracle, and
   every cross-rank ``mesh_answer`` span joins its sender's
   ``mesh_request`` span by DERIVED parent id (no header crosses the
   fabric) carrying the mesh generation.

Exit 0 on success, 1 with a diagnosis on any failure.  A few seconds —
wired into ``make test``.

Usage:
    python scripts/trace_smoke.py
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _traced_run(tracer):
    """One traced serve-plane workload; returns (owners, gens, wave)."""
    import numpy as np

    from ringpop_tpu.forward.batch import (
        BatchForwarder,
        BlockRouter,
        QuorumReader,
    )
    from ringpop_tpu.net.channel import LocalChannel, LocalNetwork
    from ringpop_tpu.ops.ring_ops import build_ring_tokens

    servers = [f"10.41.0.{i}:3000" for i in range(2)]
    toks, owns = build_ring_tokens(servers, 8)
    tokens = np.asarray(toks, np.uint32)
    owners = np.asarray(owns, np.int32)

    def lookup(h, n):
        idx = np.searchsorted(tokens, np.asarray(h, np.uint32), side="left")
        idx = np.where(idx >= tokens.shape[0], 0, idx)
        return np.asarray(owners[idx], np.int32), 3

    net = LocalNetwork(seed=0)
    for rank, addr in enumerate(servers):
        chan = LocalChannel(net, addr, app="serve")
        chan.tracer = tracer
        router = BlockRouter(
            rank, 2, lambda: tokens, lookup, servers,
            BatchForwarder(chan, tracer=tracer),
        )
        chan.register("serve", "/lookup", router.handler())
    client = LocalChannel(net, "10.41.0.99:1", app="cli")
    cfwd = BatchForwarder(client, tracer=tracer)
    frontend = BlockRouter(0, 2, lambda: tokens, lookup, servers, cfwd)
    reader = QuorumReader(cfwd, servers, r=2)

    hashes = np.asarray(
        [0x00000010, 0x40000000, 0x80000000, 0xC0000000], np.uint32
    )

    async def go():
        o, g = await frontend.route(hashes, n=1)
        wave = await reader.quorum_wave(tokens, owners, 2, hashes, salt=1)
        return o, g, wave

    loop = asyncio.new_event_loop()
    try:
        o, g, wave = loop.run_until_complete(go())
    finally:
        loop.close()
    from ringpop_tpu.forward.batch import rank_of_hashes

    return hashes, rank_of_hashes(tokens, hashes, 2), g, wave


def main() -> int:
    import numpy as np

    from ringpop_tpu.obs import trace as tracemod
    from ringpop_tpu.sim import telemetry

    failures: list[str] = []
    tmp = tempfile.mkdtemp(prefix="tracesmoke_")

    # -- leg 1: chain from the journal alone ----------------------------------
    journal_path = os.path.join(tmp, "trace.jsonl")
    with telemetry.TelemetryJournal(journal_path) as journal:
        journal.header("serve", "trace_smoke", {})
        tracer = tracemod.Tracer(journal.span, sample=1)
        hashes, owner_ranks, gens, wave = _traced_run(tracer)
    records = telemetry.read_journal(journal_path)
    spans = [r for r in records if r["kind"] == "span"]
    if not spans:
        failures.append("no span records landed in the journal")
    if not wave["answers_agree"] or wave["quorum_ok_frac"] < 1.0:
        failures.append(f"quorum wave did not hold: {wave}")

    forwarded = 0
    for key, owner_rank in zip(hashes.tolist(), owner_ranks.tolist()):
        ch = tracemod.chain(records, tracemod.trace_id_of(key))
        legs = [s["leg"] for s in ch]
        if not ch or legs[0] != "route" or ch[0]["parent"] is not None:
            failures.append(f"key {key:#x}: chain does not root at the "
                            f"frontend route: {legs}")
            continue
        if "quorum_wave" not in legs:
            failures.append(f"key {key:#x}: quorum-read leg missing: {legs}")
        if owner_rank != 0:
            forwarded += 1
            if "forward" not in legs or "handle" not in legs:
                failures.append(
                    f"key {key:#x}: forwarded chain incomplete: {legs}"
                )
        # the acceptance join: forward spans' hops == the ringpop-hops
        # value their downstream server/handle spans carried
        for s in ch:
            if s["leg"] != "forward":
                continue
            kids = [k for k in ch if k.get("parent") == s["span"]
                    and k["leg"] in ("server", "handle")]
            if not kids:
                failures.append(
                    f"key {key:#x}: forward span {s['span']} has no "
                    "downstream server/handle record"
                )
            for k in kids:
                if k["hops"] != s["hops"]:
                    failures.append(
                        f"key {key:#x}: hop mismatch — forward span says "
                        f"{s['hops']}, downstream {k['leg']} saw {k['hops']}"
                    )
    if forwarded == 0:
        failures.append("workload forwarded no keys — the smoke is vacuous")

    # -- leg 2: rerun determinism ---------------------------------------------
    rerun: list[dict] = []
    _traced_run(tracemod.Tracer(rerun.append, sample=1))
    ids = lambda rs: sorted(  # noqa: E731
        (s["trace"], s["span"], s.get("parent"), s["leg"])
        for s in rs if s.get("kind") == "span"
    )
    if ids(spans) != ids(rerun):
        failures.append(
            "rerun produced different span ids — sampling/ids are not a "
            f"pure function of the keys ({len(spans)} vs {len(rerun)} spans)"
        )

    # -- leg 3: serve-mesh bit-transparency + derived-parent join -------------
    from ringpop_tpu.serve.mesh import run_serve_mesh

    cfg = dict(n_servers=8, replica_points=16, n=3, streams=4, rounds=2,
               keys_per_stream=256, seed=3)
    oracle = run_serve_mesh(1, **cfg)[0]["digest"]
    base = run_serve_mesh(2, **cfg)
    mesh_spans: list[dict] = []
    traced = run_serve_mesh(2, trace_sink=mesh_spans.append,
                            trace_sample=32, **cfg)
    if {r["digest"] for r in base} != {oracle}:
        failures.append(f"untraced mesh digests diverge from oracle {oracle}")
    if {r["digest"] for r in traced} != {oracle}:
        failures.append(
            f"TRACED mesh digests diverge from oracle {oracle}: "
            f"{[r['digest'] for r in traced]} — tracing is not host-only"
        )
    reqs = {r["span"]: r for r in mesh_spans if r["leg"] == "mesh_request"}
    answers = [r for r in mesh_spans if r["leg"] == "mesh_answer"]
    if not answers:
        failures.append("mesh produced no answer spans at sample=32")
    for a in answers:
        mate = reqs.get(a["parent"])
        if mate is None or mate["trace"] != a["trace"]:
            failures.append(
                f"mesh_answer span {a['span']} does not join its sender's "
                "mesh_request by derived parent id"
            )
        elif a.get("gen") != 0:
            failures.append(f"mesh_answer span carries gen {a.get('gen')}")

    if failures:
        print("trace-smoke: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(json.dumps({
        "trace_smoke": {
            "spans_journaled": len(spans),
            "keys_forwarded": forwarded,
            "rerun_deterministic": True,
            "mesh_digest": oracle,
            "mesh_answer_spans": len(answers),
        }
    }))
    print("trace-smoke: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
