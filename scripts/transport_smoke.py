"""transport-smoke: the CI gate for the r21 one-transport-plane refactor.

ONE script drives all four traffic families through the unified
transport — serve lookups (shm ring + the channel folded onto the
fabric's RPC plane), a gossip-style window exchange, an obs-class
snapshot exchange, and a mesh-style batch forward — and asserts the
refactor's contracts:

* digests: owners from every transport lane are bit-identical to the
  pre-refactor host-bisect oracle (sha256 over the owner bytes);
* merged ledger: every class row of the shared ``TransportLedger``
  equals the transport's own legacy counters — "exchange"/"obs" mirror
  ``Fabric.wire_stats`` exactly, "rpc" equals the channel's legacy body
  bytes plus the 16 B/frame fabric header (the OBSERVABILITY.md
  migration mapping), and the ledger total is the sum of its classes;
* zero-copy: ``copy_bytes`` reads 0 for the shm→dispatch path (and
  everywhere else — no transport in the plane takes an intermediate
  copy it has to confess).
"""

import hashlib
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _digest(arr) -> str:
    import numpy as np

    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def main() -> int:
    import asyncio

    import numpy as np

    from ringpop_tpu.forward.batch import BatchForwarder
    from ringpop_tpu.net import TCPChannel
    from ringpop_tpu.parallel.fabric import (
        _HDR,
        Fabric,
        LocalKV,
        TransportLedger,
    )
    from ringpop_tpu.serve.bench import ServiceThread
    from ringpop_tpu.serve.client import HostBisectFrontend, ServeClient
    from ringpop_tpu.serve.shm import ShmClient
    from ringpop_tpu.serve.state import RingStore

    failures: list[str] = []
    shared = TransportLedger()

    # -- serve lookups: shm ring (zero-copy) + folded TCP channel ---------
    servers = [f"10.9.0.{i}:3000" for i in range(32)]
    store = RingStore(servers, replica_points=10)
    th = ServiceThread(store, flush_us=0.0, shm_slots=2, shm_key_cap=4096,
                       shm_max_n=4, ledger=shared)
    th.start()
    h = np.random.default_rng(7).integers(0, 2**32, size=600, dtype=np.uint32)
    oracle = _digest(HostBisectFrontend(servers, 10).lookup_hashes(h))

    name, sock, slots, cap, max_n = th.shm_address()
    cl = ShmClient(name, sock, 0, slots=slots, key_cap=cap, max_n=max_n)
    owners_shm, gen_shm = cl.lookup_hashes(h)  # >64 keys: collector lane
    owners_b1, _ = cl.lookup_hashes(h[:8])  # <=64: B=1 direct lane
    cl.close()
    if _digest(owners_shm) != oracle:
        failures.append("shm collector-lane owners diverged from the oracle")
    if _digest(owners_b1) != _digest(
        HostBisectFrontend(servers, 10).lookup_hashes(h[:8])
    ):
        failures.append("shm B=1 direct-lane owners diverged from the oracle")

    async def tcp_leg():
        chan = TCPChannel(app="smoke", ledger=shared)
        sc = ServeClient(chan, th.hostport)
        o_tcp, g = await sc.lookup_hashes(h)
        # mesh-style forward: the reference HandleOrForward RPC shape,
        # retries + hop guard, over the same folded channel
        fwd = BatchForwarder(chan, fabric_arrays=True)
        o_fwd, g2 = await fwd.forward_batch(th.hostport, h)
        legacy = dict(chan.wire_stats())
        await chan.close()
        return o_tcp, g, o_fwd, g2, legacy, fwd.stats()

    o_tcp, gen_tcp, o_fwd, gen_fwd, cli_legacy, fwd_stats = (
        asyncio.new_event_loop().run_until_complete(tcp_leg())
    )
    if _digest(o_tcp) != oracle:
        failures.append("TCP (folded channel) owners diverged from the oracle")
    if _digest(o_fwd) != oracle:
        failures.append("mesh forward owners diverged from the oracle")
    if not (gen_shm == gen_tcp == gen_fwd):
        failures.append(
            f"generation skew across transports: shm={gen_shm} "
            f"tcp={gen_tcp} fwd={gen_fwd}"
        )
    if fwd_stats["rpcs"] != 1 or fwd_stats["retries"] != 0:
        failures.append(f"forward took retries on a healthy link: {fwd_stats}")
    srv_legacy = dict(th.channel.wire_stats())
    th.stop()

    # -- gossip window exchange + obs snapshot on fabric pairs ------------
    def fabric_pair(klass: str, ns: str, ticks: int, width: int):
        kv = LocalKV()
        legacy = [None, None]
        sent = [None, None]
        got = [None, None]
        errs: list[BaseException] = []

        def run(rank: int):
            try:
                with Fabric(rank, 2, kv, namespace=ns, timeout_ms=60_000,
                            ledger=shared, ledger_class=klass) as fab:
                    peer = 1 - rank
                    rng = np.random.default_rng(40 + rank)
                    mine, theirs = [], []
                    for tick in range(ticks):
                        arrs = [rng.integers(0, 2**32, width,
                                             dtype=np.uint32)]
                        mine.append(arrs[0])
                        res = fab.exchange_async(
                            tick + 1, {peer: arrs}, [peer]
                        ).wait()
                        theirs.append(res[peer][0])
                    legacy[rank] = fab.wire_stats()
                    sent[rank] = mine
                    got[rank] = theirs
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        if errs or any(t.is_alive() for t in ts):
            failures.append(f"{klass} fabric pair failed: {errs}")
            return
        for rank in (0, 1):
            want = [_digest(a) for a in sent[1 - rank]]
            have = [_digest(a) for a in got[rank]]
            if want != have:
                failures.append(f"{klass} exchange payloads corrupted")
        row = shared.stats()["classes"].get(klass, {})
        leg = {
            k: legacy[0][k] + legacy[1][k]
            for k in ("bytes_sent", "bytes_recv",
                      "raw_bytes_sent", "raw_bytes_recv")
        }
        for k, v in leg.items():
            if row.get(k) != v:
                failures.append(
                    f"ledger class {klass!r} {k}={row.get(k)} != "
                    f"legacy fabric sum {v}"
                )

    fabric_pair("exchange", "transport-smoke-gossip", ticks=4, width=1 << 12)
    fabric_pair("obs", "transport-smoke-obs", ticks=2, width=257)

    # -- merged-ledger contracts ------------------------------------------
    st = shared.stats()
    classes = st["classes"]
    want_classes = {"rpc", "shm", "exchange", "obs"}
    if set(classes) != want_classes:
        failures.append(
            f"ledger classes {sorted(classes)} != {sorted(want_classes)}"
        )

    # rpc row == legacy channel counters (body bytes) + 16 B/frame header.
    # Client and server channels share the ledger, so the row sums both.
    rpc = classes.get("rpc", {})
    legacy_frames = cli_legacy["frames_sent"] + srv_legacy["frames_sent"]
    legacy_bytes = cli_legacy["bytes_sent"] + srv_legacy["bytes_sent"]
    if rpc.get("frames_sent") != legacy_frames:
        failures.append(
            f"rpc frames_sent {rpc.get('frames_sent')} != legacy "
            f"channel frame sum {legacy_frames}"
        )
    if rpc.get("bytes_sent") != legacy_bytes + _HDR.size * legacy_frames:
        failures.append(
            f"rpc bytes_sent {rpc.get('bytes_sent')} != legacy "
            f"{legacy_bytes} + {_HDR.size}*{legacy_frames}"
        )
    if rpc.get("frames_recv") != legacy_frames:  # both ends on one ledger
        failures.append("rpc frames_recv != frames_sent on a shared ledger")

    # shm row: request/response words accounted, NOTHING copied
    shm_row = classes.get("shm", {})
    if shm_row.get("frames_recv", 0) < 2 or shm_row.get("frames_sent", 0) < 2:
        failures.append(f"shm ring traffic unaccounted: {shm_row}")
    if shm_row.get("bytes_recv") != (600 + 8) * 4:
        failures.append(
            f"shm bytes_recv {shm_row.get('bytes_recv')} != request words"
        )

    # zero-copy: nothing in the whole plane confessed an intermediate copy
    if st["copy_bytes"] != 0:
        failures.append(f"copy_bytes {st['copy_bytes']} != 0 — a transport "
                        "took an intermediate copy")

    # total == sum of classes (the merge is lossless)
    for k in ("bytes_sent", "bytes_recv", "frames_sent", "frames_recv"):
        if st["total"][k] != sum(row[k] for row in classes.values()):
            failures.append(f"ledger total[{k}] != sum of class rows")

    if failures:
        print("transport-smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(
        "transport-smoke OK: serve(shm+tcp)/gossip/obs/forward digests == "
        f"oracle; ledger classes {sorted(classes)} reconcile with legacy "
        f"counters; copy_bytes=0 "
        f"(total {st['total']['bytes_sent']}B sent / "
        f"{st['total']['bytes_recv']}B recv)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
