"""transport-smoke: the CI gate for the r21 one-transport-plane refactor.

ONE script drives all four traffic families through the unified
transport — serve lookups (shm ring + the channel folded onto the
fabric's RPC plane), a gossip-style window exchange, an obs-class
snapshot exchange, and a mesh-style batch forward — and asserts the
refactor's contracts:

* digests: owners from every transport lane are bit-identical to the
  pre-refactor host-bisect oracle (sha256 over the owner bytes);
* merged ledger: every class row of the shared ``TransportLedger``
  equals the transport's own legacy counters — "exchange"/"obs" mirror
  ``Fabric.wire_stats`` exactly, "rpc" equals the channel's legacy body
  bytes plus the 16 B/frame fabric header (the OBSERVABILITY.md
  migration mapping), and the ledger total is the sum of its classes;
* zero-copy: ``copy_bytes`` reads 0 for the shm→dispatch path (and
  everywhere else — no transport in the plane takes an intermediate
  copy it has to confess);
* r23 latency tiers: every lane combination (tcp / tcp+coalescing /
  shm / shm+coalescing) echoes bit-identical bodies, the new lane
  counters are LIVE (``inline_completions`` on the sync echo leg,
  shm-lane frames on a same-host pair, ``coalesced_frames`` under
  threaded burst load), and per-lane ledger sums reconcile exactly
  with the per-class totals.
"""

import hashlib
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _digest(arr) -> str:
    import numpy as np

    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def main() -> int:
    import asyncio

    import numpy as np

    from ringpop_tpu.forward.batch import BatchForwarder
    from ringpop_tpu.net import TCPChannel
    from ringpop_tpu.parallel.fabric import (
        _HDR,
        Fabric,
        LocalKV,
        TransportLedger,
    )
    from ringpop_tpu.serve.bench import ServiceThread
    from ringpop_tpu.serve.client import HostBisectFrontend, ServeClient
    from ringpop_tpu.serve.shm import ShmClient
    from ringpop_tpu.serve.state import RingStore

    failures: list[str] = []
    shared = TransportLedger()

    # -- serve lookups: shm ring (zero-copy) + folded TCP channel ---------
    servers = [f"10.9.0.{i}:3000" for i in range(32)]
    store = RingStore(servers, replica_points=10)
    th = ServiceThread(store, flush_us=0.0, shm_slots=2, shm_key_cap=4096,
                       shm_max_n=4, ledger=shared)
    th.start()
    h = np.random.default_rng(7).integers(0, 2**32, size=600, dtype=np.uint32)
    oracle = _digest(HostBisectFrontend(servers, 10).lookup_hashes(h))

    name, sock, slots, cap, max_n = th.shm_address()
    cl = ShmClient(name, sock, 0, slots=slots, key_cap=cap, max_n=max_n)
    owners_shm, gen_shm = cl.lookup_hashes(h)  # >64 keys: collector lane
    owners_b1, _ = cl.lookup_hashes(h[:8])  # <=64: B=1 direct lane
    cl.close()
    if _digest(owners_shm) != oracle:
        failures.append("shm collector-lane owners diverged from the oracle")
    if _digest(owners_b1) != _digest(
        HostBisectFrontend(servers, 10).lookup_hashes(h[:8])
    ):
        failures.append("shm B=1 direct-lane owners diverged from the oracle")

    async def tcp_leg():
        chan = TCPChannel(app="smoke", ledger=shared)
        sc = ServeClient(chan, th.hostport)
        o_tcp, g = await sc.lookup_hashes(h)
        # mesh-style forward: the reference HandleOrForward RPC shape,
        # retries + hop guard, over the same folded channel
        fwd = BatchForwarder(chan, fabric_arrays=True)
        o_fwd, g2 = await fwd.forward_batch(th.hostport, h)
        legacy = dict(chan.wire_stats())
        await chan.close()
        return o_tcp, g, o_fwd, g2, legacy, fwd.stats()

    o_tcp, gen_tcp, o_fwd, gen_fwd, cli_legacy, fwd_stats = (
        asyncio.new_event_loop().run_until_complete(tcp_leg())
    )
    if _digest(o_tcp) != oracle:
        failures.append("TCP (folded channel) owners diverged from the oracle")
    if _digest(o_fwd) != oracle:
        failures.append("mesh forward owners diverged from the oracle")
    if not (gen_shm == gen_tcp == gen_fwd):
        failures.append(
            f"generation skew across transports: shm={gen_shm} "
            f"tcp={gen_tcp} fwd={gen_fwd}"
        )
    if fwd_stats["rpcs"] != 1 or fwd_stats["retries"] != 0:
        failures.append(f"forward took retries on a healthy link: {fwd_stats}")
    srv_legacy = dict(th.channel.wire_stats())
    th.stop()

    # -- gossip window exchange + obs snapshot on fabric pairs ------------
    def fabric_pair(klass: str, ns: str, ticks: int, width: int):
        kv = LocalKV()
        legacy = [None, None]
        sent = [None, None]
        got = [None, None]
        errs: list[BaseException] = []

        def run(rank: int):
            try:
                with Fabric(rank, 2, kv, namespace=ns, timeout_ms=60_000,
                            ledger=shared, ledger_class=klass) as fab:
                    peer = 1 - rank
                    rng = np.random.default_rng(40 + rank)
                    mine, theirs = [], []
                    for tick in range(ticks):
                        arrs = [rng.integers(0, 2**32, width,
                                             dtype=np.uint32)]
                        mine.append(arrs[0])
                        res = fab.exchange_async(
                            tick + 1, {peer: arrs}, [peer]
                        ).wait()
                        theirs.append(res[peer][0])
                    legacy[rank] = fab.wire_stats()
                    sent[rank] = mine
                    got[rank] = theirs
            except BaseException as e:
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        if errs or any(t.is_alive() for t in ts):
            failures.append(f"{klass} fabric pair failed: {errs}")
            return
        for rank in (0, 1):
            want = [_digest(a) for a in sent[1 - rank]]
            have = [_digest(a) for a in got[rank]]
            if want != have:
                failures.append(f"{klass} exchange payloads corrupted")
        row = shared.stats()["classes"].get(klass, {})
        leg = {
            k: legacy[0][k] + legacy[1][k]
            for k in ("bytes_sent", "bytes_recv",
                      "raw_bytes_sent", "raw_bytes_recv")
        }
        for k, v in leg.items():
            if row.get(k) != v:
                failures.append(
                    f"ledger class {klass!r} {k}={row.get(k)} != "
                    f"legacy fabric sum {v}"
                )

    fabric_pair("exchange", "transport-smoke-gossip", ticks=4, width=1 << 12)
    fabric_pair("obs", "transport-smoke-obs", ticks=2, width=257)

    # -- merged-ledger contracts ------------------------------------------
    st = shared.stats()
    classes = st["classes"]
    want_classes = {"rpc", "shm", "exchange", "obs"}
    if set(classes) != want_classes:
        failures.append(
            f"ledger classes {sorted(classes)} != {sorted(want_classes)}"
        )

    # rpc row == legacy channel counters (body bytes) + 16 B/frame header.
    # Client and server channels share the ledger, so the row sums both.
    rpc = classes.get("rpc", {})
    legacy_frames = cli_legacy["frames_sent"] + srv_legacy["frames_sent"]
    legacy_bytes = cli_legacy["bytes_sent"] + srv_legacy["bytes_sent"]
    if rpc.get("frames_sent") != legacy_frames:
        failures.append(
            f"rpc frames_sent {rpc.get('frames_sent')} != legacy "
            f"channel frame sum {legacy_frames}"
        )
    if rpc.get("bytes_sent") != legacy_bytes + _HDR.size * legacy_frames:
        failures.append(
            f"rpc bytes_sent {rpc.get('bytes_sent')} != legacy "
            f"{legacy_bytes} + {_HDR.size}*{legacy_frames}"
        )
    if rpc.get("frames_recv") != legacy_frames:  # both ends on one ledger
        failures.append("rpc frames_recv != frames_sent on a shared ledger")

    # shm row: request/response words accounted, NOTHING copied
    shm_row = classes.get("shm", {})
    if shm_row.get("frames_recv", 0) < 2 or shm_row.get("frames_sent", 0) < 2:
        failures.append(f"shm ring traffic unaccounted: {shm_row}")
    if shm_row.get("bytes_recv") != (600 + 8) * 4:
        failures.append(
            f"shm bytes_recv {shm_row.get('bytes_recv')} != request words"
        )

    # zero-copy: nothing in the whole plane confessed an intermediate copy
    if st["copy_bytes"] != 0:
        failures.append(f"copy_bytes {st['copy_bytes']} != 0 — a transport "
                        "took an intermediate copy")

    # total == sum of classes (the merge is lossless)
    for k in ("bytes_sent", "bytes_recv", "frames_sent", "frames_recv"):
        if st["total"][k] != sum(row[k] for row in classes.values()):
            failures.append(f"ledger total[{k}] != sum of class rows")

    # -- r23 latency tiers: lane combinations, live counters, lane sums ---
    # each combination gets its OWN ledger (the legacy-vs-ledger equality
    # above is pinned on default channels; sync legs + shm control frames
    # are the r23 additions it deliberately excludes)
    blob = np.random.default_rng(23).integers(
        0, 256, size=2048, dtype=np.uint8
    ).tobytes()
    payload = {"blob": blob, "k": 23}
    lane_digests: dict[str, str] = {}
    lane_stats: dict[str, dict] = {}

    def tier_leg(tag: str, burst: bool = False, **kw) -> None:
        led = TransportLedger()
        server = TCPChannel(app=f"tier-{tag}", codec="msgpack",
                            ledger=led, **kw)
        server.register("tier", "/echo", lambda b, h: b)
        client = TCPChannel(app=f"tier-{tag}-cli", codec="msgpack",
                            ledger=led, **kw)
        try:
            addr = server.listen_sync("127.0.0.1", 0)
            if kw.get("shm_lane"):
                # negotiation is async: echo until a frame rides the ring
                deadline = time.time() + 10
                while not (led.stats()["classes"].get("rpc", {})
                           .get("lanes", {}).get("shm", {})
                           .get("frames_sent", 0)):
                    if time.time() > deadline:
                        failures.append(f"tier {tag}: shm lane never engaged")
                        return
                    client.call_sync(addr, "tier", "/echo", {"w": 1},
                                     timeout=10)
            if burst:
                def caller():
                    for _ in range(20):
                        r = client.call_sync(addr, "tier", "/echo", payload,
                                             timeout=10)
                        if r["blob"] != blob:
                            failures.append(f"tier {tag}: burst echo corrupt")
                ts = [threading.Thread(target=caller) for _ in range(6)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(60)
            res = client.call_sync(addr, "tier", "/echo", payload, timeout=10)
            lane_digests[tag] = hashlib.sha256(res["blob"]).hexdigest()
            lane_stats[tag] = led.stats()
        finally:
            client.close_sync()
            server.close_sync()

    tier_leg("tcp")
    tier_leg("tcp+coalesce", burst=True, flush_us=1500.0)
    tier_leg("shm", shm_lane=True)
    tier_leg("shm+coalesce", burst=True, shm_lane=True, flush_us=1500.0)

    want = hashlib.sha256(blob).hexdigest()
    for tag, dig in lane_digests.items():
        if dig != want:
            failures.append(f"tier {tag}: echoed bytes diverged (digest)")
    if len(set(lane_digests.values())) > 1:
        failures.append("lane combinations answered non-identical bytes")

    def rpc_row(tag):
        return lane_stats.get(tag, {}).get("classes", {}).get("rpc", {})

    if rpc_row("tcp").get("inline_completions", 0) < 1:
        failures.append("inline_completions == 0 on the sync echo leg")
    if (rpc_row("shm").get("lanes", {}).get("shm", {})
            .get("frames_sent", 0)) < 1:
        failures.append("shm-lane frames == 0 on a same-host pair")
    if rpc_row("tcp+coalesce").get("coalesced_frames", 0) < 1:
        failures.append("coalesced_frames == 0 under burst load")
    for tag, stl in lane_stats.items():
        for klass, row in stl["classes"].items():
            for field in TransportLedger.FIELDS:
                if row[field] != sum(
                    r[field] for r in row["lanes"].values()
                ):
                    failures.append(
                        f"tier {tag}: class {klass!r} {field} != lane sum"
                    )
        if stl["copy_bytes"] != 0:
            failures.append(f"tier {tag}: copy_bytes {stl['copy_bytes']} != 0")

    if failures:
        print("transport-smoke FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(
        "transport-smoke OK: serve(shm+tcp)/gossip/obs/forward digests == "
        f"oracle; ledger classes {sorted(classes)} reconcile with legacy "
        f"counters; copy_bytes=0 "
        f"(total {st['total']['bytes_sent']}B sent / "
        f"{st['total']['bytes_recv']}B recv); "
        f"r23 tiers: {sorted(lane_digests)} bit-identical, "
        f"inline_completions={rpc_row('tcp')['inline_completions']}, "
        f"shm_frames={rpc_row('shm')['lanes']['shm']['frames_sent']}, "
        f"coalesced={rpc_row('tcp+coalesce')['coalesced_frames']}; "
        "per-lane sums reconcile"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
