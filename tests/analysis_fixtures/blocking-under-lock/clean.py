"""RPH302 clean: Condition.wait on its OWN lock (wait releases it — the
one legal blocking shape under a lock), blocking work outside the
critical section, and a snapshot-then-act send."""
import threading
import time


class Box:
    def __init__(self, sock):
        self._cond = threading.Condition()
        self.sock = sock
        self.v = 0

    def waiter(self):
        with self._cond:
            self._cond.wait()
        time.sleep(0.01)

    def push(self):
        with self._cond:
            v = self.v
        self.sock.sendall(str(v).encode())
