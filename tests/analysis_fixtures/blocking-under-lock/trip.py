"""RPH302 trip: a sleep inside the critical section, and a socket write
reached through a same-module call while the lock is held."""
import threading
import time


class Box:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self.sock = sock
        self.v = 0

    def slow(self):
        with self._lock:
            time.sleep(0.1)
            self.v += 1

    def indirect(self):
        with self._lock:
            self._push()

    def _push(self):
        self.sock.sendall(b"x")
