"""RPA103 clean (chaos-plane shape): ``faults_at`` stays a pure
elementwise function of device arrays and the traced tick scalar — the
real implementation's shape (``sim/chaos.py``)."""

import jax
import jax.numpy as jnp


@jax.jit
def faults_at(crash_tick, restart_tick, tick):
    t = jnp.asarray(tick, jnp.int32)
    down = (t >= crash_tick) & (t < restart_tick)
    return ~down
