"""RPA103 trip (chaos-plane shape): a ``faults_at`` that concretizes the
traced tick — ``int(tick)`` and a host-numpy coercion of the crash
schedule — turning the device-resident timeline into a per-tick
device→host round-trip (or a trace-time error).  The chaos plane's one
banned implementation shape."""

import jax
import numpy as np


@jax.jit
def faults_at(crash_tick, tick):
    t = int(tick)  # concretizes the traced tick
    down = np.asarray(crash_tick) <= t  # host-materializes the schedule
    return down
