"""RPJ203 clean: the same collective under an allowed phase scope
(shard-roll — the exchange's ppermute home)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

JAXLINT_TRACE_RULE = "RPJ203"


def build():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("node",))

    def fn(x):
        def body(xl):
            with jax.named_scope("shard-roll"):
                return jax.lax.psum(xl, "node")

        try:
            f = _shard_map(body, mesh=mesh, in_specs=(P("node"),),
                           out_specs=P(), check_vma=False)
        except TypeError:  # pragma: no cover
            f = _shard_map(body, mesh=mesh, in_specs=(P("node"),),
                           out_specs=P(), check_rep=False)
        return f(x)

    return fn, (jnp.arange(64.0),)
