"""RPJ204 clean: a same-shape carry — the donated buffer aliases the
output (the tick-block shape: state in, state out)."""

JAXLINT_TRACE_RULE = "RPJ204"


def build():
    import jax.numpy as jnp

    def fn(x):
        return x * 2 + 1

    return fn, (jnp.ones((8, 8)),)
