"""RPJ204 trip: the donated argument cannot alias any output (shape
mismatch) — the donation is silently a copy."""

import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ204"


def build():
    def fn(x):
        return x[::2].sum()

    return fn, (jnp.ones((8, 8)),)
