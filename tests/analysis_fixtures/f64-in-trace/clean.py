"""RPJ201 clean: the same reduction, 32-bit throughout."""

import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ201"


def build():
    def fn(x):
        return x.astype(jnp.float32).sum()

    return fn, (jnp.ones(8),)
