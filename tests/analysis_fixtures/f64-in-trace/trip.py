"""RPJ201 trip: an f64 aval inside the traced program (x64 enabled
mid-trace — the only way a 64-bit value sneaks past the global config)."""

import jax
import jax.experimental
import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ201"


def build():
    def fn(x):
        with jax.experimental.enable_x64(True):
            return x.astype(jnp.float64).sum()

    return fn, (jnp.ones(8),)
