"""RPJ202 clean: the doubling stays on device."""

import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ202"


def build():
    def fn(x):
        return x * 2

    return fn, (jnp.ones(4),)
