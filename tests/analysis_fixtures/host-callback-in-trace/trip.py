"""RPJ202 trip: a host callback inside the traced program — one
device→host round-trip per execution."""

import jax
import jax.numpy as jnp
import numpy as np

JAXLINT_TRACE_RULE = "RPJ202"


def build():
    def fn(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            x,
        )

    return fn, (jnp.ones(4),)
