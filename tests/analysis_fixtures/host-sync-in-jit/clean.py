"""RPA103 clean: device code stays jnp; the host coercion lives in an
un-jitted host helper, where it belongs."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good_norm(x):
    return jnp.sum(x)


def host_report(x):
    return float(np.asarray(x).sum())
