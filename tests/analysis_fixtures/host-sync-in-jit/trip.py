"""RPA103 trip: host-sync constructs inside a jit-traced function — a
host numpy coercion and a ``.item()`` readback, both concretization
fences."""

import jax
import numpy as np


@jax.jit
def bad_norm(x):
    total = np.asarray(x).sum()
    return total


def helper(x):
    # reachable from the jit root below — the call-graph closure must
    # flag the .item() here too
    return x.sum().item()


bad_jitted = jax.jit(helper)
