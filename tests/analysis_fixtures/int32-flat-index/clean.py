"""RPA106 clean: the blessed flat-index spellings.

Digest/mixing lanes that are consumed mod 2**32 route through
``packbits.flat_index_u32`` (explicit WRAPPING uint32 — no bare product
in sight); anything needing the numeric index keeps (row, col) pairs; a
deliberate in-range product states its dtype.
"""

import jax
import jax.numpy as jnp

from ringpop_tpu.sim.packbits import flat_index_u32


@jax.jit
def digest_lanes(p):
    n, w = p.shape
    rows = jnp.arange(n, dtype=jnp.uint32)
    cols = jnp.arange(w, dtype=jnp.uint32)
    # wrapping-uint32 helper: the mod-2**32 lane form, stated explicitly
    return flat_index_u32(rows[:, None], w, cols[None, :])


@jax.jit
def row_col_pairs(p):
    n, w = p.shape
    # no flat index at all: 2-D indexing keeps every coordinate < 2**31
    rows = jnp.arange(n, dtype=jnp.int32)
    cols = jnp.arange(w, dtype=jnp.int32)
    return p[rows[:, None], cols[None, :]]
