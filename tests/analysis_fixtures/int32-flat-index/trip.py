"""RPA106 trip: flat-index products that silently wrap in int32.

``rows * w + col`` with default-dtype operands lands in int32 under
disabled x64 — at N·K >= 2**31 (16M x 256) the product wraps and the
"flat index" addresses the wrong element with no error anywhere.
"""

import jax
import jax.numpy as jnp


@jax.jit
def flat_offsets(p):
    n, w = p.shape
    rows = jnp.arange(n)
    # RPA106: arange-derived index vector x array extent, no widening
    return rows * w + 3


@jax.jit
def flat_iota(p):
    n, w = p.shape
    # RPA106: an iota SIZED by a product of two extents (int32 values wrap)
    return jnp.arange(n * w)
