"""RPH305 clean: a documented kind carrying exactly its indexed keys
(plus a dynamic spread, which only the literal-key contract covers)."""


def emit(journal, extra):
    journal.write({"kind": "heal", "tick": 1})
    journal.write({"kind": "crash", "tick": 2, "nodes": [1, 2], **extra})
