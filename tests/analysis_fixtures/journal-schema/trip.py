"""RPH305 trip: one record whose kind is absent from OBSERVABILITY.md's
journal record schema index, and one documented kind emitting a key its
row doesn't list — both halves of the r22 drift class."""


def emit(journal):
    journal.write({"kind": "zz_undocumented_kind", "tick": 1})
    journal.write({"kind": "heal", "tick": 1, "zz_bogus_key": 2})
