"""RPH301 clean: both paths honor one global order (a before b) — the
acquisition graph is acyclic, including through the helper call."""
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def fwd(self):
        with self._a:
            with self._b:
                self.n += 1

    def rev(self):
        with self._a:
            self._under_a()

    def _under_a(self):
        with self._b:
            self.n -= 1
