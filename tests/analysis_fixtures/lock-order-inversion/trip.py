"""RPH301 trip: the same two locks nest in opposite orders — two
threads entering fwd() and rev() concurrently deadlock."""
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def fwd(self):
        with self._a:
            with self._b:
                self.n += 1

    def rev(self):
        with self._b:
            with self._a:
                self.n -= 1
