"""RPA105 clean: the protocol-phase function carries a canonical scope."""

import jax
import jax.numpy as jnp


def step(x):
    with jax.named_scope("tick-prologue"):
        y = x * 2
    with jax.named_scope("commit"):
        return jnp.sum(y)
