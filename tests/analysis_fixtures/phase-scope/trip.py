"""RPA105 trip: a protocol-phase function (``step``) with no
``jax.named_scope`` — its collectives would census as (unattributed) —
plus a scope name outside the canonical phase vocabulary."""

import jax
import jax.numpy as jnp


def step(x):
    return jnp.sum(x * 2)


def misnamed(x):
    with jax.named_scope("my-cool-phase"):
        return x + 1
