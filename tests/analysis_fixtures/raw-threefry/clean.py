"""RPA101 clean: the threefry draw is one branch of the rng-family
dispatch (the enclosing function references the counter stream), and
PRNGKey construction alone is always legal."""

import jax

from ringpop_tpu.sim import prng as _prng


def make_key(seed):
    return jax.random.PRNGKey(seed)


def draw_targets(key, n, use_counter):
    if use_counter:
        return _prng.draw_randint(_prng.fold_key(key), 0, _prng.D_TARGET, 0, 0, n)
    return jax.random.randint(key, (n,), 0, n)
