"""RPA101 trip: a raw threefry draw with no counter-RNG dispatch in the
enclosing function — under GSPMD this either materializes replicated or
draws different lanes sharded vs unsharded."""

import jax


def draw_targets(key, n):
    return jax.random.randint(key, (n,), 0, n)
