"""RPJ205 clean: the programs differ ONLY inside the rumor-exchange
scope (the one intentionally different lowering) — structurally equal
after excision, like the shard_roll vs roll-gather legs."""

import jax
import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ205"


def build():
    def dense(x):
        with jax.named_scope("rumor-exchange"):
            y = x * 3
        return (y - x).sum()

    def sharded(x):
        with jax.named_scope("rumor-exchange"):
            y = x + 1
        return (y - x).sum()

    return dense, sharded, (jnp.ones(8),)
