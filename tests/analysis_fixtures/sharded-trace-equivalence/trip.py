"""RPJ205 trip: the two programs differ structurally OUTSIDE the
excised exchange region — a partition-dependent computation."""

import jax.numpy as jnp

JAXLINT_TRACE_RULE = "RPJ205"


def build():
    def dense(x):
        return (x * 2).sum()

    def sharded(x):
        return (x + 2).sum()

    return dense, sharded, (jnp.ones(8),)
