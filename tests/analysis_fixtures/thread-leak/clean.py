"""RPH303 clean: the two blessed shapes — joined in the creating scope,
or daemonized (with the bounded join living on the shutdown path)."""
import threading


def run_joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class Worker:
    def __init__(self, fn):
        self._t = threading.Thread(target=fn, daemon=True)
        self._t.start()

    def close(self):
        self._t.join(timeout=5)
