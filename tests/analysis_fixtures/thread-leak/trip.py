"""RPH303 trip: a non-daemon thread started and dropped — it outlives
main and holds the process open."""
import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn)
    t.start()
