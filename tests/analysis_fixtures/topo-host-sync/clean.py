"""RPA103 clean (topology-plane shape): the tier lookup stays a pure
elementwise function of the compiled device arrays — id gathers, a
differing-level sum, and the blocked one-hot expansion over the static
tier count (the real implementation's shape, ``delta.tier_pair_drop``)."""

import jax
import jax.numpy as jnp

N_TIERS = 4


@jax.jit
def tier_pair_drop(tier_ids, tier_drop, a, b):
    da = jnp.take(tier_ids, a, axis=-1)
    db = jnp.take(tier_ids, b, axis=-1)
    tier = (da != db).astype(jnp.int32).sum(axis=0)
    drop = jnp.zeros(tier.shape, jnp.float32)
    for t in range(N_TIERS):
        drop = drop + jnp.where(tier == t, tier_drop[t], 0.0)
    return drop
