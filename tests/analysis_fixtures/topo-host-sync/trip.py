"""RPA103 trip (topology-plane shape): a tier lookup that HOST-SYNCS —
``.item()`` on the traced tier distance and a host-numpy coercion of the
tier-id plane — turning the shard-local blocked one-hot evaluation into
a per-leg device→host round-trip (or a trace-time error).  The topology
compiler's one banned implementation shape (``sim/topology.py`` compiles
host-side ONCE; the jitted step must never reach back)."""

import jax
import numpy as np


@jax.jit
def tier_pair_drop(tier_ids, tier_drop, a, b):
    ids = np.asarray(tier_ids)  # host-materializes the compiled id plane
    tier = (ids[:, a] != ids[:, b]).sum()
    return tier_drop[tier.item()]  # concretizes the traced tier
