"""RPA102 clean: the blessed lowering — a gather through a materialized
index vector (one address lookup per element, fuses cheaply)."""

import jax.numpy as jnp


def exchange_leg(plane, shift):
    n = plane.shape[0]
    idx = jnp.mod(jnp.arange(n, dtype=jnp.int32) - shift, n)
    return plane[idx]
