"""RPA102 trip: a traced-shift roll — slice-select chain on CPU,
plane-sized all-gather under GSPMD."""

import jax.numpy as jnp


def exchange_leg(plane, shift):
    return jnp.roll(plane, shift, axis=0)
