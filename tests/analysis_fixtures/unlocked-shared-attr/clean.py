"""RPH304 clean: same two thread roots, but every write to the shared
attribute happens under the one lock."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def start(self, pool):
        threading.Thread(target=self._worker, daemon=True).start()
        pool.submit(self._bump)

    def _worker(self):
        with self._lock:
            self.total = self.total + 1

    def _bump(self):
        with self._lock:
            self.total += 1
