"""RPH304 trip: ``total`` is written from two distinct thread roots (a
spawned Thread and an executor submit) and the worker's write takes no
lock — torn read-modify-write under free-running threads."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def start(self, pool):
        threading.Thread(target=self._worker, daemon=True).start()
        pool.submit(self._bump)

    def _worker(self):
        self.total = self.total + 1

    def _bump(self):
        with self._lock:
            self.total += 1
