"""RPA104 clean: stays in 32-bit — the stable-argsort restructure that
replaces a packed 64-bit composite key."""

import jax.numpy as jnp


def first_occurrence_order(owner):
    order = jnp.argsort(owner, axis=1)
    return jnp.take_along_axis(owner, order, axis=1), order.astype(jnp.int32)


def zeros32(n):
    return jnp.zeros(n, dtype="float32")
