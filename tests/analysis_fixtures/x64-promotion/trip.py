"""RPA104 trip: 64-bit dtype usage in device code — with x64 disabled,
``jnp.int64`` silently computes in int32 (the ring_ops composite-sort
overflow), and a dtype string asks for the same hazard."""

import jax.numpy as jnp


def composite_key(owner, pos, w):
    return owner.astype(jnp.int64) * w + pos


def zeros64(n):
    return jnp.zeros(n, dtype="float64")
