"""Capture golden per-tick delta-engine trajectories (same contract as
``capture_lifecycle_golden.py``: freeze the exact state evolution so the
engine's internal representation can be restructured — e.g. the round-3
bitpacked ``learned`` — with bit-for-bit proof that the dissemination
semantics, PRNG draw order included, did not move).

Run offline (``python tests/capture_delta_golden.py``) to (re)capture;
replayed by ``tests/test_delta_golden.py``.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from ringpop_tpu.sim import delta  # noqa: E402

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "delta_traj.npz")

# (name, params-kwargs, sources, fault schedule, ticks, seed) — the fault
# schedule works like the lifecycle capture's: the entry with the largest
# first_tick <= t applies at tick t.
CONFIGS = [
    ("plain_shift", dict(n=64, k=32), None, [(0, dict())], 40, 1),
    (
        "uniform_drop_tail12",
        dict(n=48, k=12, exchange="uniform"),
        None,
        [(0, dict(drop=0.1))],
        60,
        2,
    ),
    (
        "partition_heal_stuck",
        dict(n=64, k=16),
        np.zeros(16, np.int64),
        [(0, dict(group=[0] * 32 + [1] * 32)), (60, dict())],
        120,
        3,
    ),
    (
        "deadnodes_maxp2_tail48",
        dict(n=40, k=48, max_p=2),
        None,
        [(0, dict(down=[3, 9, 22, 23, 39]))],
        60,
        4,
    ),
]


from tests.sim_faults import make_faults  # noqa: E402


def run_config(pkw, sources, fault_sched, ticks, seed):
    import functools

    params = delta.DeltaParams(**pkw)
    state = delta.init_state(params, seed=seed, sources=sources)
    stepper = jax.jit(functools.partial(delta.step, params))
    frames = []
    for t in range(ticks):
        fkw = max((e for e in fault_sched if e[0] <= t), key=lambda e: e[0])[1]
        state = stepper(state, make_faults(params.n, **fkw))
        frames.append({f: np.asarray(getattr(state, f)) for f in state._fields})
    return {f: np.stack([fr[f] for fr in frames]) for f in frames[0]}


def main() -> None:
    from tests import golden_tools

    out = {}
    for name, pkw, sources, fault_sched, ticks, seed in CONFIGS:
        print(f"capturing {name} ...", flush=True)
        traj = run_config(pkw, sources, fault_sched, ticks, seed)
        for f, arr in traj.items():
            out[f"{name}/{f}"] = arr
    # record the capture toolchain so a future mismatch can be classified
    # as drift vs regression (tests/golden_tools.py)
    golden_tools.embed(out)
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    # dual-toolchain goldens: per-fingerprint sibling file, legacy npz
    # retained (see capture_lifecycle_golden.py)
    path = golden_tools.versioned_path(GOLDEN_PATH)
    np.savez_compressed(path, **out)
    print(f"wrote {path} ({os.path.getsize(path) / 1e6:.2f} MB)")
    if not os.path.exists(GOLDEN_PATH):
        np.savez_compressed(GOLDEN_PATH, **out)
        print(f"wrote {GOLDEN_PATH} (no legacy capture existed)")


if __name__ == "__main__":
    main()
