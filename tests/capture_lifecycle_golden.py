"""Capture golden per-tick lifecycle-engine trajectories.

Run offline (``python tests/capture_lifecycle_golden.py``) to freeze the
engine's exact state evolution — every field, every tick — for a set of
configs spanning the protocol surface: both exchange topologies, packet
loss, partitions + heal, the full suspect→faulty→tombstone→evict chain,
slot saturation, K>32 and K<32 tails, heal_prob on/off, and a mid-run
``admit``.  ``tests/test_lifecycle_golden.py`` replays these and asserts
bit-identical states, which is what lets the engine's internal
representation be restructured for speed (e.g. the round-3 bitpacked
``learned``) with proof that the protocol semantics — PRNG draw order
included — did not move at all.

The reference's analog is the tier-3 conformance suite pinning protocol
behavior across implementations (``test/run-integration-tests``); here the
"other implementation" is the engine's own past self.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from ringpop_tpu.sim import lifecycle  # noqa: E402

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "lifecycle_traj.npz")

# Each config: (name, params-kwargs, fault schedule, admit schedule).
# The fault schedule is [(first_tick, faults_kwargs)] — the entry with the
# largest first_tick <= t applies at tick t.  Admits happen BEFORE the
# given tick's step.
CONFIGS = [
    (
        "crash_shift",
        dict(n=64, k=32, suspect_ticks=10),
        [(0, dict(down=[7]))],
        {},
        80,
        1,
    ),
    (
        "partition_drop_heal",
        dict(n=48, k=12, suspect_ticks=6),
        [(0, dict(group=[1 if i < 10 else 0 for i in range(48)], drop=0.05)), (50, dict())],
        {},
        100,
        2,
    ),
    (
        "full_chain_uniform",
        dict(n=40, k=20, exchange="uniform", suspect_ticks=5, faulty_ticks=8, tombstone_ticks=6),
        [(0, dict(down=[3, 11]))],
        {},
        150,
        3,
    ),
    (
        "saturation",
        dict(n=24, k=2, suspect_ticks=4, alloc_per_tick=2),
        [(0, dict(down=[1, 2, 3]))],
        {},
        120,
        11,
    ),
    (
        "evict_readmit_tail48",
        dict(n=32, k=48, suspect_ticks=4, faulty_ticks=6, tombstone_ticks=6),
        [(0, dict(down=[9])), (100, dict())],
        {100: 9},
        160,
        17,
    ),
    (
        "no_heal_prob",
        dict(n=16, k=8, suspect_ticks=4, heal_prob=0.0),
        [(0, dict(down=[2]))],
        {},
        60,
        5,
    ),
]


from tests.sim_faults import make_faults  # noqa: E402


def run_config(pkw, fault_sched, admits, ticks, seed):
    import functools

    params = lifecycle.LifecycleParams(**pkw)
    state = lifecycle.init_state(params, seed=seed)
    # jit changes nothing semantically (same trace) but replaying ~700
    # eager ticks costs 10x the wall time in op dispatch; recompiles only
    # when the fault schedule changes the pytree structure
    stepper = jax.jit(functools.partial(lifecycle.step, params))
    frames = []
    for t in range(ticks):
        if t in admits:
            state = lifecycle.admit(params, state, admits[t])
        fkw = max((e for e in fault_sched if e[0] <= t), key=lambda e: e[0])[1]
        state = stepper(state, make_faults(params.n, **fkw))
        frames.append({f: np.asarray(getattr(state, f)) for f in state._fields})
    return {
        f: np.stack([fr[f] for fr in frames]) for f in frames[0]
    }


def main() -> None:
    from tests import golden_tools

    out = {}
    for name, pkw, fault_sched, admits, ticks, seed in CONFIGS:
        print(f"capturing {name} ...", flush=True)
        traj = run_config(pkw, fault_sched, admits, ticks, seed)
        for f, arr in traj.items():
            out[f"{name}/{f}"] = arr
    # record the capture toolchain so a future mismatch can be classified
    # as drift vs regression (tests/golden_tools.py)
    golden_tools.embed(out)
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    # dual-toolchain goldens: the capture lands in the per-fingerprint
    # sibling file, NEVER over the legacy npz — old-toolchain evidence is
    # retained and the loader picks whichever matches the running
    # toolchain (tests/golden_tools.load_golden).  Only a repo with no
    # legacy capture at all seeds one.
    path = golden_tools.versioned_path(GOLDEN_PATH)
    np.savez_compressed(path, **out)
    print(f"wrote {path} ({os.path.getsize(path) / 1e6:.1f} MB)")
    if not os.path.exists(GOLDEN_PATH):
        np.savez_compressed(GOLDEN_PATH, **out)
        print(f"wrote {GOLDEN_PATH} (no legacy capture existed)")


if __name__ == "__main__":
    main()
