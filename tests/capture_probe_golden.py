"""Record the XLA feature-string probe's expected outcome for THIS
toolchain (``tests/golden/xla_probe.<fp8>.json``).

What ``accel._xla_detected_target_bits`` can extract is a property of the
container's XLA: older jaxlibs wrote AOT cache entries embedding the
canonical target-machine feature string (the probe surfaces it as
``xla-fp:...``); this container's XLA (jax 0.4.37) writes entries that
carry no plain-text feature string at all, so the honest probe answer
here is the ``xla-fp-none`` fallback — and the compile cache stays safely
segmented by the cpuinfo + jax-version bits.  The probe TEST therefore
needs a per-toolchain expectation, keyed exactly like the trajectory
goldens (``tests/golden_tools.versioned_path``): this script captures the
current probe output; ``tests/test_accel_fingerprint.py`` replays it and
falls back to the legacy strict ``xla-fp:`` expectation on unrecorded
toolchains (failing with the drift diagnosis there, as before).

Run offline: ``python tests/capture_probe_golden.py``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from ringpop_tpu.util import accel  # noqa: E402
from tests import golden_tools  # noqa: E402


def main() -> None:
    bits = accel._xla_detected_target_bits()
    rec = {
        "toolchain": golden_tools.fingerprint(),
        "bits_head": bits[0],
        "n_bits": len(bits),
        "note": (
            "expected _xla_detected_target_bits()[0] on this toolchain; "
            "'xla-fp-none' means this XLA's cache entries embed no "
            "plain-text feature string (verified at capture time) and the "
            "cache keying rests on the cpuinfo/jax-version bits"
        ),
    }
    path = golden_tools.versioned_path(golden_tools.PROBE_GOLDEN_PATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {path}: bits_head={rec['bits_head']!r} n_bits={rec['n_bits']}")


if __name__ == "__main__":
    main()
