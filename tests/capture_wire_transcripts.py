"""Capture golden multi-frame wire CONVERSATIONS (VERDICT round-2 item 5).

``tests/golden/wire_corpus.json`` pins single message bodies; the
reference's tier-3 suite additionally proves multi-message *sequences* —
ping → checksum-mismatch full sync → reverse full sync
(``swim/disseminator.go:156-304``), join rounds
(``swim/join_sender.go:281-435``), heal merges with reincarnations
(``swim/heal_partition.go:33-59``) — against real processes
(``test/run-integration-tests:99-113``).  This harness drives live
host-plane nodes over an instrumented in-process channel, records every
RPC frame (caller, peer, endpoint, request body, response body) in order,
and freezes the sequences.  MockClock + fixed seeds make every field —
incarnations (clock ms), checksums, timestamps — deterministic, so the
transcripts replay bit-for-bit.

Run offline to (re)capture:  python tests/capture_wire_transcripts.py
Replayed by tests/test_wire_transcripts.py.
"""

from __future__ import annotations

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ringpop_tpu.net import LocalNetwork, LocalChannel  # noqa: E402
from ringpop_tpu.swim import heal as heal_mod  # noqa: E402
from ringpop_tpu.swim.member import Change, state_id  # noqa: E402
from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions  # noqa: E402
from ringpop_tpu.swim.ping import send_ping  # noqa: E402
from ringpop_tpu.swim.state_transitions import StateTimeouts  # noqa: E402
from ringpop_tpu.util.clock import MockClock  # noqa: E402

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden", "wire_transcripts.json")


class RecordingChannel(LocalChannel):
    """LocalChannel that logs every outbound RPC frame + its response."""

    def __init__(self, network, hostport, app, log):
        super().__init__(network, hostport, app=app)
        self._log = log

    async def call(self, peer, service, endpoint, body, headers=None, timeout=None):
        frame = {
            "caller": self.hostport,
            "peer": peer,
            "service": service,
            "endpoint": endpoint,
            "request": body,
        }
        self._log.append(frame)
        try:
            res = await super().call(peer, service, endpoint, body, headers, timeout)
        except Exception as e:  # error frames are part of the conversation
            frame["error"] = type(e).__name__
            raise
        frame["response"] = res
        return frame["response"]


def make_recorded_node(network, address, log, app="test", seed=0):
    ch = RecordingChannel(network, address, app, log)
    clock = MockClock(start=1_000_000.0)
    opts = NodeOptions(clock=clock, seed=seed, state_timeouts=StateTimeouts(suspect=5.0))
    return Node(app, address, ch, opts)


async def _boot(nodes, hosts=None):
    hosts = hosts or [n.address for n in nodes]

    async def one(n):
        await n.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=0.5))
        n.gossip.stop()
        n.healer.stop()

    await asyncio.gather(*(one(n) for n in nodes))


async def _drain():
    for _ in range(6):
        await asyncio.sleep(0)


# -- scenes -----------------------------------------------------------------


async def scene_ping_piggyback():
    """A declares its target suspect, then pings a peer: the suspect change
    rides as piggyback and the peer's reply echoes its own view
    (``swim/ping_sender.go:43-120`` / ``ping_handler.go:25-58``)."""
    log: list = []
    net = LocalNetwork()
    nodes = [
        make_recorded_node(net, f"127.0.0.1:{3000 + i}", log, seed=50 + i)
        for i in range(3)
    ]
    await _boot(nodes)
    log.clear()  # keep only the conversation, not the bootstrap
    a, b, c = nodes
    a.clock.advance(0.001)
    a.memberlist.make_suspect(c.address, a.memberlist.member(c.address).incarnation)
    await send_ping(a, b.address, timeout=1.0)
    await _drain()
    for n in nodes:
        n.destroy()
    return log


async def scene_full_sync_reverse():
    """B silently learns an extra member (join-list insert clears
    dissemination, ``memberlist.go:398-406``); A's empty-changes ping then
    hits a checksum mismatch → B answers with its FULL membership and
    starts a reverse full sync (a join call back to A) to heal the
    asymmetry (``disseminator.go:156-304``)."""
    log: list = []
    net = LocalNetwork()
    nodes = [
        make_recorded_node(net, f"127.0.0.1:{3100 + i}", log, seed=60 + i)
        for i in range(2)
    ]
    await _boot(nodes)
    a, b = nodes
    # drain bootstrap-era piggyback so A's ping carries NO changes — the
    # full-sync branch requires checksum mismatch AND an empty changes
    # response (disseminator.go:168-181)
    a.disseminator.clear_changes()
    b.disseminator.clear_changes()
    b.memberlist.add_join_list(
        [
            Change(
                address="127.0.0.1:3999",
                incarnation=1_000_000_500,
                status=state_id("alive"),
                source=b.address,
                source_incarnation=b.incarnation(),
                timestamp=1_000_000_500,
            )
        ]
    )
    log.clear()
    await send_ping(a, b.address, timeout=1.0)
    await _drain()  # lets the reverse-full-sync join land
    for n in nodes:
        n.destroy()
    return log


async def scene_join_round():
    """A fresh node joins a 2-node cluster: the full join round as the
    joiner drives it (``join_sender.go:281-435``)."""
    log: list = []
    net = LocalNetwork()
    ab = [
        make_recorded_node(net, f"127.0.0.1:{3200 + i}", log, seed=70 + i)
        for i in range(2)
    ]
    await _boot(ab)
    joiner = make_recorded_node(net, "127.0.0.1:3210", log, seed=77)
    log.clear()
    await _boot([joiner], hosts=[n.address for n in ab] + [joiner.address])
    await _drain()
    for n in ab + [joiner]:
        n.destroy()
    return log


async def scene_heal_reincarnate():
    """Two 2-node partitions that remember each other as faulty; a heal
    attempt from A to C must first re-assert the faulty members via
    Suspect declarations to both sides (refutation-by-reincarnation
    follows), then merge (``heal_partition.go:33-124``)."""
    log: list = []
    net = LocalNetwork()
    left = [
        make_recorded_node(net, f"127.0.0.1:{3300 + i}", log, seed=80 + i)
        for i in range(2)
    ]
    right = [
        make_recorded_node(net, f"127.0.0.1:{3310 + i}", log, seed=90 + i)
        for i in range(2)
    ]
    await _boot(left)
    await _boot(right)
    # each side knows the other side's members as faulty, by fiat (the
    # reference's partition tests write Faulty states directly,
    # heal_partition_test.go:420-428)
    for n in left:
        n.clock.advance(0.001)
        for m in right:
            n.memberlist.make_faulty(m.address, 1_000_000_000)
        n.disseminator.clear_changes()
    for n in right:
        n.clock.advance(0.001)
        for m in left:
            n.memberlist.make_faulty(m.address, 1_000_000_000)
        n.disseminator.clear_changes()
    log.clear()
    a, c = left[0], right[0]
    await heal_mod.attempt_heal(a, c.address)
    await _drain()
    for n in left + right:
        n.destroy()
    return log


SCENES = {
    "ping_piggyback": scene_ping_piggyback,
    "full_sync_reverse": scene_full_sync_reverse,
    "join_round": scene_join_round,
    "heal_reincarnate": scene_heal_reincarnate,
}


def capture() -> dict:
    out = {}
    for name, fn in SCENES.items():
        out[name] = asyncio.run(fn())
    return out


def main() -> None:
    out = capture()
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    with open(GOLDEN_PATH, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    for name, frames in out.items():
        print(f"{name}: {len(frames)} frames:",
              [f"{fr['caller'].split(':')[1]}->{fr['peer'].split(':')[1]} {fr['endpoint']}" for fr in frames])
    print("wrote", GOLDEN_PATH)


if __name__ == "__main__":
    main()
