import os

# Sharding tests run on a virtual 8-device CPU mesh. XLA_FLAGS must be set
# before jax initializes; JAX_PLATFORMS alone is unreliable here because the
# environment re-exports JAX_PLATFORMS=axon, so also pin via jax.config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running scale spot checks")
