import os

# Sharding tests run on a virtual 8-device CPU mesh. XLA_FLAGS must be set
# before jax initializes; JAX_PLATFORMS alone is unreliable here because the
# environment re-exports JAX_PLATFORMS=axon, so also pin via jax.config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (same as bench.py): the suite's wall
# time is dominated by single-threaded XLA:CPU compiles of the sim-engine
# programs; warming the cache once makes subsequent runs compile-free
# (VERDICT round-2 item 6 — the suite must fit its CI window).  Keyed by a
# platform/CPU-feature fingerprint (configure_compile_cache) so entries
# compiled on a different-featured container are unreachable instead of
# SIGILL bait.
from ringpop_tpu.util.accel import configure_compile_cache  # noqa: E402

configure_compile_cache()  # $RINGPOP_TPU_COMPILE_CACHE or repo .jax_cache


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running scale spot checks")
