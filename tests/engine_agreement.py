"""Shared machinery for the lifecycle-vs-fullview engine agreement study
(VERDICT round-1 item 4) — used by ``tests/test_engine_agreement.py`` and
runnable directly to print the raw distributions:

    python -m tests.engine_agreement [--seeds 20] [--n 256]

The lifecycle engine documents four approximations vs the exact fullview
engine (``sim/lifecycle.py`` module docstring).  This harness measures, at
identical params and fault schedules over many seeds:

* detection latency (crash -> every live observer believes victim faulty);
* refutation behavior (drop-rate-induced false suspicions refuted: how many
  nodes ended with a bumped self-incarnation, and whether the cluster
  returns to an all-alive converged view);
* steady-state quiescence (no faults -> no rumors / no change records).
"""

from __future__ import annotations

import os

# direct `python -m tests.engine_agreement` runs bypass tests/conftest.py's
# backend pinning; without it this environment initializes the axon platform,
# which hangs when the TPU tunnel is down.  (A plain setdefault is not
# enough: the container's sitecustomize re-exports JAX_PLATFORMS=axon at
# interpreter startup.)
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

import jax

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass  # backend already initialized (pytest: conftest pinned it)

import jax.numpy as jnp

from ringpop_tpu.sim import fullview, lifecycle
from ringpop_tpu.swim.member import ALIVE, FAULTY


from tests.sim_faults import make_faults  # noqa: E402


# -- fullview queries -------------------------------------------------------


def fv_detected(sim: fullview.FullViewSim, victims, up) -> bool:
    """Every live observer believes every victim >= FAULTY (or evicted)."""
    status = np.asarray(sim.state.status)
    present = np.asarray(sim.state.present)
    observers = np.asarray(up).copy()
    observers[list(victims)] = False
    obs_idx = np.flatnonzero(observers)
    sub = status[np.ix_(obs_idx, list(victims))]
    gone = ~present[np.ix_(obs_idx, list(victims))]
    return bool(((sub >= FAULTY) | gone).all())


def fv_all_alive_converged(sim: fullview.FullViewSim) -> bool:
    status = np.asarray(sim.state.status)
    return bool((status == ALIVE).all()) and not bool(np.asarray(sim.state.has_change).any())


def fv_refuted_count(sim: fullview.FullViewSim) -> int:
    """Nodes whose self-incarnation advanced past the epoch (= refuted at
    least once)."""
    inc = np.asarray(sim.state.incarnation)
    return int((np.diagonal(inc) > 0).sum())


# -- lifecycle queries ------------------------------------------------------


import functools


@functools.partial(jax.jit, static_argnames="min_status")
def _lc_detection_complete(state, subjects, faults, min_status):
    return lifecycle.detection_complete(state, subjects, faults, min_status)


def lc_detected(sim: lifecycle.LifecycleSim, victims, faults) -> bool:
    # jitted on-device predicate: the eager detection_fraction walk costs
    # ~0.27 s of dispatch per call, which dominated the 50-seed study
    # (checked every 2 ticks x ~30 ticks x 50 seeds)
    subjects = jnp.asarray(list(victims), jnp.int32)
    return bool(_lc_detection_complete(sim.state, subjects, faults, FAULTY))


def lc_quiet_all_alive(sim: lifecycle.LifecycleSim) -> bool:
    s = sim.state
    no_rumors = bool((np.asarray(s.r_subject) < 0).all())
    base_alive = bool((np.asarray(s.base_status) == ALIVE).all())
    return no_rumors and base_alive


def lc_refuted_count(sim: lifecycle.LifecycleSim) -> int:
    return int((np.asarray(sim.state.self_inc) > 0).sum())


# -- scenarios --------------------------------------------------------------

# one sim instance per (engine, params) combination, state re-seeded per run:
# re-instantiating per seed would recompile the jitted step each time
_sim_cache: dict = {}


def _get_sim(engine: str, n: int, seed: int, suspect_ticks: int):
    key = (engine, n, suspect_ticks)
    sim = _sim_cache.get(key)
    if engine == "fullview":
        if sim is None:
            sim = _sim_cache[key] = fullview.FullViewSim(
                n=n, seed=seed, suspect_ticks=suspect_ticks
            )
        sim.state = fullview.init_state(sim.params, seed=seed)
    else:
        if sim is None:
            sim = _sim_cache[key] = lifecycle.LifecycleSim(
                n=n, k=64, seed=seed, suspect_ticks=suspect_ticks
            )
        sim.state = lifecycle.init_state(sim.params, seed=seed)
    return sim


def detection_latency(engine: str, n: int, seed: int, victims, suspect_ticks=15, max_ticks=400):
    """Ticks until full detection of crashed victims, or max_ticks."""
    faults = make_faults(n, down=victims)
    sim = _get_sim(engine, n, seed, suspect_ticks)
    for t in range(1, max_ticks + 1):
        sim.tick(faults)
        if t % 2 == 0:
            if engine == "fullview":
                if fv_detected(sim, victims, np.asarray(faults.up)):
                    return t
            elif lc_detected(sim, victims, faults):
                return t
    return max_ticks


def refutation_run(engine: str, n: int, seed: int, drop=0.10, noisy_ticks=60,
                   quiet_ticks=300, suspect_ticks=8):
    """Run with packet loss (false suspicions accumulate), then drop-free
    until the cluster re-converges to all-alive.  Returns (refuted_count,
    recovered: bool, recovery_ticks)."""
    noisy = make_faults(n, drop=drop)
    clean = make_faults(n)
    sim = _get_sim(engine, n, seed, suspect_ticks)
    refuted = fv_refuted_count if engine == "fullview" else lc_refuted_count
    settled = fv_all_alive_converged if engine == "fullview" else lc_quiet_all_alive
    for _ in range(noisy_ticks):
        sim.tick(noisy)
    refuted_mid = refuted(sim)
    for t in range(1, quiet_ticks + 1):
        sim.tick(clean)
        if t % 4 == 0 and settled(sim):
            return max(refuted_mid, refuted(sim)), True, t
    return max(refuted_mid, refuted(sim)), False, quiet_ticks


def partition_run(engine: str, n: int, seed: int, minority_frac=0.3,
                  part_ticks=15, quiet_ticks=600, suspect_ticks=25):
    """ASYMMETRIC (30/70) hard partition for ``part_ticks`` — long enough
    that cross-partition suspicions pile up on both sides, healed before
    they convert to Faulty (suspects stay pingable, so normal gossip can
    carry the refutations after the heal; a full mutual-faulty split needs
    the discovery-provider healer, which only the lifecycle engine models
    — ``heal_via_discover_provider.go`` — so THAT deadlock cannot be an
    agreement scenario against the healer-less fullview oracle).

    Returns (cross_suspects_mid, recovered: bool, recovery_ticks,
    refuted_count).  Exercises the group-partition connectivity channel
    and the inconclusive-vs-suspect indirect-probe paths the loss scenario
    doesn't (reference: ``swim/node.go:494-510``,
    ``memberlist.go:337-354``)."""
    minority = list(range(int(n * minority_frac)))
    group = np.zeros(n, np.int32)
    group[: int(n * minority_frac)] = 1
    part = make_faults(n, group=group)
    clean = make_faults(n)
    sim = _get_sim(engine, n, seed, suspect_ticks)
    refuted = fv_refuted_count if engine == "fullview" else lc_refuted_count
    settled = fv_all_alive_converged if engine == "fullview" else lc_quiet_all_alive
    for _ in range(part_ticks):
        sim.tick(part)
    # cross-partition suspicion mass at heal time: (majority observer,
    # minority subject) pairs believed >= SUSPECT
    from ringpop_tpu.swim.member import SUSPECT

    if engine == "fullview":
        status = np.asarray(sim.state.status)
        cross = int((status[np.ix_(range(len(minority), n), minority)] >= SUSPECT).sum())
    else:
        bs = np.asarray(lifecycle.believed_status(sim.state, minority))
        cross = int((bs[len(minority):, :] >= SUSPECT).sum())
    for t in range(1, quiet_ticks + 1):
        sim.tick(clean)
        if t % 4 == 0 and settled(sim):
            return cross, True, t, refuted(sim)
    return cross, False, quiet_ticks, refuted(sim)


def quiescence_run(engine: str, n: int, seed: int, ticks=60):
    """No faults: returns True iff the engine stays fully quiet."""
    faults = make_faults(n)
    sim = _get_sim(engine, n, seed, suspect_ticks=25)
    for _ in range(ticks):
        sim.tick(faults)
    return fv_all_alive_converged(sim) if engine == "fullview" else lc_quiet_all_alive(sim)


def collect(n=256, seeds=20, n_victims=3):
    out = {"detect": {}, "refute": {}}
    rng = np.random.default_rng(7)
    victim_sets = [sorted(rng.choice(n, size=n_victims, replace=False).tolist()) for _ in range(seeds)]
    for engine in ("fullview", "lifecycle"):
        out["detect"][engine] = [
            detection_latency(engine, n, 100 + s, victim_sets[s]) for s in range(seeds)
        ]
        out["refute"][engine] = [
            refutation_run(engine, n, 200 + s) for s in range(seeds)
        ]
    return out


if __name__ == "__main__":
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=20)
    ap.add_argument("--n", type=int, default=256)
    args = ap.parse_args()
    res = collect(n=args.n, seeds=args.seeds)
    for scenario, by_engine in res.items():
        for engine, vals in by_engine.items():
            print(scenario, engine, json.dumps(vals))
    for engine in ("fullview", "lifecycle"):
        d = np.array(res["detect"][engine], float)
        print(
            f"{engine}: detect median={np.median(d):.0f} mean={d.mean():.1f} "
            f"p90={np.percentile(d, 90):.0f}"
        )
        ref = res["refute"][engine]
        counts = np.array([r[0] for r in ref], float)
        rec = np.array([r[1] for r in ref])
        rticks = np.array([r[2] for r in ref], float)
        print(
            f"{engine}: refuted mean={counts.mean():.1f} recovered={rec.mean():.2f} "
            f"recovery median={np.median(rticks):.0f}"
        )
