"""Toolchain-fingerprint support for the frozen golden trajectories.

The ``tests/golden/*.npz`` captures pin the sim engines' exact state
evolution — but a jax/jaxlib/XLA upgrade can legitimately move PRNG
lowering or fusion-order-sensitive results, and a raw array-mismatch
assertion cannot tell that apart from a protocol regression (the
ROADMAP's "Golden trajectories vs toolchain drift" open item: 10
trajectory failures at seed on this container, all pre-existing).

Two pieces:

* capture scripts embed :func:`fingerprint` into the npz under
  ``__toolchain__`` (a JSON string), so future captures carry their
  provenance;
* :func:`fail_golden` replaces the bare mismatch assert in the golden
  tests — it compares the capture-time fingerprint (when recorded)
  against the current one and fails with an explicit *"toolchain drift
  vs real regression"* classification instead of a wall of array diff.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ringpop_tpu.sim.telemetry import toolchain_fingerprint as fingerprint

TOOLCHAIN_KEY = "__toolchain__"


def embed(out: dict) -> None:
    """Add the current toolchain fingerprint to a capture dict about to be
    ``np.savez``-ed (stored as a 0-d string array)."""
    out[TOOLCHAIN_KEY] = np.array(json.dumps(fingerprint()))


def recorded(golden) -> dict | None:
    """The fingerprint a loaded golden npz was captured under, or None for
    pre-fingerprint captures."""
    if TOOLCHAIN_KEY not in getattr(golden, "files", ()):
        return None
    return json.loads(str(golden[TOOLCHAIN_KEY][()]))


def fail_golden(golden, config: str, field: str, tick) -> None:
    """pytest.fail with the drift-vs-regression diagnosis for a golden
    trajectory mismatch at (config, field, first diverging tick)."""
    captured = recorded(golden)
    current = fingerprint()
    lines = [
        f"golden trajectory mismatch: config {config!r}, field {field!r} "
        f"first diverges at tick {tick}.",
        f"  current toolchain:  {json.dumps(current, sort_keys=True)}",
    ]
    if captured is None:
        lines += [
            "  capture toolchain:  UNRECORDED (pre-fingerprint golden).",
            "  DIAGNOSIS: cannot rule out toolchain drift — the frozen "
            "goldens predate fingerprinting and are KNOWN to fail on this "
            "container's jax/XLA (ROADMAP: 'Golden trajectories vs "
            "toolchain drift'; verified pre-existing at seed).  Treat as "
            "drift unless a paired old-vs-new run of the *same* toolchain "
            "diverges; re-capturing via tests/capture_*_golden.py embeds "
            "the fingerprint for future runs.",
        ]
    elif captured == current:
        lines += [
            f"  capture toolchain:  {json.dumps(captured, sort_keys=True)}",
            "  DIAGNOSIS: toolchains MATCH — this is a REAL REGRESSION: "
            "an engine edit moved protocol semantics (PRNG draw order, "
            "tie-breaks, or deadline arithmetic included).  Bisect the "
            "engine change; do not re-capture over it.",
        ]
    else:
        drift = {
            k: (captured.get(k), current.get(k))
            for k in sorted(set(captured) | set(current))
            if captured.get(k) != current.get(k)
        }
        lines += [
            f"  capture toolchain:  {json.dumps(captured, sort_keys=True)}",
            f"  DIAGNOSIS: TOOLCHAIN DRIFT ({drift}) — the golden was "
            "frozen under a different jax/XLA; PRNG lowering or fusion "
            "order may have legitimately moved.  Not necessarily a code "
            "regression: certify engine edits with a paired old-vs-new "
            "run on ONE toolchain, and see the ROADMAP item for the "
            "re-freeze decision.",
        ]
    pytest.fail("\n".join(lines), pytrace=False)
