"""Toolchain-fingerprint support for the frozen golden trajectories.

The ``tests/golden/*.npz`` captures pin the sim engines' exact state
evolution — but a jax/jaxlib/XLA upgrade can legitimately move PRNG
lowering or fusion-order-sensitive results, and a raw array-mismatch
assertion cannot tell that apart from a protocol regression (the
ROADMAP's "Golden trajectories vs toolchain drift" open item: 10
trajectory failures at seed on this container, all pre-existing).

Three pieces:

* capture scripts embed :func:`fingerprint` into the npz under
  ``__toolchain__`` (a JSON string), so future captures carry their
  provenance;
* **dual-toolchain goldens** (r8, implementing the ROADMAP re-freeze
  decision): capture scripts write ``<stem>.<fp8>.npz`` — keyed by
  :func:`fp8`, an 8-hex digest of the capture toolchain's fingerprint —
  ALONGSIDE the legacy capture, and :func:`load_golden` picks the file
  matching the RUNNING toolchain, falling back to the legacy capture
  (whose mismatches then fail with the drift diagnosis).  Old-toolchain
  evidence is never discarded: re-freezing on a new container adds a
  file instead of overwriting history, and a future return to the old
  toolchain finds its goldens still green;
* :func:`fail_golden` replaces the bare mismatch assert in the golden
  tests — it compares the capture-time fingerprint (when recorded)
  against the current one and fails with an explicit *"toolchain drift
  vs real regression"* classification instead of a wall of array diff.

The XLA feature-string probe expectation is keyed the same way
(:func:`probe_recording` / ``xla_probe.<fp8>.json``): what the probe can
extract is a property of the container's XLA, so its pass condition is a
per-toolchain recording too.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np
import pytest

from ringpop_tpu.sim.telemetry import toolchain_fingerprint as fingerprint

TOOLCHAIN_KEY = "__toolchain__"

PROBE_GOLDEN_PATH = os.path.join(
    os.path.dirname(__file__), "golden", "xla_probe.json"
)


def fp8(fp: dict | None = None) -> str:
    """8-hex id of a toolchain fingerprint (sha256 of its sorted JSON) —
    the filename key of the dual-toolchain goldens."""
    fp = fingerprint() if fp is None else fp
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True).encode()
    ).hexdigest()[:8]


def versioned_path(legacy_path: str, fp: dict | None = None) -> str:
    """``<stem>.<fp8><ext>`` — the per-toolchain sibling of a legacy
    golden path."""
    root, ext = os.path.splitext(legacy_path)
    return f"{root}.{fp8(fp)}{ext}"


def load_golden(legacy_path: str):
    """``np.load`` the capture matching the RUNNING toolchain fingerprint
    when one exists, else the legacy capture (whose mismatches fail with
    the :func:`fail_golden` drift diagnosis)."""
    p = versioned_path(legacy_path)
    return np.load(p if os.path.exists(p) else legacy_path)


def probe_recording() -> dict | None:
    """The recorded XLA feature-string probe expectation for the RUNNING
    toolchain (``tests/golden/xla_probe.<fp8>.json``, written by
    ``tests/capture_probe_golden.py``), or None when this toolchain has
    no recording (the test then applies the legacy strict expectation)."""
    p = versioned_path(PROBE_GOLDEN_PATH)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def embed(out: dict) -> None:
    """Add the current toolchain fingerprint to a capture dict about to be
    ``np.savez``-ed (stored as a 0-d string array)."""
    out[TOOLCHAIN_KEY] = np.array(json.dumps(fingerprint()))


def recorded(golden) -> dict | None:
    """The fingerprint a loaded golden npz was captured under, or None for
    pre-fingerprint captures."""
    if TOOLCHAIN_KEY not in getattr(golden, "files", ()):
        return None
    return json.loads(str(golden[TOOLCHAIN_KEY][()]))


def fail_golden(golden, config: str, field: str, tick) -> None:
    """pytest.fail with the drift-vs-regression diagnosis for a golden
    trajectory mismatch at (config, field, first diverging tick)."""
    captured = recorded(golden)
    current = fingerprint()
    lines = [
        f"golden trajectory mismatch: config {config!r}, field {field!r} "
        f"first diverges at tick {tick}.",
        f"  current toolchain:  {json.dumps(current, sort_keys=True)}",
    ]
    if captured is None:
        lines += [
            "  capture toolchain:  UNRECORDED (pre-fingerprint golden).",
            "  DIAGNOSIS: cannot rule out toolchain drift — the frozen "
            "goldens predate fingerprinting and are KNOWN to fail on this "
            "container's jax/XLA (ROADMAP: 'Golden trajectories vs "
            "toolchain drift'; verified pre-existing at seed).  Treat as "
            "drift unless a paired old-vs-new run of the *same* toolchain "
            "diverges; re-capturing via tests/capture_*_golden.py embeds "
            "the fingerprint for future runs.",
        ]
    elif captured == current:
        lines += [
            f"  capture toolchain:  {json.dumps(captured, sort_keys=True)}",
            "  DIAGNOSIS: toolchains MATCH — this is a REAL REGRESSION: "
            "an engine edit moved protocol semantics (PRNG draw order, "
            "tie-breaks, or deadline arithmetic included).  Bisect the "
            "engine change; do not re-capture over it.",
        ]
    else:
        drift = {
            k: (captured.get(k), current.get(k))
            for k in sorted(set(captured) | set(current))
            if captured.get(k) != current.get(k)
        }
        lines += [
            f"  capture toolchain:  {json.dumps(captured, sort_keys=True)}",
            f"  DIAGNOSIS: TOOLCHAIN DRIFT ({drift}) — the golden was "
            "frozen under a different jax/XLA; PRNG lowering or fusion "
            "order may have legitimately moved.  Not necessarily a code "
            "regression: certify engine edits with a paired old-vs-new "
            "run on ONE toolchain, and see the ROADMAP item for the "
            "re-freeze decision.",
        ]
    pytest.fail("\n".join(lines), pytrace=False)
