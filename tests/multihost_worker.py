"""Worker process for the multi-host tests (see test_multihost.py).

One rank of an N-process ``jax.distributed`` job, exercising the r14
multi-host layer end to end:

1. bring-up — ``init_distributed`` from the standard env contract, global
   device enumeration, ``make_multihost_mesh`` granule layout (the rumor
   axis must not cross processes);
2. placement — ``partition.shard_put`` builds the global DeltaState from
   this rank's LOCAL block (no host materializes the global state) and
   ``host_gather`` reads back exactly the local rows, round-trip exact;
3. the process-spanning step — ``MultihostDelta`` over the host-bridged
   DCN fabric, whose global state digest must equal the digest the
   single-host engine produces for the same seeded scenario (the value is
   handed in by the test via env so the worker cannot re-derive it from
   the code under test);
4. block-sharded snapshot — save at this process count, restore, digest
   unchanged.

Argv: ``<ticks>``.  Env: the ``JAX_*`` distributed contract (set by the
test), ``MULTIHOST_EXPECT_DIGEST`` (optional engine anchor).
"""

import os
import sys

# TWO virtual devices per process (asserted below as 2 * nprocs global):
# the granule checks need a >1-device rumor row inside each process, and
# shard_put must split a process block across its local devices
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    ticks = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    from ringpop_tpu.parallel.multihost import init_distributed, make_multihost_mesh

    assert init_distributed(), "distributed env vars not set?"
    nprocs = jax.process_count()
    rank = jax.process_index()
    assert len(jax.devices()) == 2 * nprocs, jax.devices()

    mesh = make_multihost_mesh()
    # the rumor axis must never cross a process (DCN granule rule)
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1, "rumor axis crossed hosts"

    import numpy as np
    import jax.numpy as jnp

    from ringpop_tpu.parallel.fabric import DistributedKV, Fabric
    from ringpop_tpu.parallel.partition import host_gather, process_block, shard_put
    from ringpop_tpu.sim.delta import DeltaFaults, DeltaParams
    from ringpop_tpu.sim.delta_multihost import MultihostDelta

    n, k = 256, 64
    params = DeltaParams(n=n, k=k, rng="counter")

    # -- placement round-trip: local block -> global sharded -> local ----
    lo, hi = process_block(n, rank, nprocs)
    rng = np.random.default_rng(1234)  # same on every rank
    full_learned = rng.integers(0, 2**32, (n, 2), dtype=np.uint32)
    from ringpop_tpu.sim.delta import DeltaState

    local = DeltaState(
        learned=full_learned[lo:hi],
        pcount=rng.integers(0, 100, (n, k)).astype(np.int8)[lo:hi],
        ride_ok=rng.integers(0, 2**32, (n, 2), dtype=np.uint32)[lo:hi],
        tick=np.int32(5),
        key=np.zeros(2, np.uint32),
    )
    gmesh = make_multihost_mesh(rumor_shards=1)
    gstate = shard_put(local, gmesh, global_n=n)
    assert gstate.learned.shape == (n, 2), gstate.learned.shape
    back = host_gather(gstate)
    for a, b in zip(jax.tree.leaves(local), jax.tree.leaves(back)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), "round-trip diverged"

    # -- the process-spanning step + digest anchor -----------------------
    up = np.ones(n, bool)
    up[::16] = False
    faults = DeltaFaults(up=jnp.asarray(up), drop_rate=jnp.float32(0.05))
    fabric = Fabric(rank, nprocs, DistributedKV(), namespace="mh-test")
    mh = MultihostDelta(params, fabric, seed=9, faults=faults)
    for _ in range(ticks):
        mh.step()
    digest = mh.state_digest()
    expect = os.environ.get("MULTIHOST_EXPECT_DIGEST")
    if expect:
        assert digest == int(expect), f"digest {digest} != engine anchor {expect}"

    # -- block-sharded snapshot at THIS process count --------------------
    path = os.environ.get("MULTIHOST_CKPT")
    if path:
        mh.save_snapshot(path)
        mh2 = MultihostDelta.restore_snapshot(path, params, fabric, faults=faults)
        assert mh2.tick == mh.tick
        assert mh2.state_digest() == digest, "snapshot round-trip changed the state"

    fabric.close()
    print(f"rank {rank}/{nprocs} OK digest={digest}", flush=True)


if __name__ == "__main__":
    main()
