"""Worker process for the multi-host mesh test (see test_multihost.py).

One rank of a 2-process jax.distributed job: 4 virtual CPU devices per
process form a global 8-device ("node" x "rumor") mesh; runs one sharded
delta step and one sharded lifecycle step over cross-process (gloo)
collectives.  Argv: <process_id> <coordinator_port>.
"""

import functools
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from ringpop_tpu.parallel.multihost import init_distributed, make_multihost_mesh

    assert init_distributed(), "coordinator env vars set above"
    assert len(jax.devices()) == 8, jax.devices()

    mesh = make_multihost_mesh()
    assert mesh.shape == {"node": 4, "rumor": 2}, mesh.shape
    # the rumor axis must not cross DCN: both devices in each rumor row
    # belong to one process
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1, "rumor axis crossed hosts"

    from ringpop_tpu.parallel.mesh import delta_shardings
    from ringpop_tpu.sim.delta import DeltaParams, init_state, step

    # k=64 -> the packed learned plane is uint32[N, 2] words: one word per
    # rumor-axis shard
    params = DeltaParams(n=64, k=64)
    sh = delta_shardings(mesh)
    state = jax.jit(lambda: init_state(params, seed=0), out_shardings=sh)()
    out = jax.jit(functools.partial(step, params), in_shardings=(sh,), out_shardings=sh)(state)
    jax.block_until_ready(out)
    assert int(out.tick) == 1
    # dissemination progressed globally (the exchange crossed processes);
    # popcount, not sum — the packed words are not a bit count
    def bits(s):
        return int(jax.lax.population_count(s.learned).sum())

    assert bits(out) > bits(state)

    # the FLAGSHIP engine over the same cross-process mesh: a sharded
    # lifecycle state and the headline detect path (blocks + on-device
    # predicate + early exit) — the exact program the driver bench runs,
    # with its collectives crossing the process boundary.  Fault masks and
    # subjects are baked in as traced constants (host-local committed
    # arrays are not addressable across a multi-process mesh).
    import numpy as np
    import jax.numpy as jnp

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    lp = lifecycle.LifecycleParams(n=64, k=64, suspect_ticks=4)
    lsh = lifecycle.state_shardings(mesh, k=lp.k)
    lstate = jax.jit(lambda: lifecycle.init_state(lp, seed=0), out_shardings=lsh)()
    up = np.ones(lp.n, bool)
    up[lp.n // 2] = False

    @jax.jit
    def detect(s):
        return lifecycle._run_until_detected_device(
            lp,
            s,
            DeltaFaults(up=jnp.asarray(up)),
            jnp.asarray([lp.n // 2], jnp.int32),
            min_status=lifecycle.FAULTY,
            block_ticks=4,
            max_blocks=jnp.int32(16),
        )

    lout, blocks, done = detect(lstate)
    jax.block_until_ready(lout.learned)
    # the point is the PRODUCT outcome over the cross-process mesh: the
    # victim must actually be detected faulty by every live observer, via
    # the on-device predicate, with the early exit stopping short of the
    # 16-block budget
    assert bool(done), "victim not detected over the multi-host mesh"
    assert int(lout.tick) == int(blocks) * 4
    assert 1 <= int(blocks) < 16, int(blocks)

    print(f"rank {pid} OK", flush=True)


if __name__ == "__main__":
    main()
