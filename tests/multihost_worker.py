"""Worker process for the multi-host mesh test (see test_multihost.py).

One rank of a 2-process jax.distributed job: 4 virtual CPU devices per
process form a global 8-device ("node" x "rumor") mesh; runs one sharded
delta step and one sharded lifecycle step over cross-process (gloo)
collectives.  Argv: <process_id> <coordinator_port>.
"""

import functools
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from ringpop_tpu.parallel.multihost import init_distributed, make_multihost_mesh

    assert init_distributed(), "coordinator env vars set above"
    assert len(jax.devices()) == 8, jax.devices()

    mesh = make_multihost_mesh()
    assert mesh.shape == {"node": 4, "rumor": 2}, mesh.shape
    # the rumor axis must not cross DCN: both devices in each rumor row
    # belong to one process
    for row in mesh.devices:
        assert len({d.process_index for d in row}) == 1, "rumor axis crossed hosts"

    from ringpop_tpu.parallel.mesh import delta_shardings
    from ringpop_tpu.sim.delta import DeltaParams, init_state, step

    # k=64 -> the packed learned plane is uint32[N, 2] words: one word per
    # rumor-axis shard
    params = DeltaParams(n=64, k=64)
    sh = delta_shardings(mesh)
    state = jax.jit(lambda: init_state(params, seed=0), out_shardings=sh)()
    out = jax.jit(functools.partial(step, params), in_shardings=(sh,), out_shardings=sh)(state)
    jax.block_until_ready(out)
    assert int(out.tick) == 1
    # dissemination progressed globally (the exchange crossed processes);
    # popcount, not sum — the packed words are not a bit count
    def bits(s):
        return int(jax.lax.population_count(s.learned).sum())

    assert bits(out) > bits(state)
    print(f"rank {pid} OK", flush=True)


if __name__ == "__main__":
    main()
