"""The one shared ``make_faults`` helper for sim-plane tests and golden
captures (previously four byte-equivalent copies — any new DeltaFaults
field had to be threaded through all of them)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults


def make_faults(n, down=(), group=None, drop=0.0):
    up = np.ones(n, bool)
    for i in down:
        up[i] = False
    g = None if group is None else jnp.asarray(np.asarray(group, np.int32))
    return DeltaFaults(up=jnp.asarray(up), group=g, drop_rate=drop)
