"""The one shared ``make_faults`` helper for sim-plane tests and golden
captures (previously four byte-equivalent copies — any new DeltaFaults
field had to be threaded through all of them)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.sim.delta import DeltaFaults


def make_faults(n, down=(), group=None, drop=0.0, reach=None, drop_node=None):
    """Build a DeltaFaults for tests/captures.  ``drop`` of 0/0.0 maps to
    the static ``None`` fast path so the loss-free goldens keep tracing
    the exact no-drop program; any truthy rate rides as a traced leaf.
    ``reach`` is the directed [G, G] group-reachability matrix; in
    ``drop_node`` (per-node loss, float[N]) a dict maps node -> rate."""
    up = np.ones(n, bool)
    for i in down:
        up[i] = False
    g = None if group is None else jnp.asarray(np.asarray(group, np.int32))
    r = None if reach is None else jnp.asarray(np.asarray(reach, bool))
    if isinstance(drop_node, dict):
        dn_np = np.zeros(n, np.float32)
        for i, rate in drop_node.items():
            dn_np[i] = rate
        drop_node = dn_np
    dn = None if drop_node is None else jnp.asarray(np.asarray(drop_node, np.float32))
    return DeltaFaults(
        up=jnp.asarray(up),
        group=g,
        drop_rate=(None if not drop else drop),
        drop_node=dn,
        reach=r,
    )
