"""Multi-node in-process test harness (model: reference
``swim/test_utils.go`` — real channels on loopback, mock clocks, and the
synchronous-drive trick: tick every node's protocol period in a loop until no
disseminator changes remain and all checksums agree,
``test_utils.go:164-199``)."""

from __future__ import annotations

import asyncio
from typing import Optional

from ringpop_tpu.net import LocalNetwork, LocalChannel
from ringpop_tpu.swim.node import BootstrapOptions, Node, NodeOptions
from ringpop_tpu.swim.state_transitions import StateTimeouts
from ringpop_tpu.util.clock import MockClock

# the reference uses RFC-5737 TEST-NET-1 for unroutable fakes
# (test_utils.go:219-227); LocalNetwork black-holes work the same way
FAKE_HOST = "192.0.2.{}:3000"


def fake_hostports(n: int) -> list[str]:
    return [FAKE_HOST.format(i) for i in range(1, n + 1)]


def make_node(
    network: LocalNetwork,
    address: str,
    app: str = "test",
    seed: int = 0,
    suspect_timeout: float = 5.0,
) -> Node:
    channel = LocalChannel(network, address, app=app)
    clock = MockClock(start=1_000_000.0)
    opts = NodeOptions(
        clock=clock,
        seed=seed,
        state_timeouts=StateTimeouts(suspect=suspect_timeout),
    )
    return Node(app, address, channel, opts)


def make_nodes(n: int, network: Optional[LocalNetwork] = None, app: str = "test") -> list[Node]:
    network = network or LocalNetwork()
    return [
        make_node(network, f"127.0.0.1:{3000 + i}", app=app, seed=1000 + i) for i in range(n)
    ]


async def bootstrap_nodes(nodes: list[Node], stop_gossip: bool = True) -> None:
    hosts = [n.address for n in nodes]

    async def boot(node: Node):
        await node.bootstrap(BootstrapOptions(discover_provider=hosts, join_timeout=0.5))
        if stop_gossip:
            # tests drive the protocol synchronously (reference trick)
            node.gossip.stop()
            node.healer.stop()

    await asyncio.gather(*(boot(n) for n in nodes))


async def tick_all(nodes: list[Node], advance: float = 0.001) -> None:
    """One protocol period on every node; clocks advance slightly so
    reincarnation bumps are strictly increasing."""
    for node in nodes:
        node.clock.advance(advance)
        await node.gossip.protocol_period()
    # drain reverse-full-sync tasks and other spawned work
    for _ in range(3):
        await asyncio.sleep(0)


async def wait_for_convergence(nodes: list[Node], max_ticks: int = 200) -> int:
    """(model: ``test_utils.go:164-199`` waitForConvergence)"""
    for tick in range(max_ticks):
        if converged(nodes):
            return tick
        await tick_all(nodes)
    raise AssertionError(
        f"no convergence after {max_ticks} ticks; checksums="
        f"{[n.memberlist.checksum() for n in nodes]} "
        f"changes={[n.disseminator.changes_count() for n in nodes]}"
    )


def converged(nodes: list[Node]) -> bool:
    if any(n.disseminator.has_changes() for n in nodes):
        return False
    checksums = {n.memberlist.checksum() for n in nodes}
    return len(checksums) == 1


def member_statuses(node: Node) -> dict[str, int]:
    return {m.address: m.status for m in node.memberlist.get_members()}


def run(coro):
    return asyncio.run(coro)
