"""The XLA-detection cache fingerprint (VERDICT r4 item 3).

The persistent-cache dir is keyed by the target-machine feature string
XLA embeds in its own AOT entries — the exact string its loader compares
at entry-load time — so environments whose XLA detection differs can
never share entries (the round-3/4 "doesn't match the machine type /
SIGILL" warnings survived two rounds of /proc/cpuinfo-based keying).

Also regression-covers the probe's nastiest side effect: jax's
compilation-cache singleton binds its directory at FIRST use, so the
canary compile must reset it or every later cache write in the process
silently targets the deleted probe dir (observed as 'Error writing
persistent compilation cache entry ... xla_target_probe_*' warnings).
"""

from __future__ import annotations

import glob

from ringpop_tpu.util import accel


def test_probe_extracts_xla_feature_string():
    """The probe's output is a property of the container's XLA, so its
    expectation is fingerprint-keyed like the trajectory goldens
    (tests/golden_tools.probe_recording; captured by
    tests/capture_probe_golden.py): on a recorded toolchain the probe must
    reproduce its recording exactly — 'xla-fp-none' is a legitimate
    recording where that XLA's cache entries embed no plain-text feature
    string (this container's jax 0.4.37; verified at capture time), and a
    deviation from it (e.g. 'xla-fp-error') is a probe regression.  On an
    UNRECORDED toolchain the legacy strict expectation applies and a
    fallback marker fails — with the capture script named, so the failure
    diagnoses itself as drift-vs-regression the way the goldens do."""
    from tests import golden_tools

    bits = accel._xla_detected_target_bits()
    assert bits, "probe returned no fingerprint bits"
    rec = golden_tools.probe_recording()
    if rec is not None:
        assert bits[0] == rec["bits_head"], (
            f"probe output {bits[0]!r} deviates from this toolchain's "
            f"recording {rec['bits_head']!r} "
            f"(tests/golden/xla_probe.{golden_tools.fp8()}.json) — a probe "
            "regression, not toolchain drift"
        )
        assert len(bits) == rec["n_bits"], (bits, rec)
        if rec["bits_head"].startswith("xla-fp:"):
            assert bits[0].count(",") > 10, "feature string suspiciously short"
    else:
        # legacy expectation (the toolchain the probe was written on): the
        # canary must surface the canonical feature string (dozens of
        # comma-separated +/-flags); a fallback marker on an unrecorded
        # toolchain is either a broken probe or toolchain drift — run
        # tests/capture_probe_golden.py after verifying which, exactly
        # like a trajectory re-freeze
        assert bits[0].startswith("xla-fp:"), (
            f"{bits[0]!r} on an UNRECORDED toolchain "
            f"(fingerprint {golden_tools.fp8()}); if this XLA legitimately "
            "embeds no feature string, record it via "
            "tests/capture_probe_golden.py"
        )
        assert bits[0].count(",") > 10, "feature string suspiciously short"
    # memoized per process: detection is deterministic, probe runs once
    assert accel._xla_detected_target_bits() is bits


def test_fingerprint_dir_stable_and_versioned(tmp_path):
    d1 = accel.compile_cache_dir(str(tmp_path), create=False)
    d2 = accel.compile_cache_dir(str(tmp_path), create=False)
    assert d1 == d2, "fingerprint must be deterministic within one process"


def test_cache_write_lands_in_configured_dir_after_probe(tmp_path):
    """The probe's canary compile must not leave the cache singleton bound
    to the (deleted) probe dir: a post-probe compile that crosses the 1 s
    write threshold must land its entry in the *configured* directory."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.sim import lifecycle
    from ringpop_tpu.sim.delta import DeltaFaults

    d = accel.configure_compile_cache(str(tmp_path))
    assert d and d.startswith(str(tmp_path))
    # remove the 1 s write-threshold timing dependence: the assertion is
    # about WHERE the entry lands, not how slow the compile was.  MUST be
    # restored afterwards: the zero threshold persists for the process,
    # and with it every later tiny compile in the suite gets cached —
    # whose keys ignore HLO metadata, so two programs differing only in
    # named_scope/op_name alias to one executable text (this bit the
    # jaxlint RPJ206 fixtures, whose trip/clean pair differs only in the
    # scope name).
    old_threshold = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        # a genuinely slow-to-compile program (the real engine step at a
        # tiny scale compiles in seconds; toy matmul stacks dedup below
        # the 1 s write threshold and prove nothing)
        params = lifecycle.LifecycleParams(n=1500, k=32)
        state = lifecycle.init_state(params, seed=3)
        up = np.ones(1500, bool)
        up[7] = False
        faults = DeltaFaults(up=jnp.asarray(up))
        step = jax.jit(lambda s: lifecycle.step(params, s, faults))
        jax.block_until_ready(step(state).learned)
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", old_threshold
        )

    assert glob.glob(d + "/*"), (
        "no cache entry in the configured dir — the compilation-cache "
        "singleton is still bound elsewhere (probe reset regression)"
    )
