"""ringpop-admin CLI against a live TCP cluster.

The reference's admin surface is driven by external tooling over the wire
(``swim/handlers.go:63-82``); these tests exercise ours the same way — the
CLI builds its own channel and talks to real listening nodes.
"""

from __future__ import annotations

import asyncio
import json

from ringpop_tpu.net import TCPChannel
from ringpop_tpu.ringpop import Ringpop


def run(coro):
    return asyncio.run(coro)


def _cli(argv) -> tuple[int, list[dict]]:
    """Run the CLI main() in a worker thread (it owns its own event loop),
    capturing its stdout JSON lines."""
    import contextlib
    import io

    from ringpop_tpu.cli import admin

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = admin.main(argv)
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines() if ln.strip()]
    return rc, lines


def test_admin_cli_commands():
    async def main():
        chans = [TCPChannel(app="admin-test") for _ in range(2)]
        for ch in chans:
            await ch.listen()
        rps = [Ringpop("admin-test", ch) for ch in chans]
        hosts = [ch.hostport for ch in chans]
        await asyncio.gather(*(rp.bootstrap(discover_provider=hosts) for rp in rps))
        target = hosts[0]

        def drive():
            rc, out = _cli(["health", target])
            assert rc == 0 and out[0]["ok"] is True

            rc, out = _cli(["status", target])
            assert rc == 0
            assert out[0]["state"] == "ready"
            assert len(out[0]["membership"]["members"]) == 2

            rc, out = _cli(["members", target])
            assert rc == 0
            addrs = {row["address"] for row in out[:-1]}
            assert addrs == set(hosts)
            assert out[-1]["checksum"] == rps[0].node.memberlist.checksum()

            rc, out = _cli(["lookup", target, "some-key"])
            assert rc == 0 and out[0]["dest"] in hosts

            rc, out = _cli(["gossip", target, "tick"])
            assert rc == 0

            rc, out = _cli(["reap", target])
            assert rc == 0

            # unreachable target -> rc 1 + structured error
            rc, out = _cli(["--timeout", "0.5", "health", "127.0.0.1:1"])
            assert rc == 1 and out[0]["ok"] is False

        # the CLI runs its own event loop; give it a worker thread while
        # this loop keeps serving the nodes
        await asyncio.to_thread(drive)

        for rp in rps:
            rp.destroy()
        for ch in chans:
            await ch.close()

    run(main())


def test_admin_cli_msgpack_wire():
    async def main():
        ch = TCPChannel(app="admin-test", codec="msgpack")
        await ch.listen()
        rp = Ringpop("admin-test", ch)
        await rp.bootstrap(discover_provider=[ch.hostport])

        def drive():
            rc, out = _cli(["--wire", "msgpack", "health", ch.hostport])
            assert rc == 0 and out[0]["ok"] is True

        await asyncio.to_thread(drive)
        rp.destroy()
        await ch.close()

    run(main())
