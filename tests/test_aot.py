"""util/aot.py — the AOT warm-start front door's contracts.

The cross-process reload + warm-bar + digest certificate lives in
``scripts/aot_smoke.py`` (``make aot-smoke``); this suite pins the
in-process semantics: miss→save→hit flow, bit-identity against the
plain jit path, key sensitivity to shape/config, graceful degradation
on an unwritable cache dir and on argument-structure drift.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import lifecycle
from ringpop_tpu.sim.delta import DeltaFaults
from ringpop_tpu.util import aot


@pytest.fixture()
def small_block():
    params = lifecycle.LifecycleParams(n=256, k=32, suspect_ticks=5, rng="counter")
    state = lifecycle.init_state(params, seed=0)
    up = np.ones(params.n, bool)
    up[::16] = False
    faults = DeltaFaults(up=jnp.asarray(up))
    blk = jax.jit(
        functools.partial(lifecycle._run_block, params), static_argnames="ticks"
    )
    return params, state, faults, blk


def test_miss_then_hit_bit_identical(small_block, tmp_path):
    params, state, faults, blk = small_block
    kw = dict(tag="t-roundtrip", static_kw={"ticks": 3},
              statics=(repr(params),), cache_dir=str(tmp_path))
    call, info = aot.load_or_compile(blk, state, faults, **kw)
    assert not info["cache_hit"] and info["saved"] and info["error"] is None
    assert os.path.exists(info["path"])
    out = call(state, faults)
    ref = blk(state, faults, ticks=3)
    assert type(out) is type(ref)  # pytree structure survives the export
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert bool((np.asarray(a) == np.asarray(b)).all())
    call2, info2 = aot.load_or_compile(blk, state, faults, **kw)
    assert info2["cache_hit"] and info2["error"] is None
    out2 = call2(state, faults)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out2)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_key_sensitive_to_shape_statics_and_ticks(small_block, tmp_path):
    params, state, faults, blk = small_block
    _, a = aot.load_or_compile(
        blk, state, faults, tag="t-key", static_kw={"ticks": 2},
        statics=(repr(params),), cache_dir=str(tmp_path), save=False)
    _, b = aot.load_or_compile(
        blk, state, faults, tag="t-key", static_kw={"ticks": 4},
        statics=(repr(params),), cache_dir=str(tmp_path), save=False)
    assert a["key"] != b["key"]  # static kwargs key the program
    p2 = lifecycle.LifecycleParams(n=512, k=32, suspect_ticks=5, rng="counter")
    s2 = lifecycle.init_state(p2, seed=0)
    f2 = DeltaFaults(up=jnp.ones(512, bool))
    blk2 = jax.jit(
        functools.partial(lifecycle._run_block, p2), static_argnames="ticks"
    )
    _, c = aot.load_or_compile(
        blk2, s2, f2, tag="t-key", static_kw={"ticks": 2},
        statics=(repr(p2),), cache_dir=str(tmp_path), save=False)
    assert c["key"] not in (a["key"], b["key"])  # shapes/config key it too


def test_key_sensitive_to_package_source(small_block, tmp_path, monkeypatch):
    """An engine edit (simulated by swapping the memoized source
    fingerprint) must invalidate every artifact — a stale pre-edit
    executable can never be served as a hit."""
    params, state, faults, blk = small_block
    kw = dict(tag="t-src", static_kw={"ticks": 2},
              statics=(repr(params),), cache_dir=str(tmp_path), save=False)
    _, a = aot.load_or_compile(blk, state, faults, **kw)
    monkeypatch.setattr(aot, "_SOURCE_FP8", "deadbeef")
    _, b = aot.load_or_compile(blk, state, faults, **kw)
    assert a["key"] != b["key"]


def test_unwritable_cache_dir_degrades_gracefully(small_block, tmp_path):
    """A save failure must not break the call — the program still runs,
    the record says why nothing persisted."""
    params, state, faults, blk = small_block
    # a FILE where a directory is expected: unwritable even for root
    # (chmod-based denial is a no-op under uid 0, which CI runs as)
    ro = tmp_path / "ro"
    ro.write_text("not a directory")
    call, info = aot.load_or_compile(
        blk, state, faults, tag="t-ro", static_kw={"ticks": 2},
        statics=(repr(params),), cache_dir=str(ro))
    assert not info["saved"] and not info["cache_hit"]
    assert info["error"] and "save failed" in info["error"]
    out = call(state, faults)
    ref = blk(state, faults, ticks=2)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_structure_drift_falls_back_to_plain_path(small_block, tmp_path):
    """Calling the returned runner with a different faults pytree
    structure (None legs vs arrays) re-traces instead of mis-feeding the
    keyed executable."""
    params, state, faults, blk = small_block
    call, info = aot.load_or_compile(
        blk, state, faults, tag="t-drift", static_kw={"ticks": 2},
        statics=(repr(params),), cache_dir=str(tmp_path))
    drifted = DeltaFaults()  # all-None: different leaf structure
    out = call(state, drifted)
    ref = blk(state, drifted, ticks=2)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_corrupt_artifact_recompiles(small_block, tmp_path):
    params, state, faults, blk = small_block
    kw = dict(tag="t-corrupt", static_kw={"ticks": 2},
              statics=(repr(params),), cache_dir=str(tmp_path))
    _, info = aot.load_or_compile(blk, state, faults, **kw)
    with open(info["path"], "wb") as f:
        f.write(b"not an exported program")
    call, info2 = aot.load_or_compile(blk, state, faults, **kw)
    assert not info2["cache_hit"] and info2["error"] and "load failed" in info2["error"]
    out = call(state, faults)
    ref = blk(state, faults, ticks=2)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert bool((np.asarray(a) == np.asarray(b)).all())


def test_accel_cache_status_records_unwritable_base(tmp_path, monkeypatch):
    """Satellite: configure_compile_cache on an unwritable base logs +
    records the reason instead of silently no-opping."""
    from ringpop_tpu.util import accel

    # a file where the base dir should be: mkdir fails even as root
    ro = tmp_path / "robase"
    ro.write_text("not a directory")
    try:
        got = accel.configure_compile_cache(str(ro))
        assert got is None
        status = accel.cache_status()
        assert status["cache_dir"] is None
        assert status["error"]  # the reason is recorded for the journal header
    finally:
        # restore the shared test cache for the rest of the suite
        accel.configure_compile_cache()
        assert accel.cache_status()["error"] is None
