"""Chaos plane (ISSUE 5): FaultPlan timeline semantics, constant-plan
equivalence (state AND telemetry bit-identical to the static program),
the extended DeltaFaults (traced drop_rate leaf, per-node drop, directed
reach), pair_connected units, mid-scenario snapshot/resume, and the
convergence scorer."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.sim import chaos, delta, lifecycle, telemetry
from ringpop_tpu.sim.delta import (
    DeltaFaults,
    has_drop,
    leg_survives,
    pair_connected,
)

from tests.sim_faults import make_faults


def _leaves_equal(a, b) -> bool:
    return all(
        bool((np.asarray(x) == np.asarray(y)).all())
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- DeltaFaults: the re-registered pytree -----------------------------------


def test_drop_rate_is_a_traced_leaf_not_a_recompile_key():
    """Two fault models differing only in drop rate flatten to the SAME
    treedef (the jit cache key) with the rate as a leaf — a drop-rate
    sweep reuses one compilation.  The satellite fix: drop_rate used to
    ride in aux_data, recompiling per distinct rate."""
    a = DeltaFaults(up=jnp.ones(8, bool), drop_rate=0.05)
    b = DeltaFaults(up=jnp.ones(8, bool), drop_rate=0.25)
    assert jax.tree.structure(a) == jax.tree.structure(b)
    assert jax.tree.leaves(a)[-1] == 0.05 and jax.tree.leaves(b)[-1] == 0.25
    # the None fast path is static structure: a loss-free model has no
    # drop leaf at all, so its trace stays the drop-free program
    c = DeltaFaults(up=jnp.ones(8, bool))
    assert jax.tree.structure(c) != jax.tree.structure(a)
    assert not has_drop(c) and has_drop(a)


def test_drop_rate_sweep_single_compilation():
    params = delta.DeltaParams(n=64, k=8, rng="counter")
    state = delta.init_state(params, seed=0)
    stepper = jax.jit(functools.partial(delta.step, params))
    up = jnp.ones(64, bool)
    for rate in (0.05, 0.1, 0.9):
        stepper(state, DeltaFaults(up=up, drop_rate=rate))
    assert stepper._cache_size() == 1


def test_make_faults_zero_drop_maps_to_static_none():
    f = make_faults(16)
    assert f.drop_rate is None and f.drop_node is None and f.reach is None
    f2 = make_faults(16, drop=0.1, reach=[[True, False], [True, True]],
                     drop_node={3: 0.5})
    assert float(f2.drop_rate) == 0.1
    assert f2.reach.shape == (2, 2) and float(f2.drop_node[3]) == 0.5


# -- pair_connected / leg_survives units (satellite) --------------------------


def test_pair_connected_both_none_fast_path():
    f = DeltaFaults()
    a = jnp.asarray([0, 1, 2], jnp.int32)
    b = jnp.asarray([2, 0, 1], jnp.int32)
    assert bool(pair_connected(f, a, b).all())


def test_pair_connected_up_and_symmetric_group():
    f = make_faults(6, down=[5], group=[0, 0, 1, 1, -1, 0])
    a = jnp.asarray([0, 0, 0, 4, 0], jnp.int32)
    b = jnp.asarray([1, 2, 4, 2, 5], jnp.int32)
    got = np.asarray(pair_connected(f, a, b))
    # same group; cross group; -1 reaches anyone; -1 reached; down peer
    assert got.tolist() == [True, False, True, True, False]


def test_pair_connected_asymmetric_reach():
    """Directed reach: group 1 → 0 delivers while 0 → 1 is blocked; group
    -1 stays universally connected in both directions."""
    f = make_faults(6, group=[0, 0, 1, 1, -1, -1],
                    reach=[[True, False], [True, True]])
    a = jnp.asarray([0, 2, 0, 4, 2, 0], jnp.int32)
    b = jnp.asarray([2, 0, 1, 2, 4, 4], jnp.int32)
    got = np.asarray(pair_connected(f, a, b))
    # 0->1 blocked; 1->0 open; within-0 open; -1->1 open; 1->-1 open; 0->-1
    assert got.tolist() == [False, True, True, True, True, True]


def test_leg_survives_per_node_drop_composes_as_survival_product():
    dn = jnp.asarray([0.0, 0.5, 1.0, 0.0], jnp.float32)
    f = DeltaFaults(drop_node=dn)
    a = jnp.asarray([0, 0, 1, 2], jnp.int32)
    b = jnp.asarray([3, 1, 1, 0], jnp.int32)
    # keep = (1-dn[a])*(1-dn[b]); scalar rate absent
    u = jnp.asarray([0.49, 0.49, 0.24, 0.0], jnp.float32)
    got = np.asarray(leg_survives(f, u, a, b))
    assert got.tolist() == [True, True, True, False]
    # with the scalar rate folded in, keep shrinks by (1-rate):
    # keeps become [0.5, 0.25, 0.125, 0.0]
    f2 = DeltaFaults(drop_rate=jnp.float32(0.5), drop_node=dn)
    u2 = jnp.asarray([0.51, 0.24, 0.13, 0.9], jnp.float32)
    assert np.asarray(leg_survives(f2, u2, a, b)).tolist() == [False, True, False, False]


def test_leg_survives_scalar_only_is_headline_comparison():
    """The scalar-only path must be the exact historical ``u >= rate``
    comparison (bit-compat with the frozen loss goldens)."""
    f = DeltaFaults(drop_rate=0.3)
    u = jnp.asarray([0.29999, 0.3, 0.31], jnp.float32)
    assert np.asarray(leg_survives(f, u, 0, 1)).tolist() == [False, True, True]


def test_asym_reach_in_the_delta_engine():
    """Engine-level reach semantics: an exchange needs its ORDERED pair
    connected (the request direction names the RPC; rumors then ride
    both legs), so ONE open direction between two groups keeps rumors
    flowing both ways, while a reach matrix blocking both directions
    isolates exactly like the symmetric group model."""
    n, k = 64, 8
    group = np.zeros(n, np.int32)
    group[n // 2:] = 1
    params = delta.DeltaParams(n=n, k=k, rng="counter")
    sources = np.full(k, n - 1, np.int64)  # all rumors start on side 1
    from ringpop_tpu.sim.packbits import unpack_bits

    for reach, side0_learns in (
        ([[True, False], [True, True]], True),    # only 1→0 open: leaks
        ([[True, False], [False, True]], False),  # both blocked: isolated
    ):
        f = make_faults(n, group=group, reach=reach)
        state = delta.init_state(params, seed=3, sources=sources)
        stepper = jax.jit(functools.partial(delta.step, params))
        for _ in range(48):
            state = stepper(state, f)
        learned = np.asarray(unpack_bits(state.learned, k))
        assert learned[n // 2:].all()  # side 1 always saturates
        assert learned[: n // 2].any() == side0_learns, reach


def test_fullview_oracle_refuses_legs_it_cannot_express():
    """The O(N²) oracle keeps its static symmetric fault model: a
    directed-reach / per-node-drop DeltaFaults (or a whole FaultPlan)
    must raise instead of silently simulating a DIFFERENT model."""
    from ringpop_tpu.sim import fullview

    sim = fullview.FullViewSim(8, seed=0)
    with pytest.raises(ValueError, match="directed reach"):
        sim.tick(make_faults(8, group=[0] * 8, reach=[[True]]))
    with pytest.raises(ValueError, match="per-node drop"):
        sim.tick(make_faults(8, drop_node=np.zeros(8, np.float32)))
    with pytest.raises(TypeError, match="FaultPlan"):
        sim.tick(chaos.FaultPlan(base_up=jnp.ones(8, bool)))
    # the plain shared-harness model still coerces fine
    sim.tick(make_faults(8, down=[2], drop=0.1))


# -- FaultPlan timeline semantics --------------------------------------------


def test_faults_at_crash_restart_window():
    crash = jnp.asarray([chaos.NO_TICK, 5, 5, 9], jnp.int32)
    restart = jnp.asarray([chaos.NO_TICK, 8, chaos.NO_TICK, 12], jnp.int32)
    plan = chaos.FaultPlan(crash_tick=crash, restart_tick=restart)
    for t, want in ((0, [1, 1, 1, 1]), (5, [1, 0, 0, 1]),
                    (8, [1, 1, 0, 1]), (10, [1, 1, 0, 0]), (12, [1, 1, 0, 1])):
        up = np.asarray(chaos.faults_at(plan, t).up)
        assert up.tolist() == [bool(x) for x in want], t
        assert np.array_equal(chaos.up_at_host(plan, t, 4), up)


def test_faults_at_flap_schedule():
    plan = chaos.FaultPlan(
        flap_period=jnp.asarray([0, 6], jnp.int32),
        flap_phase=jnp.asarray([0, 2], jnp.int32),
        flap_down=jnp.asarray([0, 2], jnp.int32),
    )
    got = [np.asarray(chaos.faults_at(plan, t).up).tolist() for t in range(8)]
    # node 1: down iff (t+2) % 6 < 2 → down at t in {4, 5} then {10, 11}...
    want_up1 = [(t + 2) % 6 >= 2 for t in range(8)]
    assert [g[0] for g in got] == [True] * 8  # period 0 never flaps
    assert [g[1] for g in got] == want_up1
    for t in range(8):
        assert np.array_equal(chaos.up_at_host(plan, t, 2), np.asarray(got[t]))


def test_faults_at_partition_window_heals():
    group = jnp.asarray([0, 0, 1, 1], jnp.int32)
    plan = chaos.FaultPlan(group=group, part_from=jnp.int32(4), part_until=jnp.int32(8))
    assert np.asarray(chaos.faults_at(plan, 3).group).tolist() == [-1, -1, -1, -1]
    assert np.asarray(chaos.faults_at(plan, 4).group).tolist() == [0, 0, 1, 1]
    assert np.asarray(chaos.faults_at(plan, 8).group).tolist() == [-1, -1, -1, -1]


def test_flap_period_without_down_raises():
    plan = chaos.FaultPlan(flap_period=jnp.asarray([4], jnp.int32))
    with pytest.raises(ValueError, match="flap_down"):
        chaos.faults_at(plan, 0)


def test_merge_plans_rejects_duplicate_legs():
    a = chaos.FaultPlan(drop_rate=jnp.float32(0.1))
    with pytest.raises(ValueError, match="more than one plan"):
        chaos._merge_plans(a, a)


# -- constant-plan equivalence (the goldens-untouched acceptance bar) --------


@pytest.mark.parametrize("engine", ["delta", "lifecycle"])
def test_constant_plan_traces_to_the_exact_static_program(engine):
    """A FaultPlan encoding a static DeltaFaults produces the IDENTICAL
    jaxpr — not just equal values — on both engines; running both for
    several ticks (with telemetry on the lifecycle side) stays bit-equal
    leaf for leaf."""
    n, k = 96, 16
    faults = make_faults(n, down=[3, 7], group=[i % 2 for i in range(n)],
                         drop=0.05)
    plan = chaos.constant_plan(faults)
    if engine == "delta":
        params = delta.DeltaParams(n=n, k=k, rng="counter")
        step, state = delta.step, delta.init_state(params, seed=1)
        ja = jax.make_jaxpr(lambda s, f: step(params, s, f))(state, faults)
        jb = jax.make_jaxpr(lambda s, p: step(params, s, p))(state, plan)
        assert str(ja) == str(jb)
        stepper = jax.jit(functools.partial(step, params))
        a = b = state
        for _ in range(12):
            a, b = stepper(a, faults), stepper(b, plan)
        assert _leaves_equal(a, b)
    else:
        params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=5, rng="counter")
        state = lifecycle.init_state(params, seed=1)
        ja = jax.make_jaxpr(lambda s, f: lifecycle.step(params, s, f))(state, faults)
        jb = jax.make_jaxpr(lambda s, p: lifecycle.step(params, s, p))(state, plan)
        assert str(ja) == str(jb)
        stepper = jax.jit(functools.partial(lifecycle.step, params))
        a, b = state, state
        ta, tb = telemetry.zeros(params), telemetry.zeros(params)
        for _ in range(12):
            a, ta = stepper(a, faults, telemetry=ta)
            b, tb = stepper(b, plan, telemetry=tb)
        assert _leaves_equal(a, b)
        assert _leaves_equal(ta, tb)


def test_plan_flows_through_run_until_detected_driver():
    """A churn plan rides the jitted run-until machinery unchanged: the
    permanently-crashed cohort is detected, and the driver's answer
    equals a per-tick host loop's."""
    n, k = 128, 32
    crash = np.full(n, chaos.NO_TICK, np.int32)
    victims = [5, 50, 90]
    for v in victims:
        crash[v] = 3
    plan = chaos.FaultPlan(crash_tick=jnp.asarray(crash))
    sim = lifecycle.LifecycleSim(n=n, k=k, seed=2, suspect_ticks=6, rng="counter")
    ticks, ok = sim.run_until_detected(victims, plan, max_ticks=512)
    assert ok and ticks > 0
    # the resolved-faults queries agree with an explicit static model
    static = DeltaFaults(up=jnp.ones(n, bool).at[jnp.asarray(victims)].set(False))
    assert bool(lifecycle.detection_complete(sim.state, jnp.asarray(victims), plan))
    assert bool(lifecycle.detection_complete(sim.state, jnp.asarray(victims), static))


def test_restart_rejoins_and_converges():
    """Crash → detect → restart → refute-by-reincarnation → the base
    census carries the node ALIVE again and the cluster quiesces (the
    re-join path the scorer's rejoin_convergence_ticks measures)."""
    n, k = 96, 32
    crash = np.full(n, chaos.NO_TICK, np.int32)
    restart = np.full(n, chaos.NO_TICK, np.int32)
    crash[7], restart[7] = 4, 40
    plan = chaos.FaultPlan(crash_tick=jnp.asarray(crash), restart_tick=jnp.asarray(restart))
    sim = lifecycle.LifecycleSim(n=n, k=k, seed=3, suspect_ticks=5, rng="counter")
    sim.run(40, plan)
    # down and detected by the restart tick
    assert int(np.asarray(sim.state.base_status)[7]) >= lifecycle.FAULTY
    # step past the restart so the refutation actually happens (the
    # run_until driver tests quiescence on ENTRY — a detected-and-folded
    # cluster at the restart tick is already converged without it)
    sim.run(8, plan)
    ticks, ok = sim.run_until_converged(plan, max_ticks=1024)
    assert ok
    assert bool(np.asarray(sim.state.base_present)[7])
    assert int(np.asarray(sim.state.base_status)[7]) == lifecycle.ALIVE
    assert int(np.asarray(sim.state.self_inc)[7]) > 0  # reincarnated


# -- snapshot mid-scenario (satellite) ----------------------------------------


def test_snapshot_restore_mid_churn_window_resumes_bit_identically():
    """sim/snapshot.py round-trip at a tick INSIDE a churn window: the
    resumed run must continue the exact trajectory of the uninterrupted
    one — the plan's timeline is a pure function of the carried tick, so
    restore needs no extra bookkeeping."""
    from ringpop_tpu.sim.snapshot import load_state, save_state

    import os
    import tempfile

    n, k = 64, 16
    plan = chaos.scenario_plan("smoke", n, seed=5, horizon=64)
    params = lifecycle.LifecycleParams(n=n, k=k, suspect_ticks=5, rng="counter")
    stepper = jax.jit(functools.partial(lifecycle.step, params))
    state = lifecycle.init_state(params, seed=5)
    for _ in range(10):  # tick 10 is inside the smoke plan's churn window
        state = stepper(state, plan)
    down_now = ~chaos.up_at_host(plan, 10, n)
    assert down_now.any(), "tick 10 must sit inside a churn window"

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "mid_churn.npz")
        save_state(path, state, params=params)
        resumed = lifecycle.init_state(params, seed=99)  # junk, fully replaced
        resumed = load_state(path, lifecycle.LifecycleState, params=params)
    assert _leaves_equal(resumed, state)
    cont = state
    for _ in range(20):  # crosses restart boundaries of the window
        cont = stepper(cont, plan)
        resumed = stepper(resumed, plan)
    assert _leaves_equal(resumed, cont)


# -- scorer -------------------------------------------------------------------


def test_asym_refutations_attributed_to_unreachable_direction():
    """The r10 asym scenario's false-positive refutes (309 at the full
    SIMBENCH scale) happen at the UNREACHABLE side of the one-way window
    — the minority the majority cannot send to, where false accusations
    pile up and refute through the open direction.  The per-direction
    split (telemetry.fetch attributing by the plan's static group×reach,
    summed by score_blocks) must say exactly that: the split is the
    total, and the reachable side carries ~none of it."""
    n = 256
    plan = chaos.scenario_plan("asym", n, seed=1, horizon=128)
    sink = telemetry.TelemetrySink()
    sim = lifecycle.LifecycleSim(n=n, k=32, seed=2, suspect_ticks=5,
                                 rng="counter", telemetry=sink)
    for _ in range(8):
        sim.run(16, plan)
    score = chaos.score_blocks(sink.records, plan, n=n, scenario="asym")
    assert score["refutations"] > 0
    assert (
        score["refutations_unreachable_dir"] + score["refutations_reachable_dir"]
        == score["refutations"]
    )
    # the one-way window's sink side owns the refutation load
    assert score["refutations_unreachable_dir"] > score["refutations_reachable_dir"]
    up_now = chaos.up_at_host(plan, 16, n)
    assert not up_now.all()  # the rider crash cohort exists (true positives)
    # the blocks carry the split too (fetch-level attribution)
    assert all("refuted_unreachable_dir" in b for b in sink.records)


def test_plan_events_timeline():
    plan = chaos.scenario_plan("smoke", 128, seed=0, horizon=96)
    events = chaos.plan_events(plan)
    kinds = [e["kind"] for e in events]
    assert "crash" in kinds and "restart" in kinds and "flap" in kinds
    ticks = [e["tick"] for e in events]
    assert ticks == sorted(ticks)
    crash_nodes = sum(e["nodes"] for e in events if e["kind"] == "crash")
    restart_nodes = sum(e["nodes"] for e in events if e["kind"] == "restart")
    assert crash_nodes > restart_nodes  # the permanent cohort never restarts


def test_score_blocks_on_synthetic_journal():
    """Scorer arithmetic pinned on a hand-built journal: crash at tick 4,
    half-coverage by tick 32, full coverage by tick 48, restart at 20
    with census recovery + quiescence at 64."""
    n = 100
    crash = np.full(n, chaos.NO_TICK, np.int32)
    restart = np.full(n, chaos.NO_TICK, np.int32)
    crash[1], crash[2] = 4, 4
    restart[2] = 20
    plan = chaos.FaultPlan(crash_tick=jnp.asarray(crash), restart_tick=jnp.asarray(restart))

    def block(tick, frac, alive, rumors, refuted=0):
        return {"kind": "block", "tick": tick, "ticks": 16, "detect_frac": frac,
                "census_alive": alive, "rumors_active": rumors,
                "refuted": refuted, "decl_suspect": 2, "decl_faulty": 1,
                "heal_attempts": 0}

    blocks = [
        block(16, 0.0, 98, 3),
        block(32, 0.5, 98, 3, refuted=1),
        block(48, 1.0, 98, 2),
        block(64, 1.0, 99, 0),
    ]
    score = chaos.score_blocks(blocks, plan, n=n, scenario="synthetic")
    assert score["time_to_detect"] == [[4, 44]]
    assert score["rumor_half_life"] == [[4, 28]]
    assert score["time_to_detect_median"] == 44
    # node 2's one refutation is its re-join reincarnation, not a false
    # accusation — the plan-known restart count is subtracted
    assert score["refutations"] == 1
    assert score["false_positive_suspects"] == 0
    # expected alive at horizon: 99 (node 1 stays down); recovery lands
    # at the tick-64 block, 44 ticks after the restart at 20
    assert score["rejoin_convergence_ticks"] == 44
    assert score["block_granularity_ticks"] == 16
    assert score["final_detect_frac"] == 1.0


def test_emit_score_stats_skips_null_metrics():
    from ringpop_tpu.options import InMemoryStats

    stats = InMemoryStats()
    chaos.emit_score_stats(stats, {
        "time_to_detect_median": 44,
        "rumor_half_life_median": None,
        "false_positive_suspects": 3,
        "rejoin_convergence_ticks": None,
        "final_detect_frac": 1.0,
    })
    assert stats.gauges["ringpop.sim.chaos.time-to-detect"] == 44.0
    assert stats.gauges["ringpop.sim.chaos.false-positive.suspects"] == 3.0
    assert "ringpop.sim.chaos.rumor.half-life" not in stats.gauges


def test_telemetry_census_tracks_the_plan_tick():
    """telemetry.fetch resolves a FaultPlan at the state's tick: the
    detect_frac denominator is the down set in force AT FETCH, not at
    plan construction."""
    n = 64
    crash = np.full(n, chaos.NO_TICK, np.int32)
    crash[3] = 8
    plan = chaos.FaultPlan(crash_tick=jnp.asarray(crash))
    sim = lifecycle.LifecycleSim(n=n, k=16, seed=0, suspect_ticks=4,
                                 rng="counter", telemetry=True)
    for _ in range(4):
        sim.tick(plan)
    rec_before = sim.fetch_telemetry(plan)
    # empty down set (nobody crashed yet): the vacuous 1.0, same as the
    # no-fault-model branch
    assert rec_before["detect_frac"] == pytest.approx(1.0)
    assert rec_before["census_alive"] == n
    sim.run_until_detected([3], plan, max_ticks=512)
    rec_after = sim.fetch_telemetry(plan)
    assert rec_after["detect_frac"] == pytest.approx(1.0)  # node 3 absorbed
    assert rec_after["census_faulty"] >= 1
